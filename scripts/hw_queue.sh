#!/usr/bin/env bash
# The hardware re-verification queue, CHEAPEST FIRST: a short
# transport-alive window must bank the never-run kernel validations
# before any long bench can burn it (round 3 ordered bench first and a
# 03:30Z death left every cheaper check unrun — see hw_queue_r3.log).
# Each stage gets its OWN wall budget, probes the transport first, and
# is independently re-runnable.  Exit 9 = transport died mid-queue;
# hw_watch.sh resumes watching and re-fires on the next alive window.
# For the bench alone (no tier-1/tier-3 stages), `make bench-hw`
# (scripts/bench_hw.sh) is the hardened retry-with-backoff ladder that
# always banks the skip diagnosis — run it with BLUEFOG_GOSSIP_KERNEL=1
# vs unset for the single-kernel-gossip on/off delta.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG=${1:-hw_queue_r5.log}
FAILED=0
. scripts/_probe.sh   # cwd is the repo root (cd above)
run() {
    local budget=$1; shift
    # test hook (tests/test_hw_queue.py): HW_QUEUE_BUDGET_DIV shrinks the
    # per-stage wall budgets so the fake-transport integration test can
    # exercise a real budget overrun in seconds (ceil: never 0)
    local div=${HW_QUEUE_BUDGET_DIV:-1}
    budget=$(( (budget + div - 1) / div ))
    if ! probe; then
        echo "=== transport dead before: $* — aborting queue (exit 9) ===" | tee -a "$LOG"
        exit 9
    fi
    echo "=== [budget ${budget}s] $* ===" | tee -a "$LOG"
    timeout -k 30 "$budget" "$@" 2>&1 | tee -a "$LOG"
    local rc=${PIPESTATUS[0]}
    echo "=== exit $rc ($(date -u +%FT%TZ)) ===" | tee -a "$LOG"
    [ "$rc" -ne 0 ] && FAILED=$((FAILED + 1))
    return 0
}
QSTART=$(date -u +%FT%TZ)
echo "hw queue started $QSTART" | tee -a "$LOG"
# Tier 1 — minutes: the chip-lowering validations that have never run
# on silicon (VERDICT r3 missing #2).  These alone make a window count.
run 600  python scripts/hw_kernel_check.py
run 900  env BENCH_ON_TPU=1 python scripts/conv_bn_probe.py
# Tier 2 — the throughput evidence: plain bench (warms the persistent
# compile cache bench.py itself uses, so the driver's own end-of-round
# `python bench.py` run is warm), then the fused-vs-plain verdict run.
# Budgets are silicon-calibrated (r5, 2026-08-01): the ResNet-50 train
# step compiles in >9 min cold through the tunneled transport on this
# 1-core host — the old 1200 s stage / 600 s init leash killed two live
# attempts mid-compile and banked nothing.  One attempt, one long leash:
# a re-exec restarts the compile from scratch (partial compiles cache
# nothing), so retries only help against a genuinely dead transport,
# which the probe already screens for.
run 3300 env BENCH_INIT_TIMEOUT=2400 BENCH_TOTAL_BUDGET=3120 \
    BENCH_MAX_ATTEMPTS=1 python bench.py
run 3300 env BENCH_INIT_TIMEOUT=2400 BENCH_TOTAL_BUDGET=3120 \
    BENCH_MAX_ATTEMPTS=1 BLUEFOG_FUSED_CONV_BN=1 python bench.py
# Pair THIS window's two runs into FUSED_VERDICT.json (no device work —
# the r3 item-#2 deliverable lands even with no session live to read the
# log; --since refuses stale cross-session pairings).
python scripts/fused_verdict.py --since "$QSTART" 2>&1 | tee -a "$LOG"
[ "${PIPESTATUS[0]}" -ne 0 ] && FAILED=$((FAILED + 1))
# Tier 3 — ablations and tuning sweeps.
# Stage-gated fusion ablation (r5 silicon: conv2_x 4.79x, conv4_x 6.99x,
# conv5_x ~1.0 — fuse only where the probe proved a win); runs AFTER the
# all-stage fused_verdict pairing above so it can't displace it.
run 3300 env BENCH_INIT_TIMEOUT=2400 BENCH_TOTAL_BUDGET=3120 \
    BENCH_MAX_ATTEMPTS=1 BLUEFOG_FUSED_CONV_BN=1 BLUEFOG_FUSED_STAGES=2,4 \
    python bench.py
run 2400 python scripts/perf_probe.py
run 2400 python scripts/flash_tune.py
run 1800 python scripts/lm_bench.py
run 1800 python scripts/lm_bench.py --remat
run 1200 env BENCH_ON_TPU=1 python scripts/single_ops_bench.py
run 1800 python scripts/scale_bench.py
# convergence_parity is 8-rank CPU-mesh work (the single tunneled chip
# cannot host 8 ranks) — run it outside the hardware window:
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
#       python scripts/convergence_parity.py --include-resnet
echo "hw queue done $(date -u +%FT%TZ), $FAILED stage(s) failed" | tee -a "$LOG"
exit $((FAILED > 0))
