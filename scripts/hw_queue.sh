#!/usr/bin/env bash
# The round-3 hardware re-verification queue (VERDICT r2 #1/#2), one
# command: run every hardware-blocked measurement in priority order and
# tee everything to a log the round can cite.  Safe to re-run; each stage
# is independent.  Requires a live TPU backend.
set -uo pipefail
cd "$(dirname "$0")/.."
LOG=${1:-hw_queue_r3.log}
FAILED=0
# Probe before each stage — do not let a dead transport eat each
# stage's full 1200s timeout.  Exit 9 tells hw_watch.sh to resume
# watching.
. scripts/_probe.sh   # cwd is the repo root (cd above)
run() {
    if ! probe; then
        echo "=== transport dead before: $* — aborting queue (exit 9) ===" | tee -a "$LOG"
        exit 9
    fi
    echo "=== $* ===" | tee -a "$LOG"
    timeout -k 30 "${STAGE_TIMEOUT:-1200}" "$@" 2>&1 | tee -a "$LOG"
    local rc=${PIPESTATUS[0]}
    echo "=== exit $rc ===" | tee -a "$LOG"
    [ "$rc" -ne 0 ] && FAILED=$((FAILED + 1))
    return 0
}
echo "hw queue started $(date -u +%FT%TZ)" | tee -a "$LOG"
run python bench.py
# Warm the persistent compile cache for the driver's entry() compile
# check (same cache bench.py/__graft_entry__.py point at).
run python -c 'import __graft_entry__ as g, jax; fn, args = g.entry(); jax.jit(fn).lower(*args).compile(); print("entry cache warm")'
run python scripts/hw_kernel_check.py
run env BENCH_ON_TPU=1 python scripts/conv_bn_probe.py
run env BLUEFOG_FUSED_CONV_BN=1 python bench.py
run python scripts/perf_probe.py
run python scripts/flash_tune.py
run python scripts/lm_bench.py
run python scripts/lm_bench.py --remat
run env BENCH_ON_TPU=1 python scripts/single_ops_bench.py
run python scripts/scale_bench.py
# convergence_parity is 8-rank CPU-mesh work (the single tunneled chip
# cannot host 8 ranks) — run it outside the hardware window:
#   XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
#       python scripts/convergence_parity.py --include-resnet
echo "hw queue done $(date -u +%FT%TZ), $FAILED stage(s) failed" | tee -a "$LOG"
exit $((FAILED > 0))
