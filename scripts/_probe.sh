# Shared transport probe, sourced by hw_watch.sh and hw_queue.sh so the
# two agree on what "transport alive" means: a cheap REAL computation —
# a half-alive transport answers device enumeration but hangs every
# compile/execute RPC (the r2->r3 outage mode).
probe() {
    timeout -k 30 "${PROBE_TIMEOUT:-300}" python -c '
import jax, jax.numpy as jnp
y = jax.jit(lambda a: (a @ a).sum())(jnp.ones((256, 256)))
assert float(y) == 256.0 ** 3  # ones @ ones: each entry 256, summed over 256*256
print("PROBE_OK", jax.devices()[0].platform, flush=True)
' 2>&1 | grep -q PROBE_OK
}
