"""Mock bench.py for the fake-transport hw_queue integration test.

Writes the same provenance-log lines the real bench writes (start line
with the fused flag + config, RESULT / partial RESULT / SKIP) so the
REAL scripts/fused_verdict.py downstream of the two bench stages pairs
or refuses exactly as it would on hardware.  Behavior comes from argv
(the PATH shim forwards the `.behavior` spec): ``ok <img_s>``,
``partial <img_s>``, or ``fail``.
"""

import json
import os
import sys
import time

METRIC = "resnet50_bs64_neighbor_allreduce_images_per_sec_per_chip"
CFG = ("batch=64 image=224 windows=5/25 iters=4 "
       f"fused={os.environ.get('BLUEFOG_FUSED_CONV_BN', '0')} "
       "init_timeout=600 total_budget=1140")


def line(msg):
    with open(os.environ["BENCH_RUN_LOG"], "a") as f:
        f.write(f"{time.strftime('%Y-%m-%dT%H:%M:%SZ', time.gmtime())} "
                f"[pid {os.getpid()}] {msg}\n")


def main():
    behavior = sys.argv[1] if len(sys.argv) > 1 else "ok"
    value = float(sys.argv[2]) if len(sys.argv) > 2 else 2500.0
    fused = os.environ.get("BLUEFOG_FUSED_CONV_BN", "0") == "1"
    if behavior == "fail-fused":
        # plain stage banks a number, fused stage dies: the refusal case
        behavior = "fail" if fused else "ok"
    if fused and behavior in ("ok", "partial"):
        value = round(value * 1.04, 1)   # distinct sides -> a real speedup
    line(f"start attempt 1: {CFG}")
    if behavior == "fail":
        # mirrors the real watchdog: an unreachable backend is a SKIP
        # record (exit 0, no value key) — never a value-0.0 "measurement"
        skip = {"metric": METRIC, "status": "skipped",
                "unit": "img/sec/chip",
                "reason": "accelerator backend unreachable (mock)"}
        line(f"SKIP {json.dumps(skip)}")
        print(json.dumps(skip))
        sys.exit(0)
    out = {"metric": METRIC, "value": value, "unit": "img/sec/chip",
           "vs_baseline": round(value / 269.4, 3), "communication": "none",
           "timing": "two-window-differenced"}
    if behavior == "partial":
        out.update(partial=True, pairs_done=1, pairs_total=4)
        line(f"RESULT {json.dumps(out)} (partial, est so far: [0.02])")
    else:
        line(f"RESULT {json.dumps(out)} (per-pair step times: [0.02])")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
