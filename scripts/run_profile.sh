#!/usr/bin/env bash
# Profiling helper (reference counterpart: scripts/run_profile.sh, which
# drove nvprof over the benchmark).  TPU-native: captures an XLA profiler
# trace of the decentralized ResNet train step; open the output directory
# with TensorBoard (or xprof) to see per-op device timelines, or set
# BLUEFOG_TIMELINE for the built-in chrome-tracing view.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT="${1:-/tmp/bluefog_tpu_profile}"
echo "Writing profiler trace to $OUT"

python - "$OUT" <<'PYEOF'
import sys, os
import jax
# default to the virtual CPU mesh; PROFILE_ON_TPU=1 profiles real chips.
# (Querying jax.devices() to auto-detect would hang if the TPU transport
# is wedged, so the choice is explicit.)
if os.environ.get("PROFILE_ON_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp
import numpy as np
import optax
import bluefog_tpu as bf
from bluefog_tpu import training as T
from bluefog_tpu.models.resnet import ResNet18

out_dir = sys.argv[1]
bf.init()
n = bf.size()
model = ResNet18(num_classes=100, dtype=jnp.float32)
base = optax.sgd(0.05, momentum=0.9)
variables, opt_state = T.create_train_state(
    model, base, jax.random.key(0), jnp.zeros((1, 64, 64, 3)))
rng = np.random.default_rng(0)
x = jnp.asarray(rng.normal(size=(n, 8, 64, 64, 3)), jnp.float32)
y = jnp.asarray(rng.integers(0, 100, size=(n, 8)))
step = T.make_train_step(model, base, donate=False)

# warmup/compile outside the trace
variables, opt_state, _ = step(variables, opt_state, (x, y), jnp.int32(0))

with jax.profiler.trace(out_dir):
    for i in range(1, 6):
        variables, opt_state, loss = step(variables, opt_state, (x, y),
                                          jnp.int32(i))
    jax.block_until_ready(loss)
print(f"trace written; loss={float(loss):.4f}")
print(f"view with: tensorboard --logdir {out_dir}")
PYEOF
