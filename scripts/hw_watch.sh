#!/usr/bin/env bash
# Transport watcher: probe the accelerator backend with a cheap real
# computation (device enumeration alone is NOT proof — the round-2→3
# outage left enumeration answering while every compile/execute RPC hung
# forever) and fire the full hardware queue (hw_queue.sh) the moment the
# compute path works.  Runs until the queue COMPLETES once: a queue
# aborted mid-run by a dead transport (exit 9) sends the watcher back to
# watching, and the queue is re-fired on the next alive window.
#
#   bash scripts/hw_watch.sh [probe_interval_seconds] [queue_log]
set -uo pipefail
cd "$(dirname "$0")/.."
INTERVAL=${1:-300}
LOG=${2:-hw_queue_r5.log}

. scripts/_probe.sh

while true; do
    if probe; then
        echo "$(date -u +%FT%TZ) transport alive — launching hw queue"
        bash scripts/hw_queue.sh "$LOG"
        rc=$?
        if [ "$rc" -eq 9 ]; then
            # transport died mid-queue; stages are independent and safe
            # to re-run — go back to watching and re-fire on the next
            # alive window (the log appends, later runs supersede)
            echo "$(date -u +%FT%TZ) queue aborted on dead transport; resuming watch"
        else
            exit "$rc"
        fi
    else
        echo "$(date -u +%FT%TZ) transport still dead (compute probe failed); retry in ${INTERVAL}s"
    fi
    sleep "$INTERVAL"
done
