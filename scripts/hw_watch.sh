#!/usr/bin/env bash
# Transport watcher: probe the accelerator backend with a cheap real
# computation (device enumeration alone is NOT proof — the round-2→3
# outage left enumeration answering while every compile/execute RPC hung
# forever) and fire the full hardware queue (hw_queue.sh) the moment the
# compute path works.  Runs until the queue has been launched once.
#
#   bash scripts/hw_watch.sh [probe_interval_seconds] [queue_log]
set -uo pipefail
cd "$(dirname "$0")/.."
INTERVAL=${1:-300}
LOG=${2:-hw_queue_r3.log}
PROBE_TIMEOUT=${PROBE_TIMEOUT:-180}

probe() {
    timeout "$PROBE_TIMEOUT" python -c '
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
y = jax.jit(lambda a: (a @ a).sum())(x)
assert float(y) == 256.0 * 256
print("PROBE_OK", jax.devices()[0].platform, flush=True)
' 2>&1 | grep -q PROBE_OK
}

while true; do
    if probe; then
        echo "$(date -u +%FT%TZ) transport alive — launching hw queue"
        bash scripts/hw_queue.sh "$LOG"
        exit $?
    fi
    echo "$(date -u +%FT%TZ) transport still dead (compute probe failed); retry in ${INTERVAL}s"
    sleep "$INTERVAL"
done
