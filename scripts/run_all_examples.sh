#!/usr/bin/env bash
# Integration gate: smoke-run every example x optimizer combination on the
# virtual 8-device CPU mesh (reference parity: test/test_all_example.sh).
set -euo pipefail
cd "$(dirname "$0")/.."

export XLA_FLAGS="--xla_force_host_platform_device_count=8"

run() {
    local name="$1"; shift
    echo "=== $name ==="
    timeout 300 python - "$@" <<PYEOF
import jax; jax.config.update("jax_platforms", "cpu")
import runpy, sys
script = sys.argv[1]
sys.argv = sys.argv[1:]
runpy.run_path(script, run_name="__main__")
PYEOF
}

run consensus-static   examples/average_consensus.py --max-iters 60 --data-size 1000
run consensus-dynamic  examples/average_consensus.py --max-iters 80 --data-size 1000 --enable-dynamic-topology
run opt-nar            examples/optimization.py --max-iters 300
run opt-atc            examples/optimization.py --max-iters 300 --method atc
run opt-pushsum        examples/optimization.py --max-iters 300 --method push_sum
run opt-gradar         examples/optimization.py --max-iters 300 --method gradient_allreduce
run opt-exactdiff      examples/optimization.py --max-iters 500 --method exact_diffusion
run mnist-nar          examples/mnist.py --epochs 1 --batch-size 128
run mnist-gradar       examples/mnist.py --epochs 1 --batch-size 128 --dist-optimizer gradient_allreduce --disable-dynamic-topology
run mnist-atc          examples/mnist.py --epochs 1 --batch-size 128 --atc-style
run resnet-tiny        examples/resnet.py --model ResNet18 --epochs 1 --steps-per-epoch 4 --batch-size 4 --image-size 32 --dtype float32
run bench-tiny         examples/benchmark.py --model ResNet18 --batch-size 4 --image-size 64 --num-iters 2 --num-batches-per-iter 2 --num-warmup-batches 2 --dtype float32
run lm-ring            examples/long_context_lm.py --seq-len 256 --steps 3 --dim 64 --layers 1
run lm-ulysses         examples/long_context_lm.py --seq-len 256 --steps 3 --dim 64 --layers 1 --attn ulysses
run lm-remat           examples/long_context_lm.py --seq-len 256 --steps 3 --dim 64 --layers 1 --remat
run lm-gqa             examples/long_context_lm.py --seq-len 256 --steps 3 --dim 64 --layers 1 --heads 4 --kv-heads 2
run chaos-killrank     examples/chaos_training.py --steps 30 --dim 8
run serving-failover   examples/decentralized_serving.py --steps 16 --requests 4 --kill-step 7 --prefix /tmp/bf_serving_example_

# The two notebooks execute for real (reference parity: the notebooks are
# its interactive-mode showcase, examples/interactive_bluefog.ipynb).
# nbconvert runs each kernel in the notebook's own directory, which the
# notebooks' `sys.path.insert(0, abspath(".."))` bootstrap expects; they
# pin the 8-device CPU mesh themselves in their first cell.
run_nb() {
    local name="$1"; shift
    echo "=== $name ==="
    if ! python -c "import nbconvert, ipykernel" 2>/dev/null; then
        echo "run_all_examples: nbconvert/ipykernel missing — install the" \
             "'test' extra (pip install -e .[test]) to run the notebook legs" >&2
        exit 1
    fi
    timeout 900 python -m nbconvert --to notebook --execute --stdout \
        --ExecutePreprocessor.timeout=600 "$1" > /dev/null
}

run_nb nb-helloworld   examples/interactive_helloworld.ipynb
run_nb nb-resource     examples/resource_allocation.ipynb

echo "ALL EXAMPLES PASSED"
