"""Scaling-efficiency harness: per-chip throughput vs. device count.

One command, pod-ready (VERDICT r2 #3 / BASELINE.md row 2 — the reference
reports >95 % scaling on 128 V100 for ResNet-50; target >=90 %): runs the
IDENTICAL decentralized train step bench.py times, over 1, 2, 4, ...,
len(jax.devices()) chips, and prints one JSON line per point plus a
summary::

    python scripts/scale_bench.py
    {"n_chips": 1, "img_per_sec_per_chip": ..., "efficiency_vs_1chip": 1.0}
    {"n_chips": 8, "img_per_sec_per_chip": ..., "efficiency_vs_1chip": ...}
    {"metric": "resnet50_scaling_efficiency", "value": ..., ...}

On today's single tunneled chip it degenerates to the 1-chip point
(efficiency 1.0 by definition); on a pod slice it produces the BASELINE
scaling figure unmodified.  CPU-mesh plumbing test::

    XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
        SCALE_BENCH_TINY=1 python scripts/scale_bench.py

Env knobs: BENCH_BATCH (per-chip batch, default 64), BENCH_IMAGE,
BENCH_WINDOW_SMALL/LARGE + BENCH_ITERS (timing windows, see bench.py),
SCALE_BENCH_POINTS (comma list of chip counts, default powers of two),
SCALE_BENCH_TINY=1 (ResNet-18 @ 32px batch 2 — plumbing only).
"""

import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import bench
import bluefog_tpu as bf
from bluefog_tpu import training as T


def _points(n_total: int):
    env = os.environ.get("SCALE_BENCH_POINTS")
    if env:
        pts = sorted({int(p) for p in env.split(",")})
    else:
        pts, k = [], 1
        while k <= n_total:
            pts.append(k)
            k *= 2
        if pts[-1] != n_total:
            pts.append(n_total)
    bad = [p for p in pts if p < 1 or p > n_total]
    if bad:
        raise ValueError(f"chip counts {bad} exceed available {n_total}")
    return pts


def measure_point(devices, model_cls, batch, image, num_classes,
                  k_small, k_large, iters, warmup):
    """Per-chip img/s of the decentralized step on this device subset."""
    bf.shutdown()
    bf.init(devices=devices)
    n = bf.size()
    sched = None
    if n > 1:
        topo = bf.load_topology()
        sched = bf.compile_dynamic_schedule(
            lambda r: bf.GetDynamicOnePeerSendRecvRanks(topo, r), n)
    model = model_cls(num_classes=num_classes, dtype=jnp.bfloat16)
    base = optax.sgd(0.01, momentum=0.9)
    variables, opt_state = T.create_train_state(
        model, base, jax.random.key(0), jnp.zeros((1, image, image, 3)))
    step_fn = T.make_train_step(model, base,
                                communication="neighbor_allreduce",
                                sched=sched)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(n, batch, image, image, 3)), jnp.float32)
    y = jnp.asarray(rng.integers(0, num_classes, size=(n, batch)))

    loss = None
    step = 0
    for _ in range(warmup):
        variables, opt_state, loss = step_fn(
            variables, opt_state, (x, y), jnp.int32(step))
        step += 1
    _ = float(loss)  # scalar fetch: the reliable execution barrier

    def window(k):
        nonlocal variables, opt_state, loss, step
        import time
        t0 = time.perf_counter()
        for _ in range(k):
            variables, opt_state, loss = step_fn(
                variables, opt_state, (x, y), jnp.int32(step))
            step += 1
        _ = float(loss)
        return time.perf_counter() - t0

    dt, _, _ = bench.measure_step_time_amortized(window, k_small, k_large,
                                                 pairs=iters)
    return batch / dt   # per-chip: batch images per rank per step


def main():
    tiny = os.environ.get("SCALE_BENCH_TINY", "0") == "1"
    from bluefog_tpu.models.resnet import ResNet18, ResNet50
    model_cls = ResNet18 if tiny else ResNet50
    batch = int(os.environ.get("BENCH_BATCH", "2" if tiny else "64"))
    image = int(os.environ.get("BENCH_IMAGE", "32" if tiny else "224"))
    num_classes = 10 if tiny else 1000
    warmup = int(os.environ.get("BENCH_WARMUP", "1" if tiny else "3"))
    iters = int(os.environ.get("BENCH_ITERS", "2" if tiny else "3"))
    k_small = int(os.environ.get("BENCH_WINDOW_SMALL", "1" if tiny else "5"))
    k_large = int(os.environ.get("BENCH_WINDOW_LARGE", "3" if tiny else "25"))

    devices = jax.devices()
    pts = _points(len(devices))
    base_rate = None
    results = []
    for k in pts:
        rate = measure_point(devices[:k], model_cls, batch, image,
                             num_classes, k_small, k_large, iters, warmup)
        if base_rate is None:
            base_rate = rate
        eff = rate / base_rate
        point = {"n_chips": k,
                 "img_per_sec_per_chip": round(rate, 1),
                 "efficiency_vs_1chip": round(eff, 3)}
        results.append(point)
        print(json.dumps(point), flush=True)
    bf.shutdown()

    last = results[-1]
    print(json.dumps({
        "metric": ("resnet18_tiny_scaling_efficiency" if tiny
                   else "resnet50_scaling_efficiency"),
        "value": last["efficiency_vs_1chip"],
        "unit": f"per-chip efficiency at {last['n_chips']} chips",
        # BASELINE.md row 2: reference >95 % at 128 V100; target >=90 %
        "vs_baseline": round(last["efficiency_vs_1chip"] / 0.95, 3),
        "points": results,
    }))


if __name__ == "__main__":
    main()
