"""Micro-benchmark every collective (reference: scripts/single_ops_test.py,
which timed individual MPI/NCCL ops).

Times each op over a range of tensor sizes on the active mesh (real TPU
slice, or the virtual CPU mesh by default) and prints a table of
microseconds/op plus achieved algorithmic bandwidth.  Useful for checking
that neighbor_allreduce stays O(degree) rather than O(N), and for comparing
the XLA ppermute path against the fused Pallas kernel on real hardware.

Usage:
    python scripts/single_ops_bench.py [--sizes 4096,262144,4194304]
    BENCH_ON_TPU=1 python scripts/single_ops_bench.py   # real chips
"""

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

if os.environ.get("BENCH_ON_TPU") != "1":
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

import bluefog_tpu as bf


from bench import timeit_amortized  # noqa: E402


def timeit(fn, *args, iters=30, warmup=5):
    return timeit_amortized(lambda: fn(*args), n=iters, warmup=warmup)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="4096,262144,4194304",
                    help="elements per rank, comma separated")
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    bf.init()
    n = bf.size()
    topo = bf.load_topology()
    sched = bf.compile_dynamic_schedule(
        lambda r: bf.GetDynamicOnePeerSendRecvRanks(topo, r), n)
    pairs = [(i, i + 1) for i in range(0, n - 1, 2)]

    def _with_backend(backend, fn):
        """Run fn with BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND pinned."""
        def wrapped(x):
            prev = os.environ.get("BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND")
            os.environ["BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND"] = backend
            try:
                return fn(x)
            finally:
                if prev is None:
                    os.environ.pop("BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND",
                                   None)
                else:
                    os.environ["BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND"] = prev
        return wrapped

    # the Pallas fused exchange only compiles on real TPU hardware; the
    # interpreter variant is for semantics tests, far too slow to time
    # (set BENCH_FORCE_PALLAS=1 to include it on a CPU mesh anyway)
    on_tpu = jax.devices()[0].platform == "tpu"
    with_pallas = on_tpu or os.environ.get("BENCH_FORCE_PALLAS") == "1"
    pallas_backend = "pallas" if on_tpu else "pallas_interpret"
    ops = {
        "allreduce": lambda x: bf.allreduce(x),
        "broadcast(0)": lambda x: bf.broadcast(x, root_rank=0),
        "allgather": lambda x: bf.allgather(x),
        "neighbor_allreduce": lambda x: bf.neighbor_allreduce(x),
        "nar[pallas]": _with_backend(
            pallas_backend, lambda x: bf.neighbor_allreduce(x)),
        "nar_dynamic(step=1)": lambda x: bf.neighbor_allreduce(
            x, sched=sched, step=1),
        "nar_dynamic[pallas]": _with_backend(
            pallas_backend,
            lambda x: bf.neighbor_allreduce(x, sched=sched, step=1)),
        "pair_gossip": lambda x: bf.pair_gossip(x, pairs),
    }
    if not with_pallas or os.environ.get("BENCH_SKIP_PALLAS") == "1":
        ops = {k: v for k, v in ops.items() if "pallas" not in k}

    sizes = [int(s) for s in args.sizes.split(",")]
    # build + place each input ONCE: to_global pre-shards over the rank
    # axis so the timed region measures the collective, not a host->device
    # reshard of the unplaced array on every iteration
    inputs = {}
    rng = np.random.default_rng(0)
    for elems in sizes:
        inputs[elems] = bf.to_global(jnp.asarray(
            rng.normal(size=(n, elems)), jnp.float32))

    plat = jax.devices()[0].platform
    print(f"mesh: {n} x {plat}; per-rank element counts: {args.sizes}")
    header = f"{'op':22s}" + "".join(f"{s:>17,d}" for s in sizes)
    print(header)
    print("-" * len(header))
    for name, fn in ops.items():
        row = f"{name:22s}"
        for elems in sizes:
            dt = timeit(fn, inputs[elems], iters=args.iters)
            bw = elems * 4 / dt / 1e9   # GB/s of per-rank payload
            row += f"{dt * 1e6:>8.0f}us {bw:7.2f}"
        print(row)
    print("(second number per column: per-rank payload GB/s)")

    # window fusion: the same total payload as ONE pytree window vs N_WIN
    # per-leaf windows (ops/windows.py fusion-buffer equivalent) — the
    # dispatch-count ablation behind the window optimizers' design
    n_win = int(os.environ.get("BENCH_WIN_LEAVES", "32"))
    elems = sizes[0]
    leaf = bf.to_global(jnp.asarray(
        rng.normal(size=(n, max(1, elems // n_win))), jnp.float32))
    leaves = [leaf] * n_win
    for name in list(bf.get_current_created_window_names()):
        bf.win_free(name)
    bf.win_create(leaves, "fused_tree", zero_init=True)
    for i in range(n_win):
        bf.win_create(leaf, f"leafwin.{i}", zero_init=True)

    def tree_roundtrip(xs):
        bf.win_put(xs, "fused_tree")
        return bf.win_update("fused_tree")[0]

    def per_leaf_roundtrip(xs):
        for i, x in enumerate(xs):
            bf.win_put(x, f"leafwin.{i}")
        return [bf.win_update(f"leafwin.{i}") for i in range(n_win)][0]

    dt_tree = timeit(tree_roundtrip, leaves, iters=max(args.iters // 3, 3))
    dt_leaf = timeit(per_leaf_roundtrip, leaves,
                     iters=max(args.iters // 3, 3))
    print(f"\nwindow put+update, {n_win} leaves x "
          f"{max(1, elems // n_win):,d} elems:")
    print(f"  one pytree window : {dt_tree * 1e6:>8.0f}us")
    print(f"  per-leaf windows  : {dt_leaf * 1e6:>8.0f}us "
          f"({dt_leaf / dt_tree:.1f}x)")
    bf.win_free()
    bf.shutdown()


if __name__ == "__main__":
    main()
