"""Hardware self-test for every Pallas kernel in the framework.

Round-2 lesson: the Pallas interpreter (CPU test meshes) does NOT enforce
TPU tiling rules or surface Mosaic lowering errors — round 1's flash
kernel passed its whole interpret-mode suite and then failed to lower on
the first real-hardware run.  This script compiles and runs each kernel
on the real chip and checks numerics against an exact float64 host
reference, so a lowering regression is caught the same day it is written,
not at round end.

    python scripts/hw_kernel_check.py          # requires a TPU backend
    make hwcheck

Exit code 0 = every kernel lowered and matched; nonzero otherwise.
"""

import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax

# honor an explicit CPU request before any device query: the axon site
# customization pins the platform config, so the env var alone is not
# enough (same dance as __graft_entry__ / run_profile.sh)
if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np

FAILED = []


def check(name, fn):
    print(f"{name:40s}", end="", flush=True)
    try:
        fn()
        print("ok", flush=True)
    except Exception as e:  # noqa: BLE001 — report every kernel, then fail
        FAILED.append(name)
        print(f"FAIL: {type(e).__name__}: {str(e)[:200]}", flush=True)


def exact_attention(qn, kn, vn, causal):
    D = qn.shape[-1]
    s = np.einsum("bthd,bshd->bhts", qn, kn) * (D ** -0.5)
    if causal:
        T, S = s.shape[2], s.shape[3]
        s = np.where(np.tril(np.ones((T, S), bool))[None, None], s, -np.inf)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhts,bshd->bthd", p, vn)


def flash_forward():
    from bluefog_tpu.ops.flash_attention import flash_attention
    rng = np.random.default_rng(0)
    B, T, H, D = 2, 512, 4, 64
    qn, kn, vn = (rng.normal(size=(B, T, H, D)) for _ in range(3))
    q, k, v = (jnp.asarray(a, jnp.float32) for a in (qn, kn, vn))
    o = np.asarray(flash_attention(q, k, v, causal=True), np.float64)
    err = np.abs(o - exact_attention(qn, kn, vn, True)).max()
    # MXU default precision (bf16 multiplies) bounds the achievable error
    assert err < 5e-2, f"fwd err {err}"


def flash_backward():
    from bluefog_tpu.ops.flash_attention import flash_attention_trainable
    from bluefog_tpu.ops.ring_attention import attention as ref_attn
    rng = np.random.default_rng(1)
    B, T, H, D = 2, 512, 4, 64
    q, k, v = (jnp.asarray(rng.normal(size=(B, T, H, D)), jnp.float32)
               for _ in range(3))

    def grads(fn, q, k, v):
        # fn is a Python callable: closed over via partial, jitted per fn
        return jax.jit(jax.grad(
            lambda a, b, c: (fn(a, b, c) ** 2).sum(),
            argnums=(0, 1, 2)))(q, k, v)

    gf = grads(lambda a, b, c: flash_attention_trainable(a, b, c,
                                                         causal=True),
               q, k, v)
    gr = grads(lambda a, b, c: ref_attn(a, b, c, causal=True), q, k, v)
    for name, a, b in zip("qkv", gf, gr):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(b).max() + 1e-9))
        assert rel < 3e-2, f"d{name} rel err {rel}"


def flash_lse_offsets():
    from bluefog_tpu.ops.flash_attention import flash_attention_with_lse
    rng = np.random.default_rng(2)
    q, k, v = (jnp.asarray(rng.normal(size=(1, 256, 2, 64)), jnp.float32)
               for _ in range(3))
    o, lse = flash_attention_with_lse(q, k, v, causal=True,
                                      q_offset=jnp.int32(256),
                                      k_offset=jnp.int32(0))
    assert bool(jnp.isfinite(lse).all()), "non-finite lse"
    assert o.shape == q.shape


def flash_odd_length():
    # 128-granular but not 512-granular length: _fit_block must adapt
    from bluefog_tpu.ops.flash_attention import flash_attention
    rng = np.random.default_rng(3)
    qn, kn, vn = (rng.normal(size=(1, 768, 2, 64)) for _ in range(3))
    q, k, v = (jnp.asarray(a, jnp.float32) for a in (qn, kn, vn))
    o = np.asarray(flash_attention(q, k, v, causal=False), np.float64)
    err = np.abs(o - exact_attention(qn, kn, vn, False)).max()
    assert err < 5e-2, f"err {err}"


def flash_whole_odd_length():
    # T=100: not a multiple of 8, so the single whole-length block rides
    # the 'block dim == array dim' tiling exemption — prove that lowers
    # (flash_supported keeps auto-dispatch off such shapes; this covers
    # direct calls)
    from bluefog_tpu.ops.flash_attention import flash_attention
    rng = np.random.default_rng(5)
    qn, kn, vn = (rng.normal(size=(1, 100, 2, 64)) for _ in range(3))
    q, k, v = (jnp.asarray(a, jnp.float32) for a in (qn, kn, vn))
    o = np.asarray(flash_attention(q, k, v, causal=False), np.float64)
    err = np.abs(o - exact_attention(qn, kn, vn, False)).max()
    assert err < 5e-2, f"err {err}"


def conv_bn_stats_epilogue():
    from bluefog_tpu.ops.conv_bn import matmul_bn_stats
    rng = np.random.default_rng(6)
    x = jnp.asarray(rng.normal(size=(2048, 256)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(256, 128)) / 16.0, jnp.bfloat16)
    y, mean, var = matmul_bn_stats(x, w)
    ref = (x.astype(jnp.float32) @ w.astype(jnp.float32))
    err = float(jnp.max(jnp.abs(y.astype(jnp.float32) - ref)) /
                (jnp.abs(ref).max() + 1e-9))
    assert err < 3e-2, f"y rel err {err}"
    m_err = float(jnp.max(jnp.abs(mean - ref.mean(0))))
    assert m_err < 5e-2, f"mean err {m_err}"


def conv_bn_normalize_prologue():
    from bluefog_tpu.ops.conv_bn import bn_relu_matmul
    rng = np.random.default_rng(7)
    K = 128
    x = jnp.asarray(rng.normal(size=(2048, K)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(K, 128)) / 11.3, jnp.bfloat16)
    mean = jnp.asarray(rng.normal(size=(K,)), jnp.float32)
    var = jnp.asarray(rng.uniform(0.5, 2.0, size=(K,)), jnp.float32)
    gamma = jnp.asarray(rng.normal(size=(K,)), jnp.float32)
    beta = jnp.asarray(rng.normal(size=(K,)), jnp.float32)
    out = bn_relu_matmul(x, mean, var, gamma, beta, w)
    xn = (x.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + 1e-5)
    ref = jnp.maximum(xn * gamma + beta, 0.0).astype(
        jnp.bfloat16).astype(jnp.float32) @ w.astype(jnp.float32)
    err = float(jnp.max(jnp.abs(out.astype(jnp.float32) - ref)) /
                (jnp.abs(ref).max() + 1e-9))
    assert err < 3e-2, f"rel err {err}"


def conv_bn_combined_kernel():
    from bluefog_tpu.ops.conv_bn import bn_relu_matmul_stats
    rng = np.random.default_rng(8)
    K = 128
    x = jnp.asarray(rng.normal(size=(2048, K)), jnp.bfloat16)
    w = jnp.asarray(rng.normal(size=(K, 256)) / 11.3, jnp.bfloat16)
    mean = jnp.zeros((K,), jnp.float32)
    var = jnp.ones((K,), jnp.float32)
    gamma = jnp.ones((K,), jnp.float32)
    beta = jnp.zeros((K,), jnp.float32)
    y, my, vy = bn_relu_matmul_stats(x, mean, var, gamma, beta, w)
    xn = jnp.maximum(x.astype(jnp.float32) *
                     jax.lax.rsqrt(jnp.float32(1 + 1e-5)), 0.0)
    ref = xn.astype(jnp.bfloat16).astype(jnp.float32) @ w.astype(jnp.float32)
    err = float(jnp.max(jnp.abs(y.astype(jnp.float32) - ref)) /
                (jnp.abs(ref).max() + 1e-9))
    assert err < 3e-2, f"y rel err {err}"
    assert float(jnp.max(jnp.abs(my - ref.mean(0)))) < 5e-2
    # vy exercises the sumsq/_pad8 tile path — the exact layout class the
    # round-1 flash lesson is about
    v_err = float(jnp.max(jnp.abs(vy - jnp.var(ref, axis=0))) /
                  (float(jnp.var(ref)) + 1e-9))
    assert v_err < 5e-2, f"vy rel err {v_err}"


def fused_bottleneck_train_grad():
    # the full fused bottleneck (both kernels + custom VJPs) compiles and
    # differentiates on hardware with ResNet-50 stage-2 shapes, bf16
    import flax.linen as nn
    from functools import partial as _p
    from bluefog_tpu.models.resnet import FusedBottleneckBlock
    conv = _p(nn.Conv, use_bias=False, dtype=jnp.bfloat16,
              param_dtype=jnp.float32)
    norm = _p(nn.BatchNorm, use_running_average=False, momentum=0.9,
              epsilon=1e-5, dtype=jnp.bfloat16, param_dtype=jnp.float32,
              axis_name=None)
    blk = FusedBottleneckBlock(filters=64, strides=(1, 1), conv=conv,
                               norm=norm, act=nn.relu)
    x = jnp.asarray(np.random.default_rng(9).normal(size=(8, 56, 56, 256)),
                    jnp.bfloat16)
    variables = blk.init(jax.random.key(0), x)

    @jax.jit
    def loss_grad(params):
        def loss(p):
            out, _ = blk.apply(
                {"params": p,
                 "batch_stats": variables["batch_stats"]}, x,
                mutable=["batch_stats"])
            return (out.astype(jnp.float32) ** 2).mean()
        return jax.value_and_grad(loss)(params)

    val, grads = loss_grad(variables["params"])
    assert bool(jnp.isfinite(val)), f"loss {val}"
    assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))


def fused_exchange_single_device():
    # degenerate 1-device mesh: checks the kernel LOWERS on hardware
    # (exchange semantics need a multi-chip slice, tested on CPU mesh)
    from jax.sharding import Mesh
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P
    from bluefog_tpu.ops.pallas_kernels import fused_neighbor_allreduce
    from bluefog_tpu.parallel.schedule import compile_topology
    from bluefog_tpu.parallel.topology import FullyConnectedGraph

    topo = compile_topology(FullyConnectedGraph(1))
    mesh = Mesh(np.array(jax.devices()[:1]), ("r",))
    x = jnp.asarray(np.random.default_rng(4).normal(size=(1, 8, 128)),
                    jnp.float32)
    out = shard_map(
        lambda s: fused_neighbor_allreduce(s[0], "r", topo)[None],
        mesh=mesh, in_specs=P("r"), out_specs=P("r"))(x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


def main():
    backend = jax.default_backend()
    print(f"backend: {backend}; device: {jax.devices()[0].device_kind}")
    if backend != "tpu":
        print("SKIP: hardware kernel check requires a TPU backend "
              "(interpret-mode coverage lives in tests/)")
        return 0
    check("flash_attention forward vs float64", flash_forward)
    check("flash_attention backward vs XLA grad", flash_backward)
    check("flash_attention lse + traced offsets", flash_lse_offsets)
    check("flash_attention 768-length block fit", flash_odd_length)
    check("flash_attention 100-length whole block", flash_whole_odd_length)
    check("conv_bn matmul stats epilogue", conv_bn_stats_epilogue)
    check("conv_bn normalize prologue matmul", conv_bn_normalize_prologue)
    check("conv_bn combined prologue+epilogue", conv_bn_combined_kernel)
    check("fused bottleneck fwd+bwd bf16", fused_bottleneck_train_grad)
    check("fused_neighbor_allreduce lowering", fused_exchange_single_device)
    if FAILED:
        print(f"\n{len(FAILED)} kernel check(s) FAILED: {FAILED}")
        return 1
    print("\nall hardware kernel checks passed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
