"""Fused-vs-plain conv+BN verdict from bench provenance logs.

The r3 verdict's item #2: ``ResNet50Fused`` (the HBM-roofline attack,
ops/conv_bn.py) is code without a hardware measurement.  The r4 queue
runs ``python bench.py`` (plain) then ``BLUEFOG_FUSED_CONV_BN=1 python
bench.py``; this stage pairs each run's start line (which records the
fused flag) with its RESULT line by pid in ``bench_runs.log`` and writes
``FUSED_VERDICT.json``:

  speedup > 1.03  -> "fused wins — flip the bench default"
  0.97..1.03      -> "bandwidth-neutral — XLA was already optimal"
  < 0.97          -> "fused loses — keep the XLA path"

Runs as the queue stage right after the two bench runs so the verdict
lands in the committed log even when no session is live to read it.

``--since <ISO-UTC>`` (the queue passes its own start stamp) ignores
older RESULT lines, so a bench stage that died this window can never be
silently paired against a stale measurement from a previous session;
the pair must also share the bench config (batch/windows/iters) and
timing mode, or the script refuses to rule.

bench.py also banks a RESULT line after EVERY completed timing pair
(``"partial": true``) so a transport death mid-run still leaves a
citable number; a later full RESULT from the same run supersedes its
partials (newest-wins).  A verdict built from one or two partial
measurements is accepted but marked ``"partial": true`` with each
side's pairs_done, so the reader knows its precision.
"""

import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOG = os.environ.get("BENCH_RUN_LOG", os.path.join(REPO, "bench_runs.log"))
# FUSED_VERDICT_OUT: test hook so integration runs (tests/test_hw_queue.py)
# never overwrite the repo's committed verdict artifact
OUT = os.environ.get("FUSED_VERDICT_OUT",
                     os.path.join(REPO, "FUSED_VERDICT.json"))

STAMP = re.compile(r"^(\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z) ")
START = re.compile(
    r"\[pid (\d+)\] start attempt \d+: (batch=\S+ image=\S+ windows=\S+ "
    r"iters=\S+) fused=(\d)(?: fused_stages=(\S+))?")
RESULT = re.compile(r"\[pid (\d+)\] RESULT (\{.*\}) \(")


def latest_results(path, since):
    """{fused_flag: (result_dict, config_str)} from the newest RESULT per
    flag stamped at/after ``since`` (lexicographic works: fixed ISO-UTC)."""
    started, out = {}, {}
    try:
        lines = open(path).read().splitlines()
    except OSError as e:
        raise SystemExit(f"fused_verdict: cannot read {path}: {e}")
    for line in lines:
        ts = STAMP.match(line)
        if not ts or (since and ts.group(1) < since):
            continue
        m = START.search(line)
        if m:
            started[m.group(1)] = (m.group(3) == "1", m.group(2),
                                   m.group(4) or "all")
            continue
        m = RESULT.search(line)
        if m and m.group(1) in started:
            try:
                r = json.loads(m.group(2))
            except ValueError:
                continue
            if r.get("value", 0) > 0:
                flag, config, stages = started[m.group(1)]
                out[flag] = (r, config, stages)   # newest wins
    return out


def main():
    since = None
    if len(sys.argv) > 2 and sys.argv[1] == "--since":
        since = sys.argv[2]
    res = latest_results(LOG, since)
    if False not in res or True not in res:
        have = sorted("fused" if k else "plain" for k in res)
        raise SystemExit(
            f"fused_verdict: need one plain and one fused RESULT in {LOG}"
            + (f" since {since}" if since else "")
            + f"; have {have or 'none'} — run the two bench stages first")
    (plain_r, plain_cfg, _), (fused_r, fused_cfg, fused_stages) = (
        res[False], res[True])
    if plain_cfg != fused_cfg:
        raise SystemExit(
            f"fused_verdict: non-comparable runs — plain [{plain_cfg}] vs "
            f"fused [{fused_cfg}]; rerun both stages with one config")
    if plain_r.get("timing") != fused_r.get("timing"):
        raise SystemExit(
            f"fused_verdict: timing modes differ ({plain_r.get('timing')} "
            f"vs {fused_r.get('timing')}); rerun — a differenced number "
            f"must not be compared against an amortized fallback")
    plain, fused = plain_r["value"], fused_r["value"]
    speedup = fused / plain
    # The verdict names the exact fused config it judged: a stage-gated
    # run (tier-3 ablation) must not masquerade as a judgment on the
    # all-stage default if it is the newest fused RESULT in the window.
    fused_env = ("BLUEFOG_FUSED_CONV_BN=1" if fused_stages == "all" else
                 f"BLUEFOG_FUSED_CONV_BN=1 BLUEFOG_FUSED_STAGES={fused_stages}")
    if speedup > 1.03:
        verdict = f"fused wins - flip the bench default ({fused_env})"
    elif speedup >= 0.97:
        verdict = (f"bandwidth-neutral ({fused_env}) - XLA already ran the "
                   "chain at the bytes roofline; keep the XLA default and "
                   "close the item")
    else:
        verdict = f"fused ({fused_env}) loses - keep the XLA path as default"
    out = {"plain_img_s": plain, "fused_img_s": fused,
           "speedup": round(speedup, 3), "verdict": verdict,
           "config": plain_cfg, "fused_stages": fused_stages,
           "since": since,
           "plain_result": plain_r, "fused_result": fused_r,
           "provenance": os.path.basename(LOG)}
    if plain_r.get("partial") or fused_r.get("partial"):
        # a mid-run transport death left only per-pair banked numbers on
        # one or both sides; still a real measurement, but say so
        out["partial"] = True
        out["pairs_done"] = {
            "plain": plain_r.get("pairs_done", "full"),
            "fused": fused_r.get("pairs_done", "full")}
    with open(OUT, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
