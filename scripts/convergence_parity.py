"""Convergence parity: decentralized vs centralized training quality.

The reference's public claim is that decentralized (neighbor-averaging)
training reaches the centralized solution (README.rst:48-49 — its accuracy
tables were left "TO BE ADDED"; VERDICT r2 #8 asks us to actually produce
them).  This script trains the SAME model/data/seed under

  * gradient_allreduce  — centralized Horovod-style baseline
  * neighbor_allreduce  — static exp2 topology (CTA)
  * neighbor_allreduce + dynamic one-peer schedule (the flagship mode)
  * exact_diffusion     — bias-corrected ATC (opt-in:
    --include-exact-diffusion; see ED_MODE note)

and prints a markdown table of final loss / held-out accuracy / cross-rank
consensus spread, plus one JSON line per run.

    python scripts/convergence_parity.py                 # LeNet MNIST leg
    python scripts/convergence_parity.py --include-resnet  # + ResNet-18 leg

CPU-mesh: XLA_FLAGS=--xla_force_host_platform_device_count=8
JAX_PLATFORMS=cpu (the MNIST leg takes ~2 min there; the ResNet leg is
sized for a single-core host via --resnet-batch, see its help).  This is
8-rank work — it belongs on the CPU mesh, not the single tunneled chip.
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "examples"))

# Low-core XLA:CPU hazards (rendezvous terminator, Eigen pool wedge —
# see env_util.arm_low_core_cpu_mitigations).  180 s terminator, not the
# 1200 s default: with inline Eigen the straggler spread into a
# collective is ~15 s on one core, while the flaky pool wedge (a device
# thread that NEVER arrives) is only detectable by timeout — a short
# terminator makes wedged legs cheap to retry (run_table_isolated).
# Must run before backend init; opt out: BLUEFOG_NO_XLA_FLAG_INJECT=1.
from bluefog_tpu.run.env_util import arm_low_core_cpu_mitigations  # noqa: E402

arm_low_core_cpu_mitigations(os.environ, terminate_timeout_s=180)

import jax

if "cpu" in os.environ.get("JAX_PLATFORMS", ""):
    jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp
import numpy as np
import optax

import bluefog_tpu as bf
from bluefog_tpu import training as T


def synthetic_cifar(n_samples=4096, seed=0, image=32):
    """Class-conditional blobs on a 3-channel canvas (same recipe as the
    mnist example's stand-in, examples/mnist.py:48-58)."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, 10, size=n_samples).astype(np.int32)
    x = rng.normal(0.0, 0.3, size=(n_samples, image, image, 3)).astype(
        np.float32)
    for c in range(10):
        r, col = divmod(c, 4)
        sel = y == c
        x[sel, 4 + 6 * r: 10 + 6 * r, 4 + 6 * col: 10 + 6 * col, c % 3] += 1.5
    return x, y


def run_one(model, sample_shape, x, y, x_test, y_test, communication,
            dynamic, lr, momentum, epochs, batch, seed):
    bf.shutdown()
    bf.init()
    n = bf.size()
    per_rank = len(x) // n
    xs = x[: per_rank * n].reshape((n, per_rank) + x.shape[1:])
    ys = y[: per_rank * n].reshape(n, per_rank)

    sched = None
    if dynamic and n > 1:
        topo = bf.load_topology()
        sched = bf.compile_dynamic_schedule(
            lambda r: bf.GetDynamicOnePeerSendRecvRanks(topo, r), n)
    if communication == "exact_diffusion":
        # ED needs symmetric doubly-stochastic mixing (the directed exp2
        # default is rejected by the builder)
        bf.set_topology(bf.SymmetricExponentialGraph(n), is_weighted=True)

    base = optax.sgd(lr, momentum=momentum)
    variables, opt_state = T.create_train_state(
        model, base, jax.random.key(seed), jnp.zeros((1,) + sample_shape),
        communication=communication)
    step_fn = T.make_train_step(model, base, communication=communication,
                                sched=sched, donate=False)

    steps_per_epoch = per_rank // batch
    rng = np.random.default_rng(seed)
    gstep = 0
    loss = None
    for _ in range(epochs):
        order = rng.permutation(per_rank)
        for s in range(steps_per_epoch):
            idx = order[s * batch:(s + 1) * batch]
            variables, opt_state, loss = step_fn(
                variables, opt_state,
                (jnp.asarray(xs[:, idx]), jnp.asarray(ys[:, idx])),
                jnp.int32(gstep))
            gstep += 1
    final_loss = float(loss)

    params = variables["params"]
    extra = {k: v for k, v in variables.items() if k != "params"}
    spread = max((float(jnp.max(jnp.abs(p - p.mean(axis=0, keepdims=True))))
                  for p in jax.tree.leaves(params)), default=0.0)

    # evaluate the CONSENSUS model (mean over ranks), like deploying the
    # averaged decentralized solution; batch_stats average the same way
    mean_params = jax.tree.map(lambda p: p.mean(axis=0), params)
    mean_extra = jax.tree.map(lambda p: p.mean(axis=0), extra)

    @jax.jit
    def logits_fn(xb):
        return model.apply({"params": mean_params, **mean_extra}, xb,
                           train=False)
    preds = []
    for i in range(0, len(x_test), 256):
        preds.append(np.asarray(
            jnp.argmax(logits_fn(jnp.asarray(x_test[i:i + 256])), axis=-1)))
    acc = float((np.concatenate(preds) == y_test).mean())
    return {"final_loss": round(final_loss, 4),
            "test_acc_pct": round(100 * acc, 2),
            "consensus_spread": round(spread, 5)}


MODES = [
    ("gradient_allreduce", False, "gradient allreduce (centralized)"),
    ("neighbor_allreduce", False, "neighbor allreduce (static exp2)"),
    ("neighbor_allreduce", True, "neighbor allreduce (dynamic one-peer)"),
]
# Opt-in (--include-exact-diffusion): exact on deterministic heterogeneous
# objectives (closed-form test, tests/test_optimizers.py), but the
# psi-correction recirculates minibatch noise into the disagreement
# subspace — measured 84.7 % / spread 0.18 on the digits leg at the
# CTA-tuned hyperparameters vs ~95 % for CTA (83.1 % without momentum).
# Shipped for completeness with its own row label, not as a default
# comparison at hyperparameters tuned for the other modes.
ED_MODE = ("exact_diffusion", False, "exact-diffusion (symmetric exp)")


def _build_workload(key, args):
    """(name, model, sample_shape, (x, y), (x_test, y_test), hyper)."""
    if key == "lenet":
        from mnist import load_mnist, synthetic_mnist   # examples/mnist.py
        from bluefog_tpu.models.lenet import LeNet
        if args.data_dir:
            # REAL MNIST (IDX files, examples/mnist.py loader) — the
            # real-dataset column VERDICT r3 #5 asks for; no extra noise:
            # the task's own difficulty de-saturates the table
            x, y = load_mnist(args.data_dir)
            perm = np.random.default_rng(0).permutation(len(x))[:9216]
            x, y = x[perm], y[perm]
            name = "LeNet / real MNIST (8-rank)"
        else:
            x, y = synthetic_mnist(n_samples=9216, seed=0)
            if args.noise:
                x = x + np.random.default_rng(9).normal(
                    0, args.noise, size=x.shape).astype(np.float32)
            name = "LeNet / synthetic MNIST (8-rank)"
        split = 8192
        return (name, LeNet(), (28, 28, 1),
                (x[:split], y[:split]), (x[split:], y[split:]),
                dict(lr=0.01, momentum=0.5, epochs=args.epochs,
                     batch=args.batch_size, seed=args.seed))
    if key == "digits":
        # REAL handwritten-digit images that ship with this machine
        # (sklearn's bundled UCI optical-digits set, 1797 genuine 8x8
        # scans): the real-data leg that needs no download.  Bilinear
        # upscale to LeNet's 28x28 input; deterministic shuffle/split.
        from sklearn.datasets import load_digits
        from bluefog_tpu.models.lenet import LeNet
        d = load_digits()
        x8 = d.images.astype(np.float32) / 16.0
        x = np.asarray(jax.image.resize(
            jnp.asarray(x8)[..., None], (len(x8), 28, 28, 1), "bilinear"))
        y = d.target.astype(np.int32)
        perm = np.random.default_rng(0).permutation(len(x))
        x, y = x[perm], y[perm]
        split = 1536                      # 192 per rank; 261 held out
        return ("LeNet / real digits [sklearn] (8-rank)", LeNet(),
                (28, 28, 1), (x[:split], y[:split]), (x[split:], y[split:]),
                dict(lr=0.01, momentum=0.5, epochs=args.digits_epochs,
                     batch=16, seed=args.seed))
    if key == "resnet":
        from bluefog_tpu.models.resnet import ResNet18
        cx, cy = synthetic_cifar(n_samples=4608, seed=1)
        if args.noise:
            # same de-saturation as the LeNet leg: without it every mode
            # hits 100 % and the parity table shows only a ceiling effect
            cx = cx + np.random.default_rng(11).normal(
                0, args.noise, size=cx.shape).astype(np.float32)
        csplit = 4096
        return ("ResNet-18 / synthetic 32px (8-rank)",
                ResNet18(num_classes=10, dtype=jnp.float32), (32, 32, 3),
                (cx[:csplit], cy[:csplit]), (cx[csplit:], cy[csplit:]),
                dict(lr=0.05, momentum=0.9, epochs=args.epochs,
                     batch=args.resnet_batch, seed=args.seed))
    raise SystemExit(f"unknown workload {key!r}")


def _run_single(key, mode_idx, args):
    """One (workload, mode) in THIS process; prints one JSON line."""
    name, model, shape, data, test, hp = _build_workload(key, args)
    comm, dyn, label = MODES[mode_idx]
    r = run_one(model, shape, data[0], data[1], test[0], test[1],
                comm, dyn, **hp)
    r.update({"workload": name, "mode": label})
    print(json.dumps(r), flush=True)
    bf.shutdown()


def run_table_isolated(key, args):
    """Run each mode in a FRESH python subprocess and assemble the table.

    In-process back-to-back legs can wedge XLA:CPU's collective rendezvous
    on heavy graphs (observed: the ResNet static leg deadlocks at an
    allreduce with 2/8 device threads missing even with a 1200s
    termination timeout, while the same leg alone completes).  Process
    isolation sidesteps the wedge and is what a user would do anyway —
    one training run per process."""
    import subprocess
    rows = []
    for i, (comm, dyn, label) in enumerate(MODES):
        cmd = [sys.executable, os.path.abspath(__file__),
               "--single", key, str(i),
               "--epochs", str(args.epochs),
               "--batch-size", str(args.batch_size),
               "--resnet-batch", str(args.resnet_batch),
               "--digits-epochs", str(args.digits_epochs),
               "--seed", str(args.seed), "--noise", str(args.noise)]
        if args.data_dir:
            cmd += ["--data-dir", args.data_dir]
        if getattr(args, "include_exact_diffusion", False):
            cmd += ["--include-exact-diffusion"]
        leg_timeout = int(os.environ.get("CONVERGENCE_LEG_TIMEOUT", "3600"))
        tries = int(os.environ.get("CONVERGENCE_LEG_RETRIES", "3"))
        line = None
        for t in range(1, tries + 1):
            try:
                out = subprocess.run(cmd, capture_output=True, text=True,
                                     env=os.environ.copy(),
                                     timeout=leg_timeout)
            except subprocess.TimeoutExpired as e:
                # A wedged leg (e.g. an XLA build that ignores the
                # injected rendezvous terminator, or
                # BLUEFOG_NO_XLA_FLAG_INJECT) counts as a failed attempt
                # like any nonzero exit — subprocess.run already killed
                # the child; retry instead of aborting the whole table.
                tail = (e.stderr or b"")
                if isinstance(tail, bytes):
                    tail = tail.decode(errors="replace")
                sys.stderr.write(tail[-2000:] + "\n")
                more = "; retrying" if t < tries else ""
                sys.stderr.write(
                    f"mode {label!r} attempt {t}/{tries} exceeded "
                    f"{leg_timeout}s (CONVERGENCE_LEG_TIMEOUT){more}\n")
                line = None
                continue
            line = [l for l in out.stdout.splitlines() if l.startswith("{")]
            if out.returncode == 0 and line:
                break
            # The XLA:CPU intra-op pool can wedge a device thread on
            # 1-core hosts (flaky; the rendezvous terminator SIGABRTs
            # after 180 s) — a fresh attempt usually passes.
            sys.stderr.write(out.stderr[-2000:] + "\n")
            more = "; retrying" if t < tries else ""
            sys.stderr.write(f"mode {label!r} attempt {t}/{tries} failed "
                             f"(rc {out.returncode}){more}\n")
            line = None
        if line is None:
            raise SystemExit(
                f"mode {label!r} failed after {tries} attempts")
        r = json.loads(line[-1])
        rows.append(r)
        print(json.dumps(r), flush=True)
    name = rows[0]["workload"]
    _print_table(name, rows)
    return rows


def _print_table(name, rows):
    base_acc = rows[0]["test_acc_pct"]
    print(f"\n### {name}\n")
    print("| mode | final loss | test acc (%) | acc gap vs centralized "
          "(pp) | consensus spread |")
    print("|---|---|---|---|---|")
    for r in rows:
        gap = round(r["test_acc_pct"] - base_acc, 2)
        print(f"| {r['mode']} | {r['final_loss']} | {r['test_acc_pct']} "
              f"| {gap:+.2f} | {r['consensus_spread']} |")
    print(flush=True)
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--include-resnet", action="store_true",
                    help="also run the ResNet-18 synthetic leg")
    ap.add_argument("--include-exact-diffusion", action="store_true",
                    help="add the exact-diffusion row (see ED_MODE note: "
                         "exact on deterministic objectives, noisier under "
                         "minibatch stochasticity at CTA-tuned "
                         "hyperparameters)")
    ap.add_argument("--resnet-batch", type=int, default=16,
                    help="per-rank batch for the ResNet leg.  Default 16: "
                         "on a single-core host the 8 device threads "
                         "timeshare one CPU, and at batch 64 a step's "
                         "compute keeps some threads from reaching the "
                         "collective rendezvous inside XLA's 40s "
                         "termination window (observed: 7/8 arrived -> "
                         "fatal).  Smaller per-rank batches shorten the "
                         "stragglers; convergence, not throughput, is "
                         "what this script measures.")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--data-dir", default=None,
                    help="directory with MNIST IDX files: the LeNet leg "
                         "then trains on REAL MNIST (examples/mnist.py "
                         "loader) instead of the synthetic stand-in")
    ap.add_argument("--skip-digits", action="store_true",
                    help="skip the bundled real-digits leg (sklearn's "
                         "1797 genuine UCI scans; runs by default as the "
                         "no-download real-data column)")
    ap.add_argument("--digits-epochs", type=int, default=12,
                    help="epochs for the digits leg (192 samples/rank -> "
                         "12 steps/epoch at batch 16; the small real set "
                         "needs more passes to close the mixing transient)")
    ap.add_argument("--seed", type=int, default=42)
    ap.add_argument("--noise", type=float, default=1.3,
                    help="extra pixel noise stddev: de-saturates the "
                         "synthetic task so accuracy gaps are measurable "
                         "(0 => every mode hits 100%%)")
    ap.add_argument("--single", nargs=2, metavar=("WORKLOAD", "MODE_IDX"),
                    help=argparse.SUPPRESS)   # internal: one leg in-process
    args = ap.parse_args()

    if args.include_exact_diffusion:
        MODES.append(ED_MODE)

    if args.single:
        _run_single(args.single[0], int(args.single[1]), args)
        return

    run_table_isolated("lenet", args)
    if not args.skip_digits:
        try:
            import sklearn  # noqa: F401 — not a declared dependency
        except ImportError:
            sys.stderr.write(
                "skipping the real-digits leg: scikit-learn (which bundles "
                "the real UCI digit scans) is not installed\n")
        else:
            run_table_isolated("digits", args)
    if args.include_resnet:
        run_table_isolated("resnet", args)


if __name__ == "__main__":
    main()
