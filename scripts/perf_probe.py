"""Single-chip perf probe for the ResNet-50 bench step.

Ablation ladder: forward, forward+backward, full train step (with the
optimizer update and the global-view plumbing), at several batch sizes,
each with XLA's own FLOP count and bytes-accessed so the report includes a
roofline bound (compute-limited vs HBM-limited) per stage.

Timing uses a scalar device-to-host fetch as the execution barrier —
``jax.block_until_ready`` can return before remote execution completes on
tunneled transports (the probe's round-1 numbers were dispatch time, not
device time), so every timed window ends by fetching one float.
"""

import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bluefog_tpu as bf
from bluefog_tpu import training as T
from bluefog_tpu.models.resnet import ResNet50
from bench import (PEAK_FLOPS, HBM_GBPS, lookup_device_table,  # noqa: E402
                   timeit_amortized)


def timeit(fn, *args, n=10, warmup=3):
    return timeit_amortized(lambda: fn(*args), n=n, warmup=warmup)


def analyze(compiled):
    cost = compiled.cost_analysis()
    if not cost:
        return None, None
    flops = cost.get("flops")
    byt = cost.get("bytes accessed")
    return flops, byt


def report(name, t, flops, byt, peak, gbps, batch):
    line = f"{name}: {t*1e3:.2f} ms  ({batch/t:.0f} img/s)"
    if flops and peak:
        line += f"  MFU {flops/t/peak*100:.1f}%"
    if byt and gbps:
        line += f"  HBM {byt/t/1e9:.0f} GB/s ({byt/t/1e9/gbps*100:.0f}% of peak)"
    if flops and byt and peak and gbps:
        bound = max(flops / peak, byt / (gbps * 1e9))
        which = "compute" if flops / peak > byt / (gbps * 1e9) else "HBM"
        line += f"  [roofline: {bound*1e3:.2f} ms, {which}-bound]"
    print(line, flush=True)


def main():
    dev = jax.devices()[0]
    peak = lookup_device_table(PEAK_FLOPS)
    gbps = lookup_device_table(HBM_GBPS)
    peak_s = f"{peak/1e12:.0f} TFLOP/s" if peak else "unknown"
    print(f"device: {dev.device_kind} ({dev.platform}); peak bf16 "
          f"{peak_s}, HBM {gbps} GB/s", flush=True)

    bf.init()
    # PROBE_IMAGE: smoke-test knob (CPU runs before a hardware window);
    # the measurement default stays the benchmark's 224
    image = int(os.environ.get("PROBE_IMAGE", "224"))
    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    base = optax.sgd(0.01, momentum=0.9)
    variables, opt_state = T.create_train_state(
        model, base, jax.random.key(0), jnp.zeros((1, image, image, 3)))
    sq = jax.tree.map(lambda a: a[0], variables)

    batches = [int(b) for b in
               os.environ.get("PROBE_BATCHES", "64,128,256").split(",")]
    rng = np.random.default_rng(0)

    for batch in batches:
        x1 = jnp.asarray(rng.normal(size=(batch, image, image, 3)),
                         jnp.float32)
        y1 = jnp.asarray(rng.integers(0, 1000, size=(batch,)))
        print(f"--- batch {batch} ---", flush=True)

        @jax.jit
        def fwd(v, xb):
            out, _ = model.apply(v, xb, train=True, mutable=["batch_stats"])
            return out.sum()

        c = fwd.lower(sq, x1).compile()
        f, b = analyze(c)
        report("fwd           ", timeit(c, sq, x1), f, b, peak, gbps, batch)

        @jax.jit
        def fwdbwd(v, xb, yb):
            def loss_fn(p):
                out, _ = model.apply({"params": p, **{k: v[k] for k in v
                                                      if k != "params"}},
                                     xb, train=True, mutable=["batch_stats"])
                return T.cross_entropy_loss(out, yb)
            l, g = jax.value_and_grad(loss_fn)(v["params"])
            return l, jax.tree.map(lambda a: a.sum(), g)

        c = fwdbwd.lower(sq, x1, y1).compile()
        f, b = analyze(c)
        report("fwd+bwd       ", timeit(c, sq, x1, y1), f, b, peak, gbps,
               batch)

        step_fn = T.make_train_step(model, base,
                                    communication="neighbor_allreduce",
                                    sched=None, donate=False)
        xg, yg = x1[None], y1[None]
        c = step_fn.lower(variables, opt_state, (xg, yg),
                          jnp.int32(0)).compile()
        f, b = analyze(c)
        t = timeit(lambda: c(variables, opt_state, (xg, yg), jnp.int32(0))[2])
        report("full train step", t, f, b, peak, gbps, batch)


if __name__ == "__main__":
    main()
