"""Single-chip perf probe for the ResNet-50 bench step.

Times the full train step (and optionally forward-only) and reports achieved
FLOP/s vs the chip's peak (MFU), using XLA's own cost analysis for the FLOP
count.  Prints incrementally so a partial run still yields data.
"""

import os
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bluefog_tpu as bf
from bluefog_tpu import training as T
from bluefog_tpu.models.resnet import ResNet50

# bf16 peak FLOP/s per chip by device kind (public numbers)
PEAK = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def peak_flops(device_kind: str):
    for k, v in PEAK.items():
        if k.lower() in device_kind.lower():
            return v
    return None


def timeit(fn, *args, n=10, warmup=2):
    out = None
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    dev = jax.devices()[0]
    peak = peak_flops(dev.device_kind)
    print(f"device: {dev.device_kind} ({dev.platform}); "
          f"assumed peak bf16 FLOP/s: {peak}", flush=True)

    batch = int(os.environ.get("BENCH_BATCH", "64"))
    image = 224
    bf.init()

    model = ResNet50(num_classes=1000, dtype=jnp.bfloat16)
    base = optax.sgd(0.01, momentum=0.9)
    variables, opt_state = T.create_train_state(
        model, base, jax.random.key(0), jnp.zeros((1, image, image, 3)))
    step_fn = T.make_train_step(model, base,
                                communication="neighbor_allreduce",
                                sched=None, donate=False)

    rng = np.random.default_rng(0)
    x = jax.device_put(jnp.asarray(
        rng.normal(size=(1, batch, image, image, 3)), jnp.float32))
    y = jax.device_put(jnp.asarray(rng.integers(0, 1000, size=(1, batch))))

    t0 = time.perf_counter()
    compiled = step_fn.lower(variables, opt_state, (x, y),
                             jnp.int32(0)).compile()
    print(f"step compile: {time.perf_counter()-t0:.1f}s", flush=True)
    cost = compiled.cost_analysis()
    flops = cost.get("flops") if cost else None
    print(f"XLA step flops: {flops}", flush=True)

    t_step = timeit(step_fn, variables, opt_state, (x, y), jnp.int32(0))
    print(f"full step: {t_step*1e3:.2f} ms  ({batch/t_step:.0f} img/s)",
          flush=True)
    if flops and peak:
        print(f"MFU (full step): {flops/t_step/peak*100:.1f}%", flush=True)

    if os.environ.get("PROBE_FWD", "0") == "1":
        sq = jax.tree.map(lambda a: a[0], variables)

        @jax.jit
        def fwd(v, xb):
            return model.apply(v, xb, train=True, mutable=["batch_stats"])[0]

        t0 = time.perf_counter()
        fcomp = fwd.lower(sq, x[0]).compile()
        print(f"fwd compile: {time.perf_counter()-t0:.1f}s", flush=True)
        fcost = fcomp.cost_analysis()
        fflops = fcost.get("flops") if fcost else None
        t_fwd = timeit(fwd, sq, x[0])
        print(f"fwd: {t_fwd*1e3:.2f} ms  ({batch/t_fwd:.0f} img/s)",
              flush=True)
        if fflops and peak:
            print(f"MFU (fwd): {fflops/t_fwd/peak*100:.1f}%", flush=True)


if __name__ == "__main__":
    main()
