// bluefog_tpu native logging.
//
// TPU-native counterpart of the reference's BFLOG machinery
// (reference: bluefog/common/logging.{h,cc} — LogMessage levels, env
// control documented at docs/env_variable.rst:8-22).  Same contract:
// leveled, rank-tagged, single-write-per-line messages on stderr, with
//   BLUEFOG_LOG_LEVEL     = trace|debug|info|warn|error|fatal (default warn)
//   BLUEFOG_LOG_HIDE_TIME = 1 to suppress the timestamp prefix
// Used by the other native components (service.cc) and exposed to Python
// over ctypes (bluefog_tpu/utils/blog.py).

#include <atomic>
#include <chrono>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>

namespace {

enum Level { TRACE = 0, DEBUG = 1, INFO = 2, WARN = 3, ERROR = 4, FATAL = 5 };

const char* kLevelNames[] = {"trace", "debug", "info", "warn", "error", "fatal"};

int parse_level(const char* s) {
  if (!s) return WARN;
  for (int i = 0; i <= FATAL; ++i)
    if (std::strcmp(s, kLevelNames[i]) == 0) return i;
  // numeric form also accepted (reference accepts the names only; numbers
  // make programmatic control over ctypes trivial)
  if (s[0] >= '0' && s[0] <= '5' && s[1] == '\0') return s[0] - '0';
  return WARN;
}

struct Config {
  std::atomic<int> min_level;
  bool hide_time;
  std::mutex write_mu;

  Config() {
    min_level.store(parse_level(std::getenv("BLUEFOG_LOG_LEVEL")));
    const char* hide = std::getenv("BLUEFOG_LOG_HIDE_TIME");
    hide_time = hide && hide[0] == '1';
  }
};

Config* config() {
  static Config c;
  return &c;
}

}  // namespace

extern "C" {

int bft_log_level() { return config()->min_level.load(); }

void bft_log_set_level(int level) {
  if (level < TRACE) level = TRACE;
  if (level > FATAL) level = FATAL;
  config()->min_level.store(level);
}

int bft_log_enabled(int level) {
  return level >= config()->min_level.load() ? 1 : 0;
}

// rank < 0 omits the rank tag (reference BFLOG(level) vs BFLOG(level, rank)).
void bft_log(int level, int rank, const char* msg) {
  Config* c = config();
  if (level < c->min_level.load()) return;
  if (level < TRACE) level = TRACE;
  if (level > FATAL) level = FATAL;
  char line[1024];
  size_t off = 0;
  if (!c->hide_time) {
    auto now = std::chrono::system_clock::now();
    std::time_t t = std::chrono::system_clock::to_time_t(now);
    auto us = std::chrono::duration_cast<std::chrono::microseconds>(
                  now.time_since_epoch())
                  .count() %
              1000000;
    std::tm tm_buf;
    localtime_r(&t, &tm_buf);
    off += std::strftime(line + off, sizeof line - off, "%Y-%m-%d %H:%M:%S",
                         &tm_buf);
    off += std::snprintf(line + off, sizeof line - off, ".%06lld ",
                         (long long)us);
  }
  if (rank >= 0)
    off += std::snprintf(line + off, sizeof line - off, "[%d]", rank);
  std::snprintf(line + off, sizeof line - off, "[%s] %s\n",
                kLevelNames[level], msg ? msg : "");
  std::lock_guard<std::mutex> lk(c->write_mu);
  std::fputs(line, stderr);
  if (level == FATAL) std::abort();
}

}  // extern "C"
