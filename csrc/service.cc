// bluefog_tpu background communication service.
//
// TPU-native re-design of the reference's core runtime thread + handle
// manager (reference: bluefog/common/operations.cc:453-522 background loop,
// bluefog/torch/handle_manager.{h,cc} integer-handle table,
// operations.cc:388-433 stall watchdog).  On MPI the background thread IS
// the data path — every collective funnels through it.  On TPU the data
// path is XLA async dispatch, so what remains native is exactly what this
// file implements:
//
//   * a handle table: integer handles with pending/done/error state,
//     condition-variable waits, and error-message transport;
//   * an asynchronous executor: submitted tasks (Python closures delivered
//     as C function pointers over ctypes) run on a native worker pool
//     (thread_pool.h); a `lane` pins related tasks (e.g. all window ops of
//     one process) to one worker, reproducing the reference's
//     one-comm-thread FIFO ordering (global_state.h:40-43);
//   * a stall watchdog: a scanner thread that reports handles pending
//     longer than BLUEFOG_STALL_WARNING_SEC (default 60, reference
//     operations.cc:46-47) through the native log.
//
// Consumed from Python via ctypes (bluefog_tpu/service.py).

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <string>
#include <thread>
#include <unordered_map>

#include "thread_pool.h"

extern "C" void bft_log(int level, int rank, const char* msg);

namespace {

enum HandleState { PENDING = 0, DONE = 1, ERROR = 2 };

struct HandleInfo {
  HandleState state = PENDING;
  std::string error;
  std::chrono::steady_clock::time_point enqueued;
  std::chrono::steady_clock::time_point last_warn;
};

class Service {
 public:
  int start(int num_threads) {
    std::lock_guard<std::mutex> lk(lifecycle_mu_);
    if (running_) return pool_.size();
    if (num_threads <= 0) {
      const char* env = std::getenv("BLUEFOG_NUM_SERVICE_THREADS");
      num_threads = env ? std::atoi(env) : 1;
      if (num_threads <= 0) num_threads = 1;
    }
    const char* stall = std::getenv("BLUEFOG_STALL_WARNING_SEC");
    stall_warning_ms_ = stall ? (int64_t)(std::atof(stall) * 1000) : 60000;
    pool_.start(num_threads);
    watchdog_stop_ = false;
    watchdog_ = std::thread([this] { this->watchdog_loop(); });
    running_ = true;
    return num_threads;
  }

  void stop() {
    std::lock_guard<std::mutex> lk(lifecycle_mu_);
    if (!running_) return;
    pool_.stop();
    {
      std::lock_guard<std::mutex> hlk(mu_);
      watchdog_stop_ = true;
    }
    cv_.notify_all();
    if (watchdog_.joinable()) watchdog_.join();
    {
      std::lock_guard<std::mutex> hlk(mu_);
      handles_.clear();
    }
    // wake any waiter blocked on a handle whose task was dropped with the
    // queue: it re-checks, finds the handle gone, and returns "unknown"
    cv_.notify_all();
    running_ = false;
  }

  bool running() const { return running_; }

  void set_stall_warning_ms(int64_t ms) { stall_warning_ms_ = ms; }

  int64_t alloc_handle() {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t h = next_handle_++;
    HandleInfo info;
    info.enqueued = std::chrono::steady_clock::now();
    info.last_warn = info.enqueued;
    handles_[h] = std::move(info);
    return h;
  }

  int64_t submit(void (*cb)(int64_t, int64_t), int64_t tag, int lane) {
    if (!running_) return -1;
    int64_t h = alloc_handle();
    pool_.execute(
        [this, cb, tag, h] {
          cb(h, tag);
          // callbacks that hit an error mark it before returning; anything
          // still pending completed successfully
          std::lock_guard<std::mutex> lk(mu_);
          auto it = handles_.find(h);
          if (it != handles_.end() && it->second.state == PENDING)
            it->second.state = DONE;
          cv_.notify_all();
        },
        lane);
    return h;
  }

  void mark_done(int64_t h) { set_state(h, DONE, nullptr); }

  void mark_error(int64_t h, const char* msg) { set_state(h, ERROR, msg); }

  // -2 unknown handle, else HandleState
  int poll(int64_t h) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = handles_.find(h);
    if (it == handles_.end()) return -2;
    return it->second.state;
  }

  // timeout_ms < 0: wait forever.  Returns like poll(); PENDING on timeout.
  int wait(int64_t h, int64_t timeout_ms) {
    std::unique_lock<std::mutex> lk(mu_);
    auto deadline = std::chrono::steady_clock::now() +
                    std::chrono::milliseconds(timeout_ms < 0 ? 0 : timeout_ms);
    for (;;) {
      auto it = handles_.find(h);
      if (it == handles_.end()) return -2;
      if (it->second.state != PENDING) return it->second.state;
      if (timeout_ms < 0) {
        cv_.wait(lk);
      } else if (cv_.wait_until(lk, deadline) == std::cv_status::timeout) {
        auto it2 = handles_.find(h);
        if (it2 == handles_.end()) return -2;
        return it2->second.state;
      }
    }
  }

  int error_msg(int64_t h, char* buf, int len) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = handles_.find(h);
    if (it == handles_.end() || len <= 0) return -1;
    std::snprintf(buf, len, "%s", it->second.error.c_str());
    return (int)it->second.error.size();
  }

  void release(int64_t h) {
    std::lock_guard<std::mutex> lk(mu_);
    handles_.erase(h);
  }

  int64_t pending() {
    std::lock_guard<std::mutex> lk(mu_);
    int64_t n = 0;
    for (const auto& kv : handles_)
      if (kv.second.state == PENDING) ++n;
    return n;
  }

 private:
  void set_state(int64_t h, HandleState s, const char* msg) {
    std::lock_guard<std::mutex> lk(mu_);
    auto it = handles_.find(h);
    if (it == handles_.end()) return;
    it->second.state = s;
    if (msg) it->second.error = msg;
    cv_.notify_all();
  }

  void watchdog_loop() {
    std::unique_lock<std::mutex> lk(mu_);
    while (!watchdog_stop_) {
      cv_.wait_for(lk, std::chrono::milliseconds(1000));
      if (watchdog_stop_) return;
      auto now = std::chrono::steady_clock::now();
      for (auto& kv : handles_) {
        if (kv.second.state != PENDING) continue;
        auto since_warn = std::chrono::duration_cast<std::chrono::milliseconds>(
                              now - kv.second.last_warn)
                              .count();
        if (since_warn < stall_warning_ms_) continue;
        auto age_s = std::chrono::duration_cast<std::chrono::seconds>(
                         now - kv.second.enqueued)
                         .count();
        char msg[256];
        std::snprintf(msg, sizeof msg,
                      "operation handle %lld has been pending for %llds -- "
                      "one or more async ops may be stalled (reference stall "
                      "watchdog: operations.cc:388-433)",
                      (long long)kv.first, (long long)age_s);
        kv.second.last_warn = now;
        bft_log(/*warn*/ 3, -1, msg);
      }
    }
  }

  std::mutex lifecycle_mu_;
  std::mutex mu_;  // guards handles_ + watchdog wakeups
  std::condition_variable cv_;
  std::unordered_map<int64_t, HandleInfo> handles_;
  int64_t next_handle_ = 1;
  bft::ThreadPool pool_;
  std::thread watchdog_;
  bool watchdog_stop_ = false;
  std::atomic<bool> running_{false};
  std::atomic<int64_t> stall_warning_ms_{60000};
};

Service* service() {
  static Service s;
  return &s;
}

}  // namespace

extern "C" {

int bft_service_start(int num_threads) { return service()->start(num_threads); }

void bft_service_stop() { service()->stop(); }

int bft_service_running() { return service()->running() ? 1 : 0; }

void bft_service_set_stall_warning_ms(int64_t ms) {
  service()->set_stall_warning_ms(ms);
}

// cb runs on a worker thread as cb(handle, tag); lane >= 0 serializes with
// other tasks on the same lane.  Returns the handle, or -1 if not running.
int64_t bft_service_submit(void (*cb)(int64_t, int64_t), int64_t tag,
                           int lane) {
  return service()->submit(cb, tag, lane);
}

// handle table also usable without submit(): allocate, complete elsewhere
int64_t bft_handle_alloc() { return service()->alloc_handle(); }

void bft_handle_mark_done(int64_t h) { service()->mark_done(h); }

void bft_handle_mark_error(int64_t h, const char* msg) {
  service()->mark_error(h, msg);
}

int bft_handle_poll(int64_t h) { return service()->poll(h); }

int bft_handle_wait(int64_t h, int64_t timeout_ms) {
  return service()->wait(h, timeout_ms);
}

int bft_handle_error_msg(int64_t h, char* buf, int len) {
  return service()->error_msg(h, buf, len);
}

void bft_handle_release(int64_t h) { service()->release(h); }

int64_t bft_service_pending() { return service()->pending(); }

}  // extern "C"
