// bluefog_tpu native work-queue thread pool.
//
// TPU-native counterpart of the reference's finalizer pool
// (reference: bluefog/common/thread_pool.{h,cc} — execute() work queue,
// sized by BLUEFOG_NUM_FINALIZER_THREADS at nccl_controller.cc:204-209).
// Header-only; consumed by service.cc.

#ifndef BLUEFOG_TPU_CSRC_THREAD_POOL_H_
#define BLUEFOG_TPU_CSRC_THREAD_POOL_H_

#include <condition_variable>
#include <deque>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace bft {

class ThreadPool {
 public:
  ~ThreadPool() { stop(); }

  void start(int num_threads) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!threads_.empty()) return;
    stop_ = false;
    for (int i = 0; i < num_threads; ++i)
      threads_.emplace_back([this, i] { loop(i); });
  }

  void stop() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& t : threads_)
      if (t.joinable()) t.join();
    threads_.clear();
    // drop queued-but-unrun work: after stop() the owner is shutting down
    std::lock_guard<std::mutex> lk(mu_);
    queue_.clear();
  }

  int size() const { return (int)threads_.size(); }

  // lane >= 0 pins the task to worker (lane % size): tasks sharing a lane
  // execute in submission order even with a multi-thread pool — this is how
  // window ops keep the reference's single-comm-thread FIFO semantics
  // (reference global_state.h:40-43) while other work fans out.
  void execute(std::function<void()> fn, int lane = -1) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      queue_.push_back({std::move(fn), lane});
    }
    cv_.notify_all();
  }

  size_t pending() {
    std::lock_guard<std::mutex> lk(mu_);
    return queue_.size() + running_;
  }

 private:
  struct Task {
    std::function<void()> fn;
    int lane;
  };

  void loop(int worker_id) {
    for (;;) {
      Task task;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this, worker_id] {
          return stop_ || claimable(worker_id);
        });
        if (stop_) return;
        bool found = false;
        for (auto it = queue_.begin(); it != queue_.end(); ++it) {
          if (it->lane < 0 || (it->lane % (int)threads_.size()) == worker_id) {
            task = std::move(*it);
            queue_.erase(it);
            found = true;
            break;
          }
        }
        if (!found) continue;
        ++running_;
      }
      task.fn();
      {
        std::lock_guard<std::mutex> lk(mu_);
        --running_;
      }
    }
  }

  bool claimable(int worker_id) {
    for (const auto& t : queue_)
      if (t.lane < 0 || (t.lane % (int)threads_.size()) == worker_id)
        return true;
    return false;
  }

  std::mutex mu_;
  std::condition_variable cv_;
  std::deque<Task> queue_;
  std::vector<std::thread> threads_;
  size_t running_ = 0;
  bool stop_ = false;
};

}  // namespace bft

#endif  // BLUEFOG_TPU_CSRC_THREAD_POOL_H_
