// bluefog_tpu native timeline writer.
//
// TPU-native re-design of the reference's Chrome-tracing timeline
// (reference: bluefog/common/timeline.{h,cc} — boost SPSC queue at
// timeline.h:46-76, activity begin/end records at timeline.h:82-120).
// Same contract: callers enqueue fixed-size records from any thread with
// negligible latency; a dedicated writer thread serializes them into a
// chrome://tracing JSON file.  Implementation is a brand-new bounded MPMC
// ring with a monotonic-ticket scheme (no boost, no external deps).
//
// Exposed as a flat C ABI consumed from Python via ctypes
// (bluefog_tpu/timeline.py); one timeline per process, matching the
// reference's per-rank file `<prefix><rank>.json`.

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <thread>

namespace {

constexpr int kMaxName = 128;
constexpr uint32_t kQueueCapacity = 1 << 16;  // 65536 in-flight records

struct Record {
  char tensor[kMaxName];
  char activity[kMaxName];
  char phase;        // 'B' begin, 'E' end, 'X' complete, 'i' instant,
                     // 'C' counter (tensor = lane name, activity = series)
  int64_t ts_us;     // microseconds since timeline open
  int64_t dur_us;    // only for 'X'
  double value;      // only for 'C'
  uint32_t tid;      // lane id (stable hash of tensor name)
};

// Bounded MPMC ring buffer.  Each slot carries a sequence number; producers
// claim tickets with fetch_add and spin only on their own slot, consumers
// (the single writer thread) likewise.  This is the classic bounded-queue
// design (Vyukov); records are dropped, not blocked on, when full — a
// tracing subsystem must never stall the training step.
class RecordQueue {
 public:
  RecordQueue() {
    for (uint32_t i = 0; i < kQueueCapacity; ++i)
      slots_[i].seq.store(i, std::memory_order_relaxed);
  }

  bool push(const Record& r) {
    uint64_t pos = head_.load(std::memory_order_relaxed);
    for (;;) {
      Slot& s = slots_[pos & (kQueueCapacity - 1)];
      uint64_t seq = s.seq.load(std::memory_order_acquire);
      intptr_t dif = (intptr_t)seq - (intptr_t)pos;
      if (dif == 0) {
        if (head_.compare_exchange_weak(pos, pos + 1,
                                        std::memory_order_relaxed))
          {
            s.rec = r;
            s.seq.store(pos + 1, std::memory_order_release);
            return true;
          }
      } else if (dif < 0) {
        return false;  // full: drop
      } else {
        pos = head_.load(std::memory_order_relaxed);
      }
    }
  }

  bool pop(Record* out) {
    uint64_t pos = tail_.load(std::memory_order_relaxed);
    Slot& s = slots_[pos & (kQueueCapacity - 1)];
    uint64_t seq = s.seq.load(std::memory_order_acquire);
    intptr_t dif = (intptr_t)seq - (intptr_t)(pos + 1);
    if (dif < 0) return false;  // empty
    *out = s.rec;
    s.seq.store(pos + kQueueCapacity, std::memory_order_release);
    tail_.store(pos + 1, std::memory_order_relaxed);
    return true;
  }

 private:
  struct Slot {
    std::atomic<uint64_t> seq;
    Record rec;
  };
  Slot slots_[kQueueCapacity];
  // single consumer, so tail_ needs no CAS
  alignas(64) std::atomic<uint64_t> head_{0};
  alignas(64) std::atomic<uint64_t> tail_{0};
};

void json_escape(const char* in, char* out, size_t out_len) {
  size_t j = 0;
  for (size_t i = 0; in[i] && j + 2 < out_len; ++i) {
    char c = in[i];
    if (c == '"' || c == '\\') out[j++] = '\\';
    if ((unsigned char)c < 0x20) c = ' ';
    out[j++] = c;
  }
  out[j] = '\0';
}

class TimelineWriter {
 public:
  bool open(const char* path, int rank) {
    std::lock_guard<std::mutex> lk(mu_);
    if (file_) return false;
    file_ = std::fopen(path, "w");
    if (!file_) return false;
    rank_ = rank;
    t0_ = std::chrono::steady_clock::now();
    std::memset(seen_lane_, 0, sizeof seen_lane_);  // fresh session state
    dropped_.store(0, std::memory_order_relaxed);
    std::fputs("[\n", file_);
    // process metadata so chrome://tracing shows "rank N"
    std::fprintf(file_,
                 "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%d,"
                 "\"args\":{\"name\":\"rank %d\"}},\n",
                 rank_, rank_);
    stop_.store(false, std::memory_order_relaxed);
    writer_ = std::thread([this] { this->loop(); });
    return true;
  }

  void close() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      if (!file_) return;
      stop_.store(true, std::memory_order_release);
    }
    cv_.notify_all();
    if (writer_.joinable()) writer_.join();
    std::lock_guard<std::mutex> lk(mu_);
    // valid JSON even though chrome tolerates a trailing comma: close with
    // a final metadata event
    std::fprintf(file_,
                 "{\"name\":\"timeline_closed\",\"ph\":\"i\",\"pid\":%d,"
                 "\"tid\":0,\"ts\":%lld,\"s\":\"g\"}\n]\n",
                 rank_, (long long)now_us());
    std::fclose(file_);
    file_ = nullptr;
  }

  bool active() const { return file_ != nullptr; }

  int64_t now_us() const {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now() - t0_)
        .count();
  }

  // ts_us < 0 means "stamp now"; an explicit ts lets callers emit complete
  // ('X') spans whose start predates the record call (async op windows).
  void record(const char* tensor, const char* activity, char phase,
              int64_t ts_us, int64_t dur_us) {
    if (!active()) return;
    Record r;
    std::snprintf(r.tensor, kMaxName, "%s", tensor ? tensor : "");
    std::snprintf(r.activity, kMaxName, "%s", activity ? activity : "");
    r.phase = phase;
    r.ts_us = ts_us < 0 ? now_us() : ts_us;
    r.dur_us = dur_us;
    r.value = 0.0;
    r.tid = lane(r.tensor);
    if (queue_.push(r)) cv_.notify_one();
    else dropped_.fetch_add(1, std::memory_order_relaxed);
  }

  // Chrome-tracing counter sample ("ph":"C"): `name` is the lane, `series`
  // the args key, `value` the sample.  Renders as a graph lane in Perfetto.
  void counter(const char* name, const char* series, double value,
               int64_t ts_us) {
    if (!active()) return;
    Record r;
    std::snprintf(r.tensor, kMaxName, "%s", name ? name : "");
    std::snprintf(r.activity, kMaxName, "%s", series ? series : "value");
    r.phase = 'C';
    r.ts_us = ts_us < 0 ? now_us() : ts_us;
    r.dur_us = 0;
    r.value = value;
    r.tid = 0;  // counters are process-scoped; no lane metadata needed
    if (queue_.push(r)) cv_.notify_one();
    else dropped_.fetch_add(1, std::memory_order_relaxed);
  }

  int64_t dropped() const {
    return dropped_.load(std::memory_order_relaxed);
  }

 private:
  // Stable lane id per tensor name so chrome renders one row per tensor
  // (reference maps tensor→tid at timeline.h:103-111).
  uint32_t lane(const char* name) {
    uint32_t h = 2166136261u;
    for (const char* p = name; *p; ++p) h = (h ^ (uint8_t)*p) * 16777619u;
    return 1 + (h % 4096);
  }

  void emit(const Record& r) {
    char tensor[2 * kMaxName], activity[2 * kMaxName];
    json_escape(r.tensor, tensor, sizeof tensor);
    json_escape(r.activity, activity, sizeof activity);
    if (r.phase == 'C') {
      // counter lane: name = lane, args = {series: value}; no tid (the
      // lane-metadata path below would mislabel thread 0)
      std::fprintf(file_,
                   "{\"name\":\"%s\",\"cat\":\"bluefog\",\"ph\":\"C\","
                   "\"ts\":%lld,\"pid\":%d,\"args\":{\"%s\":%.17g}},\n",
                   tensor, (long long)r.ts_us, rank_, activity, r.value);
      return;
    }
    if (!seen_lane_[r.tid % 4096]) {
      seen_lane_[r.tid % 4096] = true;
      std::fprintf(file_,
                   "{\"name\":\"thread_name\",\"ph\":\"M\",\"pid\":%d,"
                   "\"tid\":%u,\"args\":{\"name\":\"%s\"}},\n",
                   rank_, r.tid, tensor);
    }
    if (r.phase == 'X') {
      std::fprintf(file_,
                   "{\"name\":\"%s\",\"cat\":\"bluefog\",\"ph\":\"X\","
                   "\"ts\":%lld,\"dur\":%lld,\"pid\":%d,\"tid\":%u},\n",
                   activity, (long long)r.ts_us, (long long)r.dur_us, rank_,
                   r.tid);
    } else if (r.phase == 'i') {
      std::fprintf(file_,
                   "{\"name\":\"%s\",\"cat\":\"bluefog\",\"ph\":\"i\","
                   "\"ts\":%lld,\"pid\":%d,\"tid\":%u,\"s\":\"t\"},\n",
                   activity, (long long)r.ts_us, rank_, r.tid);
    } else {
      std::fprintf(file_,
                   "{\"name\":\"%s\",\"cat\":\"bluefog\",\"ph\":\"%c\","
                   "\"ts\":%lld,\"pid\":%d,\"tid\":%u},\n",
                   activity, r.phase, (long long)r.ts_us, rank_, r.tid);
    }
  }

  void loop() {
    Record r;
    for (;;) {
      bool any = false;
      while (queue_.pop(&r)) {
        any = true;
        std::lock_guard<std::mutex> lk(mu_);
        if (!file_) return;
        emit(r);
      }
      if (stop_.load(std::memory_order_acquire)) {
        while (queue_.pop(&r)) {
          std::lock_guard<std::mutex> lk(mu_);
          if (!file_) return;
          emit(r);
        }
        return;
      }
      if (!any) {
        std::unique_lock<std::mutex> lk(wait_mu_);
        cv_.wait_for(lk, std::chrono::milliseconds(5));
      }
    }
  }

  std::mutex mu_;        // guards file_
  std::mutex wait_mu_;   // writer sleep
  std::condition_variable cv_;
  std::FILE* file_ = nullptr;
  int rank_ = 0;
  std::chrono::steady_clock::time_point t0_;
  std::thread writer_;
  std::atomic<bool> stop_{false};
  std::atomic<int64_t> dropped_{0};
  RecordQueue queue_;
  bool seen_lane_[4096] = {};
};

TimelineWriter* writer() {
  static TimelineWriter w;
  return &w;
}

}  // namespace

extern "C" {

int bft_timeline_open(const char* path, int rank) {
  return writer()->open(path, rank) ? 0 : -1;
}

void bft_timeline_close() { writer()->close(); }

int bft_timeline_active() { return writer()->active() ? 1 : 0; }

// phase: 'B' begin, 'E' end, 'i' instant; 'X' complete with dur_us
void bft_timeline_record(const char* tensor, const char* activity, char phase,
                         int64_t dur_us) {
  writer()->record(tensor, activity, phase, -1, dur_us);
}

// as above, with an explicit start timestamp (from bft_timeline_now_us)
void bft_timeline_record_at(const char* tensor, const char* activity,
                            char phase, int64_t ts_us, int64_t dur_us) {
  writer()->record(tensor, activity, phase, ts_us, dur_us);
}

// counter sample ("ph":"C"): renders as a Perfetto graph lane
void bft_timeline_counter(const char* name, const char* series, double value,
                          int64_t ts_us) {
  writer()->counter(name, series, value, ts_us);
}

int64_t bft_timeline_now_us() { return writer()->now_us(); }

int64_t bft_timeline_dropped() { return writer()->dropped(); }

}  // extern "C"
