"""Torch-tensor collective ops over the JAX mesh.

Parity model: the reference's TF frontend op set
(``bluefog/tensorflow/mpi_ops.py:95-226`` — allreduce/broadcast/allgather)
plus ``neighbor_allreduce``, the framework's hot op.  Tensors convert
torch→numpy→jax on the way in (zero-copy for contiguous CPU float32/64,
int32/64) and back on the way out; bfloat16/float16 stage through float32
exactly like the reference's fp16 MPI path converts through a custom dtype
(``bluefog/common/half.cc``).
"""

from typing import Dict, Optional

import numpy as np
import torch

from ..ops import api as _api

__all__ = [
    "allreduce", "allreduce_nonblocking",
    "broadcast", "broadcast_nonblocking",
    "allgather", "allgather_nonblocking",
    "neighbor_allreduce", "neighbor_allreduce_nonblocking",
    "poll", "synchronize", "wait",
    "broadcast_parameters", "allreduce_parameters",
    "broadcast_optimizer_state",
]

_STAGED_DTYPES = {torch.bfloat16: torch.float32, torch.float16: torch.float32}

# handle -> original torch dtype (restored at synchronize time)
_torch_handles: Dict[int, torch.dtype] = {}


def _to_numpy(t: torch.Tensor):
    if not isinstance(t, torch.Tensor):
        raise TypeError(f"expected a torch.Tensor, got {type(t)}")
    orig_dtype = t.dtype
    if t.dtype in _STAGED_DTYPES:
        t = t.to(_STAGED_DTYPES[t.dtype])
    return t.detach().contiguous().cpu().numpy(), orig_dtype


def _to_torch(a, dtype) -> torch.Tensor:
    # np.array (copy): a zero-copy view of a jax buffer is read-only, and
    # frontend callers mutate results (e.g. the optimizers' p.copy_)
    out = torch.from_numpy(np.array(a))
    return out.to(dtype) if out.dtype != dtype else out


def _nonblocking(api_fn, t: torch.Tensor, *args, **kwargs) -> int:
    arr, dtype = _to_numpy(t)
    handle = api_fn(arr, *args, **kwargs)
    _torch_handles[handle] = dtype
    return handle


def synchronize(handle: int) -> torch.Tensor:
    """Wait for a nonblocking torch op and return its torch output.

    Unknown / already-synchronized handles raise the core API's descriptive
    ValueError; a handle created through the JAX-level API still resolves
    (returned with its natural dtype).
    """
    dtype = _torch_handles.pop(handle, None)
    out = _api.synchronize(handle)   # raises ValueError for unknown handles
    if dtype is not None:
        return _to_torch(out, dtype)
    arr = np.array(out)
    if arr.dtype.name == "bfloat16":     # ml_dtypes — numpy bridge can't
        return torch.from_numpy(arr.astype(np.float32)).to(torch.bfloat16)
    return torch.from_numpy(arr)


wait = synchronize
poll = _api.poll


def allreduce_nonblocking(t: torch.Tensor, average: bool = True,
                          name: Optional[str] = None) -> int:
    return _nonblocking(_api.allreduce_nonblocking, t, average, name)


def allreduce(t: torch.Tensor, average: bool = True,
              name: Optional[str] = None) -> torch.Tensor:
    return synchronize(allreduce_nonblocking(t, average, name))


def broadcast_nonblocking(t: torch.Tensor, root_rank: int,
                          name: Optional[str] = None) -> int:
    return _nonblocking(_api.broadcast_nonblocking, t, root_rank, name)


def broadcast(t: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_nonblocking(t, root_rank, name))


def allgather_nonblocking(t: torch.Tensor, name: Optional[str] = None) -> int:
    return _nonblocking(_api.allgather_nonblocking, t, name)


def allgather(t: torch.Tensor, name: Optional[str] = None) -> torch.Tensor:
    return synchronize(allgather_nonblocking(t, name))


def neighbor_allreduce_nonblocking(t: torch.Tensor, **kwargs) -> int:
    return _nonblocking(_api.neighbor_allreduce_nonblocking, t, **kwargs)


def neighbor_allreduce(t: torch.Tensor, **kwargs) -> torch.Tensor:
    """Weighted neighbor average of the per-rank slices (the reference's
    flagship op, bluefog/torch/mpi_ops.py:475-645).  Keyword modes as in
    ``bluefog_tpu.neighbor_allreduce``: default topology weights,
    ``weight_matrix=W``, or ``sched=..., step=i``."""
    return synchronize(neighbor_allreduce_nonblocking(t, **kwargs))


# ---------------------------------------------------------------------------
# State-distribution helpers (reference: bluefog/torch/utility.py:26-218)
# ---------------------------------------------------------------------------

def _map_state(state_dict, fn):
    return {k: fn(v) if isinstance(v, torch.Tensor) else v
            for k, v in state_dict.items()}


def broadcast_parameters(state_dict, root_rank: int = 0):
    """Overwrite every rank's slice with ``root_rank``'s (utility.py:26).

    ``state_dict``: name -> [size, ...] torch tensor (global view).
    Returns a new dict; non-tensor entries pass through.
    """
    return _map_state(state_dict, lambda t: broadcast(t, root_rank))


def allreduce_parameters(state_dict, average: bool = True):
    """Average every rank's slice globally (utility.py:58)."""
    return _map_state(state_dict, lambda t: allreduce(t, average))


def broadcast_optimizer_state(optimizer: "torch.optim.Optimizer",
                              root_rank: int = 0):
    """Broadcast a torch optimizer's state tensors in place
    (utility.py:89-218).  State tensors must already be in global view
    ([size, ...]).  Scalar (0-dim) and non-tensor state is intentionally
    left untouched: in the single-controller global-view model every rank's
    scalar state is the same python object already."""
    for group in optimizer.param_groups:
        for p in group["params"]:
            st = optimizer.state.get(p, None)
            if not st:
                continue
            for key, val in list(st.items()):
                if isinstance(val, torch.Tensor) and val.ndim > 0:
                    st[key] = broadcast(val, root_rank)
