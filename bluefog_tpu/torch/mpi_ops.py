"""Torch-tensor collective ops over the JAX mesh.

Parity model: the reference's *primary* torch frontend op surface
(``bluefog/torch/mpi_ops.py`` — collectives :108-928, windows :998-1475):
allreduce/broadcast/allgather, neighbor_allreduce (static, per-call
weighted, dynamic, dst-weighted), neighbor_allgather (static + per-call
``src_ranks/dst_ranks``), hierarchical_neighbor_allreduce, pair_gossip, and
the full one-sided window family.  Tensors convert torch→numpy→jax on the
way in (zero-copy for contiguous CPU float32/64, int32/64) and back on the
way out; bfloat16/float16 stage through float32 exactly like the
reference's fp16 MPI path converts through a custom dtype
(``bluefog/common/half.cc``).
"""

from typing import Dict, Optional

import jax
import numpy as np
import torch

from ..ops import api as _api
from ..ops import windows as _win

__all__ = [
    "allreduce", "allreduce_nonblocking",
    "allreduce_", "allreduce_nonblocking_",
    "broadcast", "broadcast_nonblocking",
    "broadcast_", "broadcast_nonblocking_",
    "allgather", "allgather_nonblocking",
    "neighbor_allreduce", "neighbor_allreduce_nonblocking",
    "neighbor_allgather", "neighbor_allgather_nonblocking",
    "hierarchical_neighbor_allreduce",
    "hierarchical_neighbor_allreduce_nonblocking",
    "pair_gossip", "pair_gossip_nonblocking",
    "poll", "synchronize", "wait",
    "broadcast_parameters", "allreduce_parameters",
    "broadcast_optimizer_state",
    "win_create", "win_free", "win_put", "win_put_nonblocking",
    "win_accumulate", "win_accumulate_nonblocking",
    "win_get", "win_get_nonblocking",
    "win_update", "win_update_then_collect", "win_fetch", "win_publish",
    "win_wait", "win_poll", "win_mutex", "get_win_version",
    "win_associated_p", "get_current_created_window_names",
    "turn_on_win_ops_with_associated_p",
    "turn_off_win_ops_with_associated_p",
]

_STAGED_DTYPES = {torch.bfloat16: torch.float32, torch.float16: torch.float32}

# handle -> original torch dtype (restored at synchronize time)
_torch_handles: Dict[int, torch.dtype] = {}

# handle -> in-place destination: the reference's ``allreduce_`` /
# ``broadcast_`` mutate their input tensor (torch/mpi_ops.py:108-319);
# synchronize copies the result back into it and returns it.  STRONG
# references: ``allreduce_nonblocking_(p.data)`` passes a temporary
# alias whose only reference dies at the call boundary — a weakref here
# made that canonical pattern silently degrade to out-of-place (the
# result never reached the parameter).  The core handle table already
# pins the same-sized output array for abandoned handles, so a strong
# reference adds no new leak class.
_inplace_targets: Dict[int, torch.Tensor] = {}


def _to_numpy(t: torch.Tensor):
    if not isinstance(t, torch.Tensor):
        raise TypeError(f"expected a torch.Tensor, got {type(t)}")
    orig_dtype = t.dtype
    if t.dtype in _STAGED_DTYPES:
        t = t.to(_STAGED_DTYPES[t.dtype])
    return t.detach().contiguous().cpu().numpy(), orig_dtype


def _to_torch(a, dtype) -> torch.Tensor:
    # np.array (copy): a zero-copy view of a jax buffer is read-only, and
    # frontend callers mutate results (e.g. the optimizers' p.copy_)
    out = torch.from_numpy(np.array(a))
    return out.to(dtype) if out.dtype != dtype else out


def _nonblocking(api_fn, t, *args, **kwargs) -> int:
    if isinstance(t, (list, tuple)):
        # variable-size allgather family: a list of per-rank tensors with
        # differing first dims (reference test_allgather_variable_size)
        pairs = [_to_numpy(e) for e in t]
        arr = [p[0] for p in pairs]
        dtypes = {p[1] for p in pairs}
        if len(dtypes) > 1:
            # staging maps bf16/fp16 AND fp32 to float32 before the core
            # uniformity check, so a mixed list would silently coerce —
            # reject it here instead
            raise ValueError(
                f"ragged input mixes torch dtypes "
                f"{sorted(str(d) for d in dtypes)}; cast to one dtype first")
        dtype = pairs[0][1]
    else:
        arr, dtype = _to_numpy(t)
    handle = api_fn(arr, *args, **kwargs)
    _torch_handles[handle] = dtype
    return handle


def synchronize(handle: int) -> torch.Tensor:
    """Wait for a nonblocking torch op and return its torch output.

    Unknown / already-synchronized handles raise the core API's descriptive
    ValueError; a handle created through the JAX-level API still resolves
    (returned with its natural dtype).
    """
    # Look up BEFORE, pop only AFTER the core synchronize succeeds: a
    # deferred handle whose dispatch raises stays retryable in the core
    # table, and a retried wait must still find the in-place target and
    # dtype here (popping eagerly silently degraded the retry to an
    # out-of-place float32 result).  On failure, our entries live exactly
    # as long as the core's handle does — if the core dropped it (not
    # retryable), holding a strong tensor ref here would be a leak.
    dtype = _torch_handles.get(handle)
    target = _inplace_targets.get(handle)
    try:
        out = _api.synchronize(handle)   # ValueError for unknown handles
    except Exception:
        if not _api.has_handle(handle):
            _torch_handles.pop(handle, None)
            _inplace_targets.pop(handle, None)
        raise
    _torch_handles.pop(handle, None)
    _inplace_targets.pop(handle, None)
    if dtype is not None:
        res = _to_torch(out, dtype)
    else:
        arr = np.array(out)
        if arr.dtype.name == "bfloat16":     # ml_dtypes — numpy bridge can't
            res = torch.from_numpy(arr.astype(np.float32)).to(torch.bfloat16)
        else:
            res = torch.from_numpy(arr)
    if target is not None:
        with torch.no_grad():
            target.copy_(res)
        return target
    return res


wait = synchronize
poll = _api.poll


# First parameter is named ``tensor`` exactly like the reference's torch
# ops (bluefog/torch/mpi_ops.py:108-928) so keyword call sites —
# ``bf.allreduce(tensor=x)`` — port unchanged.

def allreduce_nonblocking(tensor: torch.Tensor, average: bool = True,
                          name: Optional[str] = None,
                          is_hierarchical_local: bool = False) -> int:
    return _nonblocking(_api.allreduce_nonblocking, tensor, average, name,
                        is_hierarchical_local)


def allreduce(tensor: torch.Tensor, average: bool = True,
              name: Optional[str] = None,
              is_hierarchical_local: bool = False) -> torch.Tensor:
    """Allreduce of the per-rank slices; ``is_hierarchical_local=True``
    reduces within each machine only (reference torch/mpi_ops.py:108-212)."""
    return synchronize(allreduce_nonblocking(tensor, average, name,
                                             is_hierarchical_local))


def allreduce_nonblocking_(tensor: torch.Tensor, average: bool = True,
                           name: Optional[str] = None,
                           is_hierarchical_local: bool = False) -> int:
    """In-place nonblocking allreduce: synchronize writes the result back
    into ``tensor`` and returns it (reference ``allreduce_nonblocking_``)."""
    h = allreduce_nonblocking(tensor, average, name, is_hierarchical_local)
    _inplace_targets[h] = tensor
    return h


def allreduce_(tensor: torch.Tensor, average: bool = True,
               name: Optional[str] = None,
               is_hierarchical_local: bool = False) -> torch.Tensor:
    return synchronize(allreduce_nonblocking_(tensor, average, name,
                                              is_hierarchical_local))


def broadcast_nonblocking(tensor: torch.Tensor, root_rank: int,
                          name: Optional[str] = None) -> int:
    return _nonblocking(_api.broadcast_nonblocking, tensor, root_rank, name)


def broadcast(tensor: torch.Tensor, root_rank: int,
              name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_nonblocking(tensor, root_rank, name))


def broadcast_nonblocking_(tensor: torch.Tensor, root_rank: int,
                           name: Optional[str] = None) -> int:
    """In-place nonblocking broadcast (reference ``broadcast_nonblocking_``)."""
    h = broadcast_nonblocking(tensor, root_rank, name)
    _inplace_targets[h] = tensor
    return h


def broadcast_(tensor: torch.Tensor, root_rank: int,
               name: Optional[str] = None) -> torch.Tensor:
    return synchronize(broadcast_nonblocking_(tensor, root_rank, name))


def allgather_nonblocking(tensor: torch.Tensor,
                          name: Optional[str] = None) -> int:
    return _nonblocking(_api.allgather_nonblocking, tensor, name)


def allgather(tensor: torch.Tensor,
              name: Optional[str] = None) -> torch.Tensor:
    return synchronize(allgather_nonblocking(tensor, name))


def neighbor_allreduce_nonblocking(tensor: torch.Tensor, **kwargs) -> int:
    return _nonblocking(_api.neighbor_allreduce_nonblocking, tensor,
                        **kwargs)


def neighbor_allreduce(tensor: torch.Tensor, **kwargs) -> torch.Tensor:
    """Weighted neighbor average of the per-rank slices (the reference's
    flagship op, bluefog/torch/mpi_ops.py:475-645).  Keyword modes as in
    ``bluefog_tpu.neighbor_allreduce``: default topology weights,
    ``weight_matrix=W``, or ``sched=..., step=i``."""
    return synchronize(neighbor_allreduce_nonblocking(tensor, **kwargs))


def neighbor_allgather_nonblocking(tensor: torch.Tensor,
                                   name: Optional[str] = None, *,
                                   src_ranks=None, dst_ranks=None,
                                   enable_topo_check: bool = True) -> int:
    return _nonblocking(_api.neighbor_allgather_nonblocking, tensor, name,
                        src_ranks=src_ranks, dst_ranks=dst_ranks,
                        enable_topo_check=enable_topo_check)


def neighbor_allgather(tensor: torch.Tensor, name: Optional[str] = None, *,
                       src_ranks=None, dst_ranks=None,
                       enable_topo_check: bool = True) -> torch.Tensor:
    """Gather in-neighbor slices padded to max in-degree (reference
    bluefog/torch/mpi_ops.py:397-472, incl. the per-call
    ``src_ranks/dst_ranks`` dynamic form)."""
    return synchronize(neighbor_allgather_nonblocking(
        tensor, name, src_ranks=src_ranks, dst_ranks=dst_ranks,
        enable_topo_check=enable_topo_check))


def hierarchical_neighbor_allreduce_nonblocking(
        tensor: torch.Tensor, name: Optional[str] = None) -> int:
    return _nonblocking(
        _api.hierarchical_neighbor_allreduce_nonblocking, tensor, name)


def hierarchical_neighbor_allreduce(tensor: torch.Tensor,
                                    name: Optional[str] = None):
    """Machine-level two-step average (reference
    bluefog/torch/mpi_ops.py:648-838)."""
    return synchronize(
        hierarchical_neighbor_allreduce_nonblocking(tensor, name))


def pair_gossip_nonblocking(tensor: torch.Tensor, pairs, self_weight=None,
                            pair_weight=None,
                            name: Optional[str] = None) -> int:
    return _nonblocking(_api.pair_gossip_nonblocking, tensor, pairs,
                        self_weight, pair_weight, name)


def pair_gossip(tensor: torch.Tensor, pairs, self_weight=None,
                pair_weight=None,
                name: Optional[str] = None) -> torch.Tensor:
    """Pairwise weighted averaging over a matching (reference
    bluefog/torch/mpi_ops.py:852-928; ``pairs`` is the global matching —
    the SPMD form of the reference's per-rank ``target_rank``)."""
    return synchronize(pair_gossip_nonblocking(tensor, pairs, self_weight,
                                               pair_weight, name))


# ---------------------------------------------------------------------------
# One-sided window ops (reference: bluefog/torch/mpi_ops.py:998-1475)
# ---------------------------------------------------------------------------

# window name -> torch dtype for round-tripping results
_win_dtypes: Dict[str, torch.dtype] = {}


def _win_to_numpy(t):
    """Torch tensor OR pytree of torch tensors -> (numpy tree, dtype tree).

    Pytree windows carry whole parameter sets in one window (fusion,
    ops/windows.py); torch tensors are opaque leaves to jax.tree, so the
    same code path handles both shapes."""
    arrs = jax.tree.map(lambda x: _to_numpy(x)[0], t)
    dtypes = jax.tree.map(lambda x: x.dtype, t)
    return arrs, dtypes


def win_create(tensor, name: str, zero_init: bool = False) -> bool:
    arr, dtype = _win_to_numpy(tensor)
    if _win.win_create(arr, name, zero_init=zero_init):
        _win_dtypes[name] = dtype
        return True
    return False


def win_free(name: Optional[str] = None) -> bool:
    if name is None:
        _win_dtypes.clear()
    else:
        _win_dtypes.pop(name, None)
    return _win.win_free(name)


def win_put_nonblocking(tensor, name: str, self_weight=None,
                        dst_weights=None, require_mutex: bool = False,
                        sched=None, step=None) -> int:
    arr, _ = _win_to_numpy(tensor)
    return _win.win_put_nonblocking(arr, name, self_weight, dst_weights,
                                    require_mutex, sched, step)


def win_put(tensor, name: str, self_weight=None, dst_weights=None,
            require_mutex: bool = False, sched=None, step=None) -> bool:
    _win.win_wait(win_put_nonblocking(tensor, name, self_weight, dst_weights,
                                      require_mutex, sched, step))
    return True


def win_accumulate_nonblocking(tensor, name: str, self_weight=None,
                               dst_weights=None,
                               require_mutex: bool = False,
                               sched=None, step=None) -> int:
    arr, _ = _win_to_numpy(tensor)
    return _win.win_accumulate_nonblocking(arr, name, self_weight,
                                           dst_weights, require_mutex,
                                           sched, step)


def win_accumulate(tensor, name: str, self_weight=None,
                   dst_weights=None, require_mutex: bool = False,
                   sched=None, step=None) -> bool:
    _win.win_wait(win_accumulate_nonblocking(tensor, name, self_weight,
                                             dst_weights, require_mutex,
                                             sched, step))
    return True


def win_get_nonblocking(name: str, src_weights=None,
                        require_mutex: bool = False,
                        sched=None, step=None) -> int:
    return _win.win_get_nonblocking(name, src_weights, require_mutex,
                                    sched, step)


def win_get(name: str, src_weights=None, require_mutex: bool = False,
            sched=None, step=None) -> bool:
    return _win.win_get(name, src_weights, require_mutex, sched, step)


def _win_to_torch(name: str, a):
    dtypes = _win_dtypes.get(name)
    # structure check guards against a stale entry (a same-named window
    # re-created through the JAX layer, which does not touch this map)
    if dtypes is not None and \
            jax.tree.structure(a) == jax.tree.structure(dtypes):
        return jax.tree.map(_to_torch, a, dtypes)
    return jax.tree.map(lambda leaf: _to_torch(leaf, torch.float32), a)


def win_update(name: str, self_weight=None, neighbor_weights=None,
               reset: bool = False, clone: bool = False,
               require_mutex: bool = False):
    """Returns a torch tensor — or, for pytree windows, the matching
    pytree of torch tensors."""
    return _win_to_torch(name, _win.win_update(
        name, self_weight, neighbor_weights, reset, clone, require_mutex))


def win_update_then_collect(name: str, require_mutex: bool = True):
    return _win_to_torch(name, _win.win_update_then_collect(name,
                                                            require_mutex))


def win_fetch(name: str):
    return _win_to_torch(name, _win.win_fetch(name))


def win_publish(name: str, t) -> None:
    arr, _ = _win_to_numpy(t)
    _win.win_publish(name, arr)


def win_associated_p(name: str, rank: Optional[int] = None) -> float:
    return _win.win_associated_p(name, rank)


win_wait = _win.win_wait
win_poll = _win.win_poll
win_mutex = _win.win_mutex
get_win_version = _win.get_win_version
get_current_created_window_names = _win.get_current_created_window_names
turn_on_win_ops_with_associated_p = _win.turn_on_win_ops_with_associated_p
turn_off_win_ops_with_associated_p = _win.turn_off_win_ops_with_associated_p


# ---------------------------------------------------------------------------
# State-distribution helpers (reference: bluefog/torch/utility.py:26-218)
# ---------------------------------------------------------------------------

def _map_state(state_dict, fn):
    return {k: fn(v) if isinstance(v, torch.Tensor) else v
            for k, v in state_dict.items()}


def broadcast_parameters(params, root_rank: int = 0):
    """Overwrite every rank's slice with ``root_rank``'s (utility.py:26).

    ``params``: a state_dict (name -> [size, ...] torch tensor, global
    view) or named-parameter iterable, like the reference's.  IN-PLACE
    like the reference: the given tensors are overwritten (reference
    callers discard the return value — ``bf.broadcast_parameters(
    model.named_parameters(), 0)`` must actually synchronize the model).
    Returns the same dict (non-tensor entries pass through) for
    convenience.
    """
    if not isinstance(params, dict):
        params = dict(params)   # reference accepts named_parameters() too
    return _map_state(params, lambda t: broadcast_(t, root_rank))


def allreduce_parameters(params, average: bool = True):
    """Average every rank's slice globally, IN PLACE (utility.py:58)."""
    if not isinstance(params, dict):
        params = dict(params)
    return _map_state(params, lambda t: allreduce_(t, average))


def broadcast_optimizer_state(optimizer: "torch.optim.Optimizer",
                              root_rank: int = 0):
    """Broadcast a torch optimizer's state tensors in place
    (utility.py:89-218).  State tensors must already be in global view
    ([size, ...]).  Scalar (0-dim) and non-tensor state is intentionally
    left untouched: in the single-controller global-view model every rank's
    scalar state is the same python object already."""
    for group in optimizer.param_groups:
        for p in group["params"]:
            st = optimizer.state.get(p, None)
            if not st:
                continue
            for key, val in list(st.items()):
                if isinstance(val, torch.Tensor) and val.ndim > 0:
                    st[key] = broadcast(val, root_rank)
