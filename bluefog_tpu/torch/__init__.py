"""PyTorch frontend (second-framework adapter).

The reference ships two framework frontends over one core: PyTorch
(``bluefog/torch/``) and TensorFlow (``bluefog/tensorflow/mpi_ops.py:75-212``
— allreduce/broadcast/allgather + ``DistributedOptimizer`` /
``DistributedGradientTape`` / ``broadcast_variables``).  This package plays
the same role for ``bluefog_tpu``: the JAX/XLA mesh is the core, and torch
tensors ride it through zero-copy numpy bridges.

Global-view convention as everywhere else: "rank i's tensor" is slice ``i``
of a ``[size, ...]`` torch tensor.  Ops stage through the mesh (TPU when
available), mirroring the reference's CPU-staging mode for GPU tensors
(``BLUEFOG_OPS_ON_CPU``, torch/mpi_ops.cc) in reverse.

    import bluefog_tpu as bf
    import bluefog_tpu.torch as bft
    bf.init()
    out = bft.neighbor_allreduce(torch.randn(bf.size(), 128))
"""

from .mpi_ops import (
    allreduce, allreduce_nonblocking,
    broadcast, broadcast_nonblocking,
    allgather, allgather_nonblocking,
    neighbor_allreduce, neighbor_allreduce_nonblocking,
    poll, synchronize, wait,
    broadcast_parameters, allreduce_parameters, broadcast_optimizer_state,
)
from .optimizers import (
    DistributedOptimizer,
    DistributedGradientAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
)

__all__ = [
    "allreduce", "allreduce_nonblocking",
    "broadcast", "broadcast_nonblocking",
    "allgather", "allgather_nonblocking",
    "neighbor_allreduce", "neighbor_allreduce_nonblocking",
    "poll", "synchronize", "wait",
    "broadcast_parameters", "allreduce_parameters",
    "broadcast_optimizer_state",
    "DistributedOptimizer",
    "DistributedGradientAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
]
