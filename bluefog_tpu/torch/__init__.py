"""PyTorch frontend (second-framework adapter).

The reference ships two framework frontends over one core: PyTorch
(``bluefog/torch/``) and TensorFlow (``bluefog/tensorflow/mpi_ops.py:75-212``
— allreduce/broadcast/allgather + ``DistributedOptimizer`` /
``DistributedGradientTape`` / ``broadcast_variables``).  This package plays
the same role for ``bluefog_tpu``: the JAX/XLA mesh is the core, and torch
tensors ride it through zero-copy numpy bridges.  The surface mirrors the
reference's *torch* frontend (``bluefog/torch/mpi_ops.py``): all
collectives including hierarchical/pair-gossip/neighbor-allgather, the
one-sided window family, and five optimizer factories.

Global-view convention as everywhere else: "rank i's tensor" is slice ``i``
of a ``[size, ...]`` torch tensor.  Ops stage through the mesh (TPU when
available), mirroring the reference's CPU-staging mode for GPU tensors
(``BLUEFOG_OPS_ON_CPU``, torch/mpi_ops.cc) in reverse.

    import bluefog_tpu as bf
    import bluefog_tpu.torch as bft
    bf.init()
    out = bft.neighbor_allreduce(torch.randn(bf.size(), 128))
"""

# Context/topology/timeline surface re-exported from the core so the
# frontend is a drop-in for the reference's single-module habit
# (``import bluefog.torch as bf; bf.init(); bf.rank()`` — the reference
# re-exports these from bluefog/torch/__init__.py:34-72); the functions
# are the very same objects as the top-level ``bluefog_tpu`` ones.
from .. import (
    init, shutdown, size, local_size, rank, local_rank,
    machine_size, machine_rank,
    load_topology, set_topology, load_machine_topology,
    set_machine_topology,
    in_neighbor_ranks, out_neighbor_ranks,
    in_neighbor_machine_ranks, out_neighbor_machine_ranks,
    mpi_threads_supported, unified_mpi_window_model_supported,
    nccl_built, is_homogeneous,
    suspend, resume, barrier,
    set_skip_negotiate_stage, get_skip_negotiate_stage,
    timeline_start_activity, timeline_end_activity, timeline_context,
)
from .mpi_ops import (
    allreduce, allreduce_nonblocking, allreduce_, allreduce_nonblocking_,
    broadcast, broadcast_nonblocking, broadcast_, broadcast_nonblocking_,
    allgather, allgather_nonblocking,
    neighbor_allreduce, neighbor_allreduce_nonblocking,
    neighbor_allgather, neighbor_allgather_nonblocking,
    hierarchical_neighbor_allreduce,
    hierarchical_neighbor_allreduce_nonblocking,
    pair_gossip, pair_gossip_nonblocking,
    poll, synchronize, wait,
    broadcast_parameters, allreduce_parameters, broadcast_optimizer_state,
    win_create, win_free, win_put, win_put_nonblocking,
    win_accumulate, win_accumulate_nonblocking,
    win_get, win_get_nonblocking,
    win_update, win_update_then_collect, win_fetch, win_publish,
    win_wait, win_poll, win_mutex, get_win_version,
    win_associated_p, get_current_created_window_names,
    turn_on_win_ops_with_associated_p, turn_off_win_ops_with_associated_p,
)
from .optimizers import (
    register_timeline_hooks,
    CommunicationType,
    DistributedOptimizer,
    DistributedGradientAllreduceOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedAdaptThenCombineOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedExactDiffusionOptimizer,
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)

__all__ = [
    "init", "shutdown", "size", "local_size", "rank", "local_rank",
    "machine_size", "machine_rank",
    "load_topology", "set_topology", "load_machine_topology",
    "set_machine_topology",
    "in_neighbor_ranks", "out_neighbor_ranks",
    "in_neighbor_machine_ranks", "out_neighbor_machine_ranks",
    "mpi_threads_supported", "unified_mpi_window_model_supported",
    "nccl_built", "is_homogeneous",
    "suspend", "resume", "barrier",
    "set_skip_negotiate_stage", "get_skip_negotiate_stage",
    "timeline_start_activity", "timeline_end_activity",
    "timeline_context",
    "allreduce", "allreduce_nonblocking",
    "allreduce_", "allreduce_nonblocking_",
    "broadcast", "broadcast_nonblocking",
    "broadcast_", "broadcast_nonblocking_",
    "allgather", "allgather_nonblocking",
    "neighbor_allreduce", "neighbor_allreduce_nonblocking",
    "neighbor_allgather", "neighbor_allgather_nonblocking",
    "hierarchical_neighbor_allreduce",
    "hierarchical_neighbor_allreduce_nonblocking",
    "pair_gossip", "pair_gossip_nonblocking",
    "poll", "synchronize", "wait",
    "broadcast_parameters", "allreduce_parameters",
    "broadcast_optimizer_state",
    "win_create", "win_free", "win_put", "win_put_nonblocking",
    "win_accumulate", "win_accumulate_nonblocking",
    "win_get", "win_get_nonblocking",
    "win_update", "win_update_then_collect", "win_fetch", "win_publish",
    "win_wait", "win_poll", "win_mutex", "get_win_version",
    "win_associated_p", "get_current_created_window_names",
    "turn_on_win_ops_with_associated_p",
    "turn_off_win_ops_with_associated_p",
    "register_timeline_hooks",
    "CommunicationType",
    "DistributedOptimizer",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedExactDiffusionOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
]
