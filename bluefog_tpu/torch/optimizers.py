"""Distributed wrappers for ``torch.optim`` optimizers.

Parity model: the reference TF frontend's ``DistributedOptimizer``
(``bluefog/tensorflow/optimizers.py:135``) plus the torch frontend's two
main strategies (``bluefog/torch/optimizers.py:1301,1376``):

* ``DistributedGradientAllreduceOptimizer`` — Horovod-style: allreduce
  gradients, then the local step.
* ``DistributedNeighborAllreduceOptimizer`` — CTA: neighbor-average the
  *parameters*, then apply the local step.

Like the reference (``torch/optimizers.py`` re-classes the wrapped
optimizer via ``type(...)``), the factories dynamically subclass the
wrapped optimizer's own class, so the result still IS a
``torch.optim.Optimizer`` of the original type — LR schedulers, grad
scalers, and ``isinstance`` checks keep working.

Global view as everywhere in this frontend: every parameter tensor carries
a leading ``[size]`` replica axis.  The communication runs on the JAX mesh;
the torch optimizer's own math stays untouched.
"""

from typing import Optional

import numpy as np
import torch

from . import mpi_ops as _ops
from ..optim.strategies import CommunicationType

__all__ = [
    "CommunicationType",
    "DistributedOptimizer",
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
    "register_timeline_hooks",
]


def register_timeline_hooks(module: "torch.nn.Module"):
    """Per-layer timeline spans, the reference's auto-hook feature
    (torch/optimizers.py:112-163): every leaf submodule records a FORWARD
    span around its forward and a ``GRADIENT COMPT.`` span around its
    backward into the active timeline (``BLUEFOG_TIMELINE`` /
    ``bf.timeline_start``).  Returns the hook handles (call ``.remove()``
    to detach).  No-ops (cheap flag checks) while the timeline is off.

    The JAX path needs no equivalent: flax module names land in XLA HLO
    metadata, so the profiler attributes device time per layer natively.
    """
    from .. import timeline as _tl

    handles = []
    for name, mod in module.named_modules():
        if next(mod.children(), None) is not None:
            continue                       # leaves only, like the reference
        label = name or type(mod).__name__

        def fwd_pre(mod_, inp, _label=label):
            _tl.timeline_start_activity(_label, "FORWARD")

        def fwd_post(mod_, inp, out, _label=label):
            _tl.timeline_end_activity(_label)

        def bwd_pre(mod_, gout, _label=label):
            _tl.timeline_start_activity(_label, "GRADIENT COMPT.")

        def bwd_post(mod_, gin, gout, _label=label):
            _tl.timeline_end_activity(_label)

        handles.append(mod.register_forward_pre_hook(fwd_pre))
        handles.append(mod.register_forward_hook(fwd_post))
        handles.append(mod.register_full_backward_pre_hook(bwd_pre))
        handles.append(mod.register_full_backward_hook(bwd_post))
    return handles


class _DistributedMixin:
    """step() override shared by both strategies; spliced in by re-classing."""

    def _bft_setup(self, num_steps_per_communication: int):
        self._bft_period = max(1, int(num_steps_per_communication))
        self._bft_tick = 0

    def _bft_params(self):
        for group in self.param_groups:
            yield from group["params"]

    def _bft_communicate(self):
        raise NotImplementedError

    def step(self, closure=None):
        self._bft_tick += 1
        if self._bft_tick % self._bft_period == 0:
            self._bft_communicate()
        return super().step(closure)


class _GradientAllreduceMixin(_DistributedMixin):
    """Allreduce-average gradients before the local step
    (reference ``_DistributedOptimizer``, torch/optimizers.py:166-294)."""

    def step(self, closure=None):
        # a closure recomputes gradients inside super().step(), which would
        # overwrite the allreduced ones — evaluate it once up front instead
        # (multi-evaluation optimizers like LBFGS are not supported)
        loss = None
        if closure is not None:
            with torch.enable_grad():
                loss = closure()
        super().step()
        return loss

    def _bft_communicate(self):
        for p in self._bft_params():
            if p.grad is not None:
                _ops.allreduce_(p.grad, average=True)


class _CombineMixin(_DistributedMixin):
    """Parameter averaging dispatched by ``communication_type`` — the
    combine half shared by CTA / AWC / ATC / hierarchical (reference
    ``_DistributedReduceOptimizer``, torch/optimizers.py:297-482, whose
    re-class also backs the AWC factory at :1497).  Per-step dynamic
    topologies: assign ``opt.sched``/``opt.step_index`` (mirrors the
    reference's mutable ``dst_weights`` attributes, optimizers.py:107-109).
    """

    sched = None
    step_index = 0
    communication_type = CommunicationType.neighbor_allreduce

    def _bft_combine(self):
        ct = self.communication_type
        if ct == CommunicationType.empty:
            return
        kwargs = {}
        if ct == CommunicationType.neighbor_allreduce and self.sched is not None:
            kwargs = {"sched": self.sched, "step": self.step_index}
        for p in self._bft_params():
            with torch.no_grad():
                if ct == CommunicationType.allreduce:
                    _ops.allreduce_(p.data, average=True)
                elif ct == CommunicationType.hierarchical_neighbor_allreduce:
                    p.copy_(_ops.hierarchical_neighbor_allreduce(p.data))
                else:
                    p.copy_(_ops.neighbor_allreduce(p.data, **kwargs))
        self.step_index += 1

    def _bft_communicate(self):
        self._bft_combine()


class _NeighborAllreduceMixin(_CombineMixin):
    """Combine-then-adapt with neighbor averaging — the flagship
    decentralized strategy (reference factory torch/optimizers.py:1326)."""

    communication_type = CommunicationType.neighbor_allreduce


class _AdaptThenCombineMixin(_CombineMixin):
    """ATC: the wrapped optimizer's update runs FIRST, then the adapted
    parameters are averaged (reference
    ``_DistributedAdaptThenCombineOptimizer``, torch/optimizers.py:485-841;
    factory :1426).  Same knobs as the combine mixin."""

    def step(self, closure=None):
        # the wrapped optimizer's own step (skip _DistributedMixin.step)
        loss = super(_DistributedMixin, self).step(closure)
        self._bft_tick += 1
        if self._bft_tick % self._bft_period == 0:
            self._bft_combine()
        return loss


class _ExactDiffusionMixin(_DistributedMixin):
    """Exact-Diffusion / D2 on torch tensors (beyond-reference; JAX twin:
    optim/strategies.py::exact_diffusion_step):

        psi_k   = adapt(x_k)                 # the wrapped optimizer's step
        phi_k   = psi_k + x_k - psi_{k-1}    # bias correction
        x_{k+1} = neighbor_allreduce(phi_k)  # static-topology average

    psi_prev lives in ``self.state[p]["bft_psi_prev"]`` so it (a)
    round-trips through ``state_dict()``/``load_state_dict()`` like any
    optimizer algorithm state and (b) initializes lazily per parameter —
    params added via ``add_param_group`` after the first step still get
    the correction and the exchange.  A param without saved psi_prev
    uses its own pre-step value (phi_0 = psi_0, plain ATC first step).
    Static SYMMETRIC mixing only (validated per step against the live
    topology; exchanged through the damped (I+W)/2 matrix — see
    optim/strategies.py::exact_diffusion_topology), one exchange per
    step."""

    def _bft_ed_matrix(self):
        import numpy as np
        from .. import context as _ctx
        from ..optim import strategies as _S
        topo = _ctx.ctx().compiled_topology
        cached = getattr(self, "_bft_ed_cache", None)
        if cached is None or cached[0] is not topo:
            damped = _S.exact_diffusion_topology(topo)   # validates symmetry
            self._bft_ed_cache = (topo, np.asarray(damped.weight_matrix))
        return self._bft_ed_cache[1]

    @property
    def sched(self):
        return None

    @sched.setter
    def sched(self, value):
        # other combine optimizers take this knob; silently ignoring it
        # here would train on the wrong topology belief — match the JAX
        # factory's loud rejection (optim/wrappers.py)
        if value is not None:
            raise ValueError(
                "exact-diffusion requires a static topology: the "
                "correction diverges under dynamic schedules")

    def step(self, closure=None):
        params = list(self._bft_params())
        x_prev = {id(p): p.data.clone() for p in params}
        # the wrapped optimizer's own step (skip _DistributedMixin.step)
        loss = super(_DistributedMixin, self).step(closure)
        with torch.no_grad():
            for p in params:
                st = self.state[p]
                xp = x_prev[id(p)]
                sp = st.get("bft_psi_prev", xp)      # first step: psi_prev=x_0
                psi = p.data.clone()                 # adapted weights
                p.data.add_(xp - sp)                 # phi = psi + x - psi_prev
                p.data.copy_(_ops.neighbor_allreduce(
                    p.data, weight_matrix=self._bft_ed_matrix()))
                st["bft_psi_prev"] = psi
        return loss


def _reclass(optimizer: torch.optim.Optimizer, mixin, name: str,
             num_steps_per_communication: int):
    cls = type(name, (mixin, optimizer.__class__), {})
    optimizer.__class__ = cls
    optimizer._bft_setup(num_steps_per_communication)
    return optimizer


def _check_sched_comm(sched, communication_type):
    """Dynamic schedules only ride the neighbor-allreduce combine; accepting
    one silently with another communication_type would train on the wrong
    topology."""
    if sched is not None and \
            communication_type != CommunicationType.neighbor_allreduce:
        raise ValueError(
            f"sched= requires "
            f"communication_type=CommunicationType.neighbor_allreduce, "
            f"got {communication_type}")


def _check_model(model):
    """Reference factories take ``model`` as the second positional
    argument (torch/optimizers.py:1180-1497).  Parameters are discovered
    from the optimizer's param_groups here (the reference walks the model
    instead), so the model's only runtime role is per-layer timeline
    hooks; it is validated FIRST — before any re-classing or window
    allocation — so a legacy positional num_steps/communication/prefix
    value cannot silently land in its slot or leave half-built state."""
    if model is not None and not isinstance(model, torch.nn.Module):
        raise TypeError(
            f"second positional argument is `model` (reference factory "
            f"signature); got {type(model).__name__} — pass "
            f"num_steps_per_communication / communication_type / "
            f"window_prefix by keyword")


def _attach_model(opt, model):
    opt._bft_timeline_handles = (
        register_timeline_hooks(model) if model is not None else [])
    return opt


def DistributedGradientAllreduceOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None,
        num_steps_per_communication: int = 1) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` so each step allreduce-averages gradients
    first (reference factory torch/optimizers.py:1376)."""
    _check_model(model)
    return _attach_model(
        _reclass(optimizer, _GradientAllreduceMixin,
                 "DistributedGradientAllreduceOptimizer",
                 num_steps_per_communication), model)


def DistributedAllreduceOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None,
        num_steps_per_communication: int = 1) -> torch.optim.Optimizer:
    """CTA with a GLOBAL allreduce of the weights (reference factory
    torch/optimizers.py:1301): combine = full average, then local step."""
    _check_model(model)
    opt = _reclass(optimizer, _CombineMixin,
                   "DistributedAllreduceOptimizer",
                   num_steps_per_communication)
    opt.communication_type = CommunicationType.allreduce
    return _attach_model(opt, model)


def DistributedNeighborAllreduceOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None,
        num_steps_per_communication: int = 1,
        sched=None) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` so each step neighbor-averages parameters
    first (reference factory torch/optimizers.py:1326)."""
    _check_model(model)
    opt = _reclass(optimizer, _NeighborAllreduceMixin,
                   "DistributedNeighborAllreduceOptimizer",
                   num_steps_per_communication)
    opt.sched = sched
    opt.step_index = 0
    return _attach_model(opt, model)


def DistributedHierarchicalNeighborAllreduceOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None,
        num_steps_per_communication: int = 1) -> torch.optim.Optimizer:
    """CTA with machine-level two-step averaging (reference factory
    torch/optimizers.py:1352).  Requires a machine topology
    (``bf.set_machine_topology``) like the reference."""
    _check_model(model)
    opt = _reclass(optimizer, _CombineMixin,
                   "DistributedHierarchicalNeighborAllreduceOptimizer",
                   num_steps_per_communication)
    opt.communication_type = CommunicationType.hierarchical_neighbor_allreduce
    return _attach_model(opt, model)


def DistributedAdaptThenCombineOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None,
        communication_type: CommunicationType =
        CommunicationType.neighbor_allreduce,
        num_steps_per_communication: int = 1,
        sched=None) -> torch.optim.Optimizer:
    """ATC: local update first, then average the adapted weights
    (reference factory torch/optimizers.py:1426).  Unlike the reference —
    which overrides per-parameter step math for a whitelist of optimizers
    (SGD/Adam/...) to overlap communication — any ``torch.optim.Optimizer``
    works here: the combine runs as one batched mesh program after the
    step, so there is no per-parameter hook machinery to special-case."""
    _check_model(model)
    _check_sched_comm(sched, communication_type)
    opt = _reclass(optimizer, _AdaptThenCombineMixin,
                   "DistributedAdaptThenCombineOptimizer",
                   num_steps_per_communication)
    opt.communication_type = communication_type
    opt.sched = sched
    opt.step_index = 0
    return _attach_model(opt, model)


def DistributedAdaptWithCombineOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None,
        communication_type: CommunicationType =
        CommunicationType.neighbor_allreduce,
        num_steps_per_communication: int = 1,
        sched=None) -> torch.optim.Optimizer:
    """AWC: combine computed from the pre-update weights, concurrently
    with the update (reference factory torch/optimizers.py:1497 — whose
    re-class body IS the CTA ``_DistributedReduceOptimizer``; the overlap
    is scheduling, not different math).  Combine-then-adapt semantics
    with the full ``communication_type`` knob."""
    _check_model(model)
    _check_sched_comm(sched, communication_type)
    opt = _reclass(optimizer, _CombineMixin,
                   "DistributedAdaptWithCombineOptimizer",
                   num_steps_per_communication)
    opt.communication_type = communication_type
    opt.sched = sched
    opt.step_index = 0
    return _attach_model(opt, model)


class _WinPutMixin(_DistributedMixin):
    """One-sided push flavor (reference ``_DistributedWinOptimizer`` push
    mode, torch/optimizers.py:844-1023): win_put the parameters to the
    out-neighbors, fold the receive buffers with win_update, then step.
    Per-call weighting via the mutable ``dst_weights`` attribute (global
    [N, N] matrix), mirroring the reference's per-iteration knobs.

    ALL parameters live in ONE pytree window (the fusion-buffer
    equivalent, ops/windows.py) — each communication phase is a single
    program, not one per tensor.  Window registration here is shared
    with the pull flavor subclass."""

    dst_weights = None

    def _bft_data(self):
        return [p.data for p in self._bft_params()]

    def _bft_register_windows(self, prefix: str, zero_init: bool = False):
        self._bft_name = prefix + ".params"
        if not _ops.win_create(self._bft_data(), self._bft_name,
                               zero_init=zero_init):
            raise ValueError(f"Cannot allocate window for {self._bft_name}")

    def _bft_free_windows(self):
        _ops.win_free(self._bft_name)

    def _bft_copy_in(self, values):
        with torch.no_grad():
            for p, v in zip(self._bft_params(), values):
                p.copy_(v)

    def _bft_communicate(self):
        _ops.win_wait(_ops.win_put_nonblocking(
            self._bft_data(), self._bft_name, dst_weights=self.dst_weights))
        self._bft_copy_in(_ops.win_update(self._bft_name,
                                          require_mutex=True))


class _PullGetMixin(_WinPutMixin):
    """One-sided pull flavor (reference ``_DistributedWinOptimizer`` pull
    mode, torch/optimizers.py:844-1023; factory :1225): publish the local
    parameters into the window, win_get from the (dynamic) in-neighbors,
    fold the receive buffers with win_update, then step.  Per-call
    weighting via the mutable ``src_weights`` attribute."""

    src_weights = None

    def _bft_communicate(self):
        _ops.win_publish(self._bft_name, self._bft_data())
        _ops.win_wait(_ops.win_get_nonblocking(
            self._bft_name, src_weights=self.src_weights))
        self._bft_copy_in(_ops.win_update(self._bft_name,
                                          require_mutex=True))


class _PushSumMixin(_WinPutMixin):
    """Push-sum / gradient-push (reference ``_DistributedPushSumOptimizer``,
    torch/optimizers.py:1026-1177): ONE pytree window holds the biased
    iterates x with the associated-P scalar riding every accumulate; the
    visible parameters are the de-biased x/p.

    The column-stochastic push weights are DERIVED from the topology
    (mass conservation) — the inherited mutable ``dst_weights`` knob does
    not apply here and is rejected if set."""

    def _bft_register_windows(self, prefix: str):
        from ..context import ctx
        _ops.turn_on_win_ops_with_associated_p()
        topo = ctx().compiled_topology
        A = (topo.weight_matrix != 0).astype(np.float64)
        np.fill_diagonal(A, 0.0)
        self._bft_alpha = 1.0 / (A.sum(axis=1) + 1.0)      # [N]
        self._bft_dst = A * self._bft_alpha[:, None]
        super()._bft_register_windows(prefix, zero_init=True)

    def _bft_debias_in(self, values):
        pvec = _win_p_tensor(self._bft_name)
        with torch.no_grad():
            for p, v in zip(self._bft_params(), values):
                p.copy_(v / pvec.view((-1,) + (1,) * (v.dim() - 1)))

    def step(self, closure=None):
        if self.dst_weights is not None:
            raise ValueError(
                "push-sum derives its column-stochastic weights from the "
                "topology; the dst_weights knob does not apply (use "
                "bf.set_topology to change the graph)")
        # local adapt on the *biased* iterate with gradients taken at the
        # de-biased view, then push-accumulate + collect + de-bias
        self._bft_copy_in(_ops.win_fetch(self._bft_name))
        # the wrapped optimizer's own step (skip _DistributedMixin.step)
        loss = super(_DistributedMixin, self).step(closure)
        self._bft_tick += 1
        if self._bft_tick % self._bft_period != 0:
            # local-only step: publish the adapted biased iterate, expose
            # the de-biased view
            adapted = self._bft_data()
            _ops.win_publish(self._bft_name, adapted)
            self._bft_debias_in(adapted)
            return loss
        _ops.win_accumulate(self._bft_data(), self._bft_name,
                            self_weight=self._bft_alpha,
                            dst_weights=self._bft_dst, require_mutex=True)
        self._bft_debias_in(_ops.win_update_then_collect(self._bft_name))
        return loss


def _win_p_tensor(name: str) -> torch.Tensor:
    """The [N] associated-P vector as a torch tensor."""
    from ..ops import windows as _w
    # np.array (copy): zero-copy views of jax buffers are read-only
    return torch.from_numpy(np.array(_w.win_associated_p_vector(name)))


_window_opt_counter = [0]


def _default_prefix(window_prefix: Optional[str], base: str) -> str:
    """Unique deterministic default window names, so default-constructed
    window optimizers coexist (same fix as the JAX wrappers)."""
    if window_prefix is not None:
        return window_prefix
    _window_opt_counter[0] += 1
    return f"{base}{_window_opt_counter[0]}"


def DistributedWinPutOptimizer(optimizer: torch.optim.Optimizer,
                               model: Optional["torch.nn.Module"] = None,
                               num_steps_per_communication: int = 1,
                               window_prefix: Optional[str] = None
                               ) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` for the one-sided push strategy (reference
    factory torch/optimizers.py:1271).  Windows are created immediately;
    call ``opt._bft_free_windows()`` to release them."""
    _check_model(model)
    opt = _reclass(optimizer, _WinPutMixin, "DistributedWinPutOptimizer",
                   num_steps_per_communication)
    opt._bft_register_windows(_default_prefix(window_prefix, "win_put_opt"))
    return _attach_model(opt, model)


def DistributedPullGetOptimizer(optimizer: torch.optim.Optimizer,
                                model: Optional["torch.nn.Module"] = None,
                                num_steps_per_communication: int = 1,
                                window_prefix: Optional[str] = None
                                ) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` for the one-sided pull strategy (reference
    factory torch/optimizers.py:1225).  Windows are created immediately;
    call ``opt._bft_free_windows()`` to release them."""
    _check_model(model)
    opt = _reclass(optimizer, _PullGetMixin, "DistributedPullGetOptimizer",
                   num_steps_per_communication)
    opt._bft_register_windows(_default_prefix(window_prefix, "pull_get_opt"))
    return _attach_model(opt, model)


def DistributedPushSumOptimizer(optimizer: torch.optim.Optimizer,
                                model: Optional["torch.nn.Module"] = None,
                                num_steps_per_communication: int = 1,
                                window_prefix: Optional[str] = None
                                ) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` for push-sum / gradient-push (reference
    factory torch/optimizers.py:1180)."""
    _check_model(model)
    opt = _reclass(optimizer, _PushSumMixin, "DistributedPushSumOptimizer",
                   num_steps_per_communication)
    opt._bft_register_windows(_default_prefix(window_prefix, "push_sum_opt"))
    return _attach_model(opt, model)


def DistributedExactDiffusionOptimizer(
        optimizer: torch.optim.Optimizer,
        model: Optional["torch.nn.Module"] = None) -> torch.optim.Optimizer:
    """Exact-Diffusion on torch tensors (beyond-reference; see the JAX
    factory in optim/wrappers.py for the algorithm and its static-mixing
    restriction).  One exchange per step by construction."""
    _check_model(model)
    opt = _reclass(optimizer, _ExactDiffusionMixin,
                   "DistributedExactDiffusionOptimizer", 1)
    return _attach_model(opt, model)


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         communication: str = "neighbor_allreduce",
                         num_steps_per_communication: int = 1,
                         sched=None,
                         model: Optional["torch.nn.Module"] = None
                         ) -> torch.optim.Optimizer:
    """Factory matching the reference TF frontend's single entry point
    (tensorflow/optimizers.py:135): pick the strategy by name.  Passing
    ``model=`` auto-registers the per-layer timeline hooks, like the
    reference optimizers do (torch/optimizers.py:112-163)."""
    if communication == "neighbor_allreduce":
        opt = DistributedNeighborAllreduceOptimizer(
            optimizer,
            num_steps_per_communication=num_steps_per_communication,
            sched=sched)
    elif communication == "gradient_allreduce":
        opt = DistributedGradientAllreduceOptimizer(
            optimizer,
            num_steps_per_communication=num_steps_per_communication)
    elif communication == "allreduce":
        # weight-average CTA, matching DistributedAllreduceOptimizer (the
        # reference's factory of that name averages WEIGHTS,
        # torch/optimizers.py:1301); use "gradient_allreduce" for the
        # Horovod-style gradient averaging
        opt = DistributedAllreduceOptimizer(
            optimizer,
            num_steps_per_communication=num_steps_per_communication)
    elif communication == "hierarchical_neighbor_allreduce":
        opt = DistributedHierarchicalNeighborAllreduceOptimizer(
            optimizer,
            num_steps_per_communication=num_steps_per_communication)
    else:
        raise ValueError(f"unknown communication {communication!r}")
    # hooks attach only after the strategy validates, and stay removable
    # (opt._bft_timeline_handles[i].remove())
    opt._bft_timeline_handles = (
        register_timeline_hooks(model) if model is not None else [])
    return opt
