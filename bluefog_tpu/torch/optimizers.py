"""Distributed wrappers for ``torch.optim`` optimizers.

Parity model: the reference TF frontend's ``DistributedOptimizer``
(``bluefog/tensorflow/optimizers.py:135``) plus the torch frontend's two
main strategies (``bluefog/torch/optimizers.py:1301,1376``):

* ``DistributedGradientAllreduceOptimizer`` — Horovod-style: allreduce
  gradients, then the local step.
* ``DistributedNeighborAllreduceOptimizer`` — CTA: neighbor-average the
  *parameters*, then apply the local step.

Like the reference (``torch/optimizers.py`` re-classes the wrapped
optimizer via ``type(...)``), the factories dynamically subclass the
wrapped optimizer's own class, so the result still IS a
``torch.optim.Optimizer`` of the original type — LR schedulers, grad
scalers, and ``isinstance`` checks keep working.

Global view as everywhere in this frontend: every parameter tensor carries
a leading ``[size]`` replica axis.  The communication runs on the JAX mesh;
the torch optimizer's own math stays untouched.
"""

from typing import Optional

import torch

from . import mpi_ops as _ops

__all__ = [
    "DistributedOptimizer",
    "DistributedGradientAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
]


class _DistributedMixin:
    """step() override shared by both strategies; spliced in by re-classing."""

    def _bft_setup(self, num_steps_per_communication: int):
        self._bft_period = max(1, int(num_steps_per_communication))
        self._bft_tick = 0

    def _bft_params(self):
        for group in self.param_groups:
            yield from group["params"]

    def _bft_communicate(self):
        raise NotImplementedError

    def step(self, closure=None):
        self._bft_tick += 1
        if self._bft_tick % self._bft_period == 0:
            self._bft_communicate()
        return super().step(closure)


class _GradientAllreduceMixin(_DistributedMixin):
    """Allreduce-average gradients before the local step
    (reference ``_DistributedOptimizer``, torch/optimizers.py:166-294)."""

    def step(self, closure=None):
        # a closure recomputes gradients inside super().step(), which would
        # overwrite the allreduced ones — evaluate it once up front instead
        # (multi-evaluation optimizers like LBFGS are not supported)
        loss = None
        if closure is not None:
            with torch.enable_grad():
                loss = closure()
        super().step()
        return loss

    def _bft_communicate(self):
        for p in self._bft_params():
            if p.grad is not None:
                p.grad.copy_(_ops.allreduce(p.grad, average=True))


class _NeighborAllreduceMixin(_DistributedMixin):
    """Combine-then-adapt: neighbor-average parameters, then step
    (reference ``_DistributedReduceOptimizer`` with neighbor_allreduce,
    torch/optimizers.py:297-482).  Per-step dynamic topologies: assign
    ``opt.sched``/``opt.step_index`` (mirrors the reference's mutable
    ``dst_weights`` attributes, optimizers.py:107-109)."""

    sched = None
    step_index = 0

    def _bft_communicate(self):
        kwargs = {}
        if self.sched is not None:
            kwargs = {"sched": self.sched, "step": self.step_index}
        for p in self._bft_params():
            with torch.no_grad():
                p.copy_(_ops.neighbor_allreduce(p.data, **kwargs))
        self.step_index += 1


def _reclass(optimizer: torch.optim.Optimizer, mixin, name: str,
             num_steps_per_communication: int):
    cls = type(name, (mixin, optimizer.__class__), {})
    optimizer.__class__ = cls
    optimizer._bft_setup(num_steps_per_communication)
    return optimizer


def DistributedGradientAllreduceOptimizer(
        optimizer: torch.optim.Optimizer,
        num_steps_per_communication: int = 1) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` so each step allreduce-averages gradients
    first (reference factory torch/optimizers.py:1376)."""
    return _reclass(optimizer, _GradientAllreduceMixin,
                    "DistributedGradientAllreduceOptimizer",
                    num_steps_per_communication)


def DistributedNeighborAllreduceOptimizer(
        optimizer: torch.optim.Optimizer,
        num_steps_per_communication: int = 1,
        sched=None) -> torch.optim.Optimizer:
    """Re-class ``optimizer`` so each step neighbor-averages parameters
    first (reference factory torch/optimizers.py:1326)."""
    opt = _reclass(optimizer, _NeighborAllreduceMixin,
                   "DistributedNeighborAllreduceOptimizer",
                   num_steps_per_communication)
    opt.sched = sched
    opt.step_index = 0
    return opt


def DistributedOptimizer(optimizer: torch.optim.Optimizer,
                         communication: str = "neighbor_allreduce",
                         num_steps_per_communication: int = 1,
                         sched=None) -> torch.optim.Optimizer:
    """Factory matching the reference TF frontend's single entry point
    (tensorflow/optimizers.py:135): pick the strategy by name."""
    if communication == "neighbor_allreduce":
        return DistributedNeighborAllreduceOptimizer(
            optimizer, num_steps_per_communication, sched)
    if communication in ("allreduce", "gradient_allreduce"):
        return DistributedGradientAllreduceOptimizer(
            optimizer, num_steps_per_communication)
    raise ValueError(f"unknown communication {communication!r}")
