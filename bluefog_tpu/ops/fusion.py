"""Comm-fusion layer: flat-buffer execution of pytree collectives.

The reference core fuses many small tensors into one communication buffer
before hitting MPI/NCCL (Horovod-style tensor fusion; ``mpi_controller.cc:
561-743`` packs every negotiated tensor into a single ``[self | n1, n2...]``
buffer per transmission) because per-tensor collectives are latency-bound.
The SPMD port's strategy layer used to do the opposite — ``jax.tree.map(
neighbor_allreduce)`` over the parameter pytree issues ``leaves x offsets``
``lax.ppermute``s per step, bloating the HLO, trace/compile time, and per-op
launch latency; the exponential-graph economics (one cheap transfer per
O(log N) offset) only hold when the model IS one transfer per offset.

This module is the TPU-native fusion buffer:

1. :func:`plan_for` groups the tree's leaves into **dtype-bucketed** flat
   buffers (a weighted average must not silently cast, so dtypes never
   share a buffer), chunked at leaf granularity by ``max_bucket_bytes``
   (several buckets per dtype lets XLA overlap one bucket's transfer with
   another's accumulate) and padded to a configurable element multiple
   (the Mosaic kernel wants ``8 x 128`` tiles).
2. :func:`flatten` / :func:`unflatten` move a concrete tree into / out of
   the plan's buffers with reshape+concatenate only — no copies beyond the
   one gather XLA fuses into the collective.
3. :func:`fused_tree_map` runs an elementwise-linear collective once per
   BUCKET instead of once per leaf and restores the original tree.

Exactness: every exchange this layer fuses (neighbor/dynamic/hierarchical
averaging, allreduce) is elementwise-linear with per-rank scalar weights,
and buckets never mix dtypes — so the fused arithmetic is the SAME scalar
ops on the same values, bit-exact versus the per-leaf path (asserted across
all strategies in ``tests/test_fusion.py``).  Padding tail elements are
zeros; linear ops map zeros to zeros and the tail is sliced away.

Trees are planned at trace time from static shape/dtype structure only
(plans are lru-cached on the abstract signature), so fusion adds zero
retracing and the step's compiled program count is unchanged.

Env knobs (read when a step is BUILT, like the exchange backend snapshot):
``BLUEFOG_COMM_FUSION`` (default ``1``) gates the layer; the
``BLUEFOG_FUSION_BUCKET_BYTES`` cap (default 64 MiB, the reference
controller's fusion-buffer scale) splits oversized dtype groups.
"""

import functools
import os
from dataclasses import dataclass
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..observability import metrics as _metrics

__all__ = [
    "DEFAULT_MAX_BUCKET_BYTES",
    "FusionPlan",
    "fusion_enabled",
    "resolve_max_bucket_bytes",
    "plan_bytes",
    "gossip_wire_bytes",
    "bucket_probe_sizes",
    "interleave_order",
    "plan_for",
    "shard_shape",
    "shard_groups",
    "shard_plan_for",
    "norm_spec",
    "sharded_zero_buffers",
    "flatten",
    "unflatten",
    "flat_views",
    "restore",
    "zero_buffers",
    "fused_tree_map",
]

# Reference scale: the MPI controller's fusion buffer is tens of MB
# (BLUEFOG_FUSION_THRESHOLD, operations.cc); 64 MiB keeps a ResNet-50
# (~100 MB f32) in two buckets — large enough to amortize launch latency,
# small enough that bucket 0's exchange can overlap bucket 1's pack.
DEFAULT_MAX_BUCKET_BYTES = 64 << 20


def fusion_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the fusion gate: explicit argument wins, else the
    ``BLUEFOG_COMM_FUSION`` env var (default on).  Builders resolve this
    when the step is constructed — same snapshot discipline as the
    exchange backend (``training.py``): jit traces once, so reading the
    env inside the traced function would freeze the first call's value."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("BLUEFOG_COMM_FUSION", "1") == "1"


def resolve_max_bucket_bytes(value: Optional[int] = None) -> int:
    if value is not None:
        v = int(value)
    else:
        v = int(os.environ.get("BLUEFOG_FUSION_BUCKET_BYTES",
                               str(DEFAULT_MAX_BUCKET_BYTES)))
    if v <= 0:
        raise ValueError(f"fusion bucket size must be positive, got {v}")
    return v


@dataclass(frozen=True)
class _Slot:
    """Where one original leaf lives: ``bucket < 0`` marks a zero-size
    passthrough leaf (it carries no data, so it rides no buffer and is
    re-fabricated empty at unflatten)."""
    index: int                  # leaf position in tree-flatten order
    bucket: int
    start: int                  # element offset within the bucket
    size: int                   # elements (excluding leading dims)
    shape: Tuple[int, ...]      # full original shape
    dtype: Any


@dataclass(frozen=True)
class _Bucket:
    dtype: Any
    nelems: int                 # payload elements (excluding leading dims)
    padded: int                 # nelems rounded up to the pad multiple


@dataclass(frozen=True)
class FusionPlan:
    """Static flatten/unflatten recipe for one tree signature.

    ``leading_dims`` leading axes of every leaf are preserved un-flattened
    (0 for per-rank trees inside ``shard_map``; 1 for the window
    subsystem's global-view ``[N, ...]`` state)."""
    treedef: Any
    slots: Tuple[_Slot, ...]
    buckets: Tuple[_Bucket, ...]
    leading_dims: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def _abstract_signature(tree, leading_dims: int):
    leaves, treedef = jax.tree.flatten(tree)
    sig = []
    for leaf in leaves:
        shape = tuple(int(d) for d in leaf.shape)
        if len(shape) < leading_dims:
            raise ValueError(
                f"fusion with leading_dims={leading_dims} needs every leaf "
                f"to carry those axes; got shape {shape}")
        sig.append((shape, jnp.asarray(leaf).dtype
                    if not hasattr(leaf, "dtype") else leaf.dtype))
    return treedef, tuple(sig)


@functools.lru_cache(maxsize=512)
def _build_plan(treedef, sig, max_bytes: int, pad_to: int,
                leading_dims: int,
                leaf_groups: Optional[Tuple[Any, ...]] = None) -> FusionPlan:
    # stable dtype grouping in first-appearance order (determinism matters:
    # the window subsystem persists fused state across checkpoints).
    # ``leaf_groups`` adds a caller-chosen partition on top of the dtype
    # one — the hybrid mesh path separates inner-axis-SHARDED from
    # REPLICATED leaves so a replicated leaf's bucket statistics (codec
    # scales) never see cell-varying shard data (see shard_groups).
    order: List[Any] = []
    groups = {}
    for i, (shape, dtype) in enumerate(sig):
        size = int(np.prod(shape[leading_dims:], dtype=np.int64)) \
            if len(shape) > leading_dims else 1
        # a leaf that is all leading dims (e.g. scalar per rank) still
        # carries one element per leading slice
        if len(shape) == leading_dims:
            size = 1
        if size == 0 or int(np.prod(shape, dtype=np.int64)) == 0:
            groups.setdefault(None, []).append((i, shape, dtype, 0))
            continue
        key = (leaf_groups[i] if leaf_groups is not None else None,
               jnp.dtype(dtype))
        if key not in groups:
            order.append(key)
        groups.setdefault(key, []).append((i, shape, dtype, size))

    slots: List[Optional[_Slot]] = [None] * len(sig)
    buckets: List[_Bucket] = []
    itemsize = {k: jnp.dtype(k[1]).itemsize for k in order}
    for key in order:
        current: List[Tuple[int, Tuple[int, ...], Any, int]] = []
        cur_elems = 0

        def flush(members, elems, key=key):
            if not members:
                return
            b = len(buckets)
            start = 0
            for i, shape, dtype, size in members:
                slots[i] = _Slot(index=i, bucket=b, start=start, size=size,
                                 shape=shape, dtype=jnp.dtype(dtype))
                start += size
            padded = elems + ((-elems) % pad_to)
            buckets.append(_Bucket(dtype=key[1], nelems=elems,
                                   padded=padded))

        cap_elems = max(1, max_bytes // itemsize[key])
        for member in groups[key]:
            size = member[3]
            if current and cur_elems + size > cap_elems:
                flush(current, cur_elems)
                current, cur_elems = [], 0
            current.append(member)
            cur_elems += size
            if cur_elems >= cap_elems:
                flush(current, cur_elems)
                current, cur_elems = [], 0
        flush(current, cur_elems)

    for i, shape, dtype, _ in groups.get(None, []):
        slots[i] = _Slot(index=i, bucket=-1, start=0, size=0,
                         shape=shape, dtype=jnp.dtype(dtype))
    return FusionPlan(treedef=treedef, slots=tuple(slots),
                      buckets=tuple(buckets), leading_dims=leading_dims)


def plan_bytes(plan: FusionPlan) -> Tuple[int, int]:
    """(payload bytes, padding-waste bytes) of a plan's buckets, per
    leading slice — the fusion efficiency numbers the metrics registry
    tracks.

    On a plan built over LOCAL SHARD shapes (:func:`shard_plan_for`, the
    hybrid ``(dp, fsdp)`` path) these are already PER-RANK wire numbers:
    each mesh cell ships exactly its plan's buckets per collective offset,
    so the replicated-path figure divides by the sharding factor with no
    further accounting."""
    payload = sum(b.nelems * jnp.dtype(b.dtype).itemsize
                  for b in plan.buckets)
    waste = sum((b.padded - b.nelems) * jnp.dtype(b.dtype).itemsize
                for b in plan.buckets)
    return int(payload), int(waste)


def gossip_wire_bytes(plan: FusionPlan, n_transfers: int = 1) -> int:
    """Per-rank bytes one gossip round puts on the wire for this plan:
    the PADDED bucket bytes (padding tails ride the permutes too), times
    ``n_transfers`` (one per circulant offset of the topology).  With a
    shard plan this is the 1/fsdp-size per-rank number the hybrid path
    moves — the quantity ``make bench-hybrid`` gates on."""
    total = sum(b.padded * jnp.dtype(b.dtype).itemsize
                for b in plan.buckets)
    return int(total) * int(n_transfers)


def bucket_probe_sizes(plan: FusionPlan,
                       cap_bytes: Optional[int] = None) -> Tuple[int, ...]:
    """Probe payload sizes representative of this plan's buckets — what
    the edge probe harness (``observability/commprof.py``) actually puts
    on each link: the PADDED per-bucket wire bytes (padding tails ride
    the permutes, same accounting as :func:`gossip_wire_bytes`), deduped
    and sorted, each clipped to ``cap_bytes`` (a probe must not ship a
    64 MiB bucket just to rank links).  A small latency-regime payload
    (4 KiB) is always included so the matrix separates per-message cost
    from bandwidth.  Empty plans fall back to the latency payload only."""
    cap = int(cap_bytes) if cap_bytes is not None else (4 << 20)
    sizes = {min(int(b.padded * jnp.dtype(b.dtype).itemsize), cap)
             for b in plan.buckets}
    sizes.add(min(4096, cap))
    return tuple(sorted(s for s in sizes if s > 0))


def interleave_order(plan: FusionPlan) -> Tuple[int, ...]:
    """Bucket ISSUE order for the single-kernel gossip path: ascending
    padded wire bytes, ties broken by plan position (stable).

    Rationale (docs/performance.md "Single-kernel gossip"): each bucket's
    exchange is one kernel whose RDMA time scales with its bytes, and XLA
    schedules program order when dataflow allows — issuing the SMALL
    buckets' kernels first puts their short exchanges in flight while the
    large buckets are still encoding/launching, so the small transfers
    hide entirely under the big buckets' compute instead of queueing
    behind it.  Results are always restored in plan position, so the
    order is invisible to callers; the default (non-kernel) paths keep
    strict plan order — their lowering is byte-frozen by the off-path
    identity contract."""
    sizes = [(b.padded * jnp.dtype(b.dtype).itemsize, i)
             for i, b in enumerate(plan.buckets)]
    return tuple(i for _, i in sorted(sizes))


def shard_shape(shape: Tuple[int, ...], spec,
                axis_sizes) -> Tuple[int, ...]:
    """Local shard shape of one leaf under a ``PartitionSpec`` for the
    mesh axes in ``axis_sizes`` (a ``{axis_name: size}`` mapping); axes
    the spec does not name divide nothing.  Raises on non-divisible dims
    — silent uneven sharding would corrupt the flatten offsets."""
    out = list(shape)
    for d, names in enumerate(spec):
        if names is None:
            continue
        for name in (names if isinstance(names, tuple) else (names,)):
            n = int(axis_sizes.get(name, 1))
            if n <= 1:
                continue
            if out[d] % n:
                raise ValueError(
                    f"dim {d} of shape {tuple(shape)} is not divisible by "
                    f"mesh axis {name!r} (size {n}); fusion shard plans "
                    f"need even sharding")
            out[d] //= n
    return tuple(out)


def shard_groups(specs, axis_names) -> Tuple[str, ...]:
    """Per-leaf fusion group keys for a mesh-axis-aware plan: leaves the
    given inner axes SHARD vs leaves they REPLICATE must never share a
    bucket.  A replicated leaf's exchange must come out bitwise identical
    on every inner-axis cell (its shard_map out_spec declares it
    replicated), which under a lossy codec only holds when its bucket
    statistics — e.g. the int8 per-bucket scale — see no cell-varying
    shard data."""
    from jax.sharding import PartitionSpec as P
    out = []
    wanted = set(axis_names)
    for s in jax.tree_util.tree_flatten(
            specs, is_leaf=lambda x: isinstance(x, P))[0]:
        names = set()
        for entry in s:
            if entry is None:
                continue
            names.update(entry if isinstance(entry, tuple) else (entry,))
        out.append("shard" if names & wanted else "rep")
    return tuple(out)


def shard_plan_for(tree, specs, axis_sizes, *,
                   max_bucket_bytes: Optional[int] = None,
                   pad_to: int = 1) -> FusionPlan:
    """:func:`plan_for` over the LOCAL SHARD shapes of ``tree`` — the
    mesh-axis-aware planning entry for the hybrid sharded-decentralized
    path: buckets are laid out per shard and lane padding applies to the
    shard, so the plan describes exactly the flat buffers a ``(dp, fsdp)``
    cell builds inside ``shard_map`` (each rank's gossip payload is its
    1/fsdp slice, never the replica).

    ``specs`` is the within-replica ``PartitionSpec`` tree (e.g.
    ``fsdp_specs``/``transformer_tp_rules`` output) and ``axis_sizes``
    maps the model-sharding axis names to their mesh sizes.  The result
    is the SAME cached :class:`FusionPlan` the shard_map body gets from
    ``plan_for`` on its local tree — host-side state builders (in-flight
    overlap buffers, compression residuals) use this to allocate matching
    global-view buffers."""
    from jax.sharding import PartitionSpec as P
    leaves, treedef = jax.tree.flatten(tree)
    spec_leaves = jax.tree.flatten(
        specs, is_leaf=lambda x: isinstance(x, P))[0]
    if len(leaves) != len(spec_leaves):
        raise ValueError(
            f"tree has {len(leaves)} leaves, specs describe "
            f"{len(spec_leaves)}")
    shards = [
        jax.ShapeDtypeStruct(
            shard_shape(tuple(int(d) for d in leaf.shape), spec,
                        axis_sizes),
            leaf.dtype)
        for leaf, spec in zip(leaves, spec_leaves)]
    return plan_for(jax.tree.unflatten(treedef, shards),
                    max_bucket_bytes=max_bucket_bytes, pad_to=pad_to,
                    leaf_groups=shard_groups(specs, axis_sizes.keys()))


def norm_spec(spec):
    """Strip trailing ``None`` entries from a ``PartitionSpec``:
    ``P('dp', 'fsdp', None)`` and ``P('dp', 'fsdp')`` describe the SAME
    sharding but compare UNEQUAL as ``NamedSharding``s (observed on
    jaxlib 0.4.x), and ``shard_map`` normalizes its outputs — so state
    placed with the long spelling recompiles the step on its second call.
    Every hybrid-path placement normalizes through here to match the
    steady-state output shardings."""
    from jax.sharding import PartitionSpec as P
    entries = list(spec)
    while entries and entries[-1] is None:
        entries.pop()
    return P(*entries)


def sharded_zero_buffers(params, inner_specs, mesh, *,
                         gossip_axis: str = "dp", fuse: bool = True,
                         max_bucket_bytes: Optional[int] = None):
    """Zero global-view carried buffers for the hybrid ``(dp, fsdp)``
    path — the single home for the layout every hybrid state builder
    allocates (the overlap in-flight buffers in
    ``parallel/tensor.py::hybrid_inflight_state`` and the compression
    residuals/estimates in ``compress/exchange.py::sharded_state_layout``
    must stay structurally identical, or the carried opt state diverges
    from what the shard_map body folds).

    ``params`` is the SINGLE-replica tree, ``inner_specs`` its
    within-replica spec tree.  Fused: one ``[dp, *inner_sizes, padded]``
    buffer per shard-plan bucket, placed ``P(gossip_axis, *inner)``;
    unfused: per-leaf ``[dp, ...]`` zeros with their own (normalized)
    within-replica placements.  Returns a LIST in bucket / tree-flatten
    order — callers tuple or unflatten it into their state shape."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    inner = tuple(a for a in mesh.axis_names if a != gossip_axis)
    lead = (mesh.shape[gossip_axis],) + tuple(mesh.shape[a] for a in inner)
    if fuse:
        plan = shard_plan_for(params, inner_specs,
                              {a: mesh.shape[a] for a in inner},
                              max_bucket_bytes=max_bucket_bytes)
        return [jax.device_put(
                    jnp.zeros(lead + (b.padded,), b.dtype),
                    NamedSharding(mesh, P(gossip_axis, *inner)))
                for b in plan.buckets]
    spec_leaves = jax.tree_util.tree_flatten(
        inner_specs, is_leaf=lambda x: isinstance(x, P))[0]
    return [jax.device_put(
                jnp.zeros((lead[0],) + tuple(l.shape), l.dtype),
                NamedSharding(mesh, norm_spec(P(gossip_axis, *s))))
            for l, s in zip(jax.tree.leaves(params), spec_leaves)]


def plan_for(tree, *, max_bucket_bytes: Optional[int] = None,
             pad_to: int = 1, leading_dims: int = 0,
             leaf_groups=None) -> FusionPlan:
    """Build (or fetch the cached) :class:`FusionPlan` for ``tree``'s
    abstract signature.  Safe to call inside a traced function — the plan
    depends only on static shapes/dtypes/structure.

    ``leaf_groups`` (one hashable per leaf, in tree-flatten order):
    leaves with different group keys never share a bucket, on top of the
    dtype partition.  The hybrid mesh path passes :func:`shard_groups` so
    replicated and sharded leaves bucket separately."""
    treedef, sig = _abstract_signature(tree, leading_dims)
    if leaf_groups is not None:
        leaf_groups = tuple(leaf_groups)
        if len(leaf_groups) != len(sig):
            raise ValueError(
                f"{len(leaf_groups)} leaf groups for a {len(sig)}-leaf "
                f"tree")
    plan = _build_plan(treedef, sig,
                       resolve_max_bucket_bytes(max_bucket_bytes),
                       int(pad_to), int(leading_dims), leaf_groups)
    if _metrics.enabled():
        # trace-time only (compiled steps never re-enter Python here):
        # gauges describe the LAST plan consulted, the counter every
        # consult; cache stats separate fresh builds from lru hits
        payload, waste = plan_bytes(plan)
        _metrics.counter("bf_fusion_plan_consults_total",
                         "fusion plan lookups (trace-time)").inc()
        g = _metrics.gauge("bf_fusion_plan",
                           "shape of the last fusion plan consulted")
        g.set(plan.n_buckets, field="buckets")
        g.set(len(plan.slots), field="leaves")
        g.set(payload, field="payload_bytes")
        g.set(waste, field="padding_waste_bytes")
        info = _build_plan.cache_info()
        c = _metrics.gauge("bf_fusion_plan_cache",
                           "lru stats of the fusion-plan cache")
        c.set(info.hits, field="hits")
        c.set(info.misses, field="builds")
    return plan


def flatten(plan: FusionPlan, tree) -> List[jax.Array]:
    """Tree -> list of flat buffers, one per bucket (shape
    ``leading + [padded]``)."""
    leaves = jax.tree.leaves(tree)
    if len(leaves) != len(plan.slots):
        raise ValueError(
            f"tree has {len(leaves)} leaves, plan describes "
            f"{len(plan.slots)}")
    lead = plan.leading_dims
    parts: List[List[jax.Array]] = [[] for _ in plan.buckets]
    for slot in plan.slots:
        if slot.bucket < 0:
            continue
        leaf = leaves[slot.index]
        parts[slot.bucket].append(
            leaf.reshape(tuple(leaf.shape[:lead]) + (-1,)))
    bufs = []
    for spec, ps in zip(plan.buckets, parts):
        buf = ps[0] if len(ps) == 1 else jnp.concatenate(ps, axis=lead)
        if spec.padded > spec.nelems:
            pad = [(0, 0)] * lead + [(0, spec.padded - spec.nelems)]
            buf = jnp.pad(buf, pad)
        bufs.append(buf)
    return bufs


def unflatten(plan: FusionPlan, bufs: Sequence[jax.Array]):
    """Inverse of :func:`flatten`.  Zero-size passthrough leaves are
    re-fabricated empty (a 0-element array has no content to preserve)."""
    if len(bufs) != len(plan.buckets):
        raise ValueError(
            f"{len(bufs)} buffers for a {len(plan.buckets)}-bucket plan")
    lead = plan.leading_dims
    leaves: List[Optional[jax.Array]] = [None] * len(plan.slots)
    for slot in plan.slots:
        if slot.bucket < 0:
            leaves[slot.index] = jnp.zeros(slot.shape, slot.dtype)
            continue
        buf = bufs[slot.bucket]
        seg = jax.lax.slice_in_dim(buf, slot.start, slot.start + slot.size,
                                   axis=lead)
        leaves[slot.index] = seg.reshape(slot.shape)
    return jax.tree.unflatten(plan.treedef, leaves)


def flat_views(tree, *, fuse: bool = True,
               max_bucket_bytes: Optional[int] = None, pad_to: int = 1,
               leaf_groups=None):
    """``(plan, bufs)``: the fused dtype buckets when ``fuse`` (plan is
    the trace-time-cached one), else ``(None, leaves)`` — the single home
    for "give me the tree as the flat buffers the exchange moves", shared
    by the in-graph telemetry (``observability/ingraph.py``) and the
    compressed exchange (``compress/exchange.py``).  Invert with
    :func:`restore`.  ``leaf_groups`` as in :func:`plan_for`."""
    if fuse:
        plan = plan_for(tree, max_bucket_bytes=max_bucket_bytes,
                        pad_to=pad_to, leaf_groups=leaf_groups)
        return plan, flatten(plan, tree)
    return None, list(jax.tree.leaves(tree))


def restore(plan: Optional[FusionPlan], tree, bufs):
    """Inverse of :func:`flat_views`: buffers (possibly transformed
    elementwise) back to ``tree``'s structure."""
    if plan is not None:
        return unflatten(plan, list(bufs))
    return jax.tree.unflatten(jax.tree.structure(tree), list(bufs))


def zero_buffers(plan: FusionPlan,
                 leading_shape: Tuple[int, ...] = ()) -> Tuple[jax.Array, ...]:
    """Zeroed flat buffers matching ``plan``'s buckets (shape
    ``leading_shape + [padded]`` each).

    This is the buffer-HANDLE side of cross-step reuse: a pipelined stepper
    (``optim/strategies`` overlapped mode) carries its in-flight exchange
    state as exactly these buffers inside the donated opt/train state, so
    XLA aliases the same allocations step after step — double buffering
    without any host-side pool.  The zero state is also the pipeline's
    warmup value: folding it contributes nothing (linear ops map zeros to
    zeros), which encodes "no exchange has arrived yet" with no flag."""
    return tuple(jnp.zeros(tuple(leading_shape) + (b.padded,), b.dtype)
                 for b in plan.buckets)


def fused_tree_map(fn: Callable, tree, *,
                   max_bucket_bytes: Optional[int] = None,
                   pad_to: int = 1, leaf_groups=None,
                   interleave: bool = False):
    """Apply an elementwise-linear, shape/dtype-preserving collective once
    per fusion bucket instead of once per leaf.

    The workhorse of the fused communication path: ``strategies.
    _communicate`` routes every averaging mode through here, dropping the
    per-step collective count from ``leaves x offsets`` to
    ``buckets x offsets``.  ``fn`` must preserve shape and dtype (every
    collective this layer fuses does); violations raise at trace time
    rather than silently corrupting the unflatten.

    ``interleave`` (the ``BLUEFOG_GOSSIP_KERNEL`` issue-order hint,
    default off — the off path's trace is byte-frozen): apply ``fn`` to
    the buckets in :func:`interleave_order` (small first) so short
    exchanges launch ahead of the large buckets' work; results land in
    plan position either way."""
    plan = plan_for(tree, max_bucket_bytes=max_bucket_bytes, pad_to=pad_to,
                    leading_dims=0, leaf_groups=leaf_groups)
    bufs = flatten(plan, tree)
    order = interleave_order(plan) if interleave else range(len(bufs))
    out: List[Optional[jax.Array]] = [None] * len(bufs)
    for b in order:
        buf = bufs[b]
        o = fn(buf)
        if tuple(o.shape) != tuple(buf.shape) or o.dtype != buf.dtype:
            raise ValueError(
                f"fused collective changed the buffer signature "
                f"({buf.shape}/{buf.dtype} -> {o.shape}/{o.dtype}); "
                f"fusion requires shape- and dtype-preserving ops")
        out[b] = o
    return unflatten(plan, out)
