"""Expert parallelism: switch-style MoE dispatch over the mesh.

No reference counterpart (SURVEY.md §2.6 records EP as absent in BlueFog);
built here because expert parallelism is a first-class scaling axis for a
TPU framework.  Design is the GShard/Switch static-shape recipe, which XLA
compiles well: top-1 routing with a fixed per-expert capacity, dispatch and
combine expressed as dense einsums against a one-hot dispatch tensor (no
gather/scatter with data-dependent shapes), and two ``lax.all_to_all``s
moving token slots between ranks so each rank runs only its local experts.

Shapes (per rank, inside shard_map): tokens ``[T, D]``, experts
``E = n_ranks * E_local``, capacity ``C`` slots per (expert, source rank).

    dispatch:  [T, E, C] one-hot   (token t -> slot c of expert e)
    a2a in:    [E, C, D] -> [E_local, n*C, D]
    expert FF: vmap over E_local
    a2a out:   back, combine with gate probabilities

Tokens beyond an expert's capacity are dropped (standard switch behavior);
the residual connection around the MoE block carries them through.
"""

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["switch_route", "expert_parallel_ffn", "local_moe_ffn",
           "RouterOutput"]


class RouterOutput(NamedTuple):
    dispatch: jax.Array       # [T, E, C] one-hot float
    combine: jax.Array        # [T, E, C] dispatch * gate prob
    aux_loss: jax.Array       # load-balancing loss (Switch eq. 4)


def switch_route(logits, capacity: int) -> RouterOutput:
    """Top-1 routing with static capacity (Switch Transformer).

    ``logits``: [T, E].  Token t goes to expert ``argmax`` if it wins one of
    the expert's ``capacity`` slots (first-come by position); otherwise it is
    dropped (combine weight 0).  Everything is dense one-hots — no dynamic
    shapes under jit.
    """
    T, E = logits.shape
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                       # [T]
    onehot = jax.nn.one_hot(expert, E, dtype=jnp.float32)     # [T, E]
    # position of each token within its expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot - 1.0           # [T, E]
    kept = (pos >= 0) & (pos < capacity)
    dispatch = kept[..., None] * jax.nn.one_hot(
        jnp.clip(pos, 0, capacity - 1).astype(jnp.int32), capacity,
        dtype=jnp.float32)
    gate = (probs * onehot).sum(-1)                           # [T]
    combine = dispatch * gate[:, None, None]
    # load balancing: E * sum_e (fraction routed to e) * (mean prob of e)
    frac = onehot.mean(axis=0)
    mean_prob = probs.mean(axis=0)
    aux = E * jnp.sum(frac * mean_prob)
    return RouterOutput(dispatch, combine, aux)


def expert_parallel_ffn(x, router_logits, expert_fn: Callable,
                        expert_params, axis_name,
                        capacity_factor: float = 1.25):
    """Run an expert-sharded FFN over ring-sharded tokens (inside shard_map).

    ``x``: [T, D] local tokens; ``router_logits``: [T, E] with
    ``E = n * E_local``; ``expert_params``: pytree whose leaves have leading
    dim ``E_local`` (this rank's experts); ``expert_fn(params, h)`` applies
    one expert to ``[slots, D]``.

    Two all-to-alls bracket the expert computation, so every rank computes
    only its ``E_local`` experts over slots collected from all ranks.
    Returns ``(out [T, D], aux_loss)``.
    """
    n = lax.axis_size(axis_name)
    T, D = x.shape
    E = router_logits.shape[-1]
    if E % n:
        raise ValueError(f"num experts {E} must be divisible by mesh size {n}")
    e_local = E // n
    capacity = max(1, int(capacity_factor * T / E))

    route = switch_route(router_logits, capacity)
    # [T, E, C] x [T, D] -> [E, C, D]
    slots = jnp.einsum("tec,td->ecd", route.dispatch.astype(x.dtype), x)
    # exchange: each rank keeps E_local experts, gains all ranks' slots
    slots = lax.all_to_all(slots, axis_name, split_axis=0, concat_axis=1,
                           tiled=True)                       # [E_local, n*C, D]
    out = jax.vmap(expert_fn)(expert_params, slots)          # [E_local, n*C, D]
    out = lax.all_to_all(out, axis_name, split_axis=1, concat_axis=0,
                         tiled=True)                         # [E, C, D]
    combined = jnp.einsum("tec,ecd->td", route.combine.astype(x.dtype), out)
    return combined, route.aux_loss


def local_moe_ffn(x, router_logits, expert_fn: Callable, expert_params,
                  capacity_factor: float = 1.25):
    """Single-device MoE: same routing/combine math, all experts local
    (the n=1 degenerate case of ``expert_parallel_ffn`` — used outside
    shard_map and as the correctness reference in tests)."""
    T, _ = x.shape
    E = router_logits.shape[-1]
    capacity = max(1, int(capacity_factor * T / E))
    route = switch_route(router_logits, capacity)
    slots = jnp.einsum("tec,td->ecd", route.dispatch.astype(x.dtype), x)
    out = jax.vmap(expert_fn)(expert_params, slots)          # [E, C, D]
    combined = jnp.einsum("tec,ecd->td", route.combine.astype(x.dtype), out)
    return combined, route.aux_loss
