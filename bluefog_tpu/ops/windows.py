"""One-sided "window" operations (reference parity: bluefog/torch/mpi_win_ops.cc,
bluefog/torch/mpi_ops.py:998-1475, mpi_controller.cc:793-1390).

The reference exposes MPI RMA windows: each rank owns, per window name, one
receive buffer per in-neighbor plus its registered tensor; ``win_put/get/
accumulate`` move data one-sidedly and ``win_update`` folds the buffers into
the tensor under optional distributed mutexes, with per-neighbor version
counters and an "associated P" scalar for push-sum bias correction.

TPU-native design — *buffered one-sided semantics* (SURVEY.md §7 hard part
1a): XLA collectives are bulk-synchronous, so every window op here is one
SPMD program in which data rides ``ppermute`` into device-resident neighbor
buffers.  Asynchrony appears as *bounded staleness*: a rank that does not
put this step simply carries a zero row in the destination-weight matrix and
its peers keep averaging the last value delivered into their buffers — which
is exactly the algorithmic behavior the MPI implementation produces, minus
unbounded delay.  Mutexes become no-ops (program order already serializes
buffer access); versions and associated-P are real state.

Per-rank ``dst_weights``/``src_weights`` dicts generalize in the global view
to [N, N] matrices: entry (i, j) is the weight rank i applies when sending
to / rank j applies when pulling from i.  Matrices are traced data — per-step
dynamic windows never recompile.
"""

import functools
import os
from contextlib import contextmanager
from typing import Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import service as _service
from ..compress import compressors as _compress
from ..context import ctx
from ..observability import metrics as _metrics
from ..parallel.schedule import CompiledTopology
from . import api as _api
from . import fusion as _fusion
from .api import _register_handle, synchronize

# bflint knob-outside-cache-key: ``double_buffer`` resolves once at
# window creation and lives on the window object, which owns its compiled
# fold programs — window identity keys them, there is no shared step
# cache to serve a stale program from.
_STEP_KEY_EXEMPT_KNOBS = frozenset({"double_buffer"})

__all__ = [
    "win_create", "win_free", "win_update", "win_update_then_collect",
    "win_put", "win_put_nonblocking", "win_get", "win_get_nonblocking",
    "win_accumulate", "win_accumulate_nonblocking",
    "win_poll", "win_wait", "win_flush", "win_mutex", "win_lock",
    "win_bootstrap_rank",
    "get_current_created_window_names", "get_win_version",
    "win_version_vector",
    "win_associated_p", "win_associated_p_vector",
    "turn_on_win_ops_with_associated_p",
    "turn_off_win_ops_with_associated_p", "win_fetch", "win_publish",
    "win_state_dict", "load_win_state_dict",
]


def _win_double_buffer_enabled(flag: Optional[bool] = None) -> bool:
    """Double-buffered deferred-commit semantics for the nonblocking
    window ops (``BLUEFOG_WIN_DOUBLE_BUFFER``, default on): a
    ``win_*_nonblocking`` call computes into the window's BACK buffer and
    only ``win_wait`` promotes it to the front — so a concurrent
    ``win_update``/``win_fetch`` drains the front while the back fills,
    making the nonblocking API genuinely asynchronous instead of
    wait-immediately.  Off: the pre-double-buffer behavior (every op
    commits as soon as its program is dispatched)."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("BLUEFOG_WIN_DOUBLE_BUFFER", "1") == "1"


class _Window:
    """Device-resident window state for one name.

    ``tensor`` may be a whole PYTREE: every window op then moves the full
    tree in ONE jitted SPMD program — the TPU-native equivalent of the
    reference's fusion buffers (mpi_controller.cc:561-743 packs all
    tensors into one `[self | n1, n2...]` buffer per transmission; here
    XLA schedules the per-leaf ppermutes of a single program together).
    Versions and the associated-P scalar stay per-WINDOW (one counter set,
    one P per rank — every op touches all leaves together), exactly like
    the reference's per-window metadata.

    Flat-buffer storage (comm fusion, ``ops/fusion.py``): a multi-leaf
    window additionally FUSES its internal state into one ``[N, L]``
    buffer per dtype, so every put/get/accumulate/update issues one
    ppermute per OFFSET per dtype bucket instead of one per leaf — this
    completes the reference parity above (one program AND one buffer).
    The caller-facing surface (``win_put`` inputs, ``win_fetch``/
    ``win_update`` outputs, the ``_win_input`` structure check) stays in
    the creation tree's shape; only the device-resident state is flat.
    Gate: ``win_create(fuse=)`` / ``BLUEFOG_COMM_FUSION`` (default on).
    """

    def __init__(self, tensor, topo: CompiledTopology, zero_init: bool,
                 fuse: Optional[bool] = None,
                 double_buffer: Optional[bool] = None,
                 compression=None):
        cx = ctx()
        self.topo = topo
        # wire compression for the one-sided TRANSFER ops (put / get /
        # accumulate): the outgoing weighted value is encoded per
        # leaf/bucket (compress/compressors.py) and decoded into the
        # destination buffer — the buffers themselves stay full precision,
        # and win_update's local fold is untouched.  QUANTIZERS ONLY
        # (identity/int8/fp8): a window op has no carried residual slot,
        # so (a) choco's two-sided recursion cannot run here and (b)
        # sparsifiers would decode untransmitted coordinates as zeros
        # with nothing re-injecting them — every win_update would then
        # fold hard zeros into ~(1-F) of each buffer, silently decaying
        # those parameters.  Quantizers are dense and near-exact, so
        # deterministic round-to-nearest without error feedback is sound
        # for bounded-staleness buffers (docs/compression.md).
        self.compression = _compress.resolve_compression(compression)
        if self.compression is not None and (
                self.compression.choco
                or self.compression.fraction is not None):
            raise ValueError(
                f"window ops support dense quantizing compression only "
                f"('int8', 'fp8', 'identity'); got "
                f"{self.compression.spec!r}: choco's recursion and the "
                f"sparsifiers' untransmitted-as-zero decoding both need "
                f"carried state a one-sided window op does not have — "
                f"use the optimizer/strategy layer for those")
        # double buffering (BLUEFOG_WIN_DOUBLE_BUFFER, default on):
        # deferred nonblocking ops stage their result here (the BACK
        # buffer chain) and win_wait promotes it to the front.  Chained
        # un-waited ops coalesce into one staged state — the FIFO lane
        # guarantee "waiting the last handle implies every earlier op
        # landed" is preserved, and donation-safe (each op consumes the
        # previous staged arrays, never the live front).
        self.double_buffer = _win_double_buffer_enabled(double_buffer)
        self.pending = None
        # padded layout: every rank carries max-in-degree buffer rows so the
        # SPMD shapes agree; rank i's live slots are its first in_degree(i)
        # (irregular graphs — StarGraph etc. — work, VERDICT r1 missing #2)
        self.indeg = int(topo.in_degrees().max(initial=0))
        sharding = _api.rank_sharding()
        tensor = jax.tree.map(jnp.asarray, tensor)
        # the EXTERNAL contract: structure check for _win_input, dtype
        # casting template, and the shape win_fetch/win_update restore
        self.treedef = jax.tree.structure(tensor)
        ext_leaves = jax.tree.leaves(tensor)
        if not ext_leaves:
            raise ValueError("window tensor pytree has no leaves")
        n = ext_leaves[0].shape[0]
        self.template = jax.tree.map(
            lambda t: jax.ShapeDtypeStruct(t.shape, t.dtype), tensor)
        self.plan = None
        if _fusion.fusion_enabled(fuse) and len(ext_leaves) > 1:
            # leading_dims=1 keeps the global-view rank axis unflattened:
            # buckets are [N, L] with axis 0 sharded like any leaf
            self.plan = _fusion.plan_for(tensor, leading_dims=1)
            tensor = tuple(_fusion.flatten(self.plan, tensor))
        self.tensor = jax.tree.map(
            lambda t: jax.device_put(t, sharding), tensor)

        def make_buf(t):
            if zero_init:
                return jnp.zeros((t.shape[0], self.indeg) + t.shape[1:],
                                 t.dtype)
            # reference initializes neighbor buffers with the local tensor
            # value (mpi_ops.py:1003-1006)
            return jnp.broadcast_to(
                t[:, None], (t.shape[0], self.indeg) + t.shape[1:])
        self.buffers = jax.tree.map(
            lambda t: jax.device_put(make_buf(t), sharding), self.tensor)
        self.versions = jnp.zeros((n, self.indeg), jnp.int32)
        self.p = jnp.ones((n,), jnp.float32)
        self.p_buffers = jnp.zeros((n, self.indeg), jnp.float32)

    def external(self, internal):
        """Device-resident (possibly fused) state -> the creation tree."""
        if self.plan is None:
            return internal
        return _fusion.unflatten(self.plan, list(internal))

    # -- double-buffer plumbing ---------------------------------------------

    def staged(self):
        """Latest 5-tuple ``(tensor, buffers, versions, p, p_buffers)``:
        the back buffer when a deferred op is outstanding (so chained
        nonblocking ops compose in program order), else the front."""
        if self.pending is not None:
            return self.pending
        return (self.tensor, self.buffers, self.versions, self.p,
                self.p_buffers)

    def stage(self, state) -> None:
        """Record an op's result: into the back buffer under double
        buffering, straight to the front otherwise."""
        if self.double_buffer:
            self.pending = state
        else:
            self.commit(state)

    def commit(self, state) -> None:
        (self.tensor, self.buffers, self.versions, self.p,
         self.p_buffers) = state

    def commit_pending(self) -> None:
        """Promote the back buffer to the front (win_wait / win_flush)."""
        if self.pending is not None:
            self.commit(self.pending)
            self.pending = None
            if _metrics.enabled():
                _metrics.counter(
                    "bf_win_promotes_total",
                    "double-buffer back-to-front promotions "
                    "(win_wait/win_flush)").inc()


_windows: Dict[str, _Window] = {}
_with_associated_p = [False]
# handle -> window name for deferred (double-buffered) commits: win_wait
# promotes that window's staged state after the underlying wait
_deferred_commits: Dict[int, str] = {}

# -- true-async dispatch (opt-in) -------------------------------------------
#
# By default window nonblocking ops dispatch their jitted program from the
# caller's thread (JAX async dispatch hides device latency).  With
# BLUEFOG_WIN_ASYNC=1 the enqueue itself moves onto the native background
# service (csrc/service.cc) — the caller returns before any tracing/dispatch
# happens, reproducing the reference's comm-thread model
# (operations.cc:1619-1623); all window tasks share one service lane, so
# they retain FIFO order exactly like the single MPI comm thread.  As in the
# reference, racing an un-waited put against win_update is the caller's
# responsibility (win_wait first, or take win_mutex).
_ASYNC_BASE = 1 << 40


def _win_async_enabled() -> bool:
    return os.environ.get("BLUEFOG_WIN_ASYNC", "0") == "1"


def _dispatch_win_op(run, result_of=None, op_name: str = "win_op",
                     commit_name: Optional[str] = None):
    """Run ``run()`` inline (default) or on the service lane (async mode).

    Returns an int handle valid for win_wait/win_poll either way.
    ``op_name`` labels the service task: a failing async window op then
    raises a ``ServiceTaskError`` carrying it (service.py).
    ``commit_name``: the window whose staged (back-buffer) state the
    handle's win_wait must promote — the deferred-commit half of double
    buffering."""
    # suspend() gate (reference operations.cc:1392-1400): block before any
    # tracing/dispatch/enqueue, so a suspended context issues no put/get/
    # accumulate traffic.  This covers exactly the one-sided *transfer*
    # ops routed through here; win_update/win_update_then_collect/
    # win_publish/win_fetch stay ungated — they are local buffer math that
    # the reference also runs on the caller thread while suspended
    # (DoWinSync, torch/mpi_win_ops.cc:345-427).  Unlike the collectives'
    # deferred nonblocking path (ops/api.py::_suspend_deferred), window
    # ops BLOCK the calling thread here even for *_nonblocking variants:
    # deferring a window mutation would reorder it against win_update
    # reads.  Hard constraint: resume() must come from a different thread
    # than a window-op caller (docs/faq.md).
    ctx().wait_if_suspended()
    if _metrics.enabled():
        # one funnel counts every one-sided transfer op (put/accumulate/
        # get), labeled by op and dispatch mode — the window-traffic series
        _metrics.counter("bf_win_ops_total",
                         "one-sided window transfer ops").inc(
            op=op_name, mode="async" if _win_async_enabled() else "inline")
    if _win_async_enabled():
        handle = _ASYNC_BASE + _service.submit(run, lane=_service.WIN_LANE,
                                               op_name=op_name)
    else:
        run()
        handle = _register_handle(result_of() if result_of else None)
    if commit_name is not None:
        _deferred_commits[handle] = commit_name
    return handle


def _slot_tables(topo: CompiledTopology) -> np.ndarray:
    """[n_offsets, N]: receive-buffer slot of each offset at each rank
    (in-neighbors sorted ascending), or indeg => no such edge (dropped)."""
    from .collectives import _allgather_slots
    return _allgather_slots(topo)


def windows_exist() -> bool:
    return bool(_windows)


def win_create(tensor, name: str, zero_init: bool = False,
               fuse: Optional[bool] = None,
               double_buffer: Optional[bool] = None,
               compression=None, topo: Optional[CompiledTopology] = None
               ) -> bool:
    """Create a window: per-in-neighbor device buffers + versions + P
    (reference mpi_ops.py:998, mpi_controller.cc:793-866).

    ``tensor`` may be a whole PYTREE (e.g. model parameters): every
    window op then moves the full tree in one jitted program, and — with
    ``fuse`` (default ``BLUEFOG_COMM_FUSION``, on) — over ONE flat buffer
    per dtype instead of per-leaf buffers (see :class:`_Window`): the
    full reference fusion-buffer equivalent.

    ``double_buffer`` (default ``BLUEFOG_WIN_DOUBLE_BUFFER``, on):
    nonblocking transfer ops stage their result in a BACK buffer and
    ``win_wait`` promotes it — ``win_update``/``win_fetch`` drain the
    front while an un-waited op's back buffer fills (docs/windows.md).

    ``compression`` (default ``BLUEFOG_COMM_COMPRESS``, off): put / get /
    accumulate encode their wire payload with the named compressor
    (dense quantizers only — ``'int8'``, ``'fp8'``, ``'identity'``;
    sparsifier and choco specs are rejected with guidance); the window
    buffers and ``win_update``'s local fold stay full precision
    (docs/compression.md).

    ``topo`` (default: the context topology) lets a window live on its
    OWN compiled graph — e.g. the serving tier's publisher->replica
    parameter window (``bluefog_tpu/serving/``) moves weights along a
    dedicated bipartite graph while training gossip keeps the context
    topology.  The graph must span the full mesh (``topo.size ==
    bf.size()``); its edges define the buffer slot layout exactly as the
    context topology would.

    The topology is snapshotted at creation; like the reference
    (operations.cc:1286-1311), changing the topology while windows exist is
    refused by ``bf.set_topology``.
    """
    if name in _windows:
        return False  # duplicate name (reference returns False, mpi_ops.py:1021)
    cx = ctx()
    if topo is None:
        topo = cx.compiled_topology
    elif topo.size != cx.size:
        raise ValueError(
            f"window topology is over {topo.size} ranks but the mesh has "
            f"{cx.size}; a dedicated window graph must span the full mesh")
    tensor = jax.tree.map(jnp.asarray, tensor)
    for leaf in jax.tree.leaves(tensor):
        if leaf.shape[0] != cx.size:
            raise ValueError(
                f"window tensors are global-view: expected leading dim "
                f"{cx.size}, got {leaf.shape}")
    _windows[name] = _Window(tensor, topo, zero_init, fuse=fuse,
                             double_buffer=double_buffer,
                             compression=compression)
    return True


def win_free(name: Optional[str] = None) -> bool:
    if name is None:
        _windows.clear()
        _deferred_commits.clear()
        return True
    if name not in _windows:
        return False
    del _windows[name]
    for h in [h for h, n in _deferred_commits.items() if n == name]:
        del _deferred_commits[h]
    return True


def get_current_created_window_names() -> List[str]:
    return sorted(_windows.keys())


def _window(name: str) -> _Window:
    if name not in _windows:
        raise ValueError(f"{name} is not found in the registered window object.")
    return _windows[name]


# ---------------------------------------------------------------------------
# jitted kernels (cached per topology/op)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=128)
def _push_fn(topo: CompiledTopology, accumulate: bool, mesh_id: int,
             donate: bool = True, compression=None):
    """win_put / win_accumulate kernel.

    Sends ``x * D[src, dst]`` into dst's buffer slot for src (replace or
    add), bumps versions, optionally moves associated P with the same
    weights, then scales the local tensor/P by self_weight
    (mpi_controller.cc:950-1031; self scaling per mpi_ops.py:1152-1155).

    ``x``/``buffers`` may be PYTREES — the whole tree moves in this one
    program (fusion-buffer equivalent; jit's cache keys on the tree
    structure, so arrays and trees coexist).

    ``compression`` (a :class:`~..compress.CompressionConfig`, hashable —
    part of this cache's key): the outgoing weighted value rides the wire
    in its compressed encoding per leaf/bucket per offset and is decoded
    into the destination buffer; the associated-P scalar always moves
    uncompressed (it is one float).
    """
    cx = ctx()
    size = topo.size
    slots = _slot_tables(topo)
    from .collectives import _rotation_pairs
    spec = P(cx.rank_axis)
    comp = (_compress.get_compressor(compression)
            if compression is not None else None)

    def wrapper(x, buffers, versions, p, p_buffers, D, self_w, with_p):
        def shard_fn(xs, bufs, vers, ps, pbufs, D_, self_w_, with_p_):
            x_t = jax.tree.map(lambda a: a[0], xs)
            buf_t = jax.tree.map(lambda a: a[0], bufs)
            ver, p_r, pbuf = vers[0], ps[0], pbufs[0]
            idx = lax.axis_index(cx.rank_axis)
            ar = jnp.arange(size)
            for k, offset in enumerate(topo.offsets):
                send_w = D_[ar, (ar + offset) % size][idx]
                has_edge = (D_[(ar - offset) % size, ar] != 0)[idx]
                slot = jnp.asarray(slots[k])[idx]
                # static per-offset shared key (window ops carry no step
                # index); only dense quantizers reach here and they run
                # deterministic rounding (rank_key=None)
                wkey = (jax.random.fold_in(
                    jax.random.key(0x71D0), k) if comp is not None else None)

                def leaf_exchange(x_r, buf):
                    send_val = send_w.astype(x_r.dtype) * x_r
                    if comp is not None:
                        wire = comp.compress(send_val, wkey, None)
                        arrived_wire = jax.tree.map(
                            lambda a: lax.ppermute(
                                a, cx.rank_axis,
                                _rotation_pairs(size, offset)), wire)
                        arrived = comp.decompress(arrived_wire, wkey,
                                                  x_r.shape, x_r.dtype)
                    else:
                        arrived = lax.ppermute(
                            send_val, cx.rank_axis,
                            _rotation_pairs(size, offset))
                    old = buf[slot]
                    new = arrived + old if accumulate else arrived
                    return buf.at[slot].set(
                        jnp.where(has_edge, new, old), mode="drop")

                buf_t = jax.tree.map(leaf_exchange, x_t, buf_t)
                ver = ver.at[slot].add(
                    jnp.where(has_edge, 1, 0), mode="drop")
                # associated P rides the same edges/weights, once per window
                p_send = send_w * p_r
                p_arr = lax.ppermute(
                    p_send, cx.rank_axis, _rotation_pairs(size, offset))
                p_old = pbuf[slot]
                p_new = p_arr + p_old if accumulate else p_arr
                pbuf = pbuf.at[slot].set(
                    jnp.where(with_p_ & has_edge, p_new, p_old), mode="drop")
            sw = self_w_[idx]  # [N] vector, P() spec: unsliced
            x_out = jax.tree.map(lambda x_r: x_r * sw.astype(x_r.dtype), x_t)
            p_out = jnp.where(with_p_, p_r * sw, p_r)
            lead = lambda t: jax.tree.map(lambda a: a[None], t)
            return (lead(x_out), lead(buf_t), ver[None], p_out[None],
                    pbuf[None])
        return jax.shard_map(
            shard_fn, mesh=cx.mesh,
            in_specs=(spec, spec, spec, spec, spec, P(), P(), P()),
            out_specs=(spec, spec, spec, spec, spec),
        )(x, buffers, versions, p, p_buffers, D, self_w, with_p)
    # donate the window STATE (buffers/versions/P — replaced by the
    # outputs on every call) so XLA updates it in place; x stays the
    # caller's. TPU only: host platforms ignore donation with a warning
    # per compile.  Double-buffered windows pass donate=False: their
    # kernel inputs are the live FRONT state, which must stay readable
    # (win_update drains it) until win_wait commits the staged result.
    argnums = ((1, 2, 3, 4)
               if donate and jax.default_backend() == "tpu" else ())
    return jax.jit(wrapper, donate_argnums=argnums)


@functools.lru_cache(maxsize=128)
def _update_fn(topo: CompiledTopology, mesh_id: int):
    """win_update kernel: tensor <- self_w * tensor + sum_slots U[src, i] *
    buffer[slot]; optional buffer reset; versions of read slots -> 0;
    associated P mixed with identical weights (torch/mpi_win_ops.cc:345-427).
    """
    cx = ctx()
    size = topo.size
    slots = _slot_tables(topo)
    spec = P(cx.rank_axis)

    def wrapper(x, buffers, versions, p, p_buffers, U, self_w, reset, with_p):
        def shard_fn(xs, bufs, vers, ps, pbufs, U_, self_w_, reset_, with_p_):
            x_t = jax.tree.map(lambda a: a[0], xs)
            buf_t = jax.tree.map(lambda a: a[0], bufs)
            ver, p_r, pbuf = vers[0], ps[0], pbufs[0]
            idx = lax.axis_index(cx.rank_axis)
            ar = jnp.arange(size)
            sw = self_w_[idx]  # self_w_ is the [N] vector (P() spec: unsliced)
            out_t = jax.tree.map(lambda x_r: sw.astype(x_r.dtype) * x_r, x_t)
            p_out = sw * p_r
            for k, offset in enumerate(topo.offsets):
                w = U_[(ar - offset) % size, ar][idx]
                has_edge = (topo.weight_matrix[(np.arange(size) - offset) % size,
                                               np.arange(size)] != 0)
                edge = jnp.asarray(has_edge)[idx]
                slot = jnp.asarray(slots[k])[idx]
                contrib = jnp.where(edge, w, 0.0)
                include = edge & (w != 0)
                out_t = jax.tree.map(
                    lambda o, buf: o + contrib.astype(o.dtype) * buf[slot],
                    out_t, buf_t)
                p_out = p_out + contrib * pbuf[slot]
                buf_t = jax.tree.map(
                    lambda buf: buf.at[slot].set(
                        jnp.where(reset_ & include,
                                  jnp.zeros_like(buf[slot]), buf[slot]),
                        mode="drop"), buf_t)
                pbuf = pbuf.at[slot].set(
                    jnp.where(reset_ & include & with_p_, 0.0, pbuf[slot]),
                    mode="drop")
                ver = ver.at[slot].set(
                    jnp.where(include, 0, ver[slot]), mode="drop")
            p_final = jnp.where(with_p_, p_out, p_r)
            lead = lambda t: jax.tree.map(lambda a: a[None], t)
            return (lead(out_t), lead(buf_t), ver[None], p_final[None],
                    pbuf[None])
        return jax.shard_map(
            shard_fn, mesh=cx.mesh,
            in_specs=(spec, spec, spec, spec, spec, P(), P(), P(), P()),
            out_specs=(spec, spec, spec, spec, spec),
        )(x, buffers, versions, p, p_buffers, U, self_w, reset, with_p)
    return jax.jit(wrapper)


@functools.lru_cache(maxsize=128)
def _push_sched_fn(topo: CompiledTopology, sched, accumulate: bool,
                   self_scale: bool, mesh_id: int, donate: bool = True,
                   compression=None):
    """Dynamic-schedule variant of :func:`_push_fn`: the step's mixing
    matrix is gathered ON DEVICE from the schedule tables by a traced step
    index, so per-step dynamic window ops (the push-sum paper's one-peer
    schedule, reference torch/mpi_ops.py:1144-1209 with per-call
    dst_weights) never recompile and never build a host matrix per step.

    Convention: off-diagonal entries of ``W_t`` are the transfer weights;
    ``diag(W_t)`` is the self weight for puts (``self_scale=True``) —
    exactly what ``compile_dynamic_schedule`` produces.  Gets keep the
    local tensor unscaled (``self_scale=False``).
    """
    inner = _push_fn(topo, accumulate, mesh_id, donate, compression)
    mats = jnp.asarray(sched.matrices, jnp.float32)        # [T, N, N]
    eye = jnp.eye(topo.size, dtype=jnp.float32)

    def wrapper(x, buffers, versions, p, p_buffers, step, with_p):
        W = mats[step % sched.period]
        sw = jnp.diagonal(W) if self_scale else jnp.ones((topo.size,),
                                                         jnp.float32)
        return inner(x, buffers, versions, p, p_buffers,
                     W * (1.0 - eye), sw, with_p)
    # window-state donation as in _push_fn (the inner jit's donation is
    # inlined away under this outer jit, so it must be re-declared here)
    argnums = ((1, 2, 3, 4)
               if donate and jax.default_backend() == "tpu" else ())
    return jax.jit(wrapper, donate_argnums=argnums)


def _check_sched(w: "_Window", sched, step, weights, kind: str):
    """Validate a per-call dynamic schedule against the window's snapshot
    topology: every edge the schedule can use must be an edge of the
    created topology (the slot layout is fixed at win_create), i.e. compile
    the schedule from the same graph — or a subgraph — that the window was
    created with."""
    if weights is not None:
        raise ValueError(f"pass either sched= or {kind}=, not both")
    if step is None:
        raise ValueError("dynamic window ops need the step index (step=i)")
    if sched.size != w.topo.size:
        raise ValueError(
            f"schedule is over {sched.size} ranks, window over {w.topo.size}")
    # PER-EDGE check (offset-set membership alone is too weak: on a
    # non-circulant window graph an offset can exist for some ranks but
    # not others, and a push over a missing edge would silently drop in
    # the padded slot layout): every edge any step can use must be an
    # edge of the creation topology.
    used = (np.abs(sched.matrices).sum(axis=0) != 0)
    np.fill_diagonal(used, False)
    adj = w.topo.weight_matrix != 0
    np.fill_diagonal(adj, False)
    bad = np.argwhere(used & ~adj)
    if len(bad):
        pairs = [tuple(map(int, e)) for e in bad[:4]]
        raise ValueError(
            f"schedule uses edges {pairs}{'...' if len(bad) > 4 else ''} "
            f"that are not edges of the window's creation topology; create "
            f"the window with the schedule's superset graph (its buffer "
            f"slots are fixed at win_create)")


# ---------------------------------------------------------------------------
# Matrices from defaults
# ---------------------------------------------------------------------------

def _self_weight_vector(size: int, self_weight) -> jnp.ndarray:
    """Scalar or per-rank self weight -> [N] float32 vector."""
    if self_weight is None:
        self_weight = 1.0
    return jnp.broadcast_to(
        jnp.asarray(self_weight, jnp.float32), (size,))


def _out_matrix(topo: CompiledTopology,
                weights: Optional[np.ndarray]) -> np.ndarray:
    """Default dst matrix: 1.0 on every out-edge (mpi_ops.py:1174-1176)."""
    if weights is not None:
        W = np.asarray(weights, np.float64)
        adj = topo.weight_matrix != 0
        np.fill_diagonal(adj, False)
        if np.any(W[~adj] != 0):
            raise ValueError(
                "dst/src weights may only name edges of the window's "
                "topology (out-neighbors; self rank is not allowed)")
        return W
    A = (topo.weight_matrix != 0).astype(np.float64)
    np.fill_diagonal(A, 0.0)
    return A


def _update_matrix(topo: CompiledTopology,
                   self_weight, neighbor_weights):
    """Resolve win_update weights (mpi_ops.py:1107-1135): explicit matrix, or
    topology weights when ``is_weighted``, else uniform 1/(indeg+1)."""
    n = topo.size
    if (neighbor_weights is None) != (self_weight is None):
        raise ValueError("Arguments self_weight and neighbor_weights have to "
                         "be presented at the same time")
    if neighbor_weights is not None:
        U = np.asarray(neighbor_weights, np.float64)
        adj = topo.weight_matrix != 0
        np.fill_diagonal(adj, False)
        if np.any(U[~adj] != 0):
            raise ValueError(
                "neighbor_weights may only contain ranks that belong to "
                "in-neighbors of each rank (edges of the window topology)")
        sw = np.broadcast_to(np.asarray(self_weight, np.float64), (n,)).copy()
        return U, sw
    W = topo.weight_matrix.copy()
    sw = np.diag(W).copy()
    np.fill_diagonal(W, 0.0)
    return W, sw


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def _win_input(tensor, w: "_Window"):
    """Caller data -> the window's INTERNAL global-view state: structure-
    checked against the creation tree, leaves cast to the creation dtypes,
    then packed into the fused flat buffers when the window is fused."""
    if jax.tree.structure(tensor) != w.treedef:
        raise ValueError(
            f"window tensor structure mismatch: window holds "
            f"{w.treedef}, got {jax.tree.structure(tensor)}")
    g = jax.tree.map(lambda t, wt: jnp.asarray(t, wt.dtype),
                     tensor, w.template)
    if w.plan is not None:
        g = tuple(_fusion.flatten(w.plan, g))
    return jax.tree.map(_api.to_global, g)


def _push_like_nonblocking(tensor, name: str, self_weight, dst_weights,
                           sched, step, accumulate: bool) -> int:
    """Shared body of win_put/win_accumulate (they differ only in whether
    arriving data replaces or adds into the destination buffers)."""
    w = _window(name)
    cx = ctx()
    with_p = _with_associated_p[0]
    if sched is not None:
        _check_sched(w, sched, step, dst_weights, "dst_weights")
        if self_weight is not None:
            raise ValueError(
                "sched= carries the self weights (diag of the step matrix); "
                "self_weight= cannot also be given")
        fn = _push_sched_fn(w.topo, sched, accumulate, True, id(cx.mesh),
                            not w.double_buffer, w.compression)

        def run():
            x = _win_input(tensor, w)
            _, bufs, vers, p, pbufs = w.staged()
            w.stage(fn(x, bufs, vers, p, pbufs,
                       jnp.asarray(step, jnp.int32), jnp.asarray(with_p)))
        return _dispatch_win_op(
            run, lambda: w.staged()[0],
            op_name="win_accumulate" if accumulate else "win_put",
            commit_name=name)

    D = _out_matrix(w.topo, dst_weights)
    sw = _self_weight_vector(w.topo.size, self_weight)
    fn = _push_fn(w.topo, accumulate, id(cx.mesh), not w.double_buffer,
                  w.compression)

    def run():
        x = _win_input(tensor, w)
        _, bufs, vers, p, pbufs = w.staged()
        w.stage(fn(x, bufs, vers, p, pbufs,
                   jnp.asarray(D, jnp.float32), jnp.asarray(sw),
                   jnp.asarray(with_p)))
    return _dispatch_win_op(
        run, lambda: w.staged()[0],
        op_name="win_accumulate" if accumulate else "win_put",
        commit_name=name)


def win_put_nonblocking(tensor, name: str,
                        self_weight: Optional[float] = None,
                        dst_weights: Optional[np.ndarray] = None,
                        require_mutex: bool = False,
                        sched=None, step: Optional[int] = None) -> int:
    """Put ``tensor * dst_weights[src, dst]`` into each destination's buffer
    for ``src`` (replace), then scale the local window tensor by
    ``self_weight`` (mpi_ops.py:1144-1209).

    Dynamic topologies: pass ``sched=`` (a :class:`DynamicSchedule`
    compiled from the window's creation graph or a subgraph) plus the step
    index — the step's edges and weights are selected on device, mirroring
    the reference's per-call dynamic ``dst_weights`` without a recompile.
    """
    return _push_like_nonblocking(tensor, name, self_weight, dst_weights,
                                  sched, step, accumulate=False)


def win_put(tensor, name: str, self_weight=None, dst_weights=None,
            require_mutex: bool = False, sched=None,
            step: Optional[int] = None) -> bool:
    win_wait(win_put_nonblocking(tensor, name, self_weight, dst_weights,
                                 require_mutex, sched, step))
    return True


def win_accumulate_nonblocking(tensor, name: str,
                               self_weight: Optional[float] = None,
                               dst_weights: Optional[np.ndarray] = None,
                               require_mutex: bool = False,
                               sched=None,
                               step: Optional[int] = None) -> int:
    """Like win_put but adds into the destination buffers (SUM only,
    mpi_ops.py:1279-1345).  ``sched=``/``step=`` as in win_put — the
    push-sum one-peer schedules ride this path."""
    return _push_like_nonblocking(tensor, name, self_weight, dst_weights,
                                  sched, step, accumulate=True)


def win_accumulate(tensor, name: str, self_weight=None, dst_weights=None,
                   require_mutex: bool = False, sched=None,
                   step: Optional[int] = None) -> bool:
    win_wait(win_accumulate_nonblocking(tensor, name, self_weight,
                                        dst_weights, require_mutex,
                                        sched, step))
    return True


def win_get_nonblocking(name: str,
                        src_weights: Optional[np.ndarray] = None,
                        require_mutex: bool = False,
                        sched=None, step: Optional[int] = None) -> int:
    """Pull each in-neighbor's window tensor (scaled by ``src_weights[src,
    dst]``) into the local buffer for that neighbor (mpi_ops.py:1215-1272).
    ``sched=``/``step=`` select a per-step dynamic edge set as in win_put.
    """
    w = _window(name)
    cx = ctx()
    with_p = _with_associated_p[0]
    if sched is not None:
        _check_sched(w, sched, step, src_weights, "src_weights")
        fn = _push_sched_fn(w.topo, sched, False, False, id(cx.mesh),
                            not w.double_buffer, w.compression)

        def run():
            t0, bufs, vers, p, pbufs = w.staged()
            w.stage(fn(t0, bufs, vers, p, pbufs,
                       jnp.asarray(step, jnp.int32), jnp.asarray(with_p)))
        return _dispatch_win_op(run, lambda: w.staged()[1],
                                op_name="win_get", commit_name=name)

    G = _out_matrix(w.topo, src_weights)
    fn = _push_fn(w.topo, False, id(cx.mesh), not w.double_buffer,
                  w.compression)

    def run():
        t0, bufs, vers, p, pbufs = w.staged()
        w.stage(fn(t0, bufs, vers, p, pbufs,
                   jnp.asarray(G, jnp.float32),
                   _self_weight_vector(w.topo.size, None),
                   jnp.asarray(with_p)))
    return _dispatch_win_op(run, lambda: w.staged()[1], op_name="win_get",
                            commit_name=name)


def win_get(name: str, src_weights=None, require_mutex: bool = False,
            sched=None, step: Optional[int] = None) -> bool:
    win_wait(win_get_nonblocking(name, src_weights, require_mutex,
                                 sched, step))
    return True


def _liveness_masked_update(U, sw, alive):
    """Zero the update rows of dead in-neighbors and move their mass to the
    self weight (all jnp: ``alive`` may be a device-resident liveness mask
    from ``resilience.membership`` — swapping masks never recompiles).

    Window semantics under a death: a dead neighbor's buffer holds its LAST
    delivered value forever; without masking, every ``win_update`` keeps
    averaging that stale garbage with full weight.  Masking degrades the
    edge to *bounded staleness*: the dead row's weight drops to zero, the
    receiver keeps the mass itself, and total weight is preserved."""
    a = jnp.asarray(alive, jnp.float32).reshape(-1)
    U = jnp.asarray(U, jnp.float32)
    sw = jnp.asarray(sw, jnp.float32)
    lost = (U * (1.0 - a)[:, None]).sum(axis=0)
    return U * a[:, None], sw + lost


def win_update(name: str,
               self_weight: Optional[float] = None,
               neighbor_weights: Optional[np.ndarray] = None,
               reset: bool = False, clone: bool = False,
               require_mutex: bool = False, alive=None):
    """Fold the neighbor buffers into the window tensor:
    ``t <- self_weight * t + sum_src U[src, rank] * buffer[src]``
    (mpi_ops.py:1066-1137; torch/mpi_win_ops.cc:345-427).

    ``neighbor_weights`` is the global [N, N] weight matrix (entry (src,
    dst)); defaults to topology weights when ``bf.init(is_weighted=True)``,
    else the uniform ``1/(in_degree+1)`` average.  Versions of the slots read
    drop to 0; ``reset`` zeroes those buffers after the computation.

    ``alive`` (optional [N] mask, e.g. from ``resilience.membership``):
    dead in-neighbors degrade to zero-weight rows with their mass absorbed
    into the self weight — bounded staleness instead of averaging a dead
    rank's frozen buffer forever.  The mask is traced data.

    Double buffering: this drains the FRONT state.  Committing (``clone=
    False``) while a nonblocking op is staged and un-waited is a caller
    race — that op's later ``win_wait`` overwrites this update's result
    (docs/windows.md "Double buffering"); peek with ``clone=True`` for
    mid-flight reads, or ``win_wait`` first.
    """
    w = _window(name)
    cx = ctx()
    if _metrics.enabled():
        _metrics.counter("bf_win_updates_total",
                         "win_update buffer folds").inc(
            peek="1" if clone else "0")
    U, sw = _update_matrix(w.topo, self_weight, neighbor_weights)
    U = jnp.asarray(U, jnp.float32)
    sw = jnp.asarray(sw, jnp.float32)
    if alive is not None:
        U, sw = _liveness_masked_update(U, sw, alive)
    fn = _update_fn(w.topo, id(cx.mesh))
    out = fn(w.tensor, w.buffers, w.versions, w.p, w.p_buffers,
             U, sw,
             jnp.asarray(bool(reset)), jnp.asarray(_with_associated_p[0]))
    tensor_new = out[0]
    if clone:
        # pure peek: no window state (tensor, buffers, versions, P) commits,
        # keeping x and its associated P consistent
        return w.external(tensor_new)
    w.tensor = tensor_new
    w.buffers, w.versions, w.p, w.p_buffers = out[1], out[2], out[3], out[4]
    return w.external(tensor_new)


def win_update_then_collect(name: str, require_mutex: bool = True,
                            alive=None):
    """``win_update`` with self/neighbor weights 1.0 and reset=True — the
    push-sum collect step (mpi_ops.py:1048-1064).

    ``alive`` (optional [N] mask): dead in-neighbors are DROPPED from the
    sum — unlike :func:`win_update`'s averaging fold, collect is a sum,
    so a dead row's undelivered mass must vanish rather than move to the
    self weight (inflating ``t`` by the lost weight would double-count).
    The associated-P scalar rides the identical masked weights, so
    push-sum's ``x / P`` de-biasing stays exact under the mask.  The
    mask composes with window wire compression (``win_create(
    compression=)``) — the buffers being dropped hold decoded full-
    precision values either way."""
    w = _window(name)
    U = (w.topo.weight_matrix != 0).astype(np.float64)
    np.fill_diagonal(U, 0.0)
    if alive is not None:
        # pre-masked here (NOT via win_update(alive=), whose averaging
        # semantics move the lost mass onto the self weight)
        U = U * np.asarray(alive, np.float64).reshape(-1)[:, None]
    return win_update(name, self_weight=1.0, neighbor_weights=U, reset=True,
                      require_mutex=require_mutex)


def win_bootstrap_rank(name: str, rank: int, *, self_weight: float = 0.0,
                       alive=None, reset: bool = False):
    """One joiner catch-up round: pull ``rank``'s live in-neighbor window
    tensors (a ``win_get`` restricted to its in-edges) and fold ONLY its
    row toward their average — every other rank's tensor, buffers, and
    versions stay untouched.

    This is the windows half of the elastic-membership admission
    protocol (docs/resilience.md "Elastic membership"): a joining rank's
    slot already exists in every window (windows are global-view over
    the full mesh — capacity ranks are pre-allocated by construction),
    so bootstrap is just different weight matrices flowing through the
    window's one compiled get/update program — zero recompiles per
    joiner, per fold.

    ``self_weight`` is the fraction of the joiner's own (stale) value
    kept; 0.0 = adopt the in-neighbor average outright.  ``alive``
    (optional [N] mask) drops dead feeds; a joiner with NO live
    in-neighbor keeps its value (bounded staleness, never garbage).

    ``reset`` zeroes the joiner's pulled buffer slots (and their
    versions / P buffers) after the fold.  Averaging consumers (the
    win-put family, serving collect) can leave them — leftovers are
    merely slightly-stale values at the next fold — but SUM-semantics
    consumers MUST pass ``reset=True``: an async push-sum collect
    (``async_train/``) adds buffer contents to the tensor, so a
    bootstrap leftover would re-enter the sum as phantom mass and break
    the conservation invariant ``sum(x)/sum(P) == const``.  Under
    ``with_p`` the get also pulls the in-neighbors' P scalars and the
    fold mixes them with the same weights, so the joiner lands on
    ``x/P ~= debiased average`` with no extra plumbing.
    Returns the window's global-view tensor after the fold
    (:func:`win_fetch` shape)."""
    w = _window(name)
    n = w.topo.size
    if not 0 <= rank < n:
        raise ValueError(f"rank {rank} outside [0, {n})")
    if not 0.0 <= self_weight <= 1.0:
        raise ValueError(f"self_weight must be in [0, 1], got {self_weight}")
    alive_row = (np.ones(n) if alive is None
                 else np.asarray(alive, np.float64).reshape(-1))
    srcs = [s for s in w.topo.in_neighbor_ranks(rank) if alive_row[s] > 0]
    if not srcs:
        return win_fetch(name)
    G = np.zeros((n, n))
    G[srcs, rank] = 1.0
    win_get(name, src_weights=G)
    U = np.zeros((n, n))
    U[srcs, rank] = (1.0 - self_weight) / len(srcs)
    sw = np.ones(n)
    sw[rank] = self_weight
    return win_update(name, self_weight=sw, neighbor_weights=U, reset=reset)


def win_publish(name: str, tensor) -> None:
    """Replace the local window tensor without any communication (the
    reference's registered tensor aliases the torch parameter, so local
    mutations are implicit there; JAX needs an explicit write)."""
    w = _window(name)
    w.tensor = _win_input(tensor, w)


def win_fetch(name: str):
    """Current global-view window tensor (the reference mutates the
    registered torch tensor in place; JAX arrays are immutable, so read the
    latest value here).  Fused windows unpack to the creation tree."""
    w = _window(name)
    return w.external(w.tensor)


def win_poll(handle: int) -> bool:
    if handle >= _ASYNC_BASE // 2:
        return _service.poll(handle - _ASYNC_BASE)
    return _api.poll(handle)


def win_wait(handle: int) -> bool:
    """Complete a nonblocking window op: block until its program ran, then
    — under double buffering — promote the window's staged back buffer to
    the front.  Staged ops COALESCE: waiting a later handle on the same
    window also publishes every earlier (FIFO-ordered) op's effect, and
    waiting an earlier handle publishes any later op that already
    completed — per-handle isolation is not provided (docs/windows.md)."""
    if handle >= _ASYNC_BASE // 2:
        _service.wait(handle - _ASYNC_BASE)
    else:
        synchronize(handle)
    name = _deferred_commits.pop(handle, None)
    if name is not None and name in _windows:
        _windows[name].commit_pending()
    return True


def win_flush(name: Optional[str] = None) -> None:
    """Promote any staged (back-buffer) window state without a handle —
    for one window or all.  The state-dict restore path needs this: a
    snapshot taken with a put in flight restores that put as staged
    again, and the original handle does not survive the restore."""
    if name is not None:
        _window(name).commit_pending()
        stale = [h for h, n in _deferred_commits.items() if n == name]
    else:
        for w in _windows.values():
            w.commit_pending()
        stale = list(_deferred_commits)
    # handles flushed without a win_wait would otherwise pin their map
    # entries for the process lifetime (their later win_wait, if any, is
    # a no-op commit either way)
    for h in stale:
        del _deferred_commits[h]


def get_win_version(name: str, rank: Optional[int] = None) -> Dict[int, int]:
    """Per-in-neighbor staleness counters (mpi_ops.py:1369-1383): 0 means the
    buffer was read/synced since the last write."""
    w = _window(name)
    cx = ctx()
    r = cx.rank() if rank is None else rank
    vers = np.asarray(w.versions)
    srcs = sorted(w.topo.in_neighbor_ranks(r))
    return {src: int(vers[r, slot]) for slot, src in enumerate(srcs)}


def win_version_vector(name: str) -> np.ndarray:
    """[N] effective-staleness vector: per rank, the MAX write-since-read
    counter over its in-neighbor slots — how many deliveries have
    accumulated in some buffer without a fold reading it.  This is the
    observable behind the async-training staleness histogram
    (``bf_async_staleness_steps``) and the bounded-staleness refusal
    evidence in docs/async.md: a rank gossiping every ``k`` ticks sees
    this grow to ``k`` and snap to 0 at its fold.  Host numpy (one
    device sync); padded slots never bump, so they read 0."""
    w = _window(name)
    vers = np.asarray(w.versions)
    return vers.max(axis=1) if vers.ndim == 2 and vers.shape[1] else \
        np.zeros(w.topo.size, dtype=vers.dtype)


def win_associated_p_vector(name: str):
    """The [N] device array of associated-P scalars (on-device fast path for
    push-sum de-biasing; avoids per-rank host syncs)."""
    return _window(name).p


def win_associated_p(name: str, rank: Optional[int] = None) -> float:
    """Push-sum bias-correction scalar (mpi_ops.py:1447-1456), initialized 1."""
    w = _window(name)
    r = ctx().rank() if rank is None else rank
    return float(np.asarray(w.p)[r])


def win_state_dict() -> Dict[str, Dict[str, jax.Array]]:
    """Snapshot every window's device state (tensor, neighbor buffers,
    versions, associated-P scalar + buffers) as a checkpointable pytree.

    The reference cannot checkpoint async training mid-flight (its window
    memory lives in MPI RMA buffers, SURVEY.md §5.4); here the window state
    is ordinary arrays, so push-sum runs resume exactly.  The durable-
    fleet-state subsystem captures this snapshot automatically
    (``checkpoint.fleet_state_dict`` — its ``windows`` section) and
    restores it through :func:`load_win_state_dict`; the pair also works
    standalone with any single-tree checkpointer (docs/checkpoint.md).
    """
    # COPIES, not references: window ops donate the state arrays on TPU
    # (in-place updates), so a live view would be deleted under an
    # async/overlapped checkpoint write
    snap = lambda t: jax.tree.map(lambda a: jnp.array(a, copy=True), t)
    out = {}
    for name, w in _windows.items():
        entry = {"tensor": snap(w.tensor), "buffers": snap(w.buffers),
                 "versions": snap(w.versions), "p": snap(w.p),
                 "p_buffers": snap(w.p_buffers)}
        if w.pending is not None:
            # BOTH buffers roundtrip: the staged back buffer of an
            # un-waited nonblocking op is real state — dropping it would
            # silently lose the op across a checkpoint
            pt, pb, pv, pp, ppb = w.pending
            entry["pending"] = {"tensor": snap(pt), "buffers": snap(pb),
                                "versions": snap(pv), "p": snap(pp),
                                "p_buffers": snap(ppb)}
        out[name] = entry
    return out


def load_win_state_dict(state: Dict[str, Dict], strict: bool = True) -> None:
    """Restore a :func:`win_state_dict` snapshot into the *existing*
    windows (create them with ``win_create`` under the same topology
    first — the snapshot carries data, not structure)."""
    for name, leaves in state.items():
        if name not in _windows:
            if strict:
                raise ValueError(
                    f"window {name!r} not registered; call win_create "
                    f"before restoring its state")
            continue
        w = _windows[name]
        snap_shapes = [tuple(b.shape)
                       for b in jax.tree.leaves(leaves["buffers"])]
        win_shapes = [tuple(b.shape) for b in jax.tree.leaves(w.buffers)]
        if snap_shapes != win_shapes:
            raise ValueError(
                f"window {name!r}: snapshot buffers {snap_shapes} do not "
                f"match the registered window {win_shapes} "
                f"(topology or fusion layout changed? recreate the window "
                f"with the same win_create(fuse=) setting the snapshot "
                f"ran with)")
        sharding = _api.rank_sharding()
        # copy on load: the window will DONATE these arrays on TPU; the
        # caller's snapshot dict must stay valid afterwards
        put = lambda t: jax.device_put(jnp.array(t, copy=True), sharding)
        # reconcile through the INTERNAL treedef (the creation tree for
        # unfused windows, the flat dtype buckets for fused ones — the
        # snapshot carries whatever layout the window ran with):
        # checkpoint layers may hand back a structurally different but
        # leaf-compatible tree (orbax restores tuples as lists without a
        # template)
        internal_def = jax.tree.structure(w.tensor)
        restore = lambda tree: jax.tree.unflatten(
            internal_def, [put(t) for t in jax.tree.leaves(tree)])
        w.tensor = restore(leaves["tensor"])
        w.buffers = restore(leaves["buffers"])
        w.versions = jnp.array(leaves["versions"], copy=True)
        w.p = jnp.array(leaves["p"], copy=True)
        w.p_buffers = jnp.array(leaves["p_buffers"], copy=True)
        pend = leaves.get("pending")
        if pend is not None:
            # re-staged, not committed: publishing an op the original run
            # never waited would reorder it against that run's win_updates;
            # call win_flush(name) to promote it deliberately
            w.pending = (restore(pend["tensor"]), restore(pend["buffers"]),
                         jnp.array(pend["versions"], copy=True),
                         jnp.array(pend["p"], copy=True),
                         jnp.array(pend["p_buffers"], copy=True))
        else:
            w.pending = None


def turn_on_win_ops_with_associated_p():
    _with_associated_p[0] = True


def turn_off_win_ops_with_associated_p():
    _with_associated_p[0] = False


@contextmanager
def win_mutex(name: str, for_self: bool = False,
              ranks: Optional[List[int]] = None):
    """Distributed window mutex (mpi_ops.py:1421-1445).  Bulk-synchronous
    SPMD execution already serializes every buffer access in program order,
    so acquisition is trivially satisfied; kept for API parity."""
    _window(name)  # existence check, like the reference
    yield


@contextmanager
def win_lock(name: str):
    """RMA access-epoch lock (mpi_ops.py:1390-1417) — no-op for the same
    reason as :func:`win_mutex`."""
    _window(name)
    yield
