"""Global-view op API (reference parity: ``bluefog/torch/mpi_ops.py``).

BlueFog programs are written per-MPI-process: every rank owns a tensor and
calls ``bf.neighbor_allreduce(t)``.  The TPU-native equivalent is a *global
view*: one controller drives all devices, and "rank i's tensor" is slice ``i``
of a global array of shape ``[size, ...]`` sharded over the mesh's ``rank``
axis.  Each API call runs one jitted ``shard_map`` program in which rank i's
shard exchanges data with its neighbors over ICI.

Nonblocking semantics come for free: JAX dispatch is async, so the
``*_nonblocking`` variants return a handle immediately and
``synchronize``/``wait``/``poll`` map to ``block_until_ready``/``is_ready``
(replacing the reference's handle manager + background thread,
``bluefog/torch/handle_manager.h:30-41``).

In-place variants (``allreduce_`` etc.) exist for signature parity but return
new arrays — JAX arrays are immutable.
"""

import contextlib
import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from jax.sharding import NamedSharding, PartitionSpec as P

from .. import context as _ctx_mod
from .. import timeline as _tl
from ..context import ctx
from . import collectives as C
from ..parallel.schedule import (
    CompiledTopology,
    DynamicSchedule,
    compile_weight_matrix,
)

__all__ = [
    "allreduce", "allreduce_nonblocking", "allreduce_", "allreduce_nonblocking_",
    "broadcast", "broadcast_nonblocking", "broadcast_", "broadcast_nonblocking_",
    "allgather", "allgather_nonblocking",
    "neighbor_allreduce", "neighbor_allreduce_nonblocking",
    "neighbor_allgather", "neighbor_allgather_nonblocking",
    "hierarchical_neighbor_allreduce", "hierarchical_neighbor_allreduce_nonblocking",
    "pair_gossip", "pair_gossip_nonblocking",
    "barrier", "poll", "synchronize", "wait",
    "rank_sharding", "to_global", "from_global",
    "set_weights_override", "clear_weights_override", "weights_override",
]


# ---------------------------------------------------------------------------
# Handles
# ---------------------------------------------------------------------------

# RLock: materializing a deferred op dispatches the real op under the
# lock, and that dispatch re-enters _register_handle on the same thread
_handle_lock = threading.RLock()
_handle_map: Dict[int, Tuple[jax.Array, str, int]] = {}
_next_handle = [0]


class _Deferred:
    """A nonblocking op enqueued while the context is suspended.

    Reference parity: ``EnqueueTensorAllreduce`` et al. return a handle
    immediately even while ``bluefog_suspend`` has paused the background
    loop (operations.cc:1392-1400) — only *execution* waits for resume.
    The thunk dispatches the real op on the first ``poll()`` after
    resume or inside ``synchronize()``, so the reference-legal
    single-threaded pattern ``suspend(); h = op_nonblocking(x);
    resume(); wait(h)`` completes here too instead of self-deadlocking
    at the dispatch gate."""

    __slots__ = ("thunk",)

    def __init__(self, thunk):
        self.thunk = thunk


def _suspend_gated(fn):
    """suspend() gate for BLOCKING entry points (barrier, window ops via
    ``_dispatch_win_op``): block BEFORE any tracing/dispatch so a
    suspended context issues no collective traffic at all — the SPMD
    equivalent of the reference pausing its background op loop
    (operations.cc:1392-1400); resume() from another thread releases the
    waiters.  Nonblocking collectives use ``_suspend_deferred`` instead,
    which returns a handle without blocking."""
    @functools.wraps(fn)
    def gated(*args, **kwargs):
        _ctx_mod.ctx().wait_if_suspended()
        return fn(*args, **kwargs)
    return gated


def _suspend_deferred(fn):
    """suspend() gate for ``*_nonblocking`` ops: enqueue-then-defer.

    While suspended, no tracing/dispatch happens — the call is recorded
    as a :class:`_Deferred` and a handle returns immediately (reference
    enqueue semantics).  ``synchronize``/``poll`` perform the dispatch
    once the context is running again."""
    @functools.wraps(fn)
    def gated(*args, **kwargs):
        if not _ctx_mod.ctx().suspended:
            return fn(*args, **kwargs)

        def thunk():
            inner = fn(*args, **kwargs)
            with _handle_lock:
                return _handle_map.pop(inner)

        # silent placeholder (no op/name): the timeline ENQUEUE fires
        # exactly once, at materialize time, from the real registration
        # inside fn — carrying the caller's name however it was passed
        # (positionally or by keyword), so the trace keeps one ENQUEUE +
        # one COMMUNICATE per logical op
        return _register_handle(_Deferred(thunk))
    return gated


def _materialize(handle: int):
    """Dispatch a deferred op exactly once (first waiter wins) and return
    its output.  The dispatch runs under the handle lock — serialized,
    like the reference's single comm thread."""
    with _handle_lock:
        if handle not in _handle_map:
            raise ValueError(f"unknown handle {handle}")
        out, opname, start_tok = _handle_map[handle]
        if isinstance(out, _Deferred):
            # adopt the inner registration's name/start token: its clock
            # starts at dispatch, which is when COMMUNICATE really begins
            out, opname, start_tok = out.thunk()
            _handle_map[handle] = (out, opname, start_tok)
    return out


def _register_handle(output, op: str = "", name: Optional[str] = None) -> int:
    with _handle_lock:
        handle = _next_handle[0]
        _next_handle[0] += 1
        opname = name if name else (f"{op}.noname.{handle}" if op else "")
        start_tok = _tl.op_start_us() if opname else None
        _handle_map[handle] = (output, opname, start_tok)
    if opname:
        # timeline parity (reference timeline activities ENQUEUE_* then
        # COMMUNICATE around the async op, mpi_controller.cc:333,445,510) —
        # COMMUNICATE is emitted as one complete span at synchronize time so
        # polled/abandoned handles never leave an unclosed begin event
        _tl.record_op_phase(opname, f"ENQUEUE_{op.upper()}", "i")
    return handle


def has_handle(handle: int) -> bool:
    """True while ``handle`` is live in the core table (frontends keep
    their per-handle metadata exactly as long as the core keeps the
    handle — e.g. the torch in-place target map)."""
    with _handle_lock:
        return handle in _handle_map


def poll(handle: int) -> bool:
    """True when the nonblocking op behind ``handle`` has completed.

    A handle enqueued under ``suspend()`` polls False until ``resume()``
    (the reference's paused loop hasn't run it); the first poll after
    resume dispatches it."""
    with _handle_lock:
        if handle not in _handle_map:
            raise ValueError(f"unknown handle {handle}")
        out, _, _ = _handle_map[handle]
    if isinstance(out, _Deferred):
        if _ctx_mod.ctx().suspended:
            return False
        out = _materialize(handle)
    ready = jax.tree_util.tree_all(
        jax.tree.map(lambda a: a.is_ready() if hasattr(a, "is_ready") else True, out))
    return bool(ready)


def synchronize(handle: int):
    """Wait for a nonblocking op and return its output.

    A handle enqueued under ``suspend()`` blocks here until ``resume()``
    from another thread, then dispatches — exactly the reference's
    behavior (the paused background loop runs the enqueued op only after
    ``bluefog_resume``)."""
    with _handle_lock:
        if handle not in _handle_map:
            raise ValueError("Cannot find handle to synchronize")
        out = _handle_map[handle][0]
    if isinstance(out, _Deferred):
        _ctx_mod.ctx().wait_if_suspended()
        _materialize(handle)
    with _handle_lock:
        if handle not in _handle_map:
            raise ValueError("Cannot find handle to synchronize")
        out, opname, start_tok = _handle_map.pop(handle)
    result = jax.block_until_ready(out)
    if opname:
        _tl.record_op_span(opname, "COMMUNICATE", start_tok)
    return result


wait = synchronize


# ---------------------------------------------------------------------------
# Sharding helpers
# ---------------------------------------------------------------------------

def rank_sharding() -> NamedSharding:
    return NamedSharding(ctx().mesh, P(ctx().rank_axis))


def to_global(x) -> jax.Array:
    """Place a ``[size, ...]`` array with axis 0 sharded over ranks."""
    x = jnp.asarray(x)
    if x.shape[0] != ctx().size:
        raise ValueError(
            f"global-view arrays carry one slice per rank; expected leading "
            f"dim {ctx().size}, got {x.shape}")
    return jax.device_put(x, rank_sharding())


def from_global(x) -> np.ndarray:
    return np.asarray(x)


def _shardmapped(fn, n_outputs: int = 1, check_vma: bool = True):
    """jit(shard_map(fn)) over the 1-D rank mesh; fn sees the per-rank slice
    (leading axis stripped).  ``check_vma=False`` for bodies whose
    varying-axis types JAX cannot track (pallas interpreter scratch)."""
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(*args):
        def shard_fn(*shards):
            unwrapped = [s[0] for s in shards]
            out = fn(*unwrapped)
            if n_outputs == 1:
                return out[None]
            return tuple(o[None] for o in out)
        return jax.shard_map(
            shard_fn, mesh=cx.mesh,
            in_specs=tuple(spec for _ in args),
            out_specs=spec if n_outputs == 1 else tuple(spec for _ in range(n_outputs)),
            check_vma=check_vma,
        )(*args)

    return jax.jit(wrapper)


@functools.lru_cache(maxsize=256)
def _allreduce_fn(axis, average, mesh_id):
    return _shardmapped(lambda x: C.allreduce(x, axis, average=average))


@functools.lru_cache(maxsize=256)
def _broadcast_fn(axis, root_rank, mesh_id):
    return _shardmapped(lambda x: C.broadcast(x, axis, root_rank))


@functools.lru_cache(maxsize=256)
def _allgather_fn(axis, mesh_id):
    return _shardmapped(lambda x: C.allgather(x, axis))


@functools.lru_cache(maxsize=256)
def _ragged_allgather_fn(axis, counts: Tuple[int, ...], mesh_id):
    """Variable-size allgather (the reference's MPI_Allgatherv path,
    mpi_context.cc:622-700): ranks contribute ``counts[r]`` leading rows.
    One padded exchange + a static row-gather — the ragged structure is
    data-independent, so XLA sees fixed shapes and a single gather."""
    max_k = max(counts)
    idx = np.concatenate([np.arange(c) + r * max_k
                          for r, c in enumerate(counts)]).astype(np.int32)

    def inner(x):
        g = C.allgather(x, axis)              # [n * max_k, ...]
        return jnp.take(g, jnp.asarray(idx), axis=0)

    return _shardmapped(inner)


def _nar_backend() -> str:
    """Neighbor-exchange backend: "xla" (default; chained ppermutes) or
    "pallas" (fused concurrent-RDMA kernel, ops/pallas_kernels.py;
    "pallas_interpret" runs the same kernel on the interpreter for CPU test
    meshes).  Env: BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND."""
    import os
    return os.environ.get("BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND", "xla")


@functools.lru_cache(maxsize=256)
def _neighbor_allreduce_fn(axis, topo: CompiledTopology, mesh_id,
                           backend="xla"):
    if backend.startswith("pallas"):
        from . import pallas_kernels as PK
        interp = backend == "pallas_interpret"
        return _shardmapped(
            lambda x: PK.fused_neighbor_allreduce(x, axis, topo,
                                                  interpret=interp),
            check_vma=False)
    return _shardmapped(lambda x: C.neighbor_allreduce(x, axis, topo))


@functools.lru_cache(maxsize=256)
def _neighbor_allgather_fn(axis, topo: CompiledTopology, mesh_id):
    return _shardmapped(lambda x: C.neighbor_allgather(x, axis, topo))


@functools.lru_cache(maxsize=256)
def _dynamic_nar_fn(axis, sched: DynamicSchedule, mesh_id, backend="xla"):
    cx = ctx()
    spec = P(cx.rank_axis)
    pallas = backend.startswith("pallas")
    interp = backend == "pallas_interpret"

    def wrapper(x, step):
        def shard_fn(xs, step_s):
            if pallas:
                from . import pallas_kernels as PK
                return PK.fused_dynamic_neighbor_allreduce(
                    xs[0], axis, sched, step_s, interpret=interp)[None]
            return C.dynamic_neighbor_allreduce(xs[0], axis, sched, step_s)[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh, in_specs=(spec, P()), out_specs=spec,
            check_vma=not pallas,
        )(x, step)
    return jax.jit(wrapper)


@functools.lru_cache(maxsize=256)
def _sparse_matrix_fn(axis, size, offsets: Tuple[int, ...],
                      sender_side: bool, mesh_id):
    """Per-call weight matrices with a cached sparsity structure: the
    offsets are static (K ppermutes), the weight tables are traced data —
    same-structure calls never recompile and never all-gather."""
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(x, self_w, weights):
        def shard_fn(xs, sw, w):
            return C.offset_weighted_neighbor_allreduce(
                xs[0], axis, size, offsets, sw, w,
                sender_side=sender_side)[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh, in_specs=(spec, P(), P()), out_specs=spec,
        )(x, self_w, weights)
    return jax.jit(wrapper)


def _matrix_structure(W: np.ndarray) -> Tuple[int, ...]:
    srcs, dsts = np.nonzero(W)
    n = W.shape[0]
    return tuple(sorted({int((d - s) % n)
                         for s, d in zip(srcs, dsts) if s != d}))


def _matrix_weight_tables(W: np.ndarray, offsets: Tuple[int, ...],
                          sender_side: bool):
    """[K, N] weight table for the circulant execution of matrix W."""
    n = W.shape[0]
    ranks = np.arange(n)
    tables = np.zeros((len(offsets), n))
    for k, off in enumerate(offsets):
        if sender_side:
            tables[k] = W[ranks, (ranks + off) % n]   # i's scale toward i+off
        else:
            tables[k] = W[(ranks - off) % n, ranks]   # j's scale for j-off
    return np.diag(W).copy(), tables


@functools.lru_cache(maxsize=256)
def _matrix_mix_fn(axis, mesh_id):
    """Generic traced-matrix mixing: out_j = sum_i W[i, j] x_i.

    All-gather based; used for arbitrary one-step dynamic weight matrices
    where no precompiled schedule exists.  O(N) bandwidth but always one
    compilation per shape.
    """
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(x, W):
        def shard_fn(xs, Ws):
            gathered = C.allgather(xs, axis)       # [N, ...]
            col = Ws[:, jax.lax.axis_index(axis)]  # [N]; P() spec: W unsliced
            return jnp.tensordot(col.astype(xs.dtype), gathered, axes=1)[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh, in_specs=(spec, P()), out_specs=spec,
        )(x, W)
    return jax.jit(wrapper)


@functools.lru_cache(maxsize=256)
def _pair_gossip_fn(axis, pairs, self_weight, pair_weight, mesh_id):
    return _shardmapped(
        lambda x: C.pair_gossip(x, axis, pairs, self_weight, pair_weight))


def _mesh_id():
    return id(ctx().mesh)


# ---------------------------------------------------------------------------
# Weights override (resilience hook)
# ---------------------------------------------------------------------------

# When set, default-topology neighbor_allreduce calls mix with this [N, N]
# matrix instead of the registered topology's weights.  The matrix rides the
# generic traced-matrix program (_matrix_mix_fn) as DATA, so a resilience
# layer can swap in a freshly repaired matrix every step — arbitrary
# sparsity changes included — without a single recompilation and without
# touching any call site.  Explicit weight_matrix=/sched= arguments beat the
# override (the caller asked for something specific).
_weights_override = [None]


def set_weights_override(W) -> Optional[jax.Array]:
    """Install an override mixing matrix (or ``None`` to clear); returns
    the previous override.  ``W``: [size, size], BlueFog column convention
    (``W[i, j]`` = weight receiver j applies to i's value)."""
    prev = _weights_override[0]
    if W is None:
        _weights_override[0] = None
        return prev
    W = jnp.asarray(W)
    n = ctx().size
    if W.shape != (n, n):
        raise ValueError(f"weights override must be [{n}, {n}], "
                         f"got {W.shape}")
    _weights_override[0] = W
    return prev


def clear_weights_override() -> None:
    set_weights_override(None)


@contextlib.contextmanager
def weights_override(W):
    """``with bf.weights_override(W_repaired): ...`` — scoped override for
    liveness-aware loops (see ``bluefog_tpu.resilience``)."""
    prev = set_weights_override(W)
    try:
        yield
    finally:
        _weights_override[0] = prev


# ---------------------------------------------------------------------------
# Collective ops (blocking + nonblocking)
# ---------------------------------------------------------------------------

@_suspend_deferred
def allreduce_nonblocking(x, average: bool = True, name: Optional[str] = None,
                          is_hierarchical_local: bool = False) -> int:
    cx = ctx()
    if is_hierarchical_local:
        out = _local_allreduce_fn(cx.machine_axis, cx.local_axis, average,
                                  _mesh_id())(to_global(x))
    else:
        out = _allreduce_fn(cx.rank_axis, average, _mesh_id())(to_global(x))
    return _register_handle(out, "allreduce", name)


def _shardmapped_2d(machine_axis, local_axis, inner):
    """jitted global wrapper over the 2-D (machine, local) mesh: reshape
    the flat [size, ...] global view to [machines, locals, ...], run
    ``inner`` per shard, reshape back.  Shared by the hierarchical ops."""
    cx = ctx()

    def wrapper(x):
        x2 = x.reshape((cx.machine_size, cx.local_size) + x.shape[1:])

        def shard_fn(xs):
            return inner(xs[0, 0])[None, None]
        out = jax.shard_map(
            shard_fn, mesh=cx.mesh_2d,
            in_specs=P(machine_axis, local_axis),
            out_specs=P(machine_axis, local_axis),
        )(x2)
        return out.reshape(x.shape)
    return jax.jit(wrapper)


@functools.lru_cache(maxsize=64)
def _local_allreduce_fn(machine_axis, local_axis, average, mesh_id):
    return _shardmapped_2d(
        machine_axis, local_axis,
        lambda xs: C.hierarchical_local_allreduce(xs, local_axis,
                                                  average=average))


def allreduce(x, average: bool = True, name: Optional[str] = None,
              is_hierarchical_local: bool = False):
    """Global allreduce of the per-rank slices (mpi_ops.py:108-212).

    ``is_hierarchical_local=True`` reduces within each machine's local
    ranks only (reference allreduce's hierarchical-local mode,
    torch/mpi_ops.py:94-109): rank slices become their machine-local
    mean/sum, machines stay independent."""
    return synchronize(allreduce_nonblocking(x, average, name,
                                             is_hierarchical_local))


allreduce_ = allreduce
allreduce_nonblocking_ = allreduce_nonblocking


@_suspend_deferred
def broadcast_nonblocking(x, root_rank: int, name: Optional[str] = None) -> int:
    cx = ctx()
    out = _broadcast_fn(cx.rank_axis, int(root_rank), _mesh_id())(to_global(x))
    return _register_handle(out, "broadcast", name)


def broadcast(x, root_rank: int, name: Optional[str] = None):
    """Replicate rank ``root_rank``'s slice to all ranks (mpi_ops.py:227-319)."""
    return synchronize(broadcast_nonblocking(x, root_rank, name))


broadcast_ = broadcast
broadcast_nonblocking_ = broadcast_nonblocking


def _stack_ragged(x) -> Tuple[jax.Array, Tuple[int, ...]]:
    """List of per-rank arrays with differing first dims -> zero-padded
    global stack [size, max_k, ...] + the static per-rank row counts."""
    cx = ctx()
    if len(x) != cx.size:
        raise ValueError(
            f"ragged input must list one array per rank ({cx.size}), "
            f"got {len(x)}")
    arrs = [jnp.asarray(a) for a in x]
    trail = arrs[0].shape[1:]
    dtype = arrs[0].dtype
    for i, a in enumerate(arrs):
        if a.shape[1:] != trail or a.dtype != dtype:
            raise ValueError(
                f"rank {i} slice has shape {a.shape} / dtype {a.dtype}; all "
                f"slices must share trailing dims {trail} and dtype {dtype}")
    counts = tuple(int(a.shape[0]) for a in arrs)
    max_k = max(counts)
    padded = jnp.stack([
        jnp.pad(a, [(0, max_k - a.shape[0])] + [(0, 0)] * (a.ndim - 1))
        for a in arrs])
    return padded, counts


@_suspend_deferred
def allgather_nonblocking(x, name: Optional[str] = None) -> int:
    if isinstance(x, (list, tuple)):
        padded, counts = _stack_ragged(x)
        out = _ragged_allgather_fn(ctx().rank_axis, counts, _mesh_id())(padded)
    else:
        out = _allgather_fn(ctx().rank_axis, _mesh_id())(to_global(x))
    return _register_handle(out, "allgather", name)


def allgather(x, name: Optional[str] = None):
    """Concatenate all ranks' slices along their first dim: the result's
    slice for every rank is ``concat_i x[i]`` (mpi_ops.py:334-373).

    Variable-size form (the reference's allgatherv,
    ``test_allgather_variable_size``): pass a LIST of per-rank arrays whose
    first dims differ; the global result is ``[size, sum(counts), ...]`` —
    every rank's slice is the exact ragged concatenation, no padding
    visible to the caller."""
    return synchronize(allgather_nonblocking(x, name))


@_suspend_deferred
def neighbor_allreduce_nonblocking(
        x, *,
        self_weight: Optional[float] = None,
        weight_matrix: Optional[np.ndarray] = None,
        dst_weighted: bool = False,
        dst_weight_matrix: Optional[np.ndarray] = None,
        sched: Optional[DynamicSchedule] = None,
        step: Optional[int] = None,
        name: Optional[str] = None) -> int:
    cx = ctx()
    xg = to_global(x)
    if self_weight is not None:
        # Reference per-call self_weight (torch/mpi_ops.py:475-645): each
        # rank keeps `s` of its own value and distributes 1-s across its
        # in-neighbors proportionally to their topology weights.  Ranks
        # with no in-neighbors keep weight 1 (nowhere to hand mass to).
        # Realized as a weight matrix so it rides the cached sparse-
        # ppermute path.  (Declared-but-ignored before r5 — a silent
        # default-topology fallback.)
        if (weight_matrix is not None or sched is not None
                or dst_weight_matrix is not None or dst_weighted):
            raise ValueError(
                "self_weight composes with the context topology only; for "
                "full per-edge control (including sender-side dst "
                "weighting) encode it in weight_matrix / dst_weight_matrix "
                "directly")
        s = float(self_weight)
        if not 0.0 <= s <= 1.0:
            raise ValueError(f"self_weight must be in [0, 1], got {s}")
        W = np.asarray(cx.compiled_topology.weight_matrix, np.float64).copy()
        np.fill_diagonal(W, 0.0)
        col_off = W.sum(axis=0)              # mass each receiver takes in
        scale = np.divide(1.0 - s, col_off, where=col_off > 0,
                          out=np.zeros_like(col_off))
        W *= scale[None, :]                  # column j = receiver j's weights
        np.fill_diagonal(W, np.where(col_off > 0, s, 1.0))
        weight_matrix = W
    if dst_weight_matrix is not None and sched is None:
        raise ValueError(
            "dst_weight_matrix requires a dynamic schedule (sched=...); "
            "for a static per-call matrix use weight_matrix=W with "
            "dst_weighted=True")
    if sched is not None:
        if dst_weight_matrix is not None:
            # per-call sender-side weights over the schedule's fixed offset
            # superset: structure cached once, this step's weights are data.
            # D fully determines the mixing, so `step` is not consulted —
            # the caller derives D from the step's live edges (reference
            # per-call dst_weights, torch/mpi_ops.py:475-645)
            D = np.asarray(dst_weight_matrix, np.float64)
            if D.shape != (cx.size, cx.size):
                raise ValueError(
                    f"dst_weight_matrix must be [{cx.size}, {cx.size}], "
                    f"got {D.shape}")
            extra = set(_matrix_structure(D)) - set(sched.offsets)
            if extra:
                raise ValueError(
                    f"dst_weight_matrix uses ring offsets {sorted(extra)} "
                    f"absent from the schedule's superset {sched.offsets}")
            self_w, send_w = _matrix_weight_tables(D, sched.offsets,
                                                   sender_side=True)
            out = _sparse_matrix_fn(cx.rank_axis, cx.size, sched.offsets,
                                    True, _mesh_id())(
                xg, jnp.asarray(self_w), jnp.asarray(send_w))
        else:
            if step is None:
                raise ValueError("dynamic schedule requires a step index")
            out = _dynamic_nar_fn(cx.rank_axis, sched, _mesh_id(),
                                  _nar_backend())(
                xg, jnp.asarray(step, jnp.int32))
    elif weight_matrix is not None:
        W = np.asarray(weight_matrix, np.float64)
        if W.shape != (cx.size, cx.size):
            raise ValueError(
                f"weight_matrix must be [{cx.size}, {cx.size}], got {W.shape}")
        offsets = _matrix_structure(W)
        if len(offsets) < cx.size - 1:
            # sparse: K cached ppermutes, weights as data (no allgather)
            self_w, tables = _matrix_weight_tables(W, offsets, dst_weighted)
            out = _sparse_matrix_fn(cx.rank_axis, cx.size, offsets,
                                    dst_weighted, _mesh_id())(
                xg, jnp.asarray(self_w), jnp.asarray(tables))
        else:
            # dense: one allgather mix is cheaper than N-1 permutes
            out = _matrix_mix_fn(cx.rank_axis, _mesh_id())(
                xg, jnp.asarray(W))
    elif _weights_override[0] is not None:
        # resilience hook: mix with the override matrix as traced data —
        # per-step repaired matrices never recompile (sparsity changes
        # included; the dense-mix program is structure-independent)
        out = _matrix_mix_fn(cx.rank_axis, _mesh_id())(
            xg, _weights_override[0])
    else:
        topo = cx.compiled_topology
        out = _neighbor_allreduce_fn(cx.rank_axis, topo, _mesh_id(),
                                     _nar_backend())(xg)
    return _register_handle(out, "neighbor_allreduce", name)


def neighbor_allreduce(x, **kwargs):
    """Weighted neighbor average — the hot op (mpi_ops.py:475-645).

    Modes:
      * default: the context topology's weights (or uniform 1/(deg+1) when
        ``bf.init(is_weighted=False)``, the reference default).
      * ``weight_matrix=W``: arbitrary one-step mixing matrix (covers the
        reference's per-call ``self_weight/src_weights/dst_weights`` — any
        per-rank weighting is a row/column of W).  Sparse matrices compile
        to K cached ppermutes with the weights as data (same-structure calls
        never recompile); dense matrices fall back to one allgather mix.
        ``dst_weighted=True`` applies the weights on the sender side (the
        reference's dst-weighted path, mpi_controller.cc:1444-1446) —
        numerically identical, exercised as its own program.
      * ``sched=..., step=i``: precompiled dynamic schedule; the step index
        is data, so per-step topology hops never recompile.  With
        ``dst_weight_matrix=D``, senders scale per-destination before the
        exchange (dynamic dst-weighting, torch/mpi_ops.py:475-645).
        ``BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND=pallas`` routes the schedule
        through the fused concurrent-RDMA kernel
        (``ops.pallas_kernels.fused_dynamic_neighbor_allreduce``).
      * under ``set_weights_override(W)`` / ``weights_override(W)`` the
        default mode mixes with the override matrix instead (traced data:
        per-step repaired matrices from ``bluefog_tpu.resilience`` swap in
        with zero recompilation); explicit arguments beat the override.
    """
    return synchronize(neighbor_allreduce_nonblocking(x, **kwargs))


@functools.lru_cache(maxsize=256)
def _dynamic_nag_fn(axis, size, offsets: Tuple[int, ...], out_rows: int,
                    mesh_id):
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(x, slots):
        def shard_fn(xs, sl):
            return C.dynamic_neighbor_allgather(
                xs[0], axis, size, offsets, sl, out_rows)[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh, in_specs=(spec, P()), out_specs=spec,
        )(x, slots)
    return jax.jit(wrapper)


def _edge_matrix_from_ranks(size: int, src_ranks, dst_ranks) -> np.ndarray:
    """Adjacency A[s, d] from per-rank neighbor lists; validates that the
    two views describe the same edge set when both are given (the
    reference's CheckNeighborSendRecvPattern, mpi_controller.cc:364-399)."""
    A_src = A_dst = None
    if src_ranks is not None:
        if len(src_ranks) != size:
            raise ValueError(
                f"src_ranks is the global view: one in-neighbor list per "
                f"rank (length {size}), got {len(src_ranks)}")
        A_src = np.zeros((size, size), dtype=bool)
        for d, srcs in enumerate(src_ranks):
            for s in srcs:
                if s == d:
                    raise ValueError("self rank cannot be a neighbor")
                A_src[s, d] = True
    if dst_ranks is not None:
        if len(dst_ranks) != size:
            raise ValueError(
                f"dst_ranks is the global view: one out-neighbor list per "
                f"rank (length {size}), got {len(dst_ranks)}")
        A_dst = np.zeros((size, size), dtype=bool)
        for s, dsts in enumerate(dst_ranks):
            for d in dsts:
                if s == d:
                    raise ValueError("self rank cannot be a neighbor")
                A_dst[s, d] = True
    if A_src is not None and A_dst is not None:
        if not np.array_equal(A_src, A_dst):
            raise ValueError(
                "src_ranks and dst_ranks describe different edge sets "
                "(reference topo-check parity, mpi_controller.cc:364-399)")
    A = A_src if A_src is not None else A_dst
    if A is None:
        raise ValueError("pass src_ranks and/or dst_ranks")
    return A


def _edge_slots(A: np.ndarray, offsets: Tuple[int, ...], out_rows: int):
    """[K, N] output-row table for adjacency A (sorted ascending sources;
    out_rows = drop sentinel for absent edges)."""
    n = A.shape[0]
    slots = np.full((len(offsets), n), out_rows, dtype=np.int32)
    sorted_sources = [list(np.nonzero(A[:, d])[0]) for d in range(n)]
    for k, off in enumerate(offsets):
        for d in range(n):
            s = (d - off) % n
            if A[s, d]:
                slots[k, d] = sorted_sources[d].index(s)
    return slots


@_suspend_deferred
def neighbor_allgather_nonblocking(x, name: Optional[str] = None, *,
                                   src_ranks=None, dst_ranks=None,
                                   enable_topo_check: bool = True) -> int:
    cx = ctx()
    if isinstance(x, (list, tuple)):
        # variable-size form (reference
        # test_neighbor_allgather_dynamic_variable_size): pad each rank's
        # slice to the max row count; the slot layout below is already
        # padded, so ragged sizes compose with irregular graphs.  Rank i's
        # slot for source s carries s's true rows first, zeros after.
        x, _ = _stack_ragged(x)
    if src_ranks is not None or dst_ranks is not None:
        A = _edge_matrix_from_ranks(cx.size, src_ranks, dst_ranks)
        if enable_topo_check:
            # reference enable_topo_check (torch/mpi_ops.py:397-472):
            # requested edges must exist in the registered topology —
            # catches a rank list built for a different/updated graph
            T = np.asarray(cx.compiled_topology.weight_matrix) != 0
            bad = [(int(s), int(d)) for s, d in zip(*np.nonzero(A))
                   if not T[s, d]]
            if bad:
                raise ValueError(
                    f"neighbor_allgather: requested edges {bad[:8]} are "
                    f"not in the registered topology (pass "
                    f"enable_topo_check=False for off-topology exchanges)")
        srcs, dsts = np.nonzero(A)
        offsets = tuple(sorted({int((d - s) % cx.size)
                                for s, d in zip(srcs, dsts)}))
        out_rows = int(A.sum(axis=0).max(initial=0))
        slots = _edge_slots(A, offsets, out_rows)
        out = _dynamic_nag_fn(cx.rank_axis, cx.size, offsets, out_rows,
                              _mesh_id())(to_global(x), jnp.asarray(slots))
    else:
        topo = cx.compiled_topology
        out = _neighbor_allgather_fn(cx.rank_axis, topo, _mesh_id())(
            to_global(x))
    return _register_handle(out, "neighbor_allgather", name)


def neighbor_allgather(x, name: Optional[str] = None, *,
                       src_ranks=None, dst_ranks=None,
                       enable_topo_check: bool = True):
    """Gather in-neighbor slices, ordered by ascending source rank
    (mpi_ops.py:397-472).  Global result shape: [size, max_in_degree, ...];
    on irregular graphs (allgatherv semantics, mpi_context.cc:622-700) rank
    i's valid rows are the first ``in_degree(i)`` and padding rows are zero.

    ``src_ranks``/``dst_ranks`` select a per-call edge set (the reference's
    dynamic neighbor_allgather) as global per-rank neighbor lists; when both
    are given they are cross-checked like the reference's topology check.
    Same-structure calls reuse one compiled program.
    """
    return synchronize(neighbor_allgather_nonblocking(
        x, name, src_ranks=src_ranks, dst_ranks=dst_ranks,
        enable_topo_check=enable_topo_check))


@_suspend_deferred
def hierarchical_neighbor_allreduce_nonblocking(
        x, name: Optional[str] = None) -> int:
    cx = ctx()
    mtopo = cx.compiled_machine_topology
    xg = jnp.asarray(x)
    if xg.shape[0] != cx.size:
        raise ValueError(f"expected leading dim {cx.size}, got {xg.shape}")
    fn = _hier_fn(cx.machine_axis, cx.local_axis, mtopo, _mesh_id())
    out = fn(xg)
    return _register_handle(out, "hierarchical_neighbor_allreduce", name)


@functools.lru_cache(maxsize=64)
def _hier_fn(machine_axis, local_axis, mtopo, mesh_id):
    return _shardmapped_2d(
        machine_axis, local_axis,
        lambda xs: C.hierarchical_neighbor_allreduce(
            xs, machine_axis, local_axis, mtopo))


def hierarchical_neighbor_allreduce(x, name: Optional[str] = None):
    """Machine-level neighbor average: intra-machine mean, then the machine
    topology's weighted exchange, replicated locally (mpi_ops.py:648-838)."""
    return synchronize(hierarchical_neighbor_allreduce_nonblocking(x, name))


@_suspend_deferred
def pair_gossip_nonblocking(x, pairs: Sequence[Tuple[int, int]],
                            self_weight: Optional[float] = None,
                            pair_weight: Optional[float] = None,
                            name: Optional[str] = None) -> int:
    if (self_weight is None) != (pair_weight is None):
        raise ValueError("self_weight and pair_weight have to be set at same time.")
    if self_weight is None:
        self_weight, pair_weight = 0.5, 0.5
    out = _pair_gossip_fn(ctx().rank_axis, tuple(map(tuple, pairs)),
                          float(self_weight), float(pair_weight),
                          _mesh_id())(to_global(x))
    return _register_handle(out, "pair_gossip", name)


def pair_gossip(x, pairs, self_weight=None, pair_weight=None, name=None):
    """Pairwise (weighted) averaging over a matching of ranks
    (mpi_ops.py:852-928; ``pairs`` is the global matching instead of the
    per-process ``target_rank``)."""
    return synchronize(pair_gossip_nonblocking(x, pairs, self_weight,
                                               pair_weight, name))


@_suspend_gated
def barrier():
    """Synchronize: all outstanding device work completes (mpi_ops.py:980)."""
    cx = ctx()
    fn = _allreduce_fn(cx.rank_axis, False, _mesh_id())
    jax.block_until_ready(fn(to_global(jnp.ones((cx.size, 1)))))
