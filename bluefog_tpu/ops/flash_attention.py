"""Pallas TPU flash attention: the per-chip hot op of the LM family.

Blockwise online-softmax attention with the score matrix never materialized
in HBM — the standard flash recipe mapped to TPU:

* **Forward**: grid ``(batch*heads, q_blocks, k_blocks)`` with the K axis
  innermost (sequential on TPU), so K/V stream through VMEM one
  ``block_k``-sized tile at a time (long contexts never blow up VMEM).
  Running max / denominator / accumulator live in VMEM scratch across the
  K iterations; the normalized output and the log-sum-exp (LSE) row
  statistics are flushed on the last K step.  Causal key blocks entirely
  above the diagonal are predicated off with ``pl.when``.
* **Backward**: two Pallas kernels recompute the probabilities from the
  saved LSE (no score residuals): a dQ kernel on grid ``(BH, q, k)`` and a
  dK/dV kernel on grid ``(BH, k, q)``, both streaming the non-resident
  operand blockwise and accumulating in VMEM scratch — the flash backward
  recipe, not a fallback to O(T²) reference attention.

``q_offset`` / ``k_offset`` shift the global positions and may be *traced*
values (they ride in as scalar-prefetch arguments), which makes the kernel
usable both standalone (full attention) and as the per-hop block compute of
ring attention (ops/ring_attention.py) where each hop's KV block starts at a
rank-dependent global position.

The trainable entry point also exposes the LSE and accepts its cotangent
(``ds += p * g_lse`` folds into the same kernels), which ring attention
needs to differentiate through its cross-hop merge.

Use ``interpret=True`` on CPU test meshes (Pallas interpreter).

Reference parity note: the reference has no attention op at all (SURVEY.md
§5.7); this kernel exists because long-context is first-class in the TPU
build.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_trainable",
           "flash_attention_with_lse", "best_attention",
           "merge_attention_partials", "flash_supported"]

_NEG_INF = -1e30
_LANES = 128
# Row statistics (LSE, dl) are stored with a trailing lane dim so their
# blocks satisfy the TPU tiling rule (a block's last two dims must divide
# (8, 128) or equal the array's): [BH, Tq] would give blocks (1, block_q)
# whose second-to-last dim 1 is illegal on hardware.  128 lanes matches
# the native lane width (narrower arrays degrade into per-row strided
# DMAs); the value is broadcast across lanes on write, lane 0 read back.
_STAT_LANES = 128


def _interp(flag):
    # The TPU-simulating interpreter (the only one that supports these
    # kernels under shard_map — the generic HLO interpreter trips
    # varying-manual-axes checks).  NOTE its shared-memory/DMA simulation
    # cost explodes when per-shard sequence blocks exceed one sublane
    # tile on multi-device meshes; keep interpret-mode tests at
    # 8-row-per-shard shapes (see tests/test_ring_attention.py).
    return pltpu.InterpretParams() if flag else False


# batch*heads and the non-accumulating block axis are parallel; the
# innermost axis accumulates into VMEM scratch and must stay sequential.
# Without this Mosaic treats the whole grid as sequential and the many
# small instances become DMA-issue-latency-bound.
_DIMS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


from ._pallas_util import out_struct as _out_struct  # noqa: E402


# ---------------------------------------------------------------------------
# forward
# ---------------------------------------------------------------------------

def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full_like(m_scr, _NEG_INF)
        l_scr[...] = jnp.zeros_like(l_scr)
        acc_scr[...] = jnp.zeros_like(acc_scr)

    q_offset, k_offset = off_ref[0], off_ref[1]
    row0 = q_offset + qi * block_q          # global position of first q row
    col0 = k_offset + kj * block_k          # global position of first k col

    def compute():
        q = q_ref[0].astype(jnp.float32) * scale             # [bq, D]
        k = k_ref[0].astype(jnp.float32)                     # [bk, D]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # [bq, bk]
        if causal:
            rows = row0 + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            cols = col0 + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_prev = m_scr[...]                                  # [bq, LANES]
        l_prev = l_scr[...]
        m_cur = jnp.max(s, axis=-1)[:, None]                 # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.broadcast_to(
            m_cur, m_prev.shape))
        corr = jnp.exp(m_prev - m_new)                       # [bq, LANES]
        p = jnp.exp(s - m_new[:, :1])                        # [bq, bk]
        l_new = l_prev * corr + jnp.broadcast_to(
            p.sum(axis=-1)[:, None], l_prev.shape)
        acc_scr[...] = acc_scr[...] * corr[:, :1] + lax.dot_general(
            p, v_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_scr[...] = m_new
        l_scr[...] = l_new

    if causal:
        # skip key blocks entirely above the diagonal
        pl.when(col0 <= row0 + block_q - 1)(compute)
    else:
        compute()

    @pl.when(kj == nk - 1)
    def _flush():
        m = m_scr[:, 0]
        l = l_scr[:, 0]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_scr[...] / l_safe[:, None]).astype(o_ref.dtype)
        lse = jnp.where(l == 0.0, _NEG_INF, m + jnp.log(l_safe))
        lse_ref[0] = jnp.broadcast_to(lse[:, None], lse_ref.shape[1:])


def _fwd(qh, kh, vh, offsets, *, scale, causal, block_q, block_k,
         out_dtype, interpret):
    """qh/kh/vh: [BH, T, D] heads-major. Returns (o [BH,Tq,D], lse [BH,Tq])."""
    BH, Tq, D = qh.shape
    Tk = kh.shape[1]
    nq, nk = Tq // block_q, Tk // block_k
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k)
    o, lse = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j, off: (b, i, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j, off: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, i, j, off: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, j, off: (b, i, 0)),
                pl.BlockSpec((1, block_q, _STAT_LANES),
                             lambda b, i, j, off: (b, i, 0)),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, _LANES), jnp.float32),
                pltpu.VMEM((block_q, D), jnp.float32),
            ],
        ),
        out_shape=[
            _out_struct((BH, Tq, D), out_dtype, qh, kh, vh, offsets),
            _out_struct((BH, Tq, _STAT_LANES), jnp.float32,
                        qh, kh, vh, offsets),
        ],
        compiler_params=_DIMS,
        interpret=_interp(interpret),
    )(offsets, qh, kh, vh)
    return o, lse[..., 0]


# ---------------------------------------------------------------------------
# backward
# ---------------------------------------------------------------------------

def _p_block(q_ref, k_ref, lse_ref, *, scale, causal, row0, col0,
             block_q, block_k):
    """Recompute the probability block p = exp(s*scale - lse), masked."""
    q = q_ref[0].astype(jnp.float32) * scale
    k = k_ref[0].astype(jnp.float32)
    s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                        preferred_element_type=jnp.float32)   # [bq, bk]
    p = jnp.exp(s - lse_ref[0, :, 0][:, None])
    if causal:
        rows = row0 + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = col0 + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        p = jnp.where(cols <= rows, p, 0.0)
    return p


def _bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                   dq_ref, dq_scr, *, scale, causal, block_q, block_k):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros_like(dq_scr)

    q_offset, k_offset = off_ref[0], off_ref[1]
    row0 = q_offset + qi * block_q
    col0 = k_offset + kj * block_k

    def compute():
        p = _p_block(q_ref, k_ref, lse_ref, scale=scale, causal=causal,
                     row0=row0, col0=col0, block_q=block_q, block_k=block_k)
        do = do_ref[0].astype(jnp.float32)                    # [bq, D]
        v = v_ref[0].astype(jnp.float32)                      # [bk, D]
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - dl_ref[0, :, 0][:, None]) * scale
        dq_scr[...] += lax.dot_general(
            ds, k_ref[0].astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(col0 <= row0 + block_q - 1)(compute)
    else:
        compute()

    @pl.when(kj == nk - 1)
    def _flush():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dl_ref,
                    dk_ref, dv_ref, dk_scr, dv_scr, *, scale, causal,
                    block_q, block_k):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros_like(dk_scr)
        dv_scr[...] = jnp.zeros_like(dv_scr)

    q_offset, k_offset = off_ref[0], off_ref[1]
    row0 = q_offset + qi * block_q
    col0 = k_offset + kj * block_k

    def compute():
        p = _p_block(q_ref, k_ref, lse_ref, scale=scale, causal=causal,
                     row0=row0, col0=col0, block_q=block_q, block_k=block_k)
        do = do_ref[0].astype(jnp.float32)                    # [bq, D]
        dv_scr[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]
        v = v_ref[0].astype(jnp.float32)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)  # [bq, bk]
        ds = p * (dp - dl_ref[0, :, 0][:, None]) * scale      # [bq, bk]
        dk_scr[...] += lax.dot_general(
            ds, q_ref[0].astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [bk, D]

    if causal:
        # this k block receives gradient only from q rows at/below it
        pl.when(row0 + block_q - 1 >= col0)(compute)
    else:
        compute()

    @pl.when(qi == nq - 1)
    def _flush():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


def _bwd(qh, kh, vh, doh, lse, dl, offsets, *, scale, causal,
         block_q, block_k, interpret):
    """Heads-major backward.  ``dl`` = rowsum(do*o) - g_lse, [BH, Tq]."""
    BH, Tq, D = qh.shape
    Tk = kh.shape[1]
    nq, nk = Tq // block_q, Tk // block_k

    # row stats enter with the trailing lane dim (see _STAT_LANES)
    lse = jnp.broadcast_to(lse[..., None], lse.shape + (_STAT_LANES,))
    dl = jnp.broadcast_to(dl[..., None], dl.shape + (_STAT_LANES,))

    row_specs = dict(
        q=pl.BlockSpec((1, block_q, D), lambda b, i, j, off: (b, i, 0)),
        k=pl.BlockSpec((1, block_k, D), lambda b, i, j, off: (b, j, 0)),
        vec=pl.BlockSpec((1, block_q, _STAT_LANES),
                         lambda b, i, j, off: (b, i, 0)),
    )
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nq, nk),
            in_specs=[row_specs["q"], row_specs["k"], row_specs["k"],
                      row_specs["q"], row_specs["vec"], row_specs["vec"]],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda b, i, j, off: (b, i, 0)),
            scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        ),
        out_shape=_out_struct((BH, Tq, D), qh.dtype,
                              qh, kh, vh, doh, lse, dl, offsets),
        compiler_params=_DIMS,
        interpret=_interp(interpret),
    )(offsets, qh, kh, vh, doh, lse, dl)

    # dK/dV grid: k blocks outer, q blocks inner (swap the index maps)
    kv_specs = dict(
        q=pl.BlockSpec((1, block_q, D), lambda b, j, i, off: (b, i, 0)),
        k=pl.BlockSpec((1, block_k, D), lambda b, j, i, off: (b, j, 0)),
        vec=pl.BlockSpec((1, block_q, _STAT_LANES),
                         lambda b, j, i, off: (b, i, 0)),
    )
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(BH, nk, nq),
            in_specs=[kv_specs["q"], kv_specs["k"], kv_specs["k"],
                      kv_specs["q"], kv_specs["vec"], kv_specs["vec"]],
            out_specs=[
                pl.BlockSpec((1, block_k, D), lambda b, j, i, off: (b, j, 0)),
                pl.BlockSpec((1, block_k, D), lambda b, j, i, off: (b, j, 0)),
            ],
            scratch_shapes=[pltpu.VMEM((block_k, D), jnp.float32),
                            pltpu.VMEM((block_k, D), jnp.float32)],
        ),
        out_shape=[_out_struct((BH, Tk, D), kh.dtype,
                               qh, kh, vh, doh, lse, dl, offsets),
                   _out_struct((BH, Tk, D), vh.dtype,
                               qh, kh, vh, doh, lse, dl, offsets)],
        compiler_params=_DIMS,
        interpret=_interp(interpret),
    )(offsets, qh, kh, vh, doh, lse, dl)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# public API
# ---------------------------------------------------------------------------

def _to_heads_major(x):
    B, T, H, D = x.shape
    return x.transpose(0, 2, 1, 3).reshape(B * H, T, D)


def _from_heads_major(x, B, H):
    BH, T, D = x.shape
    return x.reshape(B, H, T, D).transpose(0, 2, 1, 3)


def _fit_block(T, block):
    """Largest power-of-two shrink of ``block`` that divides ``T`` (so the
    512-default still serves 128-granular sequence lengths like 768).
    Stops at 8 — the TPU sublane minimum — leaving non-8-granular lengths
    to the divisibility error below."""
    block = min(block, T)
    while block > 8 and T % block:
        block //= 2
    return block


def _check_blocks(Tq, Tk, block_q, block_k):
    block_q, block_k = _fit_block(Tq, block_q), _fit_block(Tk, block_k)
    if Tq % block_q or Tk % block_k:
        raise ValueError(
            f"sequence lengths ({Tq}, {Tk}) must be divisible by the block "
            f"sizes ({block_q}, {block_k})")
    # a PARTIAL block (block < T) must be sublane-aligned; a whole-length
    # block rides the 'block dim == array dim' tiling exemption instead
    for blk, T, name in ((block_q, Tq, "block_q"), (block_k, Tk, "block_k")):
        if blk < T and blk % 8:
            raise ValueError(
                f"{name}={blk} tiles a longer sequence ({T}) and must be a "
                f"multiple of 8 (TPU sublane)")
    return block_q, block_k


def _expand_kv_groups(q, k, v):
    """Grouped/multi-query attention at the wrapper level: ``k``/``v`` may
    carry fewer heads than ``q`` (H_kv dividing H; H_kv=1 = MQA).  The
    kv heads are repeated to H before the kernel — the silicon-validated
    MHA kernel is untouched (a kv-head-deduplicating index map is a
    future kernel optimization; the repeat costs HBM only for the
    expanded K/V reads, the score matrix still never materializes)."""
    H, H_kv = q.shape[2], k.shape[2]
    if H_kv == H:
        return k, v
    if H % H_kv != 0:
        raise ValueError(
            f"q heads ({H}) must be a multiple of kv heads ({H_kv})")
    g = H // H_kv
    return jnp.repeat(k, g, axis=2), jnp.repeat(v, g, axis=2)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k",
                              "interpret", "return_lse"))
def flash_attention(q, k, v, *, causal: bool = False,
                    q_offset=0, k_offset=0,
                    scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 512,
                    interpret: bool = False, return_lse: bool = False):
    """Flash attention forward.  ``q``: [B, Tq, H, D]; ``k``/``v``:
    [B, Tk, H, D].  ``q_offset``/``k_offset`` may be traced scalars.

    With ``return_lse=True`` also returns the per-row log-sum-exp
    [B, H, Tq] (float32), the statistic ring attention's cross-hop merge
    needs.  ``k``/``v`` may carry fewer heads (GQA/MQA; any divisor of
    H)."""
    k, v = _expand_kv_groups(q, k, v)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale_ = scale if scale is not None else D ** -0.5
    block_q, block_k = _check_blocks(Tq, Tk, block_q, block_k)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])
    o, lse = _fwd(_to_heads_major(q), _to_heads_major(k), _to_heads_major(v),
                  offsets, scale=scale_, causal=causal, block_q=block_q,
                  block_k=block_k, out_dtype=q.dtype, interpret=interpret)
    o = _from_heads_major(o, B, H)
    if return_lse:
        return o, lse.reshape(B, H, Tq)
    return o


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _fa_with_lse(q, k, v, offsets, causal, scale, block_q, block_k,
                 interpret):
    """Differentiable (o, lse) core; offsets is a traced int32[2]."""
    B, Tq, H, D = q.shape
    o, lse = _fwd(_to_heads_major(q), _to_heads_major(k), _to_heads_major(v),
                  offsets, scale=scale, causal=causal, block_q=block_q,
                  block_k=block_k, out_dtype=q.dtype, interpret=interpret)
    return _from_heads_major(o, B, H), lse.reshape(B, H, Tq)


def _fa_fwd(q, k, v, offsets, causal, scale, block_q, block_k, interpret):
    out = _fa_with_lse(q, k, v, offsets, causal, scale, block_q, block_k,
                       interpret)
    o, lse = out
    return out, (q, k, v, o, lse, offsets)


def _fa_bwd(causal, scale, block_q, block_k, interpret, res, g):
    q, k, v, o, lse, offsets = res
    g_o, g_lse = g
    B, Tq, H, D = q.shape
    oh = _to_heads_major(o).astype(jnp.float32)
    doh = _to_heads_major(g_o)
    lse_h = lse.reshape(B * H, Tq)
    # dL/ds = p*(dp - delta) + p*g_lse  ->  fold g_lse into the delta term
    dl = (oh * doh.astype(jnp.float32)).sum(-1) - g_lse.reshape(B * H, Tq)
    dq, dk, dv = _bwd(_to_heads_major(q), _to_heads_major(k),
                      _to_heads_major(v), doh, lse_h, dl, offsets,
                      scale=scale, causal=causal, block_q=block_q,
                      block_k=block_k, interpret=interpret)
    d_off = np.zeros((2,), jax.dtypes.float0)  # int operand: zero cotangent
    return (_from_heads_major(dq, B, H), _from_heads_major(dk, B, H),
            _from_heads_major(dv, B, H), d_off)


_fa_with_lse.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_with_lse(q, k, v, *, causal: bool = False,
                             q_offset=0, k_offset=0,
                             scale: Optional[float] = None,
                             block_q: int = 512, block_k: int = 512,
                             interpret: bool = False):
    """Differentiable flash attention returning ``(o, lse)``; the LSE
    cotangent is supported (needed under ring attention's merge).
    ``k``/``v`` may carry fewer heads (GQA/MQA); their gradients come
    back group-summed to the original kv-head count (autodiff of the
    head repeat)."""
    k, v = _expand_kv_groups(q, k, v)
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale_ = scale if scale is not None else D ** -0.5
    block_q, block_k = _check_blocks(Tq, Tk, block_q, block_k)
    offsets = jnp.stack([jnp.asarray(q_offset, jnp.int32),
                         jnp.asarray(k_offset, jnp.int32)])
    return _fa_with_lse(q, k, v, offsets, causal, scale_, block_q, block_k,
                        interpret)


def flash_attention_trainable(q, k, v, *, causal: bool = False,
                              q_offset=0, k_offset=0,
                              scale: Optional[float] = None,
                              block_q: int = 512, block_k: int = 512,
                              interpret: bool = False):
    """Differentiable flash attention: Pallas forward AND Pallas backward
    (dq/dk/dv recomputed blockwise from the saved LSE — O(T) memory both
    ways)."""
    o, _ = flash_attention_with_lse(
        q, k, v, causal=causal, q_offset=q_offset, k_offset=k_offset,
        scale=scale, block_q=block_q, block_k=block_k, interpret=interpret)
    return o


def merge_attention_partials(o1, lse1, o2, lse2):
    """Fold two normalized attention partials (over disjoint key sets) into
    one: ``o = σ w_i/Σw · o_i`` with ``w_i = exp(lse_i - max)``.  Used by
    ring attention to combine per-hop flash results; differentiable XLA
    code (elementwise, negligible cost).  ``o``: [B, T, H, D]; ``lse``:
    [B, H, T]."""
    m = jnp.maximum(lse1, lse2)
    w1 = jnp.exp(lse1 - m)
    w2 = jnp.exp(lse2 - m)
    denom = w1 + w2
    lse = m + jnp.log(denom)
    c1 = (w1 / denom).transpose(0, 2, 1)[..., None]
    c2 = (w2 / denom).transpose(0, 2, 1)[..., None]
    return o1 * c1 + o2 * c2, lse


def flash_supported(q, k, block_q: int = 512, block_k: int = 512) -> bool:
    """True when the shapes tile cleanly and we are on a TPU backend."""
    Tq, Tk = q.shape[1], k.shape[1]
    bq, bk = _fit_block(Tq, block_q), _fit_block(Tk, block_k)
    return (jax.default_backend() == "tpu"
            and Tq % bq == 0 and Tk % bk == 0
            and bq % 8 == 0 and bk % 8 == 0)


def best_attention(q, k, v, *, causal: bool = False, q_offset=0, k_offset=0,
                   scale: Optional[float] = None, interpret: bool = False,
                   force_flash: bool = False):
    """Attention dispatcher: the trainable flash kernel on TPU when the
    shapes tile onto it, the XLA reference path otherwise (CPU test meshes,
    tiny/ragged shapes)."""
    from .ring_attention import attention as _ref
    k, v = _expand_kv_groups(q, k, v)   # GQA/MQA on either path
    if force_flash and not interpret and jax.default_backend() != "tpu":
        raise ValueError(
            "flash attention requires a TPU backend (pass interpret=True "
            "to run the Pallas interpreter on CPU)")
    # interpret=True is an explicit request for the Pallas kernel (under
    # the interpreter) — never silently fall back to the XLA path
    if force_flash or interpret or flash_supported(q, k):
        return flash_attention_trainable(
            q, k, v, causal=causal, q_offset=q_offset, k_offset=k_offset,
            scale=scale, interpret=interpret)
    return _ref(q, k, v, causal=causal, q_offset=q_offset,
                k_offset=k_offset, scale=scale)
