"""Pallas TPU flash attention: the per-chip hot op of the LM family.

Blockwise online-softmax attention computed in VMEM with the score matrix
never materialized in HBM — the standard flash recipe mapped to TPU: grid
over (batch·heads, query blocks), MXU matmuls per (q-block, k-block) tile,
running max / running sum carried in registers through a ``fori_loop`` over
key blocks.  With ``causal=True``, key blocks entirely above the diagonal
are skipped (the loop upper bound is derived from the q-block's last row),
so causal attention does ~half the work.

``q_offset`` / ``k_offset`` shift the global positions, which makes the
kernel usable both standalone (full attention) and as the per-hop block
compute of ring attention (ops/ring_attention.py), where each rank's shard
starts at a nonzero global position.

Use ``interpret=True`` on CPU test meshes (Pallas interpreter).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["flash_attention", "flash_attention_trainable"]

_NEG_INF = -1e30


def _kernel(off_ref, q_ref, k_ref, v_ref, o_ref, *, scale, causal,
            block_k, seq_k):
    qi = pl.program_id(1)
    bq = q_ref.shape[1]
    q = q_ref[0].astype(jnp.float32) * scale            # [bq, D]
    D = q.shape[-1]
    q_offset, k_offset = off_ref[0], off_ref[1]

    nk = pl.cdiv(seq_k, block_k)
    if causal:
        # last key index this q-block may attend to (global positions)
        last_q = q_offset + (qi + 1) * bq - 1
        # number of k blocks with any kj <= last_q
        nk_live = jnp.clip(
            (last_q - k_offset) // block_k + 1, 0, nk).astype(jnp.int32)
    else:
        nk_live = nk

    def body(j, carry):
        m, l, acc = carry
        k_blk = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v_blk = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k_blk, dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)         # [bq, bk]
        if causal:
            rows = q_offset + qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            cols = k_offset + j * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 1)
            s = jnp.where(cols <= rows, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=-1))
        corr = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])
        l_new = l * corr + p.sum(axis=-1)
        acc_new = acc * corr[:, None] + jax.lax.dot_general(
            p, v_blk, dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return m_new, l_new, acc_new

    m0 = jnp.full((bq,), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bq,), jnp.float32)
    a0 = jnp.zeros((bq, D), jnp.float32)
    m, l, acc = lax.fori_loop(0, nk_live, body, (m0, l0, a0))
    l = jnp.where(l == 0.0, 1.0, l)   # fully-masked rows -> 0, not NaN
    o_ref[0] = (acc / l[:, None]).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "scale", "block_q", "block_k",
                              "interpret"))
def flash_attention(q, k, v, *, causal: bool = False,
                    q_offset: int = 0, k_offset: int = 0,
                    scale: Optional[float] = None,
                    block_q: int = 128, block_k: int = 128,
                    interpret: bool = False):
    """Drop-in for ``ops.ring_attention.attention`` computed in one Pallas
    kernel.  ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D]."""
    B, Tq, H, D = q.shape
    Tk = k.shape[1]
    scale_ = scale if scale is not None else D ** -0.5
    block_q = min(block_q, Tq)
    block_k = min(block_k, Tk)
    if Tq % block_q or Tk % block_k:
        raise ValueError(
            f"sequence lengths ({Tq}, {Tk}) must be divisible by the block "
            f"sizes ({block_q}, {block_k})")

    # [B, T, H, D] -> [B*H, T, D] so the grid's leading axis is one
    # (batch, head) pair per program
    qh = q.transpose(0, 2, 1, 3).reshape(B * H, Tq, D)
    kh = k.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)
    vh = v.transpose(0, 2, 1, 3).reshape(B * H, Tk, D)

    kernel = functools.partial(
        _kernel, scale=scale_, causal=causal, block_k=block_k, seq_k=Tk)

    offsets = jnp.asarray([q_offset, k_offset], jnp.int32)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B * H, Tq // block_q),
            in_specs=[
                pl.BlockSpec((1, block_q, D), lambda b, i, off: (b, i, 0)),
                pl.BlockSpec((1, Tk, D), lambda b, i, off: (b, 0, 0)),
                pl.BlockSpec((1, Tk, D), lambda b, i, off: (b, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, block_q, D),
                                   lambda b, i, off: (b, i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, Tq, D), q.dtype),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(offsets, qh, kh, vh)
    return out.reshape(B, H, Tq, D).transpose(0, 2, 1, 3)


def flash_attention_trainable(q, k, v, *, causal: bool = False,
                              q_offset: int = 0, k_offset: int = 0,
                              scale: Optional[float] = None,
                              block_q: int = 128, block_k: int = 128,
                              interpret: bool = False):
    """Differentiable flash attention: Pallas forward, reference backward.

    Pallas kernels have no automatic reverse-mode; rather than ship a
    hand-written (and hard-to-validate) backward kernel, the VJP re-runs
    the mathematically identical reference ``attention`` under ``jax.vjp``.
    The forward pass gets the flash kernel's O(T) memory and fused MXU
    loop; the backward matches the XLA path exactly (and XLA rematerializes
    it from the same q/k/v residuals).
    """
    from .ring_attention import attention as _ref

    kw = dict(causal=causal, q_offset=q_offset, k_offset=k_offset,
              scale=scale)

    @jax.custom_vjp
    def _fa(q, k, v):
        return flash_attention(q, k, v, block_q=block_q, block_k=block_k,
                               interpret=interpret, **kw)

    def fwd(q, k, v):
        return _fa(q, k, v), (q, k, v)

    def bwd(res, g):
        q, k, v = res
        _, vjp = jax.vjp(lambda q_, k_, v_: _ref(q_, k_, v_, **kw), q, k, v)
        return vjp(g)

    _fa.defvjp(fwd, bwd)
    return _fa(q, k, v)
