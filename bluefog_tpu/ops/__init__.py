"""Collective ops: shard_map primitives and the global-view API."""

from . import collectives, api
from .ring_attention import attention, ring_attention, ulysses_attention
from .moe import expert_parallel_ffn, local_moe_ffn, switch_route
from .flash_attention import (flash_attention, flash_attention_trainable,
                              flash_attention_with_lse, best_attention,
                              merge_attention_partials, flash_supported)
