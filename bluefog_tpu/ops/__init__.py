"""Collective ops: shard_map primitives and the global-view API."""

from . import collectives, api
