"""SPMD collective primitives (to be called inside ``shard_map``/``pjit``).

This is the TPU-native replacement for the reference's MPI/NCCL controllers
(``bluefog/common/mpi_controller.cc``, ``nccl_controller.cc``).  There is no
background thread, negotiation, or tensor fusion here: every rank runs the
same jitted program, XLA schedules and fuses the collectives, and "nonblocking"
falls out of JAX's async dispatch (SURVEY.md §1 threading note).

Topologies execute by circulant decomposition (see ``parallel/schedule.py``):
one ``lax.ppermute`` per ring offset with per-rank weights, so a sparse graph
costs only its number of distinct offsets.  Dynamic per-step graphs use fixed
offset supersets with step-indexed weight tables — no recompilation when the
graph changes (reference parity: dynamic neighbor_allreduce,
``bluefog/torch/mpi_ops.py:475-645``).

All functions take ``axis_name`` explicitly and operate on the *per-rank
shard* of data, exactly like ``lax.psum``.
"""

import functools
from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
import numpy as np

from ..parallel.schedule import CompiledTopology, DynamicSchedule

__all__ = [
    "allreduce",
    "broadcast",
    "allgather",
    "barrier_value",
    "neighbor_allreduce",
    "dynamic_neighbor_allreduce",
    "dynamic_neighbor_allreduce_dst_weighted",
    "offset_weighted_neighbor_allreduce",
    "neighbor_allgather",
    "dynamic_neighbor_allgather",
    "pair_gossip",
    "hierarchical_neighbor_allreduce",
    "hierarchical_local_allreduce",
]



def _require_inexact(x, op_name: str):
    dtype = jnp.asarray(x).dtype
    if not jnp.issubdtype(dtype, jnp.inexact):
        raise TypeError(
            f"{op_name} computes fractional weighted averages and requires a "
            f"float dtype, got {dtype}; cast the input first")


@functools.lru_cache(maxsize=4096)
def _rotation_pairs(size: int, offset: int) -> Tuple[Tuple[int, int], ...]:
    """Full-rotation permutation: every rank sends to (rank + offset) % size.

    Cached: every dynamic/offset-weighted collective rebuilds the same
    O(N) tuples per offset on every trace, and the window kernels loop
    over them per offset per leaf — pure-Python retrace overhead that the
    cache removes (the result is immutable)."""
    return tuple((j, (j + offset) % size) for j in range(size))


def allreduce(x, axis_name, *, average: bool = True):
    """Global allreduce (reference: ``MPIController::Allreduce``,
    mpi_controller.cc:169; default op is average, torch/mpi_ops.py:108)."""
    return lax.pmean(x, axis_name) if average else lax.psum(x, axis_name)


def broadcast(x, axis_name, root_rank: int):
    """Every rank ends with ``root_rank``'s value (mpi_controller.cc:193).

    Implemented as a masked psum: contributions from non-root ranks are
    zeroed, which XLA lowers to an efficient broadcast on ICI.
    """
    idx = lax.axis_index(axis_name)
    contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
    return lax.psum(contrib, axis_name)


def allgather(x, axis_name):
    """Concatenate every rank's shard along axis 0 (mpi_controller.cc:136)."""
    return lax.all_gather(x, axis_name, tiled=True)


def barrier_value(axis_name):
    """A scalar whose computation requires all ranks (barrier semantics;
    reference barrier is an allreduce of a byte, torch/mpi_ops.py:980)."""
    return lax.psum(jnp.ones((), jnp.int32), axis_name)


# ---------------------------------------------------------------------------
# Neighbor collectives (static topology)
# ---------------------------------------------------------------------------

def neighbor_allreduce(x, axis_name, topo: CompiledTopology):
    """Weighted neighbor average: ``out_i = W[i,i] x_i + sum_j W[j,i] x_j``.

    The hot op (reference ``MPIController::NeighborAllreduce``,
    mpi_controller.cc:419-517 + averaging callback torch/mpi_ops.cc:99-164).
    One ppermute per circulant offset of the topology; weights are baked into
    the compiled program as constants.
    """
    _require_inexact(x, "neighbor_allreduce")
    idx = lax.axis_index(axis_name)
    self_w = jnp.asarray(topo.self_weights, x.dtype)[idx]
    out = self_w * x
    for shift in topo.shifts:
        received = lax.ppermute(x, axis_name, shift.pairs)
        w = jnp.asarray(shift.recv_weights, x.dtype)[idx]
        out = out + w * received
    return out


@functools.lru_cache(maxsize=512)
def _allgather_slots(topo: CompiledTopology) -> np.ndarray:
    """slots[k, i] = position of offset-k's source in rank i's sorted
    in-neighbor list, or max in_degree (=> dropped) when no such edge.

    Cached per compiled topology (``CompiledTopology`` hashes by identity
    — it is frozen and ``eq=False``): the table is O(N*K) pure-Python
    work re-done on every trace of every gather/window program otherwise.
    Callers treat the returned array as read-only."""
    n = topo.size
    sentinel = int(topo.in_degrees().max(initial=0))
    slots = np.full((len(topo.shifts), n), sentinel, dtype=np.int32)
    sorted_sources = [topo.in_neighbor_ranks(i) for i in range(n)]
    for k, shift in enumerate(topo.shifts):
        for src, dst in shift.pairs:
            slots[k, dst] = sorted_sources[dst].index(src)
    return slots


def _padded_gather(x, axis_name, permutes, slots, out_rows: int):
    """Shared padded-gather loop: one ppermute per offset, arrivals written
    to their per-rank output row (``slots[k, i]``; the out-of-range sentinel
    drops rows for ranks without that in-edge)."""
    idx = lax.axis_index(axis_name)
    slots = jnp.asarray(slots)
    out = jnp.zeros((out_rows,) + x.shape, x.dtype)
    for k, perm in enumerate(permutes):
        received = lax.ppermute(x, axis_name, perm)
        out = out.at[slots[k, idx]].set(received, mode="drop")
    return out


def neighbor_allgather(x, axis_name, topo: CompiledTopology):
    """Stack in-neighbor tensors: out has shape ``[max_in_degree, *x.shape]``,
    ordered by ascending source rank (matching MPI_Dist_graph source order,
    mpi_controller.cc:282-361; reference concatenates along dim 0).

    Irregular topologies (allgatherv semantics, mpi_context.cc:622-700) use
    the padded max-in-degree layout: rank i's valid slots are the first
    ``in_degree(i)``; padding rows stay zero.  SPMD output shapes are uniform
    by construction, so StarGraph and friends work.  The permutes carry only
    the topology's real edge pairs (non-destinations receive zeros).
    """
    indeg = int(topo.in_degrees().max(initial=0))
    return _padded_gather(x, axis_name,
                          [shift.pairs for shift in topo.shifts],
                          _allgather_slots(topo), indeg)


def dynamic_neighbor_allgather(x, axis_name, size: int,
                               offsets: Tuple[int, ...], slots,
                               out_rows: int):
    """Per-call neighbor allgather over a traced edge set.

    ``offsets``: static ring-offset superset (structure; cached).
    ``slots``: traced [K, N] — output row at rank i for the value arriving
    over ``offsets[k]`` (in-neighbors sorted ascending by source rank), or
    ``out_rows`` (the drop sentinel) when rank i has no such in-edge.
    ``out_rows``: static max in-degree — the padded output row count.

    Same-structure calls reuse one compiled program; the edges themselves
    are data (full-rotation permutes, since the live pairs are unknown at
    trace time).  This is the reference's per-call ``src_ranks/dst_ranks``
    neighbor_allgather (torch/mpi_ops.py:397-472; dynamic exchange
    mpi_controller.cc:322-361) in allgatherv-padded form.
    """
    return _padded_gather(x, axis_name,
                          [_rotation_pairs(size, off) for off in offsets],
                          slots, out_rows)


def offset_weighted_neighbor_allreduce(x, axis_name, size: int,
                                       offsets: Tuple[int, ...],
                                       self_w, weights, *,
                                       sender_side: bool = False):
    """Circulant neighbor average with *traced* weight tables.

    The offset set (the communication structure) is static; the weights are
    data, so per-call mixing matrices with the same sparsity pattern reuse
    one compiled program — the fast path for the reference's per-call
    ``self_weight/src_weights/dst_weights`` (torch/mpi_ops.py:475-645)
    instead of an O(N)-bandwidth allgather mix.

    ``self_w``: [N]. ``weights``: [K, N] —
    * receiver-side (default): ``weights[k, j]`` is the factor rank j applies
      to the value arriving over ``offsets[k]``;
    * ``sender_side=True`` (the reference's dst-weighted mode,
      mpi_controller.cc:1444-1446): ``weights[k, i]`` is the factor rank i
      applies to its value *before* sending on ``offsets[k]``; receivers add
      arrivals unscaled.
    """
    _require_inexact(x, "offset_weighted_neighbor_allreduce")
    idx = lax.axis_index(axis_name)
    self_w = jnp.asarray(self_w)
    weights = jnp.asarray(weights)
    out = self_w[idx].astype(x.dtype) * x
    for k, offset in enumerate(offsets):
        if sender_side:
            received = lax.ppermute(
                weights[k, idx].astype(x.dtype) * x, axis_name,
                _rotation_pairs(size, offset))
            out = out + received
        else:
            received = lax.ppermute(
                x, axis_name, _rotation_pairs(size, offset))
            out = out + weights[k, idx].astype(x.dtype) * received
    return out


# ---------------------------------------------------------------------------
# Neighbor collectives (dynamic topology)
# ---------------------------------------------------------------------------

def dynamic_neighbor_allreduce(x, axis_name, sched: DynamicSchedule, step):
    """Per-step dynamic neighbor average with a traced ``step`` index.

    The offset superset is fixed at trace time; which edges are live at this
    step is pure data (weight tables), so topology hops never recompile
    (SURVEY.md §7 hard part 2).  ``step`` may be a traced int32 scalar.
    """
    _require_inexact(x, "dynamic_neighbor_allreduce")
    t = jnp.asarray(step) % sched.period
    idx = lax.axis_index(axis_name)
    self_w = jnp.asarray(sched.self_weights)[t]            # [N]
    recv_w = jnp.asarray(sched.recv_weights)[t]            # [K, N]
    out = self_w[idx].astype(x.dtype) * x
    for k, offset in enumerate(sched.offsets):
        received = lax.ppermute(
            x, axis_name, _rotation_pairs(sched.size, offset))
        out = out + recv_w[k, idx].astype(x.dtype) * received
    return out


def dynamic_neighbor_allreduce_dst_weighted(
        x, axis_name, sched: DynamicSchedule, step, send_weights):
    """Dynamic neighbor average with sender-side weighting.

    ``send_weights``: [K, N] array — rank i scales its outgoing value on
    offset k by ``send_weights[k, i]`` before the permute (reference
    dst_weights path, mpi_controller.cc:1444-1446).  Receivers add arrivals
    unscaled; self contribution still uses the schedule's self weights.
    """
    _require_inexact(x, "dynamic_neighbor_allreduce_dst_weighted")
    t = jnp.asarray(step) % sched.period
    idx = lax.axis_index(axis_name)
    self_w = jnp.asarray(sched.self_weights)[t]
    send_w = jnp.asarray(send_weights)
    out = self_w[idx].astype(x.dtype) * x
    for k, offset in enumerate(sched.offsets):
        received = lax.ppermute(
            send_w[k, idx].astype(x.dtype) * x, axis_name,
            _rotation_pairs(sched.size, offset))
        out = out + received
    return out


# ---------------------------------------------------------------------------
# Pair gossip
# ---------------------------------------------------------------------------

def pair_gossip(x, axis_name, pairs: Sequence[Tuple[int, int]],
                self_weight: float = 0.5, pair_weight: float = 0.5):
    """Pairwise exchange + weighted average (mpi_controller.cc:745-771).

    ``pairs`` is a perfect (or partial) matching given as unordered rank
    pairs; both directions are exchanged in a single ppermute.  Ranks outside
    the matching keep their value unchanged.
    """
    _require_inexact(x, "pair_gossip")
    perm = []
    matched = set()
    for a, b in pairs:
        if a == b or a in matched or b in matched:
            raise ValueError(f"pairs must form a matching, got {pairs}")
        matched.update((a, b))
        perm.extend([(a, b), (b, a)])
    received = lax.ppermute(x, axis_name, perm)
    idx = lax.axis_index(axis_name)
    size = lax.axis_size(axis_name)
    in_pair = np.zeros(size, dtype=bool)
    for a, b in pairs:
        in_pair[[a, b]] = True
    mask = jnp.asarray(in_pair)[idx]
    mixed = self_weight * x + pair_weight * received
    return jnp.where(mask, mixed.astype(x.dtype), x)


# ---------------------------------------------------------------------------
# Hierarchical (machine-level) collectives on a 2-D mesh
# ---------------------------------------------------------------------------

def hierarchical_neighbor_allreduce(x, machine_axis, local_axis,
                                    machine_topo: CompiledTopology):
    """Two-level neighbor average (mpi_controller.cc:471-507).

    Reference pipeline: intra-machine allreduce -> inter-machine neighbor
    exchange by local rank 0 -> intra-machine broadcast.  On a 2-D
    ``(machine, local)`` mesh the local pmean plus a machine-axis neighbor
    average produces the same value already replicated on every local rank —
    the final broadcast disappears (the ``/local_size`` correction of
    torch/mpi_ops.cc:119-155 is the pmean).
    """
    local_avg = lax.pmean(x, local_axis)
    return neighbor_allreduce(local_avg, machine_axis, machine_topo)


def hierarchical_local_allreduce(x, local_axis, *, average: bool = True):
    """Machine-local allreduce (reference ``is_hierarchical_local`` path,
    mpi_controller.cc:177-178 over the LOCAL communicator)."""
    return lax.pmean(x, local_axis) if average else lax.psum(x, local_axis)
