"""Shared helpers for Pallas kernels."""

import jax

__all__ = ["out_struct"]


def out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose varying-mesh-axes set is the union of the
    operands' (required by shard_map's check_vma for pallas outputs)."""
    vma = set()
    for x in operands:
        vma |= set(getattr(jax.typeof(x), "vma", ()) or ())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    except TypeError:      # older JAX without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)
