"""Shared helpers for Pallas kernels."""

import jax

__all__ = ["out_struct", "collective_id", "register_collective_family"]


def out_struct(shape, dtype, *operands):
    """ShapeDtypeStruct whose varying-mesh-axes set is the union of the
    operands' (required by shard_map's check_vma for pallas outputs)."""
    vma = set()
    for x in operands:
        vma |= set(getattr(jax.typeof(x), "vma", ()) or ())
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    except TypeError:      # older JAX without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


# ---------------------------------------------------------------------------
# Collective-id registry
# ---------------------------------------------------------------------------
#
# Mosaic keys the global barrier semaphore a collective kernel grabs with
# ``get_barrier_semaphore()`` on the ``collective_id`` compiler param: two
# kernels compiled with the SAME id share one semaphore, so if both are in
# flight concurrently their neighbor barriers alias — rank A's signal for
# kernel 1 satisfies rank B's wait in kernel 2 and the RDMA lands in a
# scratch buffer that may not exist yet.  Every kernel FAMILY that can be
# live at the same time therefore needs its own id, assigned here from one
# table instead of hardcoded at each pallas_call site.
#
# The assignment is STATIC (not first-come-first-served): every rank of an
# SPMD program must compile the same kernel with the same id, and a
# registry filled in call order could diverge across processes that build
# programs in different orders.  ``gossip`` keeps the historical id 7 (the
# value ``_run_exchange`` shipped with) so the dense kernel's lowered
# bytes are unchanged.
_COLLECTIVE_FAMILIES = {
    "gossip": 7,              # dense fused exchange (_run_exchange)
    "windows": 8,             # reserved for a future window-op kernel
    "compressed_gossip": 9,   # single-kernel codec gossip (direct mode)
    "choco_gossip": 10,       # single-kernel CHOCO difference gossip
}


def collective_id(family: str) -> int:
    """Barrier-semaphore id for a kernel family (KeyError-free: unknown
    families raise with the known set, so a typo fails at build time
    instead of silently aliasing an existing semaphore)."""
    try:
        return _COLLECTIVE_FAMILIES[family]
    except KeyError:
        raise ValueError(
            f"unknown pallas collective family {family!r} "
            f"(known: {', '.join(sorted(_COLLECTIVE_FAMILIES))}); register "
            f"new families with register_collective_family") from None


def register_collective_family(family: str, cid: int = None) -> int:
    """Add a kernel family.  ``cid`` defaults to the next free id;
    an explicit id must not collide with an existing family's (the
    aliasing this registry exists to prevent)."""
    family = str(family)
    if family in _COLLECTIVE_FAMILIES:
        existing = _COLLECTIVE_FAMILIES[family]
        if cid is not None and int(cid) != existing:
            raise ValueError(
                f"collective family {family!r} is already id {existing}; "
                f"cannot re-register as {cid}")
        return existing
    if cid is None:
        cid = max(_COLLECTIVE_FAMILIES.values()) + 1
    cid = int(cid)
    if cid in _COLLECTIVE_FAMILIES.values():
        owner = next(k for k, v in _COLLECTIVE_FAMILIES.items() if v == cid)
        raise ValueError(
            f"collective id {cid} already belongs to family {owner!r}")
    _COLLECTIVE_FAMILIES[family] = cid
    return cid
