"""Pallas TPU kernels: fused weighted neighbor exchange.

The XLA path (``collectives.neighbor_allreduce``) lowers one ``lax.ppermute``
per circulant offset; XLA may serialize those transfers.  This kernel issues
ALL offsets' RDMAs concurrently — each rides a different ICI link — and folds
the weighted accumulation into the same kernel, so a K-offset exchange costs
one link time instead of up to K (SURVEY.md §7 build-order step 10; reference
fuses the analogous buffers on the MPI side, mpi_controller.cc:561-743).

Pattern follows the ring-collective recipe of the Pallas TPU guide
(async remote copy + per-slot DMA semaphores + neighbor barrier).  Semantics
are identical to the XLA path: ``out_i = W[i,i]·x_i + Σ_k W[src_k(i), i]·
recv_k`` with zero weights dropping absent edges, so partial (non-rotation)
offsets of irregular graphs stay correct — they just ship one redundant
tile.

Use via ``neighbor_allreduce(..., backend="pallas")`` on real TPU meshes, or
``interpret=True`` under the CPU test mesh (the Pallas TPU interpreter
simulates inter-device DMA).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.schedule import CompiledTopology, DynamicSchedule

__all__ = [
    "fused_neighbor_allreduce", "fused_dynamic_neighbor_allreduce",
    "fused_neighbor_allreduce_flat", "fused_dynamic_neighbor_allreduce_flat",
    "FLAT_TILE",
]

_LANE = 128
_SUBLANE = 8

# One full float32 VMEM tile.  The comm-fusion layer (ops/fusion.py) pads
# its flat buckets to this element multiple so the kernel's [R, 128]
# reshape is exact — the whole model pays ONE sub-tile padding per bucket
# instead of one per leaf (`_as_tiles` waste).
FLAT_TILE = _SUBLANE * _LANE


def _struct_vma(shape, dtype, axis_name):
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset({axis_name}))
    except TypeError:  # older JAX without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


def _pad_rows(x2d, rows_mult: int):
    pad = (-x2d.shape[0]) % rows_mult
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d


def _as_tiles(x):
    """Flatten to [R, 128] with R a multiple of the float32 sublane count."""
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % _LANE
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2d = flat.reshape(-1, _LANE)
    return _pad_rows(x2d, _SUBLANE)


def _exchange_kernel(size: int, offsets, axis_name: str):
    """Kernel body: start K concurrent RDMAs, barrier, weighted accumulate.

    refs: x, self_w [N], recv_w [K, N] -> out;
    scratch: recv_buf [K, R, 128], send/recv DMA semaphore arrays [K].
    """
    K = len(offsets)

    def kernel(x_ref, self_w_ref, recv_w_ref, out_ref,
               recv_buf, send_sems, recv_sems):
        my_id = lax.axis_index(axis_name)

        # neighbor barrier (pallas guide: "Local Barrier Between Neighbors"):
        # every rank signals each destination once, then waits for its K
        # senders — guarantees all peers' recv_buf scratch exists before any
        # RDMA lands.
        barrier_sem = pltpu.get_barrier_semaphore()
        for k in range(K):
            dst = lax.rem(my_id + offsets[k], size)
            pltpu.semaphore_signal(barrier_sem, inc=1, device_id=dst,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier_sem, K)

        # all offsets in flight together — each targets a distinct neighbor
        copies = []
        for k in range(K):
            dst = lax.rem(my_id + offsets[k], size)
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref,
                dst_ref=recv_buf.at[k],
                send_sem=send_sems.at[k],
                recv_sem=recv_sems.at[k],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            copies.append(rdma)

        acc = x_ref[...] * self_w_ref[my_id].astype(x_ref.dtype)
        for k in range(K):
            copies[k].wait()
            w = recv_w_ref[k, my_id].astype(x_ref.dtype)
            acc += w * recv_buf[k]
        out_ref[...] = acc

    return kernel


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _run_exchange(x2d, self_w, recv_w, size, offsets, axis_name, interpret):
    kernel = _exchange_kernel(size, offsets, axis_name)
    K = len(offsets)
    return pl.pallas_call(
        kernel,
        # vma: the output varies across the mesh axis (required when the
        # enclosing shard_map checks varying-mesh-axes); older JAX has no
        # vma kwarg and no such check
        out_shape=_struct_vma(x2d.shape, x2d.dtype, axis_name),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((K,) + x2d.shape, x2d.dtype),
            pltpu.SemaphoreType.DMA((K,)),
            pltpu.SemaphoreType.DMA((K,)),
        ],
        compiler_params=pltpu.CompilerParams(collective_id=7),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x2d, self_w, recv_w)


def _fused_exchange(x, axis_name, size, offsets, self_w, recv_w,
                    interpret: bool):
    if not offsets:
        return x * jnp.asarray(self_w)[lax.axis_index(axis_name)].astype(x.dtype)
    x2d = _as_tiles(x)
    out2d = _run_exchange(
        x2d, jnp.asarray(self_w, jnp.float32), jnp.asarray(recv_w, jnp.float32),
        size, tuple(int(o) for o in offsets), axis_name, bool(interpret))
    return out2d.reshape(-1)[: int(np.prod(x.shape))].reshape(x.shape)


def _static_recv_tables(topo: CompiledTopology) -> np.ndarray:
    """[K, N] receive-weight table of a static topology (the kernel's
    ``recv_w`` operand)."""
    K = len(topo.shifts)
    recv_w = np.zeros((max(K, 1), topo.size), np.float32)
    for k, s in enumerate(topo.shifts):
        recv_w[k] = s.recv_weights
    return recv_w


def fused_neighbor_allreduce(x, axis_name, topo: CompiledTopology,
                             interpret: bool = False):
    """Drop-in for ``collectives.neighbor_allreduce`` (call inside
    shard_map): one fused kernel instead of K chained ppermutes."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError("fused_neighbor_allreduce requires a float dtype")
    return _fused_exchange(x, axis_name, topo.size, topo.offsets,
                           topo.self_weights, _static_recv_tables(topo),
                           interpret)


def _fused_exchange_flat(x, axis_name, size, offsets, self_w, recv_w,
                         interpret: bool):
    """Pre-tiled fast path for the comm-fusion layer: ``x`` is a 1-D flat
    bucket whose length is a multiple of :data:`FLAT_TILE`, so the [R, 128]
    kernel layout is a pure reshape — no per-leaf ``_as_tiles`` padding."""
    if x.ndim != 1 or x.shape[0] % FLAT_TILE:
        raise ValueError(
            f"flat fused exchange expects a 1-D buffer with a multiple of "
            f"{FLAT_TILE} elements (fusion pad_to=FLAT_TILE), got shape "
            f"{tuple(x.shape)}")
    if not offsets:
        return x * jnp.asarray(self_w)[lax.axis_index(axis_name)].astype(x.dtype)
    out2d = _run_exchange(
        x.reshape(-1, _LANE), jnp.asarray(self_w, jnp.float32),
        jnp.asarray(recv_w, jnp.float32), size,
        tuple(int(o) for o in offsets), axis_name, bool(interpret))
    return out2d.reshape(x.shape)


def fused_neighbor_allreduce_flat(x, axis_name, topo: CompiledTopology,
                                  interpret: bool = False):
    """Static-topology fused exchange over one pre-tiled flat bucket."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError("fused_neighbor_allreduce_flat requires a float dtype")
    return _fused_exchange_flat(x, axis_name, topo.size, topo.offsets,
                                topo.self_weights,
                                _static_recv_tables(topo), interpret)


def fused_dynamic_neighbor_allreduce_flat(x, axis_name,
                                          sched: DynamicSchedule, step,
                                          interpret: bool = False):
    """Dynamic-schedule fused exchange over one pre-tiled flat bucket."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError(
            "fused_dynamic_neighbor_allreduce_flat requires a float dtype")
    self_w, recv_w = _sched_tables(sched, step)
    return _fused_exchange_flat(x, axis_name, sched.size, sched.offsets,
                                self_w, recv_w, interpret)


def _sched_tables(sched: DynamicSchedule, step):
    """This step's (self_w [N], recv_w [K, N]) weight tables, gathered on
    device by the traced step index — pure data, no recompilation."""
    t = jnp.asarray(step) % sched.period
    return (jnp.asarray(sched.self_weights, jnp.float32)[t],
            jnp.asarray(sched.recv_weights, jnp.float32)[t])


def fused_dynamic_neighbor_allreduce(x, axis_name, sched: DynamicSchedule,
                                     step, interpret: bool = False):
    """Dynamic-schedule variant: the step's weight tables are gathered
    outside the kernel (pure data — no recompilation across steps)."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError("fused_dynamic_neighbor_allreduce requires a float dtype")
    self_w, recv_w = _sched_tables(sched, step)
    return _fused_exchange(x, axis_name, sched.size, sched.offsets,
                           self_w, recv_w, interpret)
