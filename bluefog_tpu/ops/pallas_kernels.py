"""Pallas TPU kernels: fused weighted neighbor exchange.

The XLA path (``collectives.neighbor_allreduce``) lowers one ``lax.ppermute``
per circulant offset; XLA may serialize those transfers.  This kernel issues
ALL offsets' RDMAs concurrently — each rides a different ICI link — and folds
the weighted accumulation into the same kernel, so a K-offset exchange costs
one link time instead of up to K (SURVEY.md §7 build-order step 10; reference
fuses the analogous buffers on the MPI side, mpi_controller.cc:561-743).

Pattern follows the ring-collective recipe of the Pallas TPU guide
(async remote copy + per-slot DMA semaphores + neighbor barrier).  Semantics
are identical to the XLA path: ``out_i = W[i,i]·x_i + Σ_k W[src_k(i), i]·
recv_k`` with zero weights dropping absent edges, so partial (non-rotation)
offsets of irregular graphs stay correct — they just ship one redundant
tile.

Use via ``neighbor_allreduce(..., backend="pallas")`` on real TPU meshes, or
``interpret=True`` under the CPU test mesh (the Pallas TPU interpreter
simulates inter-device DMA).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.schedule import CompiledTopology, DynamicSchedule
from ._pallas_util import collective_id

__all__ = [
    "fused_neighbor_allreduce", "fused_dynamic_neighbor_allreduce",
    "fused_neighbor_allreduce_flat", "fused_dynamic_neighbor_allreduce_flat",
    "fused_compressed_gossip", "fused_choco_gossip",
    "FLAT_TILE", "GOSSIP_TILE",
]

_LANE = 128
_SUBLANE = 8

# One full float32 VMEM tile.  The comm-fusion layer (ops/fusion.py) pads
# its flat buckets to this element multiple so the kernel's [R, 128]
# reshape is exact — the whole model pays ONE sub-tile padding per bucket
# instead of one per leaf (`_as_tiles` waste).
FLAT_TILE = _SUBLANE * _LANE


def _struct_vma(shape, dtype, axes):
    if isinstance(axes, str):
        axes = (axes,)
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(axes))
    except TypeError:  # older JAX without the vma kwarg
        return jax.ShapeDtypeStruct(shape, dtype)


def _neighbor_device_id(my_id, offset, size, axis_name, mesh_axes):
    """(device_id, device_id_type) of the gossip neighbor at ``offset``.

    ``mesh_axes=None`` (1-D gossip mesh) keeps the historical scalar
    LOGICAL id.  On a multi-axis mesh (the hybrid ``(dp, fsdp)`` path)
    the RDMA must target the SAME cell in the neighbor replica, so the
    id is the full tuple of mesh coordinates — the gossip axis rotated
    by ``offset``, every other axis held at this rank's own coordinate —
    with ``DeviceIdType.MESH`` (Mosaic linearizes the tuple with the
    mesh strides of ``mesh.axis_names`` order)."""
    if mesh_axes is None:
        return (lax.rem(my_id + offset, size),
                pltpu.DeviceIdType.LOGICAL)
    coords = tuple(
        lax.rem(my_id + offset, size) if a == axis_name
        else lax.axis_index(a)
        for a in mesh_axes)
    return coords, pltpu.DeviceIdType.MESH


def _pad_rows(x2d, rows_mult: int):
    pad = (-x2d.shape[0]) % rows_mult
    if pad:
        x2d = jnp.pad(x2d, ((0, pad), (0, 0)))
    return x2d


def _as_tiles(x):
    """Flatten to [R, 128] with R a multiple of the float32 sublane count."""
    flat = x.reshape(-1)
    pad = (-flat.shape[0]) % _LANE
    if pad:
        flat = jnp.pad(flat, (0, pad))
    x2d = flat.reshape(-1, _LANE)
    return _pad_rows(x2d, _SUBLANE)


def _exchange_kernel(size: int, offsets, axis_name: str):
    """Kernel body: start K concurrent RDMAs, barrier, weighted accumulate.

    refs: x, self_w [N], recv_w [K, N] -> out;
    scratch: recv_buf [K, R, 128], send/recv DMA semaphore arrays [K].
    """
    K = len(offsets)

    def kernel(x_ref, self_w_ref, recv_w_ref, out_ref,
               recv_buf, send_sems, recv_sems):
        my_id = lax.axis_index(axis_name)

        # neighbor barrier (pallas guide: "Local Barrier Between Neighbors"):
        # every rank signals each destination once, then waits for its K
        # senders — guarantees all peers' recv_buf scratch exists before any
        # RDMA lands.
        barrier_sem = pltpu.get_barrier_semaphore()
        for k in range(K):
            dst = lax.rem(my_id + offsets[k], size)
            pltpu.semaphore_signal(barrier_sem, inc=1, device_id=dst,
                                   device_id_type=pltpu.DeviceIdType.LOGICAL)
        pltpu.semaphore_wait(barrier_sem, K)

        # all offsets in flight together — each targets a distinct neighbor
        copies = []
        for k in range(K):
            dst = lax.rem(my_id + offsets[k], size)
            rdma = pltpu.make_async_remote_copy(
                src_ref=x_ref,
                dst_ref=recv_buf.at[k],
                send_sem=send_sems.at[k],
                recv_sem=recv_sems.at[k],
                device_id=dst,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            rdma.start()
            copies.append(rdma)

        acc = x_ref[...] * self_w_ref[my_id].astype(x_ref.dtype)
        for k in range(K):
            copies[k].wait()
            w = recv_w_ref[k, my_id].astype(x_ref.dtype)
            acc += w * recv_buf[k]
        out_ref[...] = acc

    return kernel


@functools.partial(jax.jit, static_argnums=(3, 4, 5, 6))
def _run_exchange(x2d, self_w, recv_w, size, offsets, axis_name, interpret):
    kernel = _exchange_kernel(size, offsets, axis_name)
    K = len(offsets)
    return pl.pallas_call(
        kernel,
        # vma: the output varies across the mesh axis (required when the
        # enclosing shard_map checks varying-mesh-axes); older JAX has no
        # vma kwarg and no such check
        out_shape=_struct_vma(x2d.shape, x2d.dtype, axis_name),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * 3,
        out_specs=pl.BlockSpec(memory_space=pltpu.VMEM),
        scratch_shapes=[
            pltpu.VMEM((K,) + x2d.shape, x2d.dtype),
            pltpu.SemaphoreType.DMA((K,)),
            pltpu.SemaphoreType.DMA((K,)),
        ],
        compiler_params=pltpu.CompilerParams(
            collective_id=collective_id("gossip")),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(x2d, self_w, recv_w)


def _fused_exchange(x, axis_name, size, offsets, self_w, recv_w,
                    interpret: bool):
    if not offsets:
        return x * jnp.asarray(self_w)[lax.axis_index(axis_name)].astype(x.dtype)
    x2d = _as_tiles(x)
    out2d = _run_exchange(
        x2d, jnp.asarray(self_w, jnp.float32), jnp.asarray(recv_w, jnp.float32),
        size, tuple(int(o) for o in offsets), axis_name, bool(interpret))
    return out2d.reshape(-1)[: int(np.prod(x.shape))].reshape(x.shape)


def _static_recv_tables(topo: CompiledTopology) -> np.ndarray:
    """[K, N] receive-weight table of a static topology (the kernel's
    ``recv_w`` operand)."""
    K = len(topo.shifts)
    recv_w = np.zeros((max(K, 1), topo.size), np.float32)
    for k, s in enumerate(topo.shifts):
        recv_w[k] = s.recv_weights
    return recv_w


def fused_neighbor_allreduce(x, axis_name, topo: CompiledTopology,
                             interpret: bool = False):
    """Drop-in for ``collectives.neighbor_allreduce`` (call inside
    shard_map): one fused kernel instead of K chained ppermutes."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError("fused_neighbor_allreduce requires a float dtype")
    return _fused_exchange(x, axis_name, topo.size, topo.offsets,
                           topo.self_weights, _static_recv_tables(topo),
                           interpret)


def _fused_exchange_flat(x, axis_name, size, offsets, self_w, recv_w,
                         interpret: bool):
    """Pre-tiled fast path for the comm-fusion layer: ``x`` is a 1-D flat
    bucket whose length is a multiple of :data:`FLAT_TILE`, so the [R, 128]
    kernel layout is a pure reshape — no per-leaf ``_as_tiles`` padding."""
    if x.ndim != 1 or x.shape[0] % FLAT_TILE:
        raise ValueError(
            f"flat fused exchange expects a 1-D buffer with a multiple of "
            f"{FLAT_TILE} elements (fusion pad_to=FLAT_TILE), got shape "
            f"{tuple(x.shape)}")
    if not offsets:
        return x * jnp.asarray(self_w)[lax.axis_index(axis_name)].astype(x.dtype)
    out2d = _run_exchange(
        x.reshape(-1, _LANE), jnp.asarray(self_w, jnp.float32),
        jnp.asarray(recv_w, jnp.float32), size,
        tuple(int(o) for o in offsets), axis_name, bool(interpret))
    return out2d.reshape(x.shape)


def fused_neighbor_allreduce_flat(x, axis_name, topo: CompiledTopology,
                                  interpret: bool = False):
    """Static-topology fused exchange over one pre-tiled flat bucket."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError("fused_neighbor_allreduce_flat requires a float dtype")
    return _fused_exchange_flat(x, axis_name, topo.size, topo.offsets,
                                topo.self_weights,
                                _static_recv_tables(topo), interpret)


def fused_dynamic_neighbor_allreduce_flat(x, axis_name,
                                          sched: DynamicSchedule, step,
                                          interpret: bool = False):
    """Dynamic-schedule fused exchange over one pre-tiled flat bucket."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError(
            "fused_dynamic_neighbor_allreduce_flat requires a float dtype")
    self_w, recv_w = _sched_tables(sched, step)
    return _fused_exchange_flat(x, axis_name, sched.size, sched.offsets,
                                self_w, recv_w, interpret)


def _sched_tables(sched: DynamicSchedule, step):
    """This step's (self_w [N], recv_w [K, N]) weight tables, gathered on
    device by the traced step index — pure data, no recompilation."""
    t = jnp.asarray(step) % sched.period
    return (jnp.asarray(sched.self_weights, jnp.float32)[t],
            jnp.asarray(sched.recv_weights, jnp.float32)[t])


def fused_dynamic_neighbor_allreduce(x, axis_name, sched: DynamicSchedule,
                                     step, interpret: bool = False):
    """Dynamic-schedule variant: the step's weight tables are gathered
    outside the kernel (pure data — no recompilation across steps)."""
    if not jnp.issubdtype(jnp.asarray(x).dtype, jnp.inexact):
        raise TypeError("fused_dynamic_neighbor_allreduce requires a float dtype")
    self_w, recv_w = _sched_tables(sched, step)
    return _fused_exchange(x, axis_name, sched.size, sched.offsets,
                           self_w, recv_w, interpret)


# ---------------------------------------------------------------------------
# Single-kernel compressed gossip: codec + RDMA + mix in one pallas_call
# ---------------------------------------------------------------------------
#
# The compressed exchange chain (``compress/exchange.py::compressed_mix``)
# is quantize -> ppermute -> dequantize -> weighted mix: four HLO stages
# that each round-trip the bucket through HBM, and every receiver
# re-materializes the wire payload at decode width.  This kernel is the
# whole chain per bucket: the EF-corrected iterate ``t = x + e`` is
# quantized ON STORE into a VMEM wire buffer (int8 / fp8 payload + one
# f32 scale), the WIRE ENCODING rides K concurrent RDMAs (one per
# circulant offset, each on its own ICI link — the same concurrency as
# ``_exchange_kernel`` above, at 1/4 the bytes), receivers decode ON LOAD
# from the recv scratch, and ``self_w*x + sum_k w_k*D(recv_k)`` plus the
# error-feedback residual ``t - D(C(t))`` accumulate in-register.  The
# bucket crosses HBM exactly twice (read x/e, write out/e') no matter how
# many neighbors decode it.
#
# The codec math is ``compress/compressors.py``'s kernel-callable bodies
# (``int8_encode``/``int8_decode``/``fp8_*``) — the SAME functions the
# chain's wire classes call, so the kernel is bit-exact against the chain
# by construction; stochastic-rounding noise is precomputed outside (it
# depends only on the rank key and the element count, never the data) and
# fed in as an operand.
#
# ``mode`` selects the transport:
#   "pallas"     the Mosaic kernel on real TPU meshes
#   "interpret"  the same kernel under the TPU-simulating interpreter
#                (CPU test mesh; jaxlib >= 0.5)
#   "emulate"    the same body math with ``lax.ppermute`` standing in for
#                the RDMA — runs on ANY backend (the bit-exactness and
#                compile-count harness for hosts without the Mosaic
#                interpreter; wire dtype on the permutes is still the
#                codec's, so trace-level wire-byte evidence holds too)

# int8 VMEM tiles are (32, 128); padding buckets to this element multiple
# keeps the f32 operands (8-row tiles) AND the 8-bit wire buffers exactly
# tile-aligned, so the kernel reshapes and never pads internally.
_WIRE_SUBLANE = 32
GOSSIP_TILE = _WIRE_SUBLANE * _LANE


def _codec_encode(codec: str, t32, noise):
    from ..compress import compressors as CP
    if codec == "int8":
        return CP.int8_encode(t32, noise)
    if codec == "fp8":
        return CP.fp8_encode(t32)
    raise ValueError(f"unknown kernel codec {codec!r}")


def _codec_decode(codec: str, q, scale):
    from ..compress import compressors as CP
    if codec == "int8":
        return CP.int8_decode(q, scale)
    if codec == "fp8":
        return CP.fp8_decode(q, scale)
    raise ValueError(f"unknown kernel codec {codec!r}")


def _wire_dtype(codec: str):
    return jnp.int8 if codec == "int8" else jnp.float8_e4m3fn


def _start_wire_exchange(my_id, size, offsets, axis_name, mesh_axes,
                         wire_q, wire_s, recv_q, recv_s,
                         send_sems, recv_sems):
    """Barrier + launch of the K concurrent wire RDMAs (payload + scale
    per offset); returns the copy handles to wait on.  Shared by the
    direct and CHOCO flavors — the transport is identical, only the
    in-register math around it differs."""
    K = len(offsets)
    # neighbor barrier (same recipe as _exchange_kernel): all peers'
    # recv scratch must exist before any RDMA lands
    barrier_sem = pltpu.get_barrier_semaphore()
    for k in range(K):
        dst, id_type = _neighbor_device_id(my_id, offsets[k], size,
                                           axis_name, mesh_axes)
        pltpu.semaphore_signal(barrier_sem, inc=1, device_id=dst,
                               device_id_type=id_type)
    pltpu.semaphore_wait(barrier_sem, K)

    # all K offsets' wire payloads in flight together — each rides a
    # distinct ICI link; the scale scalar rides its own tiny copy
    copies = []
    for k in range(K):
        dst, id_type = _neighbor_device_id(my_id, offsets[k], size,
                                           axis_name, mesh_axes)
        c_q = pltpu.make_async_remote_copy(
            src_ref=wire_q, dst_ref=recv_q.at[k],
            send_sem=send_sems.at[0, k], recv_sem=recv_sems.at[0, k],
            device_id=dst, device_id_type=id_type)
        c_s = pltpu.make_async_remote_copy(
            src_ref=wire_s, dst_ref=recv_s.at[k],
            send_sem=send_sems.at[1, k], recv_sem=recv_sems.at[1, k],
            device_id=dst, device_id_type=id_type)
        c_q.start()
        c_s.start()
        copies.append((c_q, c_s))
    return copies


def _compressed_gossip_kernel(size: int, offsets, axis_name: str,
                              codec: str, has_noise: bool,
                              mesh_axes=None):
    """Kernel body: encode on store, K concurrent wire RDMAs, decode on
    load, mix + EF residual in-register.

    refs: x [R, 128], res [R, 128], (noise [R, 128] f32,) self_w [N],
    recv_w [K, N] -> out [R, 128], res_out [R, 128];
    scratch: wire_q [R, 128] wire-dtype, wire_s [1, 128] f32,
    recv_q [K, R, 128], recv_s [K, 1, 128], send/recv DMA semaphore
    arrays [2, K] (payload row 0, scale row 1)."""
    K = len(offsets)

    def kernel(*refs):
        if has_noise:
            (x_ref, res_ref, noise_ref, self_w_ref, recv_w_ref,
             out_ref, res_out_ref,
             wire_q, wire_s, recv_q, recv_s, send_sems, recv_sems) = refs
        else:
            (x_ref, res_ref, self_w_ref, recv_w_ref,
             out_ref, res_out_ref,
             wire_q, wire_s, recv_q, recv_s, send_sems, recv_sems) = refs
            noise_ref = None
        my_id = lax.axis_index(axis_name)

        # quantize-on-store: the EF-corrected iterate enters the wire
        # buffer at wire width — nothing wider ever leaves the chip
        t = x_ref[...] + res_ref[...]
        q, scale = _codec_encode(
            codec, t.astype(jnp.float32),
            noise_ref[...] if noise_ref is not None else None)
        wire_q[...] = q
        wire_s[...] = jnp.full((1, _LANE), scale, jnp.float32)

        copies = _start_wire_exchange(
            my_id, size, offsets, axis_name, mesh_axes,
            wire_q, wire_s, recv_q, recv_s, send_sems, recv_sems)

        # own reconstruction + EF residual while the wire flies: the
        # residual update t - D(C(t)) never waits on the interconnect
        d_own = _codec_decode(codec, q, scale).astype(x_ref.dtype)
        res_out_ref[...] = t - d_own
        acc = self_w_ref[my_id] * x_ref[...]
        for k in range(K):
            c_q, c_s = copies[k]
            c_q.wait()
            c_s.wait()
            dec = _codec_decode(codec, recv_q[k],
                                recv_s[k][0, 0]).astype(x_ref.dtype)
            acc = acc + recv_w_ref[k, my_id] * dec
        out_ref[...] = acc

    return kernel


def _choco_gossip_kernel(size: int, offsets, axis_name: str,
                         codec: str, has_noise: bool, mesh_axes=None):
    """CHOCO difference-gossip kernel body: the replica estimates x̂/ŝ
    fold in-register — encode ``δ = x − x̂`` on store, RDMA the wire
    encoding, decode neighbors' deltas on load, update the estimates
    ``x̂' = x̂ + D(C(δ))`` / ``ŝ' = ŝ + Σ_j W[j,i]·D(C(δ_j))`` and apply
    the mix ``x + γ·(ŝ' − x̂')`` before writeback — the bucket crosses
    HBM exactly twice, like the direct flavor.

    refs: x [R, 128], xhat [R, 128], shat [R, 128], (noise [R, 128]
    f32,) gamma [1], self_w [N], recv_w [K, N] -> out [R, 128],
    xhat_out [R, 128], shat_out [R, 128]; scratch as the direct flavor.
    ``gamma`` is the traced consensus stepsize (cfg.gamma × the PR 9
    controller's ``gamma_scale`` leaf), precomputed in ``x.dtype``
    OUTSIDE the kernel exactly as the chain does, so backoff/re-arm
    actuates without recompiling the kernel."""
    K = len(offsets)

    def kernel(*refs):
        if has_noise:
            (x_ref, xhat_ref, shat_ref, noise_ref, gamma_ref,
             self_w_ref, recv_w_ref,
             out_ref, xhat_out_ref, shat_out_ref,
             wire_q, wire_s, recv_q, recv_s, send_sems, recv_sems) = refs
        else:
            (x_ref, xhat_ref, shat_ref, gamma_ref,
             self_w_ref, recv_w_ref,
             out_ref, xhat_out_ref, shat_out_ref,
             wire_q, wire_s, recv_q, recv_s, send_sems, recv_sems) = refs
            noise_ref = None
        my_id = lax.axis_index(axis_name)

        # quantize-on-store: only the compressed DELTA against the public
        # replica estimate ever enters the wire buffer
        delta = x_ref[...] - xhat_ref[...]
        q, scale = _codec_encode(
            codec, delta.astype(jnp.float32),
            noise_ref[...] if noise_ref is not None else None)
        wire_q[...] = q
        wire_s[...] = jnp.full((1, _LANE), scale, jnp.float32)

        copies = _start_wire_exchange(
            my_id, size, offsets, axis_name, mesh_axes,
            wire_q, wire_s, recv_q, recv_s, send_sems, recv_sems)

        # own decoded delta while the wire flies; NOTE the self term
        # weights D(C(δ)) (every holder applies the identical decoded
        # delta — the CHOCO determinism contract), unlike the direct
        # flavor whose self term is the true value
        d_own = _codec_decode(codec, q, scale).astype(x_ref.dtype)
        acc = self_w_ref[my_id] * d_own
        for k in range(K):
            c_q, c_s = copies[k]
            c_q.wait()
            c_s.wait()
            dec = _codec_decode(codec, recv_q[k],
                                recv_s[k][0, 0]).astype(x_ref.dtype)
            acc = acc + recv_w_ref[k, my_id] * dec
        xhat_new = xhat_ref[...] + d_own
        shat_new = shat_ref[...] + acc
        xhat_out_ref[...] = xhat_new
        shat_out_ref[...] = shat_new
        out_ref[...] = x_ref[...] + gamma_ref[0] * (shat_new - xhat_new)

    return kernel


def _wire_scratch_shapes(x2d, wire_dt, K):
    """The wire-exchange VMEM scratch + DMA semaphores shared by the
    direct and CHOCO runners: send wire (payload + scale row), K recv
    slots, [2, K] semaphore arrays (payload row 0, scale row 1)."""
    return [
        pltpu.VMEM(x2d.shape, wire_dt),
        pltpu.VMEM((1, _LANE), jnp.float32),
        pltpu.VMEM((K,) + x2d.shape, wire_dt),
        pltpu.VMEM((K, 1, _LANE), jnp.float32),
        pltpu.SemaphoreType.DMA((2, K)),
        pltpu.SemaphoreType.DMA((2, K)),
    ]


@functools.partial(jax.jit, static_argnums=(5, 6, 7, 8, 9, 10))
def _run_compressed_exchange(x2d, res2d, noise2d, self_w, recv_w,
                             size, offsets, axis_name, codec, interpret,
                             mesh_axes=None):
    K = len(offsets)
    has_noise = noise2d is not None
    kernel = _compressed_gossip_kernel(size, offsets, axis_name, codec,
                                       has_noise, mesh_axes)
    wire_dt = _wire_dtype(codec)
    n_in = 5 if has_noise else 4
    args = ((x2d, res2d, noise2d, self_w, recv_w) if has_noise
            else (x2d, res2d, self_w, recv_w))
    vma = mesh_axes if mesh_axes is not None else axis_name
    return pl.pallas_call(
        kernel,
        out_shape=(_struct_vma(x2d.shape, x2d.dtype, vma),
                   _struct_vma(x2d.shape, x2d.dtype, vma)),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_in,
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),
                   pl.BlockSpec(memory_space=pltpu.VMEM)),
        scratch_shapes=_wire_scratch_shapes(x2d, wire_dt, K),
        compiler_params=pltpu.CompilerParams(
            collective_id=collective_id("compressed_gossip")),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(*args)


@functools.partial(jax.jit, static_argnums=(7, 8, 9, 10, 11, 12))
def _run_choco_exchange(x2d, xhat2d, shat2d, noise2d, gamma, self_w,
                        recv_w, size, offsets, axis_name, codec,
                        interpret, mesh_axes=None):
    K = len(offsets)
    has_noise = noise2d is not None
    kernel = _choco_gossip_kernel(size, offsets, axis_name, codec,
                                  has_noise, mesh_axes)
    wire_dt = _wire_dtype(codec)
    n_in = 7 if has_noise else 6
    args = ((x2d, xhat2d, shat2d, noise2d, gamma, self_w, recv_w)
            if has_noise else (x2d, xhat2d, shat2d, gamma, self_w, recv_w))
    vma = mesh_axes if mesh_axes is not None else axis_name
    out = _struct_vma(x2d.shape, x2d.dtype, vma)
    return pl.pallas_call(
        kernel,
        out_shape=(out, out, out),
        in_specs=[pl.BlockSpec(memory_space=pltpu.VMEM)] * n_in,
        out_specs=(pl.BlockSpec(memory_space=pltpu.VMEM),) * 3,
        scratch_shapes=_wire_scratch_shapes(x2d, wire_dt, K),
        compiler_params=pltpu.CompilerParams(
            collective_id=collective_id("choco_gossip")),
        interpret=pltpu.InterpretParams() if interpret else False,
    )(*args)


def _check_kernel_entry(buf, mode):
    if mode not in ("pallas", "interpret"):
        raise ValueError(f"unknown gossip-kernel transport {mode!r}")
    if buf.ndim != 1:
        raise ValueError(
            f"fused compressed gossip expects 1-D flat buckets, got shape "
            f"{tuple(buf.shape)}")


def _pad_wire_tile(arrs, n: int):
    """Pad each 1-D array (or None) to whole (32, 128) wire tiles; zeros
    are inert through both kernel bodies (|0| never raises the scale
    max, 0 quantizes to 0, decodes to 0, mixes to 0, residual/estimate
    deltas stay 0) and the caller slices them away."""
    pad = (-n) % GOSSIP_TILE
    if not pad:
        return arrs
    return tuple(jnp.pad(a, (0, pad)) if a is not None else None
                 for a in arrs)


def fused_compressed_gossip(buf, residual, noise, self_w, recv_w, *,
                            axis_name, size: int, offsets, codec: str,
                            mode: str, mesh_axes=None):
    """One bucket's compressed gossip as a single fused kernel (call
    inside shard_map, per rank).

    ``buf``/``residual``: the 1-D fusion bucket and its carried
    error-feedback residual (any float dtype).  ``noise``: the
    stochastic-rounding uniform draw, 1-D f32 of ``buf.size`` (int8
    only; ``None`` otherwise) — the chain's exact draw, precomputed
    because the kernel has no in-kernel threefry.  ``self_w [N]`` /
    ``recv_w [K, N]``: per-rank weight tables already cast to
    ``buf.dtype`` with the chain's conversions
    (``compress/exchange.py::_weight_tables``).  Partial non-rotation
    offsets of irregular static graphs ship one redundant tile (same
    semantics as the dense kernel above); the chain's ppermute delivers
    zeros there instead — both sides multiply by the same zero weight.

    ``mode``: ``"pallas"`` (Mosaic, real TPU) or ``"interpret"`` (the
    TPU-simulating interpreter on the CPU test mesh; jaxlib >= 0.5).
    The any-backend ``"emulate"`` transport lives with the chain it
    mirrors (``compress/exchange.py::_emulated_bucket_gossip``).

    ``mesh_axes``: ``None`` on a 1-D gossip mesh (scalar LOGICAL device
    ids, the historical lowering); on a multi-axis mesh (the hybrid
    ``(dp, fsdp)`` path) the full ordered tuple of mesh axis names, so
    the RDMAs target the same cell in the neighbor replica via
    mesh-coordinate device ids.

    Returns ``(mixed, residual_new)`` with ``buf``'s shape/dtype."""
    _check_kernel_entry(buf, mode)
    if not offsets:
        # size-1 mesh / edgeless topology: no exchange, but the chain
        # still encodes (the EF residual is the codec error)
        t = buf + residual
        q, scale = _codec_encode(
            codec, t.astype(jnp.float32),
            noise.reshape(-1) if noise is not None else None)
        d_own = _codec_decode(codec, q, scale).astype(buf.dtype)
        return self_w[lax.axis_index(axis_name)] * buf, t - d_own
    n = int(buf.shape[0])
    buf_p, res_p, noise_p = _pad_wire_tile((buf, residual, noise), n)
    shape2d = (-1, _LANE)
    out2d, res2d = _run_compressed_exchange(
        buf_p.reshape(shape2d), res_p.reshape(shape2d),
        noise_p.reshape(shape2d) if noise_p is not None else None,
        self_w, recv_w, size, tuple(int(o) for o in offsets), axis_name,
        codec, mode == "interpret", mesh_axes)
    return out2d.reshape(-1)[:n], res2d.reshape(-1)[:n]


def fused_choco_gossip(buf, xhat, shat, noise, gamma, self_w, recv_w, *,
                       axis_name, size: int, offsets, codec: str,
                       mode: str, mesh_axes=None):
    """One bucket's CHOCO difference gossip as a single fused kernel:
    the replica estimates fold in-register (``_choco_gossip_kernel``),
    so the low-bandwidth discipline pays the same two HBM crossings as
    the direct flavor.

    ``xhat``/``shat``: the carried replica estimate and weighted
    neighbor-estimate sum, 1-D like ``buf``.  ``gamma``: the traced
    consensus stepsize already in ``buf.dtype`` with the chain's
    construction (``cfg.gamma`` × the controller's ``gamma_scale``
    leaf), shape ``(1,)``.  Everything else as
    :func:`fused_compressed_gossip` — same transports, same weight
    tables, same ``mesh_axes`` contract for hybrid meshes.

    Returns ``(mixed, xhat_new, shat_new)`` with ``buf``'s
    shape/dtype."""
    _check_kernel_entry(buf, mode)
    idx = lax.axis_index(axis_name)
    if not offsets:
        # edgeless topology: no exchange, but the estimates still
        # advance by the own decoded delta (the chain's terms loop is
        # simply empty)
        delta = buf - xhat
        q, scale = _codec_encode(
            codec, delta.astype(jnp.float32),
            noise.reshape(-1) if noise is not None else None)
        d_own = _codec_decode(codec, q, scale).astype(buf.dtype)
        acc = self_w[idx] * d_own
        xhat_new = xhat + d_own
        shat_new = shat + acc
        return (buf + gamma[0] * (shat_new - xhat_new), xhat_new,
                shat_new)
    n = int(buf.shape[0])
    buf_p, xhat_p, shat_p, noise_p = _pad_wire_tile(
        (buf, xhat, shat, noise), n)
    shape2d = (-1, _LANE)
    out2d, xhat2d, shat2d = _run_choco_exchange(
        buf_p.reshape(shape2d), xhat_p.reshape(shape2d),
        shat_p.reshape(shape2d),
        noise_p.reshape(shape2d) if noise_p is not None else None,
        gamma, self_w, recv_w, size, tuple(int(o) for o in offsets),
        axis_name, codec, mode == "interpret", mesh_axes)
    return (out2d.reshape(-1)[:n], xhat2d.reshape(-1)[:n],
            shat2d.reshape(-1)[:n])
