"""Fused pointwise-conv + BatchNorm Pallas kernels (the HBM-ceiling attack,
VERDICT r2 #2).

``docs/performance.md`` establishes that ResNet-50 training on v5e is
HBM-bandwidth-bound and names BatchNorm's extra activation passes as the
fusable traffic.  A 1x1 convolution over NHWC is exactly a matmul
``[B*H*W, Cin] @ [Cin, Cout]`` — and 1x1 convs are half of ResNet-50's
convolutions (every bottleneck is 1x1 -> 3x3 -> 1x1, models/resnet.py:52-67)
— so the two kernels here fuse BN's passes into the matmuls around it:

* :func:`matmul_bn_stats` — the conv, with a **stats epilogue**: per-output-
  channel sum / sum-of-squares accumulate while the output tile is still in
  VMEM.  Saves the full re-read of the conv output that the separate BN
  reduce costs (one of BN-train's three activation passes).
* :func:`bn_relu_matmul` — the NEXT conv, with a **normalize prologue**:
  the input tile is normalized (given mean/var), scaled/shifted and ReLU'd
  in VMEM right before it hits the MXU.  Saves the separate
  normalize+activation pass (read + write of the full activation).

Chained, the conv1 -> BN -> ReLU -> conv2 sequence touches HBM as
``write y, read y`` instead of ``write y, read y (reduce), read y + write z
(normalize), read z (conv2)`` — the experiment
``scripts/conv_bn_probe.py`` measures both against plain XLA at ResNet-50
bottleneck shapes.  The reference has no analogue (cuDNN runs these as
separate kernels); XLA:TPU fuses the scale/shift but cannot move the
reduction into the producing conv nor the normalize into the consuming one.

Numerics: inputs may be bf16; the matmul accumulates in f32 on the MXU
(``preferred_element_type``), stats accumulate in f32, outputs cast back.
Training integration note: these are forward-path kernels; a trainable
module wraps them in ``jax.custom_vjp`` with the standard BN backward math
(XLA ops — the backward is not the bandwidth hot spot the forward passes
are).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["matmul_bn_stats", "bn_relu_matmul", "bn_relu_matmul_stats",
           "matmul_bn_stats_t", "bn_relu_matmul_stats_t",
           "pointwise_conv_bn_relu", "dense_bn_relu_dense", "fit_tile"]

_DIMS = pltpu.CompilerParams(
    dimension_semantics=("parallel", "parallel", "arbitrary"))


def fit_tile(dim: int, tile: int, minimum: int = 8) -> int:
    """Largest power-of-two shrink of ``tile`` dividing ``dim`` (same
    policy as flash_attention._fit_block); whole-length if nothing fits."""
    tile = min(tile, dim)
    while tile > minimum and dim % tile:
        tile //= 2
    return tile if dim % tile == 0 else dim


def _check_2d(x, w):
    if x.ndim != 2 or w.ndim != 2 or x.shape[1] != w.shape[0]:
        raise ValueError(f"need [M, K] @ [K, N], got {x.shape} @ {w.shape}")


from ._pallas_util import out_struct as _out_struct  # noqa: E402


def _pad8(row):
    """One stats row in an 8-sublane tile (rows 1-7 zero): (1, bn) output
    blocks are an illegal sublane-1 tile on hardware — the round-1 flash
    lesson — so partial sums ship as (8, bn) blocks and the zero rows
    vanish in the host-side sum."""
    return jnp.concatenate(
        [row, jnp.zeros((7, row.shape[1]), row.dtype)], axis=0)


def _mm_stats_kernel(x_ref, w_ref, y_ref, s_ref, sq_ref, acc_ref, *, nk):
    """Grid (m, n, k): y tile accumulates over k in f32 scratch; at the
    last k the tile is written and its per-channel sum/sumsq land in the
    (m, n)-indexed partial-stats rows."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(x_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _emit():
        y = acc_ref[...]
        y_ref[...] = y.astype(y_ref.dtype)
        # stats epilogue: the tile is still in VMEM — no HBM re-read
        s_ref[...] = _pad8(jnp.sum(y, axis=0, keepdims=True))
        sq_ref[...] = _pad8(jnp.sum(y * y, axis=0, keepdims=True))


@functools.partial(jax.jit, static_argnames=("bm", "bn", "bk", "interpret"))
def matmul_bn_stats(x, w, *, bm: int = 512, bn: int = 256, bk: int = 256,
                    interpret: bool = False):
    """``y = x @ w`` plus per-output-channel batch statistics in one pass.

    Returns ``(y [M, N], mean [N], var [N])`` with mean/var in f32 (biased
    variance, like ``jnp.var`` / flax BatchNorm).
    """
    _check_2d(x, w)
    M, K = x.shape
    N = w.shape[1]
    bm, bn, bk = fit_tile(M, bm), fit_tile(N, bn, 128), fit_tile(K, bk, 128)
    nm, nn, nk = M // bm, N // bn, K // bk

    y, psum, psumsq = pl.pallas_call(
        functools.partial(_mm_stats_kernel, nk=nk),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
            pl.BlockSpec((8, bn), lambda m, n, k: (m, n)),
            pl.BlockSpec((8, bn), lambda m, n, k: (m, n)),
        ],
        out_shape=[
            _out_struct((M, N), x.dtype, x, w),
            _out_struct((nm * 8, N), jnp.float32, x, w),
            _out_struct((nm * 8, N), jnp.float32, x, w),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_DIMS,
        interpret=interpret,
    )(x, w)
    # folding [8*nm, N] partials (7/8 zero rows) is noise next to M*N
    s = psum.sum(axis=0)
    sq = psumsq.sum(axis=0)
    mean = s / M
    var = sq / M - mean * mean
    return y, mean, var


def _bn_mm_kernel(x_ref, mu_ref, iv_ref, g_ref, b_ref, w_ref, y_ref,
                  acc_ref, *, nk, relu):
    """Grid (m, n, k): normalize+scale+shift+ReLU the x tile in VMEM, then
    feed the MXU — the standalone normalize pass never exists."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xn = (x_ref[...].astype(jnp.float32) - mu_ref[...]) * iv_ref[...]
    xn = xn * g_ref[...] + b_ref[...]
    if relu:
        xn = jnp.maximum(xn, 0.0)
    acc_ref[...] += jnp.dot(xn.astype(x_ref.dtype), w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _emit():
        y_ref[...] = acc_ref[...].astype(y_ref.dtype)


@functools.partial(jax.jit, static_argnames=("relu", "eps", "bm", "bn", "bk",
                                             "interpret"))
def bn_relu_matmul(x, mean, var, gamma, beta, w, *, relu: bool = True,
                   eps: float = 1e-5, bm: int = 512, bn: int = 256,
                   bk: int = 256, interpret: bool = False):
    """``relu(norm(x)) @ w`` with the normalize fused into the matmul's
    input read.  ``mean/var/gamma/beta`` are per-``Cin`` ([K]) vectors."""
    _check_2d(x, w)
    M, K = x.shape
    N = w.shape[1]
    for name, v in (("mean", mean), ("var", var), ("gamma", gamma),
                    ("beta", beta)):
        if v.shape != (K,):
            raise ValueError(f"{name} must be [{K}], got {v.shape}")
    bm, bn, bk = fit_tile(M, bm), fit_tile(N, bn, 128), fit_tile(K, bk, 128)
    nm, nn, nk = M // bm, N // bn, K // bk
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    row = lambda v: v.astype(jnp.float32).reshape(1, K)

    vec_spec = pl.BlockSpec((1, bk), lambda m, n, k: (0, k))
    return pl.pallas_call(
        functools.partial(_bn_mm_kernel, nk=nk, relu=relu),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            vec_spec, vec_spec, vec_spec, vec_spec,
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
        out_shape=_out_struct((M, N), x.dtype, x, mean, var, gamma, beta, w),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_DIMS,
        interpret=interpret,
    )(x, row(mean), row(inv), row(gamma), row(beta), w)


def _bn_mm_stats_kernel(x_ref, mu_ref, iv_ref, g_ref, b_ref, w_ref, y_ref,
                        s_ref, sq_ref, acc_ref, *, nk, relu):
    """Normalize prologue AND stats epilogue in one kernel: the bottleneck's
    BN2 -> ReLU -> conv3 -> BN3-stats chain as one pass over the input."""
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    xn = (x_ref[...].astype(jnp.float32) - mu_ref[...]) * iv_ref[...]
    xn = xn * g_ref[...] + b_ref[...]
    if relu:
        xn = jnp.maximum(xn, 0.0)
    acc_ref[...] += jnp.dot(xn.astype(x_ref.dtype), w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(k == nk - 1)
    def _emit():
        y = acc_ref[...]
        y_ref[...] = y.astype(y_ref.dtype)
        s_ref[...] = _pad8(jnp.sum(y, axis=0, keepdims=True))
        sq_ref[...] = _pad8(jnp.sum(y * y, axis=0, keepdims=True))


@functools.partial(jax.jit, static_argnames=("relu", "eps", "bm", "bn", "bk",
                                             "interpret"))
def bn_relu_matmul_stats(x, mean, var, gamma, beta, w, *, relu: bool = True,
                         eps: float = 1e-5, bm: int = 512, bn: int = 256,
                         bk: int = 256, interpret: bool = False):
    """``relu(norm(x)) @ w`` plus batch statistics of the OUTPUT, fused:
    the normalize rides the matmul's input read (no standalone pass) and
    the next BN's reduce rides the output write (no re-read).  Returns
    ``(y, mean_y, var_y)``."""
    _check_2d(x, w)
    M, K = x.shape
    N = w.shape[1]
    for name, v in (("mean", mean), ("var", var), ("gamma", gamma),
                    ("beta", beta)):
        if v.shape != (K,):
            raise ValueError(f"{name} must be [{K}], got {v.shape}")
    bm, bn, bk = fit_tile(M, bm), fit_tile(N, bn, 128), fit_tile(K, bk, 128)
    nm, nn, nk = M // bm, N // bn, K // bk
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    row = lambda v: v.astype(jnp.float32).reshape(1, K)

    vec_spec = pl.BlockSpec((1, bk), lambda m, n, k: (0, k))
    y, psum, psumsq = pl.pallas_call(
        functools.partial(_bn_mm_stats_kernel, nk=nk, relu=relu),
        grid=(nm, nn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda m, n, k: (m, k)),
            vec_spec, vec_spec, vec_spec, vec_spec,
            pl.BlockSpec((bk, bn), lambda m, n, k: (k, n)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bn), lambda m, n, k: (m, n)),
            pl.BlockSpec((8, bn), lambda m, n, k: (m, n)),
            pl.BlockSpec((8, bn), lambda m, n, k: (m, n)),
        ],
        out_shape=[
            _out_struct((M, N), x.dtype, x, mean, var, gamma, beta, w),
            _out_struct((nm * 8, N), jnp.float32, x, mean, var, gamma,
                        beta, w),
            _out_struct((nm * 8, N), jnp.float32, x, mean, var, gamma,
                        beta, w),
        ],
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=_DIMS,
        interpret=interpret,
    )(x, row(mean), row(inv), row(gamma), row(beta), w)
    s, sq = psum.sum(axis=0), psumsq.sum(axis=0)
    mean_y = s / M
    var_y = sq / M - mean_y * mean_y
    return y, mean_y, var_y


# ---------------------------------------------------------------------------
# Per-kernel trainable wrappers (custom VJPs with hand-written backward
# math over stored inputs — no matmul or stats recompute; the backward
# does re-derive the cheap elementwise normalize/ReLU intermediates from
# the stored input)
# ---------------------------------------------------------------------------

def _stats_dy(gy, gm, gv, y, mean, M):
    """Cotangent into y from (y, mean, var) outputs where mean/var are the
    batch stats of y: m = E[y], v = E[y^2] - m^2."""
    gy = gy.astype(jnp.float32)
    d = gy + (gm - 2.0 * mean * gv) / M
    return d + (2.0 / M) * y.astype(jnp.float32) * gv


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def matmul_bn_stats_t(x, w, interpret: bool = False):
    """Trainable :func:`matmul_bn_stats`."""
    return matmul_bn_stats(x, w, interpret=interpret)


def _mbs_fwd(x, w, interpret):
    y, mean, var = matmul_bn_stats(x, w, interpret=interpret)
    return (y, mean, var), (x, w, y, mean)


def _mbs_bwd(interpret, res, cts):
    x, w, y, mean = res
    gy, gm, gv = cts
    d_y = _stats_dy(gy, gm, gv, y, mean, y.shape[0])
    d_x = d_y @ w.astype(jnp.float32).T
    d_w = x.astype(jnp.float32).T @ d_y
    return d_x.astype(x.dtype), d_w.astype(w.dtype)


matmul_bn_stats_t.defvjp(_mbs_fwd, _mbs_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7))
def bn_relu_matmul_stats_t(x, mean, var, gamma, beta, w,
                           eps: float = 1e-5, interpret: bool = False):
    """Trainable :func:`bn_relu_matmul_stats`.  ``mean``/``var`` are
    ordinary differentiable inputs (their dependence on ``x`` — the outer
    reduce — backpropagates through the caller's autodiff)."""
    return bn_relu_matmul_stats(x, mean, var, gamma, beta, w, eps=eps,
                                interpret=interpret)


def _brms_fwd(x, mean, var, gamma, beta, w, eps, interpret):
    y, my, vy = bn_relu_matmul_stats(x, mean, var, gamma, beta, w, eps=eps,
                                     interpret=interpret)
    return (y, my, vy), (x, mean, var, gamma, beta, w, y, my)


def _brms_bwd(eps, interpret, res, cts):
    x, mean, var, gamma, beta, w, y, my = res
    gy, gmy, gvy = cts
    f32 = jnp.float32
    M = x.shape[0]
    d_y = _stats_dy(gy, gmy, gvy, y, my, M)
    inv = jax.lax.rsqrt(var.astype(f32) + eps)
    xhat = (x.astype(f32) - mean) * inv
    z = xhat * gamma + beta
    r = jnp.maximum(z, 0.0)
    d_r = d_y @ w.astype(f32).T
    d_w = r.T @ d_y
    d_z = d_r * (z > 0)
    d_gamma = jnp.sum(d_z * xhat, axis=0)
    d_beta = jnp.sum(d_z, axis=0)
    d_xhat = d_z * gamma
    d_x = d_xhat * inv
    d_mean = -inv * jnp.sum(d_xhat, axis=0)
    d_var = -0.5 * inv ** 3 * jnp.sum(d_xhat * (x.astype(f32) - mean),
                                      axis=0)
    return (d_x.astype(x.dtype), d_mean.astype(mean.dtype),
            d_var.astype(var.dtype), d_gamma.astype(gamma.dtype),
            d_beta.astype(beta.dtype), d_w.astype(w.dtype))


bn_relu_matmul_stats_t.defvjp(_brms_fwd, _brms_bwd)


# ---------------------------------------------------------------------------
# Trainable wrapper: fused forward, standard BN backward (XLA ops — the
# forward passes are the bandwidth hot spot the kernels remove; the
# backward is the usual matmul-dominated program)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def dense_bn_relu_dense(x, w1, gamma, beta, w2, eps: float = 1e-5,
                        interpret: bool = False):
    """Trainable ``relu(BN_train(x @ w1)) @ w2`` with the fused forward
    kernels.  Returns ``(out, mean, var)`` — stats feed running averages.
    Gradients match the XLA composition (verified in
    ``tests/test_conv_bn.py``)."""
    out, mean, var, _ = _dbrd_fwd(x, w1, gamma, beta, w2, eps, interpret)
    return out, mean, var


def _dbrd_fwd(x, w1, gamma, beta, w2, eps, interpret):
    y, mean, var = matmul_bn_stats(x, w1, interpret=interpret)
    out = bn_relu_matmul(y, mean, var, gamma, beta, w2, eps=eps,
                         interpret=interpret)
    return out, mean, var, (x, w1, gamma, beta, w2, y, mean, var)


def _dbrd_fwd_vjp(x, w1, gamma, beta, w2, eps, interpret):
    # fwd mirrors the primal signature (nondiff_argnums args are only
    # PREFIXED for bwd in current JAX)
    out, mean, var, res = _dbrd_fwd(x, w1, gamma, beta, w2, eps, interpret)
    return (out, mean, var), res


def _dbrd_bwd(eps, interpret, res, cts):
    g, _, _ = cts                       # no cotangents through the stats
    x, w1, gamma, beta, w2, y, mean, var = res
    f32 = jnp.float32
    yf = y.astype(f32)
    inv = jax.lax.rsqrt(var.astype(f32) + eps)
    xhat = (yf - mean) * inv
    z = xhat * gamma + beta
    relu_z = jnp.maximum(z, 0.0)
    gf = g.astype(f32)

    d_w2 = relu_z.T @ gf
    d_z = (gf @ w2.astype(f32).T) * (z > 0)
    d_gamma = jnp.sum(d_z * xhat, axis=0)
    d_beta = jnp.sum(d_z, axis=0)
    # standard BN-train backward (batch statistics are functions of y)
    d_xhat = d_z * gamma
    d_y = inv * (d_xhat - d_xhat.mean(axis=0)
                 - xhat * (d_xhat * xhat).mean(axis=0))
    d_w1 = x.astype(f32).T @ d_y
    d_x = d_y @ w1.astype(f32).T
    return (d_x.astype(x.dtype), d_w1.astype(w1.dtype),
            d_gamma.astype(gamma.dtype), d_beta.astype(beta.dtype),
            d_w2.astype(w2.dtype))


dense_bn_relu_dense.defvjp(_dbrd_fwd_vjp, _dbrd_bwd)


def pointwise_conv_bn_relu(x, w1, gamma, beta, w2, *, eps: float = 1e-5,
                           interpret: bool = False):
    """The fused bottleneck chain ``conv1x1 -> BN -> ReLU -> conv1x1`` on
    NHWC input ``[B, H, W, C]``: two kernel launches, two HBM passes over
    the intermediate activation (write + read) instead of XLA's four.

    Returns ``(out [B, H, W, N2], mean, var)`` — the stats feed the running
    averages exactly like flax BatchNorm's ``batch_stats``."""
    B, H, W, C = x.shape
    x2 = x.reshape(B * H * W, C)
    y, mean, var = matmul_bn_stats(x2, w1, interpret=interpret)
    out = bn_relu_matmul(y, mean, var, gamma, beta, w2, eps=eps,
                         interpret=interpret)
    return out.reshape(B, H, W, w2.shape[1]), mean, var
