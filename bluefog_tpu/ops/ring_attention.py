"""Sequence/context parallelism: ring attention and Ulysses all-to-all.

Long-context scaling is first-class in this framework even though the
reference never shards a sequence (SURVEY.md §5.7 records the absence and
notes that the decentralized neighbor exchange — weighted ``lax.ppermute``
on a ring — is structurally the same collective ring attention uses).  This
module supplies that missing axis:

* ``ring_attention`` — blockwise softmax attention with the KV shards
  rotating around the mesh ring via ``lax.ppermute`` while each step's
  partial attention is folded into a numerically-stable online-softmax
  accumulator (flash-attention style running max / running sum).  Sequence
  length per chip stays constant, total context scales linearly with the
  ring, and every hop rides one ICI link.
* ``ulysses_attention`` — DeepSpeed-Ulysses-style all-to-all: re-shard from
  sequence-sharded to head-sharded with ``lax.all_to_all``, run full local
  attention, and shard back.  Cheaper for moderate contexts when
  ``num_heads %% ring_size == 0``.
* ``attention`` — the single-device reference implementation both are
  tested against.

All SPMD entry points follow the conventions of ``ops/collectives.py``:
they take ``axis_name`` explicitly and operate on the per-rank shard, to be
called inside ``shard_map``/``pjit``.  Everything is differentiable (the
ring loop is a ``lax.scan``; each block is rematerialized under
``jax.checkpoint`` so the backward pass re-runs blocks instead of storing
every step's logits).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["attention", "ring_attention", "ulysses_attention"]

_NEG_INF = -1e30  # finite "minus infinity": keeps fully-masked rows NaN-free


def attention(q, k, v, *, causal: bool = False,
              q_offset: int = 0, k_offset: int = 0, scale: Optional[float] = None):
    """Plain softmax attention (single-device reference).

    ``q``: [B, Tq, H, D]; ``k``/``v``: [B, Tk, H, D].  ``q_offset`` /
    ``k_offset`` are the global positions of the first query/key, used for
    causal masking of sharded blocks.
    """
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        qi = q_offset + jnp.arange(q.shape[1])[:, None]
        kj = k_offset + jnp.arange(k.shape[1])[None, :]
        s = jnp.where(kj <= qi, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(p.dtype)).astype(q.dtype)


def _block_step(q, k, v, m, l, o, *, causal, q_pos0, k_pos0, scale):
    """Fold one KV block into the online-softmax accumulator.

    Carries: ``m`` [B, H, Tq] running row max, ``l`` [B, H, Tq] running
    softmax denominator, ``o`` [B, Tq, H, D] unnormalized output.  Fully
    masked blocks contribute nothing (the ``m_new`` guard keeps them finite).
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        qi = q_pos0 + jnp.arange(q.shape[1])[:, None]
        kj = k_pos0 + jnp.arange(k.shape[1])[None, :]
        s = jnp.where((kj <= qi)[None, None], s, _NEG_INF)
    m_new = jnp.maximum(m, s.max(axis=-1))
    # correction for previously accumulated mass; 0*inf-safe because m only
    # decreases from 0 (start) or stays _NEG_INF-bounded, never true -inf
    corr = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])                       # [B, H, Tq, Tk]
    l_new = l * corr + p.sum(axis=-1)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name, *, causal: bool = False,
                   scale: Optional[float] = None, impl: str = "auto",
                   block_q: int = 512, block_k: int = 512,
                   interpret: bool = False):
    """Exact attention over a ring-sharded sequence (call inside shard_map).

    Each rank holds the [B, T/n, H, D] shard of q/k/v for its sequence
    block.  The KV pair circulates around the ``axis_name`` ring in ``n-1``
    ``lax.ppermute`` hops; queries never move.  Online-softmax accumulation
    makes the result exactly equal to full attention over the whole
    sequence, independent of ring size.

    ``impl`` selects the per-hop block compute:

    * ``"flash"`` — the Pallas flash kernel (ops/flash_attention.py): each
      hop produces a normalized partial + LSE in O(block) memory, folded
      into the carry with ``merge_attention_partials``.  The hop offsets
      (this rank's q position, the rotating source's k position) are traced
      scalars fed to the kernel via scalar prefetch.
    * ``"xla"`` — the einsum online-softmax block (materializes one
      [B, H, Tq, Tk] score block per hop; fine for short shards/CPU).
    * ``"auto"`` (default) — flash on TPU when the shard shapes tile onto
      the kernel, xla otherwise.

    Communication: n-1 hops of 2·|KV shard| each over nearest-neighbor ICI
    links — the same circulant-shift primitive as
    ``collectives.neighbor_allreduce`` (offset 1 only).
    """
    from .flash_attention import (flash_attention_with_lse, flash_supported,
                                  merge_attention_partials)

    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    B, T, H, D = q.shape
    scale_ = scale if scale is not None else D ** -0.5
    perm = [(j, (j + 1) % n) for j in range(n)]
    if impl == "auto":
        impl = "flash" if flash_supported(q, k, block_q, block_k) else "xla"
    if impl not in ("flash", "xla"):
        raise ValueError(f"impl must be 'auto', 'flash' or 'xla', got {impl!r}")

    q_pos0 = idx * T
    _vary = lambda a: lax.pcast(a, axis_name, to="varying")

    if impl == "flash":
        def hop(q_, k_blk, v_blk, k_pos0):
            return flash_attention_with_lse(
                q_, k_blk, v_blk, causal=causal, q_offset=q_pos0,
                k_offset=k_pos0, scale=scale_, block_q=block_q,
                block_k=block_k, interpret=interpret)

        if not interpret:   # interpreter-mode callbacks can't be remat'd
            hop = jax.checkpoint(hop)
        o, lse = hop(q, k, v, idx * T)
        o = o.astype(jnp.float32)

        def step(carry, s):
            k_blk, v_blk, o, lse = carry
            k_blk = lax.ppermute(k_blk, axis_name, perm)
            v_blk = lax.ppermute(v_blk, axis_name, perm)
            src = lax.rem(idx - s + n, n)
            o_h, lse_h = hop(q, k_blk, v_blk, src * T)
            o, lse = merge_attention_partials(
                o, lse, o_h.astype(jnp.float32), lse_h)
            return (k_blk, v_blk, o, lse), None

        if n > 1:
            (_, _, o, lse), _ = lax.scan(
                step, (k, v, o, lse), jnp.arange(1, n))
        return o.astype(q.dtype)

    q32 = q.astype(jnp.float32)
    block = jax.checkpoint(
        functools.partial(_block_step, causal=causal, scale=scale_))

    # local block first, then n-1 permute→accumulate hops: exactly n-1
    # ppermutes (rotating a final, never-read KV pair would waste one ICI
    # hop per layer — XLA cannot DCE a collective inside the scan body)
    m0 = _vary(jnp.full((B, H, T), _NEG_INF, jnp.float32))
    l0 = _vary(jnp.zeros((B, H, T), jnp.float32))
    o0 = _vary(jnp.zeros((B, T, H, D), jnp.float32))
    m, l, o = block(q32, k, v, m0, l0, o0, q_pos0=q_pos0, k_pos0=idx * T)

    def step(carry, s):
        k_blk, v_blk, m, l, o = carry
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        src = lax.rem(idx - s + n, n)       # rank that produced this KV block
        m, l, o = block(q32, k_blk, v_blk, m, l, o,
                        q_pos0=q_pos0, k_pos0=src * T)
        return (k_blk, v_blk, m, l, o), None

    if n > 1:
        (_, _, m, l, o), _ = lax.scan(
            step, (k, v, m, l, o), jnp.arange(1, n))
    # l is never 0 for causal self-attention (the diagonal block always
    # contributes); guard anyway so padded/degenerate rows yield 0, not NaN
    l = jnp.where(l == 0.0, 1.0, l)
    out = o / l.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis_name, *, causal: bool = False,
                      scale: Optional[float] = None):
    """All-to-all (Ulysses) sequence parallelism (call inside shard_map).

    Input: sequence-sharded [B, T/n, H, D].  ``lax.all_to_all`` re-shards to
    head-sharded [B, T, H/n, D]; full attention runs locally over the whole
    sequence; a final all-to-all restores sequence sharding.  Requires
    ``H %% n == 0``.  Four all-to-alls of one activation volume each (q/k/v
    in, output out) versus the ring's n-1 double-KV hops — usually the
    better trade below ~32k context.
    """
    n = lax.axis_size(axis_name)
    B, T, H, D = q.shape
    if H % n != 0:
        raise ValueError(
            f"ulysses_attention needs num_heads divisible by the axis size, "
            f"got H={H}, n={n}; use ring_attention instead")
    # [B, T/n, H, D] -> [B, T, H/n, D]: split heads, concat sequence
    qg, kg, vg = (lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                 tiled=True) for x in (q, k, v))
    out = attention(qg, kg, vg, causal=causal, scale=scale)
    # back: split sequence, concat heads
    return lax.all_to_all(out, axis_name, split_axis=1, concat_axis=2,
                          tiled=True)
