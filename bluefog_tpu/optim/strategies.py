"""Decentralized update strategies as pure per-rank functions.

Reference parity: ``bluefog/torch/optimizers.py`` styles (documented at
optimizers.py:311-318):

  Global:     w_{i+1} = w_i - lr * GlobalAverage(grad(w_i))
  Consensus:  w_{i+1} = NeighborAverage(w_i) - lr * grad(w_i)
  CTA:        w_{i+1} = NeighborAverage(w_i) - lr * grad(NeighborAverage(w_i))
  ATC:        w_{i+1} = NeighborAverage(w_i - lr * grad(w_i))

The reference realizes these with per-parameter torch hooks that overlap
communication with forward/backward; here each strategy is a pure function
``(params, grads, opt_state, step) -> (params, opt_state)`` meant to run
inside one jitted SPMD program, where XLA overlaps the ppermute traffic with
the update math automatically — the hook machinery has no TPU equivalent and
needs none.  The reference's AWC (adapt-with-combine, optimizers.py:1497)
computes the same update as consensus with comm/compute running in parallel;
under XLA that parallelism is the scheduler's job, so AWC and consensus share
an implementation here.

All functions are axis-level: they expect to be called inside ``shard_map``
with per-rank pytrees, like ``lax.psum``.
"""

from enum import Enum
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from ..ops import api as _api
from ..ops import collectives as C
from ..ops import fusion as F
from ..parallel.schedule import CompiledTopology, DynamicSchedule


class CommunicationType(Enum):
    """Reference parity: optimizers.py CommunicationType."""
    allreduce = "allreduce"
    neighbor_allreduce = "neighbor.allreduce"
    hierarchical_neighbor_allreduce = "hierarchical.neighbor.allreduce"
    empty = "empty"


def _communicate(params, comm_type: CommunicationType, axis_name,
                 topo: Optional[CompiledTopology],
                 sched: Optional[DynamicSchedule],
                 step,
                 machine_axes: Optional[Tuple[str, str]] = None,
                 machine_topo: Optional[CompiledTopology] = None,
                 nar_backend: Optional[str] = None,
                 fuse: Optional[bool] = None,
                 fusion_bucket_bytes: Optional[int] = None):
    """Apply the configured averaging to ``params``.

    ``nar_backend``: exchange backend SNAPSHOT.  Builders capture it when
    the step is constructed (jit traces once and would otherwise freeze
    whatever the env said at first call — silently stale if the env
    changes later); ``None`` falls back to reading the env here.

    ``fuse`` (default: ``BLUEFOG_COMM_FUSION``, on): run the exchange over
    dtype-bucketed flat buffers (``ops/fusion.py``) — one collective per
    bucket per offset instead of one per LEAF per offset.  Bit-exact
    versus the per-leaf path (the averaging is elementwise-linear and
    buckets never mix dtypes); ``fusion_bucket_bytes`` caps bucket size
    for chunking/overlap.  Builders snapshot both like ``nar_backend``.
    """
    if comm_type == CommunicationType.empty:
        return params
    do_fuse = F.fusion_enabled(fuse)
    pad_to = 1
    if comm_type == CommunicationType.allreduce:
        fn = lambda p: C.allreduce(p, axis_name, average=True)
    elif comm_type == CommunicationType.neighbor_allreduce:
        backend = nar_backend or _api._nar_backend()
        if backend.startswith("pallas"):
            # the training step rides the same fused concurrent-RDMA
            # kernel as the op layer (BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND,
            # ops/api.py:165-171); float leaves only, like the kernel
            from ..ops import pallas_kernels as PK
            interp = backend == "pallas_interpret"
            if do_fuse:
                # flat buckets pre-padded to whole VMEM tiles: the kernel
                # reshapes, it never pads (per-leaf `_as_tiles` waste gone)
                pad_to = PK.FLAT_TILE
                if sched is not None:
                    fn = lambda p: PK.fused_dynamic_neighbor_allreduce_flat(
                        p, axis_name, sched, step, interpret=interp)
                else:
                    fn = lambda p: PK.fused_neighbor_allreduce_flat(
                        p, axis_name, topo, interpret=interp)
            elif sched is not None:
                fn = lambda p: PK.fused_dynamic_neighbor_allreduce(
                    p, axis_name, sched, step, interpret=interp)
            else:
                fn = lambda p: PK.fused_neighbor_allreduce(
                    p, axis_name, topo, interpret=interp)
        elif sched is not None:
            fn = lambda p: C.dynamic_neighbor_allreduce(
                p, axis_name, sched, step)
        else:
            fn = lambda p: C.neighbor_allreduce(p, axis_name, topo)
    elif comm_type == CommunicationType.hierarchical_neighbor_allreduce:
        machine_axis, local_axis = machine_axes
        fn = lambda p: C.hierarchical_neighbor_allreduce(
            p, machine_axis, local_axis, machine_topo)
    else:
        raise ValueError(f"Unsupported CommunicationType {comm_type}")
    if do_fuse:
        return F.fused_tree_map(fn, params,
                                max_bucket_bytes=fusion_bucket_bytes,
                                pad_to=pad_to)
    return jax.tree.map(fn, params)


def gradient_allreduce_step(base: optax.GradientTransformation, axis_name,
                            accumulate_steps: int = 1,
                            fuse: Optional[bool] = None,
                            fusion_bucket_bytes: Optional[int] = None):
    """Horovod-style synchronous data parallelism
    (reference _DistributedOptimizer, optimizers.py:166-294).

    ``accumulate_steps`` implements ``backward_passes_per_step``
    (optimizers.py:45-48): gradients accumulate locally for k calls and the
    averaged update applies on every k-th — parameters never see raw local
    gradients, so ranks stay in lockstep.  With k > 1 the optimizer state is
    ``{"base": ..., "accum": ...}`` (see ``grad_accum_init``).

    The gradient average rides the comm-fusion layer when ``fuse`` resolves
    on (this is exactly the reference's Horovod-style fusion buffer): one
    allreduce per dtype bucket instead of one per gradient leaf.
    """
    do_fuse = F.fusion_enabled(fuse)

    def _avg(tree):
        f = lambda x: C.allreduce(x, axis_name, average=True)
        if do_fuse:
            return F.fused_tree_map(f, tree,
                                    max_bucket_bytes=fusion_bucket_bytes)
        return jax.tree.map(f, tree)

    if accumulate_steps <= 1:
        def step_fn(params, grads, opt_state, step=0):
            g = _avg(grads)
            updates, opt_state = base.update(g, opt_state, params)
            return optax.apply_updates(params, updates), opt_state
        return step_fn

    k = int(accumulate_steps)

    def step_fn(params, grads, opt_state, step=0):
        accum = jax.tree.map(jnp.add, opt_state["accum"], grads)
        do_comm = (jnp.asarray(step) % k) == (k - 1)

        def comm_branch(p, acc, bs):
            g = _avg(jax.tree.map(lambda x: x / k, acc))
            updates, bs_new = base.update(g, bs, p)
            p_new = optax.apply_updates(p, updates)
            return p_new, jax.tree.map(jnp.zeros_like, acc), bs_new

        def local_branch(p, acc, bs):
            return p, acc, bs

        p_new, accum_new, base_new = jax.lax.cond(
            do_comm, comm_branch, local_branch, params, accum,
            opt_state["base"])
        return p_new, {"base": base_new, "accum": accum_new}

    return step_fn


def grad_accum_init(base: optax.GradientTransformation, params):
    """Per-rank init for the accumulating gradient-allreduce state."""
    return {"base": base.init(params),
            "accum": jax.tree.map(jnp.zeros_like, params)}


def consensus_step(base: optax.GradientTransformation,
                   comm_type: CommunicationType, axis_name,
                   topo=None, sched=None, machine_axes=None,
                   machine_topo=None, nar_backend=None, fuse=None,
                   fusion_bucket_bytes=None):
    """Consensus/CTA/AWC family (reference _DistributedReduceOptimizer,
    optimizers.py:297-482): average the *weights*, apply the local update
    computed from gradients at the pre-average point.  Only the exchange
    is fused (``fuse``); the optimizer state stays per-leaf."""
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)

    def step_fn(params, grads, opt_state, step=0):
        averaged = _communicate(params, comm_type, axis_name, topo, sched,
                                step, machine_axes, machine_topo,
                                nar_backend, fuse, fusion_bucket_bytes)
        updates, opt_state = base.update(grads, opt_state, averaged)
        return optax.apply_updates(averaged, updates), opt_state

    return step_fn


def atc_step(base: optax.GradientTransformation,
             comm_type: CommunicationType, axis_name,
             topo=None, sched=None, machine_axes=None, machine_topo=None,
             nar_backend=None, fuse=None, fusion_bucket_bytes=None):
    """Adapt-then-combine (reference _DistributedAdaptThenCombineOptimizer,
    optimizers.py:485-841): local update first, then average the updated
    weights.  The reference re-implements each torch optimizer's math inside
    the gradient hook; with optax the base transformation is already a pure
    function, so ATC is just the other composition order.  Only the
    exchange is fused (``fuse``); the optimizer state stays per-leaf."""
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)

    def step_fn(params, grads, opt_state, step=0):
        updates, opt_state = base.update(grads, opt_state, params)
        adapted = optax.apply_updates(params, updates)
        combined = _communicate(adapted, comm_type, axis_name, topo, sched,
                                step, machine_axes, machine_topo,
                                nar_backend, fuse, fusion_bucket_bytes)
        return combined, opt_state

    return step_fn


def exact_diffusion_step(base: optax.GradientTransformation,
                         comm_type: CommunicationType, axis_name,
                         topo=None, sched=None, machine_axes=None,
                         machine_topo=None, nar_backend=None, fuse=None,
                         fusion_bucket_bytes=None):
    """Exact-Diffusion (a.k.a. D2): the bias-corrected diffusion recursion
    from the reference authors' own line of work (Yuan/Ying et al.; no
    reference-code counterpart — a beyond-parity strategy):

        psi_k  = adapt(x_k)                      # local optax update
        phi_k  = psi_k + x_k - psi_{k-1}         # the one-line correction
        x_{k+1} = combine(phi_k)                 # weighted neighbor average

    Plain diffusion (ATC) converges, with a CONSTANT step size under
    heterogeneous per-rank objectives, only to a biased fixed point whose
    per-rank spread is O(alpha * zeta) (zeta = gradient heterogeneity);
    the correction term cancels that bias exactly — every rank reaches
    the true global optimum (asserted against closed form in
    tests/test_optimizers.py::test_exact_diffusion_removes_diffusion_bias).
    State: ``{"base": ..., "psi_prev": ...}`` (psi_prev starts at x_0, so
    the first step reduces to plain ATC — the standard initialization).
    Only the phi exchange is fused (``fuse``); psi_prev stays per-leaf."""
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)

    def step_fn(params, grads, opt_state, step=0):
        updates, base_new = base.update(grads, opt_state["base"], params)
        psi = optax.apply_updates(params, updates)
        phi = jax.tree.map(lambda s, x, sp: s + x - sp,
                           psi, params, opt_state["psi_prev"])
        combined = _communicate(phi, comm_type, axis_name, topo, sched,
                                step, machine_axes, machine_topo,
                                nar_backend, fuse, fusion_bucket_bytes)
        return combined, {"base": base_new, "psi_prev": psi}

    return step_fn


def exact_diffusion_topology(compiled_topo):
    """Validate + damp the mixing matrix for exact-diffusion.

    The D2/Exact-Diffusion stability theory assumes a SYMMETRIC doubly-
    stochastic W (and uses the damped \bar W = (I + W)/2, whose spectrum
    is nonnegative, to guarantee convergence for any stable step size).
    This is not pedantry: on the default DIRECTED exp2 topology the
    recursion measurably diverges (logistic-regression example, lr 0.2:
    error 1.9e5 after 500 iters) while converging on the same problem
    over a symmetric graph.  Returns the compiled damped topology."""
    import numpy as _np
    from ..parallel.schedule import compile_weight_matrix
    W = _np.asarray(compiled_topo.weight_matrix, _np.float64)
    if not _np.allclose(W, W.T, atol=1e-9):
        raise ValueError(
            "exact-diffusion requires a symmetric doubly-stochastic "
            "topology (e.g. bf.SymmetricExponentialGraph, MeshGrid2DGraph, "
            "RingGraph with is_weighted=True); the current topology's "
            "weight matrix is asymmetric (directed exp2?) and the "
            "recursion diverges on it")
    if not _np.allclose(W.sum(axis=1), 1.0, atol=1e-9):
        # symmetric but sub/super-stochastic mixing silently scales the
        # parameter mass every exchange (rows summing to 0.9 decay the
        # iterates ~10%/step toward zero) — reject, don't corrupt
        raise ValueError(
            "exact-diffusion requires row sums of exactly 1 (doubly "
            "stochastic); got row sums in "
            f"[{W.sum(axis=1).min():.4f}, {W.sum(axis=1).max():.4f}]")
    n = W.shape[0]
    return compile_weight_matrix((_np.eye(n) + W) / 2.0)


def exact_diffusion_init(base: optax.GradientTransformation, params):
    """Per-rank init for exact-diffusion: psi_prev = x_0 as a COPY —
    aliasing the live parameter buffers would double-donate them on the
    first step under ``jax.jit(..., donate_argnums=...)``."""
    return {"base": base.init(params),
            "psi_prev": jax.tree.map(jnp.array, params)}


def with_local_steps(step_fn: Callable, local_step_fn: Callable,
                     num_steps_per_communication: int):
    """Communicate every k-th call, run the local-only update otherwise
    (reference ``num_steps_per_communication``/``backward_passes_per_step``,
    optimizers.py:344-349).  ``step`` may be traced; both branches compile."""
    k = int(num_steps_per_communication)
    if k <= 1:
        return step_fn

    def stepped(params, grads, opt_state, step=0):
        do_comm = (jnp.asarray(step) % k) == (k - 1)
        return jax.lax.cond(
            do_comm,
            lambda p, g, s: step_fn(p, g, s, step),
            lambda p, g, s: local_step_fn(p, g, s, step),
            params, grads, opt_state)

    return stepped


def local_sgd_like_step(base: optax.GradientTransformation):
    """The no-communication branch: plain local update."""

    def step_fn(params, grads, opt_state, step=0):
        updates, opt_state = base.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    return step_fn


def with_degraded_guard(step_fn: Callable, local_step_fn: Callable):
    """Skip-comm branch for degraded steps (resilience integration).

    Returns ``guarded(params, grads, opt_state, step, degraded)``: when the
    traced boolean ``degraded`` is set, the step takes the local-only
    branch — no exchange is issued at all — instead of averaging through a
    topology that membership currently distrusts (suspected stall, link
    storm, watchdog-flagged stragglers; see ``resilience.membership``).

    ``degraded`` is DATA: flipping it between steps reuses one compiled
    program (both branches trace).  It must also be mesh-uniform — every
    rank must take the same branch, or the live ranks' collectives deadlock
    waiting on peers that skipped; derive it from replicated state (the
    fault plan, a majority vote, the service watchdog), never from
    rank-local values.  Per-EDGE degradation belongs in the mixing matrix
    (``repair.repair_matrix_traced``), not here.
    """

    def guarded(params, grads, opt_state, step=0, degraded=False):
        return jax.lax.cond(
            jnp.asarray(degraded, bool),
            lambda p, g, s: local_step_fn(p, g, s, step),
            lambda p, g, s: step_fn(p, g, s, step),
            params, grads, opt_state)

    return guarded
