"""Decentralized update strategies as pure per-rank functions.

Reference parity: ``bluefog/torch/optimizers.py`` styles (documented at
optimizers.py:311-318):

  Global:     w_{i+1} = w_i - lr * GlobalAverage(grad(w_i))
  Consensus:  w_{i+1} = NeighborAverage(w_i) - lr * grad(w_i)
  CTA:        w_{i+1} = NeighborAverage(w_i) - lr * grad(NeighborAverage(w_i))
  ATC:        w_{i+1} = NeighborAverage(w_i - lr * grad(w_i))

The reference realizes these with per-parameter torch hooks that overlap
communication with forward/backward; here each strategy is a pure function
``(params, grads, opt_state, step) -> (params, opt_state)`` meant to run
inside one jitted SPMD program, where XLA overlaps the ppermute traffic with
the update math automatically — the hook machinery has no TPU equivalent and
needs none.  The reference's AWC (adapt-with-combine, optimizers.py:1497)
computes the same update as consensus with comm/compute running in parallel;
under XLA that parallelism is the scheduler's job, so AWC and consensus share
an implementation here.

All functions are axis-level: they expect to be called inside ``shard_map``
with per-rank pytrees, like ``lax.psum``.
"""

import os
from enum import Enum
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from ..compress import compressors as CP
from ..compress import exchange as CX
from ..observability import ingraph as IG
from ..ops import api as _api
from ..ops import collectives as C
from ..ops import fusion as F
from ..parallel.schedule import CompiledTopology, DynamicSchedule

# bflint knob-outside-cache-key: builder knobs the cache key covers
# through other identities, or that pin the returned closure's recurrence
# at build time.  topo/machine_topo/machine_axes are keyed as
# ``id(cx._compiled)`` / ``id(cx._compiled_machine)`` / mesh identity in
# step_cache_key; ``sched`` is traced data (the step index selects the
# edge set); accumulate_steps/exact_diffusion/degraded shape the
# recurrence of the closure a builder call RETURNS — the wrapper that
# jits it keys the owning instance, and a new builder call is a new
# closure.
_STEP_KEY_EXEMPT_KNOBS = frozenset({
    "topo", "machine_topo", "machine_axes", "sched",
    "accumulate_steps", "exact_diffusion", "degraded",
})


class CommunicationType(Enum):
    """Reference parity: optimizers.py CommunicationType."""
    allreduce = "allreduce"
    neighbor_allreduce = "neighbor.allreduce"
    hierarchical_neighbor_allreduce = "hierarchical.neighbor.allreduce"
    empty = "empty"


def _communicate(params, comm_type: CommunicationType, axis_name,
                 topo: Optional[CompiledTopology],
                 sched: Optional[DynamicSchedule],
                 step,
                 machine_axes: Optional[Tuple[str, str]] = None,
                 machine_topo: Optional[CompiledTopology] = None,
                 nar_backend: Optional[str] = None,
                 fuse: Optional[bool] = None,
                 fusion_bucket_bytes: Optional[int] = None,
                 compression: Optional[CP.CompressionConfig] = None,
                 comp_state=None,
                 fusion_groups=None,
                 gossip_kernel: Optional[str] = None,
                 interleave: bool = False,
                 kernel_mesh_axes: Optional[Tuple[str, ...]] = None):
    """Apply the configured averaging to ``params``.

    ``axis_name`` is the GOSSIP axis — it need not be the whole mesh.
    Inside a 2-level ``(dp, fsdp)`` ``shard_map`` (the hybrid sharded-
    decentralized path, ``parallel/tensor.py``) every weight lookup,
    mixing column, and collective here indexes ``lax.axis_index(axis_name)``
    only, so the exchange runs per fsdp cell over the dp axis and each
    rank's payload is its 1/fsdp shard; the fsdp axis never appears in
    the schedule (GSPMD sharding of the flat buffers handles it).

    ``nar_backend``: exchange backend SNAPSHOT.  Builders capture it when
    the step is constructed (jit traces once and would otherwise freeze
    whatever the env said at first call — silently stale if the env
    changes later); ``None`` falls back to reading the env here.

    ``fuse`` (default: ``BLUEFOG_COMM_FUSION``, on): run the exchange over
    dtype-bucketed flat buffers (``ops/fusion.py``) — one collective per
    bucket per offset instead of one per LEAF per offset.  Bit-exact
    versus the per-leaf path (the averaging is elementwise-linear and
    buckets never mix dtypes); ``fusion_bucket_bytes`` caps bucket size
    for chunking/overlap.  Builders snapshot both like ``nar_backend``.

    ``compression`` (a resolved :class:`~..compress.CompressionConfig`):
    route the exchange through the compressed wire
    (``compress/exchange.py``) — the call then returns ``(averaged,
    new_comp_state, diag)`` instead of the bare tree, with ``comp_state``
    the carried residual/estimate buffers.  ``None`` takes EXACTLY the
    pre-compression path (byte-identical StableHLO, asserted by
    ``tests/test_compress.py``).  The compressed path runs its own
    ppermute loop, so ``nar_backend`` (the pallas kernels) does not apply
    to it.

    ``fusion_groups`` (``ops/fusion.py::shard_groups``, hybrid path):
    per-leaf bucket-partition keys — sharded and replicated leaves must
    not share codec statistics on a 2-level mesh.

    ``gossip_kernel`` (a resolved mode from ``CX.effective_gossip_
    kernel``, builders validate): run the compressed neighbor exchange
    as ONE fused kernel per bucket instead of the codec/permute/mix
    chain.  ``interleave`` (its codec-free companion): issue small
    buckets' collectives first on the fused paths.  Both default off —
    the default lowering is byte-frozen by the off-path contract.
    ``kernel_mesh_axes``: on a multi-axis shard_map (the hybrid
    ``(dp, fsdp)`` path) the full ordered mesh axis tuple, so the
    kernel's RDMAs target the neighbor replica's matching cell; the
    replicated 1-D path leaves it ``None``.  This function is the ONE
    bucket-kernel entry — the hybrid mixers (``parallel/tensor.py``)
    and the replicated steppers both reach the kernel through here.
    """
    if compression is not None:
        if comm_type == CommunicationType.empty:
            return params, comp_state, _null_comp_diag()
        mode = ("allreduce" if comm_type == CommunicationType.allreduce
                else "neighbor")
        return CX.compressed_mix(
            params, comp_state, compression, mode=mode,
            axis_name=axis_name, topo=topo, sched=sched, step=step,
            fuse=F.fusion_enabled(fuse),
            bucket_bytes=fusion_bucket_bytes, leaf_groups=fusion_groups,
            kernel=gossip_kernel, kernel_mesh_axes=kernel_mesh_axes)
    if comm_type == CommunicationType.empty:
        return params
    do_fuse = F.fusion_enabled(fuse)
    pad_to = 1
    if comm_type == CommunicationType.allreduce:
        fn = lambda p: C.allreduce(p, axis_name, average=True)
    elif comm_type == CommunicationType.neighbor_allreduce:
        backend = nar_backend or _api._nar_backend()
        if backend.startswith("pallas"):
            # the training step rides the same fused concurrent-RDMA
            # kernel as the op layer (BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND,
            # ops/api.py:165-171); float leaves only, like the kernel
            from ..ops import pallas_kernels as PK
            interp = backend == "pallas_interpret"
            if do_fuse:
                # flat buckets pre-padded to whole VMEM tiles: the kernel
                # reshapes, it never pads (per-leaf `_as_tiles` waste gone)
                pad_to = PK.FLAT_TILE
                if sched is not None:
                    fn = lambda p: PK.fused_dynamic_neighbor_allreduce_flat(
                        p, axis_name, sched, step, interpret=interp)
                else:
                    fn = lambda p: PK.fused_neighbor_allreduce_flat(
                        p, axis_name, topo, interpret=interp)
            elif sched is not None:
                fn = lambda p: PK.fused_dynamic_neighbor_allreduce(
                    p, axis_name, sched, step, interpret=interp)
            else:
                fn = lambda p: PK.fused_neighbor_allreduce(
                    p, axis_name, topo, interpret=interp)
        elif sched is not None:
            fn = lambda p: C.dynamic_neighbor_allreduce(
                p, axis_name, sched, step)
        else:
            fn = lambda p: C.neighbor_allreduce(p, axis_name, topo)
    elif comm_type == CommunicationType.hierarchical_neighbor_allreduce:
        machine_axis, local_axis = machine_axes
        fn = lambda p: C.hierarchical_neighbor_allreduce(
            p, machine_axis, local_axis, machine_topo)
    else:
        raise ValueError(f"Unsupported CommunicationType {comm_type}")
    if do_fuse:
        return F.fused_tree_map(fn, params,
                                max_bucket_bytes=fusion_bucket_bytes,
                                pad_to=pad_to, leaf_groups=fusion_groups,
                                interleave=interleave)
    return jax.tree.map(fn, params)


def _null_comp_diag():
    """Diag for a compressed build whose step moved nothing (empty comm)."""
    return {"residual_norm": jnp.float32(0.0), "wire_bytes": 0.0,
            "ratio": 1.0}


def _communicate_c(params, comm_type, axis_name, topo, sched, step,
                   machine_axes, machine_topo, nar_backend, fuse,
                   fusion_bucket_bytes, cfg, comp_state,
                   fusion_groups=None, gossip_kernel=None,
                   interleave=False, kernel_mesh_axes=None):
    """:func:`_communicate` with a UNIFORM ``(tree, comp_state', diag)``
    return, so the strategy bodies need no per-site branching: ``cfg is
    None`` takes the exact uncompressed path (byte-identical StableHLO)
    and reports ``(tree, None, None)``."""
    if cfg is None:
        tree = _communicate(params, comm_type, axis_name, topo, sched,
                            step, machine_axes, machine_topo, nar_backend,
                            fuse, fusion_bucket_bytes,
                            fusion_groups=fusion_groups,
                            interleave=interleave)
        return tree, None, None
    return _communicate(params, comm_type, axis_name, topo, sched, step,
                        machine_axes, machine_topo, nar_backend, fuse,
                        fusion_bucket_bytes, cfg, comp_state,
                        fusion_groups=fusion_groups,
                        gossip_kernel=gossip_kernel, interleave=interleave,
                        kernel_mesh_axes=kernel_mesh_axes)


def _comp_snap_kwargs(diag):
    """Compression fields for :func:`~..observability.ingraph.
    strategy_snapshot` from a compressed exchange's diag (``None`` =
    compression off: ratio 1, nothing carried, wire bytes unmeasured)."""
    if diag is None:
        return {}
    return dict(compress_ratio=diag["ratio"],
                residual_norm=diag["residual_norm"],
                wire_bytes=diag["wire_bytes"])


def _telemetry_axis(comm_type: CommunicationType, axis_name, machine_axes,
                    gossip_axis=None):
    """Axis (or axes) the telemetry pmean runs over: the flat rank axis,
    or both mesh axes under the hierarchical 2-D plumbing.

    ``gossip_axis`` (the hybrid sharded-decentralized path,
    ``parallel/tensor.py``): when set, the pmean runs over it ONLY — on a
    ``(dp, fsdp)`` mesh a pmean over fsdp would average DIFFERENT
    parameter shards, hiding exactly the cross-pod disagreement consensus
    distance exists to expose; the fsdp reduction is a psum of squared
    per-shard distances instead (``ingraph.strategy_snapshot(sum_axis=)``).
    """
    if gossip_axis is not None:
        return gossip_axis
    if (comm_type == CommunicationType.hierarchical_neighbor_allreduce
            and machine_axes is not None):
        return tuple(machine_axes)
    return axis_name


def gradient_allreduce_step(base: optax.GradientTransformation, axis_name,
                            accumulate_steps: int = 1,
                            fuse: Optional[bool] = None,
                            fusion_bucket_bytes: Optional[int] = None,
                            telemetry: bool = False,
                            compression=None):
    """Horovod-style synchronous data parallelism
    (reference _DistributedOptimizer, optimizers.py:166-294).

    ``accumulate_steps`` implements ``backward_passes_per_step``
    (optimizers.py:45-48): gradients accumulate locally for k calls and the
    averaged update applies on every k-th — parameters never see raw local
    gradients, so ranks stay in lockstep.  With k > 1 the optimizer state is
    ``{"base": ..., "accum": ...}`` (see ``grad_accum_init``).

    The gradient average rides the comm-fusion layer when ``fuse`` resolves
    on (this is exactly the reference's Horovod-style fusion buffer): one
    allreduce per dtype bucket instead of one per gradient leaf.

    ``telemetry`` (build-time bool, observability/ingraph.py): the step
    additionally returns a :class:`~..observability.ingraph.
    TelemetrySnapshot` aux — consensus distance over the updated weights
    (~0 for lockstep gradient averaging; drift means divergence), norms,
    and identity mix mass.  Off (the default) leaves the traced program
    untouched — bit-identical StableHLO, asserted by test.

    ``compression`` (spec/config, ``compress/``): compress the GRADIENT
    average's wire (error-feedback EF-SGD) — lossy configs add a
    ``"compress"`` key to the state (see :func:`grad_accum_init`).
    """
    do_fuse = F.fusion_enabled(fuse)
    cfg = CP.resolve_compression(compression)
    if cfg is not None:
        CX.check_supported(cfg, comm_value="allreduce")
    comp_stateful = CX.stateful(cfg)

    def _avg(tree, cs, step):
        # rides the shared plumbing: _communicate's allreduce branch is
        # the exact pre-compression fused/per-leaf gradient average
        return _communicate_c(
            tree, CommunicationType.allreduce, axis_name, None, None,
            step, None, None, None, do_fuse, fusion_bucket_bytes, cfg, cs)

    def _snap(step, p_new, p_old, grads, diag):
        return IG.strategy_snapshot(
            step=step, new_params=p_new, old_params=p_old, grads=grads,
            axis_name=axis_name, col_sum=1.0, row_sum=1.0, fuse=do_fuse,
            bucket_bytes=fusion_bucket_bytes, **_comp_snap_kwargs(diag))

    if accumulate_steps <= 1:
        def step_fn(params, grads, opt_state, step=0):
            if comp_stateful:
                bs, cs = opt_state["base"], opt_state["compress"]
            else:
                bs, cs = opt_state, None
            g, cs_new, diag = _avg(grads, cs, step)
            updates, bs_new = base.update(g, bs, params)
            new_params = optax.apply_updates(params, updates)
            out_state = ({"base": bs_new, "compress": cs_new}
                         if comp_stateful else bs_new)
            if telemetry:
                return new_params, out_state, _snap(step, new_params,
                                                    params, grads, diag)
            return new_params, out_state
        return step_fn

    k = int(accumulate_steps)

    def step_fn(params, grads, opt_state, step=0):
        accum = jax.tree.map(jnp.add, opt_state["accum"], grads)
        do_comm = (jnp.asarray(step) % k) == (k - 1)
        cs = opt_state["compress"] if comp_stateful else None

        def comm_branch(p, acc, bs):
            g, cs_new, diag = _avg(jax.tree.map(lambda x: x / k, acc),
                                   cs, step)
            updates, bs_new = base.update(g, bs, p)
            p_new = optax.apply_updates(p, updates)
            return (p_new, jax.tree.map(jnp.zeros_like, acc), bs_new,
                    cs_new, diag)

        def local_branch(p, acc, bs):
            # residuals persist across accumulate-only steps: EF error is
            # re-injected at the NEXT transmission, not discarded
            return p, acc, bs, cs

        def pack(p_new, acc_new, bs_new, cs_new):
            st = {"base": bs_new, "accum": acc_new}
            if comp_stateful:
                st["compress"] = cs_new
            return p_new, st

        if telemetry:
            # both cond branches must carry the snapshot; the local branch
            # issues no collective and reports consensus as UNMEASURED
            def comm_branch_t(p, acc, bs):
                p_new, acc_new, bs_new, cs_new, diag = comm_branch(
                    p, acc, bs)
                # diag is consumed INSIDE the branch (its static fields
                # cannot cross the cond boundary)
                return (p_new, acc_new, bs_new, cs_new,
                        _snap(step, p_new, p, grads, diag))

            def local_branch_t(p, acc, bs):
                snap = IG.strategy_snapshot(
                    step=step, new_params=p, old_params=p, grads=grads,
                    axis_name=axis_name, col_sum=1.0, row_sum=1.0,
                    fuse=do_fuse, bucket_bytes=fusion_bucket_bytes,
                    measure_consensus=False)
                return p, acc, bs, cs, snap

            p_new, accum_new, base_new, cs_new, snap = jax.lax.cond(
                do_comm, comm_branch_t, local_branch_t, params, accum,
                opt_state["base"])
            out = pack(p_new, accum_new, base_new, cs_new)
            return out[0], out[1], snap

        p_new, accum_new, base_new, cs_new = jax.lax.cond(
            do_comm, lambda p, a, b: comm_branch(p, a, b)[:4],
            local_branch, params, accum, opt_state["base"])
        return pack(p_new, accum_new, base_new, cs_new)

    return step_fn


def compression_state(compression, params, fuse=None,
                      fusion_bucket_bytes=None):
    """Per-rank compression state for a resolved config (or spec), or
    ``None`` when stateless — the single init used by every strategy's
    state builder.  Must see the SAME ``fuse``/``fusion_bucket_bytes`` the
    step builder resolves (the carried-buffer layout is part of the state
    structure, exactly like :func:`delayed_init`)."""
    cfg = CP.resolve_compression(compression)
    return CX.init_state(cfg, params, fuse=F.fusion_enabled(fuse),
                         bucket_bytes=fusion_bucket_bytes)


def compress_wrap_init(base: optax.GradientTransformation, params,
                       compression, fuse=None, fusion_bucket_bytes=None):
    """Per-rank init for the consensus/CTA/ATC family under STATEFUL
    compression: ``{"base": ..., "compress": ...}`` (the plain family
    keeps the raw base state when compression is off or lossless)."""
    return {"base": base.init(params),
            "compress": compression_state(compression, params, fuse,
                                          fusion_bucket_bytes)}


def grad_accum_init(base: optax.GradientTransformation, params,
                    compression=None, fuse=None, fusion_bucket_bytes=None):
    """Per-rank init for the accumulating gradient-allreduce state
    (plus the EF residual buffers when ``compression`` is stateful)."""
    st = {"base": base.init(params),
          "accum": jax.tree.map(jnp.zeros_like, params)}
    cfg = CP.resolve_compression(compression)
    if CX.stateful(cfg):
        st["compress"] = compression_state(cfg, params, fuse,
                                           fusion_bucket_bytes)
    return st


def consensus_step(base: optax.GradientTransformation,
                   comm_type: CommunicationType, axis_name,
                   topo=None, sched=None, machine_axes=None,
                   machine_topo=None, nar_backend=None, fuse=None,
                   fusion_bucket_bytes=None, telemetry: bool = False,
                   compression=None, gossip_kernel=None):
    """Consensus/CTA/AWC family (reference _DistributedReduceOptimizer,
    optimizers.py:297-482): average the *weights*, apply the local update
    computed from gradients at the pre-average point.  Only the exchange
    is fused (``fuse``); the optimizer state stays per-leaf.

    ``telemetry`` (build-time bool): return an extra
    ``TelemetrySnapshot`` — consensus distance over the post-update
    weights (one pmean per fusion bucket), the step's mixing-matrix
    column/row mass at this rank, and the norm trio.  ``False`` (default)
    is the exact pre-telemetry trace (bit-identical StableHLO).

    ``compression`` (spec string or config, ``compress/``): compress the
    exchange wire.  Stateful configs (lossy / choco) change the state
    layout to ``{"base": ..., "compress": ...}`` — create it with
    :func:`compress_wrap_init`.

    ``gossip_kernel`` (mode string/bool, default ``BLUEFOG_GOSSIP_
    KERNEL``, off): fuse the compressed neighbor exchange into one
    kernel per bucket (``compress/exchange.py``); needs a dense
    quantizer spec."""
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value=comm_type.value, sched=sched)
    gossip_kernel, interleave = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value=comm_type.value, fuse=fuse)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        if comp_stateful:
            st, cs = opt_state["base"], opt_state["compress"]
        else:
            st, cs = opt_state, None
        averaged, cs_new, diag = _communicate_c(
            params, comm_type, axis_name, topo, sched, step,
            machine_axes, machine_topo, nar_backend, fuse,
            fusion_bucket_bytes, cfg, cs,
            gossip_kernel=gossip_kernel, interleave=interleave)
        updates, st_new = base.update(grads, st, averaged)
        new_params = optax.apply_updates(averaged, updates)
        out_state = ({"base": st_new, "compress": cs_new}
                     if comp_stateful else st_new)
        if telemetry:
            col, row = IG.mix_mass(comm_type, axis_name, topo, sched, step,
                                   machine_axes, machine_topo)
            snap = IG.strategy_snapshot(
                step=step, new_params=new_params, old_params=params,
                grads=grads,
                axis_name=_telemetry_axis(comm_type, axis_name,
                                          machine_axes),
                col_sum=col, row_sum=row, fuse=fuse,
                bucket_bytes=fusion_bucket_bytes, **_comp_snap_kwargs(diag))
            return new_params, out_state, snap
        return new_params, out_state

    return step_fn


def atc_step(base: optax.GradientTransformation,
             comm_type: CommunicationType, axis_name,
             topo=None, sched=None, machine_axes=None, machine_topo=None,
             nar_backend=None, fuse=None, fusion_bucket_bytes=None,
             telemetry: bool = False, compression=None,
             gossip_kernel=None):
    """Adapt-then-combine (reference _DistributedAdaptThenCombineOptimizer,
    optimizers.py:485-841): local update first, then average the updated
    weights.  The reference re-implements each torch optimizer's math inside
    the gradient hook; with optax the base transformation is already a pure
    function, so ATC is just the other composition order.  Only the
    exchange is fused (``fuse``); the optimizer state stays per-leaf.
    ``telemetry`` as in :func:`consensus_step`; ``compression`` as in
    :func:`consensus_step` (the ADAPTED iterate's wire is compressed);
    ``gossip_kernel`` as in :func:`consensus_step`."""
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value=comm_type.value, sched=sched)
    gossip_kernel, interleave = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value=comm_type.value, fuse=fuse)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        if comp_stateful:
            st, cs = opt_state["base"], opt_state["compress"]
        else:
            st, cs = opt_state, None
        updates, st_new = base.update(grads, st, params)
        adapted = optax.apply_updates(params, updates)
        combined, cs_new, diag = _communicate_c(
            adapted, comm_type, axis_name, topo, sched, step,
            machine_axes, machine_topo, nar_backend, fuse,
            fusion_bucket_bytes, cfg, cs,
            gossip_kernel=gossip_kernel, interleave=interleave)
        out_state = ({"base": st_new, "compress": cs_new}
                     if comp_stateful else st_new)
        if telemetry:
            col, row = IG.mix_mass(comm_type, axis_name, topo, sched, step,
                                   machine_axes, machine_topo)
            snap = IG.strategy_snapshot(
                step=step, new_params=combined, old_params=params,
                grads=grads,
                axis_name=_telemetry_axis(comm_type, axis_name,
                                          machine_axes),
                col_sum=col, row_sum=row, fuse=fuse,
                bucket_bytes=fusion_bucket_bytes, **_comp_snap_kwargs(diag))
            return combined, out_state, snap
        return combined, out_state

    return step_fn


def exact_diffusion_step(base: optax.GradientTransformation,
                         comm_type: CommunicationType, axis_name,
                         topo=None, sched=None, machine_axes=None,
                         machine_topo=None, nar_backend=None, fuse=None,
                         fusion_bucket_bytes=None, telemetry: bool = False,
                         compression=None, gossip_kernel=None):
    """Exact-Diffusion (a.k.a. D2): the bias-corrected diffusion recursion
    from the reference authors' own line of work (Yuan/Ying et al.; no
    reference-code counterpart — a beyond-parity strategy):

        psi_k  = adapt(x_k)                      # local optax update
        phi_k  = psi_k + x_k - psi_{k-1}         # the one-line correction
        x_{k+1} = combine(phi_k)                 # weighted neighbor average

    Plain diffusion (ATC) converges, with a CONSTANT step size under
    heterogeneous per-rank objectives, only to a biased fixed point whose
    per-rank spread is O(alpha * zeta) (zeta = gradient heterogeneity);
    the correction term cancels that bias exactly — every rank reaches
    the true global optimum (asserted against closed form in
    tests/test_optimizers.py::test_exact_diffusion_removes_diffusion_bias).
    State: ``{"base": ..., "psi_prev": ...}`` (psi_prev starts at x_0, so
    the first step reduces to plain ATC — the standard initialization).
    Only the phi exchange is fused (``fuse``); psi_prev stays per-leaf.
    ``compression`` compresses the PHI exchange (stateful configs add a
    ``"compress"`` key; :func:`exact_diffusion_init` carries it);
    ``gossip_kernel`` as in :func:`consensus_step` (the phi wire)."""
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value=comm_type.value, sched=sched)
    gossip_kernel, interleave = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value=comm_type.value, fuse=fuse)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        updates, base_new = base.update(grads, opt_state["base"], params)
        psi = optax.apply_updates(params, updates)
        phi = jax.tree.map(lambda s, x, sp: s + x - sp,
                           psi, params, opt_state["psi_prev"])
        combined, cs_new, diag = _communicate_c(
            phi, comm_type, axis_name, topo, sched, step,
            machine_axes, machine_topo, nar_backend, fuse,
            fusion_bucket_bytes, cfg,
            opt_state["compress"] if comp_stateful else None,
            gossip_kernel=gossip_kernel, interleave=interleave)
        state_new = {"base": base_new, "psi_prev": psi}
        if comp_stateful:
            state_new["compress"] = cs_new
        if telemetry:
            # the mixed topology is the DAMPED (I+W)/2 matrix the caller
            # validated/compiled (exact_diffusion_topology) — its mass
            # telemetry is what the recursion actually uses
            col, row = IG.mix_mass(comm_type, axis_name, topo, sched, step,
                                   machine_axes, machine_topo)
            snap = IG.strategy_snapshot(
                step=step, new_params=combined, old_params=params,
                grads=grads,
                axis_name=_telemetry_axis(comm_type, axis_name,
                                          machine_axes),
                col_sum=col, row_sum=row, fuse=fuse,
                bucket_bytes=fusion_bucket_bytes, **_comp_snap_kwargs(diag))
            return combined, state_new, snap
        return combined, state_new

    return step_fn


def exact_diffusion_topology(compiled_topo):
    """Validate + damp the mixing matrix for exact-diffusion.

    The D2/Exact-Diffusion stability theory assumes a SYMMETRIC doubly-
    stochastic W (and uses the damped \bar W = (I + W)/2, whose spectrum
    is nonnegative, to guarantee convergence for any stable step size).
    This is not pedantry: on the default DIRECTED exp2 topology the
    recursion measurably diverges (logistic-regression example, lr 0.2:
    error 1.9e5 after 500 iters) while converging on the same problem
    over a symmetric graph.  Returns the compiled damped topology."""
    import numpy as _np
    from ..parallel.schedule import compile_weight_matrix
    W = _np.asarray(compiled_topo.weight_matrix, _np.float64)
    if not _np.allclose(W, W.T, atol=1e-9):
        raise ValueError(
            "exact-diffusion requires a symmetric doubly-stochastic "
            "topology (e.g. bf.SymmetricExponentialGraph, MeshGrid2DGraph, "
            "RingGraph with is_weighted=True); the current topology's "
            "weight matrix is asymmetric (directed exp2?) and the "
            "recursion diverges on it")
    if not _np.allclose(W.sum(axis=1), 1.0, atol=1e-9):
        # symmetric but sub/super-stochastic mixing silently scales the
        # parameter mass every exchange (rows summing to 0.9 decay the
        # iterates ~10%/step toward zero) — reject, don't corrupt
        raise ValueError(
            "exact-diffusion requires row sums of exactly 1 (doubly "
            "stochastic); got row sums in "
            f"[{W.sum(axis=1).min():.4f}, {W.sum(axis=1).max():.4f}]")
    n = W.shape[0]
    return compile_weight_matrix((_np.eye(n) + W) / 2.0)


def exact_diffusion_init(base: optax.GradientTransformation, params,
                         compression=None, fuse=None,
                         fusion_bucket_bytes=None):
    """Per-rank init for exact-diffusion: psi_prev = x_0 as a COPY —
    aliasing the live parameter buffers would double-donate them on the
    first step under ``jax.jit(..., donate_argnums=...)``.  Stateful
    ``compression`` adds the carried residual/estimate buffers."""
    st = {"base": base.init(params),
          "psi_prev": jax.tree.map(jnp.array, params)}
    cfg = CP.resolve_compression(compression)
    if CX.stateful(cfg):
        st["compress"] = compression_state(cfg, params, fuse,
                                           fusion_bucket_bytes)
    return st


# ---------------------------------------------------------------------------
# Overlapped stepping: the staleness-1 delayed-mix pipeline
# ---------------------------------------------------------------------------
#
# The synchronous strategies above issue their neighbor exchange on the
# critical path of the step that consumes it.  The reference hides that
# latency with per-parameter backward hooks (optimizers.py:354-414); the
# XLA-native equivalent is to pipeline the mix across STEP boundaries:
#
#   * the jitted step at t FOLDS IN the exchange launched at t-1 (its
#     result rides the carried opt state as in-flight flat buffers — one
#     per dtype bucket, ``ops/fusion.py`` — plus the self weight of the
#     matrix that produced it), and
#   * LAUNCHES the exchange whose result step t+1 will fold.
#
# For the consensus/CTA/AWC family the launch runs on the step's INPUT
# parameters, so inside one program the ppermutes depend only on program
# inputs and their result feeds only a program output: XLA's scheduler is
# free to run the entire forward/backward/update concurrently with the
# collective (with the async-collective flags it emits start/done pairs
# spanning the whole step).  For ATC and exact-diffusion the launch value
# is the adapted iterate, so the collective sits at the program tail; the
# fold still takes it OFF the consuming step's critical path.
#
# Semantics — the self term is always FRESH, the neighbor contributions are
# one step STALE (classic delayed-gossip / staleness-1 mixing):
#
#   consensus:  x_{t+1} = adapt(d_{t-1} x_t + N_{t-1}(x_{t-1}), g(x_t))
#   ATC:        z_t = adapt(x_t, g(x_t));  x_{t+1} = d_{t-1} z_t + N_{t-1}(z_{t-1})
#   exact-diff: same as ATC over the bias-corrected phi iterate
#
# where N_t(x) = C_t(x) - d_t x is the neighbor part of the step-t mix
# C_t and d_t its self weight.  Warmup: the pipeline starts with a ZERO
# buffer and self weight 1, so step 0 is a pure local step (the first
# exchange is in flight); from step 1 on the recurrence above holds
# exactly — bit-for-bit, asserted in tests/test_overlap.py.


def overlap_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the overlapped-stepping gate: explicit argument wins, else
    ``BLUEFOG_COMM_OVERLAP`` (default OFF — staleness-1 mixing is a
    semantic change, unlike fusion, so it is opt-in).  Snapshot at
    build/init time like the fusion knobs: the in-flight buffers live in
    the opt state, so the resolved value shapes the state layout."""
    if flag is not None:
        return bool(flag)
    return os.environ.get("BLUEFOG_COMM_OVERLAP", "0") == "1"


_OVERLAP_COMM_TYPES = (CommunicationType.neighbor_allreduce,
                       CommunicationType.allreduce)


def _check_overlap_comm(comm_type: CommunicationType, sched) -> None:
    if comm_type not in _OVERLAP_COMM_TYPES:
        raise ValueError(
            f"overlapped stepping supports neighbor_allreduce and allreduce "
            f"mixing only (got {comm_type}): hierarchical's two-level mix "
            f"has no single in-flight self weight, and empty has no "
            f"exchange to pipeline")
    if comm_type == CommunicationType.allreduce and sched is not None:
        raise ValueError("dynamic schedules apply to neighbor_allreduce only")


def _mix_self_weight(comm_type: CommunicationType, axis_name,
                     topo: Optional[CompiledTopology],
                     sched: Optional[DynamicSchedule], step):
    """Self weight of the mix the current launch uses, as a traced f32
    scalar.  It rides the in-flight state so the NEXT step's fold pairs
    the stale neighbor sum with the self weight of the same matrix —
    total mass stays 1 even under per-step dynamic schedules."""
    if comm_type == CommunicationType.allreduce:
        return jnp.float32(1.0) / lax.axis_size(axis_name)
    if sched is not None:
        t = jnp.asarray(step) % sched.period
        return jnp.asarray(sched.self_weights,
                           jnp.float32)[t][lax.axis_index(axis_name)]
    return jnp.asarray(topo.self_weights,
                       jnp.float32)[lax.axis_index(axis_name)]


def _inflight_pack(neigh, fuse: bool, bucket_bytes: Optional[int],
                   fusion_groups=None):
    """Neighbor-part tree -> carried representation (flat dtype buckets
    under fusion: the plan is trace-time-cached, the buffers themselves are
    donated with the opt state, so XLA reuses the same handles every
    step)."""
    if not fuse:
        return neigh
    plan = F.plan_for(neigh, max_bucket_bytes=bucket_bytes,
                      leaf_groups=fusion_groups)
    return tuple(F.flatten(plan, neigh))


def _inflight_unpack(bufs, template, fuse: bool,
                     bucket_bytes: Optional[int], fusion_groups=None):
    if not fuse:
        return bufs
    plan = F.plan_for(template, max_bucket_bytes=bucket_bytes,
                      leaf_groups=fusion_groups)
    return F.unflatten(plan, list(bufs))


def _delayed_launch(x, comm_type, axis_name, topo, sched, step,
                    machine_axes, machine_topo, nar_backend,
                    fuse, bucket_bytes, compression=None, comp_state=None,
                    fusion_groups=None, gossip_kernel=None,
                    interleave=False, kernel_mesh_axes=None):
    """Run the exchange on ``x`` and return the in-flight state the NEXT
    step folds: the neighbor part ``C_t(x) - d_t x`` (packed) plus d_t.

    With ``compression`` the launch's WIRE is compressed (direct mode
    only; choco is rejected at build time) — the carried in-flight buffers
    hold the already-DECOMPRESSED neighbor part, and the error-feedback
    residual rides the opt state next to them, double-buffered by the
    same donation discipline.  Returns ``(inflight, comp_state', diag)``
    then."""
    full, cs_new, diag = _communicate_c(
        x, comm_type, axis_name, topo, sched, step, machine_axes,
        machine_topo, nar_backend, fuse, bucket_bytes, compression,
        comp_state, fusion_groups=fusion_groups,
        gossip_kernel=gossip_kernel, interleave=interleave,
        kernel_mesh_axes=kernel_mesh_axes)
    d = _mix_self_weight(comm_type, axis_name, topo, sched, step)
    neigh = jax.tree.map(lambda f, l: f - d.astype(l.dtype) * l, full, x)
    infl = {"bufs": _inflight_pack(neigh, fuse, bucket_bytes,
                                   fusion_groups),
            "self_w": d}
    if compression is not None:
        return infl, cs_new, diag
    return infl


def _delayed_fold(x, inflight, fuse: bool, bucket_bytes: Optional[int],
                  fusion_groups=None):
    """Fold the in-flight neighbor sum with the FRESH self term:
    ``d_prev * x + N_prev``.  At warmup (zero buffer, d=1) this is ``x``."""
    neigh = _inflight_unpack(inflight["bufs"], x, fuse, bucket_bytes,
                             fusion_groups)
    d = inflight["self_w"]
    return jax.tree.map(lambda l, nb: d.astype(l.dtype) * l + nb, x, neigh)


def delayed_init(base: optax.GradientTransformation, params,
                 fuse: Optional[bool] = None,
                 fusion_bucket_bytes: Optional[int] = None,
                 exact_diffusion: bool = False,
                 compression=None):
    """Per-rank init for the overlapped strategies: base state plus the
    warmup in-flight state (zero buffers, self weight 1 — step 0 folds
    nothing and is a pure local step).  ``fuse``/``fusion_bucket_bytes``
    must resolve to the SAME values the step builder will use: the
    carried-buffer layout is part of the state structure.  Stateful
    ``compression`` adds the error-feedback residual buffers next to the
    in-flight exchange buffers (same donation discipline)."""
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    if fuse:
        bufs = F.zero_buffers(F.plan_for(params, max_bucket_bytes=bucket))
    else:
        bufs = jax.tree.map(jnp.zeros_like, params)
    state = {"base": base.init(params),
             "inflight": {"bufs": bufs, "self_w": jnp.float32(1.0)}}
    if exact_diffusion:
        # copy, not alias, for the same donation reason as
        # exact_diffusion_init
        state["psi_prev"] = jax.tree.map(jnp.array, params)
    cfg = CP.resolve_compression(compression)
    if CX.stateful(cfg):
        state["compress"] = compression_state(cfg, params, fuse, bucket)
    return state


def _delayed_snapshot(comm_type, axis_name, topo, sched, step, machine_axes,
                      machine_topo, fuse, bucket, *, new_params, old_params,
                      grads, inflight_prev, diag=None):
    """Snapshot for the overlapped family: staleness 1, warmup derived
    from the folded in-flight state (self weight 1 <=> zero buffer — the
    step-0 / post-reset warmup fold), mix mass of the CURRENT launch."""
    col, row = IG.mix_mass(comm_type, axis_name, topo, sched, step,
                           machine_axes, machine_topo)
    warmup = (inflight_prev["self_w"] >= 1.0).astype(jnp.float32)
    return IG.strategy_snapshot(
        step=step, new_params=new_params, old_params=old_params,
        grads=grads,
        axis_name=_telemetry_axis(comm_type, axis_name, machine_axes),
        col_sum=col, row_sum=row, fuse=fuse, bucket_bytes=bucket,
        staleness=1.0, warmup=warmup, **_comp_snap_kwargs(diag))


def delayed_consensus_step(base: optax.GradientTransformation,
                           comm_type: CommunicationType, axis_name,
                           topo=None, sched=None, machine_axes=None,
                           machine_topo=None, nar_backend=None, fuse=None,
                           fusion_bucket_bytes=None, telemetry: bool = False,
                           compression=None, gossip_kernel=None):
    """Overlapped consensus/CTA/AWC: fold the previous step's mix, adapt at
    the folded point (gradients at the pre-fold parameters, matching
    :func:`consensus_step`'s composition), and launch this step's exchange
    on the INPUT parameters — the flagship overlap case: the collective
    depends only on program inputs and feeds only a program output, so XLA
    schedules it concurrently with the whole forward/backward/update.

    Recurrence (after the step-0 warmup):
    ``x_{t+1} = adapt(d_{t-1} x_t + N_{t-1}(x_{t-1}), g(x_t))``.
    State: ``{"base": ..., "inflight": {"bufs", "self_w"}}`` —
    create it with :func:`delayed_init` using the same fusion knobs.
    ``compression`` (direct specs only): the launch's wire is compressed;
    the carried buffers hold the decompressed neighbor part and the EF
    residual rides the state (``delayed_init(compression=...)``).
    ``gossip_kernel`` as in :func:`consensus_step` (the launch's wire —
    the kernel-fused exchange composes with the pipeline: the carried
    buffers hold the kernel's decoded neighbor part)."""
    _check_overlap_comm(comm_type, sched)
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value=comm_type.value, sched=sched,
                       overlap=True)
    gossip_kernel, interleave = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value=comm_type.value, fuse=fuse)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        mixed = _delayed_fold(params, opt_state["inflight"], fuse, bucket)
        updates, base_new = base.update(grads, opt_state["base"], mixed)
        new_params = optax.apply_updates(mixed, updates)
        launch = _delayed_launch(params, comm_type, axis_name, topo,
                                 sched, step, machine_axes, machine_topo,
                                 nar_backend, fuse, bucket, cfg,
                                 opt_state.get("compress")
                                 if comp_stateful else None,
                                 gossip_kernel=gossip_kernel,
                                 interleave=interleave)
        infl_new, cs_new, diag = (launch if cfg is not None
                                  else (launch, None, None))
        state_new = {"base": base_new, "inflight": infl_new}
        if comp_stateful:
            state_new["compress"] = cs_new
        if telemetry:
            snap = _delayed_snapshot(
                comm_type, axis_name, topo, sched, step, machine_axes,
                machine_topo, fuse, bucket, new_params=new_params,
                old_params=params, grads=grads,
                inflight_prev=opt_state["inflight"], diag=diag)
            return new_params, state_new, snap
        return new_params, state_new

    return step_fn


def delayed_atc_step(base: optax.GradientTransformation,
                     comm_type: CommunicationType, axis_name,
                     topo=None, sched=None, machine_axes=None,
                     machine_topo=None, nar_backend=None, fuse=None,
                     fusion_bucket_bytes=None, telemetry: bool = False,
                     compression=None, gossip_kernel=None):
    """Overlapped adapt-then-combine: local adapt, fold the PREVIOUS
    adapted iterate's exchange, launch this one's.  The launch value is
    the adapted iterate, so the collective sits at the program tail; the
    consuming fold at t+1 still reads only carried state — the exchange
    result never blocks a step's critical path.

    Recurrence (after the step-0 warmup): ``z_t = adapt(x_t, g(x_t));
    x_{t+1} = d_{t-1} z_t + N_{t-1}(z_{t-1})``.  ``compression`` and
    ``gossip_kernel`` as in :func:`delayed_consensus_step` (the adapted
    iterate's wire)."""
    _check_overlap_comm(comm_type, sched)
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value=comm_type.value, sched=sched,
                       overlap=True)
    gossip_kernel, interleave = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value=comm_type.value, fuse=fuse)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        updates, base_new = base.update(grads, opt_state["base"], params)
        adapted = optax.apply_updates(params, updates)
        combined = _delayed_fold(adapted, opt_state["inflight"], fuse,
                                 bucket)
        launch = _delayed_launch(adapted, comm_type, axis_name, topo,
                                 sched, step, machine_axes, machine_topo,
                                 nar_backend, fuse, bucket, cfg,
                                 opt_state.get("compress")
                                 if comp_stateful else None,
                                 gossip_kernel=gossip_kernel,
                                 interleave=interleave)
        infl_new, cs_new, diag = (launch if cfg is not None
                                  else (launch, None, None))
        state_new = {"base": base_new, "inflight": infl_new}
        if comp_stateful:
            state_new["compress"] = cs_new
        if telemetry:
            snap = _delayed_snapshot(
                comm_type, axis_name, topo, sched, step, machine_axes,
                machine_topo, fuse, bucket, new_params=combined,
                old_params=params, grads=grads,
                inflight_prev=opt_state["inflight"], diag=diag)
            return combined, state_new, snap
        return combined, state_new

    return step_fn


def delayed_exact_diffusion_step(base: optax.GradientTransformation,
                                 comm_type: CommunicationType, axis_name,
                                 topo=None, machine_axes=None,
                                 machine_topo=None, nar_backend=None,
                                 fuse=None, fusion_bucket_bytes=None,
                                 telemetry: bool = False,
                                 compression=None, gossip_kernel=None):
    """Overlapped exact-diffusion (the gradient-tracking-family member):
    the psi/phi bias correction runs exactly as in
    :func:`exact_diffusion_step`, but the combine of phi is the delayed
    fold and the launch carries phi's exchange to the next step.  Static
    symmetric topology only, like the synchronous variant (validate with
    :func:`exact_diffusion_topology` first).  Warmup: step 0 reduces to
    the plain local adapt (phi_0 folds against the zero buffer).
    State adds ``psi_prev`` (:func:`delayed_init` with
    ``exact_diffusion=True``).  ``compression`` and ``gossip_kernel``
    as in :func:`delayed_consensus_step` (the phi iterate's wire)."""
    _check_overlap_comm(comm_type, None)
    nar_backend = nar_backend or _api._nar_backend()
    fuse = F.fusion_enabled(fuse)
    bucket = F.resolve_max_bucket_bytes(fusion_bucket_bytes)
    cfg = CP.resolve_compression(compression)
    CX.check_supported(cfg, comm_value=comm_type.value, overlap=True)
    gossip_kernel, interleave = CX.effective_gossip_kernel(
        gossip_kernel, cfg, comm_value=comm_type.value, fuse=fuse)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        updates, base_new = base.update(grads, opt_state["base"], params)
        psi = optax.apply_updates(params, updates)
        phi = jax.tree.map(lambda s, x, sp: s + x - sp,
                           psi, params, opt_state["psi_prev"])
        combined = _delayed_fold(phi, opt_state["inflight"], fuse, bucket)
        launch = _delayed_launch(phi, comm_type, axis_name, topo,
                                 None, step, machine_axes, machine_topo,
                                 nar_backend, fuse, bucket, cfg,
                                 opt_state.get("compress")
                                 if comp_stateful else None,
                                 gossip_kernel=gossip_kernel,
                                 interleave=interleave)
        infl_new, cs_new, diag = (launch if cfg is not None
                                  else (launch, None, None))
        state_new = {"base": base_new, "psi_prev": psi,
                     "inflight": infl_new}
        if comp_stateful:
            state_new["compress"] = cs_new
        if telemetry:
            snap = _delayed_snapshot(
                comm_type, axis_name, topo, None, step, machine_axes,
                machine_topo, fuse, bucket, new_params=combined,
                old_params=params, grads=grads,
                inflight_prev=opt_state["inflight"], diag=diag)
            return combined, state_new, snap
        return combined, state_new

    return step_fn


def delayed_local_step(base: optax.GradientTransformation,
                       telemetry: bool = False):
    """Local-only branch for overlapped steps — the resilience
    integration: besides the plain local adapt, it RESETS the pipeline
    (zero buffers, self weight 1).  A degraded step must not leave the
    old in-flight buffer around: folding it after recovery would mix
    staleness-2+ garbage — and if a rank died mid-pipeline, its
    contribution is already summed into the buffer and cannot be masked
    out post-hoc.  Resetting degrades the NEXT fold to pure self weight
    (the warmup fold), exactly the bounded-staleness semantics
    ``ops/windows.py`` documents for dead neighbors.  Pair with the
    overlapped step via :func:`with_degraded_guard` (both branches carry
    the same state structure, including ``psi_prev`` when present)."""

    def step_fn(params, grads, opt_state, step=0):
        updates, base_new = base.update(grads, opt_state["base"], params)
        new_params = optax.apply_updates(params, updates)
        infl = opt_state["inflight"]
        out = {"base": base_new,
               "inflight": {"bufs": jax.tree.map(jnp.zeros_like,
                                                 infl["bufs"]),
                            "self_w": jnp.ones_like(infl["self_w"])}}
        if "psi_prev" in opt_state:
            # restart the correction at the new local point (plain-ATC
            # restart): the old psi_prev belongs to the abandoned pipeline
            out["psi_prev"] = new_params
        if "compress" in opt_state:
            # same reasoning as the pipeline reset: residuals/replica
            # estimates accumulated against the distrusted topology must
            # not be re-injected after recovery (compress/exchange.py)
            out["compress"] = CX.reset_state(opt_state["compress"])
        if telemetry:
            # degraded pipeline-reset branch: NO collective may be issued
            # (the topology is distrusted), so consensus is UNMEASURED;
            # identity mix, warmup flagged (the next fold is the warmup
            # fold against the freshly zeroed buffer)
            snap = IG.strategy_snapshot(
                step=step, new_params=new_params, old_params=params,
                grads=grads, axis_name=None, col_sum=1.0, row_sum=1.0,
                fuse=False, bucket_bytes=None, staleness=1.0, warmup=1.0,
                degraded=1.0, measure_consensus=False)
            return new_params, out, snap
        return new_params, out

    return step_fn


def with_local_steps(step_fn: Callable, local_step_fn: Callable,
                     num_steps_per_communication: int):
    """Communicate every k-th call, run the local-only update otherwise
    (reference ``num_steps_per_communication``/``backward_passes_per_step``,
    optimizers.py:344-349).  ``step`` may be traced; both branches compile."""
    k = int(num_steps_per_communication)
    if k <= 1:
        return step_fn

    def stepped(params, grads, opt_state, step=0):
        do_comm = (jnp.asarray(step) % k) == (k - 1)
        return jax.lax.cond(
            do_comm,
            lambda p, g, s: step_fn(p, g, s, step),
            lambda p, g, s: local_step_fn(p, g, s, step),
            params, grads, opt_state)

    return stepped


def local_sgd_like_step(base: optax.GradientTransformation,
                        telemetry: bool = False, axis_name=None,
                        fuse=None, fusion_bucket_bytes=None,
                        degraded: bool = False, compression=None):
    """The no-communication branch: plain local update.

    ``telemetry``: return the snapshot too (both ``lax.cond`` branches of
    :func:`with_local_steps` / :func:`with_degraded_guard` must carry the
    same structure).  ``degraded=True`` marks the degraded-guard flavor:
    consensus stays UNMEASURED (a degraded step must issue NO collective)
    and the ``degraded`` field is set; the default (routine local steps of
    a ``num_steps_per_communication`` schedule) measures consensus over
    ``axis_name`` — drift between exchanges is exactly what local-step
    schedules need to watch.

    ``compression``: pass the SAME config the comm branch uses so the
    cond structures match — the local branch carries the
    residual/estimate state through unchanged (EF errors are re-injected
    at the next exchange) except under ``degraded=True``, where it RESETS
    them: the repaired column falls back to self weight and stale
    residuals must not ride into the recovered topology."""
    do_fuse = F.fusion_enabled(fuse)
    cfg = CP.resolve_compression(compression)
    comp_stateful = CX.stateful(cfg)

    def step_fn(params, grads, opt_state, step=0):
        if comp_stateful:
            st, cs = opt_state["base"], opt_state["compress"]
        else:
            st, cs = opt_state, None
        updates, st_new = base.update(grads, st, params)
        new_params = optax.apply_updates(params, updates)
        if comp_stateful:
            out_state = {"base": st_new,
                         "compress": CX.reset_state(cs) if degraded else cs}
        else:
            out_state = st_new
        if telemetry:
            measure = (axis_name is not None) and not degraded
            snap = IG.strategy_snapshot(
                step=step, new_params=new_params, old_params=params,
                grads=grads, axis_name=axis_name, col_sum=1.0, row_sum=1.0,
                fuse=do_fuse, bucket_bytes=fusion_bucket_bytes,
                degraded=1.0 if degraded else 0.0,
                measure_consensus=measure)
            return new_params, out_state, snap
        return new_params, out_state

    return step_fn


def with_degraded_guard(step_fn: Callable, local_step_fn: Callable):
    """Skip-comm branch for degraded steps (resilience integration).

    Returns ``guarded(params, grads, opt_state, step, degraded)``: when the
    traced boolean ``degraded`` is set, the step takes the local-only
    branch — no exchange is issued at all — instead of averaging through a
    topology that membership currently distrusts (suspected stall, link
    storm, watchdog-flagged stragglers; see ``resilience.membership``).

    ``degraded`` is DATA: flipping it between steps reuses one compiled
    program (both branches trace).  It must also be mesh-uniform — every
    rank must take the same branch, or the live ranks' collectives deadlock
    waiting on peers that skipped; derive it from replicated state (the
    fault plan, a majority vote, the service watchdog), never from
    rank-local values.  Per-EDGE degradation belongs in the mixing matrix
    (``repair.repair_matrix_traced``), not here.

    Elastic membership rides the same guard: a joiner that is announced
    or syncing but not yet admitted
    (``resilience.membership.ElasticMembership.degraded``) runs the
    local branch — it trains on its bootstrapped parameters without
    issuing exchanges — until the fleet-uniform admission step flips the
    flag, with zero recompiles (docs/resilience.md "Elastic
    membership").

    Telemetry: build BOTH branches with the same ``telemetry`` flag (the
    local branch via ``local_sgd_like_step(..., degraded=True)`` or
    ``delayed_local_step(..., telemetry=True)``) so the cond outputs
    match; the local branch's snapshot flags ``degraded=1`` — the
    degraded-guard branch-hit series.
    """

    def guarded(params, grads, opt_state, step=0, degraded=False):
        return jax.lax.cond(
            jnp.asarray(degraded, bool),
            lambda p, g, s: local_step_fn(p, g, s, step),
            lambda p, g, s: step_fn(p, g, s, step),
            params, grads, opt_state)

    return guarded
