"""Shared shard_map plumbing for global-view steppers.

Both the optimizer wrappers and the train-step builder run per-rank cores
inside ``shard_map`` over either the flat ``rank`` mesh or the 2-D
``(machine, local)`` mesh; this module is the single home for the
wrap/unwrap and [N] <-> [M, L] reshaping that entails.
"""

from typing import Any, Callable, NamedTuple

import jax
from jax.sharding import PartitionSpec as P


class MeshPlumbing(NamedTuple):
    mesh: Any
    spec: Any
    unwrap: Callable    # strip the per-shard leading singleton axis/axes
    rewrap: Callable    # restore them on outputs
    reshape_in: Callable   # [N, ...] -> mesh-shaped leading dims
    reshape_out: Callable  # and back


def mesh_plumbing(cx, hierarchical: bool) -> MeshPlumbing:
    if hierarchical:
        msize, lsize = cx.machine_size, cx.local_size
        return MeshPlumbing(
            mesh=cx.mesh_2d,
            spec=P(cx.machine_axis, cx.local_axis),
            unwrap=lambda t: jax.tree.map(lambda a: a[0, 0], t),
            rewrap=lambda t: jax.tree.map(lambda a: a[None, None], t),
            reshape_in=lambda t: jax.tree.map(
                lambda a: a.reshape((msize, lsize) + a.shape[1:]), t),
            reshape_out=lambda t: jax.tree.map(
                lambda a: a.reshape((msize * lsize,) + a.shape[2:]), t),
        )
    return MeshPlumbing(
        mesh=cx.mesh,
        spec=P(cx.rank_axis),
        unwrap=lambda t: jax.tree.map(lambda a: a[0], t),
        rewrap=lambda t: jax.tree.map(lambda a: a[None], t),
        reshape_in=lambda t: t,
        reshape_out=lambda t: t,
    )
