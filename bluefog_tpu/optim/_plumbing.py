"""Shared shard_map plumbing for global-view steppers.

Both the optimizer wrappers and the train-step builder run per-rank cores
inside ``shard_map`` over either the flat ``rank`` mesh or the 2-D
``(machine, local)`` mesh; this module is the single home for the
wrap/unwrap and [N] <-> [M, L] reshaping that entails, and for the
step-cache key that decides when a wrapper must rebuild its jitted step.
"""

from typing import Any, Callable, NamedTuple

import jax
from jax.sharding import PartitionSpec as P


def step_cache_key(cx, params, nar_backend: str, fuse: bool,
                   bucket_bytes: int, overlap: bool = False,
                   telemetry: bool = False, compression=None,
                   gossip_axis=None, control: bool = False,
                   gossip_kernel=None):
    """Everything that changes the COMPILED step program: mesh/topology
    identity, the exchange backend, the fusion knobs (they reshape the
    collective schedule), the overlap mode (it reshapes the carried state
    and the whole pipeline), the telemetry gate (it adds the snapshot
    outputs and their pmeans), the compression config (it changes the
    wire dtypes, the collective schedule, and possibly the state layout),
    the gossip axis (the hybrid mesh builders exchange over one named
    axis of a larger mesh — a different axis is a different collective
    schedule), the control gate (``BLUEFOG_CONTROL=on`` threads the γ
    knob through the carried state — the gate itself is keyed; every
    value the controller later actuates is traced data), the gossip-
    kernel mode (``BLUEFOG_GOSSIP_KERNEL`` — it replaces the codec/
    permute/mix chain with one pallas_call per bucket, and its
    interleave hint reorders bucket issue), and the
    parameter tree structure.  One home for the tuple so the wrappers
    and any future cache agree on what invalidates a step — a knob
    resolved at build time but missing here would silently serve a stale
    program."""
    return (id(cx.mesh),
            id(cx._compiled),
            id(cx._compiled_machine),
            nar_backend,
            bool(fuse),
            int(bucket_bytes),
            bool(overlap),
            bool(telemetry),
            None if compression is None else compression.spec,
            gossip_axis,
            bool(control),
            gossip_kernel,
            jax.tree.structure(params))


class MeshPlumbing(NamedTuple):
    mesh: Any
    spec: Any
    unwrap: Callable    # strip the per-shard leading singleton axis/axes
    rewrap: Callable    # restore them on outputs
    reshape_in: Callable   # [N, ...] -> mesh-shaped leading dims
    reshape_out: Callable  # and back


def mesh_plumbing(cx, hierarchical: bool) -> MeshPlumbing:
    if hierarchical:
        msize, lsize = cx.machine_size, cx.local_size
        return MeshPlumbing(
            mesh=cx.mesh_2d,
            spec=P(cx.machine_axis, cx.local_axis),
            unwrap=lambda t: jax.tree.map(lambda a: a[0, 0], t),
            rewrap=lambda t: jax.tree.map(lambda a: a[None, None], t),
            reshape_in=lambda t: jax.tree.map(
                lambda a: a.reshape((msize, lsize) + a.shape[1:]), t),
            reshape_out=lambda t: jax.tree.map(
                lambda a: a.reshape((msize * lsize,) + a.shape[2:]), t),
        )
    return MeshPlumbing(
        mesh=cx.mesh,
        spec=P(cx.rank_axis),
        unwrap=lambda t: jax.tree.map(lambda a: a[0], t),
        rewrap=lambda t: jax.tree.map(lambda a: a[None], t),
        reshape_in=lambda t: t,
        reshape_out=lambda t: t,
    )
