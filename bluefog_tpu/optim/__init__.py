"""Distributed optimizer wrappers (not yet implemented — this package will
hold the CTA/ATC/AWC, gradient-allreduce, and window/push-sum strategies)."""
