"""Distributed optimizer wrappers: the nine reference strategies
(gradient-allreduce, allreduce/neighbor/hierarchical CTA, ATC, AWC,
win-put, pull-get, push-sum) over optax base transformations."""

from .strategies import CommunicationType, with_degraded_guard
from .wrappers import (
    DistributedGradientAllreduceOptimizer,
    DistributedAllreduceOptimizer,
    DistributedNeighborAllreduceOptimizer,
    DistributedHierarchicalNeighborAllreduceOptimizer,
    DistributedAdaptThenCombineOptimizer,
    DistributedAdaptWithCombineOptimizer,
    DistributedExactDiffusionOptimizer,
    DistributedWinPutOptimizer,
    DistributedPullGetOptimizer,
    DistributedPushSumOptimizer,
)
