"""Distributed optimizer factories (reference parity:
``bluefog/torch/optimizers.py:1180-1554`` — the nine public factories).

Each wrapper pairs an ``optax`` base transformation with a communication
strategy and exposes::

    opt = bf.DistributedNeighborAllreduceOptimizer(optax.sgd(0.1))
    state = opt.init(params)                     # params: global view [N, *S]
    params, state = opt.step(params, grads, state, step=i)

The whole step — averaging plus base update over the full parameter pytree —
is one jitted ``shard_map`` program, so XLA overlaps the neighbor traffic
with the update math (the reference needs per-parameter torch hooks to get
that overlap; optimizers.py:354-414).

Reference knobs carried over: ``num_steps_per_communication`` (local steps
between exchanges), mutable per-iteration topology via ``sched=`` (compiled
dynamic schedule; the traced step index selects the edge set), and the
window-based asynchronous family (win-put / pull-get / push-sum) built on
``ops/windows.py``.
"""

import functools
from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

from .. import timeline as _tl
from ..compress import compressors as _cp
from ..compress import exchange as _cx
from ..context import ctx
from ..control import policy as _ctl_policy
from ..observability import commprof as _cprof
from ..observability import ingraph as IG
from ..observability import phases as _ph
from ..ops import api as _api
from ..ops import fusion as _fusion
from ..ops import windows as W
from ..parallel.schedule import DynamicSchedule
from ..utils.compile_cache import note_step_cache
from . import strategies as S
from ._plumbing import mesh_plumbing, step_cache_key

__all__ = [
    "DistributedGradientAllreduceOptimizer",
    "DistributedAllreduceOptimizer",
    "DistributedNeighborAllreduceOptimizer",
    "DistributedHierarchicalNeighborAllreduceOptimizer",
    "DistributedAdaptThenCombineOptimizer",
    "DistributedAdaptWithCombineOptimizer",
    "DistributedExactDiffusionOptimizer",
    "DistributedWinPutOptimizer",
    "DistributedPullGetOptimizer",
    "DistributedPushSumOptimizer",
    "CommunicationType",
]

CommunicationType = S.CommunicationType

# bflint knob-outside-cache-key: per-INSTANCE constants.  The step cache
# lives on the optimizer instance (``self._step_cache``), so a knob fixed
# in ``__init__`` for the instance's lifetime is keyed by instance
# identity and must not churn the tuple; ``sched`` is traced data (the
# step index selects the edge set), ``window_prefix`` names the window
# (identity, not program shape).
_STEP_KEY_EXEMPT_KNOBS = frozenset({
    "atc", "gradient_allreduce", "exact_diffusion",
    "num_steps_per_communication", "sched", "window_prefix",
})


class _JittedStrategyOptimizer:
    """Shared machinery: vmapped base state over ranks, one jitted SPMD step."""

    def __init__(self, base: optax.GradientTransformation,
                 comm_type: CommunicationType,
                 atc: bool = False,
                 gradient_allreduce: bool = False,
                 exact_diffusion: bool = False,
                 num_steps_per_communication: int = 1,
                 sched: Optional[DynamicSchedule] = None,
                 fuse: Optional[bool] = None,
                 fusion_bucket_bytes: Optional[int] = None,
                 overlap: Optional[bool] = None,
                 telemetry: Optional[bool] = None,
                 compression=None,
                 control: Optional[bool] = None,
                 gossip_kernel=None):
        self.base = base
        self.comm_type = comm_type
        self.atc = atc
        self.gradient_allreduce = gradient_allreduce
        self.exact_diffusion = exact_diffusion
        # wire compression (compress/): resolved HERE, like overlap — a
        # stateful config (lossy / choco) shapes the opt-state layout
        # created by init(), so it must bind once for the optimizer's
        # lifetime.  The resolved spec joins the step-cache key.
        self.compression = _cp.resolve_compression(compression)
        _cx.check_supported(
            self.compression,
            comm_value=("allreduce" if gradient_allreduce
                        else comm_type.value),
            sched=sched, overlap=S.overlap_enabled(overlap))
        self._comp_stateful = _cx.stateful(self.compression)
        # single-kernel gossip (BLUEFOG_GOSSIP_KERNEL, compress/exchange.
        # py): validated HERE so a bad combo (sparsifier spec, unfused
        # build, explicit knob without a codec) fails at construction;
        # the raw knob re-resolves at every step build like fuse, and
        # the resolved mode joins the step-cache key.  The state layout
        # is UNCHANGED by the kernel (the EF residual buffers are the
        # same buckets), so the knob composes with checkpoints.
        self.gossip_kernel = gossip_kernel
        _cx.effective_gossip_kernel(
            gossip_kernel, self.compression,
            comm_value=("allreduce" if gradient_allreduce
                        else comm_type.value),
            fuse=_fusion.fusion_enabled(fuse))
        # in-graph telemetry gate (observability/ingraph.py): None =
        # resolve from BLUEFOG_TELEMETRY at step-build time, like the
        # fusion knobs; the resolved value joins the step-cache key.  With
        # telemetry on, step() returns (params, state, TelemetrySnapshot).
        self.telemetry = telemetry
        # comm-fusion knobs (ops/fusion.py): only the EXCHANGE fuses into
        # flat dtype buckets; optimizer state (momentum, psi_prev, accum)
        # stays per-leaf.  None = resolve from BLUEFOG_COMM_FUSION /
        # BLUEFOG_FUSION_BUCKET_BYTES at step-build time (the resolved
        # values join the step-cache key, like the exchange backend).
        self.fuse = fuse
        self.fusion_bucket_bytes = fusion_bucket_bytes
        # overlapped stepping (staleness-1 delayed-mix pipeline,
        # strategies.py): resolved HERE, not per step build — the
        # in-flight buffers live in the opt state created by init(), so
        # the mode (and, under overlap, the fusion knobs shaping those
        # buffers) must bind once for the optimizer's lifetime.
        self.overlap = S.overlap_enabled(overlap)
        if self.overlap:
            if gradient_allreduce:
                raise ValueError(
                    "overlap=True does not apply to gradient allreduce: "
                    "there is no weight exchange to pipeline (the gradient "
                    "average IS the step's input)")
            if comm_type not in (CommunicationType.neighbor_allreduce,
                                 CommunicationType.allreduce):
                raise ValueError(
                    f"overlap=True supports neighbor_allreduce/allreduce "
                    f"mixing only, got {comm_type}")
            if num_steps_per_communication != 1:
                raise ValueError(
                    "overlap=True assumes one exchange per step "
                    "(num_steps_per_communication=1); local-steps schedules "
                    "already take the exchange off most steps entirely")
        if self.overlap or self._comp_stateful:
            # the fusion knobs pin at construction: the carried buffers
            # (in-flight exchange under overlap, residuals/estimates under
            # stateful compression) are laid out by init() and must match
            # every step the builder ever produces
            self._pinned_fuse = _fusion.fusion_enabled(fuse)
            self._pinned_bucket = _fusion.resolve_max_bucket_bytes(
                fusion_bucket_bytes)
        if exact_diffusion and num_steps_per_communication != 1:
            raise ValueError(
                "exact-diffusion's correction assumes one exchange per "
                "adapt step (num_steps_per_communication=1)")
        if exact_diffusion and sched is not None:
            raise ValueError(
                "exact-diffusion requires a static topology: the "
                "correction diverges under dynamic schedules (measured "
                "~1e34 blow-up at lr 0.2 on the quadratic benchmark)")
        self.k = num_steps_per_communication
        self.sched = sched
        # closed-loop controller plumbing (control/): the gate resolves
        # at construction (None = BLUEFOG_CONTROL == "on") and joins the
        # step-cache key; every value the controller later actuates —
        # the schedule mode via the traced step index, the CHOCO gamma
        # scale via the carried compression state — is traced data, so
        # interventions never rebuild the step (tests/test_control.py).
        self._control = (bool(control) if control is not None
                         else _ctl_policy.control_mode() == "on")
        self.control_knobs = {"gamma_scale": 1.0}
        self._controller = None
        self._gamma_plumbed = (self._control
                               and self.compression is not None
                               and self.compression.choco)
        self._step_cache = {}
        # overlap-probe programs (commprof.measure_overlap inputs), keyed
        # like the step cache so knob changes rebuild them in lockstep
        self._probe_cache = {}

    def init(self, params):
        """Base optimizer state, batched over the rank axis (so scalar state
        like momentum/count exists per rank, matching N independent
        reference processes)."""
        cfg = self.compression
        if self.overlap:
            # warmup in-flight state rides along (zero buffers, self
            # weight 1): the SAME fusion knobs the step builder will use
            return jax.vmap(lambda p: S.delayed_init(
                self.base, p, fuse=self._pinned_fuse,
                fusion_bucket_bytes=self._pinned_bucket,
                exact_diffusion=self.exact_diffusion,
                compression=cfg))(params)
        if self.gradient_allreduce and self.k > 1:
            return jax.vmap(lambda p: S.grad_accum_init(
                self.base, p, compression=cfg,
                fuse=self._pinned_fuse if self._comp_stateful else None,
                fusion_bucket_bytes=(self._pinned_bucket
                                     if self._comp_stateful else None))
            )(params)
        if self.exact_diffusion:
            # psi_prev carries the rank axis already (it IS the params)
            return jax.vmap(
                lambda p: S.exact_diffusion_init(
                    self.base, p, compression=cfg,
                    fuse=self._pinned_fuse if self._comp_stateful else None,
                    fusion_bucket_bytes=(self._pinned_bucket
                                         if self._comp_stateful else None))
            )(params)
        if self._comp_stateful:
            # plain consensus/CTA/ATC family: the state gains the carried
            # residual/estimate buffers ({"base", "compress"})
            return jax.vmap(lambda p: S.compress_wrap_init(
                self.base, p, cfg, fuse=self._pinned_fuse,
                fusion_bucket_bytes=self._pinned_bucket))(params)
        return jax.vmap(self.base.init)(params)

    def _build(self, key, telemetry: bool = False):
        cx = ctx()
        hierarchical = (
            self.comm_type == CommunicationType.hierarchical_neighbor_allreduce)
        topo = None
        machine_topo = None
        if self.comm_type == CommunicationType.neighbor_allreduce and self.sched is None:
            topo = cx.compiled_topology
        if hierarchical:
            machine_topo = cx.compiled_machine_topology

        if self.overlap or self._comp_stateful:
            fuse, bucket_bytes = self._pinned_fuse, self._pinned_bucket
        else:
            fuse = _fusion.fusion_enabled(self.fuse)
            bucket_bytes = _fusion.resolve_max_bucket_bytes(
                self.fusion_bucket_bytes)
        cfg = self.compression
        gk_mode, _ = _cx.effective_gossip_kernel(
            self.gossip_kernel, cfg,
            comm_value=("allreduce" if self.gradient_allreduce
                        else self.comm_type.value),
            fuse=fuse)
        if self.overlap:
            if self.exact_diffusion:
                if self.comm_type == CommunicationType.neighbor_allreduce:
                    topo = S.exact_diffusion_topology(cx.compiled_topology)
                step_core = S.delayed_exact_diffusion_step(
                    self.base, self.comm_type, cx.rank_axis, topo=topo,
                    machine_axes=(cx.machine_axis, cx.local_axis),
                    machine_topo=machine_topo, fuse=fuse,
                    fusion_bucket_bytes=bucket_bytes, telemetry=telemetry,
                    compression=cfg, gossip_kernel=self.gossip_kernel)
            else:
                builder = (S.delayed_atc_step if self.atc
                           else S.delayed_consensus_step)
                step_core = builder(
                    self.base, self.comm_type, cx.rank_axis, topo=topo,
                    sched=self.sched,
                    machine_axes=(cx.machine_axis, cx.local_axis),
                    machine_topo=machine_topo, fuse=fuse,
                    fusion_bucket_bytes=bucket_bytes, telemetry=telemetry,
                    compression=cfg, gossip_kernel=self.gossip_kernel)
        elif self.gradient_allreduce:
            step_core = S.gradient_allreduce_step(
                self.base, cx.rank_axis, accumulate_steps=self.k,
                fuse=fuse, fusion_bucket_bytes=bucket_bytes,
                telemetry=telemetry, compression=cfg)
        elif self.exact_diffusion:
            if self.comm_type not in (
                    CommunicationType.neighbor_allreduce,
                    CommunicationType.allreduce):
                raise ValueError(
                    "exact-diffusion supports neighbor_allreduce (symmetric "
                    "topology) or allreduce mixing only")
            if self.comm_type == CommunicationType.neighbor_allreduce:
                topo = S.exact_diffusion_topology(cx.compiled_topology)
            step_core = S.exact_diffusion_step(
                self.base, self.comm_type, cx.rank_axis, topo=topo,
                sched=self.sched,
                machine_axes=(cx.machine_axis, cx.local_axis),
                machine_topo=machine_topo, fuse=fuse,
                fusion_bucket_bytes=bucket_bytes, telemetry=telemetry,
                compression=cfg, gossip_kernel=self.gossip_kernel)
        else:
            builder = S.atc_step if self.atc else S.consensus_step
            step_core = builder(
                self.base, self.comm_type, cx.rank_axis, topo=topo,
                sched=self.sched,
                machine_axes=(cx.machine_axis, cx.local_axis),
                machine_topo=machine_topo, fuse=fuse,
                fusion_bucket_bytes=bucket_bytes, telemetry=telemetry,
                compression=cfg, gossip_kernel=self.gossip_kernel)
        if not (self.gradient_allreduce or self.exact_diffusion
                or self.overlap):
            # grad-allreduce accumulates internally; exact-diffusion and
            # overlap are one-exchange-per-step by construction.  The local
            # branch must mirror the comm branch's telemetry AND
            # compression-state structure.
            tel_axis = S._telemetry_axis(
                self.comm_type, cx.rank_axis,
                (cx.machine_axis, cx.local_axis))
            step_core = S.with_local_steps(
                step_core,
                S.local_sgd_like_step(self.base, telemetry=telemetry,
                                      axis_name=tel_axis, fuse=fuse,
                                      fusion_bucket_bytes=bucket_bytes,
                                      compression=cfg),
                self.k)

        pl = mesh_plumbing(cx, hierarchical)

        def stepper(params, grads, opt_state, step_idx):
            def shard_fn(p, g, st, si):
                out = step_core(
                    pl.unwrap(p), pl.unwrap(g), pl.unwrap(st), si)
                if telemetry:
                    p_new, st_new, snap = out
                    return (pl.rewrap(p_new), pl.rewrap(st_new),
                            pl.rewrap(snap))
                p_new, st_new = out
                return pl.rewrap(p_new), pl.rewrap(st_new)
            p2, g2, st2 = (pl.reshape_in(params), pl.reshape_in(grads),
                           pl.reshape_in(opt_state))
            n_out = 3 if telemetry else 2
            # check_vma off under the pallas backend AND the gossip
            # kernel (same exemption as ops/api.py / training.py: a
            # pallas kernel's outputs carry no varying-manual-axes tags)
            out = jax.shard_map(
                shard_fn, mesh=pl.mesh,
                in_specs=(pl.spec, pl.spec, pl.spec, P()),
                out_specs=(pl.spec,) * n_out,
                check_vma=not (_api._nar_backend().startswith("pallas")
                               or gk_mode in ("pallas", "interpret")),
            )(p2, g2, st2, step_idx)
            return tuple(pl.reshape_out(o) for o in out)

        return jax.jit(stepper)

    def _exec_config(self, params):
        """Resolve the per-call execution knobs and the step-cache key —
        the ONE copy :meth:`step` and :meth:`probe_overlap` share.  A
        drifted second copy would make the probe price a DIFFERENT
        program than the step actually runs, and the measured overlap
        efficiency (and the ``overlap_collapse`` health rule) would
        judge the wrong exchange with no test failing."""
        cx = ctx()
        # under overlap / stateful compression the fusion knobs were
        # pinned at construction (they shape the carried buffers created
        # by init())
        if self.overlap or self._comp_stateful:
            fuse, bucket = self._pinned_fuse, self._pinned_bucket
        else:
            fuse = _fusion.fusion_enabled(self.fuse)
            bucket = _fusion.resolve_max_bucket_bytes(
                self.fusion_bucket_bytes)
        telemetry = IG.telemetry_enabled(self.telemetry)
        key = step_cache_key(cx, params, _api._nar_backend(), fuse, bucket,
                             self.overlap, telemetry, self.compression,
                             gossip_axis=cx.rank_axis,
                             control=self._control,
                             gossip_kernel=_cx.resolve_gossip_kernel(
                                 self.gossip_kernel))
        return fuse, bucket, telemetry, key

    # -- closed-loop controller hook (control/) ------------------------------

    def attach_controller(self, controller) -> None:
        """Attach a controller/actuator (``control.Controller`` or a bare
        ``control.Actuator``).  The object supplies ``graph_step(step)``
        — the traced step index actually dispatched (a
        ``SwitchableSchedule`` selects its mode this way) — and
        ``after_step(step)``, invoked after every dispatch (where the
        Controller runs its sensing/policy pass)."""
        self._controller = controller

    def detach_controller(self) -> None:
        self._controller = None

    def _with_control_state(self, opt_state):
        """Inject the current γ scale as a traced leaf of the carried
        compression state (``control=True`` + choco only).  The value
        lives in ``self.control_knobs`` (the actuator's write target);
        re-injected every call, so the program only ever sees a stable
        state STRUCTURE with a varying traced value — backoff/re-arm
        never retrace."""
        if not self._gamma_plumbed:
            return opt_state
        comp = dict(opt_state["compress"])
        # [N] like every carried state leaf (the step shard_maps the
        # state over the rank axis; each rank sees its scalar)
        comp["gamma_scale"] = jnp.full(
            (ctx().size,), self.control_knobs.get("gamma_scale", 1.0),
            jnp.float32)
        out = dict(opt_state)
        out["compress"] = comp
        return out

    def step(self, params, grads, opt_state, step: int = 0):
        """One optimizer step.  Returns ``(params, opt_state)`` — plus a
        global-view :class:`~..observability.ingraph.TelemetrySnapshot`
        (``[N]`` per field) when telemetry resolves on."""
        # the controller hook remaps the step index (a SwitchableSchedule
        # mode select — pure traced data) and injects the current γ scale
        ctl = self._controller
        gstep = step if ctl is None else ctl.graph_step(step)
        _fuse, _bucket, telemetry, key = self._exec_config(params)
        hit = key in self._step_cache
        note_step_cache(hit)
        if not hit:
            self._step_cache[key] = self._build(key, telemetry)
        # periodic overlap measurement (BLUEFOG_OVERLAP_PROBE_EVERY):
        # re-price the exposed/hidden exchange split every K-th step
        # while profiling is on; the sample stages the
        # `overlap_efficiency` JSONL field the health engine watches
        every = _cprof.overlap_probe_every()
        if every and _ph.profiling_active() and int(step) % every == 0:
            self.probe_overlap(params, grads, opt_state, gstep)
        opt_state = self._with_control_state(opt_state)
        # `compute` phase = the whole jitted dispatch: for this family
        # the exchange is fused INTO the graph, so exchange/fold have no
        # separate host extent (the window family times them apart).
        # The gossip-round span is the cross-rank sync anchor bftrace
        # aligns per-rank clocks with.
        tok = _tl.op_start_us()
        with _ph.step_phase("compute"):
            out = self._step_cache[key](params, grads, opt_state,
                                        jnp.asarray(gstep, jnp.int32))
            if _tl.timeline_enabled():
                # the round span must end when the COLLECTIVE finishes,
                # not when the host finishes enqueueing — ranks run ahead
                # of the device by different queue depths, and bftrace's
                # clock alignment reads span ends as collective-
                # completion times.  Tracing pays the run-ahead loss;
                # the un-traced hot path stays fully async.
                jax.block_until_ready(out)
        _tl.record_gossip_round(step, tok)
        if ctl is not None:
            # the sensing/policy pass (control.Controller.after_step)
            # runs AFTER the dispatch, before the caller logs step t —
            # so an evaluation at step t sees records <= t-1, the same
            # cutoff `bfctl replay` applies (trail determinism)
            ctl.after_step(step)
        return out

    def _comm_layout(self):
        """``(comm_type, topo, machine_topo, hierarchical)`` of the
        exchange this optimizer runs — MUST mirror how :meth:`_build`'s
        branches resolve them (grad-allreduce maps to allreduce mixing,
        exact-diffusion folds the topology, hierarchical adds the
        machine topo), or :meth:`probe_overlap` prices a different
        exchange than the step executes."""
        cx = ctx()
        hierarchical = (self.comm_type
                        == CommunicationType.hierarchical_neighbor_allreduce)
        comm_type = (CommunicationType.allreduce if self.gradient_allreduce
                     else self.comm_type)
        topo = None
        machine_topo = None
        if (comm_type == CommunicationType.neighbor_allreduce
                and self.sched is None):
            topo = cx.compiled_topology
            if self.exact_diffusion:
                topo = S.exact_diffusion_topology(cx.compiled_topology)
        if hierarchical:
            machine_topo = cx.compiled_machine_topology
        return comm_type, topo, machine_topo, hierarchical

    def _build_comm_probe(self, fuse, bucket_bytes):
        """Exchange-only jitted program: prices the step's FULL exchange
        (same topology/schedule/backend/fusion/compression knobs) for
        :meth:`probe_overlap`'s efficiency denominator."""
        cx = ctx()
        comm_type, topo, machine_topo, hierarchical = self._comm_layout()
        cfg = self.compression
        stateful = self._comp_stateful
        backend = _api._nar_backend()
        gk_mode, gk_interleave = _cx.effective_gossip_kernel(
            self.gossip_kernel, cfg,
            comm_value=("allreduce" if self.gradient_allreduce
                        else self.comm_type.value),
            fuse=fuse)
        pl = mesh_plumbing(cx, hierarchical)
        check_vma = not (backend.startswith("pallas")
                         or gk_mode in ("pallas", "interpret"))

        def core(tree_s, cs_s, si):
            out = S._communicate_c(
                pl.unwrap(tree_s), comm_type, cx.rank_axis, topo,
                self.sched, si, (cx.machine_axis, cx.local_axis),
                machine_topo, backend, fuse, bucket_bytes, cfg,
                pl.unwrap(cs_s) if stateful else None,
                gossip_kernel=gk_mode, interleave=gk_interleave)
            return pl.rewrap(out[0])

        if stateful:
            def comm_fn(tree, cs, step_idx):
                return pl.reshape_out(jax.shard_map(
                    core, mesh=pl.mesh,
                    in_specs=(pl.spec, pl.spec, P()), out_specs=pl.spec,
                    check_vma=check_vma,
                )(pl.reshape_in(tree), pl.reshape_in(cs), step_idx))
        else:
            def comm_fn(tree, step_idx):
                return pl.reshape_out(jax.shard_map(
                    lambda t, si: core(t, None, si), mesh=pl.mesh,
                    in_specs=(pl.spec, P()), out_specs=pl.spec,
                    check_vma=check_vma,
                )(pl.reshape_in(tree), step_idx))
        return jax.jit(comm_fn)

    def probe_overlap(self, params, grads, opt_state, step: int = 0,
                      repeats: int = 2):
        """Measure this optimizer's exposed/hidden exchange split
        (:func:`~..observability.commprof.measure_overlap`).

        Times three non-donating programs on the given arguments: the
        cached step, a pruned variant whose carried ``inflight`` (and
        ``compress``) state passes through unchanged — so XLA
        dead-code-eliminates the delayed-mix LAUNCH, leaving exactly the
        parameter critical path — and the exchange alone.  Returns an
        :class:`~..observability.commprof.OverlapSample` (efficiency ~0
        = synchronous, ~1 = fully pipelined), or None when the step has
        no exchange to price.  Stages the ``overlap_efficiency`` JSONL
        field and ``bf_overlap`` gauges as a side effect."""
        if (self.comm_type == CommunicationType.empty
                and not self.gradient_allreduce):
            return None
        # under control the probe prices the SAME state structure the
        # step dispatches (γ-scale leaf injected)
        opt_state = self._with_control_state(opt_state)
        fuse, bucket, telemetry, key = self._exec_config(params)
        if key not in self._step_cache:
            self._step_cache[key] = self._build(key, telemetry)
        full = self._step_cache[key]
        probes = self._probe_cache.get(key)
        if probes is None:
            def pruned_fn(p, g, s, i):
                out = full(p, g, s, i)
                st = out[1]
                if isinstance(st, dict):
                    # pass the carried launch products through unchanged:
                    # the collectives feeding only them go dead and XLA
                    # removes them — what remains IS the params critical
                    # path.  (Without overlap the exchange feeds params
                    # directly and survives: hidden time reads ~0.)
                    keep = {k: s[k] for k in ("inflight", "compress")
                            if k in st}
                    if keep:
                        st = {**st, **keep}
                # the telemetry snapshot is dropped: its compression
                # diagnostics would keep the pruned launch alive
                return out[0], st
            probes = (jax.jit(pruned_fn), self._build_comm_probe(
                fuse, bucket))
            self._probe_cache[key] = probes
        pruned, comm = probes
        si = jnp.asarray(step, jnp.int32)
        target = grads if self.gradient_allreduce else params
        if self._comp_stateful:
            comm_args = (target, opt_state["compress"], si)
        else:
            comm_args = (target, si)
        return _cprof.measure_overlap(
            full, pruned, comm, (params, grads, opt_state, si),
            comm_args, repeats=repeats)


def DistributedGradientAllreduceOptimizer(base, num_steps_per_communication=1,
                                          fuse=None, fusion_bucket_bytes=None,
                                          telemetry=None, compression=None):
    """Synchronous Horovod-style gradient averaging
    (optimizers.py:1376; internal _DistributedOptimizer:166-294).

    ``telemetry`` (default ``BLUEFOG_TELEMETRY``, off): ``step()``
    additionally returns a per-rank ``TelemetrySnapshot``
    (docs/observability.md); off is bit-identical to the plain step."""
    return _JittedStrategyOptimizer(
        base, CommunicationType.empty, gradient_allreduce=True,
        num_steps_per_communication=num_steps_per_communication,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes,
        telemetry=telemetry, compression=compression)


def DistributedAllreduceOptimizer(base, num_steps_per_communication=1,
                                  fuse=None, fusion_bucket_bytes=None,
                                  overlap=None, telemetry=None,
                                  compression=None, control=None):
    """CTA with global weight averaging (optimizers.py:1301)."""
    return _JittedStrategyOptimizer(
        base, CommunicationType.allreduce,
        num_steps_per_communication=num_steps_per_communication,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes, overlap=overlap,
        telemetry=telemetry, compression=compression, control=control)


def DistributedNeighborAllreduceOptimizer(base, num_steps_per_communication=1,
                                          sched: Optional[DynamicSchedule] = None,
                                          fuse=None, fusion_bucket_bytes=None,
                                          overlap=None, telemetry=None,
                                          compression=None, control=None,
                                          gossip_kernel=None):
    """CTA with (possibly dynamic) neighbor averaging — the flagship
    decentralized optimizer (optimizers.py:1326).

    ``overlap`` (default ``BLUEFOG_COMM_OVERLAP``, off): staleness-1
    delayed-mix pipeline — the step folds the PREVIOUS step's exchange and
    launches its own off the critical path (docs/performance.md
    "Overlap").  Changes the recurrence (fresh self term, one-step-stale
    neighbor terms); keep it off for exact-averaging tests.

    ``telemetry`` (default ``BLUEFOG_TELEMETRY``, off): ``step()`` returns
    ``(params, state, TelemetrySnapshot)`` — consensus distance, mixing
    mass, norms, pipeline flags per rank (docs/observability.md).

    ``control`` (default ``BLUEFOG_CONTROL == "on"``): thread the
    closed-loop controller's runtime knobs through the step — the
    schedule mode of an attached ``control.SwitchableSchedule`` (via the
    traced step index) and the CHOCO γ scale (via the carried
    compression state).  Attach with
    ``control.Controller(opt, ...)`` (docs/control.md)."""
    return _JittedStrategyOptimizer(
        base, CommunicationType.neighbor_allreduce,
        num_steps_per_communication=num_steps_per_communication, sched=sched,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes, overlap=overlap,
        telemetry=telemetry, compression=compression, control=control,
        gossip_kernel=gossip_kernel)


def DistributedHierarchicalNeighborAllreduceOptimizer(
        base, num_steps_per_communication=1, fuse=None,
        fusion_bucket_bytes=None, telemetry=None, compression=None):
    """CTA with machine-level neighbor averaging (optimizers.py:1352).
    ``compression`` is accepted for API uniformity but any non-off value
    is rejected with guidance (the two-level mix has no compressed wire
    format yet; see docs/compression.md)."""
    return _JittedStrategyOptimizer(
        base, CommunicationType.hierarchical_neighbor_allreduce,
        num_steps_per_communication=num_steps_per_communication,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes,
        telemetry=telemetry, compression=compression)


def DistributedAdaptThenCombineOptimizer(
        base, communication_type=CommunicationType.neighbor_allreduce,
        num_steps_per_communication=1,
        sched: Optional[DynamicSchedule] = None,
        fuse=None, fusion_bucket_bytes=None, overlap=None, telemetry=None,
        compression=None, control=None, gossip_kernel=None):
    """ATC: local update inside the step, then communicate the adapted
    weights (optimizers.py:1426; internal :485-841).  ``overlap``: the
    combine of the adapted iterate lands one step later (staleness-1
    delayed mix, docs/performance.md "Overlap")."""
    return _JittedStrategyOptimizer(
        base, communication_type, atc=True,
        num_steps_per_communication=num_steps_per_communication, sched=sched,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes, overlap=overlap,
        telemetry=telemetry, compression=compression, control=control,
        gossip_kernel=gossip_kernel)


def DistributedAdaptWithCombineOptimizer(
        base, communication_type=CommunicationType.neighbor_allreduce,
        num_steps_per_communication=1,
        sched: Optional[DynamicSchedule] = None,
        fuse=None, fusion_bucket_bytes=None, overlap=None, telemetry=None,
        compression=None, control=None, gossip_kernel=None):
    """AWC: update and communication computed concurrently
    (optimizers.py:1497).  Same fixed point as consensus/CTA; XLA already
    runs the collective and the update math in parallel.  ``overlap``
    goes further: the exchange result is consumed one step later, taking
    even its LATENCY off the critical path (shared delayed-consensus
    implementation; docs/performance.md "Overlap")."""
    return _JittedStrategyOptimizer(
        base, communication_type, atc=False,
        num_steps_per_communication=num_steps_per_communication, sched=sched,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes, overlap=overlap,
        telemetry=telemetry, compression=compression, control=control,
        gossip_kernel=gossip_kernel)


def DistributedExactDiffusionOptimizer(
        base, communication_type=CommunicationType.neighbor_allreduce,
        fuse=None, fusion_bucket_bytes=None, overlap=None, telemetry=None,
        compression=None, control=None, gossip_kernel=None):
    """Exact-Diffusion / D2 (beyond-reference; the bias-corrected
    diffusion from the BlueFog authors' research line): ATC with the
    psi-correction, so constant-step-size decentralized training reaches
    the EXACT global optimum under heterogeneous per-rank objectives
    instead of an O(alpha*zeta) neighborhood.  See
    optim/strategies.py::exact_diffusion_step.

    STATIC mixing only: the correction's convergence theory assumes a
    fixed doubly-stochastic W, and empirically the recursion DIVERGES
    under a dynamic one-peer schedule (measured blow-up to ~1e34 at
    lr 0.2 on the quadratic benchmark) — so ``sched=`` is deliberately
    not accepted; use the neighbor-CTA/ATC families for time-varying
    graphs.

    ``overlap``: the phi-combine lands one step later (staleness-1 delayed
    mix with a documented warmup local step — the gradient-tracking-family
    member of the pipeline, strategies.delayed_exact_diffusion_step)."""
    return _JittedStrategyOptimizer(
        base, communication_type, exact_diffusion=True,
        fuse=fuse, fusion_bucket_bytes=fusion_bucket_bytes, overlap=overlap,
        telemetry=telemetry, compression=compression, control=control,
        gossip_kernel=gossip_kernel)


# ---------------------------------------------------------------------------
# Window-based asynchronous family
# ---------------------------------------------------------------------------

class _WindowOptimizerBase:
    """Shared state for the win-put / pull-get / push-sum wrappers: ONE
    window holding the whole parameter pytree, so every communication
    step is one jitted SPMD program over all leaves — the TPU-native
    fusion-buffer (the reference registers one window per tensor,
    optimizers.py:933-944, and fuses transmissions into a single buffer
    in the controller, mpi_controller.cc:561-743; here the fusion is the
    program itself)."""

    _instance_counter = [0]   # default names stay unique AND deterministic

    def __init__(self, base, window_prefix: Optional[str] = None,
                 num_steps_per_communication: int = 1,
                 telemetry: Optional[bool] = None,
                 compression=None):
        self.base = base
        if window_prefix is None:
            # deterministic per creation order, so same-program checkpoint
            # restores line up; pass window_prefix for stable custom names
            window_prefix = f"win_opt{self._instance_counter[0]}"
            self._instance_counter[0] += 1
        self._name = window_prefix + ".params"
        self.k = num_steps_per_communication
        self._created = False
        # in-graph telemetry now extends to the window family (the old
        # 2-tuple pin is gone): the local-adapt core carries the snapshot
        # — consensus distance over the post-window-average weights plus
        # the norm trio; identity mix mass (the window fold's weights live
        # host-side, watch them via the metrics registry).  With telemetry
        # resolved on, step() returns (params, state, TelemetrySnapshot).
        self.telemetry = telemetry
        self._local = _JittedStrategyOptimizer(base, CommunicationType.empty,
                                               telemetry=telemetry)
        # wire compression for the window transfer ops rides win_create
        # (the window owns the wire format; direct specs only)
        self.compression = _cp.resolve_compression(compression)
        # mutable per-iteration weighting knobs (matrices), reference
        # optimizers.py:852-858
        self.dst_weights = None
        self.src_weights = None

    def _require_init(self):
        if not self._created:
            raise RuntimeError(
                "window optimizer used before init(); call "
                "state = opt.init(params) first to create the windows")

    def init(self, params, zero_init: bool = False):
        if not W.win_create(params, self._name, zero_init=zero_init,
                            compression=self.compression):
            raise ValueError(f"Cannot allocate window for {self._name}")
        self._created = True
        return self._local.init(params)

    def free(self):
        if self._name in W.get_current_created_window_names():
            W.win_free(self._name)
        self._created = False

    def _apply_base(self, params, grads, opt_state, step):
        return self._local.step(params, grads, opt_state, step)

    def _should_communicate(self, step: int) -> bool:
        """Communicate on every k-th step (reference
        num_steps_per_communication, optimizers.py:344-349)."""
        return self.k <= 1 or (int(step) % self.k) == (self.k - 1)


class DistributedWinPutOptimizer(_WindowOptimizerBase):
    """Push flavor (optimizers.py:1271): put weights to (dynamic)
    out-neighbors, fold buffers with win_update, then local update —
    the whole parameter tree in one program per phase."""

    def step(self, params, grads, opt_state, step: int = 0):
        self._require_init()
        if not self._should_communicate(step):
            return self._apply_base(params, grads, opt_state, step)
        # step-phase timers (observability/phases.py): `exchange` = the
        # one-sided launch + wait, `fold` = the buffer average; the local
        # adapt inside _apply_base times itself as `compute`.  The
        # gossip-round span anchors bftrace's cross-rank clock alignment.
        tok = _tl.op_start_us()
        with _ph.step_phase("exchange"):
            W.win_wait(W.win_put_nonblocking(params, self._name,
                                             dst_weights=self.dst_weights))
        _tl.record_gossip_round(step, tok)
        with _ph.step_phase("fold"):
            averaged = W.win_update(self._name, require_mutex=True)
        return self._apply_base(averaged, grads, opt_state, step)


class DistributedPullGetOptimizer(_WindowOptimizerBase):
    """Pull flavor (optimizers.py:1225): win_get from (dynamic) in-neighbors
    instead of pushing."""

    def step(self, params, grads, opt_state, step: int = 0):
        self._require_init()
        if not self._should_communicate(step):
            return self._apply_base(params, grads, opt_state, step)
        # publish current weights in the window, then pull neighbors'
        tok = _tl.op_start_us()
        with _ph.step_phase("exchange"):
            W.win_publish(self._name, params)
            W.win_wait(W.win_get_nonblocking(self._name,
                                             src_weights=self.src_weights))
        _tl.record_gossip_round(step, tok)
        with _ph.step_phase("fold"):
            averaged = W.win_update(self._name, require_mutex=True)
        return self._apply_base(averaged, grads, opt_state, step)


class DistributedPushSumOptimizer(_WindowOptimizerBase):
    """Gradient-push / push-sum (optimizers.py:1180; internal :1026-1177).

    Windows hold the biased iterate x with the associated-P scalar riding
    every op; the user-visible parameters are the de-biased x/p.  Per step:
    local update on the biased iterate, self-scaled push-accumulate with
    weight 1/(out_degree+1), collect, de-bias.

    ``sched=`` runs the accumulate over a per-step dynamic edge set (the
    push-sum paper's actual one-peer schedule — reference usage
    torch/mpi_ops.py:1144-1209 with per-iteration dst_weights); the
    schedule's matrices must be column-stochastic (one-peer schedules
    from ``compile_dynamic_schedule`` are) so mass is conserved."""

    def __init__(self, base, window_prefix: Optional[str] = None,
                 num_steps_per_communication: int = 1, sched=None,
                 telemetry: Optional[bool] = None, compression=None):
        super().__init__(base, window_prefix, num_steps_per_communication,
                         telemetry=telemetry, compression=compression)
        self.sched = sched

    def init(self, params):
        W.turn_on_win_ops_with_associated_p()
        cx = ctx()
        A = (cx.compiled_topology.weight_matrix != 0).astype(np.float64)
        np.fill_diagonal(A, 0.0)
        # per-rank alpha_i = 1/(out_degree_i + 1) keeps each column of the
        # push matrix summing to 1 (mass conservation) even when out-degrees
        # differ (optimizers.py:1032-1035 computes this per process)
        outdeg = A.sum(axis=1)
        self.alpha = 1.0 / (outdeg + 1.0)          # [N]
        self.dst_weights = A * self.alpha[:, None]
        return super().init(params, zero_init=True)

    def _debias(self, tree):
        p = W.win_associated_p_vector(self._name)  # [N] device, no host sync
        return jax.tree.map(
            lambda leaf: leaf / p.reshape(
                (-1,) + (1,) * (leaf.ndim - 1)).astype(leaf.dtype), tree)

    def step(self, params, grads, opt_state, step: int = 0):
        self._require_init()
        if not self._should_communicate(step):
            # local step: adapt the *biased* window iterate so the update
            # survives the next collect (gradients are at the de-biased view)
            biased = W.win_fetch(self._name)
            out = self._apply_base(biased, grads, opt_state, step)
            adapted, opt_state = out[0], out[1]
            W.win_publish(self._name, adapted)
            if len(out) == 3:           # telemetry snapshot rides along
                return self._debias(adapted), opt_state, out[2]
            return self._debias(adapted), opt_state
        # the biased iterate lives in the window; `params` is the de-biased
        # view; local adapt on the biased variable with gradients at the
        # de-biased point (stochastic gradient-push)
        biased = W.win_fetch(self._name)
        out = self._apply_base(biased, grads, opt_state, step)
        adapted, opt_state = out[0], out[1]
        tok = _tl.op_start_us()
        with _ph.step_phase("exchange"):
            if self.sched is not None:
                W.win_accumulate(adapted, self._name, require_mutex=True,
                                 sched=self.sched, step=step)
            else:
                W.win_accumulate(adapted, self._name,
                                 self_weight=self.alpha,
                                 dst_weights=self.dst_weights,
                                 require_mutex=True)
        _tl.record_gossip_round(step, tok)
        with _ph.step_phase("fold"):
            collected = W.win_update_then_collect(self._name)
        if len(out) == 3:
            return self._debias(collected), opt_state, out[2]
        return self._debias(collected), opt_state
