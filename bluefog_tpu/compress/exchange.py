"""Compressed gossip exchange over the fusion layer's flat buffers.

The uncompressed strategies move every fused bucket across the wire at
full parameter precision (``optim/strategies._communicate``).  This module
is the compressed drop-in: the SAME mixing weights and collective
schedule, but the ``lax.ppermute``/``all_gather`` payload is each bucket's
*wire* encoding (``compress/compressors.py``) — int8/fp8 quantized or
top-k/random-k sparsified — with per-bucket f32 scales riding alongside.

Three exchange disciplines, selected by the :class:`~.compressors.
CompressionConfig`:

* **direct** (default): receivers mix ``self_w * x_i + sum_j w_ij
  D(C(x_j + e_j))`` — the self term is the rank's TRUE value (never
  compressed), and the **error-feedback residual** ``e_j = (x_j + e_j) -
  D(C(x_j + e_j))`` is carried in the donated opt state (the PR-3 overlap
  buffer pattern) and re-injected next step, so quantization error
  accumulates into later transmissions instead of being lost.
* **allreduce** flavor of direct: global averaging ships compressed
  payloads via ``all_gather`` and reduces locally (the GRACE-style
  compressed allreduce); lossless compressors short-circuit to the plain
  ``pmean`` (bit-exact).
* **CHOCO** (``choco:`` specs): difference gossip (Koloskova et al.,
  CHOCO-SGD).  Each rank carries its own public replica estimate
  ``x_hat_i`` plus the weighted neighbor-estimate sum ``s_i = sum_j W[j,i]
  x_hat_j``; only the compressed DELTA ``C(x_i - x_hat_i)`` crosses the
  wire, every holder applies the identical decompressed delta (the
  determinism contract in ``compressors.py``), and the iterate mixes with
  rate gamma: ``x_i <- x_i + gamma * (s_i - x_hat_i)``.  Consensus
  contracts linearly even under aggressive sparsification, where direct
  top-k gossip stalls.  Requires a STATIC topology (the accumulated
  ``s_i`` is only meaningful under a constant W) and column-stochastic
  weights (every compiled topology here is).

State layout (per rank, rides the donated opt state; create with
:func:`init_state`, reset on degraded steps with :func:`reset_state`):

    direct + lossy:  {"residual": (buf per bucket, ...)}
    choco:           {"xhat": (...), "shat": (...)}
    lossless direct: None  (no state -> no layout change)

Every per-step quantity (step index for the shared PRNG key, weights
under dynamic schedules) is traced data — compression never adds a
recompile.
"""

import os
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..ops import fusion as F
from ..ops.collectives import _rotation_pairs, allgather
from ..observability import metrics as _metrics
from . import compressors as CP

__all__ = [
    "stateful", "init_state", "sharded_state_layout", "reset_state",
    "compressed_mix", "wire_stats", "check_supported",
    "GOSSIP_KERNEL_ENV", "resolve_gossip_kernel", "effective_gossip_kernel",
]

# base PRNG seed for the shared (step, bucket) keys; any constant works —
# it only has to be the SAME constant on every rank
_KEY_SEED = 0xC0213


def stateful(cfg: Optional[CP.CompressionConfig]) -> bool:
    """Does this config carry per-rank state (residuals / replica
    estimates) in the opt state?  Decides the state LAYOUT, so builders
    resolve it once at construction, like the overlap knob."""
    if cfg is None:
        return False
    if cfg.choco:
        return True
    return not CP.get_compressor(cfg).lossless


def check_supported(cfg: Optional[CP.CompressionConfig], *,
                    comm_value: str, sched=None,
                    overlap: bool = False) -> None:
    """Build-time validation of a (config, communication mode) pairing;
    raises ValueError with guidance instead of tracing something wrong."""
    if cfg is None:
        return
    if comm_value == "hierarchical.neighbor.allreduce":
        raise ValueError(
            "compression does not support hierarchical_neighbor_allreduce "
            "yet: the two-level mix would need per-level wire formats; "
            "use neighbor_allreduce or allreduce, or compression=None")
    if cfg.choco:
        if comm_value != "neighbor.allreduce":
            raise ValueError(
                f"choco compression is difference GOSSIP — it applies to "
                f"neighbor_allreduce mixing only (got {comm_value!r})")
        if sched is not None:
            raise ValueError(
                "choco compression requires a static topology: the "
                "accumulated neighbor-estimate sum s_i = sum_j W[j,i] "
                "x_hat_j is only meaningful under a constant W (dynamic "
                "schedules change W per step); use a direct spec like "
                "'int8' or 'topk:0.01' with dynamic schedules")
        if overlap:
            raise ValueError(
                "choco compression does not compose with overlap=True: "
                "the CHOCO mix x + gamma*(s - x_hat) has no single "
                "in-flight self weight to pipeline; use a direct spec "
                "('int8', 'topk:...') under overlap")


# ---------------------------------------------------------------------------
# Single-kernel gossip knob (BLUEFOG_GOSSIP_KERNEL)
# ---------------------------------------------------------------------------

GOSSIP_KERNEL_ENV = "BLUEFOG_GOSSIP_KERNEL"

_KERNEL_ON_VALUES = ("1", "on", "true", "pallas")


def resolve_gossip_kernel(value=None) -> Optional[str]:
    """Resolve the single-kernel gossip knob to a transport mode or
    ``None`` (off).  Explicit argument wins, else ``BLUEFOG_GOSSIP_KERNEL``
    (default off).  Modes: ``"pallas"`` (the Mosaic kernel, real TPU;
    spelled ``1``/``on``/``pallas``), ``"interpret"`` (the same kernel
    under the TPU-simulating interpreter — CPU test mesh, jaxlib >= 0.5),
    ``"emulate"`` (the kernel body's math over a ppermute transport — any
    backend; the CI bit-exactness harness).  Resolved when the step is
    BUILT, like every comm knob, and joins ``step_cache_key``."""
    if isinstance(value, bool):
        return "pallas" if value else None
    if value is None:
        value = os.environ.get(GOSSIP_KERNEL_ENV, "")
    if not isinstance(value, str):
        raise TypeError(
            f"gossip_kernel must be a mode string, bool, or None, got "
            f"{type(value).__name__}")
    v = value.strip().lower()
    if v in ("", "0", "none", "off", "false"):
        return None
    if v in _KERNEL_ON_VALUES:
        return "pallas"
    if v in ("interpret", "emulate"):
        return v
    raise ValueError(
        f"unknown gossip-kernel mode {value!r}: expected off "
        f"(''/'0'/'none'/'off'), '1'/'pallas' (Mosaic kernel), "
        f"'interpret' (TPU-simulating interpreter), or 'emulate' "
        f"(ppermute transport, any backend)")


def effective_gossip_kernel(value, cfg: Optional[CP.CompressionConfig], *,
                            comm_value: str, fuse: bool = True
                            ) -> Tuple[Optional[str], bool]:
    """Resolve + validate the gossip-kernel knob against the build's
    compression config and communication mode: ``(kernel_mode_or_None,
    interleave)``.

    The fused kernel is the COMPRESSED neighbor-gossip hot path, so it
    needs a dense quantizer (int8/fp8 — the only codecs with a
    fixed-shape wire the kernel can RDMA) on ``neighbor.allreduce``
    mixing over fused buckets.  The rules, matching ``check_supported``'s
    raise-with-guidance convention:

    * env-resolved knob on a build it cannot apply to (no compression, or
      a non-gossip comm mode) is INERT — except that with fused gossip
      and no codec it still turns on bucket INTERLEAVING (small buckets'
      exchanges issue first), the half of the optimization that needs no
      codec;
    * an EXPLICIT ``gossip_kernel=`` argument in those combos raises (a
      named request that cannot be honored must not silently no-op);
    * a sparsifier / unfused build under the knob raises either way —
      these are misconfigurations worth surfacing, not composition gaps
      to paper over (docs/performance.md lists the rejected combos).

    CHOCO difference gossip with a dense-quantizer inner codec
    (``choco:int8`` / ``choco:fp8``) IS kernel-supported: the replica
    estimates fold in-register (``ops/pallas_kernels.
    _choco_gossip_kernel``), so the look-through in
    :func:`~.compressors.kernel_codec` accepts it and only
    ``choco:topk``-style sparsifier wrappers fall into the no-codec
    rejection below.
    """
    kernel = resolve_gossip_kernel(value)
    if kernel is None:
        return None, False
    explicit = value is not None
    if comm_value != "neighbor.allreduce":
        if explicit:
            raise ValueError(
                f"the gossip kernel fuses neighbor_allreduce gossip only "
                f"(got {comm_value!r}): allreduce ships via all_gather, "
                f"hierarchical has a two-level mix — drop gossip_kernel= "
                f"for this communication mode")
        return None, False
    if not fuse:
        raise ValueError(
            "the gossip kernel operates on fused flat buckets "
            "(one pallas_call per bucket); fuse=False / "
            "BLUEFOG_COMM_FUSION=0 leaves it nothing to fuse — enable "
            "comm fusion or drop BLUEFOG_GOSSIP_KERNEL")
    if cfg is None:
        if explicit:
            raise ValueError(
                "gossip_kernel= needs a dense-quantizer compression "
                "config ('int8' or 'fp8'): the kernel IS the compressed "
                "hot path (quantize-on-store, wire RDMA, decode-on-load); "
                "without a codec use the dense pallas backend "
                "(BLUEFOG_NEIGHBOR_ALLREDUCE_BACKEND=pallas) instead")
        # the env knob still buys the issue-order half of the win
        return None, True
    if CP.kernel_codec(cfg) is None:
        raise ValueError(
            f"the gossip kernel's wire format is dense quantization: "
            f"spec {cfg.spec!r} has no kernel codec (sparsifiers ship "
            f"ragged values+indices — also under a choco: wrapper; "
            f"identity has no codec work to fuse) — use 'int8'/'fp8' "
            f"(or 'choco:int8'/'choco:fp8'), or drop "
            f"BLUEFOG_GOSSIP_KERNEL")
    return kernel, True


def _zero_state_bufs(tree, fuse: bool, bucket_bytes: Optional[int],
                     leaf_groups=None):
    plan, bufs = F.flat_views(tree, fuse=fuse, max_bucket_bytes=bucket_bytes,
                              leaf_groups=leaf_groups)
    return tuple(jnp.zeros_like(b) for b in bufs)


def init_state(cfg: Optional[CP.CompressionConfig], params, *,
               fuse: Optional[bool] = None,
               bucket_bytes: Optional[int] = None, leaf_groups=None):
    """Per-rank compression state for ``params``, or ``None`` when the
    config is stateless.  ``fuse``/``bucket_bytes`` must resolve to the
    SAME values the step builder uses — the carried-buffer layout is part
    of the state structure (exactly the ``delayed_init`` contract);
    ``leaf_groups`` likewise when the exchange buckets with groups."""
    if not stateful(cfg):
        return None
    fuse = F.fusion_enabled(fuse)
    bufs = _zero_state_bufs(params, fuse, bucket_bytes, leaf_groups)
    if cfg.choco:
        # the warmup estimates are ZERO (not x_0): every rank's copy of
        # x_hat_j must start identical WITHOUT a communication round, and
        # zero is the only value all ranks agree on for free.  The first
        # few steps transmit large deltas while x_hat catches up — the
        # documented CHOCO warmup.
        return {"xhat": bufs,
                "shat": tuple(jnp.zeros_like(b) for b in bufs)}
    return {"residual": bufs}


def sharded_state_layout(cfg: Optional[CP.CompressionConfig], params,
                         inner_specs, mesh, *, gossip_axis: str = "dp",
                         fuse: Optional[bool] = None,
                         bucket_bytes: Optional[int] = None):
    """Zero per-rank compression state for the HYBRID sharded-
    decentralized path, in the GLOBAL view a ``(dp, fsdp)`` train step
    carries (``parallel/tensor.py``).

    The codec there encodes each mesh cell's 1/fsdp SHARD of every fused
    bucket, so the error-feedback residuals (and CHOCO replica estimates)
    are shard-sized too and live SHARDED in the donated opt state: fused
    buffers come out ``[dp, fsdp, padded_shard]`` placed
    ``P(gossip, fsdp)``; the unfused layout mirrors the parameter leaves
    with their own within-replica specs.  ``params`` is the SINGLE-replica
    tree, ``inner_specs`` its within-replica spec tree.  Returns ``None``
    for stateless configs — no layout change, exactly like
    :func:`init_state`."""
    if not stateful(cfg):
        return None
    fuse = F.fusion_enabled(fuse)

    def zeros():
        return tuple(F.sharded_zero_buffers(
            params, inner_specs, mesh, gossip_axis=gossip_axis,
            fuse=fuse, max_bucket_bytes=bucket_bytes))

    if cfg.choco:
        return {"xhat": zeros(), "shat": zeros()}
    return {"residual": zeros()}


def reset_state(state):
    """Zero every carried buffer — the degraded-step reset: a repaired or
    guard-skipped step must not re-inject residuals (or trust replica
    estimates) accumulated against a topology that membership now
    distrusts.  Mesh-uniform like the degraded flag itself, so choco
    estimates stay rank-consistent (every rank restarts the warmup
    together)."""
    if state is None:
        return None
    return jax.tree.map(jnp.zeros_like, state)


def wire_stats(cfg: CP.CompressionConfig, bufs) -> Tuple[int, int]:
    """(wire bytes, raw bytes) of one compressed transfer of ``bufs`` —
    static ints, computable at trace time."""
    comp = CP.get_compressor(cfg)
    wire = sum(comp.wire_nbytes(int(b.size), b.dtype)
               for b in bufs if b.size)
    raw = sum(int(b.size) * jnp.dtype(b.dtype).itemsize
              for b in bufs if b.size)
    return int(wire), int(raw)


def _shared_key(step, bucket: int):
    key = jax.random.key(_KEY_SEED)
    key = jax.random.fold_in(key, jnp.asarray(step, jnp.int32))
    return jax.random.fold_in(key, bucket)


def _neighbor_terms(axis_name, topo, sched, step, dtype, idx):
    """(self_w, [(pairs, w), ...]) in ``dtype`` — EXACTLY the weight
    construction of ``collectives.neighbor_allreduce`` (static) /
    ``dynamic_neighbor_allreduce`` (sched), so the identity compressor's
    mix is bit-identical to the uncompressed path."""
    if sched is not None:
        t = jnp.asarray(step) % sched.period
        self_w = jnp.asarray(sched.self_weights)[t][idx].astype(dtype)
        recv_w = jnp.asarray(sched.recv_weights)[t]
        terms = [(_rotation_pairs(sched.size, off),
                  recv_w[k, idx].astype(dtype))
                 for k, off in enumerate(sched.offsets)]
        return self_w, terms
    self_w = jnp.asarray(topo.self_weights, dtype)[idx]
    terms = [(shift.pairs, jnp.asarray(shift.recv_weights, dtype)[idx])
             for shift in topo.shifts]
    return self_w, terms


def _weight_tables(axis_name, topo, sched, step, dtype):
    """Full ``(self_w [N], recv_w [K, N])`` weight tables in ``dtype``
    for the KERNEL transports — the kernel body reads its per-rank
    scalars as ``table[my_id]`` in-kernel.  The casts mirror
    :func:`_neighbor_terms` (numpy source -> ``dtype`` in one conversion
    for static topologies; f32 gather -> ``astype`` for dynamic
    schedules), so the values are bitwise the chain's."""
    if sched is not None:
        t = jnp.asarray(step) % sched.period
        self_w = jnp.asarray(sched.self_weights)[t].astype(dtype)
        recv_w = jnp.asarray(sched.recv_weights)[t].astype(dtype)
        return self_w, recv_w
    self_w = jnp.asarray(topo.self_weights, dtype)
    if not topo.shifts:
        # edgeless topology (size-1 gossip axis): no rows to stack — the
        # kernel entry's no-exchange branch consumes only self_w
        return self_w, jnp.zeros((0, topo.size), dtype)
    recv_np = np.stack([np.asarray(shift.recv_weights, np.float64)
                        for shift in topo.shifts])
    return self_w, jnp.asarray(recv_np, dtype)


def _emulated_bucket_gossip(buf, residual, codec: str, rkey,
                            axis_name, topo, sched, step, idx):
    """The ``"emulate"`` transport: the fused kernel's body — shared
    codec bodies (``compressors.int8_encode``/...), wire-dtype exchange,
    self-true mix, in-loop EF residual — executed as plain jnp with
    ``lax.ppermute`` standing in for the RDMA, so it runs on ANY
    backend (the bit-exactness and compile-count harness for hosts
    whose jaxlib has no Mosaic TPU interpreter).

    The expressions deliberately mirror the chain's direct-mode bucket
    body OP FOR OP — same ``_neighbor_terms`` scalars, same wire-dict
    ``tree.map`` permute, same thunked scale slice and noise draw
    position — because the contract is checked at the BIT level and
    XLA's fusion decisions (FMA formation around the mix's
    multiply-adds) key on the local op patterns: a mathematically equal
    but structurally different program was measured to drift by an ulp
    on the CPU backend."""
    t_val = buf + residual
    f = t_val.astype(jnp.float32).reshape(-1)
    if codec == "int8":
        q, scale = CP.int8_encode(
            f, lambda: jax.random.uniform(rkey, f.shape))
        decode = CP.int8_decode
    else:
        q, scale = CP.fp8_encode(f)
        decode = CP.fp8_decode
    wire = {"q": q, "scale": scale.reshape(1)}
    d_own = decode(wire["q"],
                   lambda: wire["scale"][0]).astype(buf.dtype).reshape(
                       buf.shape)
    self_w, terms = _neighbor_terms(axis_name, topo, sched, step,
                                    buf.dtype, idx)
    out = self_w * buf
    for pairs, w in terms:
        arrived = jax.tree.map(
            lambda a, pairs=pairs: lax.ppermute(a, axis_name, pairs), wire)
        dec = decode(arrived["q"],
                     lambda arrived=arrived: arrived["scale"][0])
        out = out + w * dec.astype(buf.dtype).reshape(buf.shape)
    return out, t_val - d_own


def _emulated_bucket_choco_gossip(buf, xhat, shat, gamma, codec: str,
                                  rkey, axis_name, topo, sched, step,
                                  idx):
    """The ``"emulate"`` transport's CHOCO flavor: the
    ``_choco_gossip_kernel`` body as plain jnp with ``lax.ppermute``
    standing in for the RDMA.  Like :func:`_emulated_bucket_gossip`,
    the expressions mirror the chain's choco bucket body OP FOR OP —
    same compress/decompress calls on the same values, same thunked
    scale slice, same gamma multiply position — because the parity
    contract covers params AND both replica estimates at the bit level,
    and XLA's FMA formation keys on the local op patterns.  ``gamma``
    arrives precomputed in ``buf.dtype`` (cfg.gamma × the controller's
    ``gamma_scale`` leaf) exactly as the kernel transports take it."""
    delta = buf - xhat
    f = delta.astype(jnp.float32).reshape(-1)
    if codec == "int8":
        q, scale = CP.int8_encode(
            f, lambda: jax.random.uniform(rkey, f.shape))
        decode = CP.int8_decode
    else:
        q, scale = CP.fp8_encode(f)
        decode = CP.fp8_decode
    wire = {"q": q, "scale": scale.reshape(1)}
    d_own = decode(wire["q"],
                   lambda: wire["scale"][0]).astype(buf.dtype).reshape(
                       buf.shape)
    self_w, terms = _neighbor_terms(axis_name, topo, sched, step,
                                    buf.dtype, idx)
    acc = self_w * d_own
    for pairs, w in terms:
        arrived = jax.tree.map(
            lambda a, pairs=pairs: lax.ppermute(a, axis_name, pairs), wire)
        dec = decode(arrived["q"],
                     lambda arrived=arrived: arrived["scale"][0])
        acc = acc + w * dec.astype(buf.dtype).reshape(buf.shape)
    xhat_new = xhat + d_own
    shat_new = shat + acc
    return buf + gamma * (shat_new - xhat_new), xhat_new, shat_new


def _choco_gamma(state, cfg, dtype):
    """The traced consensus stepsize in the bucket dtype: ``cfg.gamma``
    times the controller's ``gamma_scale`` leaf when present — the
    chain's exact construction (same casts, same multiply position), so
    γ backoff/re-arm actuates identically on every transport."""
    gamma = jnp.asarray(cfg.gamma, dtype)
    scale = state.get("gamma_scale")
    if scale is not None:
        gamma = gamma * jnp.asarray(scale, dtype)
    return gamma


def _kernel_mix(plan, tree, bufs, state, cfg: CP.CompressionConfig,
                kernel: str, axis_name, topo, sched, step,
                wire_bytes: int, raw_bytes: int, mesh_axes=None):
    """The single-kernel gossip execution of one compressed exchange:
    one fused kernel call per fusion bucket (codec + RDMA + mix + the
    carried state update — EF residual for direct specs,
    :func:`~..ops.pallas_kernels.fused_compressed_gossip`; replica
    estimates for choco, :func:`~..ops.pallas_kernels.
    fused_choco_gossip`), issued in :func:`~..ops.fusion.
    interleave_order` (small buckets first, so their short exchanges
    hide under the large buckets' work).  Reached only for validated
    builds (``effective_gossip_kernel``): dense-quantizer wire formats
    over fused neighbor gossip.  Bit-exact vs the chain below — the
    kernel runs the same codec bodies on the same values in the same
    order (asserted across schedules, dtypes, and both disciplines in
    tests/test_gossip_kernel.py).  ``mesh_axes``: the hybrid sharded
    path's full mesh axis tuple for RDMA device ids (``None`` on 1-D
    gossip meshes)."""
    from ..ops import pallas_kernels as PK
    choco = cfg.choco
    needed = ("xhat", "shat") if choco else ("residual",)
    if plan is None or state is None or any(k not in state
                                           for k in needed):
        raise ValueError(
            "kernel gossip needs fused buckets and the discipline's "
            "carried state (EF residual for direct quantizers, "
            "xhat/shat replica estimates for choco) — builder "
            "validation should have rejected this configuration")
    idx = lax.axis_index(axis_name)
    size = sched.size if sched is not None else topo.size
    offsets = (tuple(sched.offsets) if sched is not None
               else tuple(topo.offsets))
    mixed: List[Any] = [None] * len(bufs)
    state_a: List[Any] = [None] * len(bufs)   # residual | xhat
    state_b: List[Any] = [None] * len(bufs)   # (choco) shat
    tables: Dict[Any, Any] = {}
    for b in F.interleave_order(plan):
        buf = bufs[b]
        skey = _shared_key(step, b)
        rkey = jax.random.fold_in(skey, idx)
        if kernel == "emulate":
            if choco:
                mixed[b], state_a[b], state_b[b] = (
                    _emulated_bucket_choco_gossip(
                        buf, state["xhat"][b], state["shat"][b],
                        _choco_gamma(state, cfg, buf.dtype), cfg.name,
                        rkey, axis_name, topo, sched, step, idx))
            else:
                mixed[b], state_a[b] = _emulated_bucket_gossip(
                    buf, state["residual"][b], cfg.name, rkey,
                    axis_name, topo, sched, step, idx)
            continue
        # the chain draws this inside compress(); same key, same shape,
        # same draw — precomputed because the kernel has no threefry
        noise = (jax.random.uniform(rkey, (int(buf.size),))
                 if cfg.name == "int8" else None)
        dt = jnp.dtype(buf.dtype)
        if dt not in tables:
            tables[dt] = _weight_tables(axis_name, topo, sched, step,
                                        buf.dtype)
        self_w, recv_w = tables[dt]
        if choco:
            gamma = _choco_gamma(state, cfg, buf.dtype).reshape(1)
            mixed[b], state_a[b], state_b[b] = PK.fused_choco_gossip(
                buf, state["xhat"][b], state["shat"][b], noise, gamma,
                self_w, recv_w, axis_name=axis_name, size=size,
                offsets=offsets, codec=cfg.name, mode=kernel,
                mesh_axes=mesh_axes)
        else:
            mixed[b], state_a[b] = PK.fused_compressed_gossip(
                buf, state["residual"][b], noise, self_w, recv_w,
                axis_name=axis_name, size=size, offsets=offsets,
                codec=cfg.name, mode=kernel, mesh_axes=mesh_axes)
    # diag accumulates in PLAN order like the chain's bucket loop, so the
    # telemetry residual norm is bitwise unchanged by the issue order;
    # for choco the chain's "residual" is the estimate lag buf - xhat'
    res_norm2 = jnp.float32(0.0)
    for b, r in enumerate(state_a):
        err = (bufs[b] - r) if choco else r
        r32 = err.astype(jnp.float32)
        res_norm2 = res_norm2 + jnp.sum(r32 * r32)
    if choco:
        new_state = {"xhat": tuple(state_a), "shat": tuple(state_b)}
    else:
        new_state = {"residual": tuple(state_a)}
    if "gamma_scale" in state:
        new_state["gamma_scale"] = state["gamma_scale"]
    diag = {"residual_norm": jnp.sqrt(res_norm2),
            "wire_bytes": float(wire_bytes),
            "ratio": float(raw_bytes) / float(max(wire_bytes, 1))}
    return F.restore(plan, tree, mixed), new_state, diag


def _note_metrics(cfg, wire_bytes: int, raw_bytes: int) -> None:
    if not _metrics.enabled():
        return
    # trace-time only, like the fusion-plan gauges: describes the LAST
    # compressed exchange planned, counts every plan consult
    _metrics.counter("bf_compress_consults_total",
                     "compressed-exchange plans (trace-time)").inc(
        spec=cfg.spec)
    g = _metrics.gauge("bf_compress_plan",
                       "shape of the last compressed exchange planned")
    g.set(wire_bytes, field="wire_bytes")
    g.set(raw_bytes, field="raw_bytes")
    g.set(raw_bytes / max(wire_bytes, 1), field="ratio")


def compressed_mix(tree, state, cfg: CP.CompressionConfig, *,
                   mode: str, axis_name, topo=None, sched=None, step=0,
                   fuse: bool = True, bucket_bytes: Optional[int] = None,
                   leaf_groups=None, kernel: Optional[str] = None,
                   kernel_mesh_axes: Optional[Tuple[str, ...]] = None):
    """One compressed exchange of ``tree`` (per-rank, inside shard_map).

    ``mode``: ``"neighbor"`` (weighted gossip over ``topo``/``sched``) or
    ``"allreduce"`` (global mean via compressed all_gather).  Returns
    ``(mixed_tree, new_state, diag)`` where ``diag`` carries traced f32
    ``residual_norm`` plus static ``wire_bytes``/``ratio`` for the
    telemetry snapshot.  ``leaf_groups`` (hybrid 2-level meshes,
    ``ops/fusion.py::shard_groups``): partitions the buckets so
    inner-axis-replicated leaves never share codec statistics with
    cell-varying shard data — their mixed value must be identical on
    every cell.

    ``kernel`` (a mode from :func:`resolve_gossip_kernel`, validated by
    :func:`effective_gossip_kernel`): run the whole per-bucket hot path
    — quantize, exchange, decode, mix, and the carried state update
    (EF residual, or choco's x̂/ŝ estimates) — as ONE fused kernel per
    bucket (``ops/pallas_kernels.fused_compressed_gossip`` /
    ``fused_choco_gossip``) instead of the ~4-op chain below; bit-exact
    vs the chain.  ``None`` (the default) is the chain — byte-identical
    StableHLO to the pre-kernel lowering, the standing off-path
    contract.  ``kernel_mesh_axes``: the enclosing shard_map's full
    ordered mesh axis tuple when it spans MORE than the gossip axis
    (the hybrid ``(dp, fsdp)`` path) — the kernel's RDMA device ids
    become mesh-coordinate tuples targeting the same cell in the
    neighbor replica; ``None`` on 1-D gossip meshes."""
    comp = CP.get_compressor(cfg)
    plan, bufs = F.flat_views(tree, fuse=fuse, max_bucket_bytes=bucket_bytes,
                              leaf_groups=leaf_groups)
    wire_bytes, raw_bytes = wire_stats(cfg, bufs)
    _note_metrics(cfg, wire_bytes, raw_bytes)
    if kernel is not None:
        if mode != "neighbor":
            raise ValueError(
                "kernel gossip applies to neighbor mixing only — "
                "builder validation (effective_gossip_kernel) should "
                "have rejected this configuration")
        return _kernel_mix(plan, tree, bufs, state, cfg, kernel,
                           axis_name, topo, sched, step,
                           wire_bytes, raw_bytes,
                           mesh_axes=kernel_mesh_axes)
    idx = lax.axis_index(axis_name)
    res_norm2 = jnp.float32(0.0)
    mixed: List[jax.Array] = []
    new_parts: Dict[str, List[jax.Array]] = {}

    for b, buf in enumerate(bufs):
        if buf.size == 0:
            # zero-size passthrough leaf (unfused mode): nothing to move
            mixed.append(buf)
            for k in ("residual", "xhat", "shat"):
                if state is not None and k in state:
                    new_parts.setdefault(k, []).append(state[k][b])
            continue
        skey = _shared_key(step, b)
        rkey = jax.random.fold_in(skey, idx)

        if cfg.choco:
            xhat, shat = state["xhat"][b], state["shat"][b]
            delta = buf - xhat
            wire = comp.compress(delta, skey, rkey)
            d_own = comp.decompress(wire, skey, buf.shape, buf.dtype)
            self_w, terms = _neighbor_terms(axis_name, topo, sched, step,
                                            buf.dtype, idx)
            acc = self_w * d_own
            for pairs, w in terms:
                arrived = jax.tree.map(
                    lambda a: lax.ppermute(a, axis_name, pairs), wire)
                acc = acc + w * comp.decompress(arrived, skey, buf.shape,
                                                buf.dtype)
            xhat_new = xhat + d_own
            shat_new = shat + acc
            gamma = jnp.asarray(cfg.gamma, buf.dtype)
            # the closed-loop controller's γ knob (control/actuate.py):
            # a traced scalar riding the carried state, injected by the
            # optimizer wrapper when built with control=True — backoff /
            # re-arm never recompiles.  Absent key (the default) leaves
            # the math — and the traced program — exactly as before;
            # scale 1.0 multiplies bit-exactly.
            scale = state.get("gamma_scale")
            if scale is not None:
                gamma = gamma * jnp.asarray(scale, buf.dtype)
            mixed.append(buf + gamma * (shat_new - xhat_new))
            new_parts.setdefault("xhat", []).append(xhat_new)
            new_parts.setdefault("shat", []).append(shat_new)
            # the carried compression error: how far the public estimate
            # lags the true iterate
            err = (buf - xhat_new).astype(jnp.float32)
            res_norm2 = res_norm2 + jnp.sum(err * err)
            continue

        # -- direct mode (with error feedback when lossy) ----------------
        residual = state["residual"][b] if state is not None else None
        t_val = buf if residual is None else buf + residual
        if mode == "allreduce" and comp.lossless:
            # nothing to gain from the gather path; pmean is bit-exact
            mixed.append(lax.pmean(buf, axis_name))
            continue
        wire = comp.compress(t_val, skey, rkey)
        d_own = comp.decompress(wire, skey, buf.shape, buf.dtype)
        if mode == "allreduce":
            gathered = jax.tree.map(lambda a: allgather(a[None], axis_name),
                                    wire)
            dec = jax.vmap(lambda w: comp.decompress(w, skey, buf.shape,
                                                     buf.dtype))(gathered)
            n = lax.axis_size(axis_name)
            # self term is the TRUE value; neighbors contribute their
            # decompressed transmissions
            out = (buf + dec.sum(axis=0) - dec[idx]) / n
        else:
            self_w, terms = _neighbor_terms(axis_name, topo, sched, step,
                                            buf.dtype, idx)
            out = self_w * buf
            for pairs, w in terms:
                arrived = jax.tree.map(
                    lambda a: lax.ppermute(a, axis_name, pairs), wire)
                out = out + w * comp.decompress(arrived, skey, buf.shape,
                                                buf.dtype)
        mixed.append(out)
        if residual is not None:
            res_new = t_val - d_own
            new_parts.setdefault("residual", []).append(res_new)
            r32 = res_new.astype(jnp.float32)
            res_norm2 = res_norm2 + jnp.sum(r32 * r32)

    if state is None:
        new_state = None
    else:
        new_state = {k: tuple(v) for k, v in new_parts.items()}
        if "gamma_scale" in state:
            # carried through unchanged so the state STRUCTURE is stable
            # across steps (the wrapper overwrites the value host-side)
            new_state["gamma_scale"] = state["gamma_scale"]
    diag = {"residual_norm": jnp.sqrt(res_norm2),
            "wire_bytes": float(wire_bytes),
            "ratio": float(raw_bytes) / float(max(wire_bytes, 1))}
    return F.restore(plan, tree, mixed), new_state, diag
