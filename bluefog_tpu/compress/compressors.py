"""Wire compressors for the gossip exchange: quantize / sparsify ONE flat
buffer at a time.

The fusion layer (``ops/fusion.py``) already packs the parameter pytree
into a handful of dtype-bucketed flat buffers, so compression operates at
exactly that granularity: one compress/decompress per BUCKET per exchange,
never per leaf.  A compressor maps a buffer to a *wire* pytree of arrays
(what actually rides ``lax.ppermute``/``all_gather``) and back:

    wire = comp.compress(buf, shared_key, rank_key)
    buf' = comp.decompress(wire, shared_key, shape, dtype)

Design rules every compressor obeys:

* **Deterministic decompression.**  ``decompress`` is a pure function of
  the wire data and the SHARED key (derived from ``(step, bucket)``, never
  the rank), so the sender's own reconstruction bit-matches every
  receiver's — the invariant the error-feedback residual and the CHOCO
  replica estimates rest on.  Randomness that decorrelates SENDERS
  (stochastic-rounding noise) uses ``rank_key`` inside ``compress`` only.
* **Static wire signature.**  The wire arrays' shapes/dtypes depend only
  on the buffer's static shape/dtype and the config — jit traces once and
  the collective schedule is fixed.
* **Known cost.**  :meth:`Compressor.wire_nbytes` reports the wire payload
  bytes for a buffer size so telemetry (and ``bench.py --trace-only``) can
  report compression ratio without parsing HLO.

Registry / selection: specs are strings —

    "int8"            uniform 8-bit quantization, per-bucket scale,
                      stochastic rounding (unbiased)
    "fp8"             float8_e4m3fn cast with per-bucket scale
    "topk:0.01"       keep the 1% largest-|x| entries (values + indices)
    "randomk:0.05"    keep 5% entries at shared-seed random positions
                      (indices are re-derived from the shared key, so the
                      wire carries VALUES ONLY)
    "identity"        no-op compressor (wire = the buffer; exercises the
                      compressed code path bit-exactly)
    "choco:<spec>[:gamma=G]"   CHOCO-style difference gossip: compress the
                      delta against the neighbor replica estimate and mix
                      with rate gamma (``compress/exchange.py``)

resolved via :func:`resolve_compression` — explicit argument wins, else
``BLUEFOG_COMM_COMPRESS`` (default off).  ``None``/``"none"``/``"off"``/
``"0"``/``""`` all mean *no compression*: the builders then take the
exact pre-compression code path (byte-identical StableHLO, asserted by
``tests/test_compress.py``).
"""

import os
from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = [
    "COMPRESS_ENV", "CompressionConfig", "Compressor",
    "resolve_compression", "get_compressor", "available_compressors",
    "register_compressor",
    "int8_encode", "int8_decode", "fp8_encode", "fp8_decode",
    "KERNEL_CODECS", "kernel_codec",
]

COMPRESS_ENV = "BLUEFOG_COMM_COMPRESS"

_OFF_VALUES = ("", "0", "none", "off", "false")


@dataclass(frozen=True)
class CompressionConfig:
    """Parsed, hashable compression selection (joins the step-cache key).

    ``name``/``fraction`` select the compressor; ``choco`` switches the
    exchange from direct compressed gossip to CHOCO difference gossip with
    mixing rate ``gamma`` (``compress/exchange.py``).

    ``gamma`` stability: CHOCO's consensus stepsize must scale with the
    compression quality ω (Koloskova et al.: γ* ∝ δ²ω).  Too-large γ
    under aggressive sparsification contracts for a few dozen steps and
    then DIVERGES (measured on the 8-rank exp2 mesh, top-10%: γ=0.1
    reaches 2e-10, γ=0.5 blows past 5e3 by step 200).  The parser
    therefore defaults γ to ``min(0.5, fraction)`` for sparsifiers and
    0.5 for quantizers/identity; an explicit ``gamma=`` in the spec
    always wins."""
    name: str
    fraction: Optional[float] = None
    choco: bool = False
    gamma: float = 0.5

    @property
    def spec(self) -> str:
        """Canonical spec string (round-trips through the parser)."""
        s = self.name
        if self.fraction is not None:
            s += f":{self.fraction:g}"
        if self.choco:
            s = f"choco:{s}:gamma={self.gamma:g}"
        return s


def resolve_compression(value=None) -> Optional[CompressionConfig]:
    """Resolve the compression knob: explicit argument wins, else the
    ``BLUEFOG_COMM_COMPRESS`` env var (default off).  Builders resolve this
    when the step is constructed — the same snapshot discipline as the
    fusion/overlap knobs (jit traces once; and when the compressor carries
    state, the resolved value shapes the opt-state layout)."""
    if isinstance(value, CompressionConfig):
        return value
    if value is False:
        return None
    if value is None:
        value = os.environ.get(COMPRESS_ENV, "")
    if not isinstance(value, str):
        raise TypeError(
            f"compression must be a spec string, CompressionConfig, or "
            f"None, got {type(value).__name__}")
    if value.strip().lower() in _OFF_VALUES:
        return None
    return _parse_spec(value.strip())


def _parse_spec(spec: str) -> CompressionConfig:
    tokens = spec.lower().split(":")
    choco = tokens[0] == "choco"
    if choco:
        tokens = tokens[1:]
    if not tokens or not tokens[0]:
        raise ValueError(
            f"compression spec {spec!r} names no compressor; expected e.g. "
            f"'int8', 'topk:0.01', 'choco:int8:gamma=0.5' "
            f"(available: {', '.join(available_compressors())})")
    name, params = tokens[0], tokens[1:]
    fraction = None
    gamma = None
    for p in params:
        if p.startswith("gamma="):
            gamma = float(p[len("gamma="):])
            if not choco:
                raise ValueError(
                    f"compression spec {spec!r}: gamma applies to the "
                    f"choco mode only (prefix the spec with 'choco:')")
        else:
            fraction = float(p)
    if name not in _REGISTRY:
        raise ValueError(
            f"unknown compressor {name!r} in spec {spec!r} "
            f"(available: {', '.join(available_compressors())})")
    if name in ("topk", "randomk"):
        if fraction is None:
            fraction = 0.01
        if not (0.0 < fraction <= 1.0):
            raise ValueError(
                f"{name} fraction must be in (0, 1], got {fraction}")
    elif fraction is not None:
        raise ValueError(
            f"compressor {name!r} takes no fraction parameter "
            f"(spec {spec!r})")
    if gamma is None:
        # default γ tracks the compression quality: a sparsifier keeping
        # fraction F of the coordinates is stable only for γ = O(F)
        # (see CompressionConfig docstring); quantizers are near-exact
        # (ω ≈ 1) and take the generous 0.5
        gamma = min(0.5, fraction) if fraction is not None else 0.5
    if not (0.0 < gamma <= 1.0):
        raise ValueError(f"choco gamma must be in (0, 1], got {gamma}")
    cfg = CompressionConfig(name=name, fraction=fraction, choco=choco,
                            gamma=gamma)
    get_compressor(cfg)   # fail fast on unsupported dtypes (fp8 gate)
    return cfg


# ---------------------------------------------------------------------------
# Kernel-callable codec bodies
# ---------------------------------------------------------------------------
#
# The dense quantizers' encode/decode math lives in these module-level
# functions so BOTH entries share one body: the wire classes below (the
# ``compressed_mix`` chain) and the single-kernel gossip path
# (``ops/pallas_kernels.py``), which runs the same jnp ops on values
# loaded from VMEM refs inside the fused kernel.  One body means the
# fused kernel is bit-exact against the chain by construction — same ops
# in the same order, not a re-derivation that could drift.
#
# ``noise`` is the stochastic-rounding uniform draw.  The chain computes
# it inside ``compress`` from ``rank_key``; the kernel path precomputes
# the SAME draw outside the kernel (the noise depends only on the key and
# the bucket's element count, never on the data) and feeds it in as an
# operand, so the kernel needs no in-kernel threefry.

KERNEL_CODECS = ("int8", "fp8")


def kernel_codec(cfg: Optional["CompressionConfig"]) -> Optional[str]:
    """The fused-gossip-kernel codec a config maps to, or ``None`` when
    the config is outside the kernel's wire format (sparsifiers ship
    ragged values+indices; identity has no codec win to fuse).  The
    mapping looks THROUGH the choco wrapper: ``choco:int8`` wires the
    same int8 payload as ``int8`` — only the in-register math around it
    differs (``ops/pallas_kernels._choco_gossip_kernel``) — while
    ``choco:topk`` stays ``None`` like plain ``topk``."""
    if cfg is None:
        return None
    return cfg.name if cfg.name in KERNEL_CODECS else None


def int8_encode(f, noise=None):
    """Quantize one flat f32 array: ``(int8 payload, f32 scale scalar)``.
    ``noise`` (same shape, U[0,1); an array, or a zero-arg thunk so the
    chain's draw keeps its historical trace position after the divide —
    byte-identity of the off path is checked to the byte) selects
    stochastic rounding; ``None`` falls back to round-to-nearest (the
    window path, which has no step index to derive a key from)."""
    scale = jnp.maximum(jnp.max(jnp.abs(f)), jnp.float32(1e-30)) / 127.0
    t = f / scale
    u = noise() if callable(noise) else noise
    if u is not None:
        q = jnp.floor(t + u)
    else:
        q = jnp.round(t)
    return jnp.clip(q, -127.0, 127.0).astype(jnp.int8), scale


def int8_decode(q, scale):
    """Inverse of :func:`int8_encode` (f32 result; the caller casts to
    the bucket dtype — receivers re-materialize at decode width exactly
    once, in-register on the kernel path).  ``scale``: a scalar, or a
    zero-arg thunk evaluated after the payload convert (the chain's
    historical trace order, kept to the byte)."""
    f = q.astype(jnp.float32)
    s = scale() if callable(scale) else scale
    return f * s


_FP8_MAX = 448.0


def fp8_encode(f):
    """float8_e4m3fn cast with one f32 scale (bucket max lands at the
    format's max normal, 448)."""
    scale = jnp.maximum(jnp.max(jnp.abs(f)), jnp.float32(1e-30)) / _FP8_MAX
    return (f / scale).astype(jnp.float8_e4m3fn), scale


def fp8_decode(q, scale):
    f = q.astype(jnp.float32)
    s = scale() if callable(scale) else scale
    return f * s


# ---------------------------------------------------------------------------
# Compressors
# ---------------------------------------------------------------------------

class Compressor:
    """One bucket's wire codec.  Subclasses operate on a single array of
    any shape (raveled internally); see the module docstring for the
    determinism contract."""

    name = "abstract"
    lossless = False

    def compress(self, buf: jax.Array, shared_key, rank_key
                 ) -> Dict[str, jax.Array]:
        raise NotImplementedError

    def decompress(self, wire: Dict[str, jax.Array], shared_key,
                   shape: Tuple[int, ...], dtype) -> jax.Array:
        raise NotImplementedError

    def wire_nbytes(self, nelems: int, dtype) -> int:
        """Static wire payload bytes for an ``nelems`` buffer of
        ``dtype``."""
        raise NotImplementedError


class IdentityCompressor(Compressor):
    """Wire = the buffer itself.  Exists so the compressed code path can
    be exercised (and asserted bit-exact) without changing any value."""

    name = "identity"
    lossless = True

    def compress(self, buf, shared_key, rank_key):
        return {"v": buf}

    def decompress(self, wire, shared_key, shape, dtype):
        return wire["v"].reshape(shape).astype(dtype)

    def wire_nbytes(self, nelems, dtype):
        return int(nelems) * jnp.dtype(dtype).itemsize


class Int8Compressor(Compressor):
    """Uniform 8-bit quantization with one f32 scale per bucket.

    ``scale = max|x| / 127``; encoding uses STOCHASTIC rounding
    (``floor(x/scale + u)``, u ~ U[0,1) from ``rank_key``) so the
    quantizer is unbiased — consensus noise averages out instead of
    biasing the fixed point.  ``rank_key=None`` (the window path, which
    has no step index) falls back to deterministic round-to-nearest."""

    name = "int8"

    def compress(self, buf, shared_key, rank_key):
        f = buf.astype(jnp.float32).reshape(-1)
        noise = ((lambda: jax.random.uniform(rank_key, f.shape))
                 if rank_key is not None else None)
        q, scale = int8_encode(f, noise)
        return {"q": q, "scale": scale.reshape(1)}

    def decompress(self, wire, shared_key, shape, dtype):
        f = int8_decode(wire["q"], lambda: wire["scale"][0])
        return f.astype(dtype).reshape(shape)

    def wire_nbytes(self, nelems, dtype):
        return int(nelems) + 4    # int8 payload + one f32 scale


class Fp8Compressor(Compressor):
    """float8_e4m3fn cast with one f32 scale per bucket (scaled so the
    bucket max lands at the format's max normal, 448)."""

    name = "fp8"
    _MAX = _FP8_MAX

    def __init__(self):
        if not hasattr(jnp, "float8_e4m3fn"):
            raise ValueError(
                "fp8 compression needs jnp.float8_e4m3fn (ml_dtypes); "
                "this jax build does not provide it — use 'int8' instead")

    def compress(self, buf, shared_key, rank_key):
        f = buf.astype(jnp.float32).reshape(-1)
        q, scale = fp8_encode(f)
        return {"q": q, "scale": scale.reshape(1)}

    def decompress(self, wire, shared_key, shape, dtype):
        f = fp8_decode(wire["q"], lambda: wire["scale"][0])
        return f.astype(dtype).reshape(shape)

    def wire_nbytes(self, nelems, dtype):
        return int(nelems) + 4


def _k_of(nelems: int, fraction: float) -> int:
    return max(1, min(int(nelems), int(round(nelems * fraction))))


class TopKCompressor(Compressor):
    """Magnitude sparsification: keep the k = ceil(fraction * n) entries
    of largest |x|.  Wire = values (original dtype) + int32 indices —
    per-rank index sets differ, so indices must ride the wire."""

    name = "topk"

    def __init__(self, fraction: float):
        self.fraction = float(fraction)

    def compress(self, buf, shared_key, rank_key):
        f = buf.reshape(-1)
        k = _k_of(f.shape[0], self.fraction)
        _, idx = jax.lax.top_k(jnp.abs(f.astype(jnp.float32)), k)
        return {"v": f[idx], "i": idx.astype(jnp.int32)}

    def decompress(self, wire, shared_key, shape, dtype):
        n = 1
        for d in shape:
            n *= int(d)
        out = jnp.zeros((n,), dtype).at[wire["i"]].set(
            wire["v"].astype(dtype))
        return out.reshape(shape)

    def wire_nbytes(self, nelems, dtype):
        k = _k_of(int(nelems), self.fraction)
        return k * (jnp.dtype(dtype).itemsize + 4)


class RandomKCompressor(Compressor):
    """Shared-seed random sparsification: the k kept positions derive from
    the SHARED key (a pure function of ``(step, bucket)``), so every rank
    uses the same mask and receivers re-derive it — the wire carries
    VALUES ONLY, the cheapest sparse wire format.  (Per-rank independent
    masks would need index transmission like top-k; the shared mask is
    the standard decentralized choice because the mix stays a convex
    combination coordinate-wise.)"""

    name = "randomk"

    def __init__(self, fraction: float):
        self.fraction = float(fraction)

    def _indices(self, shared_key, n: int):
        k = _k_of(n, self.fraction)
        return jax.random.choice(shared_key, n, shape=(k,), replace=False)

    def compress(self, buf, shared_key, rank_key):
        f = buf.reshape(-1)
        return {"v": f[self._indices(shared_key, f.shape[0])]}

    def decompress(self, wire, shared_key, shape, dtype):
        n = 1
        for d in shape:
            n *= int(d)
        idx = self._indices(shared_key, n)
        out = jnp.zeros((n,), dtype).at[idx].set(wire["v"].astype(dtype))
        return out.reshape(shape)

    def wire_nbytes(self, nelems, dtype):
        return _k_of(int(nelems), self.fraction) * jnp.dtype(dtype).itemsize


_REGISTRY = {
    "identity": lambda cfg: IdentityCompressor(),
    "int8": lambda cfg: Int8Compressor(),
    "fp8": lambda cfg: Fp8Compressor(),
    "topk": lambda cfg: TopKCompressor(cfg.fraction),
    "randomk": lambda cfg: RandomKCompressor(cfg.fraction),
}


def register_compressor(name: str, factory) -> None:
    """Add a custom compressor: ``factory(cfg) -> Compressor``.  The name
    becomes valid in specs (``compression="myname"``)."""
    _REGISTRY[str(name)] = factory


def available_compressors():
    return sorted(_REGISTRY)


def get_compressor(cfg: CompressionConfig) -> Compressor:
    """Instantiate the compressor a config names (fresh instance; they are
    stateless — all carried state lives in the opt state,
    ``compress/exchange.py``)."""
    if cfg.name not in _REGISTRY:
        raise ValueError(
            f"unknown compressor {cfg.name!r} "
            f"(available: {', '.join(available_compressors())})")
    return _REGISTRY[cfg.name](cfg)
