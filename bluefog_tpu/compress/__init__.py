"""Compressed neighbor exchange: quantized/sparsified gossip wire formats
with error feedback and CHOCO difference gossip.

Select with ``compression=`` on the strategy builders / optimizer
factories / ``training.make_train_step`` or the ``BLUEFOG_COMM_COMPRESS``
env var; see ``docs/compression.md`` for the composition matrix with
fusion / overlap / windows / resilience.
"""

from .compressors import (          # noqa: F401
    COMPRESS_ENV,
    CompressionConfig,
    Compressor,
    available_compressors,
    get_compressor,
    register_compressor,
    resolve_compression,
)
from .exchange import (             # noqa: F401
    check_supported,
    compressed_mix,
    init_state,
    reset_state,
    stateful,
    wire_stats,
)

__all__ = [
    "COMPRESS_ENV", "CompressionConfig", "Compressor",
    "available_compressors", "get_compressor", "register_compressor",
    "resolve_compression", "check_supported", "compressed_mix",
    "init_state", "reset_state", "stateful", "wire_stats",
]
