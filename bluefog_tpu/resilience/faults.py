"""Deterministic, seeded fault plans — injectable into any step as traced data.

A :class:`FaultPlan` is a host-side description of what goes wrong and when:
rank death at step k, straggler slow-down, flaky-link drops, value
corruption.  :meth:`FaultPlan.compile` lowers it to fixed-shape per-step
tables ([T, N] / [T, N, N]); jitted programs index the tables with the
*traced* step, so injecting, editing, or clearing a fault between steps never
changes program shape and never recompiles (asserted in
``tests/test_resilience.py::test_fault_plans_do_not_recompile``).

Conventions:

* ``alive[t, i]``     1.0 while rank i is up at step t, 0.0 once down.
* ``active[t, i]``    1.0 when rank i participates at step t.  Stragglers
                      are alive but *intermittently* active: a factor-k
                      straggler only joins every k-th step, so its peers see
                      stale, late contributions — the SPMD analog of a slow
                      MPI rank (a dead rank is never active).
* ``link_ok[t, i, j]`` 1.0 when the i->j edge delivers at step t.
* ``corrupt[t, i]``   multiplicative scale on rank i's *outgoing* value at
                      step t (1.0 = clean; ``nan`` models bit-rot — the
                      harness's finite-guard must catch it).

Beyond the horizon T the plan holds its LAST state (tables are indexed with
``min(step, T-1)``): a rank that dies stays dead, transient faults end.
"""

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["FaultEvent", "FaultPlan", "CompiledFaultPlan", "empty_plan",
           "random_plan"]


@dataclass(frozen=True)
class FaultEvent:
    """One fault.  ``until`` is exclusive; ``None`` = rest of the run."""
    kind: str                      # rank_down | straggler | flaky_link | corrupt
    rank: int
    step: int
    until: Optional[int] = None
    peer: Optional[int] = None     # flaky_link destination
    factor: float = 1.0            # straggler period / corruption scale


@dataclass(frozen=True)
class CompiledFaultPlan:
    """Fixed-shape per-step fault tables (see module docstring)."""
    size: int
    horizon: int
    alive: np.ndarray        # [T, N] float32
    active: np.ndarray       # [T, N] float32
    link_ok: np.ndarray      # [T, N, N] float32
    corrupt: np.ndarray      # [T, N] float32
    events: Tuple[FaultEvent, ...] = ()

    def tables(self) -> Dict[str, "np.ndarray"]:
        """The tables as device arrays, ready to pass into a jitted step.

        Every plan of the same ``(size, horizon)`` produces identically
        shaped tables — swap plans freely between calls of one compiled
        program."""
        import jax.numpy as jnp
        return {"alive": jnp.asarray(self.alive),
                "active": jnp.asarray(self.active),
                "link_ok": jnp.asarray(self.link_ok),
                "corrupt": jnp.asarray(self.corrupt)}

    def num_dead_at(self, step: int) -> int:
        t = min(step, self.horizon - 1)
        return int((self.alive[t] == 0).sum())

    def alive_at(self, step: int) -> np.ndarray:
        """Host-side [N] liveness row at ``step`` (clamped to the
        horizon) — the mask host-side consumers (the serving router,
        ``win_update(alive=)`` callers, report code) feed per step
        without instantiating device tables."""
        return self.alive[min(step, self.horizon - 1)]

    def active_at(self, step: int) -> np.ndarray:
        """Host-side [N] participation row at ``step`` (stragglers are
        alive but intermittently active — e.g. a publisher that only
        ships weights on its active steps)."""
        return self.active[min(step, self.horizon - 1)]


def at_step(tables: Dict, step):
    """Index the device tables with a traced step (clamped to the horizon).

    Returns ``(alive[N], active[N], link_ok[N, N], corrupt[N])`` for the
    step — all traced values; use inside jit."""
    import jax.numpy as jnp
    t = jnp.minimum(jnp.asarray(step, jnp.int32),
                    tables["alive"].shape[0] - 1)
    return (tables["alive"][t], tables["active"][t],
            tables["link_ok"][t], tables["corrupt"][t])


class FaultPlan:
    """Builder for deterministic fault scenarios.

    >>> plan = FaultPlan(size=8, horizon=40)
    >>> plan.rank_down(3, at=10)                 # rank 3 dies at step 10
    >>> plan.straggler(5, at=4, factor=3)        # rank 5 joins every 3rd step
    >>> plan.flaky_link(0, 1, at=6, until=9)     # edge 0->1 drops for 3 steps
    >>> plan.corrupt(2, at=7, scale=1e3)         # rank 2 emits garbage once
    >>> tables = plan.compile().tables()
    """

    def __init__(self, size: int, horizon: int, seed: int = 0):
        if size <= 0 or horizon <= 0:
            raise ValueError(f"need size > 0 and horizon > 0, got "
                             f"{size}, {horizon}")
        self.size = size
        self.horizon = horizon
        self.seed = seed
        self.events: List[FaultEvent] = []

    # -- builders (all return self for chaining) ----------------------------

    def _check(self, rank: int, step: int):
        if not 0 <= rank < self.size:
            raise ValueError(f"rank {rank} outside [0, {self.size})")
        if step < 0:
            raise ValueError(f"step {step} must be >= 0")

    def rank_down(self, rank: int, at: int,
                  until: Optional[int] = None) -> "FaultPlan":
        """Rank stops participating at step ``at`` (forever unless
        ``until`` — a bounce models checkpoint-rejoin scenarios)."""
        self._check(rank, at)
        self.events.append(FaultEvent("rank_down", rank, at, until))
        return self

    def straggler(self, rank: int, at: int, factor: int = 2,
                  until: Optional[int] = None) -> "FaultPlan":
        """Rank slows by ``factor``: it participates only every
        ``factor``-th step while the fault is live."""
        self._check(rank, at)
        if factor < 1:
            raise ValueError(f"straggler factor must be >= 1, got {factor}")
        self.events.append(
            FaultEvent("straggler", rank, at, until, factor=float(factor)))
        return self

    def flaky_link(self, src: int, dst: int, at: int,
                   until: Optional[int] = None) -> "FaultPlan":
        """The src->dst edge drops every step in [at, until)."""
        self._check(src, at)
        self._check(dst, at)
        self.events.append(FaultEvent("flaky_link", src, at, until, peer=dst))
        return self

    def corrupt(self, rank: int, at: int, scale: float = float("nan"),
                until: Optional[int] = None) -> "FaultPlan":
        """Rank's outgoing values are scaled by ``scale`` (default NaN:
        pure bit-rot) while the fault is live."""
        self._check(rank, at)
        self.events.append(
            FaultEvent("corrupt", rank, at, until, factor=float(scale)))
        return self

    # -- lowering -----------------------------------------------------------

    def _window(self, ev: FaultEvent) -> Tuple[int, int]:
        lo = min(ev.step, self.horizon)
        hi = self.horizon if ev.until is None else min(ev.until, self.horizon)
        return lo, max(hi, lo)

    def compile(self) -> CompiledFaultPlan:
        T, N = self.horizon, self.size
        alive = np.ones((T, N), np.float32)
        active = np.ones((T, N), np.float32)
        link_ok = np.ones((T, N, N), np.float32)
        corrupt = np.ones((T, N), np.float32)
        for ev in self.events:
            lo, hi = self._window(ev)
            if ev.kind == "rank_down":
                alive[lo:hi, ev.rank] = 0.0
            elif ev.kind == "straggler":
                k = int(ev.factor)
                for t in range(lo, hi):
                    if (t - lo) % k != 0:
                        active[t, ev.rank] = 0.0
            elif ev.kind == "flaky_link":
                link_ok[lo:hi, ev.rank, ev.peer] = 0.0
            elif ev.kind == "corrupt":
                corrupt[lo:hi, ev.rank] = ev.factor
            else:  # pragma: no cover — builders gate the kinds
                raise ValueError(f"unknown fault kind {ev.kind!r}")
        active *= alive  # dead ranks are never active
        return CompiledFaultPlan(size=N, horizon=T, alive=alive,
                                 active=active, link_ok=link_ok,
                                 corrupt=corrupt, events=tuple(self.events))


def empty_plan(size: int, horizon: int) -> CompiledFaultPlan:
    """A fault-free plan (same table shapes: swap in for a clean run
    without recompiling)."""
    return FaultPlan(size, horizon).compile()


def random_plan(size: int, horizon: int, seed: int = 0,
                p_down: float = 0.1, p_straggler: float = 0.1,
                p_flaky: float = 0.05, p_corrupt: float = 0.05,
                max_dead: Optional[int] = None) -> FaultPlan:
    """A seeded random scenario — same seed, same faults, every run.

    Per-rank Bernoulli draws decide which faults appear; onset steps,
    durations, and factors are drawn uniformly.  ``max_dead`` caps the
    number of permanently-dead ranks (default: minority, ``(size-1)//2``),
    so survivors always hold a quorum."""
    rng = np.random.default_rng(seed)
    plan = FaultPlan(size, horizon, seed=seed)
    cap = (size - 1) // 2 if max_dead is None else max_dead
    dead = 0
    for r in range(size):
        if dead < cap and rng.random() < p_down:
            plan.rank_down(r, at=int(rng.integers(1, max(2, horizon // 2))))
            dead += 1
            continue
        if rng.random() < p_straggler:
            plan.straggler(r, at=int(rng.integers(0, horizon)),
                           factor=int(rng.integers(2, 5)))
        if rng.random() < p_corrupt:
            at = int(rng.integers(0, horizon))
            plan.corrupt(r, at=at, until=at + 1,
                         scale=float(rng.choice([np.nan, 1e3, -1e2])))
    n_links = int(p_flaky * size * size)
    for _ in range(n_links):
        s, d = rng.integers(0, size, 2)
        if s == d:
            continue
        at = int(rng.integers(0, horizon))
        plan.flaky_link(int(s), int(d), at=at,
                        until=at + int(rng.integers(1, 4)))
    return plan
