"""Deterministic, seeded fault plans — injectable into any step as traced data.

A :class:`FaultPlan` is a host-side description of what goes wrong and when:
rank death at step k, straggler slow-down, flaky-link drops, value
corruption — and, for elastic membership, ranks *arriving*: a
``rank_join`` pre-allocates a capacity slot that is dead until its join
step, heartbeats through a bounded *syncing* window (parameter
bootstrap), then turns fully active; ``rank_leave`` is the orderly
departure mirror.  :meth:`FaultPlan.compile` lowers everything to
fixed-shape per-step tables ([T, N] / [T, N, N]); jitted programs index
the tables with the *traced* step, so injecting, editing, or clearing a
fault — or admitting and removing a rank — between steps never changes
program shape and never recompiles (asserted in
``tests/test_resilience.py::test_fault_plans_do_not_recompile`` and
``tests/test_elastic.py::test_elastic_episode_zero_recompiles``).

Conventions:

* ``alive[t, i]``     1.0 while rank i is up at step t, 0.0 once down
                      (capacity ranks are 0.0 before their join step).
* ``active[t, i]``    1.0 when rank i participates at step t.  Stragglers
                      are alive but *intermittently* active: a factor-k
                      straggler only joins every k-th step, so its peers see
                      stale, late contributions — the SPMD analog of a slow
                      MPI rank (a dead rank is never active).
* ``sync[t, i]``      1.0 while rank i is in its *syncing* window after a
                      join: alive (heartbeats flow, liveness spreads) but
                      not yet active (it bootstraps parameters and
                      contributes zero mixing weight) — the middle state
                      of the announced → syncing → active admission
                      protocol (docs/resilience.md "Elastic membership").
* ``link_ok[t, i, j]`` 1.0 when the i->j edge delivers at step t.
* ``corrupt[t, i]``   multiplicative scale on rank i's *outgoing* value at
                      step t (1.0 = clean; ``nan`` models bit-rot — the
                      harness's finite-guard must catch it).

Beyond the horizon T the plan holds its LAST state (tables are indexed with
``min(step, T-1)``): a rank that dies stays dead, transient faults end.
"""

import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

__all__ = ["FaultEvent", "FaultPlan", "CompiledFaultPlan", "empty_plan",
           "random_plan", "scale_up_plan", "scale_down_plan", "churn_plan",
           "resolve_sync_steps", "SYNC_STEPS_ENV"]

SYNC_STEPS_ENV = "BLUEFOG_ELASTIC_SYNC_STEPS"


def resolve_sync_steps(value: Optional[int] = None) -> int:
    """``BLUEFOG_ELASTIC_SYNC_STEPS`` (default 2): length of a joiner's
    syncing window — alive-but-inactive steps between its join step and
    full activation, during which it bootstraps parameters and
    contributes no mixing weight."""
    if value is not None:
        sync = int(value)
    else:
        sync = int(os.environ.get(SYNC_STEPS_ENV, "2"))
    if sync < 0:
        raise ValueError(f"sync_steps must be >= 0, got {sync}")
    return sync


@dataclass(frozen=True)
class FaultEvent:
    """One fault.  ``until`` is exclusive; ``None`` = rest of the run."""
    kind: str   # rank_down | straggler | flaky_link | corrupt | rank_join | rank_leave
    rank: int
    step: int
    until: Optional[int] = None
    peer: Optional[int] = None     # flaky_link destination
    factor: float = 1.0            # straggler period / corruption scale /
                                   # join sync-window length


@dataclass(frozen=True)
class CompiledFaultPlan:
    """Fixed-shape per-step fault tables (see module docstring)."""
    size: int
    horizon: int
    alive: np.ndarray        # [T, N] float32
    active: np.ndarray       # [T, N] float32
    link_ok: np.ndarray      # [T, N, N] float32
    corrupt: np.ndarray      # [T, N] float32
    sync: np.ndarray         # [T, N] float32 (joiner syncing windows)
    events: Tuple[FaultEvent, ...] = ()

    def tables(self) -> Dict[str, "np.ndarray"]:
        """The tables as device arrays, ready to pass into a jitted step.

        Every plan of the same ``(size, horizon)`` produces identically
        shaped tables — swap plans freely between calls of one compiled
        program.  The device upload is CACHED per plan instance: calling
        this every step of a loop hands back the same arrays instead of
        re-uploading fresh device buffers each time."""
        cached = self.__dict__.get("_tables")
        if cached is None:
            import jax.numpy as jnp
            cached = {"alive": jnp.asarray(self.alive),
                      "active": jnp.asarray(self.active),
                      "link_ok": jnp.asarray(self.link_ok),
                      "corrupt": jnp.asarray(self.corrupt),
                      "sync": jnp.asarray(self.sync)}
            object.__setattr__(self, "_tables", cached)
        return cached

    def num_dead_at(self, step: int) -> int:
        t = min(step, self.horizon - 1)
        return int((self.alive[t] == 0).sum())

    def alive_at(self, step: int) -> np.ndarray:
        """Host-side [N] liveness row at ``step`` (clamped to the
        horizon) — the mask host-side consumers (the serving router,
        ``win_update(alive=)`` callers, report code) feed per step
        without instantiating device tables."""
        return self.alive[min(step, self.horizon - 1)]

    def active_at(self, step: int) -> np.ndarray:
        """Host-side [N] participation row at ``step`` (stragglers are
        alive but intermittently active — e.g. a publisher that only
        ships weights on its active steps)."""
        return self.active[min(step, self.horizon - 1)]

    def sync_at(self, step: int) -> np.ndarray:
        """Host-side [N] syncing row at ``step`` — 1.0 for joiners in
        their bootstrap window (alive, zero mixing weight)."""
        return self.sync[min(step, self.horizon - 1)]

    @property
    def capacity_ranks(self) -> Tuple[int, ...]:
        """Ranks pre-allocated as elastic capacity (they carry a
        ``rank_join`` event and are dead before its step — a join at the
        horizon reserves the slot without ever admitting it)."""
        return tuple(sorted({ev.rank for ev in self.events
                             if ev.kind == "rank_join"}))


def at_step(tables: Dict, step):
    """Index the device tables with a traced step (clamped to the horizon).

    Returns ``(alive[N], active[N], link_ok[N, N], corrupt[N], sync[N])``
    for the step — all traced values; use inside jit."""
    import jax.numpy as jnp
    t = jnp.minimum(jnp.asarray(step, jnp.int32),
                    tables["alive"].shape[0] - 1)
    return (tables["alive"][t], tables["active"][t],
            tables["link_ok"][t], tables["corrupt"][t],
            tables["sync"][t])


class FaultPlan:
    """Builder for deterministic fault scenarios.

    >>> plan = FaultPlan(size=8, horizon=40)
    >>> plan.rank_down(3, at=10)                 # rank 3 dies at step 10
    >>> plan.straggler(5, at=4, factor=3)        # rank 5 joins every 3rd step
    >>> plan.flaky_link(0, 1, at=6, until=9)     # edge 0->1 drops for 3 steps
    >>> plan.corrupt(2, at=7, scale=1e3)         # rank 2 emits garbage once
    >>> tables = plan.compile().tables()
    """

    def __init__(self, size: int, horizon: int, seed: int = 0):
        if size <= 0 or horizon <= 0:
            raise ValueError(f"need size > 0 and horizon > 0, got "
                             f"{size}, {horizon}")
        self.size = size
        self.horizon = horizon
        self.seed = seed
        self.events: List[FaultEvent] = []

    # -- builders (all return self for chaining) ----------------------------

    def _check(self, rank: int, step: int):
        if not 0 <= rank < self.size:
            raise ValueError(f"rank {rank} outside [0, {self.size})")
        if step < 0:
            raise ValueError(f"step {step} must be >= 0")

    def rank_down(self, rank: int, at: int,
                  until: Optional[int] = None) -> "FaultPlan":
        """Rank stops participating at step ``at`` (forever unless
        ``until`` — a bounce models checkpoint-rejoin scenarios)."""
        self._check(rank, at)
        self.events.append(FaultEvent("rank_down", rank, at, until))
        return self

    def straggler(self, rank: int, at: int, factor: int = 2,
                  until: Optional[int] = None) -> "FaultPlan":
        """Rank slows by ``factor``: it participates only every
        ``factor``-th step while the fault is live."""
        self._check(rank, at)
        if factor < 1:
            raise ValueError(f"straggler factor must be >= 1, got {factor}")
        self.events.append(
            FaultEvent("straggler", rank, at, until, factor=float(factor)))
        return self

    def flaky_link(self, src: int, dst: int, at: int,
                   until: Optional[int] = None) -> "FaultPlan":
        """The src->dst edge drops every step in [at, until)."""
        self._check(src, at)
        self._check(dst, at)
        self.events.append(FaultEvent("flaky_link", src, at, until, peer=dst))
        return self

    def corrupt(self, rank: int, at: int, scale: float = float("nan"),
                until: Optional[int] = None) -> "FaultPlan":
        """Rank's outgoing values are scaled by ``scale`` (default NaN:
        pure bit-rot) while the fault is live."""
        self._check(rank, at)
        self.events.append(
            FaultEvent("corrupt", rank, at, until, factor=float(scale)))
        return self

    def rank_join(self, rank: int, at: int,
                  sync_steps: Optional[int] = None,
                  until: Optional[int] = None) -> "FaultPlan":
        """Elastic admission: ``rank`` is a pre-allocated capacity slot —
        dead before step ``at``, *syncing* (alive, heartbeating, zero
        mixing weight — the parameter-bootstrap window) for
        ``sync_steps`` steps (default ``BLUEFOG_ELASTIC_SYNC_STEPS``),
        fully active from ``at + sync_steps`` until ``until`` (exclusive;
        ``None`` = rest of the run).

        ``at >= horizon`` reserves the capacity slot without ever
        admitting it (the tables keep their fixed shape, so a later plan
        that does admit it swaps in with zero recompiles)."""
        if not 0 <= rank < self.size:
            raise ValueError(f"rank {rank} outside [0, {self.size})")
        if at < 0:
            raise ValueError(f"join step {at} must be >= 0")
        self.events.append(FaultEvent(
            "rank_join", rank, at, until,
            factor=float(resolve_sync_steps(sync_steps))))
        return self

    def rank_leave(self, rank: int, at: int) -> "FaultPlan":
        """Orderly departure (elastic scale-down): same lowering as
        :meth:`rank_down` — the rank stops participating at ``at``,
        permanently — but recorded as a distinct event kind so
        membership observers report a *departure*, not a failure."""
        self._check(rank, at)
        self.events.append(FaultEvent("rank_leave", rank, at, None))
        return self

    # -- lowering -----------------------------------------------------------

    def _window(self, ev: FaultEvent) -> Tuple[int, int]:
        lo = min(ev.step, self.horizon)
        hi = self.horizon if ev.until is None else min(ev.until, self.horizon)
        return lo, max(hi, lo)

    def compile(self) -> CompiledFaultPlan:
        T, N = self.horizon, self.size
        alive = np.ones((T, N), np.float32)
        active = np.ones((T, N), np.float32)
        link_ok = np.ones((T, N, N), np.float32)
        corrupt = np.ones((T, N), np.float32)
        sync = np.zeros((T, N), np.float32)
        for ev in self.events:
            lo, hi = self._window(ev)
            if ev.kind in ("rank_down", "rank_leave"):
                alive[lo:hi, ev.rank] = 0.0
            elif ev.kind == "straggler":
                k = int(ev.factor)
                for t in range(lo, hi):
                    if (t - lo) % k != 0:
                        active[t, ev.rank] = 0.0
            elif ev.kind == "flaky_link":
                link_ok[lo:hi, ev.rank, ev.peer] = 0.0
            elif ev.kind == "corrupt":
                corrupt[lo:hi, ev.rank] = ev.factor
            elif ev.kind == "rank_join":
                # capacity pre-allocation: dead before the join step,
                # syncing (alive, inactive) through the bootstrap
                # window, active after — and dead again past `until`
                alive[:lo, ev.rank] = 0.0
                alive[hi:, ev.rank] = 0.0
                s_hi = min(lo + int(ev.factor), hi)
                sync[lo:s_hi, ev.rank] = 1.0
                active[:s_hi, ev.rank] = 0.0
            else:  # pragma: no cover — builders gate the kinds
                raise ValueError(f"unknown fault kind {ev.kind!r}")
        active *= alive           # dead ranks are never active
        sync *= alive             # ...and never syncing
        active *= (1.0 - sync)    # syncing ranks carry no mixing weight
        return CompiledFaultPlan(size=N, horizon=T, alive=alive,
                                 active=active, link_ok=link_ok,
                                 corrupt=corrupt, sync=sync,
                                 events=tuple(self.events))


def empty_plan(size: int, horizon: int) -> CompiledFaultPlan:
    """A fault-free plan, returned **compiled** (same table shapes: swap
    in for a clean run without recompiling).  Note the deliberate API
    asymmetry with :func:`random_plan`, which returns the *builder* so
    callers can stack more events — pass ``compiled=True`` there for the
    symmetric behavior."""
    return FaultPlan(size, horizon).compile()


def random_plan(size: int, horizon: int, seed: int = 0,
                p_down: float = 0.1, p_straggler: float = 0.1,
                p_flaky: float = 0.05, p_corrupt: float = 0.05,
                max_dead: Optional[int] = None,
                p_join: float = 0.0, capacity: int = 0,
                sync_steps: Optional[int] = None, compiled: bool = False
                ) -> Union[FaultPlan, CompiledFaultPlan]:
    """A seeded random scenario — same seed, same faults, every run.

    Per-rank Bernoulli draws decide which faults appear; onset steps,
    durations, and factors are drawn uniformly.  ``max_dead`` caps the
    number of permanently-dead ranks (default: minority of the non-capacity
    base, ``(size-capacity-1)//2``), so survivors always hold a quorum.

    Churn (elastic membership): the LAST ``capacity`` ranks are
    pre-allocated capacity slots — dead at step 0, each joining with
    probability ``p_join`` at a random step in the first half of the run
    (``rank_join`` with a ``sync_steps`` bootstrap window; a slot that
    does not join stays reserved via a join at the horizon), and each
    admitted joiner later leaving with probability ``p_down``
    (``rank_leave``) — so one seeded plan covers scale-up, scale-down,
    and full churn.  Base faults never land on capacity ranks.

    Returns the :class:`FaultPlan` builder (stack more events, then
    ``.compile()``); ``compiled=True`` returns the
    :class:`CompiledFaultPlan` directly — the same shape
    :func:`empty_plan` returns."""
    if not 0 <= capacity < size:
        raise ValueError(f"capacity must be in [0, {size}), got {capacity}")
    rng = np.random.default_rng(seed)
    plan = FaultPlan(size, horizon, seed=seed)
    base = size - capacity
    cap = (base - 1) // 2 if max_dead is None else max_dead
    dead = 0
    for r in range(base):
        if dead < cap and rng.random() < p_down:
            plan.rank_down(r, at=int(rng.integers(1, max(2, horizon // 2))))
            dead += 1
            continue
        if rng.random() < p_straggler:
            plan.straggler(r, at=int(rng.integers(0, horizon)),
                           factor=int(rng.integers(2, 5)))
        if rng.random() < p_corrupt:
            at = int(rng.integers(0, horizon))
            plan.corrupt(r, at=at, until=at + 1,
                         scale=float(rng.choice([np.nan, 1e3, -1e2])))
    k = resolve_sync_steps(sync_steps)
    for r in range(base, size):
        if rng.random() < p_join:
            at = int(rng.integers(1, max(2, horizon // 2)))
            plan.rank_join(r, at=at, sync_steps=k)
            if rng.random() < p_down:
                leave_lo = min(at + k + 1, horizon - 1)
                plan.rank_leave(r, at=int(rng.integers(leave_lo, horizon)))
        else:
            plan.rank_join(r, at=horizon, sync_steps=k)  # reserved slot
    n_links = int(p_flaky * size * size)
    for _ in range(n_links):
        s, d = rng.integers(0, size, 2)
        if s == d:
            continue
        at = int(rng.integers(0, horizon))
        plan.flaky_link(int(s), int(d), at=at,
                        until=at + int(rng.integers(1, 4)))
    return plan.compile() if compiled else plan


def _rank_steps(spec: Union[Dict[int, int], Sequence[Tuple[int, int]]]
                ) -> List[Tuple[int, int]]:
    if isinstance(spec, dict):
        return [(int(r), int(t)) for r, t in sorted(spec.items())]
    return [(int(r), int(t)) for r, t in spec]


def scale_up_plan(size: int, horizon: int,
                  joins: Union[Dict[int, int], Sequence[Tuple[int, int]]],
                  sync_steps: Optional[int] = None) -> FaultPlan:
    """Elastic scale-up scenario: each ``rank: join_step`` entry is a
    pre-allocated capacity rank admitted mid-run (``rank_join`` with the
    default sync window).  The chaos harness runs it like any plan —
    admission is traced data."""
    plan = FaultPlan(size, horizon)
    for r, at in _rank_steps(joins):
        plan.rank_join(r, at=at, sync_steps=sync_steps)
    return plan


def scale_down_plan(size: int, horizon: int,
                    leaves: Union[Dict[int, int], Sequence[Tuple[int, int]]]
                    ) -> FaultPlan:
    """Elastic scale-down scenario: each ``rank: leave_step`` entry is an
    orderly mid-run departure (``rank_leave``)."""
    plan = FaultPlan(size, horizon)
    for r, at in _rank_steps(leaves):
        plan.rank_leave(r, at=at)
    return plan


def churn_plan(size: int, horizon: int,
               episodes: Sequence[Tuple[int, int, int]],
               sync_steps: Optional[int] = None) -> FaultPlan:
    """Full churn: each ``(rank, join_at, leave_at)`` episode admits a
    capacity rank and later removes it (``rank_join(..., until=leave_at)``
    — the bounded-engagement form)."""
    plan = FaultPlan(size, horizon)
    for r, join_at, leave_at in episodes:
        if leave_at <= join_at:
            raise ValueError(
                f"churn episode for rank {r}: leave step {leave_at} must "
                f"be after join step {join_at}")
        plan.rank_join(r, at=join_at, sync_steps=sync_steps, until=leave_at)
    return plan
