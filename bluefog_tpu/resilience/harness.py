"""Chaos-test harness: run a training loop under a fault plan and check
that the decentralized algorithm degrades gracefully.

The harness compiles ONE jitted SPMD chaos step containing the full
resilience loop — fault injection, heartbeat gossip, per-rank liveness
beliefs, traced matrix repair, the consensus update (``optim.strategies``
CTA semantics: mix the weights, adapt from local gradients), and survivor
freezing — with every per-step quantity (step index, fault tables) as
traced data.  Injecting, moving, or clearing faults between steps therefore
never recompiles (``tests/test_resilience.py`` asserts the compile count).

What a step does, per rank j:

1. gossip heartbeats over the topology's edges (``membership``), masked by
   this step's liveness/link tables (``faults``);
2. build j's receive column from its OWN beliefs: in-weights of peers it
   has confirmed dead (or that dropped out / sent non-finite values this
   step) go to zero and the lost mass moves to j's self weight
   (``repair.repair_matrix_traced`` semantics, computed per column);
3. mix the gathered neighbor values with that column, then apply the local
   optimizer update at the mixed point (consensus/CTA);
4. freeze: inactive ranks keep their old parameters and optimizer state.

The per-rank columns are also emitted as the step's effective global mixing
matrix so the report can assert stochasticity invariants.
"""

import functools
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import timeline as _tl
from ..compress import compressors as _cp
from ..compress import exchange as _cx
from ..context import ctx
from ..observability import metrics as _metrics
from ..ops import collectives as C
from ..ops import fusion as _fusion
from ..parallel.schedule import CompiledTopology
from ..optim.strategies import overlap_enabled as _strategies_overlap_enabled
from . import faults as _faults
from . import membership as _mem

__all__ = ["ChaosHarness", "ChaosReport"]

# bflint knob-outside-cache-key: ChaosHarness pins its episode
# configuration (fault plan, base optimizer, liveness config, loss,
# topology) at construction and builds its programs once per instance —
# instance identity keys them; fault flips themselves are traced data
# (the whole point of the seeded fault tables).
_STEP_KEY_EXEMPT_KNOBS = frozenset({"base_opt", "cfg", "loss_fn", "topo"})


@dataclass
class ChaosReport:
    """Trajectories and final state of one chaos run."""
    losses: np.ndarray            # [T] survivor-mean loss per step
    consensus_errors: np.ndarray  # [T] survivor RMS distance to survivor mean
    dead_votes: np.ndarray        # [T, N] confirmed-dead votes per step
    mixing_matrices: np.ndarray   # [T, N, N] effective repaired W per step
    alive_steps: np.ndarray       # [T, N] plan liveness at each run step
    sync_steps: np.ndarray        # [T, N] plan syncing windows (joiners)
    params_final: object          # global-view parameter tree
    events: List[str] = field(default_factory=list)
    # elastic-membership audit log: (step, rank, new_state) transitions
    # the host directory observed (membership.ElasticMembership)
    membership_transitions: List[tuple] = field(default_factory=list)

    @property
    def alive_final(self) -> np.ndarray:
        return self.alive_steps[-1]

    @property
    def confirmed_dead(self) -> np.ndarray:
        """Ranks a survivor majority had confirmed dead by the end."""
        n = self.dead_votes.shape[1]
        n_alive = int(self.alive_final.sum())
        return np.nonzero(self.dead_votes[-1] > n_alive // 2)[0]

    @property
    def admitted(self) -> List[int]:
        """Ranks the membership directory observed turning active
        (elastic admissions, in transition order)."""
        return [r for _, r, s in self.membership_transitions
                if s == _mem.STATE_ACTIVE]

    @property
    def departed(self) -> List[int]:
        """Ranks the membership directory observed leaving."""
        return [r for _, r, s in self.membership_transitions
                if s == _mem.STATE_LEFT]

    def check_matrix_invariants(self, step: int = -1, atol: float = 1e-5):
        """Assert the step's effective matrix is column-stochastic,
        non-negative, carries zero weight to/from ranks dead AT THAT
        STEP (a rank that dies mid-run legitimately mixes before its
        death), and that syncing joiners receive (their catch-up fold)
        but contribute nothing until admitted."""
        W = self.mixing_matrices[step]
        np.testing.assert_allclose(W.sum(axis=0), 1.0, atol=atol,
                                   err_msg="columns must sum to 1")
        assert (W >= -atol).all(), "negative mixing weight after repair"
        dead = np.nonzero(self.alive_steps[step] == 0)[0]
        for r in dead:
            off_col = np.delete(W[:, r], r)
            assert np.allclose(off_col, 0.0, atol=atol), \
                f"dead rank {r} still receives weight"
            off_row = np.delete(W[r, :], r)
            assert np.allclose(off_row, 0.0, atol=atol), \
                f"dead rank {r} still contributes weight"
        for r in np.nonzero(self.sync_steps[step] > 0)[0]:
            off_row = np.delete(W[r, :], r)
            assert np.allclose(off_row, 0.0, atol=atol), \
                f"syncing rank {r} contributes weight before admission"

    def assert_bounded(self, max_consensus_error: float,
                       settle_frac: float = 0.5):
        """Assert the survivor consensus error stays bounded over the run
        and is still bounded at the end (no divergence after faults)."""
        tail = self.consensus_errors[int(len(self.consensus_errors)
                                         * settle_frac):]
        assert np.isfinite(self.losses).all(), "loss went non-finite"
        assert np.isfinite(self.consensus_errors).all(), \
            "consensus error went non-finite"
        assert float(tail.max()) <= max_consensus_error, (
            f"consensus error {tail.max():.4g} exceeded bound "
            f"{max_consensus_error:.4g} after faults")


def _default_quadratic(params, target):
    """Per-rank quadratic: minimizing the survivor mean drives consensus
    toward the mean target — heterogeneous per-rank objectives, the
    standard decentralized-SGD testbed."""
    return 0.5 * jnp.sum((params - target) ** 2)


class ChaosHarness:
    """Wraps a ``training.py``-style consensus loop with a fault plan.

    ``plan`` is a :class:`~bluefog_tpu.resilience.faults.FaultPlan` (or an
    already-compiled one).  ``loss_fn(params_local, batch_local)`` defaults
    to a per-rank quadratic toward seeded targets.  ``base_opt`` defaults
    to SGD(0.1).  Thresholds come from ``cfg``
    (:class:`~bluefog_tpu.resilience.membership.LivenessConfig`).

    ``fuse`` (default ``BLUEFOG_COMM_FUSION``, on): the per-step parameter
    gather + consensus mix run over dtype-bucketed flat buffers
    (``ops/fusion.py``) — one allgather per bucket instead of one per
    parameter leaf, bit-exact (the mix is elementwise-linear).

    ``overlap`` (default ``BLUEFOG_COMM_OVERLAP``, off): staleness-1
    delayed-mix pipeline under chaos — the step mixes the gathered values
    LAUNCHED at the previous step (carried in the loop state) while
    launching this step's gather off the critical path.  Crucially, the
    liveness-masked repair column is built at FOLD time from the CURRENT
    beliefs/fault tables: a rank that died after its value entered the
    pipeline gets zero weight when the stale buffer is folded, its mass
    absorbed into the receiver's self weight — a mid-pipeline death
    degrades to self-weight instead of folding stale garbage.  Step 0
    folds the gathered initial parameters (synchronous warmup).

    ``compression`` (default ``BLUEFOG_COMM_COMPRESS``, off): the gather
    moves compressed wire payloads (direct specs only); error-feedback
    residuals ride the loop state and reset for inactive ranks — the
    repaired column falls back to self weight with residuals cleared
    (docs/compression.md).
    """

    def __init__(self, plan, *, base_opt=None,
                 topo: Optional[CompiledTopology] = None,
                 cfg: Optional[_mem.LivenessConfig] = None,
                 loss_fn: Optional[Callable] = None,
                 fuse: Optional[bool] = None,
                 overlap: Optional[bool] = None,
                 compression=None):
        if isinstance(plan, _faults.FaultPlan):
            plan = plan.compile()
        self.plan: _faults.CompiledFaultPlan = plan
        self.cx = ctx()
        if plan.size != self.cx.size:
            raise ValueError(
                f"fault plan is over {plan.size} ranks, mesh has "
                f"{self.cx.size}")
        self.topo = topo or self.cx.compiled_topology
        self.cfg = cfg or _mem.LivenessConfig()
        self.base_opt = base_opt or optax.sgd(0.1)
        self.loss_fn = loss_fn or _default_quadratic
        # snapshot at construction (the chaos step compiles once)
        self.fuse = _fusion.fusion_enabled(fuse)
        self.overlap = _strategies_overlap_enabled(overlap)
        # wire compression under chaos (compress/): the per-step gather
        # moves compressed payloads; error-feedback residuals ride the
        # loop-carried state and RESET for inactive ranks — a repaired/
        # degraded column falls back to self weight without re-injecting
        # residuals accumulated against the dead topology.  Direct specs
        # only: choco's accumulated estimates assume a constant W, which
        # is exactly what liveness repair violates.
        self.compression = _cp.resolve_compression(compression)
        if self.compression is not None and self.compression.choco:
            raise ValueError(
                "ChaosHarness supports direct compression specs only "
                "('int8', 'topk:0.01', ...): choco's accumulated replica "
                "estimates assume a constant mixing matrix, which liveness "
                "repair deliberately changes per step")
        self._comp_stateful = _cx.stateful(self.compression)
        self._step_fn = None

    # -- the one jitted chaos step ------------------------------------------

    def _build_step(self):
        cx, topo, cfg = self.cx, self.topo, self.cfg
        base_opt, loss_fn = self.base_opt, self.loss_fn
        fuse, overlap = self.fuse, self.overlap
        comp_cfg = self.compression
        comp = (_cp.get_compressor(comp_cfg)
                if comp_cfg is not None else None)
        comp_stateful = self._comp_stateful
        axis = cx.rank_axis
        n = topo.size
        W0 = topo.weight_matrix
        spec = P(axis)

        def shard_fn(p_s, opt_s, lh_s, batch_s, step, alive, active,
                     link_ok, corrupt, sync, gprev_s, fprev_s, rprev_s):
            x = jax.tree.map(lambda a: a[0], p_s)
            st = jax.tree.map(lambda a: a[0], opt_s)
            b = jax.tree.map(lambda a: a[0], batch_s)
            row = lh_s[0]
            idx = lax.axis_index(axis)

            # 1. membership gossip over the live edges.  Heartbeats flow
            #    for active AND syncing ranks: a joiner in its bootstrap
            #    window announces itself through the gossip (that is how
            #    the fleet's beliefs re-admit it) while still carrying
            #    zero mixing weight below.
            heartbeat = jnp.maximum(active, sync)
            row = _mem.gossip_last_heard(row, axis, topo, step, heartbeat,
                                         link_ok)
            stale = jnp.asarray(step, jnp.int32) - row
            trusted = (stale <= cfg.suspect_after)     # fresh enough to mix
            confirmed_dead = (stale > cfg.confirm_after)

            # 2. local loss/grads at the pre-mix point (consensus/CTA)
            loss, grads = jax.value_and_grad(loss_fn)(x, b)

            # 3. outgoing values: corruption rides the wire; receivers
            #    drop non-finite contributions (finite-guard).  Under
            #    fusion the gather moves dtype-bucketed flat buffers —
            #    one allgather per bucket, not per leaf.  Under
            #    compression the gather moves each bucket's WIRE encoding
            #    (compress/compressors.py) and decodes all rows locally;
            #    error-feedback residuals ride rprev_s.
            out_x = jax.tree.map(
                lambda l: l * corrupt[idx].astype(l.dtype), x)
            if fuse:
                fplan = _fusion.plan_for(out_x)
                x_bufs = _fusion.flatten(fplan, x)
                out_bufs = _fusion.flatten(fplan, out_x)
            else:
                fplan, x_bufs = None, jax.tree.leaves(x)
                out_bufs = jax.tree.leaves(out_x)
            finite_own = jnp.asarray(True)
            for leaf in out_bufs:
                finite_own &= jnp.isfinite(leaf).all()
            if comp is not None:
                gathered_bufs, res_new = [], []
                res_prev = [r[0] for r in rprev_s]
                for b, ob in enumerate(out_bufs):
                    skey = jax.random.fold_in(jax.random.fold_in(
                        jax.random.key(0xC405), step), b)
                    rkey = jax.random.fold_in(skey, idx)
                    t = ob + res_prev[b] if comp_stateful else ob
                    wire = comp.compress(t, skey, rkey)
                    gw = jax.tree.map(
                        lambda a: C.allgather(a[None], axis), wire)
                    dec = jax.vmap(lambda w: comp.decompress(
                        w, skey, ob.shape, ob.dtype))(gw)
                    gathered_bufs.append(dec)
                    if comp_stateful:
                        res_new.append(t - dec[idx])
            else:
                gathered_bufs = [C.allgather(l[None], axis)
                                 for l in out_bufs]
                res_new = []
            finite = C.allgather(finite_own[None], axis)      # [N]
            if overlap:
                # staleness-1 pipeline: this step's gather only LAUNCHES
                # (it becomes the next step's carried buffer, so XLA can
                # overlap it with the rest of the step); the values mixed
                # BELOW are the ones launched at step t-1, with their
                # launch-time finite verdicts
                mix_bufs_in = [g[0] for g in gprev_s]
                mix_finite = fprev_s[0]
            else:
                mix_bufs_in, mix_finite = gathered_bufs, finite

            # 4. this rank's repaired receive column (traced surgery):
            #    zero anything dead/suspect/inactive/dropped/non-finite,
            #    self weight absorbs the lost mass.  Under overlap this
            #    column is built from the CURRENT step's beliefs and fault
            #    tables but applied to the IN-FLIGHT (stale) values — the
            #    liveness repair reaches into the pipeline: a rank that
            #    died after launch contributes nothing at fold time.
            col = jnp.asarray(W0)[:, idx]
            # trusted already excludes confirmed-dead peers (suspect_after
            # <= confirm_after by LivenessConfig)
            keep = trusted & (active > 0) & (link_ok[:, idx] > 0) & mix_finite
            col = jnp.where(keep, col, 0.0).at[idx].set(0.0)
            self_w = 1.0 - col.sum()
            col = col.at[idx].set(self_w)

            # 5. mix, then adapt at the mixed point.  The self term uses
            #    the LOCAL clean value, not the (possibly corrupted)
            #    outgoing one — corruption rides the wire, it does not
            #    poison the sender's own state.  (Under overlap the self
            #    term is FRESH while neighbor terms are one step stale —
            #    the delayed-mix semantics of optim/strategies.)
            neigh_col = col.at[idx].set(0.0)
            # zero-weight is not enough against NaN (0 * NaN = NaN): scrub
            # non-finite contributions out of the gathered values too
            mix_one = lambda g, l: (jnp.tensordot(
                neigh_col.astype(l.dtype),
                jnp.where(jnp.isfinite(g), g, 0), axes=1)
                                    + self_w.astype(l.dtype) * l)
            mixed_bufs = [mix_one(g, l)
                          for g, l in zip(mix_bufs_in, x_bufs)]
            if fuse:
                mixed = _fusion.unflatten(fplan, mixed_bufs)
            else:
                mixed = jax.tree.unflatten(jax.tree.structure(x),
                                           mixed_bufs)
            updates, st_new = base_opt.update(grads, st, mixed)
            x_new = optax.apply_updates(mixed, updates)

            # 5b. syncing-joiner catch-up fold (elastic admission): a
            #     rank in its bootstrap window adopts the average of its
            #     ACTIVE trusted in-neighbors outright — no self term
            #     (its own value is whatever the capacity slot held),
            #     no gradient step — so it converges to the fleet
            #     average BEFORE it contributes mixing weight.  No live
            #     feed => keep own value (bounded staleness).
            neigh_mass = neigh_col.sum()
            cat_col = jnp.where(
                neigh_mass > 0,
                neigh_col / jnp.maximum(neigh_mass, 1e-20),
                jnp.zeros_like(neigh_col))
            cat_self = jnp.where(neigh_mass > 0, 0.0, 1.0)
            catch_bufs = [jnp.tensordot(
                cat_col.astype(l.dtype),
                jnp.where(jnp.isfinite(g), g, 0), axes=1)
                + cat_self.astype(l.dtype) * l
                for g, l in zip(mix_bufs_in, x_bufs)]
            if fuse:
                x_catch = _fusion.unflatten(fplan, catch_bufs)
            else:
                x_catch = jax.tree.unflatten(jax.tree.structure(x),
                                             catch_bufs)

            # 6. freeze inactive ranks (dead or straggling this step) —
            #    their effective receive column is identity, they keep
            #    their value — except syncing joiners, which take the
            #    catch-up fold (their column is the normalized pull)
            me_active = active[idx] > 0
            me_sync = sync[idx] > 0
            x_new = jax.tree.map(
                lambda new, catch, old: jnp.where(
                    me_active, new, jnp.where(me_sync, catch, old)),
                x_new, x_catch, x)
            st_new = jax.tree.map(
                lambda new, old: jnp.where(me_active, new, old), st_new, st)
            sync_col = cat_col.at[idx].set(cat_self)
            ident_col = jnp.zeros_like(col).at[idx].set(1.0)
            col = jnp.where(me_active, col,
                            jnp.where(me_sync, sync_col, ident_col))

            votes = confirmed_dead.astype(jnp.int32)          # my view
            # residual reset for inactive ranks: a frozen/degraded rank's
            # error feedback must not re-inject into the repaired topology
            # when (if) it recovers — it restarts clean, like the overlap
            # pipeline reset in optim/strategies.delayed_local_step
            res_out = tuple(
                jnp.where(me_active, r, jnp.zeros_like(r))
                for r in res_new)
            lead = lambda t: jax.tree.map(lambda a: a[None], t)
            return (lead(x_new), lead(st_new), row[None], loss[None],
                    col[None], votes[None],
                    tuple(g[None] for g in gathered_bufs), finite[None],
                    tuple(r[None] for r in res_out))

        def stepper(params, opt_state, last_heard, batch, step, tables,
                    carried):
            (alive, active, link_ok, corrupt,
             sync) = _faults.at_step(tables, step)
            gprev, fprev, rprev = carried
            (p2, o2, lh2, loss_r, cols, votes, gnew,
             fnew, rnew) = jax.shard_map(
                shard_fn, mesh=cx.mesh,
                in_specs=(spec, spec, spec, spec, P(), P(), P(), P(), P(),
                          P(), spec, spec, spec),
                out_specs=(spec, spec, spec, spec, spec, spec, spec, spec,
                           spec),
            )(params, opt_state, last_heard, batch,
              jnp.asarray(step, jnp.int32), alive, active, link_ok, corrupt,
              sync, gprev, fprev, rprev)
            # survivor metrics (active-weighted)
            wsum = jnp.maximum(active.sum(), 1.0)
            loss_mean = (loss_r * active).sum() / wsum
            flat = jnp.concatenate(
                [l.reshape(n, -1) for l in jax.tree.leaves(p2)], axis=1)
            mean = (flat * active[:, None]).sum(0) / wsum
            dist2 = ((flat - mean[None]) ** 2).sum(1)
            cons = jnp.sqrt((dist2 * active).sum() / wsum)
            W_eff = cols.T                       # cols[j] is column j
            dead_votes = votes.sum(axis=0)
            return (p2, o2, lh2, loss_mean, cons, W_eff, dead_votes,
                    (gnew, fnew, rnew))

        return jax.jit(stepper)

    def _initial_carried(self, params):
        """Warmup in-flight state: the gathered INITIAL parameters with
        all-finite verdicts, tiled to every rank's view — step 0 then
        folds x_0's values (a synchronous first mix), and from step 1 on
        the carried buffer is one step stale.  Built host-side: no
        collective needed, params are already global-view."""
        n = self.plan.size
        if self.fuse:
            # leading_dims=1 keeps the rank axis: same bucket layout as
            # the per-rank plan inside the step (sizes exclude lead dims)
            gplan = _fusion.plan_for(params, leading_dims=1)
            bufs = _fusion.flatten(gplan, params)     # [N, L] per bucket
        else:
            bufs = list(jax.tree.leaves(params))
        from ..ops import api as _api
        # rank-sharded like every other loop-carried array: an uncommitted
        # host layout here would give the first call its own jit cache
        # entry (sharding is part of the key) — one warmup recompile
        gathered0 = tuple(
            _api.to_global(jnp.broadcast_to(b[None], (n,) + b.shape))
            for b in bufs)
        finite0 = _api.to_global(jnp.ones((n, n), bool))
        # error-feedback residuals start at zero (nothing transmitted
        # yet), shaped like the per-rank buffers ([N, ...] global view);
        # empty tuple when the compression config carries no state
        if self._comp_stateful:
            res0 = tuple(_api.to_global(jnp.zeros_like(b)) for b in bufs)
        else:
            res0 = ()
        return (gathered0, finite0, res0)

    # -- driver --------------------------------------------------------------

    def run(self, params0, *, steps: int, batches=None,
            opt_state=None, membership_trail=None) -> ChaosReport:
        """Run ``steps`` chaos steps from global-view ``params0`` [N, ...].

        ``batches``: optional callable ``step -> global batch`` (defaults
        to seeded per-rank quadratic targets held constant).  Returns a
        :class:`ChaosReport`; fault onsets and majority-confirmed deaths
        are recorded on the timeline as host activities.

        Elastic membership: when the plan carries ``rank_join`` /
        ``rank_leave`` events, a host-side
        :class:`~bluefog_tpu.resilience.membership.ElasticMembership`
        directory observes the run — plan onsets announce/depart, the
        gossiped ``last_heard`` table drives announced → syncing →
        active — and its transitions land in
        ``report.membership_transitions`` (+ the ``bf_membership_*``
        gauges).  ``membership_trail``: metrics prefix (or explicit
        path) for the sidecar ``<prefix>membership.jsonl`` trail
        ``bfmonitor --membership`` renders."""
        from ..observability import export as _export
        from ..ops import api as _api
        if isinstance(self.plan, _faults.FaultPlan):
            # plans injected between runs may be builders; compiling here
            # keeps the swap-a-plan idiom uniform (same table shapes,
            # same compiled step)
            self.plan = self.plan.compile()
        if self._step_fn is None:
            self._step_fn = self._build_step()
        n = self.plan.size
        params = jax.tree.map(lambda a: _api.to_global(jnp.asarray(a)),
                              params0)
        if opt_state is None:
            opt_state = jax.vmap(self.base_opt.init)(params)
        if batches is None:
            lead = jax.tree.leaves(params)[0]
            rng = np.random.default_rng(self.plan.horizon + 17 * n)
            targets = jnp.asarray(
                rng.normal(size=lead.shape).astype(np.float32) * 2.0)
            batch_of = lambda _t: targets
        else:
            batch_of = batches
        tables = self.plan.tables()
        state = _mem.init_state(n)["last_heard"]
        state = _api.to_global(state)

        events = [f"plan: {ev.kind} rank={ev.rank} step={ev.step}"
                  for ev in getattr(self.plan, "events", [])]
        if _metrics.enabled():
            # fault ONSETS come from the compiled plan (the ground truth
            # the injected tables execute); suspects/confirms/repairs are
            # counted as they are observed below
            for ev in getattr(self.plan, "events", []):
                _metrics.counter(
                    "bf_resilience_faults_total",
                    "planned fault onsets by kind").inc(kind=ev.kind)
        _tl.record_resilience_event("chaos_run_start",
                                    f"{steps} steps, {n} ranks")
        carried = self._initial_carried(params)

        # elastic-membership directory: capacity ranks come from the
        # plan's join events; the plan announces/departs (ground truth
        # onsets, like fault onsets above), the gossiped last_heard
        # table drives the announced -> syncing -> active observation
        elastic_events = [ev for ev in getattr(self.plan, "events", [])
                          if ev.kind in ("rank_join", "rank_leave")]
        directory = _mem.ElasticMembership(
            n, capacity=getattr(self.plan, "capacity_ranks", ()),
            cfg=self.cfg)
        trail = None
        if membership_trail:
            path = (membership_trail
                    if membership_trail.endswith(".jsonl")
                    else membership_trail + _export.MEMBERSHIP_SUFFIX)
            trail = _export.MembershipTrail(
                path, size=n,
                capacity=[r for r, s in directory.states.items()
                          if s == _mem.STATE_INACTIVE])

        def note_transitions(trs, t):
            for (ts, r, s) in trs:
                msg = f"rank {r} membership -> {s} at step {ts}"
                events.append(msg)
                _tl.record_resilience_event("membership", msg)
                if trail is not None:
                    trail.write_event(ts, r, s)
            if trail is not None:
                trail.write_state(t, directory.states, directory.counts())

        losses, cons, votes_t, mats = [], [], [], []
        announced = set()
        for t in range(steps):
            trs = []
            for ev in elastic_events:
                if (ev.kind == "rank_join" and ev.step == t
                        and ev.step < self.plan.horizon):
                    # a join at the horizon is a RESERVED capacity slot
                    # (never admitted) — the tables clamp to the last
                    # row, where the rank is still dead
                    tr = directory.announce(ev.rank, t)
                    if tr:
                        trs.append(tr)
                if ev.kind == "rank_leave" and ev.step == t:
                    tr = directory.leave(ev.rank, t)
                    if tr:
                        trs.append(tr)
                if (ev.kind == "rank_join"
                        and t == ev.step + int(ev.factor)):
                    # the plan's sync window elapsed: the traced tables
                    # activate the joiner this step — report bootstrap
                    # completion so the observer can confirm admission
                    directory.mark_synced(ev.rank)
            (params, opt_state, state, loss, ce, W_eff,
             votes, carried) = self._step_fn(params, opt_state, state,
                                             batch_of(t), t, tables,
                                             carried)
            losses.append(float(loss))
            cons.append(float(ce))
            votes_np = np.asarray(votes)
            votes_t.append(votes_np)
            mats.append(np.asarray(W_eff))
            if elastic_events or directory.transitions:
                trs += directory.observe(np.asarray(state), t)
            if trs or trail is not None:
                note_transitions(trs, t)
            n_alive = int(self.plan.alive[min(t, self.plan.horizon - 1)]
                          .sum())
            if _metrics.enabled():
                # fleet-size gauge for the health engine / bfmonitor
                # degraded-rank summary (docs/observability.md)
                _metrics.gauge(
                    "bf_resilience_alive_ranks",
                    "ranks alive per the compiled fault plan at the "
                    "current chaos step").set(float(n_alive))
            for r in np.nonzero(votes_np > n_alive // 2)[0]:
                if r not in announced:
                    announced.add(int(r))
                    msg = f"rank {r} confirmed dead at step {t}; " \
                          f"mixing matrix repaired"
                    events.append(msg)
                    if _metrics.enabled():
                        _metrics.counter(
                            "bf_resilience_confirms_total",
                            "majority-confirmed deaths (each implies a "
                            "matrix repair)").inc()
                    _tl.record_resilience_event("repair", msg)
        _tl.record_resilience_event("chaos_run_end",
                                    f"final consensus error {cons[-1]:.3g}")
        if trail is not None:
            trail.close()
        clamp = lambda t: min(t, self.plan.horizon - 1)
        return ChaosReport(
            losses=np.asarray(losses),
            consensus_errors=np.asarray(cons),
            dead_votes=np.stack(votes_t),
            mixing_matrices=np.stack(mats),
            alive_steps=np.stack(
                [self.plan.alive[clamp(t)] for t in range(steps)]),
            sync_steps=np.stack(
                [self.plan.sync[clamp(t)] for t in range(steps)]),
            params_final=params,
            events=events,
            membership_transitions=list(directory.transitions),
        )
