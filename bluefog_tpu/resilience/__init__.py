"""Resilience subsystem: fault injection, liveness, matrix repair, chaos.

The reference framework assumes a fixed healthy MPI world — one dead or slow
rank stalls the job.  Here rank loss is a *matrix repair* problem: mixing
matrices are traced data, so a repaired topology is just different numbers
flowing through the same compiled program.  Four layers:

* :mod:`~bluefog_tpu.resilience.faults` — deterministic, seeded fault plans
  compiled to fixed-shape per-step tables (rank death, stragglers, flaky
  links, value corruption); injectable into any step with zero recompiles.
* :mod:`~bluefog_tpu.resilience.membership` — per-rank liveness beliefs as
  device-resident state, maintained by heartbeat gossip over the topology's
  own edges, with suspect/confirm staleness thresholds — plus the
  elastic-membership protocol (``ElasticMembership``: announced →
  syncing → active join state machine, window-subsystem parameter
  bootstrap via ``bootstrap_join``) that lets ranks ARRIVE at runtime
  with zero recompiles (capacity ranks pre-allocated in the fault
  tables).
* :mod:`~bluefog_tpu.resilience.repair` — mixing-matrix surgery: masking +
  diagonal absorption (column-stochastic families), Hastings re-weighting
  (doubly-stochastic families), disconnection fallback rings, and
  liveness-masked dynamic one-peer schedules.
* :mod:`~bluefog_tpu.resilience.harness` — a chaos harness that runs a
  consensus training loop under a fault plan and reports loss/consensus
  trajectories plus the per-step effective (repaired) mixing matrices.

See ``docs/resilience.md`` and ``examples/chaos_training.py``.
"""

from .faults import (FaultEvent, FaultPlan, CompiledFaultPlan, empty_plan,
                     random_plan, scale_up_plan, scale_down_plan,
                     churn_plan, resolve_sync_steps)
from .membership import (LivenessConfig, init_state, gossip_step,
                         gossip_last_heard, belief_alive, belief_suspect,
                         confirmed_dead_votes, ElasticMembership,
                         bootstrap_join)
from .repair import (repair_matrix, repair_matrix_traced, repair_topology,
                     hastings_matrix, fallback_ring_matrix, spectral_gap,
                     liveness_masked_matrices, liveness_masked_schedule,
                     survivors_connected)
from .harness import ChaosHarness, ChaosReport

__all__ = [
    "FaultEvent", "FaultPlan", "CompiledFaultPlan", "empty_plan",
    "random_plan", "scale_up_plan", "scale_down_plan", "churn_plan",
    "resolve_sync_steps",
    "LivenessConfig", "init_state", "gossip_step", "gossip_last_heard",
    "belief_alive", "belief_suspect", "confirmed_dead_votes",
    "ElasticMembership", "bootstrap_join",
    "repair_matrix", "repair_matrix_traced", "repair_topology",
    "hastings_matrix", "fallback_ring_matrix", "spectral_gap",
    "liveness_masked_matrices", "liveness_masked_schedule",
    "survivors_connected",
    "ChaosHarness", "ChaosReport",
]
