"""Mixing-matrix surgery: repair topologies around dead ranks.

Decentralized averaging converges at a rate governed by the mixing matrix's
spectral gap (exponential-graph analysis, arXiv:2110.13363) — so surviving
rank loss is a *matrix repair* problem: zero the dead rows/columns, give the
lost mass somewhere principled to keep the stochasticity invariant of the
topology family, and keep the survivor subgraph connected so the gap stays
positive.  Because topologies here are virtual graphs over a physical mesh,
repair may also *rewire*: when deaths disconnect the survivors (e.g. a star
losing its center), any replacement edge set is physically available, and
the fallback ring restores connectivity.

Two implementations:

* **Host (numpy)** — :func:`repair_matrix` / :func:`repair_topology`, full
  policy surface (column vs doubly-stochastic families, Hastings
  re-weighting, disconnection fallback).  Use when membership *confirms* a
  death and the run re-plans its compiled topology.
* **Traced (jnp)** — :func:`repair_matrix_traced`, the jit-safe subset
  (masking + diagonal absorption).  Use inside a step program with liveness
  beliefs as data: per-step repair with zero recompilation.

Column convention throughout (``parallel/topology.py``): ``W[i, j]`` is the
weight receiver j applies to i's value; columns sum to 1.
"""

from typing import Optional

import numpy as np
import networkx as nx

from ..parallel.schedule import (CompiledTopology, DynamicSchedule,
                                 compile_dynamic_matrices,
                                 compile_weight_matrix)

__all__ = ["repair_matrix", "repair_matrix_traced", "repair_topology",
           "hastings_matrix", "fallback_ring_matrix", "spectral_gap",
           "liveness_masked_matrices", "liveness_masked_schedule",
           "survivors_connected"]


def _alive_bool(alive, n: int) -> np.ndarray:
    a = np.asarray(alive).astype(bool).reshape(-1)
    if a.shape != (n,):
        raise ValueError(f"alive mask must be [{n}], got {a.shape}")
    return a


# ---------------------------------------------------------------------------
# Traced path (jit-safe; masking + diagonal absorption)
# ---------------------------------------------------------------------------

def repair_matrix_traced(W0, belief=None, alive=None, link_ok=None):
    """Column-stochastic repair with everything as traced data.

    ``W0`` [N, N] is the healthy mixing matrix.  Optional masks (all
    multiplicative on the off-diagonal):

    * ``belief`` [N, N] — ``membership.belief_alive``: entry (i, j) keeps
      i's weight in j's column only while j believes i alive (each column
      repairs from its OWN belief — no global agreement required).
    * ``alive`` [N] — ground-truth/plan mask; drops every edge touching a
      dead rank on both sides (rows *and* columns), so the reported matrix
      carries zero weight to and from the dead.
    * ``link_ok`` [N, N] — per-step link drops.

    The mass removed from a column is absorbed into its diagonal, keeping
    every column summing to exactly 1 (a fully-masked column degrades to
    identity: the rank keeps its value — bounded-staleness behavior, not
    stale-garbage averaging).
    """
    import jax.numpy as jnp
    W0 = jnp.asarray(W0)
    n = W0.shape[0]
    eye = jnp.eye(n, dtype=W0.dtype)
    mask = jnp.ones_like(W0)
    if belief is not None:
        mask = mask * jnp.asarray(belief, W0.dtype)
    if link_ok is not None:
        mask = mask * jnp.asarray(link_ok, W0.dtype)
    if alive is not None:
        a = jnp.asarray(alive, W0.dtype)
        mask = mask * (a[:, None] * a[None, :])
    off = W0 * mask * (1 - eye)
    return off + jnp.diag(1.0 - off.sum(axis=0))


# ---------------------------------------------------------------------------
# Host path (full policy)
# ---------------------------------------------------------------------------

def survivors_connected(W: np.ndarray, alive) -> bool:
    """True when the surviving off-diagonal edge set is strongly connected
    (single survivor counts as connected)."""
    W = np.asarray(W)
    alive = _alive_bool(alive, W.shape[0])
    idx = np.nonzero(alive)[0]
    if len(idx) <= 1:
        return True
    sub = (W[np.ix_(idx, idx)] != 0)
    np.fill_diagonal(sub, False)
    return nx.is_strongly_connected(
        nx.from_numpy_array(sub, create_using=nx.DiGraph))


def hastings_matrix(adj: np.ndarray) -> np.ndarray:
    """Metropolis–Hastings weights for a symmetric adjacency: ``W[i, j] =
    1 / max(deg_i, deg_j)`` on edges (degrees counted including self, the
    ``MeshGrid2DGraph`` convention), diagonal absorbs the remainder.
    Symmetric input gives a symmetric doubly-stochastic output — the
    re-weighting rule for irregular survivor graphs."""
    A = np.asarray(adj).astype(bool).copy()
    if not np.array_equal(A, A.T):
        raise ValueError("Hastings re-weighting needs a symmetric adjacency")
    np.fill_diagonal(A, False)
    n = A.shape[0]
    deg = A.sum(axis=1) + 1
    W = np.zeros((n, n))
    pair = np.maximum(deg[:, None], deg[None, :])
    W[A] = 1.0 / pair[A]
    np.fill_diagonal(W, 1.0 - W.sum(axis=1))
    return W


def fallback_ring_matrix(size: int, alive) -> np.ndarray:
    """Bidirectional ring over the survivors (in rank order), identity for
    the dead — the last-resort rewiring when deaths disconnect the virtual
    topology (every edge is physically available on the mesh)."""
    alive = _alive_bool(alive, size)
    idx = np.nonzero(alive)[0]
    W = np.eye(size)
    k = len(idx)
    if k <= 1:
        return W
    if k == 2:
        i, j = idx
        W[np.ix_(idx, idx)] = 0.5
        return W
    for pos, j in enumerate(idx):
        left, right = idx[(pos - 1) % k], idx[(pos + 1) % k]
        W[j, j] = 1.0 / 3.0
        W[left, j] = 1.0 / 3.0
        W[right, j] = 1.0 / 3.0
    return W


def repair_matrix(W: np.ndarray, alive, family: str = "auto") -> np.ndarray:
    """Repair a mixing matrix around dead ranks (host path).

    Families:

    * ``"column"`` — zero dead rows/columns, absorb each column's lost mass
      into its diagonal.  Preserves column-stochasticity for any topology.
    * ``"doubly"`` — Hastings re-weighting over the surviving symmetric
      adjacency: preserves *double* stochasticity (symmetric families:
      MeshGrid2D, symmetric rings) even when survivors end up with
      irregular degrees.
    * ``"auto"`` — ``"doubly"`` when W is symmetric, else ``"column"``.

    Whatever the family, if the deaths disconnect the survivors the repair
    falls back to a ring over them (see :func:`fallback_ring_matrix`) —
    a disconnected mixing matrix has spectral gap zero and consensus never
    contracts.  Dead ranks keep identity columns; every returned matrix is
    column-stochastic with zero weight to and from the dead.

    The same surgery runs in the *grow* direction (elastic membership,
    docs/resilience.md): repair always starts from the healthy ``W``
    over the FULL capacity, so admitting a rank is just calling this
    with the larger ``alive`` mask — its pre-allocated edges re-enter,
    the diagonal mass they displaced flows back, and a fallback-ring
    repair regrows to the original family.  Exercised both ways in
    ``tests/test_elastic.py``.
    """
    W = np.asarray(W, np.float64)
    n = W.shape[0]
    alive = _alive_bool(alive, n)
    if alive.all():
        return W.copy()
    if not survivors_connected(W, alive):
        return fallback_ring_matrix(n, alive)
    if family == "auto":
        family = "doubly" if np.allclose(W, W.T, atol=1e-12) else "column"
    if family == "doubly":
        A = (W != 0) & (W.T != 0)         # surviving undirected edges
        A &= alive[:, None] & alive[None, :]
        if not survivors_connected(A.astype(float), alive):
            return fallback_ring_matrix(n, alive)
        R = hastings_matrix(A | np.eye(n, dtype=bool))
        # dead ranks: identity column/row (Hastings gave them diag 1 already
        # since they have no surviving edges)
        return R
    if family != "column":
        raise ValueError(f"unknown repair family {family!r}")
    mask = (alive[:, None] & alive[None, :]).astype(np.float64)
    off = W * mask
    np.fill_diagonal(off, 0.0)
    out = off + np.diag(1.0 - off.sum(axis=0))
    return out


def repair_topology(topo: CompiledTopology, alive,
                    family: str = "auto") -> CompiledTopology:
    """Compile the repaired matrix of a topology — the host-side re-plan
    once membership *confirms* a death (one recompilation per membership
    change; per-step suspicion uses the traced path instead)."""
    return compile_weight_matrix(repair_matrix(topo.weight_matrix, alive,
                                               family))


def spectral_gap(W: np.ndarray, alive=None) -> float:
    """``1 - |lambda_2|`` of the survivor submatrix (1.0 for a single
    survivor).  Positive gap <=> consensus contracts among survivors."""
    W = np.asarray(W, np.float64)
    if alive is not None:
        idx = np.nonzero(_alive_bool(alive, W.shape[0]))[0]
        W = W[np.ix_(idx, idx)]
    if W.shape[0] <= 1:
        return 1.0
    lam = np.sort(np.abs(np.linalg.eigvals(W)))[::-1]
    return float(1.0 - lam[1])


# ---------------------------------------------------------------------------
# Liveness-aware dynamic schedules
# ---------------------------------------------------------------------------

def liveness_masked_matrices(mats: np.ndarray, alive) -> np.ndarray:
    """Apply column repair to every step of a ``[T, N, N]`` matrix stack:
    dead ranks drop out of each step's exchange, each column's lost mass
    goes to its diagonal.  A step whose only in-peer died degrades to a
    local step for that rank — bounded staleness, never garbage."""
    mats = np.asarray(mats, np.float64)
    alive = _alive_bool(alive, mats.shape[1])
    mask = (alive[:, None] & alive[None, :]).astype(np.float64)
    out = mats * mask[None]
    for t in range(out.shape[0]):
        np.fill_diagonal(out[t], 0.0)
        out[t] += np.diag(1.0 - out[t].sum(axis=0))
    return out


def liveness_masked_schedule(sched: DynamicSchedule,
                             alive) -> DynamicSchedule:
    """Liveness-aware variant of a compiled dynamic one-peer schedule: the
    repaired schedule keeps the period and an offset subset, so it drops
    into every ``sched=`` consumer (``neighbor_allreduce``, window ops,
    ``make_train_step``)."""
    return compile_dynamic_matrices(
        liveness_masked_matrices(sched.matrices, alive))
