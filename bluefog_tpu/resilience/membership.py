"""Per-rank liveness/health masks as device-resident state — and the
elastic-membership protocol built on top of them.

There is no failure detector oracle in a decentralized system: each rank can
only *infer* peer health from what arrives over its in-edges.  The state here
is a global-view ``last_heard[N, N]`` table (row j = rank j's most recent
heartbeat step observed for every peer), maintained gossip-style with the
same circulant ``ppermute`` exchanges the neighbor collectives use: every
step each active rank stamps its own entry with the current step and
max-merges the tables arriving from its in-neighbors, so heartbeat knowledge
spreads along graph edges at one hop per step (SWIM-style dissemination,
bulk-synchronous flavor).

Two configurable thresholds grade staleness (suspect/confirm, the classic
accrual-detector split):

* ``suspect_after``  — peers this stale are *suspected*: keep their last
  value out of fresh averages (skip-comm / degraded branch,
  ``optim.strategies.with_degraded_guard``) but don't rewire yet.
* ``confirm_after``  — peers this stale are *confirmed dead*: mixing-matrix
  surgery (``resilience.repair``) removes them and renormalizes.

Everything is traced data — the tables ride inside jitted programs, so
liveness transitions never recompile.

**Elastic membership** (docs/resilience.md "Elastic membership"): ranks
also *arrive* at runtime.  :class:`ElasticMembership` is the host-side
join/leave state machine — per rank ``inactive`` (a pre-allocated
capacity slot) → ``announced`` (the rank declared itself and started
heartbeating) → ``syncing`` (a quorum of active ranks heard it; it
bootstraps parameters over the window subsystem, :func:`bootstrap_join`)
→ ``active`` (it contributes mixing weight) → ``left``.  The machine is
an *observer* driven by the same ``last_heard`` gossip: admission itself
is traced data (capacity ranks pre-allocated in the fault tables, the
repaired mixing matrix flowing as numbers), so growth never recompiles.
"""

import functools
import os
from typing import Dict, Iterable, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..observability import metrics as _metrics
from ..parallel.schedule import CompiledTopology
from .faults import SYNC_STEPS_ENV, resolve_sync_steps

__all__ = ["LivenessConfig", "init_state", "gossip_last_heard",
           "gossip_step", "belief_alive", "belief_suspect",
           "confirmed_dead_votes",
           "ElasticMembership", "bootstrap_join",
           "STATE_INACTIVE", "STATE_ANNOUNCED", "STATE_SYNCING",
           "STATE_ACTIVE", "STATE_LEFT",
           "resolve_sync_steps", "resolve_bootstrap_folds",
           "resolve_bootstrap_tol", "SYNC_STEPS_ENV",
           "BOOTSTRAP_FOLDS_ENV", "BOOTSTRAP_TOL_ENV"]

BOOTSTRAP_FOLDS_ENV = "BLUEFOG_ELASTIC_BOOTSTRAP_FOLDS"
BOOTSTRAP_TOL_ENV = "BLUEFOG_ELASTIC_BOOTSTRAP_TOL"


def resolve_bootstrap_folds(value: Optional[int] = None) -> int:
    """``BLUEFOG_ELASTIC_BOOTSTRAP_FOLDS`` (default 2): cap on the
    ``win_get`` + catch-up-fold rounds a joiner runs while syncing."""
    folds = int(os.environ.get(BOOTSTRAP_FOLDS_ENV, "2")
                if value is None else value)
    if folds < 1:
        raise ValueError(f"bootstrap folds must be >= 1, got {folds}")
    return folds


def resolve_bootstrap_tol(value: Optional[float] = None) -> float:
    """``BLUEFOG_ELASTIC_BOOTSTRAP_TOL`` (default 1e-6): relative
    movement of the joiner's row below which :func:`bootstrap_join`
    stops folding early (the row converged to the neighbor average)."""
    tol = float(os.environ.get(BOOTSTRAP_TOL_ENV, "1e-6")
                if value is None else value)
    if tol < 0:
        raise ValueError(f"bootstrap tol must be >= 0, got {tol}")
    return tol


class LivenessConfig:
    """Staleness thresholds, in steps."""

    def __init__(self, suspect_after: int = 2, confirm_after: int = 4):
        if not 0 < suspect_after <= confirm_after:
            raise ValueError(
                f"need 0 < suspect_after <= confirm_after, got "
                f"{suspect_after}, {confirm_after}")
        self.suspect_after = suspect_after
        self.confirm_after = confirm_after


def init_state(size: int) -> Dict[str, jnp.ndarray]:
    """Fresh liveness state: everyone heard from everyone at step 0."""
    return {"last_heard": jnp.zeros((size, size), jnp.int32)}


# ---------------------------------------------------------------------------
# Axis-level gossip (call inside shard_map, like ops.collectives)
# ---------------------------------------------------------------------------

def gossip_last_heard(row, axis_name, topo: CompiledTopology, step,
                      active, link_ok):
    """One gossip round for this rank's ``last_heard`` row ([N] int32).

    ``active`` ([N], traced) marks ranks participating this step;
    ``link_ok`` ([N, N], traced) marks edges delivering this step.  Dead or
    inactive senders and dropped links contribute nothing — their entries
    simply stop advancing, which is exactly how the staleness thresholds
    see them."""
    from ..ops.collectives import _rotation_pairs
    size = topo.size
    idx = lax.axis_index(axis_name)
    step = jnp.asarray(step, jnp.int32)
    # own heartbeat: stamp only while participating (a straggler's entry
    # advances on its active steps, a dead rank's never does)
    row = row.at[idx].set(
        jnp.where(active[idx] > 0, jnp.maximum(row[idx], step), row[idx]))
    ar = jnp.arange(size)
    for shift in topo.shifts:
        received = lax.ppermute(row, axis_name,
                                _rotation_pairs(size, shift.offset))
        src = (idx - shift.offset) % size
        # static edge mask: ppermute rotates ALL ranks; only real edges of
        # this offset may merge (non-destinations receive zeros)
        has_edge = jnp.asarray(shift.recv_weights != 0)[idx]
        valid = has_edge & (active[src] > 0) & (link_ok[src, idx] > 0)
        row = jnp.where(valid, jnp.maximum(row, received), row)
    return row


# ---------------------------------------------------------------------------
# Global-view convenience wrapper (one jitted SPMD program per topology)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _gossip_fn(axis, topo: CompiledTopology, mesh_id):
    from ..context import ctx
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(last_heard, step, active, link_ok):
        def shard_fn(rows, step_s, active_s, link_s):
            return gossip_last_heard(rows[0], axis, topo, step_s,
                                     active_s, link_s)[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh, in_specs=(spec, P(), P(), P()),
            out_specs=spec,
        )(last_heard, step, active, link_ok)
    return jax.jit(wrapper)


def gossip_step(state: Dict[str, jnp.ndarray], step,
                active=None, link_ok=None,
                topo: Optional[CompiledTopology] = None
                ) -> Dict[str, jnp.ndarray]:
    """Run one gossip round over the context topology (or ``topo``).

    ``step``/``active``/``link_ok`` are data — calling this every step with
    changing faults reuses one compiled program."""
    from ..context import ctx
    from ..ops import api as _api
    cx = ctx()
    topo = topo or cx.compiled_topology
    n = topo.size
    if active is None:
        active = jnp.ones((n,), jnp.float32)
    if link_ok is None:
        link_ok = jnp.ones((n, n), jnp.float32)
    fn = _gossip_fn(cx.rank_axis, topo, id(cx.mesh))
    last = jax.device_put(jnp.asarray(state["last_heard"], jnp.int32),
                          _api.rank_sharding())
    new = fn(last, jnp.asarray(step, jnp.int32),
             jnp.asarray(active, jnp.float32),
             jnp.asarray(link_ok, jnp.float32))
    return {"last_heard": new}


# ---------------------------------------------------------------------------
# Belief masks (traced; usable on host or inside jit)
# ---------------------------------------------------------------------------

def _staleness(last_heard, step):
    return jnp.asarray(step, jnp.int32) - jnp.asarray(last_heard, jnp.int32)

def belief_alive(last_heard, step, cfg: LivenessConfig):
    """``B[i, j] = 1`` iff rank j believes rank i is alive (not yet
    *confirmed* dead).  Column j is j's receive mask — feed it to
    ``repair.repair_matrix_traced``."""
    return (_staleness(last_heard, step).T
            <= cfg.confirm_after).astype(jnp.float32)


def belief_suspect(last_heard, step, cfg: LivenessConfig):
    """``S[i, j] = 1`` iff rank j *suspects* rank i (stale beyond
    ``suspect_after`` but not yet confirmed dead)."""
    st = _staleness(last_heard, step).T
    return ((st > cfg.suspect_after)
            & (st <= cfg.confirm_after)).astype(jnp.float32)


def confirmed_dead_votes(last_heard, step, cfg: LivenessConfig):
    """Per-rank vote count: how many ranks have confirmed each peer dead.

    ``votes[i] > alive_majority`` is the aggregation a coordinator (or the
    chaos harness's report) uses to declare a single global death — the
    mixing itself never needs this, each column repairs from its own
    belief."""
    st = _staleness(last_heard, step)
    dead_view = (st > cfg.confirm_after)          # [viewer, peer]
    return dead_view.sum(axis=0).astype(jnp.int32)


# ---------------------------------------------------------------------------
# Elastic membership: the join/leave state machine (host-side observer)
# ---------------------------------------------------------------------------

STATE_INACTIVE = "inactive"     # pre-allocated capacity slot, not joined
STATE_ANNOUNCED = "announced"   # declared itself; heartbeats started
STATE_SYNCING = "syncing"       # heard by a quorum; bootstrapping params
STATE_ACTIVE = "active"         # contributes mixing weight
STATE_LEFT = "left"             # departed (orderly) or confirmed dead

_ALIVE_STATES = (STATE_ANNOUNCED, STATE_SYNCING, STATE_ACTIVE)


class ElasticMembership:
    """Host-side elastic-membership directory: per-rank join/leave state
    machine driven by the liveness gossip.

    The machine OBSERVES — the traced data (fault tables, repaired
    mixing matrices, liveness masks) executes admission; this directory
    turns the same ``last_heard`` table into auditable state
    transitions, the masks host-side consumers feed to
    :func:`~bluefog_tpu.resilience.repair.repair_matrix` /
    ``win_update(alive=)`` / ``bf.weights_override``, and the
    ``bf_membership_*`` gauges + membership JSONL trail ``bfmonitor
    --membership`` renders.

    Transitions:

    * ``announce(rank, step)`` — inactive/left → announced (the rank's
      own declaration; in a chaos run, the plan's ``rank_join`` onset).
    * announced → syncing: :meth:`observe` sees a quorum of active
      ranks heard the joiner within ``suspect_after`` steps (heartbeat
      dissemination reached the fleet).
    * syncing → active: the caller reported bootstrap completion
      (:meth:`mark_synced` — e.g. :func:`bootstrap_join` converged)
      and the quorum still holds.
    * any alive state → left: ``leave(rank, step)`` (orderly), or
      :meth:`observe` counts a quorum of confirmed-dead votes
      (staleness beyond ``confirm_after`` — failure-as-departure).
    """

    def __init__(self, size: int, *, capacity: Iterable[int] = (),
                 cfg: Optional[LivenessConfig] = None,
                 quorum: Optional[int] = None):
        self.size = int(size)
        self.cfg = cfg or LivenessConfig()
        cap = set(int(r) for r in capacity)
        for r in cap:
            if not 0 <= r < self.size:
                raise ValueError(f"capacity rank {r} outside "
                                 f"[0, {self.size})")
        self.states: Dict[int, str] = {
            r: (STATE_INACTIVE if r in cap else STATE_ACTIVE)
            for r in range(self.size)}
        self.quorum = quorum              # None = majority of active ranks
        self._synced: set = set()
        self._announced_at: Dict[int, int] = {}
        # (step, rank, new_state) — the audit log the chaos report and
        # the membership JSONL trail bank
        self.transitions: List[Tuple[int, int, str]] = []

    # -- bookkeeping --------------------------------------------------------

    def _quorum(self) -> int:
        n_active = sum(1 for s in self.states.values()
                       if s == STATE_ACTIVE)
        return self.quorum if self.quorum else n_active // 2 + 1

    def _set(self, rank: int, state: str, step: int) -> Tuple[int, int, str]:
        self.states[rank] = state
        tr = (int(step), int(rank), state)
        self.transitions.append(tr)
        if _metrics.enabled():
            _metrics.counter(
                "bf_membership_transitions_total",
                "elastic-membership state transitions, by target state"
            ).inc(state=state)
        self._export_gauges()
        return tr

    def _export_gauges(self) -> None:
        if not _metrics.enabled():
            return
        counts = self.counts()
        _metrics.gauge(
            "bf_membership_active_ranks",
            "ranks in the elastic-membership active state").set(
            float(counts[STATE_ACTIVE]))
        _metrics.gauge(
            "bf_membership_syncing_ranks",
            "joiners currently in their parameter-bootstrap window").set(
            float(counts[STATE_SYNCING]))

    # -- explicit transitions -----------------------------------------------

    def announce(self, rank: int, step: int) -> Optional[Tuple]:
        """A capacity (or departed) rank declares itself; its heartbeats
        start flowing.  No-op for ranks already alive."""
        if self.states[rank] in (STATE_INACTIVE, STATE_LEFT):
            self._synced.discard(rank)
            self._announced_at[rank] = int(step)
            return self._set(rank, STATE_ANNOUNCED, step)
        return None

    def leave(self, rank: int, step: int) -> Optional[Tuple]:
        """Orderly departure (elastic scale-down)."""
        if self.states[rank] in _ALIVE_STATES:
            self._synced.discard(rank)
            return self._set(rank, STATE_LEFT, step)
        return None

    def mark_synced(self, rank: int) -> None:
        """Report parameter-bootstrap completion for a syncing/announced
        joiner (e.g. :func:`bootstrap_join` converged, or the fault
        plan's sync window elapsed) — activation still waits for the
        gossip quorum in :meth:`observe`."""
        self._synced.add(rank)

    def admit_restored(self, rank: int, step: int
                       ) -> List[Tuple[int, int, str]]:
        """Offline admission: narrate the full announced → syncing →
        active transition for a rank whose parameter bootstrap happened
        from CHECKPOINTED shards rather than the live window gossip
        (``checkpoint/restore.py``'s elastic grow path).  The quorum
        machine is deliberately not consulted — during a restore there
        is no fleet to gossip with; the trusted in-neighbors are the
        checkpoint itself.  Returns the transitions recorded."""
        out = []
        tr = self.announce(rank, step)
        if tr is not None:
            out.append(tr)
        self.mark_synced(rank)
        if self.states[rank] == STATE_ANNOUNCED:
            out.append(self._set(rank, STATE_SYNCING, step))
        if self.states[rank] == STATE_SYNCING:
            out.append(self._set(rank, STATE_ACTIVE, step))
        return out

    # -- the gossip-driven drive --------------------------------------------

    def observe(self, last_heard, step: int) -> List[Tuple[int, int, str]]:
        """Advance the machine from one ``last_heard`` snapshot (the
        global-view [N, N] table; row j = viewer j).  Returns the
        transitions this observation caused."""
        lh = np.asarray(last_heard)
        if lh.shape != (self.size, self.size):
            raise ValueError(f"last_heard must be "
                             f"[{self.size}, {self.size}], got {lh.shape}")
        out: List[Tuple[int, int, str]] = []
        viewers = [v for v, s in self.states.items() if s == STATE_ACTIVE]
        q = self._quorum()
        stale = int(step) - lh                       # [viewer, peer]
        for r in range(self.size):
            state = self.states[r]
            if state not in _ALIVE_STATES:
                continue
            heard = sum(1 for v in viewers if v != r
                        and stale[v, r] <= self.cfg.suspect_after)
            dead_votes = sum(1 for v in viewers if v != r
                             and stale[v, r] > self.cfg.confirm_after)
            others = sum(1 for v in viewers if v != r)
            if state == STATE_ANNOUNCED and heard >= min(q, max(others, 1)):
                out.append(self._set(r, STATE_SYNCING, step))
                state = STATE_SYNCING
            if (state == STATE_SYNCING and r in self._synced
                    and heard >= min(q, max(others, 1))):
                out.append(self._set(r, STATE_ACTIVE, step))
                continue
            if (state == STATE_ACTIVE and others
                    and dead_votes >= min(q, others)):
                # failure-as-departure: the fleet confirmed it dead
                self._synced.discard(r)
                out.append(self._set(r, STATE_LEFT, step))
            elif state in (STATE_ANNOUNCED, STATE_SYNCING) and others:
                # a joiner that dies (or whose heartbeats never spread)
                # MID-admission must also depart, or it would report as
                # announced/syncing forever and its alive_mask bit would
                # keep a dead rank's buffer in every fold.  It departs
                # once silent for confirm_after steps measured from the
                # freshest heartbeat any active viewer holds — or from
                # its announcement, so a never-heard joiner gets the
                # same grace before the directory gives up on it.
                freshest = max(int(lh[v, r]) for v in viewers if v != r)
                basis = max(freshest, self._announced_at.get(r, 0))
                if int(step) - basis > self.cfg.confirm_after:
                    self._synced.discard(r)
                    out.append(self._set(r, STATE_LEFT, step))
        return out

    def observe_direct(self, last_heard_row, step: int
                       ) -> List[Tuple[int, int, str]]:
        """:meth:`observe` for a SINGLE authoritative observer: a ``[N]``
        row of per-rank last-heard steps is broadcast to every viewer
        seat, so quorum degenerates to that one view.  This is the fleet
        supervisor's drive (``fleet/supervisor.py``): it hears worker
        heartbeats directly over its socket, so the row it holds IS the
        fleet's liveness truth — there is no second process to gossip
        with about it."""
        row = np.asarray(last_heard_row)
        if row.shape != (self.size,):
            raise ValueError(
                f"last_heard_row must be [{self.size}], got {row.shape}")
        return self.observe(np.tile(row, (self.size, 1)), step)

    # -- masks and summaries ------------------------------------------------

    def state_of(self, rank: int) -> str:
        return self.states[rank]

    def alive_mask(self) -> np.ndarray:
        """[N] float32 — 1.0 for announced/syncing/active ranks (feed to
        ``win_update(alive=)`` / the serving router's ``observe``)."""
        return np.asarray([1.0 if self.states[r] in _ALIVE_STATES else 0.0
                           for r in range(self.size)], np.float32)

    def active_mask(self) -> np.ndarray:
        """[N] float32 — 1.0 only for fully-active ranks (feed to
        :func:`~bluefog_tpu.resilience.repair.repair_matrix`: the mixing
        matrix regenerates over exactly these)."""
        return np.asarray([1.0 if self.states[r] == STATE_ACTIVE else 0.0
                           for r in range(self.size)], np.float32)

    def degraded(self, rank: int) -> bool:
        """True while ``rank`` must run the skip-comm local branch
        (``optim.strategies.with_degraded_guard``): a joiner that is not
        yet active trains locally and exchanges nothing."""
        return self.states[rank] != STATE_ACTIVE

    def counts(self) -> Dict[str, int]:
        out = {s: 0 for s in (STATE_INACTIVE, STATE_ANNOUNCED,
                              STATE_SYNCING, STATE_ACTIVE, STATE_LEFT)}
        for s in self.states.values():
            out[s] += 1
        return out


# ---------------------------------------------------------------------------
# Parameter bootstrap over the window subsystem
# ---------------------------------------------------------------------------

def bootstrap_join(window_name: str, rank: int, *, alive=None,
                   folds: Optional[int] = None,
                   tol: Optional[float] = None,
                   self_weight: float = 0.0):
    """Parameter bootstrap for a joiner: converge ``rank``'s window row
    to its live in-neighbors' average before it contributes mixing
    weight.

    Each round is one ``win_get`` snapshot of the in-neighbor tensors
    plus one bounded-staleness catch-up fold restricted to the joiner's
    row (``ops.windows.win_bootstrap_rank`` — a ``win_update`` whose
    weight matrices are traced data, so every joiner and every fold
    reuses the window's one compiled program).  Stops after ``folds``
    rounds (``BLUEFOG_ELASTIC_BOOTSTRAP_FOLDS``) or as soon as the
    joiner's row moves less than ``tol`` relatively
    (``BLUEFOG_ELASTIC_BOOTSTRAP_TOL``).

    ``alive`` (optional [N] mask) drops dead feeds from the average —
    the same bounded-staleness degradation as every other fold.
    Returns ``(tree, folds_used)`` with the window's post-bootstrap
    global-view tensor."""
    from ..ops import windows as _win
    folds = resolve_bootstrap_folds(folds)     # always >= 1: the loop runs
    tol = resolve_bootstrap_tol(tol)
    prev = None
    out = None
    used = 0
    for used in range(1, folds + 1):
        out = _win.win_bootstrap_rank(window_name, rank, alive=alive,
                                      self_weight=self_weight)
        row = np.concatenate([
            np.asarray(leaf[rank], np.float64).ravel()
            for leaf in jax.tree.leaves(out)])
        if prev is not None:
            denom = max(float(np.linalg.norm(prev)), 1e-12)
            if float(np.linalg.norm(row - prev)) <= tol * denom:
                break
        prev = row
    return out, used
