"""Per-rank liveness/health masks as device-resident state.

There is no failure detector oracle in a decentralized system: each rank can
only *infer* peer health from what arrives over its in-edges.  The state here
is a global-view ``last_heard[N, N]`` table (row j = rank j's most recent
heartbeat step observed for every peer), maintained gossip-style with the
same circulant ``ppermute`` exchanges the neighbor collectives use: every
step each active rank stamps its own entry with the current step and
max-merges the tables arriving from its in-neighbors, so heartbeat knowledge
spreads along graph edges at one hop per step (SWIM-style dissemination,
bulk-synchronous flavor).

Two configurable thresholds grade staleness (suspect/confirm, the classic
accrual-detector split):

* ``suspect_after``  — peers this stale are *suspected*: keep their last
  value out of fresh averages (skip-comm / degraded branch,
  ``optim.strategies.with_degraded_guard``) but don't rewire yet.
* ``confirm_after``  — peers this stale are *confirmed dead*: mixing-matrix
  surgery (``resilience.repair``) removes them and renormalizes.

Everything is traced data — the tables ride inside jitted programs, so
liveness transitions never recompile.
"""

import functools
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.schedule import CompiledTopology

__all__ = ["LivenessConfig", "init_state", "gossip_last_heard",
           "gossip_step", "belief_alive", "belief_suspect",
           "confirmed_dead_votes"]


class LivenessConfig:
    """Staleness thresholds, in steps."""

    def __init__(self, suspect_after: int = 2, confirm_after: int = 4):
        if not 0 < suspect_after <= confirm_after:
            raise ValueError(
                f"need 0 < suspect_after <= confirm_after, got "
                f"{suspect_after}, {confirm_after}")
        self.suspect_after = suspect_after
        self.confirm_after = confirm_after


def init_state(size: int) -> Dict[str, jnp.ndarray]:
    """Fresh liveness state: everyone heard from everyone at step 0."""
    return {"last_heard": jnp.zeros((size, size), jnp.int32)}


# ---------------------------------------------------------------------------
# Axis-level gossip (call inside shard_map, like ops.collectives)
# ---------------------------------------------------------------------------

def gossip_last_heard(row, axis_name, topo: CompiledTopology, step,
                      active, link_ok):
    """One gossip round for this rank's ``last_heard`` row ([N] int32).

    ``active`` ([N], traced) marks ranks participating this step;
    ``link_ok`` ([N, N], traced) marks edges delivering this step.  Dead or
    inactive senders and dropped links contribute nothing — their entries
    simply stop advancing, which is exactly how the staleness thresholds
    see them."""
    from ..ops.collectives import _rotation_pairs
    size = topo.size
    idx = lax.axis_index(axis_name)
    step = jnp.asarray(step, jnp.int32)
    # own heartbeat: stamp only while participating (a straggler's entry
    # advances on its active steps, a dead rank's never does)
    row = row.at[idx].set(
        jnp.where(active[idx] > 0, jnp.maximum(row[idx], step), row[idx]))
    ar = jnp.arange(size)
    for shift in topo.shifts:
        received = lax.ppermute(row, axis_name,
                                _rotation_pairs(size, shift.offset))
        src = (idx - shift.offset) % size
        # static edge mask: ppermute rotates ALL ranks; only real edges of
        # this offset may merge (non-destinations receive zeros)
        has_edge = jnp.asarray(shift.recv_weights != 0)[idx]
        valid = has_edge & (active[src] > 0) & (link_ok[src, idx] > 0)
        row = jnp.where(valid, jnp.maximum(row, received), row)
    return row


# ---------------------------------------------------------------------------
# Global-view convenience wrapper (one jitted SPMD program per topology)
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=64)
def _gossip_fn(axis, topo: CompiledTopology, mesh_id):
    from ..context import ctx
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(last_heard, step, active, link_ok):
        def shard_fn(rows, step_s, active_s, link_s):
            return gossip_last_heard(rows[0], axis, topo, step_s,
                                     active_s, link_s)[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh, in_specs=(spec, P(), P(), P()),
            out_specs=spec,
        )(last_heard, step, active, link_ok)
    return jax.jit(wrapper)


def gossip_step(state: Dict[str, jnp.ndarray], step,
                active=None, link_ok=None,
                topo: Optional[CompiledTopology] = None
                ) -> Dict[str, jnp.ndarray]:
    """Run one gossip round over the context topology (or ``topo``).

    ``step``/``active``/``link_ok`` are data — calling this every step with
    changing faults reuses one compiled program."""
    from ..context import ctx
    from ..ops import api as _api
    cx = ctx()
    topo = topo or cx.compiled_topology
    n = topo.size
    if active is None:
        active = jnp.ones((n,), jnp.float32)
    if link_ok is None:
        link_ok = jnp.ones((n, n), jnp.float32)
    fn = _gossip_fn(cx.rank_axis, topo, id(cx.mesh))
    last = jax.device_put(jnp.asarray(state["last_heard"], jnp.int32),
                          _api.rank_sharding())
    new = fn(last, jnp.asarray(step, jnp.int32),
             jnp.asarray(active, jnp.float32),
             jnp.asarray(link_ok, jnp.float32))
    return {"last_heard": new}


# ---------------------------------------------------------------------------
# Belief masks (traced; usable on host or inside jit)
# ---------------------------------------------------------------------------

def _staleness(last_heard, step):
    return jnp.asarray(step, jnp.int32) - jnp.asarray(last_heard, jnp.int32)

def belief_alive(last_heard, step, cfg: LivenessConfig):
    """``B[i, j] = 1`` iff rank j believes rank i is alive (not yet
    *confirmed* dead).  Column j is j's receive mask — feed it to
    ``repair.repair_matrix_traced``."""
    return (_staleness(last_heard, step).T
            <= cfg.confirm_after).astype(jnp.float32)


def belief_suspect(last_heard, step, cfg: LivenessConfig):
    """``S[i, j] = 1`` iff rank j *suspects* rank i (stale beyond
    ``suspect_after`` but not yet confirmed dead)."""
    st = _staleness(last_heard, step).T
    return ((st > cfg.suspect_after)
            & (st <= cfg.confirm_after)).astype(jnp.float32)


def confirmed_dead_votes(last_heard, step, cfg: LivenessConfig):
    """Per-rank vote count: how many ranks have confirmed each peer dead.

    ``votes[i] > alive_majority`` is the aggregation a coordinator (or the
    chaos harness's report) uses to declare a single global death — the
    mixing itself never needs this, each column repairs from its own
    belief."""
    st = _staleness(last_heard, step)
    dead_view = (st > cfg.confirm_after)          # [viewer, peer]
    return dead_view.sum(axis=0).astype(jnp.int32)
