"""Parameter/state distribution helpers (reference parity:
``bluefog/torch/utility.py``).

The reference walks torch ``state_dict``s parameter-by-parameter and
broadcasts each through the C layer (utility.py:26-218, including the
scalar-by-scalar optimizer-state reconstruction).  With pytrees this
collapses to a tree_map over one collective.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops import api as _api

__all__ = [
    "broadcast_parameters",
    "allreduce_parameters",
    "broadcast_optimizer_state",
    "deprecated_function_arg",
    "check_extension",
]


def check_extension(ext_name: str = "bluefog_tpu.native", *args) -> None:
    """Verify the named native component is buildable/loadable.

    Reference parity: ``bluefog.common.util.check_extension`` raises
    ``ImportError`` when the compiled framework extension is absent
    (the reference checks for the built ``mpi_lib`` shared object).
    Here the compute path is pure JAX/XLA — nothing to check — but the
    native runtime (``csrc/`` service/timeline/logging via
    ``bluefog_tpu.native``) is a real shared object; this builds it on
    demand and raises ``ImportError`` if that fails.  Extra positional
    args (the reference's env-var/path hints) are accepted and ignored.
    """
    base = ext_name.rsplit(".", 1)[-1].lower()
    if base in ("jax", "xla", "tensorflow", "torch", "bluefog_tpu"):
        return   # pure-JAX compute paths: always available, nothing compiled
    if base in ("native", "mpi_lib", "mpi"):
        try:
            from .. import native
            native.build()
            return
        except Exception as e:
            raise ImportError(
                f"Extension {ext_name} has not been built "
                f"(native build failed: {e}). Run `python -m "
                f"bluefog_tpu.native` or check the g++ toolchain.") from e
    # unknown component: raise at check time, like the reference does for
    # an extension whose shared object cannot be found
    raise ImportError(f"Extension {ext_name} has not been built.")


def deprecated_function_arg(arg_name: str, fix: str):
    """Decorator rejecting a deprecated keyword argument with a pointer to
    the replacement (reference ``torch/utility.py:219-229``)."""
    from functools import wraps

    def deprecated_decorator(f):
        @wraps(f)
        def wrapper(*args, **kwargs):
            if arg_name in kwargs:
                raise TypeError(
                    f"{arg_name} is deprecated in {f.__name__}: {fix}")
            return f(*args, **kwargs)

        return wrapper

    return deprecated_decorator


def broadcast_parameters(params: Any, root_rank: int = 0):
    """Replicate ``root_rank``'s slice of every leaf to all ranks
    (reference utility.py:26 — run once before training so all ranks start
    from identical weights)."""
    return jax.tree.map(lambda p: _api.broadcast(p, root_rank), params)


def allreduce_parameters(params: Any):
    """Replace every leaf with its cross-rank average (utility.py:58 —
    used to force consensus, e.g. before evaluation)."""
    return jax.tree.map(lambda p: _api.allreduce(p, average=True), params)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0):
    """Broadcast optimizer state (utility.py:89-218).  The reference must
    reconstruct the torch state dict scalar-by-scalar; optax state is a
    pytree of [N, ...] arrays, so the same tree broadcast applies.  Non-array
    leaves (None, callables, empty states) pass through unchanged."""
    def bcast(leaf):
        if leaf is None:
            return None
        try:
            arr = jnp.asarray(leaf)
        except TypeError:
            return leaf  # callables/strings etc. pass through as documented
        if arr.ndim == 0 or arr.shape[0] != _api.ctx().size:
            return leaf  # replicated/static leaf — nothing to distribute
        return _api.broadcast(arr, root_rank)
    return jax.tree.map(bcast, opt_state)
