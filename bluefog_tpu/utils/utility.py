"""Parameter/state distribution helpers (reference parity:
``bluefog/torch/utility.py``).

The reference walks torch ``state_dict``s parameter-by-parameter and
broadcasts each through the C layer (utility.py:26-218, including the
scalar-by-scalar optimizer-state reconstruction).  With pytrees this
collapses to a tree_map over one collective.
"""

from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops import api as _api

__all__ = [
    "broadcast_parameters",
    "allreduce_parameters",
    "broadcast_optimizer_state",
    "deprecated_function_arg",
]


def deprecated_function_arg(arg_name: str, fix: str):
    """Decorator rejecting a deprecated keyword argument with a pointer to
    the replacement (reference ``torch/utility.py:219-229``)."""
    from functools import wraps

    def deprecated_decorator(f):
        @wraps(f)
        def wrapper(*args, **kwargs):
            if arg_name in kwargs:
                raise TypeError(
                    f"{arg_name} is deprecated in {f.__name__}: {fix}")
            return f(*args, **kwargs)

        return wrapper

    return deprecated_decorator


def broadcast_parameters(params: Any, root_rank: int = 0):
    """Replicate ``root_rank``'s slice of every leaf to all ranks
    (reference utility.py:26 — run once before training so all ranks start
    from identical weights)."""
    return jax.tree.map(lambda p: _api.broadcast(p, root_rank), params)


def allreduce_parameters(params: Any):
    """Replace every leaf with its cross-rank average (utility.py:58 —
    used to force consensus, e.g. before evaluation)."""
    return jax.tree.map(lambda p: _api.allreduce(p, average=True), params)


def broadcast_optimizer_state(opt_state: Any, root_rank: int = 0):
    """Broadcast optimizer state (utility.py:89-218).  The reference must
    reconstruct the torch state dict scalar-by-scalar; optax state is a
    pytree of [N, ...] arrays, so the same tree broadcast applies.  Non-array
    leaves (None, callables, empty states) pass through unchanged."""
    def bcast(leaf):
        if leaf is None:
            return None
        try:
            arr = jnp.asarray(leaf)
        except TypeError:
            return leaf  # callables/strings etc. pass through as documented
        if arr.ndim == 0 or arr.shape[0] != _api.ctx().size:
            return leaf  # replicated/static leaf — nothing to distribute
        return _api.broadcast(arr, root_rank)
    return jax.tree.map(bcast, opt_state)
