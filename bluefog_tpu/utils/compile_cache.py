"""Persistent XLA compilation cache shared by the benchmark/driver entry
points.

The ResNet-50 train-step compile is ~4-6 min cold through the tunneled
transport — most of a bench run — and a warmed cache turns re-runs (and
the driver's end-of-round run) into seconds of compile, shrinking the
window a transport stall can kill.  Opt out with
``JAX_COMPILATION_CACHE_DIR=""`` (empty).
"""

import os

import jax

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".jax_cache")


def enable_persistent_cache(cache_dir: str = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit argument, ``JAX_COMPILATION_CACHE_DIR``
    env (empty string disables), repo-root ``.jax_cache``.  Returns the
    directory used, or ``""`` when disabled/unsupported.
    """
    if cache_dir is None:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", _DEFAULT_DIR)
    if not cache_dir:
        return ""
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        return ""   # older jax without the knobs: cold compiles still work
    return cache_dir
