"""Persistent XLA compilation cache shared by the benchmark/driver entry
points.

The ResNet-50 train-step compile is ~4-6 min cold through the tunneled
transport — most of a bench run — and a warmed cache turns re-runs (and
the driver's end-of-round run) into seconds of compile, shrinking the
window a transport stall can kill.  Opt out with
``JAX_COMPILATION_CACHE_DIR=""`` (empty).
"""

import os

import jax

from ..observability import metrics as _metrics

_DEFAULT_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), ".jax_cache")


def enable_persistent_cache(cache_dir: str = None) -> str:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    Resolution order: explicit argument, ``JAX_COMPILATION_CACHE_DIR``
    env (empty string disables), repo-root ``.jax_cache``.  Returns the
    directory used, or ``""`` when disabled/unsupported.
    """
    if cache_dir is None:
        cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR", _DEFAULT_DIR)
    if not cache_dir:
        return ""
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:
        return ""   # older jax without the knobs: cold compiles still work
    return cache_dir


def note_step_cache(hit: bool) -> None:
    """Record a jitted-step cache consult in the host metrics registry
    (``bf_step_cache_total{result="hit"|"build"}``).

    A "build" is a retrace+recompile of the whole SPMD step — the
    canonical silent performance bug in this codebase (a knob missing
    from ``optim/_plumbing.step_cache_key`` serves stale programs; a knob
    churning per step recompiles every call).  The counter makes the
    recompile rate a first-class series next to step times in the bench
    JSON (``bench.py "metrics"``).  Free when the registry is disabled.
    """
    if _metrics.enabled():
        _metrics.counter(
            "bf_step_cache_total",
            "jitted-step cache consults by result (build = recompile)",
        ).inc(result="hit" if hit else "build")
