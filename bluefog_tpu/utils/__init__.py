"""Utilities: timeline tracing, logging, parameter distribution helpers."""
