"""Utilities: timeline tracing, logging, parameter distribution helpers,
checkpoint/resume."""

from . import utility


def __getattr__(name):
    # checkpoint pulls in orbax; defer it (PEP 562) like parallel.tensor
    if name == "checkpoint":
        import importlib
        return importlib.import_module(".checkpoint", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
