"""Leveled native logging (reference parity: ``BFLOG`` macros,
bluefog/common/logging.{h,cc}; env surface docs/env_variable.rst:8-22).

Routes through ``csrc/logging.cc`` when the native library is available so
Python and C++ components share one sink, level filter
(``BLUEFOG_LOG_LEVEL``), and format; falls back to the stdlib ``logging``
logger "bluefog" otherwise (reference basics.py:27-34 keeps the same
Python-side logger name).
"""

import logging as _pylogging
import os

from .. import native

__all__ = ["TRACE", "DEBUG", "INFO", "WARN", "ERROR", "FATAL",
           "log", "set_level", "get_level", "enabled"]

TRACE, DEBUG, INFO, WARN, ERROR, FATAL = range(6)

_LEVEL_NAMES = ["trace", "debug", "info", "warn", "error", "fatal"]
_PY_LEVELS = [5, _pylogging.DEBUG, _pylogging.INFO, _pylogging.WARNING,
              _pylogging.ERROR, _pylogging.CRITICAL]

_pylogger = _pylogging.getLogger("bluefog")
_fallback_level = [None]


def _configure_fallback() -> None:
    """Make the stdlib logger actually emit what blog's filter passes: the
    'bluefog' logger would otherwise inherit the root WARNING level and drop
    debug/info exactly where the fallback is needed."""
    if _fallback_level[0] is None:
        _fallback_level[0] = _env_level()
    if not _pylogger.handlers:
        handler = _pylogging.StreamHandler()
        handler.setFormatter(_pylogging.Formatter("%(message)s"))
        _pylogger.addHandler(handler)
        _pylogger.propagate = False
    _pylogger.setLevel(_PY_LEVELS[_fallback_level[0]])


def _env_level() -> int:
    name = os.environ.get("BLUEFOG_LOG_LEVEL", "warn")
    if name in _LEVEL_NAMES:
        return _LEVEL_NAMES.index(name)
    try:
        return max(TRACE, min(FATAL, int(name)))
    except ValueError:
        return WARN


def log(level: int, msg: str, rank: int = -1) -> None:
    """Emit one leveled line; ``rank`` tags the message like BFLOG(level,
    rank).  FATAL aborts the process in the native path (reference parity)."""
    lib = native.load()
    if lib is not None:
        lib.bft_log(int(level), int(rank), str(msg).encode())
        return
    _configure_fallback()
    if level < _fallback_level[0]:
        return
    prefix = f"[{rank}]" if rank >= 0 else ""
    _pylogger.log(_PY_LEVELS[max(TRACE, min(FATAL, level))], "%s%s", prefix, msg)


def set_level(level: int) -> None:
    lib = native.load()
    if lib is not None:
        lib.bft_log_set_level(int(level))
    else:
        _fallback_level[0] = int(level)
        _configure_fallback()


def get_level() -> int:
    lib = native.load()
    if lib is not None:
        return int(lib.bft_log_level())
    if _fallback_level[0] is None:
        _fallback_level[0] = _env_level()
    return _fallback_level[0]


def enabled(level: int) -> bool:
    return int(level) >= get_level()
