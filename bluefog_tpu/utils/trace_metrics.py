"""Trace-level collective metrics: count communication ops in lowered HLO.

The comm-fusion layer's headline claim — a step's collective count drops
from ``leaves x offsets`` to ``buckets x offsets`` — is a property of the
COMPILED program, measurable on any backend (the StableHLO is produced at
lowering time, before backend-specific compilation).  This module is the
single home for that proof: ``tests/test_fusion.py`` asserts regression
bounds with it and ``bench.py --trace-only`` / ``make bench-trace`` report
it as a CPU-only benchmark mode.

Counting convention: one occurrence of the StableHLO op mnemonic = one
collective in the program.  ``lax.ppermute`` lowers to
``stablehlo.collective_permute``, ``psum``/``pmean`` to
``stablehlo.all_reduce``, ``all_gather`` to ``stablehlo.all_gather``
(pmean's mean division is elementwise math, not a second collective).

Async split ops: backends that hide collective latency split an op into a
``collective-permute-start`` / ``collective-permute-done`` pair in the
OPTIMIZED HLO (the latency-hiding scheduler then moves compute between
the two halves).  The counters recognize both dialect spellings; a fused
(synchronous) ``collective-permute`` never matches the ``-start/-done``
forms and vice versa.  ``compiled_collective_counts`` inspects the
post-compile text where the split happens — CPU lowering keeps
collectives synchronous, which is itself the documented evidence mode for
the overlap pipeline (per-step sync count unchanged while the mix
consumes the prior step's buffer).
"""

import re
import time
from typing import Any, Dict, Tuple

import jax

__all__ = ["collective_counts", "compiled_collective_counts",
           "count_collectives_in_text", "lower_text"]

# op-name mnemonics in jax's StableHLO output and the optimized-HLO
# dialect; matched with a word boundary so e.g. all_gather never
# double-counts all_reduce, and the sync forms never match the async
# -start/-done splits.  HLO-dialect forms carry a (?<!%) guard:
# instruction NAMES and operand references are %-prefixed
# (`%collective-permute.1 = ... collective-permute(%x)`), and counting
# them would tally every op at least twice — only the un-prefixed opcode
# position is the op itself.
_PATTERNS = {
    "ppermute": re.compile(
        r"\bstablehlo\.collective_permute\b(?!_)"
        r"|(?<!%)\bcollective-permute\b(?!-(?:start|done))"),
    "all_reduce": re.compile(
        r"\bstablehlo\.all_reduce\b"
        r"|(?<!%)\ball-reduce\b(?!-(?:start|done))"),
    "all_gather": re.compile(
        r"\bstablehlo\.all_gather\b"
        r"|(?<!%)\ball-gather\b(?!-(?:start|done))"),
    "all_to_all": re.compile(
        r"\bstablehlo\.all_to_all\b|(?<!%)\ball-to-all\b"),
    "reduce_scatter": re.compile(
        r"\bstablehlo\.reduce_scatter\b|(?<!%)\breduce-scatter\b"),
}

# async split halves (overlap-eligible collectives), outside "total"
_ASYNC_PATTERNS = {
    "ppermute_start": re.compile(
        r"\bstablehlo\.collective_permute_start\b"
        r"|(?<!%)\bcollective-permute-start\b"),
    "ppermute_done": re.compile(
        r"\bstablehlo\.collective_permute_done\b"
        r"|(?<!%)\bcollective-permute-done\b"),
}


def count_collectives_in_text(text: str) -> Dict[str, int]:
    """Per-kind collective-op counts in an HLO/StableHLO module string.

    ``total`` sums the synchronous kinds; the async split halves are
    reported separately as ``ppermute_start``/``ppermute_done`` with
    ``ppermute_pairs`` = complete start/done pairs (the overlap-eligible
    collective count)."""
    counts = {kind: len(pat.findall(text)) for kind, pat in _PATTERNS.items()}
    counts["total"] = sum(counts.values())
    for kind, pat in _ASYNC_PATTERNS.items():
        counts[kind] = len(pat.findall(text))
    counts["ppermute_pairs"] = min(counts["ppermute_start"],
                                   counts["ppermute_done"])
    return counts


def lower_text(fn, *args, **kwargs) -> Tuple[str, float]:
    """Lower ``fn(*args, **kwargs)`` to StableHLO text; returns
    ``(text, trace_seconds)``.  Accepts an already-jitted callable (has
    ``.lower``) or a plain one (wrapped in ``jax.jit`` first).  Lowering
    only TRACES — no backend compile happens, so this is cheap and runs
    identically on CPU."""
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    t0 = time.perf_counter()
    lowered = fn.lower(*args, **kwargs)
    text = lowered.as_text()
    return text, time.perf_counter() - t0


def collective_counts(fn, *args, **kwargs) -> Dict[str, Any]:
    """Counts of every collective kind in the lowered program, plus
    ``trace_s`` (wall-clock tracing+lowering time) and ``hlo_lines``
    (program size — fusion shrinks this too)."""
    text, trace_s = lower_text(fn, *args, **kwargs)
    out: Dict[str, Any] = count_collectives_in_text(text)
    out["trace_s"] = trace_s
    out["hlo_lines"] = text.count("\n")
    return out


def compiled_collective_counts(fn, *args, **kwargs) -> Dict[str, Any]:
    """Collective counts in the POST-COMPILE (optimized) HLO — where a
    latency-hiding backend splits async collectives into start/done pairs
    (``ppermute_pairs`` counts them; ``ppermute`` counts the ops left
    synchronous).  Unlike :func:`collective_counts` this runs the backend
    compiler; on CPU the collectives stay synchronous, so a zero pair
    count there is expected, not a regression — assert on the sync count
    instead."""
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    t0 = time.perf_counter()
    compiled = fn.lower(*args, **kwargs).compile()
    text = compiled.as_text()
    out: Dict[str, Any] = count_collectives_in_text(text)
    out["compile_s"] = time.perf_counter() - t0
    return out
