"""Trace-level collective metrics: count communication ops in lowered HLO.

The comm-fusion layer's headline claim — a step's collective count drops
from ``leaves x offsets`` to ``buckets x offsets`` — is a property of the
COMPILED program, measurable on any backend (the StableHLO is produced at
lowering time, before backend-specific compilation).  This module is the
single home for that proof: ``tests/test_fusion.py`` asserts regression
bounds with it and ``bench.py --trace-only`` / ``make bench-trace`` report
it as a CPU-only benchmark mode.

Counting convention: one occurrence of the StableHLO op mnemonic = one
collective in the program.  ``lax.ppermute`` lowers to
``stablehlo.collective_permute``, ``psum``/``pmean`` to
``stablehlo.all_reduce``, ``all_gather`` to ``stablehlo.all_gather``
(pmean's mean division is elementwise math, not a second collective).

Async split ops: backends that hide collective latency split an op into a
``collective-permute-start`` / ``collective-permute-done`` pair in the
OPTIMIZED HLO (the latency-hiding scheduler then moves compute between
the two halves).  The counters recognize both dialect spellings; a fused
(synchronous) ``collective-permute`` never matches the ``-start/-done``
forms and vice versa.  ``compiled_collective_counts`` inspects the
post-compile text where the split happens — CPU lowering keeps
collectives synchronous, which is itself the documented evidence mode for
the overlap pipeline (per-step sync count unchanged while the mix
consumes the prior step's buffer).
"""

import re
import time
from typing import Any, Dict, Tuple

import jax

__all__ = ["collective_counts", "compiled_collective_counts",
           "count_collectives_in_text", "lower_text"]

# op-name mnemonics in jax's StableHLO output and the optimized-HLO
# dialect; matched with a word boundary so e.g. all_gather never
# double-counts all_reduce, and the sync forms never match the async
# -start/-done splits.  HLO-dialect forms carry a (?<!%) guard:
# instruction NAMES and operand references are %-prefixed
# (`%collective-permute.1 = ... collective-permute(%x)`), and counting
# them would tally every op at least twice — only the un-prefixed opcode
# position is the op itself.
_PATTERNS = {
    "ppermute": re.compile(
        r"\bstablehlo\.collective_permute\b(?!_)"
        r"|(?<!%)\bcollective-permute\b(?!-(?:start|done))"),
    "all_reduce": re.compile(
        r"\bstablehlo\.all_reduce\b"
        r"|(?<!%)\ball-reduce\b(?!-(?:start|done))"),
    "all_gather": re.compile(
        r"\bstablehlo\.all_gather\b"
        r"|(?<!%)\ball-gather\b(?!-(?:start|done))"),
    "all_to_all": re.compile(
        r"\bstablehlo\.all_to_all\b|(?<!%)\ball-to-all\b"),
    "reduce_scatter": re.compile(
        r"\bstablehlo\.reduce_scatter\b|(?<!%)\breduce-scatter\b"),
}

# async split halves (overlap-eligible collectives), outside "total"
_ASYNC_PATTERNS = {
    "ppermute_start": re.compile(
        r"\bstablehlo\.collective_permute_start\b"
        r"|(?<!%)\bcollective-permute-start\b"),
    "ppermute_done": re.compile(
        r"\bstablehlo\.collective_permute_done\b"
        r"|(?<!%)\bcollective-permute-done\b"),
}

# ---------------------------------------------------------------------------
# Payload-byte estimation: parse operand/result tensor types off the op line
# ---------------------------------------------------------------------------
#
# StableHLO spells types `tensor<8x128xf32>` (result types after `->`);
# the HLO dialect spells them `f32[8,128]{1,0}` with the RESULT type first
# on the line (`%name = f32[8,128]{1,0} collective-permute(...)`).  Bytes
# are counted from the RESULT side — for every collective here the result
# payload equals the moved payload (permute/reduce preserve shape; gather's
# result IS the gathered volume), so "bytes moved per program execution"
# is the honest reading.  Layout annotations and tuple wrappers are
# tolerated; unknown dtypes count as 0 rather than guessing.

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "i8": 1, "ui8": 1, "i1": 1,
    "s16": 2, "u16": 2, "i16": 2, "ui16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "i32": 4, "ui32": 4, "f32": 4,
    "s64": 8, "u64": 8, "i64": 8, "ui64": 8, "f64": 8, "c64": 8,
    "c128": 16,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "f8e3m4": 1, "f8e4m3": 1, "f8e8m0fnu": 1,
}


def _dtype_nbytes(dtype: str):
    """Per-element bytes of a dialect dtype token, or None when unknown.

    StableHLO capitalizes the f8 family (``f8E4M3FN``) while the HLO
    dialect spells it lowercase (``f8e4m3fn``) — compression puts these
    (and ``i8``) on the wire, so byte estimation must not silently drop
    them (the pre-fix estimator was effectively f32-only in practice:
    every uncompressed buffer it ever saw was 4-byte)."""
    size = _DTYPE_BYTES.get(dtype)
    if size is None:
        size = _DTYPE_BYTES.get(dtype.lower())
    return size

_STABLEHLO_TENSOR = re.compile(r"tensor<([^>]*)>")
_HLO_TYPE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\](?:\{[^}]*\})?")
# un-prefixed opcode position on an HLO-dialect line (instruction names
# are %-prefixed); sync and async-split spellings both terminate the
# result-type head
_HLO_OPCODE = re.compile(
    r"(?<!%)\b(?:all-reduce|collective-permute|all-gather|all-to-all|"
    r"reduce-scatter)(?:-(?:start|done))?\b")


def _stablehlo_tensor_bytes(spec: str) -> int:
    """``'8x128xf32'`` / ``'f32'`` (0-d) -> byte count (0 if unknown)."""
    parts = spec.strip().split("x")
    dtype = parts[-1].strip()
    size = _dtype_nbytes(dtype)
    if size is None:
        return 0
    n = 1
    for d in parts[:-1]:
        d = d.strip()
        if not d.isdigit():
            return 0      # dynamic dim ('?') — unknowable, do not guess
        n *= int(d)
    return n * size


def _hlo_type_bytes(dtype: str, dims: str) -> int:
    size = _dtype_nbytes(dtype)
    if size is None:
        return 0
    n = 1
    for d in dims.split(","):
        d = d.strip()
        if d:
            n *= int(d)
    return n * size


def _stablehlo_arrow_bytes(line: str) -> int:
    """Bytes of the result types after ``->`` on a StableHLO line."""
    specs = _STABLEHLO_TENSOR.findall(line.split("->", 1)[1])
    return sum(_stablehlo_tensor_bytes(s) for s in specs)


def _op_result_bytes(lines, i: int, lookahead: int = 64) -> int:
    """Result-side payload bytes of the op whose mnemonic sits on
    ``lines[i]`` (see module comment).

    StableHLO regioned ops (all_reduce with its reducer block) put the
    type signature on the region-CLOSING line, so when the mnemonic line
    carries no ``->`` the scanner walks forward to the first line that
    does (bounded; reducer-body element ops carry bare ``: tensor<f32>``
    types without an arrow, so the first arrow is the op's signature).
    """
    line = lines[i]
    if "stablehlo" in line or "tensor<" in line:
        if "->" in line:
            return _stablehlo_arrow_bytes(line)
        stripped = line.rstrip()
        matches = list(_STABLEHLO_TENSOR.finditer(stripped))
        if matches and matches[-1].end() == len(stripped):
            # single-line arrowless form ends WITH its value type
            # (`stablehlo.add %a, %b : tensor<f32>`); a trailing `({`
            # region opener means any tensor<> on the line is an attr
            # type (replica_groups), not the signature
            return _stablehlo_tensor_bytes(matches[-1].group(1))
        for j in range(i + 1, min(i + 1 + lookahead, len(lines))):
            if "->" in lines[j]:
                return _stablehlo_arrow_bytes(lines[j])
        return 0
    # HLO dialect (single-line): the result type(s) precede the OPCODE —
    # cut at the opcode occurrence, not at the first '(' (a tuple result
    # `(f32[100]{0}, f32[50]{0}) all-reduce(...)` opens a paren before the
    # operand list), then parse every type token in the head
    m = _HLO_OPCODE.search(line)
    head = line[:m.start()] if m else line.split("(", 1)[0]
    return sum(_hlo_type_bytes(d, dims)
               for d, dims in _HLO_TYPE.findall(head))


def count_collectives_in_text(text: str) -> Dict[str, int]:
    """Per-kind collective-op counts in an HLO/StableHLO module string.

    ``total`` sums the synchronous kinds; the async split halves are
    reported separately as ``ppermute_start``/``ppermute_done`` with
    ``ppermute_pairs`` = complete start/done pairs (the overlap-eligible
    collective count).

    Per-kind ``<kind>_bytes`` estimate the payload moved per program
    execution (result-side tensor volume parsed off each op line; see the
    payload-estimation comment above), with ``total_bytes`` summing the
    synchronous kinds — so ``bench.py --trace-only`` reports bytes moved,
    not just op counts."""
    counts = {kind: len(pat.findall(text)) for kind, pat in _PATTERNS.items()}
    counts["total"] = sum(counts.values())
    for kind, pat in _ASYNC_PATTERNS.items():
        counts[kind] = len(pat.findall(text))
    counts["ppermute_pairs"] = min(counts["ppermute_start"],
                                   counts["ppermute_done"])
    sync_kinds = list(_PATTERNS)
    bytes_by_kind = {kind: 0 for kind in sync_kinds}
    lines = text.splitlines()
    for i, line in enumerate(lines):
        for kind in sync_kinds:
            if _PATTERNS[kind].search(line):
                bytes_by_kind[kind] += _op_result_bytes(lines, i)
    for kind in sync_kinds:
        counts[f"{kind}_bytes"] = bytes_by_kind[kind]
    counts["total_bytes"] = sum(bytes_by_kind.values())
    return counts


def lower_text(fn, *args, **kwargs) -> Tuple[str, float]:
    """Lower ``fn(*args, **kwargs)`` to StableHLO text; returns
    ``(text, trace_seconds)``.  Accepts an already-jitted callable (has
    ``.lower``) or a plain one (wrapped in ``jax.jit`` first).  Lowering
    only TRACES — no backend compile happens, so this is cheap and runs
    identically on CPU."""
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    t0 = time.perf_counter()
    lowered = fn.lower(*args, **kwargs)
    text = lowered.as_text()
    return text, time.perf_counter() - t0


def collective_counts(fn, *args, **kwargs) -> Dict[str, Any]:
    """Counts of every collective kind in the lowered program, plus
    ``trace_s`` (wall-clock tracing+lowering time) and ``hlo_lines``
    (program size — fusion shrinks this too)."""
    text, trace_s = lower_text(fn, *args, **kwargs)
    out: Dict[str, Any] = count_collectives_in_text(text)
    out["trace_s"] = trace_s
    out["hlo_lines"] = text.count("\n")
    return out


def compiled_collective_counts(fn, *args, **kwargs) -> Dict[str, Any]:
    """Collective counts in the POST-COMPILE (optimized) HLO — where a
    latency-hiding backend splits async collectives into start/done pairs
    (``ppermute_pairs`` counts them; ``ppermute`` counts the ops left
    synchronous).  Unlike :func:`collective_counts` this runs the backend
    compiler; on CPU the collectives stay synchronous, so a zero pair
    count there is expected, not a regression — assert on the sync count
    instead."""
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    t0 = time.perf_counter()
    compiled = fn.lower(*args, **kwargs).compile()
    text = compiled.as_text()
    out: Dict[str, Any] = count_collectives_in_text(text)
    out["compile_s"] = time.perf_counter() - t0
    return out
