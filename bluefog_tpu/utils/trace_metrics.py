"""Trace-level collective metrics: count communication ops in lowered HLO.

The comm-fusion layer's headline claim — a step's collective count drops
from ``leaves x offsets`` to ``buckets x offsets`` — is a property of the
COMPILED program, measurable on any backend (the StableHLO is produced at
lowering time, before backend-specific compilation).  This module is the
single home for that proof: ``tests/test_fusion.py`` asserts regression
bounds with it and ``bench.py --trace-only`` / ``make bench-trace`` report
it as a CPU-only benchmark mode.

Counting convention: one occurrence of the StableHLO op mnemonic = one
collective in the program.  ``lax.ppermute`` lowers to
``stablehlo.collective_permute``, ``psum``/``pmean`` to
``stablehlo.all_reduce``, ``all_gather`` to ``stablehlo.all_gather``
(pmean's mean division is elementwise math, not a second collective).
"""

import re
import time
from typing import Any, Dict, Tuple

import jax

__all__ = ["collective_counts", "count_collectives_in_text", "lower_text"]

# op-name mnemonics in jax's StableHLO output; matched with a word
# boundary so e.g. all_gather never double-counts all_reduce
_PATTERNS = {
    "ppermute": re.compile(r"\bstablehlo\.collective_permute\b"),
    "all_reduce": re.compile(r"\bstablehlo\.all_reduce\b"),
    "all_gather": re.compile(r"\bstablehlo\.all_gather\b"),
    "all_to_all": re.compile(r"\bstablehlo\.all_to_all\b"),
    "reduce_scatter": re.compile(r"\bstablehlo\.reduce_scatter\b"),
}


def count_collectives_in_text(text: str) -> Dict[str, int]:
    """Per-kind collective-op counts in a StableHLO module string."""
    counts = {kind: len(pat.findall(text)) for kind, pat in _PATTERNS.items()}
    counts["total"] = sum(counts.values())
    return counts


def lower_text(fn, *args, **kwargs) -> Tuple[str, float]:
    """Lower ``fn(*args, **kwargs)`` to StableHLO text; returns
    ``(text, trace_seconds)``.  Accepts an already-jitted callable (has
    ``.lower``) or a plain one (wrapped in ``jax.jit`` first).  Lowering
    only TRACES — no backend compile happens, so this is cheap and runs
    identically on CPU."""
    if not hasattr(fn, "lower"):
        fn = jax.jit(fn)
    t0 = time.perf_counter()
    lowered = fn.lower(*args, **kwargs)
    text = lowered.as_text()
    return text, time.perf_counter() - t0


def collective_counts(fn, *args, **kwargs) -> Dict[str, Any]:
    """Counts of every collective kind in the lowered program, plus
    ``trace_s`` (wall-clock tracing+lowering time) and ``hlo_lines``
    (program size — fusion shrinks this too)."""
    text, trace_s = lower_text(fn, *args, **kwargs)
    out: Dict[str, Any] = count_collectives_in_text(text)
    out["trace_s"] = trace_s
    out["hlo_lines"] = text.count("\n")
    return out
