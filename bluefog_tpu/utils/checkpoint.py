"""Checkpoint / resume — compat shim over ``bluefog_tpu/checkpoint/``.

The reference has no in-framework checkpointing — its supported pattern
is vanilla torch ``save``/``load`` on rank 0 plus the state
*distribution* helpers ``broadcast_parameters`` /
``broadcast_optimizer_state`` (bluefog/torch/utility.py:26-218,
SURVEY.md §5.4).  An earlier revision of this module claimed the
TPU-native equivalent is simpler because "one controller owns the
global state" — that was wrong for exactly the reason this framework
exists: decentralized ranks hold DIVERGENT parameters (plus per-rank
error-feedback residuals, CHOCO estimates, and in-flight overlap
buffers), which is why the real subsystem's manifest records one shard
per rank instead of one global tree.

This module keeps its historical public API (:class:`Checkpointer`,
:func:`save_checkpoint`, :func:`restore_checkpoint` — orbax-backed
single-tree save/restore) as a thin delegation to
``bluefog_tpu.checkpoint.compat``.  New code should use the subsystem
proper — ``checkpoint.fleet_state_dict`` +
``checkpoint.FleetCheckpointer`` for crash-consistent, neighbor-
replicated, elastically-restorable fleet snapshots (docs/checkpoint.md).

    ckpt = bf.utils.checkpoint.Checkpointer("/tmp/run1", max_to_keep=3)
    ckpt.save(step, {"variables": variables, "opt_state": opt_state})
    ...
    restored = ckpt.restore()          # latest, or .restore(step)
    step0 = ckpt.latest_step()
"""

from ..checkpoint.compat import (Checkpointer,  # noqa: F401
                                 restore_checkpoint, save_checkpoint)

__all__ = ["Checkpointer", "save_checkpoint", "restore_checkpoint"]
