"""Single-tree orbax checkpointing (the pre-subsystem surface).

This is the API ``utils/checkpoint.py`` has always exported, kept
verbatim for callers that checkpoint one pytree through orbax
(``examples/resnet.py``, the plain-state tests).  It is a *partial*
capture: orbax writes whatever tree you hand it, and a decentralized
run's state does not live in one tree — ranks hold divergent params,
the opt state carries compression/overlap buffers, windows double-
buffer, and the fault-plan/membership/controller state is host-side.
For the complete, crash-consistent, per-rank-sharded capture use the
subsystem proper: :func:`~.state.fleet_state_dict` +
:class:`~.snapshot.FleetCheckpointer` (docs/checkpoint.md).
"""

import os
from typing import Any, Optional

__all__ = ["Checkpointer", "save_checkpoint", "restore_checkpoint"]


class Checkpointer:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    State is any pytree of jax/numpy arrays (shardings are preserved and
    restored).  Python scalars/ints ride along as pytree leaves.
    """

    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(directory)
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True),
        )

    def save(self, step: int, state: Any, *, force: bool = False,
             wait: bool = True) -> bool:
        """Write ``state`` for ``step``; async under the hood.  ``wait``
        blocks until the write is durable (set False to overlap with the
        next training steps and call ``wait_until_finished`` later)."""
        ok = self._mgr.save(
            int(step), args=self._ocp.args.StandardSave(state), force=force)
        if wait:
            self._mgr.wait_until_finished()
        return ok

    def restore(self, step: Optional[int] = None, template: Any = None):
        """Restore ``step`` (default: latest).  ``template``: a pytree of
        like-shaped (possibly sharded) arrays — supply it to restore
        directly onto the right devices/shardings."""
        step = self.latest_step() if step is None else int(step)
        if step is None:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory}")
        if template is not None:
            args = self._ocp.args.StandardRestore(template)
            return self._mgr.restore(step, args=args)
        try:
            return self._mgr.restore(step)
        except KeyError:
            # older orbax (<0.9) cannot infer the handler for an argless
            # restore of a StandardSave item; an explicit template-less
            # StandardRestore names the handler and restores as numpy
            return self._mgr.restore(
                step, args=self._ocp.args.StandardRestore())

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self):
        self._mgr.wait_until_finished()

    def close(self):
        self._mgr.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def save_checkpoint(directory: str, step: int, state: Any) -> None:
    """One-shot convenience (reference users called torch.save on rank 0)."""
    with Checkpointer(directory) as ckpt:
        ckpt.save(step, state)


def restore_checkpoint(directory: str, step: Optional[int] = None,
                       template: Any = None):
    """One-shot convenience; returns the restored pytree."""
    with Checkpointer(directory) as ckpt:
        return ckpt.restore(step, template)
