"""Crash-consistent snapshot writer: shards → fsync → atomic manifest.

The durability contract (docs/checkpoint.md "Commit protocol"): a
checkpoint EXISTS only once its ``manifest.json`` does.  A save writes
every shard to a temp name, fsyncs, renames into place, then publishes
the manifest with one atomic ``os.replace`` — so a kill at ANY point
mid-save leaves either the previous complete checkpoint (no new
manifest) or the new complete one (manifest published after every shard
it names is durable).  Per-shard CRC32 checksums ride the manifest:
a shard torn AFTER publish (disk loss, truncation) is detected at
restore time and repaired from a neighbor replica
(``checkpoint/redundancy.py``) or, failing that, the restore falls back
to the previous durable manifest.

The :class:`FleetCheckpointer` keeps saves off the critical path with a
host-side copy-on-save double buffer: :func:`~.state.fleet_state_dict`
already hands over host COPIES (the donated device buffers keep
stepping immediately), and the shard/fsync/publish work drains on a
single background thread.  At most one commit is in flight; a cadence
tick that lands while one is still draining is SKIPPED (counted,
trailed) rather than queued — checkpoint pressure must degrade to a
longer interval, never to an unbounded host-memory queue of snapshots.

Directory layout::

    <dir>/step-00000012/rank-0.npz ... rank-7.npz   per-rank shards
    <dir>/step-00000012/global.npz                  unsharded leaves
    <dir>/step-00000012/replicas/rank-3.held-by-5.npz
    <dir>/step-00000012/manifest.json               published LAST
"""

import json
import os
import shutil
import threading
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability import export as _export
from ..observability import metrics as _metrics
from . import state as _state

__all__ = ["FleetCheckpointer", "MANIFEST_NAME", "GLOBAL_SHARD",
           "shard_name", "step_dir_name", "process_scoped_dir",
           "write_shard", "file_crc32",
           "durable_manifests", "load_manifest", "split_shards",
           "DIR_ENV", "EVERY_ENV", "KEEP_ENV", "REPLICAS_ENV", "ASYNC_ENV",
           "resolve_every", "resolve_keep", "resolve_replicas",
           "resolve_async"]

MANIFEST_NAME = "manifest.json"
GLOBAL_SHARD = "global.npz"

DIR_ENV = "BLUEFOG_CKPT_DIR"
EVERY_ENV = "BLUEFOG_CKPT_EVERY"
KEEP_ENV = "BLUEFOG_CKPT_KEEP"
REPLICAS_ENV = "BLUEFOG_CKPT_REPLICAS"
ASYNC_ENV = "BLUEFOG_CKPT_ASYNC"


def resolve_every(value: Optional[int] = None) -> int:
    """``BLUEFOG_CKPT_EVERY`` (default 0 = no cadence): save every k-th
    step via :meth:`FleetCheckpointer.maybe_save`."""
    every = int(os.environ.get(EVERY_ENV, "0") if value is None else value)
    if every < 0:
        raise ValueError(f"ckpt cadence must be >= 0, got {every}")
    return every


def resolve_keep(value: Optional[int] = None) -> int:
    """``BLUEFOG_CKPT_KEEP`` (default 2): durable checkpoints retained.
    Two is the crash-consistency floor — the newest may be the one a
    torn shard invalidates."""
    keep = int(os.environ.get(KEEP_ENV, "2") if value is None else value)
    if keep < 1:
        raise ValueError(f"ckpt keep must be >= 1, got {keep}")
    return keep


def resolve_replicas(value: Optional[int] = None) -> int:
    """``BLUEFOG_CKPT_REPLICAS`` (default 1): out-neighbors holding a
    copy of each rank's shard (0 disables redundancy)."""
    k = int(os.environ.get(REPLICAS_ENV, "1") if value is None else value)
    if k < 0:
        raise ValueError(f"ckpt replicas must be >= 0, got {k}")
    return k


def resolve_async(value: Optional[bool] = None) -> bool:
    """``BLUEFOG_CKPT_ASYNC`` (default on): commit on the background
    thread.  Off = synchronous saves (deterministic tests, debugging)."""
    if value is not None:
        return bool(value)
    return os.environ.get(ASYNC_ENV, "1").lower() not in ("0", "false", "off")


def process_scoped_dir(directory: str,
                       process_index: Optional[int] = None) -> str:
    """Scope a checkpoint directory to one fleet process:
    ``<dir>/proc-<index>``.

    A ``bfrun --fleet`` worker runs its own full-size virtual mesh, so
    every process would otherwise write the SAME
    ``<dir>/step-N/rank-R.npz`` paths and clobber its siblings on a
    shared filesystem.  Resolution: the explicit ``process_index`` wins,
    else ``BLUEFOG_FLEET_RANK`` (the supervisor's per-worker env); with
    neither the directory comes back unchanged (single-process runs keep
    the seed layout)."""
    if process_index is None:
        v = os.environ.get("BLUEFOG_FLEET_RANK")
        if v is None:
            return directory
        process_index = int(v)
    return os.path.join(directory, f"proc-{int(process_index)}")


def step_dir_name(step: int) -> str:
    return f"step-{int(step):08d}"


def shard_name(rank: int) -> str:
    return f"rank-{int(rank)}.npz"


def write_shard(path: str, named: Dict[str, np.ndarray]
                ) -> Tuple[int, int]:
    """Write one ``.npz`` shard durably: temp name, fsync, rename.
    Returns ``(crc32, bytes)`` of the final file content — the checksum
    is computed over the very bytes that hit the disk (read back after
    the fsync), which is exactly what restore will verify."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **named)
        f.flush()
        os.fsync(f.fileno())
    crc = file_crc32(tmp)
    nbytes = os.path.getsize(tmp)
    os.replace(tmp, path)
    return crc, nbytes


def file_crc32(path: str, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def split_shards(state: Dict[str, Any], size: Optional[int] = None
                 ) -> Tuple[List[Dict[str, np.ndarray]],
                            Dict[str, np.ndarray], int]:
    """Split a snapshot's arrays into per-rank + global shard payloads.

    A leaf whose leading dimension equals the fleet size is per-rank
    state (the global-view convention — every train/window/compression
    leaf): shard r gets ``leaf[r]``.  Everything else (RNG key data,
    odd-shaped user leaves) rides the shared ``global`` shard.  Returns
    ``(per_rank_payloads, global_payload, size)``."""
    flat = _state.flat_arrays(state)
    if size is None:
        size = state.get("meta", {}).get("size")
    if size is None:
        # infer: the most common leading dim across non-scalar leaves
        dims: Dict[int, int] = {}
        for v in flat.values():
            if v.ndim >= 1:
                dims[v.shape[0]] = dims.get(v.shape[0], 0) + 1
        if not dims:
            raise ValueError("snapshot has no array leaves to shard")
        size = max(dims, key=lambda d: dims[d])
    size = int(size)
    per_rank: List[Dict[str, np.ndarray]] = [dict() for _ in range(size)]
    global_payload: Dict[str, np.ndarray] = {}
    for key, v in flat.items():
        if v.ndim >= 1 and v.shape[0] == size:
            for r in range(size):
                per_rank[r][key] = v[r]
        else:
            global_payload[key] = v
    return per_rank, global_payload, size


def load_manifest(path: str) -> Optional[dict]:
    """Parse one manifest; None when missing/unreadable/truncated (a
    torn manifest write never published — it does not exist)."""
    try:
        with open(path) as f:
            m = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(m, dict) or "shards" not in m or "step" not in m:
        return None
    return m


def durable_manifests(directory: str) -> List[Tuple[int, str]]:
    """Every published checkpoint under ``directory``, oldest first:
    ``[(step, manifest_path)]``.  Unpublished step dirs (killed
    mid-save) simply have no manifest and are invisible here."""
    out = []
    try:
        entries = sorted(os.listdir(directory))
    except OSError:
        return []
    for name in entries:
        if not name.startswith("step-"):
            continue
        path = os.path.join(directory, name, MANIFEST_NAME)
        m = load_manifest(path)
        if m is not None:
            out.append((int(m["step"]), path))
    out.sort(key=lambda t: t[0])
    return out


class FleetCheckpointer:
    """Durable-fleet-state writer: cadence, copy-on-save double buffer,
    background commit, neighbor redundancy, retention, and the
    ``ckpt``/``ckpt_event`` trail + ``bf_ckpt_*`` gauges.

    >>> ckpt = FleetCheckpointer("/path/run1", every=100)
    >>> for t in range(steps):
    ...     params, st, loss = step(params, st, batch, t)
    ...     ckpt.maybe_save(t + 1, lambda: checkpoint.fleet_state_dict(
    ...         t + 1, {"params": params, "opt_state": st}))
    >>> ckpt.close()
    """

    def __init__(self, directory: Optional[str] = None, *,
                 every: Optional[int] = None, keep: Optional[int] = None,
                 replicas: Optional[int] = None,
                 async_commit: Optional[bool] = None,
                 trail_path: Optional[str] = None,
                 size: Optional[int] = None):
        if directory is None:
            directory = os.environ.get(DIR_ENV)
        if not directory:
            raise ValueError(
                "no checkpoint directory: pass directory= or set "
                "BLUEFOG_CKPT_DIR")
        # one fleet process must not clobber its siblings' shards
        self.directory = os.path.abspath(process_scoped_dir(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.every = resolve_every(every)
        self.keep = resolve_keep(keep)
        self.replicas = resolve_replicas(replicas)
        self.async_commit = resolve_async(async_commit)
        self.size = size
        self.last_durable: Optional[int] = None
        existing = durable_manifests(self.directory)
        if existing:
            self.last_durable = existing[-1][0]
        self._pending: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._trail = None
        self._owns_trail = False
        if trail_path is None:
            prefix = os.environ.get(_export.METRICS_ENV)
            if prefix:
                trail_path = prefix + _export.CKPT_SUFFIX
        if trail_path:
            self._trail = _export.CkptTrail(
                trail_path, directory=self.directory, every=self.every,
                keep=self.keep, replicas=self.replicas,
                size=size if size is not None else -1)
            self._owns_trail = True

    # -- trail/metrics plumbing ---------------------------------------------

    @property
    def trail(self):
        """The open :class:`~..observability.export.CkptTrail` (or None)
        — pass it to ``restore_latest(trail=...)`` so restore/repair
        events land on the same sidecar the saves write."""
        return self._trail

    def _event(self, step: int, event: str, *, rank=None, detail=None):
        # CkptTrail.write is internally locked: the step loop, the
        # background committer, and restore callers share this sidecar
        if self._trail is not None:
            self._trail.write_event(step, event, rank=rank, detail=detail)

    def _counter(self, name: str, help_: str):
        if _metrics.enabled():
            _metrics.counter(name, help_).inc()

    # -- cadence + async front door -----------------------------------------

    def maybe_save(self, step: int, state_or_fn) -> bool:
        """Cadence gate: save when ``step`` hits the ``every`` grid
        (``every`` 0 = never).  ``state_or_fn``: a snapshot dict or a
        zero-arg callable building one (preferred — capture cost is
        paid only on cadence steps)."""
        if not self.every or int(step) % self.every != 0:
            return False
        return self.save(step, state_or_fn)

    def save(self, step: int, state_or_fn) -> bool:
        """Snapshot now.  Async mode hands the host copies to the
        background committer and returns immediately; a save requested
        while one is still draining is SKIPPED (counted + trailed).
        Returns True when a commit was started (or completed)."""
        with self._lock:
            if self._pending is not None and self._pending.is_alive():
                self._counter("bf_ckpt_save_skipped_total",
                              "cadence saves skipped because the "
                              "previous commit was still draining")
                self._event(step, "save_skipped",
                            detail="previous commit still draining")
                return False
            self._pending = None
        state = state_or_fn() if callable(state_or_fn) else state_or_fn
        self._event(step, "save_begin")
        if not self.async_commit:
            self._commit(int(step), state)
            return True
        t = threading.Thread(target=self._commit_guarded,
                             args=(int(step), state),
                             name=f"bf-ckpt-{step}", daemon=True)
        with self._lock:
            self._pending = t
        t.start()
        return True

    def _commit_guarded(self, step: int, state: Dict[str, Any]) -> None:
        """The background-thread entry: a commit that fails (full disk,
        lost mount, permissions) must be VISIBLE — the caller's save()
        already returned True, so without this the trail would show
        save_begin with no save_commit, no counter would move, and the
        operator would discover the stale checkpoint only at restore
        time.  Synchronous saves propagate instead (the caller is
        there to see the exception)."""
        try:
            self._commit(step, state)
        except Exception as e:          # noqa: BLE001 — alert, don't die
            self._counter("bf_ckpt_save_failed_total",
                          "background checkpoint commits that raised "
                          "(disk full, lost mount, permissions)")
            self._event(step, "save_failed", detail=repr(e)[:200])

    def wait(self) -> None:
        """Block until the in-flight commit (if any) is durable."""
        with self._lock:
            t = self._pending
        if t is not None:
            t.join()

    def close(self) -> None:
        self.wait()
        if self._trail is not None and self._owns_trail:
            self._trail.close()
            self._trail = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- the commit protocol ------------------------------------------------

    def _commit(self, step: int, state: Dict[str, Any]) -> str:
        """Write shards → fsync → replicate → atomically publish the
        manifest → prune retention.  Runs on the background thread in
        async mode; any kill before the final ``os.replace`` leaves the
        previous checkpoint as the newest durable one."""
        t0 = time.perf_counter()
        per_rank, global_payload, size = split_shards(state, self.size)
        sdir = os.path.join(self.directory, step_dir_name(step))
        os.makedirs(sdir, exist_ok=True)
        shards: Dict[str, dict] = {}
        total = 0
        for r, payload in enumerate(per_rank):
            name = shard_name(r)
            crc, nbytes = write_shard(os.path.join(sdir, name), payload)
            shards[name] = {"crc32": crc, "bytes": nbytes, "rank": r}
            total += nbytes
        if global_payload:
            crc, nbytes = write_shard(os.path.join(sdir, GLOBAL_SHARD),
                                      global_payload)
            shards[GLOBAL_SHARD] = {"crc32": crc, "bytes": nbytes,
                                    "rank": None}
            total += nbytes
        replica_map: Dict[str, List[str]] = {}
        if self.replicas:
            from . import redundancy as _red
            replica_map = _red.push_replicas(
                sdir, size, k=self.replicas,
                topology=state.get("meta", {}).get("topology"))
        manifest = {
            "version": _state.FLEET_STATE_VERSION,
            "step": int(step),
            "size": int(size),
            "bytes": int(total),
            "shards": shards,
            "replicas": replica_map,
            "meta": state.get("meta", {}),
        }
        tmp = os.path.join(sdir, MANIFEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        # THE publish: durable shards first, one atomic rename after
        os.replace(tmp, os.path.join(sdir, MANIFEST_NAME))
        _fsync_dir(sdir)
        _fsync_dir(self.directory)
        self.last_durable = int(step)
        save_s = time.perf_counter() - t0
        self._prune()
        if _metrics.enabled():
            _metrics.gauge("bf_ckpt_save_seconds",
                           "wall seconds of the last durable fleet "
                           "checkpoint commit").set(save_s)
            _metrics.gauge("bf_ckpt_bytes",
                           "total shard bytes of the last durable fleet "
                           "checkpoint").set(float(total))
            _metrics.gauge("bf_ckpt_last_durable_step",
                           "step index of the newest durable fleet "
                           "checkpoint manifest").set(float(step))
            _metrics.counter("bf_ckpt_saves_total",
                             "durable fleet checkpoint commits").inc()
        if self._trail is not None:
            self._trail.write_save(step, durable_step=step, nbytes=total,
                                   save_s=save_s, shards=len(shards))
            self._event(step, "save_commit")
        return os.path.join(sdir, MANIFEST_NAME)

    def _prune(self) -> None:
        """Retention: keep the newest ``keep`` durable checkpoints; also
        sweep unpublished (torn) step dirs older than the newest durable
        one — they can never become durable."""
        durable = durable_manifests(self.directory)
        for _, mpath in durable[:-self.keep]:
            shutil.rmtree(os.path.dirname(mpath), ignore_errors=True)
        if not durable:
            return
        newest = os.path.dirname(durable[-1][1])
        try:
            entries = sorted(os.listdir(self.directory))
        except OSError:
            return
        for name in entries:
            path = os.path.join(self.directory, name)
            if (name.startswith("step-") and path < newest
                    and not os.path.exists(
                        os.path.join(path, MANIFEST_NAME))):
                shutil.rmtree(path, ignore_errors=True)
