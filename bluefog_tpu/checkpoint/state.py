"""Complete fleet-state capture: everything a decentralized run carries.

The reference framework's supported durable-state pattern is
``torch.save`` on rank 0 plus ``broadcast_parameters`` (SURVEY.md §5.4) —
which only works because its optimizers carry no cross-step runtime
state.  Twelve PRs of runtime machinery changed that here: a mid-run
fleet also holds per-bucket error-feedback residuals and CHOCO estimates
(``compress/exchange.py``), overlapped in-flight flat buffers
(``strategies.delayed_*``), both window double buffers
(``win_state_dict``), the fault-plan/membership step index and the
:class:`~..resilience.membership.ElasticMembership` directory, the
controller's decision state (``SwitchableSchedule`` mode remap, CHOCO
``gamma_scale``, per-knob cooldowns), RNG keys, serving watermarks, and
the host metrics counters.  :func:`fleet_state_dict` composes ALL of it
into one versioned snapshot so a resumed run is bit-exact versus never
stopping, with every knob on — and :func:`load_fleet_state` reapplies
each section to a freshly constructed run.

Layout contract: the snapshot separates **arrays** (a nested pytree of
host-copied numpy arrays — the shardable payload ``checkpoint/snapshot``
writes per rank) from **meta** (a JSON-able dict — the manifest-resident
description: step index, fault-plan events, membership directory,
controller knobs, counters).  Array leaves whose leading dimension is
the fleet size are per-rank shards; everything else (RNG key data)
rides the shared ``global`` shard.  Restore is template-driven, exactly
like ``load_win_state_dict``: the snapshot carries data, not structure —
the resuming process builds the same optimizer/windows first and the
leaves flow back in by tree path.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = ["FLEET_STATE_VERSION", "fleet_state_dict", "load_fleet_state",
           "FleetRestore", "flat_arrays", "membership_state",
           "restore_membership", "plan_state", "restore_plan",
           "async_cadence_state", "restore_async_cadence",
           "controller_state", "apply_controller_state",
           "serving_state", "apply_serving_state"]

FLEET_STATE_VERSION = 1

# tree-path prefixes of the arrays sections (the shard keys the manifest
# records; restore matches templates against these)
TRAIN_PREFIX = "['train']"
WINDOWS_PREFIX = "['windows']"
RNG_PREFIX = "['rng']"

# the CHOCO γ-scale leaf the controller plumbing re-injects into the
# carried compression state every step (optim/wrappers.py
# ``_with_control_state``): present in a STEPPED opt state, absent from
# an init-fresh one — optional on both sides of the template match, its
# value recorded in (and restored from) the "control" meta section
_INJECTED_GAMMA = "['compress']['gamma_scale']"


def _keystr(path) -> str:
    import jax
    return jax.tree_util.keystr(path)


def flat_arrays(state: Dict[str, Any]) -> Dict[str, np.ndarray]:
    """Flatten a snapshot's ``arrays`` section (or hand back an
    already-flat ``{tree path: array}`` dict, the form
    ``restore.restore_latest`` returns)."""
    import jax
    arrays = state.get("arrays", {})
    if arrays and all(isinstance(k, str) and k.startswith("[")
                      for k in arrays):
        return dict(arrays)
    flat, _ = jax.tree_util.tree_flatten_with_path(arrays)
    return {_keystr(p): np.asarray(v) for p, v in flat}


def _host_copy(tree):
    """Device -> host COPIES (the copy-on-save boundary): the donated
    device buffers keep stepping while the writer drains these."""
    import jax
    return jax.tree.map(lambda a: np.array(a, copy=True), tree)


# ---------------------------------------------------------------------------
# Section serializers (host dicts, JSON-able)
# ---------------------------------------------------------------------------

def membership_state(m) -> Dict[str, Any]:
    """JSON-able snapshot of an :class:`ElasticMembership` directory."""
    return {
        "size": int(m.size),
        "suspect_after": int(m.cfg.suspect_after),
        "confirm_after": int(m.cfg.confirm_after),
        "quorum": m.quorum,
        "states": {str(r): s for r, s in sorted(m.states.items())},
        "synced": sorted(int(r) for r in m._synced),
        "announced_at": {str(r): int(s)
                         for r, s in sorted(m._announced_at.items())},
        "transitions": [[int(t), int(r), s] for t, r, s in m.transitions],
    }


def restore_membership(meta: Dict[str, Any]):
    """Rebuild the :class:`ElasticMembership` directory a snapshot
    recorded — states, sync marks, announcement times, and the audit
    log, so the resumed observer continues mid-admission."""
    from ..resilience.membership import ElasticMembership, LivenessConfig
    m = ElasticMembership(
        int(meta["size"]),
        cfg=LivenessConfig(int(meta["suspect_after"]),
                           int(meta["confirm_after"])),
        quorum=meta.get("quorum"))
    m.states = {int(r): s for r, s in meta["states"].items()}
    m._synced = set(int(r) for r in meta.get("synced", ()))
    m._announced_at = {int(r): int(s)
                       for r, s in meta.get("announced_at", {}).items()}
    m.transitions = [(int(t), int(r), s)
                     for t, r, s in meta.get("transitions", ())]
    return m


def async_cadence_state(scheduler) -> Dict[str, Any]:
    """JSON-able snapshot of an async-training
    :class:`~..async_train.CadenceScheduler` — the period vector,
    staleness cap, refusal count, and throttle set.  Together with the
    auto-captured window section (which already carries the push-sum
    associated-P scalars and BOTH buffers of every double-buffered
    window), this is everything a mid-asynchrony resume needs to be
    bit-exact (docs/async.md "Checkpointing")."""
    return scheduler.state_dict()


def restore_async_cadence(meta: Dict[str, Any]):
    """Rebuild the :class:`CadenceScheduler` a snapshot recorded —
    periods, cap, refusals, throttles — so the resumed run fires the
    same ranks at the same ticks."""
    from ..async_train import CadenceScheduler
    sched = CadenceScheduler(int(meta["size"]),
                             base_period=int(meta["base_period"]),
                             max_staleness=int(meta["max_staleness"]))
    sched.load_state_dict(meta)
    return sched


def plan_state(plan, plan_step: int) -> Dict[str, Any]:
    """JSON-able snapshot of a :class:`CompiledFaultPlan` — its event
    list plus the step index the run had advanced the tables to.  The
    tables themselves are deterministic from the events, so restore
    re-lowers instead of shipping [T, N, N] float tables."""
    return {
        "size": int(plan.size),
        "horizon": int(plan.horizon),
        "step": int(plan_step),
        "events": [{"kind": ev.kind, "rank": int(ev.rank),
                    "step": int(ev.step),
                    "until": None if ev.until is None else int(ev.until),
                    "peer": None if ev.peer is None else int(ev.peer),
                    "factor": float(ev.factor)}
                   for ev in plan.events],
    }


def restore_plan(meta: Dict[str, Any]):
    """Re-lower the fault plan a snapshot recorded.  Returns
    ``(CompiledFaultPlan, plan_step)`` — the resumed run indexes the
    tables from ``plan_step``, so mid-episode faults/joins continue
    exactly where the killed run left them."""
    from ..resilience.faults import FaultEvent, FaultPlan
    plan = FaultPlan(int(meta["size"]), int(meta["horizon"]))
    plan.events = [FaultEvent(kind=e["kind"], rank=int(e["rank"]),
                              step=int(e["step"]), until=e.get("until"),
                              peer=e.get("peer"),
                              factor=float(e.get("factor", 1.0)))
                   for e in meta.get("events", ())]
    return plan.compile(), int(meta.get("step", 0))


def controller_state(controller) -> Dict[str, Any]:
    """JSON-able snapshot of an :class:`~..control.actuate.Actuator` (or
    full ``Controller``): the schedule mode, the γ scale riding
    ``opt.control_knobs``, and — when a sensing engine is attached — the
    PolicyEngine's hysteresis state (cooldowns, healthy streak,
    deviation flag), so a restored controller neither re-fires a
    decision inside a cooldown nor forgets it had intervened."""
    out: Dict[str, Any] = {
        "sched_mode": int(getattr(controller, "sched_mode", 0)),
        "mode_name": getattr(controller, "mode_name", None),
        "gamma_scale": float(getattr(controller, "gamma_scale", 1.0)),
    }
    engine = getattr(controller, "engine", None)
    if engine is not None:
        out["engine"] = {
            "sched_mode": engine.sched_mode,
            "base_mode": engine.base_mode,
            "gamma_scale": float(engine.gamma_scale),
            "healthy_streak": int(engine._healthy_streak),
            "deviated": bool(engine._deviated),
            "cooldowns": {k: int(v) for k, v in engine._last_step.items()},
        }
    return out


def apply_controller_state(controller, meta: Dict[str, Any]) -> None:
    """Reapply :func:`controller_state` onto a freshly built actuator/
    controller (same schedule stack).  The knobs are traced data, so
    this never recompiles the step."""
    controller.sched_mode = int(meta.get("sched_mode", 0))
    gamma = float(meta.get("gamma_scale", 1.0))
    knobs = getattr(getattr(controller, "opt", None), "control_knobs", None)
    if knobs is not None:
        knobs["gamma_scale"] = gamma
    engine = getattr(controller, "engine", None)
    saved = meta.get("engine")
    if engine is not None and saved is not None:
        engine.sched_mode = saved.get("sched_mode", engine.sched_mode)
        engine.base_mode = saved.get("base_mode", engine.base_mode)
        engine.gamma_scale = float(saved.get("gamma_scale", 1.0))
        engine._healthy_streak = int(saved.get("healthy_streak", 0))
        engine._deviated = bool(saved.get("deviated", False))
        engine._last_step = {k: int(v)
                             for k, v in saved.get("cooldowns", {}).items()}


def serving_state(replicas) -> Dict[str, Any]:
    """JSON-able snapshot of a serving :class:`ReplicaSet`'s host state:
    per-replica staleness watermarks plus the publisher's
    ``last_published`` stream headers — what a restarted serving tier
    needs to keep refusing requests past the staleness bound instead of
    optimistically serving pre-crash weights as fresh."""
    marks = getattr(replicas, "_watermark", {}) or {}
    pub = getattr(replicas, "publisher", None)
    last_pub = dict(getattr(pub, "last_published", {}) or {})
    return {
        "watermark": {str(r): v for r, v in marks.items()},
        "last_published": {str(r): v for r, v in last_pub.items()},
    }


def apply_serving_state(replicas, meta: Dict[str, Any]) -> None:
    """Reapply :func:`serving_state` onto a freshly built ReplicaSet
    (same publisher/window layout)."""
    marks = meta.get("watermark", {})
    if hasattr(replicas, "_watermark"):
        for r in list(replicas._watermark):
            if str(r) in marks:
                replicas._watermark[r] = marks[str(r)]
    pub = getattr(replicas, "publisher", None)
    if pub is not None and hasattr(pub, "last_published"):
        for r, v in meta.get("last_published", {}).items():
            pub.last_published[int(r)] = v


def _rng_sections(rng) -> Tuple[Dict[str, np.ndarray], Dict[str, str]]:
    """Split a PRNG key (or flat dict of keys) into host key-data arrays
    plus the impl names needed to rebuild typed keys."""
    import jax
    if rng is None:
        return {}, {}
    if not isinstance(rng, dict):
        rng = {"key": rng}
    data, impls = {}, {}
    for name, key in rng.items():
        if jax.dtypes.issubdtype(getattr(key, "dtype", None),
                                 jax.dtypes.prng_key):
            impls[name] = str(jax.random.key_impl(key))
            data[name] = np.array(jax.random.key_data(key), copy=True)
        else:
            # old-style uint32 raw key: plain array round-trip
            data[name] = np.array(key, copy=True)
    return data, impls


def _restore_rng(data: Dict[str, np.ndarray], impls: Dict[str, str]):
    import jax
    out = {}
    for name, arr in data.items():
        impl = impls.get(name)
        if impl is not None:
            out[name] = jax.random.wrap_key_data(
                np.asarray(arr, np.uint32), impl=impl)
        else:
            out[name] = np.asarray(arr)
    if set(out) == {"key"}:
        return out["key"]
    return out or None


# ---------------------------------------------------------------------------
# The composed snapshot
# ---------------------------------------------------------------------------

def fleet_state_dict(step: int, train=None, *, rng=None,
                     windows: Optional[bool] = None,
                     plan=None, plan_step: Optional[int] = None,
                     membership=None, controller=None, replicas=None,
                     cadence=None,
                     counters: bool = True, topology: bool = True,
                     extra: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """Compose the versioned, manifest-described fleet snapshot.

    ``step``: the number of COMPLETED steps — the resumed run executes
    step index ``step`` next.  ``train``: the donated train state
    pytree in global view (e.g. ``{"variables": ..., "opt_state": ...}``
    — the opt state brings the carried EF residuals / CHOCO estimates /
    overlap in-flight buffers along for free, they are ordinary leaves).
    ``rng``: a PRNG key or ``{name: key}`` dict.  ``windows``: ``None``
    auto-captures :func:`win_state_dict` when windows exist (BOTH
    buffers of every double-buffered window), ``False`` skips,
    ``True`` requires.  ``plan``/``plan_step``: the live
    :class:`CompiledFaultPlan` and the step its tables had reached
    (default ``step``).  ``membership`` / ``controller`` / ``replicas`` /
    ``cadence``: the host-side directories whose decision state must
    survive the restart (``cadence`` is the async-training
    :class:`~..async_train.CadenceScheduler`; the window section it
    pairs with — push-sum P included — is auto-captured).  ``counters`` records the metrics-registry snapshot;
    ``topology`` records the compiled mixing matrix (the elastic-restore
    and neighbor-replica fan-outs read it from the manifest).

    Returns ``{"version", "arrays", "meta"}`` — every array leaf a HOST
    COPY (safe to write while the donated device buffers keep stepping).
    """
    from ..context import ctx, is_initialized

    arrays: Dict[str, Any] = {}
    meta: Dict[str, Any] = {"step": int(step)}
    if train is not None:
        arrays["train"] = _host_copy(train)
    if windows is None or windows is True:
        from ..ops import windows as _win
        if _win.windows_exist():
            arrays["windows"] = _host_copy(_win.win_state_dict())
        elif windows is True:
            raise ValueError(
                "windows=True but no windows are registered "
                "(win_create first, or pass windows=False)")
    rng_data, rng_impls = _rng_sections(rng)
    if rng_data:
        arrays["rng"] = rng_data
        meta["rng_impl"] = rng_impls
    if is_initialized():
        cx = ctx()
        meta["size"] = int(cx.size)
        if topology:
            meta["topology"] = np.asarray(
                cx.compiled_topology.weight_matrix, np.float64).tolist()
    if plan is not None:
        meta["plan"] = plan_state(plan, step if plan_step is None
                                  else plan_step)
    if membership is not None:
        meta["membership"] = membership_state(membership)
    if controller is not None:
        meta["control"] = controller_state(controller)
    if replicas is not None:
        meta["serving"] = serving_state(replicas)
    if cadence is not None:
        meta["async_cadence"] = async_cadence_state(cadence)
    if counters:
        from ..observability import metrics as _metrics
        meta["counters"] = _metrics.registry.snapshot()
    if extra:
        meta["extra"] = dict(extra)
    meta["sections"] = sorted(arrays) + sorted(
        k for k in ("plan", "membership", "control", "serving",
                    "async_cadence")
        if k in meta)
    return {"version": FLEET_STATE_VERSION, "arrays": arrays, "meta": meta}


class FleetRestore:
    """What :func:`load_fleet_state` hands back: the re-deviced train
    tree, the resume step, and the rebuilt host directories."""

    __slots__ = ("train", "step", "rng", "membership", "plan", "plan_step",
                 "meta")

    def __init__(self, train, step, rng, membership, plan, plan_step, meta):
        self.train = train
        self.step = step
        self.rng = rng
        self.membership = membership
        self.plan = plan
        self.plan_step = plan_step
        self.meta = meta


def _device_put_leaves(template, leaves: List[np.ndarray]):
    import jax
    import jax.numpy as jnp
    from ..context import is_initialized
    from ..ops import api as _api
    sharding = _api.rank_sharding() if is_initialized() else None
    out = []
    for t, leaf in zip(jax.tree.leaves(template), leaves):
        a = jnp.asarray(np.asarray(leaf), dtype=getattr(t, "dtype", None))
        if sharding is not None and a.ndim >= 1:
            a = jax.device_put(a, sharding)
        out.append(a)
    return jax.tree.unflatten(jax.tree.structure(template), out)


def load_fleet_state(state: Dict[str, Any], *, train_template=None,
                     optimizer=None, controller=None,
                     windows: str = "auto",
                     strict: bool = True) -> FleetRestore:
    """Reapply a :func:`fleet_state_dict` snapshot (or the flat-arrays
    form ``restore.restore_latest`` returns).

    ``train_template``: a like-structured pytree (the freshly built
    ``{"variables", "opt_state"}``) the train leaves flow back into —
    required when the snapshot carries a train section (the snapshot
    stores data by tree path, not structure).  ``optimizer`` /
    ``controller``: reapply the γ scale and schedule-mode knobs
    (traced data — reapplying never recompiles).  ``windows``:
    ``"auto"`` restores the window section into registered windows when
    both exist, ``"require"`` raises when either side is missing,
    ``"skip"`` leaves windows alone.

    Returns a :class:`FleetRestore`; ``strict=True`` raises on a train
    template/snapshot leaf mismatch instead of silently resuming with
    half-restored state."""
    import jax
    flat = flat_arrays(state)
    meta = dict(state.get("meta", {}))
    step = int(meta.get("step", 0))

    train = None
    train_keys = {k: v for k, v in flat.items()
                  if k.startswith(TRAIN_PREFIX)}
    if train_keys:
        if train_template is None:
            if strict:
                raise ValueError(
                    "snapshot carries a train section: pass "
                    "train_template= (the freshly built train-state "
                    "pytree) so the leaves can flow back in by tree path")
        else:
            tpl_flat, _ = jax.tree_util.tree_flatten_with_path(
                train_template)
            leaves = []
            for p, t in tpl_flat:
                key = TRAIN_PREFIX + _keystr(p)
                if key not in train_keys:
                    if key.endswith(_INJECTED_GAMMA):
                        # the controller's per-step-injected γ leaf: a
                        # stepped template carries it, an init-fresh
                        # snapshot may not — synthesize from the
                        # recorded knob (same thing the optimizer's
                        # _with_control_state does every step)
                        gamma = float(meta.get("control", {})
                                      .get("gamma_scale", 1.0))
                        leaves.append(np.full(
                            t.shape, gamma,
                            getattr(t, "dtype", np.float32)))
                        continue
                    if not strict:
                        # tolerant resume across a small layout delta:
                        # a leaf the snapshot never saw keeps its
                        # fresh-init template value
                        leaves.append(np.asarray(t))
                        continue
                    raise ValueError(
                        f"train template leaf {key} missing from the "
                        f"snapshot (layout changed? rebuild the "
                        f"optimizer with the same fuse/overlap/"
                        f"compression knobs the snapshot ran with)")
                leaves.append(train_keys[key])
            extra_keys = set(train_keys) - {
                TRAIN_PREFIX + _keystr(p) for p, _ in tpl_flat}
            # the injected γ leaf is likewise tolerated in the snapshot
            # of a STEPPED state restored into an init-fresh template
            extra_keys = {k for k in extra_keys
                          if not k.endswith(_INJECTED_GAMMA)}
            if extra_keys and strict:
                raise ValueError(
                    f"snapshot train leaves not in the template: "
                    f"{sorted(extra_keys)[:4]}")
            train = _device_put_leaves(train_template, leaves)

    win_keys = {k for k in flat if k.startswith(WINDOWS_PREFIX)}
    if windows not in ("auto", "require", "skip"):
        raise ValueError(f"windows must be auto|require|skip, "
                         f"got {windows!r}")
    if win_keys and windows != "skip":
        from ..ops import windows as _win
        if not _win.windows_exist():
            if windows == "require" or strict:
                raise ValueError(
                    "snapshot carries window state but no windows are "
                    "registered — win_create the same windows first "
                    "(or pass windows='skip')")
        else:
            tpl = _win.win_state_dict()
            tpl_flat, tdef = jax.tree_util.tree_flatten_with_path(tpl)
            leaves = []
            ok = True
            for p, t in tpl_flat:
                key = WINDOWS_PREFIX + _keystr(p)
                if key not in flat:
                    if windows == "require" or strict:
                        raise ValueError(
                            f"window snapshot missing leaf {key} "
                            f"(window layout changed?)")
                    ok = False
                    break
                leaves.append(flat[key])
            if ok:
                _win.load_win_state_dict(
                    jax.tree.unflatten(tdef, leaves))
    elif windows == "require" and not win_keys:
        raise ValueError("windows='require' but the snapshot has no "
                         "window section")

    rng_keys = {k[len(RNG_PREFIX):].strip("[']\""): v
                for k, v in flat.items() if k.startswith(RNG_PREFIX)}
    rng = _restore_rng(rng_keys, meta.get("rng_impl", {}))

    membership = (restore_membership(meta["membership"])
                  if "membership" in meta else None)
    plan, plan_step = (restore_plan(meta["plan"])
                       if "plan" in meta else (None, None))
    if controller is not None and "control" in meta:
        apply_controller_state(controller, meta["control"])
    elif optimizer is not None and "control" in meta:
        knobs = getattr(optimizer, "control_knobs", None)
        if knobs is not None:
            knobs["gamma_scale"] = float(
                meta["control"].get("gamma_scale", 1.0))
    return FleetRestore(train, step, rng, membership, plan, plan_step, meta)
