"""Neighbor redundancy: each rank's shard survives its host's disk.

A fleet checkpoint is only as durable as its weakest local directory —
on a real pod each rank writes its own shard to its own storage, and a
preempted host takes that shard with it.  The repair is the same move
the runtime makes everywhere else: trust your OUT-NEIGHBORS.  Each
rank's shard is additionally replicated to ``k`` of its out-neighbors
in the compiled mixing topology (the manifest records who holds what),
so a lost or torn local shard restores from a neighbor replica with the
checksum re-verified (``checkpoint/restore.py``).

Transport: durable byte-copies under
``<step_dir>/replicas/rank-<r>.held-by-<n>.npz`` (fsynced, renamed into
place) — on a shared filesystem this directly models "neighbor n's
directory holds r's shard", and an object store mounts the same way.
The window subsystem was considered and rejected as the replica wire:
window payloads ride the f32 gossip path (optionally quantized), which
re-encodes mixed-dtype shard leaves — a replica that is not
byte-faithful to its primary cannot share its checksum and silently
breaks the bit-exact-resume contract.  Replication is a file-transport
problem; the mixing topology only decides WHO holds the copy
(docs/checkpoint.md "Neighbor redundancy").
"""

import os
import shutil
from typing import Dict, List

import numpy as np

from . import snapshot as _snap

__all__ = ["out_neighbors", "replica_name", "push_replicas",
           "replica_holders", "replica_holders_by_name"]


def out_neighbors(topology, rank: int, size: int) -> List[int]:
    """Out-neighbors of ``rank`` under a mixing matrix (``W[src, dst]``
    != 0 convention, ``parallel/topology.py``) — the ranks that already
    receive its gossip every step, and therefore the natural replica
    holders.  Falls back to the ring successor when no matrix is
    available (a fleet of one holds no replicas)."""
    if topology is not None:
        W = np.asarray(topology, np.float64)
        nbrs = [int(j) for j in np.nonzero(W[int(rank)])[0]
                if int(j) != int(rank)]
        if nbrs:
            return nbrs
    if size <= 1:
        return []
    return [(int(rank) + 1) % int(size)]


def replica_name(rank: int, holder: int) -> str:
    return f"rank-{int(rank)}.held-by-{int(holder)}.npz"


def _copy_durable(primary: str, step_dir: str, rel: str) -> None:
    tmp = os.path.join(step_dir, rel + ".tmp")
    with open(primary, "rb") as src, open(tmp, "wb") as dst:
        shutil.copyfileobj(src, dst)
        dst.flush()
        os.fsync(dst.fileno())
    os.replace(tmp, os.path.join(step_dir, rel))


def push_replicas(step_dir: str, size: int, *, k: int = 1,
                  topology=None) -> Dict[str, List[str]]:
    """Replicate every primary shard under ``step_dir`` to ``k``
    out-neighbors.  Returns the manifest's ``replicas`` map:
    ``{primary shard name: [relative replica paths]}``.  Replica files
    are durable byte-copies (fsynced before rename), so a replica's
    checksum IS the primary's — restore verifies both against the same
    manifest entry.

    The ``global`` shard (RNG keys, unsharded leaves) is replicated
    too, to the writer rank's (rank 0's) out-neighbors — without it a
    torn ``global.npz`` would abandon the whole manifest no matter how
    many rank-shard replicas survive."""
    rdir = os.path.join(step_dir, "replicas")
    os.makedirs(rdir, exist_ok=True)
    out: Dict[str, List[str]] = {}
    for r in range(int(size)):
        primary = os.path.join(step_dir, _snap.shard_name(r))
        if not os.path.exists(primary):
            continue
        holders = out_neighbors(topology, r, size)[:max(0, int(k))]
        paths = []
        for h in holders:
            rel = os.path.join("replicas", replica_name(r, h))
            _copy_durable(primary, step_dir, rel)
            paths.append(rel)
        if paths:
            out[_snap.shard_name(r)] = paths
    gprimary = os.path.join(step_dir, _snap.GLOBAL_SHARD)
    if os.path.exists(gprimary):
        paths = []
        for h in out_neighbors(topology, 0, size)[:max(0, int(k))]:
            rel = os.path.join("replicas", f"global.held-by-{h}.npz")
            _copy_durable(gprimary, step_dir, rel)
            paths.append(rel)
        if paths:
            out[_snap.GLOBAL_SHARD] = paths
    return out


def replica_holders(manifest: dict, rank) -> List[str]:
    """The relative replica paths the manifest records for ``rank``'s
    shard — ``rank=None`` for the global shard (empty when redundancy
    was off)."""
    name = _snap.GLOBAL_SHARD if rank is None else _snap.shard_name(rank)
    return replica_holders_by_name(manifest, name)


def replica_holders_by_name(manifest: dict, name: str) -> List[str]:
    """The replica paths for one primary shard by manifest name."""
    return list(manifest.get("replicas", {}).get(name, ()))
