"""Restore: newest durable manifest, shard verification, elastic resize.

Three escalation levels, each one failure class deeper:

1. **Clean restore** (:func:`restore_latest`): newest manifest, every
   shard CRC-verified, arrays re-stacked to the global view.
2. **Repair**: a missing/torn shard (checksum mismatch) restores from a
   neighbor replica recorded in the manifest (byte-copy — same CRC);
   the repaired primary is optionally written back.  A manifest with an
   unrecoverable shard is abandoned entirely and the previous durable
   manifest is used — a kill mid-save can never produce a Franken-state
   mixing two checkpoints.
3. **Elastic restore** (:func:`elastic_restore`): the fleet comes back
   at N′ ≠ N.  Shrink merges the orphaned ranks' shards into the
   survivors by consensus-average (the PR 13 departure path: orphans
   are departures; the global parameter average is preserved exactly).
   Grow admits the new ranks through the bootstrap protocol with the
   checkpointed ranks as trusted in-neighbors: each new rank's state is
   the renormalized in-neighbor average under the regenerated mixing
   matrix.  Either way the regenerated matrix must pass the repair
   invariants — column (and, for symmetric families, row)
   stochasticity and a positive spectral gap — before the restore is
   handed back (:func:`check_restore_matrix`).
"""

import io
import os
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..observability import metrics as _metrics
from . import redundancy as _red
from . import snapshot as _snap
from . import state as _state

__all__ = ["RestoredFleet", "restore_latest", "elastic_restore",
           "ElasticRestore", "check_restore_matrix", "TornCheckpointError"]


class TornCheckpointError(RuntimeError):
    """No durable manifest could be fully verified (all candidates had
    unrecoverable shards)."""


class RestoredFleet:
    """A verified snapshot read back from disk: flat ``{tree path:
    array}`` arrays (feed to :func:`~.state.load_fleet_state`), the
    manifest meta, and the repair audit."""

    __slots__ = ("arrays", "meta", "step", "manifest_path", "repaired",
                 "fell_back")

    def __init__(self, arrays, meta, step, manifest_path, repaired,
                 fell_back):
        self.arrays = arrays
        self.meta = meta
        self.step = step
        self.manifest_path = manifest_path
        self.repaired = repaired          # [(rank, replica_path)]
        self.fell_back = fell_back        # manifests abandoned on the way

    # load_fleet_state accepts this directly via flat_arrays()
    def __getitem__(self, key):
        return {"arrays": self.arrays, "meta": self.meta}[key]

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default


def _event(trail, step, event, **kw):
    if trail is not None:
        trail.write_event(step, event, **kw)


def _count(name: str, help_: str, n: float = 1.0) -> None:
    if _metrics.enabled():
        _metrics.counter(name, help_).inc(n)


def _read_verified(path: str, want: int) -> Optional[bytes]:
    """The file's bytes when it exists and its CRC32 matches — one disk
    read serves both the checksum pass and the np.load that follows."""
    import zlib
    try:
        with open(path, "rb") as f:
            data = f.read()
    except OSError:
        return None
    return data if zlib.crc32(data) == want else None


def _verified_shard(sdir: str, name: str, entry: dict, manifest: dict,
                    *, repair: bool, trail, step: int
                    ) -> Tuple[Optional[bytes], Optional[str]]:
    """Locate a readable copy of one shard: the primary when its CRC
    matches, else the first intact neighbor replica (optionally copied
    back over the primary).  Returns ``(payload_bytes, replica_used)``
    or ``(None, None)`` when unrecoverable."""
    primary = os.path.join(sdir, name)
    want = int(entry["crc32"])
    data = _read_verified(primary, want)
    if data is not None:
        return data, None
    _count("bf_ckpt_torn_shards_total",
           "primary shards found missing or checksum-torn at restore")
    _event(trail, step, "torn_shard",
           rank=entry.get("rank"), detail=name)
    for rel in _red.replica_holders_by_name(manifest, name):
        data = _read_verified(os.path.join(sdir, rel), want)
        if data is not None:
            if repair:
                try:
                    tmp = primary + ".tmp"
                    with open(tmp, "wb") as f:
                        f.write(data)
                    os.replace(tmp, primary)
                except OSError:
                    pass
            _count("bf_ckpt_replica_repairs_total",
                   "shards restored from a neighbor replica")
            _event(trail, step, "replica_repair",
                   rank=entry.get("rank"), detail=rel)
            return data, rel
    return None, None


def _load_verified(manifest_path: str, *, repair: bool, trail
                   ) -> Optional[Tuple[Dict[str, np.ndarray], dict, list]]:
    """Load + verify every shard a manifest names; None when any shard
    is unrecoverable (the caller falls back to an older manifest)."""
    manifest = _snap.load_manifest(manifest_path)
    if manifest is None:
        return None
    sdir = os.path.dirname(manifest_path)
    step = int(manifest["step"])
    size = int(manifest["size"])
    per_rank: List[Optional[Dict[str, np.ndarray]]] = [None] * size
    global_payload: Dict[str, np.ndarray] = {}
    repaired = []
    for name, entry in manifest["shards"].items():
        data, replica = _verified_shard(sdir, name, entry, manifest,
                                        repair=repair, trail=trail,
                                        step=step)
        if data is None:
            return None
        with np.load(io.BytesIO(data), allow_pickle=False) as z:
            payload = {k: np.array(z[k]) for k in z.files}
        if replica is not None:
            repaired.append((entry.get("rank"), replica))
        if entry.get("rank") is None:
            global_payload.update(payload)
        else:
            per_rank[int(entry["rank"])] = payload
    arrays: Dict[str, np.ndarray] = {}
    live = [p for p in per_rank if p is not None]
    if live:
        keys = sorted(live[0])
        for p in live:
            if sorted(p) != keys:
                return None          # shards from different layouts
        for k in keys:
            arrays[k] = np.stack([p[k] for p in per_rank
                                  if p is not None])
    arrays.update(global_payload)
    return arrays, manifest, repaired


def restore_latest(directory: str, *, repair: bool = True,
                   trail=None) -> RestoredFleet:
    """Restore the newest durable checkpoint under ``directory``.

    Walks manifests newest → oldest; per manifest, every shard is
    CRC-verified with neighbor-replica fallback.  A manifest with an
    unrecoverable shard is abandoned (the kill-mid-save guarantee:
    restore always lands on a COMPLETE checkpoint).  Raises
    :class:`TornCheckpointError` when nothing survives and
    ``FileNotFoundError`` when no manifest was ever published."""
    manifests = _snap.durable_manifests(directory)
    if not manifests:
        raise FileNotFoundError(
            f"no durable checkpoint manifest under {directory}")
    fell_back = []
    for step, mpath in reversed(manifests):
        loaded = _load_verified(mpath, repair=repair, trail=trail)
        if loaded is None:
            fell_back.append(mpath)
            _event(trail, step, "manifest_fallback",
                   detail=os.path.basename(os.path.dirname(mpath)))
            continue
        arrays, manifest, repaired = loaded
        _count("bf_ckpt_restores_total",
               "fleet restores served from a durable manifest")
        _event(trail, int(manifest["step"]), "restore",
               detail=os.path.basename(os.path.dirname(mpath)))
        return RestoredFleet(arrays, manifest.get("meta", {}),
                             int(manifest["step"]), mpath, repaired,
                             fell_back)
    raise TornCheckpointError(
        f"every durable manifest under {directory} had an unrecoverable "
        f"shard: {fell_back}")


# ---------------------------------------------------------------------------
# Elastic restore (N' != N)
# ---------------------------------------------------------------------------

def check_restore_matrix(W: np.ndarray, *, gap_floor: float = 1e-9,
                         atol: float = 1e-8) -> Dict[str, float]:
    """The repair invariants, asserted on a regenerated mixing matrix:
    non-negative entries, every column summing to 1 (mass
    conservation), rows too when the family is symmetric, and a
    spectral gap above ``gap_floor`` (consensus must contract on the
    restored fleet).  Returns the measured invariants; raises
    ``ValueError`` on violation."""
    from ..resilience.repair import spectral_gap
    W = np.asarray(W, np.float64)
    if W.ndim != 2 or W.shape[0] != W.shape[1]:
        raise ValueError(f"mixing matrix must be square, got {W.shape}")
    if (W < -atol).any():
        raise ValueError("regenerated mixing matrix has negative entries")
    col = W.sum(axis=0)
    if not np.allclose(col, 1.0, atol=atol):
        raise ValueError(
            f"regenerated mixing matrix is not column-stochastic "
            f"(column sums {col})")
    symmetric = bool(np.allclose(W, W.T, atol=1e-12))
    row = W.sum(axis=1)
    if symmetric and not np.allclose(row, 1.0, atol=atol):
        raise ValueError(
            f"symmetric-family matrix is not row-stochastic "
            f"(row sums {row})")
    gap = spectral_gap(W)
    if not gap > gap_floor:
        raise ValueError(
            f"regenerated mixing matrix spectral gap {gap} <= floor "
            f"{gap_floor}: consensus would not contract")
    return {"spectral_gap": float(gap), "symmetric": float(symmetric),
            "col_err": float(np.abs(col - 1.0).max()),
            "row_err": float(np.abs(row - 1.0).max())}


class ElasticRestore:
    """An N→N′ restore: resized flat arrays, the regenerated verified
    mixing matrix, a membership directory narrating the resize, and the
    measured invariants."""

    __slots__ = ("arrays", "meta", "step", "old_size", "new_size",
                 "matrix", "membership", "invariants", "base")

    def __init__(self, arrays, meta, step, old_size, new_size, matrix,
                 membership, invariants, base):
        self.arrays = arrays
        self.meta = meta
        self.step = step
        self.old_size = old_size
        self.new_size = new_size
        self.matrix = matrix
        self.membership = membership
        self.invariants = invariants
        self.base = base                  # the verified RestoredFleet

    def __getitem__(self, key):
        return {"arrays": self.arrays, "meta": self.meta}[key]

    def get(self, key, default=None):
        try:
            return self[key]
        except KeyError:
            return default


def _default_matrix(size: int) -> np.ndarray:
    from ..parallel.topology import ExponentialTwoGraph, mixing_matrix
    if size == 1:
        return np.ones((1, 1))
    return np.asarray(mixing_matrix(ExponentialTwoGraph(int(size))),
                      np.float64)


def elastic_restore(directory: str, new_size: int, *,
                    topology_matrix=None, gap_floor: float = 1e-9,
                    repair: bool = True, trail=None) -> ElasticRestore:
    """Restore the newest durable checkpoint onto a fleet of
    ``new_size`` ranks.

    **Shrink** (N′ < N): ranks N′.. are orphans — their shards merge
    into every survivor by consensus-average, ``x_r ← (1−α)·x_r +
    α·mean(orphans)`` with ``α = (N−N′)/N``, which preserves the global
    parameter average exactly (the quantity decentralized averaging
    conserves).  The membership directory records them as departures —
    the same path a runtime ``rank_leave`` takes.

    **Grow** (N′ > N): new ranks bootstrap from their trusted
    in-neighbors — the CHECKPOINTED ranks feeding them under the
    regenerated matrix, weights renormalized over those feeds (a new
    rank fed only by other new ranks falls back to the checkpointed
    fleet mean).  The directory records an announce → sync → activate
    admission per new rank.

    ``topology_matrix``: the N′-sized mixing matrix of the restored run
    (default: the exponential-2 family regenerated at N′).  The repair
    invariants are asserted on it before anything is returned.  Float
    (inexact-dtype) leaves merge; integer leaves (step counters,
    versions) take the survivor/neighbor values unaveraged."""
    new_size = int(new_size)
    if new_size < 1:
        raise ValueError(f"new_size must be >= 1, got {new_size}")
    base = restore_latest(directory, repair=repair, trail=trail)
    old_size = int(base.meta.get("size")
                   or _infer_size(base.arrays))
    W = (np.asarray(topology_matrix, np.float64)
         if topology_matrix is not None else _default_matrix(new_size))
    if W.shape != (new_size, new_size):
        raise ValueError(
            f"topology_matrix must be [{new_size}, {new_size}], "
            f"got {W.shape}")
    invariants = check_restore_matrix(W, gap_floor=gap_floor)

    from ..resilience.membership import ElasticMembership
    # grown ranks start as pre-allocated capacity slots so the restore
    # narrates their admission through the real announce/sync protocol
    membership = ElasticMembership(
        max(old_size, new_size),
        capacity=range(old_size, new_size) if new_size > old_size else ())
    step = base.step
    arrays: Dict[str, np.ndarray] = {}
    if new_size == old_size:
        arrays = dict(base.arrays)
    elif new_size < old_size:
        alpha = (old_size - new_size) / float(old_size)
        for r in range(new_size, old_size):
            membership.leave(r, step)
        for key, v in base.arrays.items():
            if key.startswith(_state.WINDOWS_PREFIX):
                continue      # see _is_sharded: windows recreate fresh
            if _is_sharded(v, old_size, key):
                keep = v[:new_size]
                if np.issubdtype(v.dtype, np.inexact):
                    orphan_mean = v[new_size:].mean(axis=0)
                    merged = ((1.0 - alpha) * keep.astype(np.float64)
                              + alpha * orphan_mean.astype(np.float64))
                    arrays[key] = merged.astype(v.dtype)
                else:
                    arrays[key] = keep
            else:
                arrays[key] = v
        _event(trail, step, "elastic_restore",
               detail=f"shrink {old_size}->{new_size}")
    else:
        # grow: per new rank, its checkpointed in-neighbors under W'
        feeds = {}
        for r in range(old_size, new_size):
            col = W[:, r].copy()
            col[r] = 0.0
            trusted = [(i, col[i]) for i in range(old_size)
                       if col[i] > 0]
            feeds[r] = trusted
            membership.admit_restored(r, step)
        for key, v in base.arrays.items():
            if key.startswith(_state.WINDOWS_PREFIX):
                continue      # see _is_sharded: windows recreate fresh
            if _is_sharded(v, old_size, key):
                rows = [v[r] for r in range(old_size)]
                ckpt_mean = v.astype(np.float64).mean(axis=0) \
                    if np.issubdtype(v.dtype, np.inexact) else None
                for r in range(old_size, new_size):
                    trusted = feeds[r]
                    if np.issubdtype(v.dtype, np.inexact):
                        if trusted:
                            tot = sum(w for _, w in trusted)
                            boot = sum(
                                v[i].astype(np.float64) * (w / tot)
                                for i, w in trusted)
                        else:
                            boot = ckpt_mean
                        rows.append(boot.astype(v.dtype))
                    else:
                        src = trusted[0][0] if trusted else 0
                        rows.append(v[src])
                arrays[key] = np.stack(rows)
            else:
                arrays[key] = v
        _event(trail, step, "elastic_restore",
               detail=f"grow {old_size}->{new_size}")
    meta = dict(base.meta)
    meta["size"] = new_size
    meta["topology"] = W.tolist()
    if new_size != old_size:
        # old-fleet-sized host sections must not survive the resize:
        # the recorded fault tables re-lower to [T, N], the membership
        # directory and serving watermarks are keyed by old ranks —
        # feeding any of them to the N' fleet gives shape mismatches or
        # silently wrong masks.  The resize-narrated directory is
        # `er.membership`; plans/watermarks re-derive on the new fleet.
        for stale in ("plan", "membership", "serving"):
            meta.pop(stale, None)
        if "sections" in meta:
            meta["sections"] = [s for s in meta["sections"]
                                if s not in ("plan", "membership",
                                             "serving", "windows")]
    return ElasticRestore(arrays, meta, step, old_size, new_size, W,
                          membership, invariants, base)


def _infer_size(arrays: Dict[str, np.ndarray]) -> int:
    dims: Dict[int, int] = {}
    for v in arrays.values():
        if v.ndim >= 1:
            dims[v.shape[0]] = dims.get(v.shape[0], 0) + 1
    if not dims:
        raise ValueError("restored checkpoint has no array leaves")
    return max(dims, key=lambda d: dims[d])


def _is_sharded(v: np.ndarray, size: int, key: str) -> bool:
    """Sharded = per-rank leaf.  Window state is deliberately EXCLUDED
    from elastic resizing: window buffer shapes are functions of the
    old topology's in-degree and would not match the restored fleet's
    windows — windows are bounded-staleness caches, recreated fresh by
    ``win_create`` on the new fleet (docs/checkpoint.md)."""
    if key.startswith(_state.WINDOWS_PREFIX):
        return False
    return v.ndim >= 1 and v.shape[0] == size
