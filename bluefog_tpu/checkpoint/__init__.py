"""Durable fleet state: crash-consistent decentralized checkpointing.

The resilience stack (PRs 1/13) survives rank death and elastic churn
*at runtime*; this subsystem survives the failure production actually
hits most — a full-fleet preemption or restart.  Four legs
(docs/checkpoint.md):

* **Complete capture** (``state.py``): :func:`fleet_state_dict` /
  :func:`load_fleet_state` compose a versioned snapshot of ALL runtime
  state — the donated train state with its carried compression/overlap
  buffers, both window buffers, the fault-plan step index and
  membership directory, controller decision state, RNG keys, serving
  watermarks, and a metrics snapshot — so a resumed run is bit-exact
  versus never stopping.
* **Crash consistency** (``snapshot.py``): :class:`FleetCheckpointer`
  saves off the critical path (host copy-on-save + background commit),
  committed by write-shards → fsync → atomically-publish-manifest with
  per-shard checksums: a kill mid-save always restores the previous
  complete checkpoint.
* **Neighbor redundancy** (``redundancy.py``): each rank's shard is
  replicated to ``k`` out-neighbors of the mixing topology; a lost
  local shard restores from a replica.
* **Elastic restore** (``restore.py``): restore onto N′ ≠ N — shrink
  merges orphans by consensus-average (the departure path), grow
  bootstraps new ranks from checkpointed in-neighbors, and the repair
  invariants are asserted on the regenerated mixing matrix.

The reference framework punts here (``torch.save`` on rank 0 +
``broadcast_parameters``, SURVEY §5.4) — this is capability beyond the
paper, and the last leg of the fault-tolerance + autoscaling north star.
"""

from .compat import (Checkpointer, restore_checkpoint,  # noqa: F401
                     save_checkpoint)
from .redundancy import (out_neighbors, push_replicas,  # noqa: F401
                         replica_holders, replica_holders_by_name,
                         replica_name)
from .restore import (ElasticRestore, RestoredFleet,  # noqa: F401
                      TornCheckpointError, check_restore_matrix,
                      elastic_restore, restore_latest)
from .snapshot import (ASYNC_ENV, DIR_ENV, EVERY_ENV,  # noqa: F401
                       GLOBAL_SHARD, KEEP_ENV, MANIFEST_NAME,
                       REPLICAS_ENV, FleetCheckpointer, durable_manifests,
                       file_crc32, load_manifest, resolve_async,
                       resolve_every, resolve_keep, resolve_replicas,
                       process_scoped_dir, shard_name, split_shards,
                       step_dir_name, write_shard)
from .state import (FLEET_STATE_VERSION, FleetRestore,  # noqa: F401
                    apply_controller_state, apply_serving_state,
                    async_cadence_state, controller_state,
                    fleet_state_dict, flat_arrays, load_fleet_state,
                    membership_state, plan_state, restore_async_cadence,
                    restore_membership, restore_plan, serving_state)

__all__ = [
    # capture
    "FLEET_STATE_VERSION", "fleet_state_dict", "load_fleet_state",
    "FleetRestore", "flat_arrays", "membership_state",
    "restore_membership", "plan_state", "restore_plan",
    "controller_state", "apply_controller_state", "serving_state",
    "apply_serving_state", "async_cadence_state", "restore_async_cadence",
    # crash-consistent snapshots
    "FleetCheckpointer", "MANIFEST_NAME", "GLOBAL_SHARD", "shard_name",
    "step_dir_name", "process_scoped_dir", "write_shard", "file_crc32",
    "durable_manifests",
    "load_manifest", "split_shards", "DIR_ENV", "EVERY_ENV", "KEEP_ENV",
    "REPLICAS_ENV", "ASYNC_ENV", "resolve_every", "resolve_keep",
    "resolve_replicas", "resolve_async",
    # redundancy
    "out_neighbors", "push_replicas", "replica_holders",
    "replica_holders_by_name", "replica_name",
    # restore
    "RestoredFleet", "restore_latest", "elastic_restore", "ElasticRestore",
    "check_restore_matrix", "TornCheckpointError",
    # single-tree compat (utils/checkpoint.py's historical surface)
    "Checkpointer", "save_checkpoint", "restore_checkpoint",
]
