"""Comm-path profiler: measured per-edge link costs and overlap efficiency.

PR 4 made training health observable and PR 7 turned per-rank series into
fleet verdicts, but every performance claim still rode on *trace-level
estimates* (ppermute counts, ``plan_bytes``).  This module is the missing
half of the sensing stack — it MEASURES the communication path:

* **Edge probe harness** (:func:`probe_edges`): time ``lax.ppermute``
  round-trips along every edge of the compiled topology at
  fusion-bucket-representative payload sizes and produce an
  :class:`EdgeCostMatrix` — per-``(src, dst)`` one-way latency (µs) and
  effective bandwidth (GB/s).  This is the measured per-edge cost model
  the ROADMAP's closed-loop controller needs to pick bandwidth-optimal
  exchange schedules for a direct-connect topology (arXiv:2309.13541) or
  decide when to switch to one-peer dynamic exponential graphs
  (arXiv:2110.13363).  The matrix exports three ways: ``bf_edge_*``
  registry gauges, a JSONL ``"edges"`` record on the metrics series, and
  a machine-readable JSON artifact (``BLUEFOG_EDGE_ARTIFACT``).

  Probe rounds are **step-indexed traced data**: one jitted program per
  (edge pair, payload size) whose round index is a traced scalar, so
  repeated rounds NEVER recompile, and the probe programs live in their
  own cache — the training step cache is untouched (zero step recompiles,
  asserted by ``tests/test_commprof.py``).

* **Measured overlap efficiency** (:func:`measure_overlap`,
  ``optimizer.probe_overlap``): split a step's exchange time into
  *hidden* (off the parameter critical path) vs *exposed* by timing three
  programs — the full step, a **pruned** step whose in-flight launch is
  dead-code-eliminated (the carried ``inflight`` state passes through
  unchanged, so XLA drops the ppermutes feeding it), and an
  exchange-only program that prices the full exchange.  ``efficiency =
  hidden / exchange_total``: ≈0 means the exchange sits on the critical
  path (synchronous), ≈1 means the delayed-mix pipeline took all of it
  off.  The sample stages an ``overlap_efficiency`` JSONL field
  (``phases.stage_field``) the health engine's ``overlap_collapse`` rule
  watches.

Virtual-mesh semantics: on the single-process CPU test mesh all "links"
share one host, so absolute numbers measure dispatch+execute cost, not
wire time — the ORDERING is still meaningful, and the synthetic delay
hook (``BLUEFOG_EDGE_PROBE_DELAY_US`` / ``inject_delay_s=``) lets the
smoke gate assert the whole pipeline ranks a seeded slow edge slowest
(``make profile-smoke``).
"""

import dataclasses
import json
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from . import metrics as _metrics
from . import phases as _phases
from .. import timeline as _tl

__all__ = [
    "EdgeCostMatrix", "OverlapSample", "probe_edges", "topology_edges",
    "export_edge_matrix", "measure_overlap", "resolve_injected_delays",
    "matrix_is_usable",
    "EDGE_ARTIFACT_ENV", "EDGE_DELAY_ENV", "EDGE_MAX_BYTES_ENV",
]

# when this process started sensing (import time = before any probe this
# run could have written): the staleness epoch matrix_is_usable gates
# artifact mtimes against — an artifact left behind by a PREVIOUS run
# (possibly a different fleet) must not be consumed as live link costs
_RUN_EPOCH = time.time()

EDGE_ARTIFACT_ENV = "BLUEFOG_EDGE_ARTIFACT"
EDGE_DELAY_ENV = "BLUEFOG_EDGE_PROBE_DELAY_US"
EDGE_MAX_BYTES_ENV = "BLUEFOG_EDGE_PROBE_MAX_BYTES"

# default probe payload cap: big enough to leave the latency regime on a
# real interconnect, small enough that a full exp2 probe stays sub-second
DEFAULT_MAX_PROBE_BYTES = 4 << 20


@dataclasses.dataclass
class EdgeCostMatrix:
    """Measured per-edge link costs for one topology.

    ``entries``: one dict per probed (directed edge, payload size) —
    ``{"src", "dst", "bytes", "rounds", "inner", "latency_us", "gbps"}``
    with ``latency_us`` the estimated ONE-WAY time (half the measured
    round trip) and ``gbps`` the one-way payload rate.  This nested-list
    form is exactly the JSONL ``"edges"`` record and the controller
    artifact — no separate wire schema.

    ``platform`` records what the probe actually priced (``"tpu"`` =
    real links, ``"cpu"`` = the single-host virtual mesh, where absolute
    numbers are dispatch cost and only the ORDERING is meaningful) — a
    controller must not consume a synthetic matrix as a link model."""

    n: int
    entries: List[dict]
    step: Optional[int] = None
    platform: Optional[str] = None

    def asdict(self) -> dict:
        return {"n": self.n, "step": self.step, "platform": self.platform,
                "entries": self.entries}

    @classmethod
    def fromdict(cls, d: dict) -> "EdgeCostMatrix":
        return cls(n=int(d["n"]), entries=list(d["entries"]),
                   step=d.get("step"), platform=d.get("platform"))

    def save(self, path: str) -> str:
        """The machine-readable artifact the controller consumes."""
        with open(path, "w") as f:
            json.dump(self.asdict(), f)
        return path

    @classmethod
    def load(cls, path: str) -> "EdgeCostMatrix":
        with open(path) as f:
            return cls.fromdict(json.load(f))

    def edges(self) -> List[Tuple[int, int]]:
        return sorted({(e["src"], e["dst"]) for e in self.entries})

    def latency_us(self, src: int, dst: int,
                   nbytes: Optional[int] = None) -> Optional[float]:
        """One-way latency for an edge — at ``nbytes``, or the LARGEST
        probed payload (the bandwidth-regime number) when unspecified."""
        cand = [e for e in self.entries
                if e["src"] == src and e["dst"] == dst
                and (nbytes is None or e["bytes"] == nbytes)]
        if not cand:
            return None
        return max(cand, key=lambda e: e["bytes"])["latency_us"]

    def slowest_edge(self, nbytes: Optional[int] = None
                     ) -> Optional[Tuple[int, int]]:
        """The edge a schedule optimizer should route around."""
        worst, arg = -1.0, None
        for src, dst in self.edges():
            lat = self.latency_us(src, dst, nbytes)
            if lat is not None and lat > worst:
                worst, arg = lat, (src, dst)
        return arg

    def to_gauges(self) -> None:
        """Mirror onto the host registry as ``bf_edge_*`` gauges (one
        cell per edge x payload size) — the scrape-endpoint view."""
        if not _metrics.enabled():
            return
        lat = _metrics.gauge(
            "bf_edge_latency_us",
            "measured one-way edge latency (ppermute round-trip / 2)")
        bw = _metrics.gauge(
            "bf_edge_gbps", "measured one-way edge payload rate")
        for e in self.entries:
            labels = dict(src=e["src"], dst=e["dst"], bytes=e["bytes"])
            lat.set(e["latency_us"], **labels)
            bw.set(e["gbps"], **labels)


def matrix_is_usable(matrix: EdgeCostMatrix, *,
                     path: Optional[str] = None,
                     platform: Optional[str] = None,
                     run_epoch: Optional[float] = None,
                     age_steps: Optional[int] = None,
                     max_age_steps: Optional[int] = None
                     ) -> Tuple[bool, str]:
    """Gate a sensing artifact before anything ACTS on it: ``(ok,
    reason)``.

    The probe records what it actually priced (``matrix.platform``); a
    matrix probed on a different backend than the live one — the classic
    case being a CPU virtual-mesh matrix (dispatch cost, not wire time)
    consumed on a TPU fleet — is refused, as is a matrix that recorded
    no platform at all.  With ``path`` given, an artifact whose mtime
    predates this run (``run_epoch``, default: process sensing start) is
    refused too: a file left behind by a previous run describes a fleet
    that no longer exists.

    A matrix that arrived OVER THE FABRIC instead of a file — the
    telemetry plane's gossiped edge-cost rows
    (``observability.plane.matrix_from_view``) — has no mtime; its
    freshness is the plane age of the rows it was assembled from.  Pass
    that as ``age_steps``: ages beyond ``max_age_steps`` (default
    ``BLUEFOG_PLANE_MAX_AGE``) are refused exactly like a stale file.

    ``platform`` defaults to the live JAX backend.  This is the shared
    guard the closed-loop controller (``control/``), ``bfctl``, the
    serving router, and any schedule optimizer must route matrices
    through — ``bench.py --profile-edges`` documents the
    synthetic-matrix hazard; this enforces it."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    if matrix.platform is None:
        return False, ("matrix records no platform — probed by a "
                       "pre-guard writer; re-probe before acting on it")
    if matrix.platform != platform:
        return False, (f"matrix probed on {matrix.platform!r} but the "
                       f"live backend is {platform!r} — a synthetic "
                       f"matrix must not become a link model")
    if path is not None:
        if run_epoch is None:
            run_epoch = _RUN_EPOCH
        try:
            mtime = os.path.getmtime(path)
        except OSError as e:
            return False, f"artifact unreadable: {e}"
        if mtime < run_epoch:
            return False, (f"artifact mtime predates this run by "
                           f"{run_epoch - mtime:.0f}s — stale link "
                           f"costs from a previous fleet")
    if age_steps is not None:
        if max_age_steps is None:
            from . import plane as _plane
            max_age_steps = _plane.resolve_max_age()
        if age_steps > max_age_steps:
            return False, (f"plane-gossiped rows are {age_steps} steps "
                           f"old (bound {max_age_steps}) — stale link "
                           f"costs from sources that stopped advancing")
    return True, "ok"


def topology_edges(topo=None) -> List[Tuple[int, int]]:
    """Directed edges (src -> dst) of a compiled topology: ``W[src, dst]
    != 0`` off the diagonal (``W[i, j]`` = weight of i's value at j, the
    ``compile_weight_matrix`` convention — ``src`` transmits to ``dst``,
    who folds it).  ``topo`` defaults to the current context's compiled
    topology; a networkx ``DiGraph`` (``bf.load_topology()``) works too
    (``nx.to_numpy_array`` keeps the same i->j orientation)."""
    if topo is None:
        from ..context import ctx
        topo = ctx().compiled_topology
    if not hasattr(topo, "weight_matrix"):    # networkx DiGraph
        return sorted((int(s), int(d)) for s, d in topo.edges()
                      if int(s) != int(d))
    W = np.asarray(topo.weight_matrix)
    out = []
    for src in range(W.shape[0]):
        for dst in range(W.shape[1]):
            if src != dst and W[src, dst] != 0:
                out.append((src, dst))
    return sorted(out)


def resolve_injected_delays(spec: Optional[str] = None
                            ) -> Dict[Tuple[int, int], float]:
    """Parse the synthetic-delay hook: ``"src-dst:us[,src-dst:us...]"``
    (``BLUEFOG_EDGE_PROBE_DELAY_US``) -> ``{(src, dst): seconds}``.  The
    virtual-mesh test hook: the probe harness sleeps this long inside the
    timed window of that edge's rounds, so the smoke gate can assert the
    matrix ranks a seeded slow edge slowest without real slow hardware."""
    if spec is None:
        spec = os.environ.get(EDGE_DELAY_ENV, "")
    out: Dict[Tuple[int, int], float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        try:
            edge, us = part.split(":")
            src, dst = edge.split("-")
            out[(int(src), int(dst))] = float(us) * 1e-6
        except ValueError:
            raise ValueError(
                f"bad {EDGE_DELAY_ENV} entry {part!r} "
                f"(want 'src-dst:us[,src-dst:us...]')")
    return out


def _resolve_max_probe_bytes(value: Optional[int] = None) -> int:
    if value is not None:
        return int(value)
    return int(os.environ.get(EDGE_MAX_BYTES_ENV,
                              str(DEFAULT_MAX_PROBE_BYTES)))


# (mesh, axis, unordered pair, nelems, dtype, inner) -> jitted probe.
# A pair's program serves BOTH directed edges (the round trip crosses both
# directions); the round index is traced, so re-probing never recompiles.
# Keyed by the Mesh VALUE (jax meshes hash by devices + axis names), not
# id() — a context re-init that frees the old mesh must not alias a new
# mesh allocated at the recycled address onto a stale cached program.
_probe_programs: Dict[tuple, object] = {}
_PROBE_CACHE_CAP = 4096    # re-init churn backstop, far above any real use


def _probe_program(mesh, axis: str, pair: Tuple[int, int],
                   nelems: int, dtype, inner: int):
    key = (mesh, axis, pair, nelems, jnp.dtype(dtype).name, inner)
    fn = _probe_programs.get(key)
    if fn is not None:
        return fn
    a, b = pair
    fwd, rev = ((a, b),), ((b, a),)

    def shard_body(buf, r):
        # fold the traced round index into the payload so back-to-back
        # rounds cannot be served from a constant-folded result
        v = buf + r.astype(buf.dtype)

        def one(_, x):
            x = lax.ppermute(x, axis, fwd)
            return lax.ppermute(x, axis, rev)

        return lax.fori_loop(0, inner, one, v)

    def probe(buf, r):
        return jax.shard_map(shard_body, mesh=mesh,
                             in_specs=(P(axis), P()), out_specs=P(axis))(
            buf, r)

    fn = jax.jit(probe)
    if len(_probe_programs) >= _PROBE_CACHE_CAP:
        _probe_programs.clear()
    _probe_programs[key] = fn
    if _metrics.enabled():
        _metrics.counter(
            "bf_edge_probe_programs_total",
            "edge-probe programs built (one per pair x payload size; "
            "rounds are traced data and never add to this)").inc()
    return fn


def probe_cache_size() -> int:
    """Compiled edge-probe programs currently cached (test hook: a second
    probe pass over the same config must not grow this)."""
    return len(_probe_programs)


def _timed_probe_rounds(fn, buf, repeats: int, delay_s: float,
                        label: str) -> float:
    """Minimum wall seconds over ``repeats`` timed rounds (round 0 pays
    the compile and is discarded); ``delay_s`` sleeps inside the timed
    window (the synthetic slow-edge hook)."""
    best = float("inf")
    for r in range(repeats + 1):
        tok = _tl.op_start_us()
        t0 = time.perf_counter()
        out = fn(buf, jnp.int32(r))
        if delay_s:
            time.sleep(delay_s)
        out.block_until_ready()
        dt = time.perf_counter() - t0
        _tl.record_op_span("edge_probe", label, tok)
        if r:
            best = min(best, dt)
    if _metrics.enabled():
        _metrics.counter(
            "bf_edge_probe_rounds_total",
            "timed edge-probe rounds executed").inc(repeats)
    return best


def probe_edges(sizes: Optional[Sequence[int]] = None, *,
                topo=None, edges: Optional[Sequence[Tuple[int, int]]] = None,
                repeats: int = 3, inner: int = 4,
                dtype=jnp.float32, step: Optional[int] = None,
                inject_delay_s: Optional[Dict[Tuple[int, int], float]] = None,
                export: bool = True) -> EdgeCostMatrix:
    """Measure every topology edge and return the :class:`EdgeCostMatrix`.

    ``sizes``: payload bytes per probe, each capped at
    ``BLUEFOG_EDGE_PROBE_MAX_BYTES``.  Default ``(4096, 1 MiB)`` —
    generic latency- and bandwidth-regime payloads; there is no
    "current params" to derive real bucket sizes from, so callers that
    have a tree should pass
    ``fusion.bucket_probe_sizes(fusion.plan_for(params))`` (what
    ``bench.py --profile-edges`` does) to price the links at the
    payloads the fused exchange actually ships.  ``repeats`` timed rounds per
    (edge, size) keep the MINIMUM (the standard latency-probe estimator —
    scheduler noise only ever adds time); ``inner`` round trips run
    inside one dispatch so per-dispatch overhead amortizes.

    ``inject_delay_s``: ``{(src, dst): seconds}`` synthetic per-edge
    delay applied host-side inside the timed window (test hook; merged
    with the µs-denominated ``BLUEFOG_EDGE_PROBE_DELAY_US``).  ``export``: mirror the matrix
    to gauges / JSONL / artifact via :func:`export_edge_matrix`.

    Cost: one compile per (unordered pair, size) on first probe — reused
    forever after — plus ``repeats`` timed dispatches per UNORDERED pair
    (both directed entries share the pair's round-trip measurement; a
    direction is only re-timed when it carries an injected delay).
    The training step cache is never consulted or invalidated."""
    from ..context import ctx
    cx = ctx()
    topo = topo if topo is not None else cx.compiled_topology
    mesh, axis, n = cx.mesh, cx.rank_axis, cx.size
    if edges is None:
        edges = topology_edges(topo)
    if sizes is None:
        sizes = default_probe_sizes()
    cap = _resolve_max_probe_bytes()
    itemsize = jnp.dtype(dtype).itemsize
    sizes = sorted({max(itemsize, min(int(s), cap)) for s in sizes})
    delays = dict(resolve_injected_delays())
    if inject_delay_s:
        delays.update(inject_delay_s)

    entries: List[dict] = []
    for nbytes in sizes:
        nelems = max(1, nbytes // itemsize)
        buf = jnp.zeros((n, nelems), dtype)
        # one timed pass per UNORDERED pair: the probe program's round
        # trip crosses both directions, so timing (a,b) and (b,a)
        # separately would measure the identical quantity twice for
        # double the synced dispatches.  Both directed entries share the
        # pair's number; only a direction carrying an injected test
        # delay is re-timed with the delay in its window.
        base: Dict[Tuple[int, int], float] = {}
        for pair in sorted({(min(s, d), max(s, d)) for s, d in edges}):
            fn = _probe_program(mesh, axis, pair, nelems, dtype, inner)
            base[pair] = _timed_probe_rounds(
                fn, buf, repeats, 0.0,
                f"probe {pair[0]}<->{pair[1]} {nbytes}B")
        for src, dst in edges:
            pair = (min(src, dst), max(src, dst))
            delay = delays.get((src, dst), 0.0)
            if delay:
                fn = _probe_program(mesh, axis, pair, nelems, dtype, inner)
                best = _timed_probe_rounds(
                    fn, buf, repeats, delay,
                    f"probe {src}->{dst} {nbytes}B")
            else:
                best = base[pair]
            round_trip_s = best / inner
            latency_us = round_trip_s / 2.0 * 1e6
            gbps = (nelems * itemsize) / max(round_trip_s / 2.0, 1e-12) / 1e9
            entries.append({
                "src": int(src), "dst": int(dst),
                "bytes": int(nelems * itemsize), "rounds": int(repeats),
                "inner": int(inner),
                "latency_us": round(latency_us, 3),
                "gbps": round(gbps, 6),
            })
    platform = getattr(np.asarray(mesh.devices).flat[0], "platform", None)
    matrix = EdgeCostMatrix(n=n, entries=entries, step=step,
                            platform=platform)
    if export:
        export_edge_matrix(matrix, step=step)
    return matrix


def default_probe_sizes() -> Tuple[int, ...]:
    """Generic latency-regime + bandwidth-regime payloads — the
    ``sizes=None`` default.  Callers with a real tree should pass
    ``ops.fusion.bucket_probe_sizes(plan)`` instead."""
    return (4096, 1 << 20)


def export_edge_matrix(matrix: EdgeCostMatrix,
                       step: Optional[int] = None,
                       artifact_path: Optional[str] = None) -> Optional[dict]:
    """Fan the matrix out to every sink: ``bf_edge_*`` gauges, a JSONL
    ``"edges"`` record on the open metrics series (the round-trip the
    acceptance gate walks: matrix -> JSONL -> ``bfmonitor --once
    --json``), and the controller artifact when ``artifact_path`` or
    ``BLUEFOG_EDGE_ARTIFACT`` names one.

    With an explicit ``step``, a dedicated record is written at that
    step and returned.  With ``step=None`` (a probe inside a live
    training loop) the matrix is STAGED instead (``phases.stage_field``)
    and rides the loop's next ``export.log_step`` record — a standalone
    write would collide with the record the loop already logged for that
    step (the fleet view keeps the last record per (rank, step), so the
    edges-only line would evict that step's telemetry).  Returns None in
    staging mode."""
    from . import export as _export
    matrix.to_gauges()
    if artifact_path is None:
        artifact_path = os.environ.get(EDGE_ARTIFACT_ENV)
    if artifact_path:
        matrix.save(artifact_path)
    if step is None and matrix.step is None:
        _phases.stage_field("edges", matrix.entries)
        if matrix.platform is not None:
            _phases.stage_field("edges_platform", matrix.platform)
        return None
    extra = {"edges": matrix.entries}
    if matrix.platform is not None:
        # the consumer-side guard (matrix_is_usable / the controller)
        # needs to know what the in-series matrix priced
        extra["edges_platform"] = matrix.platform
    return _export.log_step(step if step is not None else matrix.step,
                            extra=extra)


# ---------------------------------------------------------------------------
# Measured overlap efficiency
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class OverlapSample:
    """One measured exposed/hidden split of a step's exchange time.

    ``hidden_s``   exchange time OFF the parameter critical path (the
                   full program minus the launch-pruned program),
    ``exposed_s``  exchange time still ON it (exchange total - hidden),
    ``efficiency`` hidden / exchange total in [0, 1]: 0 = the pipeline
                   degenerated to synchronous, 1 = fully overlapped."""

    efficiency: float
    hidden_s: float
    exposed_s: float
    t_full_s: float
    t_pruned_s: float
    t_comm_s: float

    def asdict(self) -> dict:
        return dataclasses.asdict(self)


def _timed_once(fn, args) -> float:
    """One synced dispatch, wall seconds."""
    t0 = time.perf_counter()
    out = fn(*args)
    jax.block_until_ready(out)
    return time.perf_counter() - t0


def _time_interleaved(fns_args, repeats: int) -> List[float]:
    """Minimum wall seconds per program over ``repeats`` INTERLEAVED
    rounds (one discarded warmup round absorbs the compiles).

    Interleaving matters: the efficiency estimate subtracts two
    near-equal times (full vs pruned step), so timing all repeats of one
    program and then all of the other would let slow host drift (CPU
    frequency, cache state, a background process ramping up) land
    directly in the difference.  Round-robin sampling makes each round
    see the same host conditions for every program."""
    best = [float("inf")] * len(fns_args)
    for r in range(repeats + 1):
        for i, (fn, args) in enumerate(fns_args):
            dt = _timed_once(fn, args)
            if r:
                best[i] = min(best[i], dt)
    return best


def measure_overlap(full_fn, pruned_fn, comm_fn, args,
                    comm_args=None, *, repeats: int = 2,
                    stage: bool = True) -> Optional[OverlapSample]:
    """Time the three probe programs and compute the exposed/hidden split.

    ``full_fn(*args)``   the real step (all outputs);
    ``pruned_fn(*args)`` the same step with the in-flight launch pruned —
                         built by passing the carried ``inflight`` state
                         through unchanged so XLA dead-code-eliminates
                         the ppermutes feeding it (verified structurally
                         in ``tests/test_commprof.py``: the pruned
                         lowering carries zero collective-permutes under
                         overlap);
    ``comm_fn(*comm_args)`` the exchange alone (prices the full
                         exchange this step would run).

    None of the three may donate their inputs (they are re-invoked on the
    same arguments).  Returns None when the exchange is too small to
    price (< 20 µs — nothing to hide).  ``stage=True`` stages the
    ``overlap_efficiency`` field for the next ``export.log_step`` record,
    mirrors the ``bf_overlap{field=efficiency|hidden_s|exposed_s}``
    gauge, and emits ``overlap/*`` timeline counter lanes."""
    if comm_args is None:
        comm_args = args
    t_comm, t_full, t_pruned = _time_interleaved(
        [(comm_fn, comm_args), (full_fn, args), (pruned_fn, args)],
        repeats)
    if t_comm < 20e-6:
        return None
    hidden = max(0.0, t_full - t_pruned)
    hidden = min(hidden, t_comm)
    exposed = max(0.0, t_comm - hidden)
    sample = OverlapSample(
        efficiency=hidden / t_comm, hidden_s=hidden, exposed_s=exposed,
        t_full_s=t_full, t_pruned_s=t_pruned, t_comm_s=t_comm)
    if stage:
        _stage_overlap_sample(sample)
    return sample


def _stage_overlap_sample(sample: OverlapSample) -> None:
    _phases.stage_field("overlap_efficiency", sample.efficiency)
    if _metrics.enabled():
        g = _metrics.gauge(
            "bf_overlap",
            "last measured overlap split of the exchange "
            "(efficiency = hidden / exchange total)")
        g.set(sample.efficiency, field="efficiency")
        g.set(sample.hidden_s, field="hidden_s")
        g.set(sample.exposed_s, field="exposed_s")
    _tl.record_counter("overlap/efficiency", sample.efficiency)
    _tl.record_counter("overlap/hidden_ms", sample.hidden_s * 1e3)
    _tl.record_counter("overlap/exposed_ms", sample.exposed_s * 1e3)


def overlap_probe_every(value: Optional[int] = None) -> int:
    """Resolve the auto-probe cadence (``BLUEFOG_OVERLAP_PROBE_EVERY``,
    default 0 = off): every K-th optimizer step re-measures the overlap
    split while profiling is active.  Each probe costs a few extra synced
    dispatches, so it is opt-in like the timeline."""
    if value is not None:
        return int(value)
    return int(os.environ.get("BLUEFOG_OVERLAP_PROBE_EVERY", "0"))
