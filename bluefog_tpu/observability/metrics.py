"""Process-local host metrics registry: counters, gauges, histograms.

The in-graph telemetry (``observability/ingraph.py``) measures what happens
*inside* the jitted step; this registry measures everything around it — how
many collectives the fusion layer planned, how often windows promote their
back buffer, how deep the service queue runs, how often the step cache
recompiles.  The reference has no equivalent (its only observability is the
timeline); this is the Prometheus-shaped half of the observability layer.

Design constraints:

* **Disabled by default, free when disabled.**  Every instrumentation site
  guards with ``if metrics.enabled():`` — a single list-indexed bool read,
  no argument packing, no dict allocation — so the hot path (window ops,
  service submits) pays nothing until someone opts in
  (``BLUEFOG_METRICS=<prefix>`` at init, or :func:`enable`).  Asserted by
  ``tests/test_observability.py``.
* **Named labels.**  ``counter("bf_win_ops_total").inc(op="put")`` keeps one
  float per label combination, Prometheus-style; the label key is the
  sorted kv tuple so ``(a=1, b=2)`` and ``(b=2, a=1)`` share a cell.
* **JSON-able snapshots.**  :meth:`Registry.snapshot` returns a flat
  ``{"name{k=v}": value}`` dict (histograms nest ``count/sum/buckets``) that
  drops straight into a ``BENCH_*.json`` or a JSONL line; the Prometheus
  text rendering lives in ``observability/export.py``.
"""

import threading
from typing import Dict, Iterable, Optional, Tuple

__all__ = [
    "enabled", "enable", "disable",
    "Counter", "Gauge", "Histogram", "Registry",
    "registry", "counter", "gauge", "histogram",
]

# single-cell state read by every hot-path guard; a list (not a module
# global rebound on toggle) so `from ... import enabled` call sites and the
# toggles always see the same cell
_state = [False]


def enabled() -> bool:
    """Hot-path gate: instrumentation sites call this FIRST and skip all
    metric work (including label-kwarg packing) when it returns False."""
    return _state[0]


def enable() -> None:
    _state[0] = True


def disable() -> None:
    _state[0] = False


def _label_key(labels: Dict[str, object]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_repr(key: Tuple[Tuple[str, str], ...]) -> str:
    if not key:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in key) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._values: Dict[Tuple, float] = {}

    def _items(self):
        with self._lock:
            return list(self._values.items())


class Counter(_Metric):
    """Monotonic counter with optional named labels."""

    kind = "counter"

    def inc(self, value: float = 1.0, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(value)

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)


class Gauge(_Metric):
    """Last-write-wins gauge with optional named labels."""

    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = float(value)

    def add(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + float(value)

    def value(self, **labels) -> float:
        return self._values.get(_label_key(labels), 0.0)


# default buckets span microseconds-to-minutes of seconds and 1B-to-1GB of
# bytes reasonably; override per histogram when the range is known
DEFAULT_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 1e-2, 0.1, 1.0, 10.0, 100.0,
                   1e3, 1e4, 1e5, 1e6, 1e7, 1e8, 1e9)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics): each cell keeps
    per-bucket counts plus running sum/count."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Iterable[float]] = None):
        super().__init__(name, help)
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        value = float(value)
        with self._lock:
            cell = self._values.get(key)
            if cell is None:
                cell = {"count": 0, "sum": 0.0,
                        "buckets": [0] * len(self.buckets)}
                self._values[key] = cell
            cell["count"] += 1
            cell["sum"] += value
            for i, le in enumerate(self.buckets):
                if value <= le:
                    cell["buckets"][i] += 1

    def cell(self, **labels):
        return self._values.get(_label_key(labels))


class Registry:
    """Name -> metric map.  Get-or-create accessors are the public surface;
    re-registering a name with a different kind is a programming error and
    raises rather than silently aliasing two meanings."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    def _get_or_create(self, cls, name: str, help: str, **kw):
        m = self._metrics.get(name)          # lock-free fast path (GIL-safe)
        if m is None:
            with self._lock:
                m = self._metrics.get(name)
                if m is None:
                    m = cls(name, help, **kw)
                    self._metrics[name] = m
        if not isinstance(m, cls):
            raise TypeError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Iterable[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def metrics(self):
        with self._lock:
            return list(self._metrics.values())

    def snapshot(self) -> Dict[str, object]:
        """Flat JSON-able view: ``{"name" or "name{k=v}": value}``;
        histogram cells nest ``{"count", "sum", "buckets": {"le": n}}``."""
        out: Dict[str, object] = {}
        for m in self.metrics():
            for key, val in m._items():
                cell_name = m.name + _label_repr(key)
                if m.kind == "histogram":
                    out[cell_name] = {
                        "count": val["count"], "sum": val["sum"],
                        "buckets": {repr(le): c for le, c in
                                    zip(m.buckets, val["buckets"])}}
                else:
                    out[cell_name] = val
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


registry = Registry()


def counter(name: str, help: str = "") -> Counter:
    return registry.counter(name, help)


def gauge(name: str, help: str = "") -> Gauge:
    return registry.gauge(name, help)


def histogram(name: str, help: str = "",
              buckets: Optional[Iterable[float]] = None) -> Histogram:
    return registry.histogram(name, help, buckets)
