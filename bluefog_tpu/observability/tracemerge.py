"""``bftrace`` — merge N per-rank Chrome traces into one fleet trace.

Each rank's timeline (``BLUEFOG_TIMELINE=<prefix>`` ->
``<prefix><rank>.json``) is written against that process's OWN clock
(``time.perf_counter`` at ``timeline_start``), so N files loaded side by
side in Perfetto tell N unrelated stories.  This module makes them one
causal trace:

* **per-rank process rows** — every event is re-pinned to ``pid = rank``
  with ``process_name`` / ``process_sort_index`` metadata, so Perfetto
  renders one row block per rank, in rank order;
* **clock alignment** — per-rank offsets are estimated from matched
  exchange spans: the step loop stamps a ``round <k>`` span on the
  ``gossip`` lane (``timeline.record_gossip_round``), and since a gossip
  round is a collective, every participating rank finishes round *k*
  together — the median end-time difference of shared rounds versus the
  reference rank (lowest rank) IS the clock offset, robust to a few
  straggling rounds;
* **cross-rank flow events** — for every gossip round and topology edge,
  a Chrome-trace flow arrow (``ph:"s"``/``"f"``) links the send side's
  round span to the receive side's, so a straggling edge shows up as a
  visibly skewed arrow instead of a guess.  Edges come from an
  :class:`~.commprof.EdgeCostMatrix` artifact or an explicit list; with
  neither, flows are omitted (the merge is still aligned).

Pure host-side stdlib: importing this module never touches JAX.

CLI (console script ``bftrace``)::

    bftrace /tmp/trace_ -o merged.json              # <prefix><rank>.json
    bftrace a.json b.json -o merged.json --edges 0-1,1-0
    bftrace /tmp/trace_ -o merged.json --edge-matrix edges.json

Prints one JSON report line (ranks, per-rank offsets µs, sync rounds
matched, flows emitted) and exits non-zero when nothing could be merged.
"""

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "load_trace", "discover_traces", "sync_spans", "estimate_offsets",
    "merge_traces", "validate_merged", "main", "SYNC_PREFIX",
]

# the span-name prefix the step loops stamp per gossip round
# (timeline.record_gossip_round) — the cross-rank matching key
SYNC_PREFIX = "round "


def _drop_partial_tail(text: str) -> Optional[list]:
    """Last-resort repair for a file truncated MID-EVENT (writer killed
    mid-flush): close the array at the last complete top-level event.
    Not every ``}`` ends an event (``args`` nests), so try each trailing
    candidate, bounded — the partial tail is at most one event long."""
    base = text.rstrip().rstrip(",")
    cut = len(base)
    for _ in range(64):
        cut = base.rfind("}", 0, cut)
        if cut < 0:
            return None
        try:
            out = json.loads(base[:cut + 1] + "\n]")
        except json.JSONDecodeError:
            continue
        return out if isinstance(out, list) else None
    return None


def load_trace(path: str) -> List[dict]:
    """Read one Chrome-trace JSON array, tolerantly.

    A writer killed mid-run leaves the array unclosed (the native writer
    flushes events but only ``close()`` writes the bracket), possibly
    with a partial event at EOF; the merge exists precisely to debug
    such runs, so repair — strip a trailing comma, close the array,
    drop a truncated tail event — rather than refuse."""
    with open(path) as f:
        text = f.read()
    try:
        events = json.loads(text)
    except json.JSONDecodeError:
        repaired = text.rstrip().rstrip(",")
        if not repaired.endswith("]"):
            repaired += "\n]"
        try:
            events = json.loads(repaired)
        except json.JSONDecodeError as e:
            events = _drop_partial_tail(text)
            if events is None:
                raise ValueError(f"{path}: not a Chrome trace array ({e})")
    if isinstance(events, dict):           # {"traceEvents": [...]} form
        events = events.get("traceEvents", [])
    if not isinstance(events, list):
        raise ValueError(f"{path}: expected a JSON array of events")
    return [e for e in events if isinstance(e, dict)]


def discover_traces(prefix: str) -> Dict[int, str]:
    """``<prefix><rank>.json`` files on disk, keyed by integer rank —
    the same discovery contract as the metrics JSONL aggregator."""
    out: Dict[int, str] = {}
    pat = re.compile(re.escape(os.path.basename(prefix)) + r"(\d+)\.json$")
    for path in glob.glob(glob.escape(prefix) + "*.json"):
        m = pat.match(os.path.basename(path))
        if m:
            out[int(m.group(1))] = path
    return out


def sync_spans(events: Sequence[dict],
               sync_prefix: str = SYNC_PREFIX) -> Dict[str, dict]:
    """Complete (``ph:"X"``) spans whose name carries the sync prefix,
    keyed by name — first occurrence wins (a restarted loop re-stamping
    ``round 0`` must not skew the estimate with a late duplicate)."""
    out: Dict[str, dict] = {}
    for e in events:
        if (e.get("ph") == "X" and isinstance(e.get("name"), str)
                and e["name"].startswith(sync_prefix)
                and e["name"] not in out):
            out[e["name"]] = e
    return out


def _span_end(e: dict) -> float:
    return float(e.get("ts", 0)) + float(e.get("dur", 0))


def estimate_offsets(per_rank_events: Dict[int, Sequence[dict]],
                     sync_prefix: str = SYNC_PREFIX
                     ) -> Tuple[Dict[int, float], Dict[int, int]]:
    """Per-rank clock offsets (µs to ADD to a rank's timestamps) against
    the reference rank (lowest), from the median end-time difference of
    shared sync spans.  A collective finishes on every rank together, so
    the end-to-end difference of round *k* is (mostly) clock skew; the
    median survives a few genuinely straggling rounds.  Ranks sharing no
    sync span stay at offset 0 (flagged via a 0 match count)."""
    ranks = sorted(per_rank_events)
    if not ranks:
        return {}, {}
    ref = ranks[0]
    ref_spans = sync_spans(per_rank_events[ref], sync_prefix)
    offsets: Dict[int, float] = {ref: 0.0}
    matched: Dict[int, int] = {ref: len(ref_spans)}
    for rank in ranks[1:]:
        spans = sync_spans(per_rank_events[rank], sync_prefix)
        shared = sorted(set(ref_spans) & set(spans))
        matched[rank] = len(shared)
        if not shared:
            offsets[rank] = 0.0
            continue
        deltas = [_span_end(ref_spans[name]) - _span_end(spans[name])
                  for name in shared]
        offsets[rank] = float(statistics.median(deltas))
    return offsets, matched


def _parse_edges(spec: Optional[str]) -> List[Tuple[int, int]]:
    if not spec:
        return []
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        src, dst = part.split("-")
        out.append((int(src), int(dst)))
    return out


def _flow_events(shifted: Dict[int, List[dict]],
                 edges: Sequence[Tuple[int, int]],
                 sync_prefix: str) -> List[dict]:
    """One flow arrow per (gossip round, edge): send side = the src
    rank's round span end, receive side = the dst rank's — after clock
    alignment, a skewed arrow IS a straggling edge."""
    spans = {rank: sync_spans(evs, sync_prefix)
             for rank, evs in shifted.items()}
    flows: List[dict] = []
    fid = 0
    for src, dst in edges:
        if src not in spans or dst not in spans:
            continue
        for name in sorted(set(spans[src]) & set(spans[dst])):
            s, d = spans[src][name], spans[dst][name]
            fid += 1
            flows.append({"ph": "s", "cat": "gossip",
                          "name": f"{name} {src}->{dst}", "id": fid,
                          "pid": src, "tid": s.get("tid", 0),
                          "ts": _span_end(s)})
            flows.append({"ph": "f", "bp": "e", "cat": "gossip",
                          "name": f"{name} {src}->{dst}", "id": fid,
                          "pid": dst, "tid": d.get("tid", 0),
                          "ts": _span_end(d)})
    return flows


def merge_traces(paths: Dict[int, str], *,
                 edges: Optional[Sequence[Tuple[int, int]]] = None,
                 sync_prefix: str = SYNC_PREFIX,
                 out_path: Optional[str] = None) -> dict:
    """Merge per-rank trace files into one aligned fleet trace.

    Returns a report dict: ``events`` (the merged list), ``offsets_us``,
    ``sync_matched`` (rounds matched per rank), ``flows``, ``ranks``.
    ``out_path`` additionally writes the merged array to disk."""
    per_rank = {rank: load_trace(path) for rank, path in sorted(paths.items())}
    offsets, matched = estimate_offsets(per_rank, sync_prefix)
    shifted: Dict[int, List[dict]] = {}
    merged: List[dict] = []
    for rank in sorted(per_rank):
        off = offsets.get(rank, 0.0)
        evs = []
        for e in per_rank[rank]:
            # the writers' own process metadata is replaced by the
            # canonical per-rank rows below (two process_name events on
            # one pid would race in the viewer)
            if (e.get("ph") == "M" and e.get("name")
                    in ("process_name", "process_sort_index")):
                continue
            e = dict(e)
            e["pid"] = rank                 # one process row per rank
            if "ts" in e:
                e["ts"] = float(e["ts"]) + off
            evs.append(e)
        # rank-ordered, named process rows regardless of what the
        # original writer emitted
        evs.insert(0, {"name": "process_name", "ph": "M", "pid": rank,
                       "args": {"name": f"rank {rank}"}})
        evs.insert(1, {"name": "process_sort_index", "ph": "M",
                       "pid": rank, "args": {"sort_index": rank}})
        shifted[rank] = evs
        merged.extend(evs)
    flows = _flow_events(shifted, edges or [], sync_prefix)
    merged.extend(flows)
    if out_path:
        with open(out_path, "w") as f:
            json.dump(merged, f)
    return {
        "ranks": sorted(per_rank),
        "offsets_us": {str(r): round(offsets.get(r, 0.0), 3)
                       for r in sorted(per_rank)},
        "sync_matched": {str(r): matched.get(r, 0)
                         for r in sorted(per_rank)},
        "flows": len(flows) // 2,
        "events": merged,
        "out_path": out_path,
    }


def validate_merged(events: Sequence[dict]) -> List[str]:
    """Structural checks on a merged trace; returns a list of problems
    (empty = valid).  Complete spans must be time-ordered per (pid, tid)
    row — the invariant the golden-merge test gates on — and every flow
    start must have its finish."""
    problems: List[str] = []
    rows: Dict[Tuple, float] = {}
    for e in events:
        if e.get("ph") != "X":
            continue
        key = (e.get("pid"), e.get("tid"))
        ts = float(e.get("ts", 0))
        if key in rows and ts < rows[key]:
            problems.append(
                f"row {key}: span {e.get('name')!r} at {ts} precedes the "
                f"previous span start {rows[key]}")
        rows[key] = max(rows.get(key, ts), ts)
    starts = {e["id"] for e in events if e.get("ph") == "s"}
    ends = {e["id"] for e in events if e.get("ph") == "f"}
    for fid in sorted(starts ^ ends):
        problems.append(f"flow {fid} is unpaired")
    return problems


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bftrace",
        description="merge per-rank BLUEFOG_TIMELINE Chrome traces into "
                    "one clock-aligned fleet trace "
                    "(docs/observability.md)")
    p.add_argument("inputs", nargs="+",
                   help="a timeline prefix (discovers <prefix><rank>"
                        ".json) or explicit per-rank trace files "
                        "(rank = position)")
    p.add_argument("-o", "--out", required=True,
                   help="merged trace path (open in Perfetto)")
    p.add_argument("--sync-prefix", default=SYNC_PREFIX,
                   help=f"span-name prefix matched across ranks for "
                        f"clock alignment (default {SYNC_PREFIX!r})")
    p.add_argument("--edges", default=None,
                   help="comma-separated src-dst pairs to draw gossip "
                        "flow arrows for (e.g. 0-1,1-2)")
    p.add_argument("--edge-matrix", default=None, metavar="PATH",
                   help="EdgeCostMatrix artifact (bench.py "
                        "--profile-edges); its edges supply the flow "
                        "arrows")
    args = p.parse_args(argv)

    if len(args.inputs) == 1 and not os.path.exists(args.inputs[0]):
        paths = discover_traces(args.inputs[0])
        if not paths:
            print(f"bftrace: no <prefix><rank>.json files match "
                  f"{args.inputs[0]!r}", file=sys.stderr)
            return 1
    elif len(args.inputs) == 1 and args.inputs[0].endswith(".json"):
        paths = {0: args.inputs[0]}
    else:
        paths = {i: path for i, path in enumerate(args.inputs)}

    edges = _parse_edges(args.edges)
    if args.edge_matrix:
        with open(args.edge_matrix) as f:
            d = json.load(f)
        edges = sorted({(int(e["src"]), int(e["dst"]))
                        for e in d.get("entries", [])} | set(edges))

    report = merge_traces(paths, edges=edges,
                          sync_prefix=args.sync_prefix, out_path=args.out)
    problems = validate_merged(report["events"])
    out = {k: v for k, v in report.items() if k != "events"}
    out["event_count"] = len(report["events"])
    out["problems"] = problems
    print(json.dumps(out))
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main())
