"""In-band telemetry plane: gossip the fleet's health over the fabric.

Every sensing surface before this PR (health engine, edge profiler,
controller, router liveness) read per-rank JSONL files off one process's
filesystem — a centralized monitor bolted onto a decentralized system.
This module moves that state onto the fabric itself: each rank packs a
fixed-shape telemetry vector (step counter, heartbeat, consensus
residual, staleness watermark, health-verdict bits, its top-k measured
edge costs) into one f32 wire slot and disseminates it with the same
circulant ``ppermute`` exchanges the neighbor collectives use.  A
newest-version-wins merge per SOURCE row makes every rank's local table
eventually consistent: a fact injected anywhere reaches all N ranks
within graph-diameter rounds (O(log N) on the one-peer exponential
family), with no shared filesystem and no central collector.

Wire schema (``SCHEMA_VERSION`` 1) — one ``[WIRE]`` f32 row per source:

====================  =====================================================
lane                  meaning
====================  =====================================================
``SLOT_STEP``         source's own step counter
``SLOT_HEARTBEAT``    source's heartbeat tick (its local step clock)
``SLOT_CONSENSUS``    consensus residual (``UNMEASURED`` = -1 when none)
``SLOT_STALENESS``    source's staleness watermark (async/serving lag)
``SLOT_HEALTH``       packed health-verdict bits (:func:`pack_health_bits`)
``SLOT_EDGE_*``       provenance (platform code, probe step) + ``EDGE_K``
                      ``(dst, latency_us)`` pairs: the source's slowest
                      measured out-edges
``LANE_VERSION``      per-source version (publisher step + 1; 0 = never
                      heard).  Strictly-greater wins on merge.
``LANE_HOP``          hops this copy travelled from its source
====================  =====================================================

All lanes ride one f32 array, so integers are exact up to 2**24 — at
one version per step that is ~16M steps before wraparound, checked in
:func:`pack_payload`.

Dissemination and merge are ONE jitted shard_map program per (axis,
topology, mesh) — ``step``/``payload``/``active``/``link_ok`` are traced
data, so plane updates, rank death, and elastic re-join never recompile
(``_plane_fn(...)._cache_size() == 1`` is asserted in tests and ``make
bench-plane``).  With the plane off the program is never built, so the
train step's StableHLO is byte-identical to a plane-free process.

Dead sources age out: each rank tracks ``last_heard[src]`` (the local
step at which ``src``'s row last advanced); ages beyond
``BLUEFOG_PLANE_MAX_AGE`` flag the source stale in
:class:`FleetViewLive` and ``bfmonitor --plane``.  A rank that dies and
elastically re-joins publishes at its (higher) current step, so its
version resumes above every stale copy still circulating.

Consumers (docs/observability.md "In-band telemetry plane"):
``health.evaluate`` accepts the plane-backed :class:`FleetViewLive`
(it IS a FleetView), the serving router takes liveness/staleness from
:meth:`RequestRouter.observe_plane`, and the controller admits a
plane-gossiped edge-cost row via :func:`matrix_from_view` behind the
``commprof.matrix_is_usable`` gate.
"""

import functools
import math
import os
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..parallel.schedule import CompiledTopology
from . import aggregate as AG
from . import metrics as _metrics

__all__ = [
    "SCHEMA_VERSION", "EDGE_K", "WIDTH", "WIRE",
    "SLOT_STEP", "SLOT_HEARTBEAT", "SLOT_CONSENSUS", "SLOT_STALENESS",
    "SLOT_HEALTH", "SLOT_EDGE_PLATFORM", "SLOT_EDGE_STEP", "SLOT_EDGES",
    "LANE_VERSION", "LANE_HOP",
    "MAX_AGE_ENV", "WINDOW_ENV",
    "resolve_max_age", "resolve_window",
    "platform_code", "platform_name",
    "pack_health_bits", "unpack_health_bits",
    "top_edges", "pack_payload", "decode_row",
    "init_state", "plane_exchange", "exchange",
    "permutes_per_round", "wire_bytes_per_round", "diameter",
    "snapshot", "TelemetryPlane", "FleetViewLive", "matrix_from_view",
    "host_merge",
]

SCHEMA_VERSION = 1

# -- wire layout -------------------------------------------------------------

EDGE_K = 4                       # slowest measured out-edges carried

SLOT_STEP = 0
SLOT_HEARTBEAT = 1
SLOT_CONSENSUS = 2
SLOT_STALENESS = 3
SLOT_HEALTH = 4
SLOT_EDGE_PLATFORM = 5
SLOT_EDGE_STEP = 6
SLOT_EDGES = 7                   # then EDGE_K x (dst, latency_us) pairs

WIDTH = SLOT_EDGES + 2 * EDGE_K  # payload lanes a publisher fills
LANE_VERSION = WIDTH             # appended by the exchange program
LANE_HOP = WIDTH + 1
WIRE = WIDTH + 2                 # full per-source wire row

_F32_EXACT = float(1 << 24)      # integer lanes stay exact below this

# mirrors health.UNMEASURED: "this step measured no consensus distance"
UNMEASURED = -1.0

MAX_AGE_ENV = "BLUEFOG_PLANE_MAX_AGE"
WINDOW_ENV = "BLUEFOG_PLANE_WINDOW"


def resolve_max_age(value: Optional[int] = None) -> int:
    """``BLUEFOG_PLANE_MAX_AGE`` (default 8): steps since a source's row
    last advanced before the local view flags it stale (dead sources age
    out; ``bfmonitor --plane`` marks them)."""
    age = int(os.environ.get(MAX_AGE_ENV, "8") if value is None else value)
    if age < 1:
        raise ValueError(f"plane max age must be >= 1, got {age}")
    return age


def resolve_window(value: Optional[int] = None) -> int:
    """``BLUEFOG_PLANE_WINDOW`` (default 32): per-source snapshots the
    local :class:`TelemetryPlane` history retains for the health engine's
    trailing-window rules."""
    win = int(os.environ.get(WINDOW_ENV, "32") if value is None else value)
    if win < 2:
        raise ValueError(f"plane window must be >= 2, got {win}")
    return win


# -- platform provenance codes ----------------------------------------------

_PLATFORM_CODES = {"cpu": 1, "gpu": 2, "cuda": 2, "rocm": 2, "tpu": 3}
_PLATFORM_NAMES = {1: "cpu", 2: "gpu", 3: "tpu"}


def platform_code(name: Optional[str]) -> int:
    """Platform -> wire code (0 = unknown/absent: consumers must refuse)."""
    return _PLATFORM_CODES.get((name or "").lower(), 0)


def platform_name(code: float) -> Optional[str]:
    return _PLATFORM_NAMES.get(int(code))


# -- health-verdict bits -----------------------------------------------------

HEALTH_ALERT_BIT = 1             # any warn/critical verdict
HEALTH_CRITICAL_BIT = 2
HEALTH_CONSENSUS_BIT = 4         # consensus_stall / consensus_diverge
HEALTH_STRAGGLER_BIT = 8
HEALTH_DEAD_RANK_BIT = 16

_HEALTH_RULE_BITS = {
    "consensus_stall": HEALTH_CONSENSUS_BIT,
    "consensus_diverge": HEALTH_CONSENSUS_BIT,
    "straggler": HEALTH_STRAGGLER_BIT,
    "dead_rank": HEALTH_DEAD_RANK_BIT,
    "rank_silent": HEALTH_DEAD_RANK_BIT,
}


def pack_health_bits(report) -> int:
    """Compress a :class:`health.HealthReport` into the wire bitfield."""
    bits = 0
    for v in report.alerts:
        bits |= HEALTH_ALERT_BIT
        if v.severity == "critical":
            bits |= HEALTH_CRITICAL_BIT
        bits |= _HEALTH_RULE_BITS.get(v.rule, 0)
    return bits


def unpack_health_bits(bits: float) -> Dict[str, bool]:
    b = int(bits)
    return {
        "alert": bool(b & HEALTH_ALERT_BIT),
        "critical": bool(b & HEALTH_CRITICAL_BIT),
        "consensus": bool(b & HEALTH_CONSENSUS_BIT),
        "straggler": bool(b & HEALTH_STRAGGLER_BIT),
        "dead_rank": bool(b & HEALTH_DEAD_RANK_BIT),
    }


# -- payload packing ---------------------------------------------------------

def top_edges(matrix, rank: int, k: int = EDGE_K
              ) -> List[Tuple[int, float]]:
    """``rank``'s ``k`` slowest measured out-edges from an
    :class:`~bluefog_tpu.observability.commprof.EdgeCostMatrix` as
    ``(dst, latency_us)`` pairs — the fixed-shape fragment the plane can
    afford to carry (the full matrix is O(N^2))."""
    worst: Dict[int, float] = {}
    for e in matrix.entries:
        if int(e["src"]) != int(rank):
            continue
        dst = int(e["dst"])
        us = float(e["latency_us"])
        if dst not in worst or us > worst[dst]:
            worst[dst] = us
    mine = sorted(worst.items(), key=lambda p: (-p[1], p[0]))
    return [(d, us) for d, us in mine[:k]]


def pack_payload(step: int, *,
                 heartbeat: Optional[int] = None,
                 consensus_dist: float = UNMEASURED,
                 staleness: float = 0.0,
                 health_bits: int = 0,
                 edges: Optional[Sequence[Tuple[int, float]]] = None,
                 edge_platform: Optional[str] = None,
                 edge_step: Optional[int] = None) -> np.ndarray:
    """One rank's ``[WIDTH]`` payload row.

    ``edges`` is the :func:`top_edges` fragment; empty pairs encode
    ``dst = -1``.  Integer lanes must stay f32-exact (< 2**24)."""
    step = int(step)
    if not 0 <= step < _F32_EXACT:
        raise ValueError(f"plane step {step} outside exact f32 range")
    row = np.zeros((WIDTH,), np.float32)
    row[SLOT_STEP] = step
    row[SLOT_HEARTBEAT] = step if heartbeat is None else int(heartbeat)
    row[SLOT_CONSENSUS] = float(consensus_dist)
    row[SLOT_STALENESS] = float(staleness)
    row[SLOT_HEALTH] = int(health_bits)
    row[SLOT_EDGE_PLATFORM] = platform_code(edge_platform)
    row[SLOT_EDGE_STEP] = int(edge_step if edge_step is not None else step)
    row[SLOT_EDGES:SLOT_EDGES + 2 * EDGE_K:2] = -1.0
    for i, (dst, us) in enumerate(list(edges or [])[:EDGE_K]):
        row[SLOT_EDGES + 2 * i] = int(dst)
        row[SLOT_EDGES + 2 * i + 1] = float(us)
    return row


def decode_row(row, *, rank: Optional[int] = None) -> dict:
    """One wire row back into a record dict (plus ``edges`` /
    ``edges_platform`` when the source carried a measured fragment)."""
    row = np.asarray(row, np.float32)
    rec = {
        "step": int(row[SLOT_STEP]),
        "heartbeat": int(row[SLOT_HEARTBEAT]),
        "consensus_dist": float(row[SLOT_CONSENSUS]),
        "staleness": float(row[SLOT_STALENESS]),
        "plane_health": int(row[SLOT_HEALTH]),
        "plane_version": int(row[LANE_VERSION]),
        "plane_hop": int(row[LANE_HOP]),
    }
    if rank is not None:
        rec["rank"] = int(rank)
    pname = platform_name(row[SLOT_EDGE_PLATFORM])
    pairs = []
    for i in range(EDGE_K):
        dst = int(row[SLOT_EDGES + 2 * i])
        if dst >= 0:
            pairs.append((dst, float(row[SLOT_EDGES + 2 * i + 1])))
    if pname and pairs and rank is not None:
        rec["edges"] = [{"src": int(rank), "dst": d, "latency_us": us,
                         "bytes": 0, "rounds": 0, "inner": 0, "gbps": 0.0}
                        for d, us in pairs]
        rec["edges_platform"] = pname
        rec["edges_step"] = int(row[SLOT_EDGE_STEP])
    return rec


# -- state + cost model ------------------------------------------------------

def init_state(size: int) -> Dict[str, jnp.ndarray]:
    """Fresh plane state: nobody has heard anything (version 0
    everywhere).  ``table[j]`` is rank j's local view of all N sources;
    ``last_heard[j, s]`` the local step at which source s's row last
    advanced in j's view."""
    return {"table": jnp.zeros((size, size, WIRE), jnp.float32),
            "last_heard": jnp.zeros((size, size), jnp.int32)}


def permutes_per_round(topo: CompiledTopology) -> int:
    """Collective-permutes one exchange round issues: exactly one per
    circulant offset (the bflint plane-on budget and the ``bench-plane``
    overhead gate both count from here)."""
    return len(topo.shifts)


def wire_bytes_per_round(topo: CompiledTopology) -> int:
    """Bytes each rank sends per exchange round: the whole ``[N, WIRE]``
    f32 table once per offset."""
    return permutes_per_round(topo) * topo.size * WIRE * 4


def diameter(topo: CompiledTopology) -> int:
    """Hop-count diameter of the topology's edge graph — the propagation
    bound: a fact injected anywhere is fleet-wide within this many
    rounds (infinity encoded as ``topo.size`` when disconnected)."""
    n = topo.size
    adj = (np.asarray(topo.weight_matrix) != 0)
    np.fill_diagonal(adj, True)
    reach = np.eye(n, dtype=bool)
    for rounds in range(1, n + 1):
        nxt = reach @ adj
        if nxt.all():
            return rounds
        if (nxt == reach).all():
            return n                      # disconnected: never converges
        reach = nxt
    return n


# -- the exchange program ----------------------------------------------------

def plane_exchange(table, last_heard, axis_name, topo: CompiledTopology,
                   step, payload, active, link_ok):
    """One plane round for this rank: stamp own row, then per circulant
    offset ppermute the whole table and adopt strictly-newer source rows
    (hop + 1).  Axis-level — call inside an existing shard_map to
    piggyback on a training exchange, or through :func:`exchange` for
    the dedicated program.

    ``table``: [N, WIRE] local view.  ``last_heard``: [N] int32.
    ``payload``: [WIDTH] own telemetry.  ``active`` ([N]) and
    ``link_ok`` ([N, N]) are traced masks exactly as in
    ``resilience.membership.gossip_last_heard`` — dead senders and
    dropped links contribute nothing, so their sources age out."""
    from ..ops.collectives import _rotation_pairs
    size = topo.size
    idx = lax.axis_index(axis_name)
    stepi = jnp.asarray(step, jnp.int32)
    stepf = stepi.astype(jnp.float32)
    ar = jnp.arange(size)

    # own row: version = step + 1 (monotone with the step clock; 0 means
    # "never heard").  Only a participating rank stamps — a dead rank's
    # version freezes, which is exactly how it ages out everywhere.
    own = jnp.concatenate([
        jnp.asarray(payload, jnp.float32),
        jnp.stack([stepf + 1.0, jnp.float32(0.0)])])
    me_active = active[idx] > 0
    newer_self = own[LANE_VERSION] > table[idx, LANE_VERSION]
    stamp = me_active & newer_self
    table = table.at[idx].set(jnp.where(stamp, own, table[idx]))
    advanced = stamp & (ar == idx)

    for shift in topo.shifts:
        received = lax.ppermute(table, axis_name,
                                _rotation_pairs(size, shift.offset))
        src = (idx - shift.offset) % size
        # static edge mask: ppermute rotates ALL ranks; only real edges
        # of this offset may merge (non-destinations receive zeros)
        has_edge = jnp.asarray(shift.recv_weights != 0)[idx]
        valid = has_edge & (active[src] > 0) & (link_ok[src, idx] > 0)
        newer = received[:, LANE_VERSION] > table[:, LANE_VERSION]
        adopt = valid & newer
        table = jnp.where(adopt[:, None],
                          received.at[:, LANE_HOP].add(1.0), table)
        advanced = advanced | adopt
    last_heard = jnp.where(advanced, stepi, last_heard)
    return table, last_heard


@functools.lru_cache(maxsize=64)
def _plane_fn(axis, topo: CompiledTopology, mesh_id):
    from ..context import ctx
    cx = ctx()
    spec = P(cx.rank_axis)

    def wrapper(table, last_heard, step, payload, active, link_ok):
        def shard_fn(tables, lh, step_s, pay_s, active_s, link_s):
            t, h = plane_exchange(tables[0], lh[0], axis, topo, step_s,
                                  pay_s[0], active_s, link_s)
            return t[None], h[None]
        return jax.shard_map(
            shard_fn, mesh=cx.mesh,
            in_specs=(spec, spec, P(), spec, P(), P()),
            out_specs=(spec, spec),
        )(table, last_heard, step, payload, active, link_ok)
    return jax.jit(wrapper)


def exchange(state: Dict[str, jnp.ndarray], payload, step,
             active=None, link_ok=None,
             topo: Optional[CompiledTopology] = None
             ) -> Dict[str, jnp.ndarray]:
    """Run one plane round over the context topology (or ``topo``).

    ``payload``: [N, WIDTH] — every rank's own row (a single-controller
    SPMD program publishes for the whole virtual fleet at once).
    ``step``/``payload``/``active``/``link_ok`` are all traced data:
    every call reuses ONE compiled program per (axis, topo, mesh)."""
    from ..context import ctx
    from ..ops import api as _api
    cx = ctx()
    topo = topo or cx.compiled_topology
    n = topo.size
    if active is None:
        active = jnp.ones((n,), jnp.float32)
    if link_ok is None:
        link_ok = jnp.ones((n, n), jnp.float32)
    fn = _plane_fn(cx.rank_axis, topo, id(cx.mesh))
    sharding = _api.rank_sharding()
    table = jax.device_put(
        jnp.asarray(state["table"], jnp.float32), sharding)
    heard = jax.device_put(
        jnp.asarray(state["last_heard"], jnp.int32), sharding)
    pay = jax.device_put(jnp.asarray(payload, jnp.float32), sharding)
    table, heard = fn(table, heard, jnp.asarray(step, jnp.int32), pay,
                      jnp.asarray(active, jnp.float32),
                      jnp.asarray(link_ok, jnp.float32))
    return {"table": table, "last_heard": heard}


def host_merge(table, received, last_heard, step):
    """Host-side (numpy) newest-version-wins merge of a received
    ``[N, WIRE]`` table into a local one — the EXACT rule
    :func:`plane_exchange` applies on-device, for transports that carry
    plane rows outside the mesh (``fleet/peers.py``'s per-process socket
    gossip between OS processes).  Adopted source rows travel one more
    hop; ``last_heard`` entries of adopted rows advance to ``step``.
    Returns ``(table, last_heard)`` as fresh arrays."""
    table = np.asarray(table, np.float32)
    received = np.asarray(received, np.float32)
    heard = np.asarray(last_heard, np.int64).copy()
    if received.shape != table.shape:
        raise ValueError(
            f"received table shape {received.shape} != local "
            f"{table.shape}")
    newer = received[:, LANE_VERSION] > table[:, LANE_VERSION]
    adopted = received.copy()
    adopted[:, LANE_HOP] += 1.0
    out = np.where(newer[:, None], adopted, table)
    heard[newer] = int(step)
    return out, heard


# -- local fleet view --------------------------------------------------------

def snapshot(state, step: int, *, rank: int = 0,
             max_age: Optional[int] = None) -> List[dict]:
    """Decode rank ``rank``'s local table into per-source record dicts
    (sources never heard — version 0 — are omitted)."""
    max_age = resolve_max_age(max_age)
    table = np.asarray(state["table"])[rank]
    heard = np.asarray(state["last_heard"])[rank]
    now_us = int(time.time() * 1e6)
    out = []
    for src in range(table.shape[0]):
        if table[src, LANE_VERSION] <= 0:
            continue
        rec = decode_row(table[src], rank=src)
        age = int(step) - int(heard[src])
        rec["plane_age"] = age
        rec["plane_stale"] = age > max_age
        rec["t_us"] = now_us
        out.append(rec)
    return out


class FleetViewLive(AG.FleetView):
    """A plane-backed fleet view: the health engine's FleetView surface
    over one rank's gossiped table instead of JSONL files on disk.

    ``per_source``: rank -> ``{"version", "age", "hop", "stale",
    "step"}`` — the merge metadata ``bfmonitor --plane`` renders.
    ``plane_step``: the observer's step at snapshot time.

    Unlike a file-backed view, plane snapshots are SAMPLES of an
    eventually-consistent table, not an append-only log — a publish
    cadence above 1 leaves legitimate holes in each source's step
    sequence, so loader-style ``missing_steps`` gaps are dropped (dead
    sources are still caught by the ``dead_rank`` rule and the stale
    flag; silent sources by ``expected_ranks`` -> ``rank_silent``)."""

    def __init__(self, series, gaps, expected_ranks, per_source,
                 plane_step: int):
        super().__init__(series, gaps, expected_ranks=expected_ranks)
        self.gaps = [g for g in self.gaps if g.kind != "missing_steps"]
        self.per_source = per_source
        self.plane_step = int(plane_step)

    def alive_mask(self, confirm_after: Optional[int] = None) -> np.ndarray:
        """[N] float32 liveness from plane age: 1.0 while a source's row
        advanced within ``confirm_after`` steps (default: the stale
        flag's ``BLUEFOG_PLANE_MAX_AGE``).  Feed to the serving router's
        ``observe`` / ``repair_matrix``."""
        n = self.expected_ranks or (max(self.per_source) + 1
                                    if self.per_source else 0)
        out = np.zeros((n,), np.float32)
        for src, meta in self.per_source.items():
            if src >= n:
                continue
            if confirm_after is None:
                out[src] = 0.0 if meta["stale"] else 1.0
            else:
                out[src] = 1.0 if meta["age"] <= confirm_after else 0.0
        return out

    def staleness_of(self, rank: int) -> Optional[float]:
        """A source's own reported staleness watermark (newest sample)."""
        series = self.series_of(rank, "staleness")
        return series[-1][1] if series else None


def matrix_from_view(view: FleetViewLive):
    """Assemble the plane-gossiped edge-cost rows into one
    :class:`~bluefog_tpu.observability.commprof.EdgeCostMatrix` (None
    when no live source carried a measured fragment or platforms
    disagree).  Rows from stale sources are skipped; the result carries
    the newest probe step and the common platform, so
    ``commprof.matrix_is_usable(..., age_steps=)`` gates it exactly like
    a file artifact."""
    from . import commprof as CP
    entries: Dict[Tuple[int, int], dict] = {}
    platforms = set()
    newest = None
    n = view.expected_ranks or 0
    for src in view.ranks:
        meta = view.per_source.get(src)
        if meta is None or meta["stale"]:
            continue
        by_step = view.per_rank.get(src) or {}
        for step in sorted(by_step):
            rec = by_step[step]
            if not rec.get("edges"):
                continue
            for e in rec["edges"]:
                entries[(int(e["src"]), int(e["dst"]))] = dict(e)
            platforms.add(rec.get("edges_platform"))
            es = rec.get("edges_step")
            if es is not None:
                newest = es if newest is None else max(newest, es)
        n = max(n, src + 1)
    if not entries or len(platforms) != 1:
        return None
    return CP.EdgeCostMatrix(n, list(entries.values()), step=newest,
                             platform=platforms.pop())


# -- the host-side plane object ----------------------------------------------

class TelemetryPlane:
    """One rank's handle on the in-band telemetry plane.

    Owns the gossiped state, runs :func:`exchange` rounds, and keeps a
    bounded per-source history of LOCAL snapshots so the health engine's
    trailing-window rules see series, not just the newest sample.
    Everything it consumes arrived over the fabric: :meth:`view` needs
    nothing but this rank's own table."""

    def __init__(self, topo: Optional[CompiledTopology] = None, *,
                 rank: Optional[int] = None,
                 max_age: Optional[int] = None,
                 window: Optional[int] = None):
        from ..context import ctx
        cx = ctx()
        self.topo = topo or cx.compiled_topology
        self.size = self.topo.size
        self.rank = cx.rank() if rank is None else int(rank)
        self.max_age = resolve_max_age(max_age)
        self.window = resolve_window(window)
        self.state = init_state(self.size)
        self.step = 0
        self._records: Dict[int, Dict[int, dict]] = {}
        self._trail = None

    def attach_trail(self, trail) -> None:
        """Bank a ``kind: plane`` record per observation into a
        :class:`~bluefog_tpu.observability.export.PlaneTrail`."""
        self._trail = trail

    # -- publish / observe ---------------------------------------------------

    def publish(self, payloads, step, *, active=None, link_ok=None,
                rounds: int = 1):
        """Stamp + disseminate: run ``rounds`` exchange rounds with the
        fleet's ``[N, WIDTH]`` payload rows (see :func:`pack_payload`),
        then snapshot the local view into the history."""
        for _ in range(max(1, int(rounds))):
            self.state = exchange(self.state, payloads, step,
                                  active=active, link_ok=link_ok,
                                  topo=self.topo)
        self.observe(step)
        return self.state

    def observe(self, step) -> List[dict]:
        """Snapshot this rank's table at ``step`` into the rolling
        history (and the trail / registry gauges when enabled)."""
        self.step = int(step)
        recs = snapshot(self.state, self.step, rank=self.rank,
                        max_age=self.max_age)
        for rec in recs:
            by_step = self._records.setdefault(rec["rank"], {})
            by_step[rec["step"]] = rec
            for old in sorted(by_step)[:-self.window]:
                del by_step[old]
        if _metrics.enabled():
            live = [r for r in recs if not r["plane_stale"]]
            _metrics.gauge(
                "bf_plane_live_sources",
                "plane sources whose row advanced within the max age"
            ).set(float(len(live)))
            _metrics.gauge(
                "bf_plane_age_max",
                "oldest per-source age in the local plane view (steps)"
            ).set(float(max((r["plane_age"] for r in recs), default=0)))
        if self._trail is not None:
            self._trail.write({
                "kind": "plane", "step": self.step,
                "sources": [{
                    "rank": r["rank"], "step": r["step"],
                    "version": r["plane_version"], "age": r["plane_age"],
                    "hop": r["plane_hop"], "stale": r["plane_stale"],
                } for r in recs]})
        return recs

    # -- consumption ---------------------------------------------------------

    def per_source(self) -> Dict[int, dict]:
        meta = {}
        for rec in snapshot(self.state, self.step, rank=self.rank,
                            max_age=self.max_age):
            meta[rec["rank"]] = {
                "version": rec["plane_version"], "age": rec["plane_age"],
                "hop": rec["plane_hop"], "stale": rec["plane_stale"],
                "step": rec["step"],
            }
        return meta

    def view(self, *, expected_ranks: Optional[int] = None
             ) -> FleetViewLive:
        """The plane-backed FleetView over this rank's local table —
        hand it straight to ``health.evaluate`` / the router / the
        controller."""
        series = []
        for src in sorted(self._records):
            recs = [self._records[src][s]
                    for s in sorted(self._records[src])]
            series.append(AG.RankSeries(rank=src, records=recs))
        return FleetViewLive(
            series, [], expected_ranks or self.size,
            self.per_source(), self.step)

    def versions(self) -> np.ndarray:
        """[N] per-source versions in this rank's view (0 = never
        heard)."""
        return np.asarray(
            self.state["table"])[self.rank, :, LANE_VERSION].copy()

    def reached(self, src: int) -> np.ndarray:
        """[N] bool: which ranks hold a copy of ``src``'s row — the
        propagation-bound probe ``make bench-plane`` loops on."""
        table = np.asarray(self.state["table"])
        return table[:, src, LANE_VERSION] > 0
