"""Telemetry/metrics exporters: JSONL step series, Prometheus text,
Perfetto counter lanes.

Three sinks over the same data (``ingraph.TelemetrySnapshot`` aux outputs
plus the host registry, ``observability/metrics.py``):

* **JSONL** — one line per logged step, ``<prefix><rank>.jsonl``
  (activation mirrors the timeline: ``BLUEFOG_METRICS=<prefix>`` before
  ``bf.init()``, or :func:`metrics_start` explicitly).  Schema:
  ``{"step", "t_us", "rank", <telemetry fields as float or [N] list>,
  "counters": {registry snapshot}}`` — see ``docs/observability.md``.
* **Prometheus text** — :func:`prometheus_text` renders the registry in
  exposition format for a scrape endpoint or a ``curl``-able dump.
* **Timeline counter lanes** — when the Chrome-tracing timeline is open,
  :func:`log_step` also emits ``"ph":"C"`` counter events
  (``timeline.record_counter``), so consensus distance, norms, and queue
  depth render as live graph lanes NEXT TO the op spans in Perfetto —
  the "watch the consensus process" headline UX.
"""

import json
import os
import time
from typing import Dict, Optional

from .. import timeline as _tl
from . import metrics as _metrics

__all__ = [
    "METRICS_ENV", "metrics_start", "metrics_end", "metrics_active",
    "metrics_path", "log_step", "telemetry_to_host", "prometheus_text",
    "validate_jsonl", "REQUIRED_JSONL_KEYS",
]

METRICS_ENV = "BLUEFOG_METRICS"

# every JSONL line carries at least these keys (validate_jsonl contract,
# shared by the tests and `make metrics-smoke`)
REQUIRED_JSONL_KEYS = ("step", "t_us", "rank")

# (file handle, path, rank, t0, enabled_registry_here)
_sink = [None]


def metrics_active() -> bool:
    return _sink[0] is not None


def metrics_path() -> Optional[str]:
    return _sink[0][1] if _sink[0] else None


def metrics_start(file_prefix: Optional[str] = None,
                  rank: Optional[int] = None) -> Optional[str]:
    """Open the per-rank JSONL metrics file and enable the host registry.

    Called automatically by ``bf.init()`` when ``BLUEFOG_METRICS`` is set
    (the same activation pattern as ``BLUEFOG_TIMELINE``).  Returns the
    path, or None when no prefix resolves."""
    if metrics_active():
        raise RuntimeError(
            "metrics export already started; call metrics_end() first")
    if file_prefix is None:
        file_prefix = os.environ.get(METRICS_ENV)
    if not file_prefix:
        return None
    if rank is None:
        from .. import context as _ctx
        rank = _ctx.ctx().rank() if _ctx.is_initialized() else 0
    path = f"{file_prefix}{rank}.jsonl"
    f = open(path, "w")
    enabled_here = not _metrics.enabled()
    _metrics.enable()
    _sink[0] = (f, path, rank, time.perf_counter(), enabled_here)
    return path


def metrics_end() -> None:
    """Close the JSONL sink (idempotent).  The registry keeps its values —
    only the enable flag is restored when :func:`metrics_start` set it."""
    if _sink[0] is None:
        return
    f, _path, _rank, _t0, enabled_here = _sink[0]
    _sink[0] = None
    try:
        f.close()
    finally:
        if enabled_here:
            _metrics.disable()


def telemetry_to_host(snapshot) -> Dict[str, object]:
    """TelemetrySnapshot (or mapping) with device leaves -> plain floats /
    float lists, ready for ``json.dumps``.  ``[N]`` leaves (the global
    view a wrapped step returns) become per-rank lists; scalars become
    floats."""
    import numpy as np
    if hasattr(snapshot, "asdict"):
        snapshot = snapshot.asdict()
    elif hasattr(snapshot, "_asdict"):
        snapshot = snapshot._asdict()
    out = {}
    for k, v in dict(snapshot).items():
        a = np.asarray(v, dtype=np.float64)
        if a.ndim == 0:
            out[k] = float(a)
        else:
            out[k] = [float(x) for x in a.reshape(-1)]
    return out


def _mean(v) -> float:
    return float(sum(v) / len(v)) if isinstance(v, list) else float(v)


def log_step(step: int, telemetry=None, extra: Optional[Dict] = None,
             counters: bool = True) -> Optional[Dict]:
    """Write one JSONL record for ``step`` and mirror the numeric fields
    onto the timeline as counter lanes.

    ``telemetry``: a :class:`~.ingraph.TelemetrySnapshot` (device arrays
    fine — fetched here, OUTSIDE the jitted step) or an already-host dict.
    ``extra``: additional JSON-able fields merged into the record.
    ``counters=False`` skips the registry snapshot (cheaper lines).
    Returns the record written, or None when no sink is open AND no
    timeline is recording (nothing to do)."""
    sink = _sink[0]
    timeline_on = _tl.timeline_enabled()
    if sink is None and not timeline_on:
        return None
    record: Dict[str, object] = {
        "step": int(step),
        "t_us": int((time.perf_counter() - (sink[3] if sink else 0.0)) * 1e6),
        "rank": sink[2] if sink else 0,
    }
    tel_host = telemetry_to_host(telemetry) if telemetry is not None else {}
    record.update(tel_host)
    if extra:
        record.update(extra)
    if counters and _metrics.enabled():
        record["counters"] = _metrics.registry.snapshot()
    if sink is not None:
        f = sink[0]
        f.write(json.dumps(record) + "\n")
        f.flush()
    if timeline_on:
        # Perfetto counter lanes: per-rank telemetry collapses to the mean
        # (one value per timestamp per lane); host gauges ride along so
        # queue depth lines up with the op spans
        for k, v in tel_host.items():
            if k == "step":
                continue
            _tl.record_counter(f"telemetry/{k}", _mean(v))
        if extra:
            for k, v in extra.items():
                if isinstance(v, (int, float)):
                    _tl.record_counter(f"telemetry/{k}", float(v))
    return record


def prometheus_text(reg: Optional[_metrics.Registry] = None) -> str:
    """Render the registry in Prometheus exposition format."""
    reg = reg or _metrics.registry
    lines = []
    for m in reg.metrics():
        if m.help:
            lines.append(f"# HELP {m.name} {m.help}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key, val in m._items():
            labels = ",".join(f'{k}="{v}"' for k, v in key)
            if m.kind == "histogram":
                for le, c in zip(m.buckets, val["buckets"]):
                    ls = (labels + "," if labels else "") + f'le="{le}"'
                    lines.append(f"{m.name}_bucket{{{ls}}} {c}")
                ls = (labels + "," if labels else "") + 'le="+Inf"'
                lines.append(f"{m.name}_bucket{{{ls}}} {val['count']}")
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"{m.name}_sum{suffix} {val['sum']}")
                lines.append(f"{m.name}_count{suffix} {val['count']}")
            else:
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"{m.name}{suffix} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


def validate_jsonl(path: str, required=REQUIRED_JSONL_KEYS):
    """Parse a metrics JSONL file, enforcing the schema: every line is a
    JSON object carrying ``required`` keys, with every numeric field
    finite.  Returns the records; raises ValueError on violations (the
    ``make metrics-smoke`` gate)."""
    import math
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON: {e}")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{lineno}: not a JSON object")
            missing = [k for k in required if k not in rec]
            if missing:
                raise ValueError(f"{path}:{lineno}: missing keys {missing}")

            def check(k, v):
                if isinstance(v, float) and not math.isfinite(v):
                    raise ValueError(
                        f"{path}:{lineno}: non-finite value for {k!r}")
                if isinstance(v, list):
                    for x in v:
                        check(k, x)
            for k, v in rec.items():
                if not isinstance(v, dict):
                    check(k, v)
            records.append(rec)
    return records
