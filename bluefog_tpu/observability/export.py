"""Telemetry/metrics exporters: JSONL step series, Prometheus text,
Perfetto counter lanes.

Three sinks over the same data (``ingraph.TelemetrySnapshot`` aux outputs
plus the host registry, ``observability/metrics.py``):

* **JSONL** — one line per logged step, ``<prefix><rank>.jsonl``
  (activation mirrors the timeline: ``BLUEFOG_METRICS=<prefix>`` before
  ``bf.init()``, or :func:`metrics_start` explicitly).  Schema:
  ``{"step", "t_us", "rank", <telemetry fields as float or [N] list>,
  "counters": {registry snapshot}}`` — see ``docs/observability.md``.
* **Prometheus text** — :func:`prometheus_text` renders the registry in
  exposition format for a scrape endpoint or a ``curl``-able dump.
* **Timeline counter lanes** — when the Chrome-tracing timeline is open,
  :func:`log_step` also emits ``"ph":"C"`` counter events
  (``timeline.record_counter``), so consensus distance, norms, and queue
  depth render as live graph lanes NEXT TO the op spans in Perfetto —
  the "watch the consensus process" headline UX.
"""

import json
import os
import time
from typing import Dict, Optional

from .. import timeline as _tl
from . import metrics as _metrics
from . import phases as _phases

__all__ = [
    "METRICS_ENV", "metrics_start", "metrics_end", "metrics_active",
    "metrics_path", "log_step", "telemetry_to_host", "prometheus_text",
    "validate_jsonl", "REQUIRED_JSONL_KEYS",
]

METRICS_ENV = "BLUEFOG_METRICS"

# every JSONL line carries at least these keys (validate_jsonl contract,
# shared by the tests and `make metrics-smoke`)
REQUIRED_JSONL_KEYS = ("step", "t_us", "rank")


class _Sink:
    """Open JSONL sink: file handle + rank + clocks.  ``last_log`` feeds
    the per-record ``step_wall_us`` field (host wall time since the
    previous ``log_step`` — the straggler-attribution time base the
    fleet aggregator reads)."""

    __slots__ = ("f", "path", "rank", "t0", "enabled_here", "last_log")

    def __init__(self, f, path, rank, t0, enabled_here):
        self.f = f
        self.path = path
        self.rank = rank
        self.t0 = t0
        self.enabled_here = enabled_here
        self.last_log = None


_sink = [None]


def metrics_active() -> bool:
    return _sink[0] is not None


def metrics_path() -> Optional[str]:
    return _sink[0].path if _sink[0] else None


def metrics_start(file_prefix: Optional[str] = None,
                  rank: Optional[int] = None) -> Optional[str]:
    """Open the per-rank JSONL metrics file and enable the host registry.

    Called automatically by ``bf.init()`` when ``BLUEFOG_METRICS`` is set
    (the same activation pattern as ``BLUEFOG_TIMELINE``).  Returns the
    path, or None when no prefix resolves."""
    if metrics_active():
        raise RuntimeError(
            "metrics export already started; call metrics_end() first")
    if file_prefix is None:
        file_prefix = os.environ.get(METRICS_ENV)
    if not file_prefix:
        return None
    if rank is None:
        from .. import context as _ctx
        rank = _ctx.ctx().rank() if _ctx.is_initialized() else 0
    path = f"{file_prefix}{rank}.jsonl"
    f = open(path, "w")
    enabled_here = not _metrics.enabled()
    _metrics.enable()
    # phases timed by a previous loop that never logged them must not be
    # misattributed to this sink's first record
    _phases.reset_step_phases()
    _sink[0] = _Sink(f, path, rank, time.perf_counter(), enabled_here)
    return path


def metrics_end() -> None:
    """Close the JSONL sink (idempotent).  The registry keeps its values —
    only the enable flag is restored when :func:`metrics_start` set it."""
    sink = _sink[0]
    if sink is None:
        return
    _sink[0] = None
    try:
        sink.f.close()
    finally:
        if sink.enabled_here:
            _metrics.disable()


def telemetry_to_host(snapshot) -> Dict[str, object]:
    """TelemetrySnapshot (or mapping) with device leaves -> plain floats /
    float lists, ready for ``json.dumps``.  ``[N]`` leaves (the global
    view a wrapped step returns) become per-rank lists; scalars become
    floats."""
    import numpy as np
    if hasattr(snapshot, "asdict"):
        snapshot = snapshot.asdict()
    elif hasattr(snapshot, "_asdict"):
        snapshot = snapshot._asdict()
    out = {}
    for k, v in dict(snapshot).items():
        a = np.asarray(v, dtype=np.float64)
        if a.ndim == 0:
            out[k] = float(a)
        else:
            out[k] = [float(x) for x in a.reshape(-1)]
    return out


def _mean(v) -> float:
    return float(sum(v) / len(v)) if isinstance(v, list) else float(v)


def log_step(step: int, telemetry=None, extra: Optional[Dict] = None,
             counters: bool = True) -> Optional[Dict]:
    """Write one JSONL record for ``step`` and mirror the numeric fields
    onto the timeline as counter lanes.

    ``telemetry``: a :class:`~.ingraph.TelemetrySnapshot` (device arrays
    fine — fetched here, OUTSIDE the jitted step) or an already-host dict.
    ``extra``: additional JSON-able fields merged into the record.
    ``counters=False`` skips the registry snapshot (cheaper lines).

    Beyond the telemetry fields the record carries ``step_wall_us``
    (host wall time since the previous ``log_step`` on this sink — the
    per-rank step-time series the fleet aggregator and the health
    engine's straggler rule consume) and, when the step loop timed any
    :mod:`~.phases` phases, a ``"phases": {name: seconds}`` dict (the
    device->host telemetry fetch below is itself timed as the
    ``export`` phase).

    Returns the record written, or None when no sink is open AND no
    timeline is recording (nothing to do)."""
    sink = _sink[0]
    timeline_on = _tl.timeline_enabled()
    if sink is None and not timeline_on:
        return None
    now = time.perf_counter()
    record: Dict[str, object] = {
        "step": int(step),
        "t_us": int((now - (sink.t0 if sink else 0.0)) * 1e6),
        "rank": sink.rank if sink else 0,
    }
    if sink is not None:
        if sink.last_log is not None:
            record["step_wall_us"] = int((now - sink.last_log) * 1e6)
        sink.last_log = now
    # the snapshot fetch is the device sync — THE host-visible export
    # cost; time it as the `export` phase so it lands in this record
    t_fetch = time.perf_counter()
    tel_host = telemetry_to_host(telemetry) if telemetry is not None else {}
    if telemetry is not None:
        _phases.record_phase("export", time.perf_counter() - t_fetch)
    # the snapshot's in-graph step counter must not clobber the caller's
    # log index (several loops may share one sink, and on the virtual
    # mesh it is an [N] list, not a scalar)
    tel_host.pop("step", None)
    record.update(tel_host)
    if extra:
        record.update(extra)
    staged = _phases.take_step_phases()
    if staged:
        record["phases"] = staged
    if counters and _metrics.enabled():
        record["counters"] = _metrics.registry.snapshot()
    if sink is not None:
        sink.f.write(json.dumps(record) + "\n")
        sink.f.flush()
    if timeline_on:
        # Perfetto counter lanes: each per-rank telemetry field renders
        # as its cross-rank mean PLUS `_min`/`_max` companion lanes —
        # a single straggling or diverging rank must stay visible in the
        # trace instead of averaging away; host gauges ride along so
        # queue depth lines up with the op spans
        for k, v in tel_host.items():
            _tl.record_counter(f"telemetry/{k}", _mean(v))
            if isinstance(v, list) and len(v) > 1:
                _tl.record_counter(f"telemetry/{k}_min", min(v))
                _tl.record_counter(f"telemetry/{k}_max", max(v))
        if extra:
            for k, v in extra.items():
                if isinstance(v, (int, float)):
                    _tl.record_counter(f"telemetry/{k}", float(v))
    return record


def _escape_label_value(v: str) -> str:
    """Label-value escaping per the Prometheus exposition format:
    backslash, double-quote, and line-feed must be escaped (in that
    order — escaping the backslash last would double the others)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    """HELP text escaping (exposition format): backslash and line-feed
    only — quotes are legal in HELP."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(reg: Optional[_metrics.Registry] = None) -> str:
    """Render the registry in Prometheus exposition format."""
    reg = reg or _metrics.registry
    lines = []
    for m in reg.metrics():
        if m.help:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key, val in m._items():
            labels = ",".join(f'{k}="{_escape_label_value(v)}"'
                              for k, v in key)
            if m.kind == "histogram":
                for le, c in zip(m.buckets, val["buckets"]):
                    ls = (labels + "," if labels else "") + f'le="{le}"'
                    lines.append(f"{m.name}_bucket{{{ls}}} {c}")
                ls = (labels + "," if labels else "") + 'le="+Inf"'
                lines.append(f"{m.name}_bucket{{{ls}}} {val['count']}")
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"{m.name}_sum{suffix} {val['sum']}")
                lines.append(f"{m.name}_count{suffix} {val['count']}")
            else:
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"{m.name}{suffix} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


def validate_jsonl(path: str, required=REQUIRED_JSONL_KEYS):
    """Parse a metrics JSONL file, enforcing the schema: every line is a
    JSON object carrying ``required`` keys, with every numeric field
    finite.  Returns the records; raises ValueError on violations (the
    ``make metrics-smoke`` gate)."""
    import math
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON: {e}")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{lineno}: not a JSON object")
            missing = [k for k in required if k not in rec]
            if missing:
                raise ValueError(f"{path}:{lineno}: missing keys {missing}")

            def check(k, v):
                if isinstance(v, float) and not math.isfinite(v):
                    raise ValueError(
                        f"{path}:{lineno}: non-finite value for {k!r}")
                if isinstance(v, list):
                    for x in v:
                        check(k, x)
            for k, v in rec.items():
                if not isinstance(v, dict):
                    check(k, v)
            records.append(rec)
    return records
