"""Telemetry/metrics exporters: JSONL step series, Prometheus text,
Perfetto counter lanes.

Three sinks over the same data (``ingraph.TelemetrySnapshot`` aux outputs
plus the host registry, ``observability/metrics.py``):

* **JSONL** — one line per logged step, ``<prefix><rank>.jsonl``
  (activation mirrors the timeline: ``BLUEFOG_METRICS=<prefix>`` before
  ``bf.init()``, or :func:`metrics_start` explicitly).  Schema:
  ``{"step", "t_us", "rank", <telemetry fields as float or [N] list>,
  "counters": {registry snapshot}}`` — see ``docs/observability.md``.
* **Prometheus text** — :func:`prometheus_text` renders the registry in
  exposition format for a scrape endpoint or a ``curl``-able dump.
* **Timeline counter lanes** — when the Chrome-tracing timeline is open,
  :func:`log_step` also emits ``"ph":"C"`` counter events
  (``timeline.record_counter``), so consensus distance, norms, and queue
  depth render as live graph lanes NEXT TO the op spans in Perfetto —
  the "watch the consensus process" headline UX.
"""

import json
import os
import time
from typing import Dict, Optional

from .. import timeline as _tl
from . import metrics as _metrics
from . import phases as _phases

__all__ = [
    "METRICS_ENV", "metrics_start", "metrics_end", "metrics_active",
    "metrics_path", "log_step", "telemetry_to_host", "prometheus_text",
    "validate_jsonl", "REQUIRED_JSONL_KEYS", "resolve_rotation",
    "rotate_file", "read_trail", "Trail", "MAX_MB_ENV", "KEEP_ENV",
    "MEMBERSHIP_SUFFIX", "MembershipTrail", "read_membership_trail",
    "CKPT_SUFFIX", "CkptTrail", "read_ckpt_trail",
    "ASYNC_SUFFIX", "AsyncTrail", "read_async_trail",
    "PLANE_SUFFIX", "PlaneTrail", "read_plane_trail",
    "FLEET_SUFFIX", "FleetTrail", "read_fleet_trail",
]

METRICS_ENV = "BLUEFOG_METRICS"

# size-based rotation of the append-only JSONL sinks (the per-rank
# telemetry series here and the health verdict trail in health.py): a
# long fleet run must not fill the disk.  0 / unset = unbounded.
MAX_MB_ENV = "BLUEFOG_METRICS_MAX_MB"
KEEP_ENV = "BLUEFOG_METRICS_KEEP"
DEFAULT_KEEP = 3

# every JSONL line carries at least these keys (validate_jsonl contract,
# shared by the tests and `make metrics-smoke`)
REQUIRED_JSONL_KEYS = ("step", "t_us", "rank")


def resolve_rotation(max_mb: Optional[float] = None,
                     keep: Optional[int] = None) -> tuple:
    """``(max_bytes, keep)`` rotation policy: explicit arguments win,
    else ``BLUEFOG_METRICS_MAX_MB`` / ``BLUEFOG_METRICS_KEEP``.
    ``max_bytes`` 0 disables rotation."""
    if max_mb is None:
        max_mb = float(os.environ.get(MAX_MB_ENV, "0") or 0)
    if keep is None:
        keep = int(os.environ.get(KEEP_ENV, str(DEFAULT_KEEP)))
    return int(max_mb * (1 << 20)), max(1, keep)


def read_trail(path: str, config_kind: str, kinds=None):
    """Tolerant sidecar-trail reader shared by the controller's decision
    trail and the serving trail: ``(config_record_or_None, records)``.

    Unparseable or non-object lines are skipped, a missing file reads as
    empty (a monitor's discovery probe must never raise), and the FIRST
    ``config_kind`` record wins as the head.  ``kinds`` (optional tuple)
    keeps only records of those kinds; None keeps every non-config
    record."""
    config, records = None, []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(rec, dict):
                    continue
                if rec.get("kind") == config_kind and config is None:
                    config = rec
                elif kinds is None or rec.get("kind") in kinds:
                    records.append(rec)
    except OSError:
        pass
    return config, records


class Trail:
    """Append-only sidecar JSONL with the shared size-based rotation
    (``BLUEFOG_METRICS_MAX_MB`` / ``BLUEFOG_METRICS_KEEP``) — the writer
    half of :func:`read_trail`, shared by the controller's decision
    trail, the serving trail (``serving/router.py``), and the
    elastic-membership trail (:class:`MembershipTrail`).

    ``head_kind``: the config-record kind whose first occurrence is
    re-written after every rotation, so a rotated trail never orphans
    its records from the run's identity."""

    def __init__(self, path: str, head_kind: Optional[str] = None):
        self.path = path
        self.head_kind = head_kind
        self.t0 = time.perf_counter()
        self.max_bytes, self.keep = resolve_rotation()
        self._bytes = 0
        self._head_line = None
        self.f = open(path, "w")

    def write(self, record: dict) -> dict:
        record = dict(record)
        record.setdefault("t_us",
                          int((time.perf_counter() - self.t0) * 1e6))
        line = json.dumps(record) + "\n"
        if (self.head_kind is not None and self._head_line is None
                and record.get("kind") == self.head_kind):
            self._head_line = line
        if (self.max_bytes and self._bytes
                and self._bytes + len(line) > self.max_bytes):
            self.f.close()
            rotate_file(self.path, self.keep)
            self.f = open(self.path, "w")
            self._bytes = 0
            if self._head_line and line != self._head_line:
                self.f.write(self._head_line)
                self._bytes += len(self._head_line)
        self.f.write(line)
        self.f.flush()
        self._bytes += len(line)
        return record

    def close(self) -> None:
        try:
            self.f.close()
        except Exception:
            pass


# -- elastic-membership trail (resilience/membership.py's reporting sink) ----

MEMBERSHIP_SUFFIX = "membership.jsonl"


class MembershipTrail(Trail):
    """Sidecar JSONL for elastic-membership runs
    (``<prefix>membership.jsonl``): a ``membership_config`` head record
    (fleet size + pre-allocated capacity ranks), one periodic
    ``membership`` state record per logged step, and one
    ``membership_event`` line per state transition — the
    machine-readable feed ``bfmonitor --membership`` renders and
    ``validate_jsonl`` gates (docs/resilience.md "Elastic membership")."""

    def __init__(self, path: str, *, size: int, capacity=()):
        super().__init__(path, head_kind="membership_config")
        self.write({"kind": "membership_config", "size": int(size),
                    "capacity": [int(r) for r in capacity]})

    def write_state(self, step: int, states: Dict[int, str],
                    counts: Dict[str, int]) -> dict:
        return self.write({
            "kind": "membership", "step": int(step),
            "states": {str(r): s for r, s in sorted(states.items())},
            "active": int(counts.get("active", 0)),
            "syncing": int(counts.get("syncing", 0)),
            "alive": int(counts.get("active", 0)
                         + counts.get("syncing", 0)
                         + counts.get("announced", 0)),
        })

    def write_event(self, step: int, rank: int, transition: str) -> dict:
        return self.write({"kind": "membership_event", "step": int(step),
                           "rank": int(rank), "transition": transition})


def read_membership_trail(path: str):
    """Tolerant reader: ``(config_record_or_None, records)`` — the same
    contract as ``read_decisions`` / ``read_serving_trail``."""
    return read_trail(path, "membership_config")


# -- durable-fleet-state trail (checkpoint/ subsystem's reporting sink) ------

CKPT_SUFFIX = "ckpt.jsonl"


class CkptTrail(Trail):
    """Sidecar JSONL for the durable-fleet-state subsystem
    (``<prefix>ckpt.jsonl``): a ``ckpt_config`` head record (directory,
    cadence, retention, replica fan-out), one ``ckpt`` record per
    durable save (last durable step, bytes, wall seconds), and one
    ``ckpt_event`` line per protocol event (``save_begin`` /
    ``save_commit`` / ``save_skipped`` / ``torn_shard`` /
    ``replica_repair`` / ``manifest_fallback`` / ``restore`` /
    ``elastic_restore``) — the machine-readable feed ``bfmonitor
    --checkpoint`` renders and ``validate_jsonl`` gates
    (docs/checkpoint.md).

    Unlike the other trails (single-writer by construction) this one is
    written from several threads — the step loop (save_begin/skip
    events), the background commit thread (ckpt records), and a restore
    caller handed ``FleetCheckpointer.trail`` — so :meth:`write` is
    serialized with an internal lock (the base ``Trail``'s rotation
    bookkeeping is not thread-safe on its own)."""

    def __init__(self, path: str, *, directory: str, every: int,
                 keep: int, replicas: int, size: int):
        import threading
        self._wlock = threading.Lock()
        super().__init__(path, head_kind="ckpt_config")
        self.write({"kind": "ckpt_config", "dir": str(directory),
                    "every": int(every), "keep": int(keep),
                    "replicas": int(replicas), "size": int(size)})

    def write(self, record: dict) -> dict:
        with self._wlock:
            return super().write(record)

    def write_save(self, step: int, *, durable_step: int, nbytes: int,
                   save_s: float, shards: int) -> dict:
        return self.write({"kind": "ckpt", "step": int(step),
                           "durable_step": int(durable_step),
                           "bytes": int(nbytes), "save_s": float(save_s),
                           "shards": int(shards)})

    def write_event(self, step: int, event: str, *,
                    rank: Optional[int] = None,
                    detail: Optional[str] = None) -> dict:
        rec = {"kind": "ckpt_event", "step": int(step), "event": str(event)}
        if rank is not None:
            rec["rank"] = int(rank)
        if detail is not None:
            rec["detail"] = str(detail)
        return self.write(rec)


def read_ckpt_trail(path: str):
    """Tolerant reader: ``(config_record_or_None, records)`` — the same
    contract as the other sidecar trails."""
    return read_trail(path, "ckpt_config")


# -- async-training trail (async_train/ subsystem's reporting sink) ----------

ASYNC_SUFFIX = "async.jsonl"


class AsyncTrail(Trail):
    """Sidecar JSONL for asynchronous push-sum/win-put training runs
    (``<prefix>async.jsonl``): an ``async_config`` head record (fleet
    size, per-rank cadence periods, the bounded-staleness cap), then one
    ``async`` record per logged tick — how many ranks fired, the worst
    un-folded delivery count observed at the fold (the effective
    staleness, ``win_version_vector``), the push-sum P-scalar spread
    (de-bias drift evidence), the live period vector, and the
    scheduler's cumulative bounded-staleness refusals — the
    machine-readable feed ``bfmonitor --async`` renders and
    ``validate_jsonl`` gates (docs/async.md)."""

    def __init__(self, path: str, *, size: int, periods=(),
                 max_staleness: int = 0):
        super().__init__(path, head_kind="async_config")
        self.write({"kind": "async_config", "size": int(size),
                    "periods": [int(p) for p in periods],
                    "max_staleness": int(max_staleness)})

    def write_step(self, step: int, *, active: int, staleness_max: float,
                   p_min: Optional[float] = None,
                   p_max: Optional[float] = None,
                   periods=None, refusals: Optional[int] = None) -> dict:
        rec = {"kind": "async", "step": int(step), "active": int(active),
               "staleness_max": float(staleness_max)}
        if p_min is not None:
            rec["p_min"] = float(p_min)
        if p_max is not None:
            rec["p_max"] = float(p_max)
        if periods is not None:
            rec["periods"] = [int(p) for p in periods]
        if refusals is not None:
            rec["refusals"] = int(refusals)
        return self.write(rec)


def read_async_trail(path: str):
    """Tolerant reader: ``(config_record_or_None, records)`` — the same
    contract as the other sidecar trails."""
    return read_trail(path, "async_config")


# -- in-band telemetry-plane trail (observability/plane.py's sink) -----------

PLANE_SUFFIX = "plane.jsonl"


class PlaneTrail(Trail):
    """Sidecar JSONL for the in-band telemetry plane
    (``<prefix>plane.jsonl``): a ``plane_config`` head record (fleet
    size, wire schema version/width, the staleness cap), then one
    ``plane`` record per local observation — the observer's step and a
    per-source list of ``{rank, step, version, age, hop, stale}`` merge
    metadata.  This trail records ONE rank's eventually-consistent view
    of the gossiped table (there is no central collector to log from);
    ``bfmonitor --plane`` renders it and ``validate_jsonl`` gates it
    (docs/observability.md "In-band telemetry plane")."""

    def __init__(self, path: str, *, size: int, rank: int = 0,
                 schema_version: int = 1, wire: int = 0,
                 max_age: int = 0):
        super().__init__(path, head_kind="plane_config")
        self.write({"kind": "plane_config", "size": int(size),
                    "rank": int(rank),
                    "schema_version": int(schema_version),
                    "wire": int(wire), "max_age": int(max_age)})


def read_plane_trail(path: str):
    """Tolerant reader: ``(config_record_or_None, records)`` — the same
    contract as the other sidecar trails."""
    return read_trail(path, "plane_config")


# -- fleet-supervisor trail (fleet/supervisor.py's sink) ---------------------

FLEET_SUFFIX = "fleet.jsonl"


class FleetTrail(Trail):
    """Sidecar JSONL for the fleet supervisor (``<prefix>fleet.jsonl``):
    a ``fleet_config`` head record (fleet size, respawn policy, the
    command line), then one ``fleet_event`` line per process-lifecycle
    event — ``spawn``/``heartbeat``/``synced``/``exit``/``respawn``/
    ``terminate``/``done`` with the acting rank, OS pid, worker step,
    and exit code where each applies.  This is the machine-readable
    audit of REAL process lifecycle driving the elastic-membership
    protocol; ``bfmonitor --fleet`` renders it and ``validate_jsonl``
    gates it (docs/running.md "Fleet mode")."""

    def __init__(self, path: str, *, size: int, respawn: bool = False,
                 max_respawns: int = 0, command=()):
        super().__init__(path, head_kind="fleet_config")
        self.write({"kind": "fleet_config", "size": int(size),
                    "respawn": bool(respawn),
                    "max_respawns": int(max_respawns),
                    "command": [str(c) for c in command]})

    def write_event(self, event: str, *, rank: Optional[int] = None,
                    pid: Optional[int] = None,
                    step: Optional[int] = None,
                    rc: Optional[int] = None,
                    respawns: Optional[int] = None,
                    transition: Optional[str] = None) -> dict:
        rec = {"kind": "fleet_event", "event": str(event)}
        for key, val in (("rank", rank), ("pid", pid), ("step", step),
                         ("rc", rc), ("respawns", respawns)):
            if val is not None:
                rec[key] = int(val)
        if transition is not None:
            rec["transition"] = str(transition)
        return self.write(rec)


def read_fleet_trail(path: str):
    """Tolerant reader: ``(config_record_or_None, records)`` — the same
    contract as the other sidecar trails."""
    return read_trail(path, "fleet_config")


def rotate_file(path: str, keep: int) -> None:
    """Shift ``path`` -> ``path.1`` -> ... -> ``path.<keep>`` (oldest
    dropped).  Rotated names no longer end in ``.jsonl``, so the fleet
    aggregator's discovery never double-counts them; the live reader's
    ``TailCache`` sees the fresh (smaller) file and resets its offset —
    rotation looks like a restarted writer, which it is."""
    for i in range(keep - 1, 0, -1):
        src, dst = f"{path}.{i}", f"{path}.{i + 1}"
        if os.path.exists(src):
            os.replace(src, dst)
    if os.path.exists(path):
        os.replace(path, f"{path}.1")


class _Sink:
    """Open JSONL sink: file handle + rank + clocks.  ``last_log`` feeds
    the per-record ``step_wall_us`` field (host wall time since the
    previous ``log_step`` — the straggler-attribution time base the
    fleet aggregator reads).  ``max_bytes``/``keep`` bound the file with
    size-based rotation (``BLUEFOG_METRICS_MAX_MB``)."""

    __slots__ = ("f", "path", "rank", "t0", "enabled_here", "last_log",
                 "max_bytes", "keep", "bytes_written")

    def __init__(self, f, path, rank, t0, enabled_here,
                 max_bytes=0, keep=DEFAULT_KEEP):
        self.f = f
        self.path = path
        self.rank = rank
        self.t0 = t0
        self.enabled_here = enabled_here
        self.last_log = None
        self.max_bytes = max_bytes
        self.keep = keep
        self.bytes_written = 0

    def write_line(self, line: str) -> None:
        # rotate BEFORE the write that would cross the cap: the live
        # file must always end with the newest record (a monitor tailing
        # it right after rotation would otherwise see an empty series)
        if (self.max_bytes and self.bytes_written
                and self.bytes_written + len(line) > self.max_bytes):
            self.f.close()
            rotate_file(self.path, self.keep)
            self.f = open(self.path, "w")
            self.bytes_written = 0
            if _metrics.enabled():
                _metrics.counter(
                    "bf_metrics_rotations_total",
                    "size-based rotations of the JSONL metrics sink"
                ).inc()
        self.f.write(line)
        self.f.flush()
        self.bytes_written += len(line)


_sink = [None]


def metrics_active() -> bool:
    return _sink[0] is not None


def metrics_path() -> Optional[str]:
    return _sink[0].path if _sink[0] else None


def metrics_start(file_prefix: Optional[str] = None,
                  rank: Optional[int] = None) -> Optional[str]:
    """Open the per-rank JSONL metrics file and enable the host registry.

    Called automatically by ``bf.init()`` when ``BLUEFOG_METRICS`` is set
    (the same activation pattern as ``BLUEFOG_TIMELINE``).  Returns the
    path, or None when no prefix resolves."""
    if metrics_active():
        raise RuntimeError(
            "metrics export already started; call metrics_end() first")
    if file_prefix is None:
        file_prefix = os.environ.get(METRICS_ENV)
    if not file_prefix:
        return None
    if rank is None:
        from .. import context as _ctx
        rank = _ctx.ctx().rank() if _ctx.is_initialized() else 0
    path = f"{file_prefix}{rank}.jsonl"
    f = open(path, "w")
    enabled_here = not _metrics.enabled()
    _metrics.enable()
    # phases timed by a previous loop that never logged them must not be
    # misattributed to this sink's first record
    _phases.reset_step_phases()
    max_bytes, keep = resolve_rotation()
    _sink[0] = _Sink(f, path, rank, time.perf_counter(), enabled_here,
                     max_bytes=max_bytes, keep=keep)
    return path


def metrics_end() -> None:
    """Close the JSONL sink (idempotent).  The registry keeps its values —
    only the enable flag is restored when :func:`metrics_start` set it."""
    sink = _sink[0]
    if sink is None:
        return
    _sink[0] = None
    try:
        sink.f.close()
    finally:
        if sink.enabled_here:
            _metrics.disable()


def telemetry_to_host(snapshot) -> Dict[str, object]:
    """TelemetrySnapshot (or mapping) with device leaves -> plain floats /
    float lists, ready for ``json.dumps``.  ``[N]`` leaves (the global
    view a wrapped step returns) become per-rank lists; scalars become
    floats."""
    import numpy as np
    if hasattr(snapshot, "asdict"):
        snapshot = snapshot.asdict()
    elif hasattr(snapshot, "_asdict"):
        snapshot = snapshot._asdict()
    out = {}
    for k, v in dict(snapshot).items():
        a = np.asarray(v, dtype=np.float64)
        if a.ndim == 0:
            out[k] = float(a)
        else:
            out[k] = [float(x) for x in a.reshape(-1)]
    return out


def _mean(v) -> float:
    return float(sum(v) / len(v)) if isinstance(v, list) else float(v)


def log_step(step: int, telemetry=None, extra: Optional[Dict] = None,
             counters: bool = True) -> Optional[Dict]:
    """Write one JSONL record for ``step`` and mirror the numeric fields
    onto the timeline as counter lanes.

    ``telemetry``: a :class:`~.ingraph.TelemetrySnapshot` (device arrays
    fine — fetched here, OUTSIDE the jitted step) or an already-host dict.
    ``extra``: additional JSON-able fields merged into the record.
    ``counters=False`` skips the registry snapshot (cheaper lines).

    Beyond the telemetry fields the record carries ``step_wall_us``
    (host wall time since the previous ``log_step`` on this sink — the
    per-rank step-time series the fleet aggregator and the health
    engine's straggler rule consume) and, when the step loop timed any
    :mod:`~.phases` phases, a ``"phases": {name: seconds}`` dict (the
    device->host telemetry fetch below is itself timed as the
    ``export`` phase).

    Returns the record written, or None when no sink is open AND no
    timeline is recording (nothing to do)."""
    sink = _sink[0]
    timeline_on = _tl.timeline_enabled()
    if sink is None and not timeline_on:
        return None
    now = time.perf_counter()
    record: Dict[str, object] = {
        "step": int(step),
        "t_us": int((now - (sink.t0 if sink else 0.0)) * 1e6),
        "rank": sink.rank if sink else 0,
    }
    if sink is not None:
        if sink.last_log is not None:
            record["step_wall_us"] = int((now - sink.last_log) * 1e6)
        sink.last_log = now
    # the snapshot fetch is the device sync — THE host-visible export
    # cost; time it as the `export` phase so it lands in this record
    t_fetch = time.perf_counter()
    tel_host = telemetry_to_host(telemetry) if telemetry is not None else {}
    if telemetry is not None:
        _phases.record_phase("export", time.perf_counter() - t_fetch)
    # the snapshot's in-graph step counter must not clobber the caller's
    # log index (several loops may share one sink, and on the virtual
    # mesh it is an [N] list, not a scalar)
    tel_host.pop("step", None)
    record.update(tel_host)
    # profiler-staged top-level fields (e.g. overlap_efficiency) land on
    # this step's record; explicit extras win on key collisions
    fields = _phases.take_step_fields()
    if fields:
        record.update(fields)
    if extra:
        record.update(extra)
    staged = _phases.take_step_phases()
    if staged:
        record["phases"] = staged
    if counters and _metrics.enabled():
        record["counters"] = _metrics.registry.snapshot()
    if sink is not None:
        sink.write_line(json.dumps(record) + "\n")
    if timeline_on:
        # Perfetto counter lanes: each per-rank telemetry field renders
        # as its cross-rank mean PLUS `_min`/`_max` companion lanes —
        # a single straggling or diverging rank must stay visible in the
        # trace instead of averaging away; host gauges ride along so
        # queue depth lines up with the op spans
        for k, v in tel_host.items():
            _tl.record_counter(f"telemetry/{k}", _mean(v))
            if isinstance(v, list) and len(v) > 1:
                _tl.record_counter(f"telemetry/{k}_min", min(v))
                _tl.record_counter(f"telemetry/{k}_max", max(v))
        for src in (fields, extra):
            if src:
                for k, v in src.items():
                    if isinstance(v, (int, float)):
                        _tl.record_counter(f"telemetry/{k}", float(v))
    return record


def _escape_label_value(v: str) -> str:
    """Label-value escaping per the Prometheus exposition format:
    backslash, double-quote, and line-feed must be escaped (in that
    order — escaping the backslash last would double the others)."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _escape_help(v: str) -> str:
    """HELP text escaping (exposition format): backslash and line-feed
    only — quotes are legal in HELP."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(reg: Optional[_metrics.Registry] = None) -> str:
    """Render the registry in Prometheus exposition format."""
    reg = reg or _metrics.registry
    lines = []
    for m in reg.metrics():
        if m.help:
            lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        for key, val in m._items():
            labels = ",".join(f'{k}="{_escape_label_value(v)}"'
                              for k, v in key)
            if m.kind == "histogram":
                for le, c in zip(m.buckets, val["buckets"]):
                    ls = (labels + "," if labels else "") + f'le="{le}"'
                    lines.append(f"{m.name}_bucket{{{ls}}} {c}")
                ls = (labels + "," if labels else "") + 'le="+Inf"'
                lines.append(f"{m.name}_bucket{{{ls}}} {val['count']}")
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"{m.name}_sum{suffix} {val['sum']}")
                lines.append(f"{m.name}_count{suffix} {val['count']}")
            else:
                suffix = f"{{{labels}}}" if labels else ""
                lines.append(f"{m.name}{suffix} {val}")
    return "\n".join(lines) + ("\n" if lines else "")


# structured fields with a defined shape (the schema gate checks them;
# anything NOT named here is tolerated — unknown fields must never break
# an old validator reading a new writer's series)
_EDGE_KEYS = ("src", "dst", "bytes", "latency_us", "gbps")

# controller-trail record kinds (control/policy.py) and their required
# keys: a "decision" line is the closed-loop controller's audit unit, a
# "control_config" line the trail's replayable head record.  Lines of
# these kinds replace the telemetry-record required keys (they carry no
# "rank" — decisions are fleet-scoped) but keep the numeric-finiteness
# and unknown-field-tolerance contracts.  The serving trail
# (serving/router.py, ``<prefix>serving.jsonl``) follows the same
# pattern: a "serve_config" head record, periodic "serve" records
# (per-replica staleness + request rate), and "serve_failover" events.
_KIND_REQUIRED = {
    "decision": ("step", "t_us", "knob", "action", "mode", "applied"),
    "control_config": ("t_us",),
    "serve": ("step", "t_us", "requests_per_s"),
    "serve_failover": ("step", "t_us", "replica_from", "replica_to",
                       "reason"),
    "serve_config": ("t_us",),
    # serving autoscaling events (serving/router.py admit/retire — the
    # elastic-membership hook): one line per replica entering/leaving
    # the active serving set
    "serve_admit": ("step", "t_us", "replica"),
    "serve_retire": ("step", "t_us", "replica"),
    # elastic-membership trail (MembershipTrail above, fed by
    # resilience/membership.py's ElasticMembership): a config head, one
    # periodic per-step state record, one event line per transition
    "membership_config": ("t_us",),
    "membership": ("step", "t_us", "active", "syncing"),
    "membership_event": ("step", "t_us", "rank", "transition"),
    # durable-fleet-state trail (CkptTrail above, fed by the
    # checkpoint/ subsystem's FleetCheckpointer and restore path): a
    # config head, one "ckpt" record per durable save, one "ckpt_event"
    # line per commit-protocol event (docs/checkpoint.md)
    "ckpt_config": ("t_us",),
    "ckpt": ("step", "t_us", "durable_step", "bytes", "save_s"),
    "ckpt_event": ("step", "t_us", "event"),
    # async-training trail (AsyncTrail above, fed by the
    # async_train/ subsystem's optimizers + CadenceScheduler): a config
    # head with the cadence vector, then one periodic record per logged
    # tick carrying the fired-rank count, the effective-staleness
    # watermark, and the push-sum P spread (docs/async.md)
    "async_config": ("t_us",),
    "async": ("step", "t_us", "active", "staleness_max"),
    # in-band telemetry-plane trail (PlaneTrail above, fed by
    # observability/plane.py's TelemetryPlane): a config head with the
    # wire-schema identity, then one record per local observation
    # carrying the per-source merge metadata (version/age/hop/stale) of
    # this rank's gossiped fleet view
    "plane_config": ("t_us",),
    "plane": ("step", "t_us", "sources"),
    # fleet-supervisor trail (FleetTrail above, fed by
    # fleet/supervisor.py): a config head with the fleet size + respawn
    # policy, then one event line per process-lifecycle action —
    # spawn/heartbeat/synced/exit/respawn/terminate/membership/done
    # (docs/running.md "Fleet mode")
    "fleet_config": ("t_us",),
    "fleet_event": ("event", "t_us"),
    # health verdict trail (observability/health.py write_verdicts): one
    # "report" summary line per evaluation window, then one "verdict"
    # line per finding.  The trail shares this module's rotation policy
    # and must validate with the same tool (bflint: jsonl-kind-drift).
    "report": ("t_us", "step_lo", "step_hi", "ok"),
    "verdict": ("t_us", "rule", "severity", "message"),
    # schedule-synthesis record (control/synthesize.py
    # write_schedule_record): the armed schedule's identity
    # (fingerprint), shape (period, offset superset, rounds) and —
    # when a pricing matrix was at hand — predicted per-round costs
    "schedule": ("t_us", "source", "fingerprint", "period"),
}

_DECISION_STR_KEYS = ("knob", "action", "mode")


def _check_decision(path, lineno, rec):
    for k in _DECISION_STR_KEYS:
        if not isinstance(rec[k], str):
            raise ValueError(
                f"{path}:{lineno}: decision field {k!r} must be a string")
    if not isinstance(rec["applied"], bool):
        raise ValueError(
            f"{path}:{lineno}: decision field 'applied' must be a bool")
    if rec["mode"] not in ("shadow", "on"):
        raise ValueError(
            f"{path}:{lineno}: decision mode {rec['mode']!r} not in "
            f"('shadow', 'on')")
    if isinstance(rec.get("step"), bool) or not isinstance(
            rec.get("step"), (int, float)):
        raise ValueError(
            f"{path}:{lineno}: decision field 'step' is not numeric")


def _check_serve(path, lineno, rec):
    """Serving-trail record shapes (serving/router.py): ``serve``
    carries per-replica staleness + the request rate; ``serve_failover``
    one sticky-target switch.  Unknown fields stay tolerated."""
    kind = rec["kind"]
    if kind == "serve":
        rps = rec["requests_per_s"]
        if isinstance(rps, bool) or not isinstance(rps, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: 'requests_per_s' is not numeric")
        for field in ("hits", "serve_staleness"):
            v = rec.get(field)
            if v is None:
                continue
            if not isinstance(v, dict):
                raise ValueError(
                    f"{path}:{lineno}: {field!r} must be an object "
                    f"(replica -> value)")
            for k, x in v.items():
                if isinstance(x, bool) or not isinstance(x, (int, float)):
                    raise ValueError(
                        f"{path}:{lineno}: {field}[{k!r}] is not numeric")
    elif kind == "serve_failover":
        if not isinstance(rec["reason"], str):
            raise ValueError(
                f"{path}:{lineno}: failover 'reason' must be a string")
        for field in ("replica_from", "replica_to"):
            v = rec[field]
            # replica_to None = no surviving candidate (total outage)
            if field == "replica_to" and v is None:
                continue
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: failover {field!r} is not numeric")
    elif kind in ("serve_admit", "serve_retire"):
        v = rec["replica"]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: {kind} 'replica' is not numeric")


def _check_membership(path, lineno, rec):
    """Membership-trail record shapes (MembershipTrail): ``membership``
    carries the per-rank state map + counts, ``membership_event`` one
    state transition.  Unknown fields stay tolerated."""
    kind = rec["kind"]
    if kind == "membership":
        states = rec.get("states")
        if states is not None:
            if not isinstance(states, dict):
                raise ValueError(
                    f"{path}:{lineno}: 'states' must be an object "
                    f"(rank -> state)")
            for k, v in states.items():
                if not isinstance(v, str):
                    raise ValueError(
                        f"{path}:{lineno}: states[{k!r}] is not a string")
        for field in ("active", "syncing"):
            v = rec[field]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: membership {field!r} is not numeric")
    elif kind == "membership_event":
        if not isinstance(rec["transition"], str):
            raise ValueError(
                f"{path}:{lineno}: membership_event 'transition' must be "
                f"a string")
        v = rec["rank"]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: membership_event 'rank' is not numeric")


def _check_ckpt(path, lineno, rec):
    """Checkpoint-trail record shapes (CkptTrail): ``ckpt`` carries the
    durable-save accounting, ``ckpt_event`` one commit-protocol event.
    Unknown fields stay tolerated."""
    kind = rec["kind"]
    if kind == "ckpt":
        for field in ("durable_step", "bytes", "save_s"):
            v = rec[field]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: ckpt {field!r} is not numeric")
        shards = rec.get("shards")
        if shards is not None and (isinstance(shards, bool)
                                   or not isinstance(shards, (int, float))):
            raise ValueError(
                f"{path}:{lineno}: ckpt 'shards' is not numeric")
    elif kind == "ckpt_event":
        if not isinstance(rec["event"], str):
            raise ValueError(
                f"{path}:{lineno}: ckpt_event 'event' must be a string")
        rank = rec.get("rank")
        if rank is not None and (isinstance(rank, bool)
                                 or not isinstance(rank, (int, float))):
            raise ValueError(
                f"{path}:{lineno}: ckpt_event 'rank' is not numeric")


def _check_async(path, lineno, rec):
    """Async-trail record shapes (AsyncTrail): ``async`` carries the
    per-tick cadence accounting — fired-rank count, effective-staleness
    watermark, push-sum P spread, live period vector.  Unknown fields
    stay tolerated."""
    for field in ("active", "staleness_max"):
        v = rec[field]
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: async {field!r} is not numeric")
    for field in ("p_min", "p_max", "refusals"):
        v = rec.get(field)
        if v is not None and (isinstance(v, bool)
                              or not isinstance(v, (int, float))):
            raise ValueError(
                f"{path}:{lineno}: async {field!r} is not numeric")
    periods = rec.get("periods")
    if periods is not None:
        if not isinstance(periods, list):
            raise ValueError(
                f"{path}:{lineno}: async 'periods' must be a list")
        for x in periods:
            if isinstance(x, bool) or not isinstance(x, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: async 'periods' entry is not "
                    f"numeric")


def _check_plane(path, lineno, rec):
    """Plane-trail record shape (PlaneTrail): one local observation of
    the gossiped table — a per-source list of merge metadata.  Unknown
    fields stay tolerated."""
    sources = rec["sources"]
    if not isinstance(sources, list):
        raise ValueError(
            f"{path}:{lineno}: plane 'sources' must be a list")
    for s in sources:
        if not isinstance(s, dict):
            raise ValueError(
                f"{path}:{lineno}: plane 'sources' entries must be "
                f"objects")
        for field in ("rank", "step", "version", "age", "hop"):
            v = s.get(field)
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: plane source field {field!r} is "
                    f"not numeric")
        stale = s.get("stale")
        if stale is not None and not isinstance(stale, bool):
            raise ValueError(
                f"{path}:{lineno}: plane source field 'stale' must be "
                f"a bool")


def _check_fleet(path, lineno, rec):
    """Fleet-trail record shape (FleetTrail): one process-lifecycle
    event with the acting rank/pid/step/rc where each applies.  Unknown
    fields stay tolerated."""
    if not isinstance(rec["event"], str):
        raise ValueError(
            f"{path}:{lineno}: fleet_event 'event' must be a string")
    for field in ("rank", "pid", "step", "rc", "respawns"):
        v = rec.get(field)
        if v is None:
            continue
        if isinstance(v, bool) or not isinstance(v, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: fleet_event field {field!r} is not "
                f"numeric")
    transition = rec.get("transition")
    if transition is not None and not isinstance(transition, str):
        raise ValueError(
            f"{path}:{lineno}: fleet_event 'transition' must be a "
            f"string")


def _check_schedule(path, lineno, rec):
    """Schedule-synthesis record shape (control/synthesize.py): the
    armed schedule's identity and round structure.  Unknown fields stay
    tolerated."""
    if not isinstance(rec["source"], str):
        raise ValueError(
            f"{path}:{lineno}: schedule 'source' must be a string")
    if not isinstance(rec["fingerprint"], str):
        raise ValueError(
            f"{path}:{lineno}: schedule 'fingerprint' must be a string")
    v = rec["period"]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        raise ValueError(
            f"{path}:{lineno}: schedule 'period' is not numeric")
    rounds = rec.get("rounds")
    if rounds is not None:
        if not isinstance(rounds, list):
            raise ValueError(
                f"{path}:{lineno}: schedule 'rounds' must be a list")
        for r in rounds:
            if not isinstance(r, dict) or not isinstance(
                    r.get("edges", []), list):
                raise ValueError(
                    f"{path}:{lineno}: schedule round entries must be "
                    f"objects with an 'edges' list")


def _check_structured(path, lineno, rec, check):
    """Shape checks for the documented structured fields: ``phases``
    (PR 7), ``step_wall_us`` (PR 7), ``edges`` and ``overlap_efficiency``
    (PR 8), ``serve_staleness`` (PR 11 — also valid staged onto a
    telemetry record).  ``counters`` stays free-form (registry
    snapshot)."""
    stale = rec.get("serve_staleness")
    if stale is not None and rec.get("kind") not in ("serve",):
        # on a telemetry record: a per-replica map or an [N] list
        if isinstance(stale, dict):
            vals = stale.values()
        elif isinstance(stale, list):
            vals = stale
        else:
            raise ValueError(
                f"{path}:{lineno}: 'serve_staleness' must be an object "
                f"or list")
        for x in vals:
            if isinstance(x, bool) or not isinstance(x, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: 'serve_staleness' entry is not "
                    f"numeric")
    phases = rec.get("phases")
    if phases is not None:
        if not isinstance(phases, dict):
            raise ValueError(f"{path}:{lineno}: 'phases' must be an object")
        for k, v in phases.items():
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                raise ValueError(
                    f"{path}:{lineno}: phase {k!r} duration is not numeric")
            check(f"phases.{k}", float(v))
    wall = rec.get("step_wall_us")
    if wall is not None:
        if isinstance(wall, bool) or not isinstance(wall, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: 'step_wall_us' is not numeric")
        check("step_wall_us", float(wall))
    eff = rec.get("overlap_efficiency")
    if eff is not None:
        if isinstance(eff, bool) or not isinstance(eff, (int, float)):
            raise ValueError(
                f"{path}:{lineno}: 'overlap_efficiency' is not numeric")
        check("overlap_efficiency", float(eff))
    edges = rec.get("edges")
    if edges is not None:
        if not isinstance(edges, list):
            raise ValueError(f"{path}:{lineno}: 'edges' must be a list")
        for e in edges:
            if not isinstance(e, dict):
                raise ValueError(
                    f"{path}:{lineno}: 'edges' entries must be objects")
            missing = [k for k in _EDGE_KEYS if k not in e]
            if missing:
                raise ValueError(
                    f"{path}:{lineno}: edge entry missing keys {missing}")
            for k in _EDGE_KEYS:
                if isinstance(e[k], bool) or not isinstance(
                        e[k], (int, float)):
                    raise ValueError(
                        f"{path}:{lineno}: edge field {k!r} is not numeric")
                check(f"edges.{k}", float(e[k]))


def validate_jsonl(path: str, required=REQUIRED_JSONL_KEYS):
    """Parse a metrics JSONL file, enforcing the schema: every line is a
    JSON object carrying ``required`` keys, every numeric field finite,
    and the documented structured fields (``phases``, ``step_wall_us``,
    ``edges``, ``overlap_efficiency``, ``serve_staleness``) well-shaped.
    Controller-trail lines (``kind: decision`` / ``control_config``,
    control/policy.py), serving-trail lines (``kind: serve`` /
    ``serve_failover`` / ``serve_admit`` / ``serve_retire`` /
    ``serve_config``, serving/router.py), membership-trail lines
    (``kind: membership`` / ``membership_event`` /
    ``membership_config``, the :class:`MembershipTrail` above),
    checkpoint-trail lines (``kind: ckpt`` / ``ckpt_event`` /
    ``ckpt_config``, the :class:`CkptTrail` above), async-trail lines
    (``kind: async`` / ``async_config``, the :class:`AsyncTrail`
    above), plane-trail lines (``kind: plane`` / ``plane_config``, the
    :class:`PlaneTrail` above), schedule-synthesis lines (``kind:
    schedule``, control/synthesize.py), and health-verdict-trail lines
    (``kind: report`` / ``verdict``, health.py) validate against their own
    required keys and shape
    instead — ``bflint``'s jsonl-kind-drift rule derives both sides and
    keeps ``_KIND_REQUIRED`` in lockstep with every exporter.  Fields
    the schema does not know are tolerated (forward compatibility is
    part of the contract and regression-tested).  Returns the records;
    raises ValueError on violations (the ``make metrics-smoke`` /
    ``make control-smoke`` gates)."""
    import math
    records = []
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: invalid JSON: {e}")
            if not isinstance(rec, dict):
                raise ValueError(f"{path}:{lineno}: not a JSON object")
            kind = rec.get("kind")
            required_here = (_KIND_REQUIRED[kind]
                             if isinstance(kind, str)
                             and kind in _KIND_REQUIRED else required)
            missing = [k for k in required_here if k not in rec]
            if missing:
                raise ValueError(f"{path}:{lineno}: missing keys {missing}")
            if kind == "decision":
                _check_decision(path, lineno, rec)
            elif kind in ("serve", "serve_failover", "serve_admit",
                          "serve_retire"):
                _check_serve(path, lineno, rec)
            elif kind in ("membership", "membership_event"):
                _check_membership(path, lineno, rec)
            elif kind in ("ckpt", "ckpt_event"):
                _check_ckpt(path, lineno, rec)
            elif kind == "async":
                _check_async(path, lineno, rec)
            elif kind == "plane":
                _check_plane(path, lineno, rec)
            elif kind == "fleet_event":
                _check_fleet(path, lineno, rec)
            elif kind == "schedule":
                _check_schedule(path, lineno, rec)

            def check(k, v):
                if isinstance(v, float) and not math.isfinite(v):
                    raise ValueError(
                        f"{path}:{lineno}: non-finite value for {k!r}")
                if isinstance(v, list):
                    for x in v:
                        check(k, x)
            for k, v in rec.items():
                if not isinstance(v, dict) and k != "edges":
                    check(k, v)
            _check_structured(path, lineno, rec, check)
            records.append(rec)
    return records
