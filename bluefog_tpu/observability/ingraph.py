"""In-graph training-health telemetry: traced per-step aggregates.

The paper's neighbor-averaging claim — sparse-topology mixing matches
allreduce quality at a fraction of the communication — rests on spectral
properties that runtime machinery can silently degrade: the resilience
layer repairs mixing matrices around deaths (``resilience/repair.py``),
dynamic schedules rotate edge sets, and the overlapped stepper mixes
one-step-stale neighbor values.  This module computes the health signals
*inside* the jitted step, where they cost one extra ``pmean`` per fusion
bucket instead of a post-hoc host reduction over the whole parameter tree:

* **consensus distance** ``||x_i - x_bar||^2`` — THE consensus-process
  observable (exponential-graph analysis, arXiv:2110.13363: convergence =
  optimization error + consensus error).  Computed over the same fused
  flat buffers the exchange already built (``ops/fusion.py``), so the
  extra collective count is ``buckets``, not ``leaves``.
* **mix column/row sums** — the step's effective mixing-matrix mass at
  this rank.  Column sum != 1 means the receiver's weights no longer
  conserve mass (a broken repair corrupts the iterates); row sum != 1
  with column sum == 1 means the matrix is column- but not
  doubly-stochastic (exact-averaging fixed points are gone — exactly the
  silent degradation a column-family repair introduces).
* **param / grad / update norms** — the weight-update telemetry gap
  (arXiv:2004.13336) for sharded training.
* **staleness / warmup / degraded flags** — which pipeline the value came
  from: synchronous (0) vs the staleness-1 overlapped fold (1), whether
  the fold was a warmup fold (zero in-flight buffer, self weight 1), and
  whether the degraded guard's local branch ran.

Everything is returned as a :class:`TelemetrySnapshot` — a small NamedTuple
pytree of f32 scalars per rank — threaded through ``optim/strategies.py``
as an aux output.  The gate is build-time (``telemetry=`` argument, env
``BLUEFOG_TELEMETRY``): with telemetry off the builders take the exact
pre-telemetry code path, asserted bit-identical on the lowered StableHLO
by ``tests/test_observability.py``.
"""

import os
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import fusion as F

__all__ = [
    "TELEMETRY_ENV", "telemetry_enabled", "TelemetrySnapshot",
    "consensus_distance", "tree_l2", "tree_diff_l2", "mix_mass",
    "strategy_snapshot", "UNMEASURED",
]

TELEMETRY_ENV = "BLUEFOG_TELEMETRY"

# sentinel for "this step did not measure the field" (e.g. consensus
# distance in a degraded step that must issue no collective at all) —
# distinguishable from every real squared distance, which is >= 0
UNMEASURED = -1.0


def telemetry_enabled(flag: Optional[bool] = None) -> bool:
    """Resolve the in-graph telemetry gate: explicit argument wins, else
    ``BLUEFOG_TELEMETRY`` (default OFF).  Builders resolve this when the
    step is constructed — same snapshot discipline as the fusion knobs
    (jit traces once; the resolved value joins the step-cache key)."""
    if flag is not None:
        return bool(flag)
    return os.environ.get(TELEMETRY_ENV, "0") == "1"


class TelemetrySnapshot(NamedTuple):
    """Per-rank, per-step training-health aggregates (f32 scalars inside
    the shard_map body; ``[N]`` arrays once gathered to the global view).

    ``consensus_dist`` is ``||x_i - x_bar||^2`` over the post-step
    parameters (:data:`UNMEASURED` when the step could not issue the
    pmean); ``mix_col_sum``/``mix_row_sum`` are this rank's column/row
    mass of the step's mixing matrix; ``staleness`` is 0 for synchronous
    mixing, 1 for the overlapped staleness-1 fold; ``warmup`` flags a
    warmup fold (zero in-flight buffer); ``degraded`` flags the
    degraded-guard/local branch.

    Compression fields (``compress/``): ``compress_ratio`` is raw bytes /
    wire bytes of one exchange payload (1 with compression off),
    ``residual_norm`` the l2 of the carried error-feedback residual (for
    choco, of ``x - x_hat``; 0 when nothing is carried), ``wire_bytes``
    the compressed payload bytes of one transfer (0 = unmeasured,
    compression off)."""
    step: jax.Array
    consensus_dist: jax.Array
    param_norm: jax.Array
    grad_norm: jax.Array
    update_norm: jax.Array
    mix_col_sum: jax.Array
    mix_row_sum: jax.Array
    staleness: jax.Array
    warmup: jax.Array
    degraded: jax.Array
    compress_ratio: jax.Array
    residual_norm: jax.Array
    wire_bytes: jax.Array

    def asdict(self):
        return dict(zip(self._fields, self))


FIELDS = TelemetrySnapshot._fields


def _buffers(tree, fuse: bool, bucket_bytes: Optional[int]):
    """Tree -> flat f32 views: the fused dtype buckets when fusion is on
    (the plan is the trace-time-cached one the exchange already uses, so
    the telemetry pmean count is ``buckets``, not ``leaves``), else the
    non-empty leaves."""
    _, bufs = F.flat_views(tree, fuse=fuse, max_bucket_bytes=bucket_bytes)
    return [b.astype(jnp.float32) for b in bufs if b.size]


def consensus_distance(tree, axis_name, fuse: bool = True,
                       bucket_bytes: Optional[int] = None, sum_axis=None,
                       leaf_weights=None):
    """``||x_i - x_bar||^2`` in f32: one pmean per fusion bucket, squared
    distance accumulated over buckets.  Padding tail elements are equal
    (zero) on every rank and contribute exactly 0.

    ``sum_axis`` (the hybrid sharded-decentralized path): the mesh
    axis/axes the PARAMETERS are sharded over.  The pmean must run over
    ``axis_name`` (the gossip axis) ONLY — averaging over the model-
    sharding axis would compare different parameter shards and hide
    cross-pod disagreement — while the per-shard squared distances psum
    over ``sum_axis`` so every rank reports its replica's FULL-parameter
    consensus distance.

    ``leaf_weights`` (a float tree matching ``tree``): per-leaf factor on
    the squared contribution.  The hybrid path passes 1/replication for
    leaves the fsdp axis could not shard (every cell holds them whole, so
    the ``sum_axis`` psum would otherwise count them fsdp times).  The
    collective count stays one pmean per non-empty bucket — only the
    local accumulation changes."""
    if leaf_weights is None:
        d = jnp.float32(0.0)
        for b in _buffers(tree, fuse, bucket_bytes):
            mean = lax.pmean(b, axis_name)
            d = d + jnp.sum((b - mean) ** 2)
        if sum_axis:
            d = lax.psum(d, sum_axis)
        return d
    plan, bufs = F.flat_views(tree, fuse=fuse, max_bucket_bytes=bucket_bytes)
    diffs = []
    for b in bufs:
        b32 = b.astype(jnp.float32)
        diffs.append(b32 - lax.pmean(b32, axis_name) if b.size else b32)
    d = jnp.float32(0.0)
    for dl, w in zip(jax.tree.leaves(F.restore(plan, tree, diffs)),
                     jax.tree.leaves(leaf_weights)):
        if dl.size:
            d = d + jnp.float32(w) * jnp.sum(jnp.square(dl))
    if sum_axis:
        d = lax.psum(d, sum_axis)
    return d


def tree_l2(tree, sum_axis=None, leaf_weights=None):
    """f32 l2 norm over every element of the tree (``sum_axis``: psum the
    squared sum over the model-sharding axis first, so sharded trees
    report the full-replica norm; ``leaf_weights`` as in
    :func:`consensus_distance`)."""
    s = jnp.float32(0.0)
    ws = (None if leaf_weights is None
          else jax.tree.leaves(leaf_weights))
    for i, l in enumerate(jax.tree.leaves(tree)):
        if l.size:
            q = jnp.sum(jnp.square(l.astype(jnp.float32)))
            if ws is not None:
                q = jnp.float32(ws[i]) * q
            s = s + q
    if sum_axis:
        s = lax.psum(s, sum_axis)
    return jnp.sqrt(s)


def tree_diff_l2(a, b, sum_axis=None, leaf_weights=None):
    """f32 l2 norm of ``a - b`` (same structure; ``sum_axis`` and
    ``leaf_weights`` as in :func:`tree_l2`)."""
    s = jnp.float32(0.0)
    ws = (None if leaf_weights is None
          else jax.tree.leaves(leaf_weights))
    for i, (la, lb) in enumerate(zip(jax.tree.leaves(a),
                                     jax.tree.leaves(b))):
        if la.size:
            diff = la.astype(jnp.float32) - lb.astype(jnp.float32)
            q = jnp.sum(jnp.square(diff))
            if ws is not None:
                q = jnp.float32(ws[i]) * q
            s = s + q
    if sum_axis:
        s = lax.psum(s, sum_axis)
    return jnp.sqrt(s)


def mix_mass(comm_type, axis_name, topo=None, sched=None, step=0,
             machine_axes=None, machine_topo=None):
    """This rank's (column sum, row sum) of the step's mixing matrix, as
    traced f32 scalars.

    ``comm_type`` is duck-typed on ``.value`` (the
    ``strategies.CommunicationType`` enum) to keep this module importable
    without the optimizer stack.  Column convention throughout
    (``parallel/topology.py``): ``W[i, j]`` is the weight receiver j
    applies to i's value, so MY column sum is the mass I apply to what I
    receive and MY row sum is the mass my value gets across receivers.
    """
    value = getattr(comm_type, "value", str(comm_type))
    one = jnp.float32(1.0)
    if value in ("empty", "allreduce"):
        # identity / uniform-1/N mixing: both sums are exactly 1
        return one, one
    if value == "neighbor.allreduce":
        idx = lax.axis_index(axis_name)
        if sched is not None:
            t = jnp.asarray(step) % sched.period
            W = jnp.asarray(sched.matrices, jnp.float32)[t]
        else:
            W = jnp.asarray(topo.weight_matrix, jnp.float32)
        return W[:, idx].sum(), W[idx, :].sum()
    if value == "hierarchical.neighbor.allreduce":
        machine_axis, _local_axis = machine_axes
        W = jnp.asarray(machine_topo.weight_matrix, jnp.float32)
        m = lax.axis_index(machine_axis)
        return W[:, m].sum(), W[m, :].sum()
    raise ValueError(f"unknown communication type {value!r}")


def strategy_snapshot(*, step, new_params, old_params, grads, axis_name,
                      col_sum, row_sum, fuse, bucket_bytes,
                      staleness=0.0, warmup=0.0, degraded=0.0,
                      compress_ratio=1.0, residual_norm=0.0,
                      wire_bytes=0.0, sum_axis=None, leaf_weights=None,
                      measure_consensus: bool = True) -> TelemetrySnapshot:
    """Assemble the snapshot a strategy step returns.

    ``axis_name`` may be a tuple (hierarchical mode pmeans over both mesh
    axes).  ``measure_consensus=False`` (the degraded/local guard branch,
    which must issue NO collective) reports :data:`UNMEASURED` instead.
    ``warmup`` may be traced (the overlapped variants derive it from the
    in-flight self weight); ``residual_norm`` may be traced (the
    compressed exchange's carried-error l2).

    ``sum_axis`` (the hybrid ``(dp, fsdp)`` path): the model-sharding
    axis/axes.  Consensus stays a pmean over ``axis_name`` — the gossip
    axis only — and every squared aggregate (consensus, norms) psums over
    ``sum_axis``, so each rank reports full-replica health for its 1/fsdp
    shard's exchange; ``leaf_weights`` de-duplicates leaves the sharding
    replicated (:func:`consensus_distance`)."""
    if measure_consensus:
        cd = consensus_distance(new_params, axis_name, fuse, bucket_bytes,
                                sum_axis=sum_axis,
                                leaf_weights=leaf_weights)
    else:
        cd = jnp.float32(UNMEASURED)
    return TelemetrySnapshot(
        step=jnp.asarray(step, jnp.int32),
        consensus_dist=cd,
        param_norm=tree_l2(new_params, sum_axis=sum_axis,
                           leaf_weights=leaf_weights),
        grad_norm=tree_l2(grads, sum_axis=sum_axis,
                          leaf_weights=leaf_weights),
        update_norm=tree_diff_l2(new_params, old_params,
                                 sum_axis=sum_axis,
                                 leaf_weights=leaf_weights),
        mix_col_sum=jnp.asarray(col_sum, jnp.float32),
        mix_row_sum=jnp.asarray(row_sum, jnp.float32),
        staleness=jnp.asarray(staleness, jnp.float32),
        warmup=jnp.asarray(warmup, jnp.float32),
        degraded=jnp.asarray(degraded, jnp.float32),
        compress_ratio=jnp.asarray(compress_ratio, jnp.float32),
        residual_norm=jnp.asarray(residual_norm, jnp.float32),
        wire_bytes=jnp.asarray(wire_bytes, jnp.float32),
    )
