"""Fleet-wide aggregation of the per-rank JSONL step series.

PR 4's exporter writes one ``<prefix><rank>.jsonl`` per process; this
module merges them back into a STEP-ALIGNED fleet view — the sensing
input for the health engine (``observability/health.py``) and the
``bfmonitor`` dashboard (``run/monitor.py``), and the series the
ROADMAP's closed-loop controller will consume.

Robustness is the whole point — a fleet view that dies on the first
sick rank can never diagnose one:

* **missing / lagging ranks** — a rank absent at a step simply does not
  contribute to that step's spread stats; the gap is recorded as a
  :class:`Gap` so the health engine can turn it into a verdict instead
  of the reader crashing.
* **truncated final lines** — a writer killed mid-step leaves a partial
  last line; it is dropped and flagged (``kind="truncated"``), never a
  parse abort.  Mid-file garbage (disk-level corruption) likewise skips
  the line and records a ``parse_error`` gap.
* **ranks that never wrote** — when the caller states the expected
  fleet size, silent ranks surface as ``missing_file`` gaps.
* **single-process virtual meshes** — the CPU test mesh runs N ranks in
  one process, so ONE file carries ``[N]``-list telemetry fields.
  :func:`load_fleet` explodes those lists into N virtual rank series
  (list position = rank), so the same fleet view works on a laptop run
  and a real multi-host fleet.

Pure host-side stdlib + numpy: importing this module never touches JAX.
"""

import dataclasses
import glob
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "Gap", "RankSeries", "SpreadStats", "FleetView", "TailCache",
    "read_jsonl_tolerant", "discover_series", "load_fleet", "spread",
    "STEP_WALL_FIELD",
]

# per-step host wall time, microseconds (written by export.log_step;
# older series fall back to consecutive t_us deltas)
STEP_WALL_FIELD = "step_wall_us"


def _step_of(rec: dict) -> Optional[int]:
    """The record's step index as an int, or None when absent/garbled.
    Older series written before the exporter stopped letting the
    in-graph counter clobber the log index may carry an [N] list here —
    every virtual rank saw the same counter, so position 0 serves."""
    v = rec.get("step")
    if isinstance(v, list):
        v = v[0] if v else None
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return int(v)

# telemetry fields the fleet view treats as per-rank health series (the
# TelemetrySnapshot fields plus the exporter extras); anything else
# numeric still aggregates, these are just the documented core set
CORE_FIELDS = (
    "consensus_dist", "param_norm", "grad_norm", "update_norm",
    "mix_col_sum", "mix_row_sum", "staleness", "warmup", "degraded",
    "compress_ratio", "residual_norm", "wire_bytes",
    "overlap_efficiency",
)


def _numeric_list(v) -> bool:
    """True for a list of plain numbers — the only list shape the
    virtual-mesh explode may split.  Structured lists (the ``edges``
    record's entry dicts) must pass through whole even when their length
    happens to equal the fleet width."""
    return (isinstance(v, list)
            and all(isinstance(x, (int, float))
                    and not isinstance(x, bool) for x in v))


@dataclasses.dataclass
class Gap:
    """One observed hole in the fleet's series (health-event input).

    ``kind``: ``missing_file`` (expected rank never wrote),
    ``truncated`` (final line cut mid-write — writer killed),
    ``parse_error`` (mid-file garbage skipped), ``missing_steps``
    (holes inside one rank's step sequence).  ``step``: where the hole
    sits in the step sequence (the nearest preceding parsed step for a
    corrupt line, the newest missing step for a hole) — lets the health
    engine window out gaps the fleet has long since moved past."""
    kind: str
    rank: Optional[int] = None
    detail: str = ""
    step: Optional[int] = None

    def asdict(self):
        return {"kind": self.kind, "rank": self.rank,
                "detail": self.detail, "step": self.step}


@dataclasses.dataclass
class RankSeries:
    """One rank's parsed step series (physical file or virtual slice)."""
    rank: int
    records: List[dict]
    path: Optional[str] = None
    truncated: bool = False

    def steps(self) -> List[int]:
        out = []
        for r in self.records:
            s = _step_of(r)
            if s is not None:
                out.append(s)
        return out

    def last_step(self) -> Optional[int]:
        s = self.steps()
        return max(s) if s else None


@dataclasses.dataclass
class SpreadStats:
    """Cross-rank spread of one field at one step."""
    n: int
    min: float
    max: float
    p50: float
    p95: float
    mean: float

    def asdict(self):
        return {k: getattr(self, k)
                for k in ("n", "min", "max", "p50", "p95", "mean")}


def spread(values: Sequence[float]) -> Optional[SpreadStats]:
    """min/max/p50/p95/mean over the ranks present (None when empty).
    Non-finite samples participate — a NaN consensus distance must
    poison the stat visibly, not vanish from it."""
    vals = np.asarray([float(v) for v in values], np.float64)
    if vals.size == 0:
        return None
    if np.isfinite(vals).all():
        p50, p95 = np.percentile(vals, [50, 95])
    else:
        p50 = p95 = float("nan")
    return SpreadStats(n=int(vals.size), min=float(vals.min()),
                       max=float(vals.max()), p50=float(p50),
                       p95=float(p95), mean=float(vals.mean()))


class TailCache:
    """Per-file incremental parse state for live tailing.

    ``load_fleet(..., cache=)`` with one cache held across frames makes
    each monitoring pass parse only the bytes APPENDED since the last
    one — the live ``bfmonitor`` loop skips re-reading and re-parsing
    the run's history every 2 seconds (the view over the cached records
    is still rebuilt per call).  A file that shrank (rotated /
    restarted writer) resets its entry."""

    def __init__(self):
        # path -> [byte offset past last complete line, records, gaps,
        #          complete-line count, step of last parsed record,
        #          inode of the file the offset belongs to]
        self._files: Dict[str, list] = {}


def read_jsonl_tolerant(path: str, cache: Optional[TailCache] = None
                        ) -> Tuple[List[dict], List[Gap]]:
    """Parse a metrics JSONL file without ever raising on bad data.

    Unlike ``export.validate_jsonl`` (the strict CI gate), this reader is
    for live monitoring of files another process is still writing — or
    stopped writing mid-line when it was killed.  Returns
    ``(records, gaps)``: an unparseable FINAL line is dropped as a
    ``truncated`` gap (the writer died or has not finished the line);
    unparseable mid-file lines are skipped as ``parse_error`` gaps.

    ``cache``: a :class:`TailCache` carried across calls parses only
    appended bytes.  The offset only ever advances past COMPLETE
    (newline-terminated) lines, so a partial final line is re-examined
    next call once the writer finishes it — transient tail state
    (records without a newline yet, truncated gaps) is returned but
    never cached."""
    state = cache._files.get(path) if cache is not None else None
    if state is None:
        state = [0, [], [], 0, None, None]
    try:
        with open(path, "rb") as f:
            st = os.fstat(f.fileno())
            # a rotated writer REPLACES the live file (export.rotate_file)
            # — a new inode, or a same-inode truncation, means the cached
            # offset belongs to a different byte stream: start over
            # rather than resume mid-line in the new file
            if (state[5] is not None and state[5] != st.st_ino) or \
                    (state[0] and st.st_size < state[0]):
                state = [0, [], [], 0, None, None]
            state[5] = st.st_ino
            f.seek(state[0])
            chunk = f.read()
    except OSError as e:
        return [], [Gap("missing_file", detail=f"{path}: {e}")]
    complete, sep, remainder = chunk.rpartition(b"\n")
    if sep:
        for raw in complete.split(b"\n"):
            state[3] += 1
            line = raw.decode("utf-8", errors="replace").strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
                if not isinstance(rec, dict):
                    raise ValueError("not a JSON object")
            except ValueError as e:
                state[2].append(Gap("parse_error",
                                    detail=f"{path}:{state[3]}: {e}",
                                    step=state[4]))
                continue
            state[1].append(rec)
            s = _step_of(rec)
            if s is not None:
                state[4] = s
        state[0] += len(complete) + 1
    if cache is not None:
        cache._files[path] = state
    records = list(state[1])
    gaps = list(state[2])
    tail = remainder.decode("utf-8", errors="replace").strip()
    if tail:
        try:
            rec = json.loads(tail)
            if not isinstance(rec, dict):
                raise ValueError("not a JSON object")
            records.append(rec)        # complete line missing its newline
        except ValueError as e:
            gaps.append(Gap("truncated",
                            detail=f"{path}: final line cut ({e})",
                            step=state[4]))
    return records, gaps


def discover_series(prefix: str) -> Dict[int, str]:
    """``<prefix><rank>.jsonl`` files on disk, keyed by integer rank."""
    out: Dict[int, str] = {}
    pat = re.compile(re.escape(os.path.basename(prefix)) + r"(\d+)\.jsonl$")
    for path in glob.glob(glob.escape(prefix) + "*.jsonl"):
        m = pat.match(os.path.basename(path))
        if m:
            out[int(m.group(1))] = path
    return out


def _virtual_width(records: List[dict]) -> int:
    """Longest consistent per-rank list width across the core telemetry
    fields (0 = no list fields, nothing to explode)."""
    width = 0
    for rec in records:
        for k in CORE_FIELDS:
            v = rec.get(k)
            if isinstance(v, list) and len(v) > 1:
                if width and len(v) != width:
                    return 0          # inconsistent: do not explode
                width = len(v)
    return width


def _explode(series: RankSeries, width: int) -> List[RankSeries]:
    """Split one physical series whose telemetry fields are [N] lists
    into N virtual rank series (list position = rank).  Host-shared
    fields (t_us, step_wall_us, counters, loss, ...) replicate — on a
    virtual mesh every rank lives in the same process clock."""
    out = []
    for r in range(width):
        recs = []
        for rec in series.records:
            sub = {}
            for k, v in rec.items():
                if _numeric_list(v) and len(v) == width:
                    sub[k] = v[r]
                else:
                    sub[k] = v
            sub["rank"] = r
            recs.append(sub)
        out.append(RankSeries(rank=r, records=recs, path=series.path,
                              truncated=series.truncated))
    return out


class FleetView:
    """Step-aligned merge of per-rank series.

    ``per_rank``: rank -> {step -> record}; ``gaps``: every hole the
    loader observed (missing files, truncation, parse errors, missing
    steps).  All accessors tolerate partial data — a stat over a step
    only sees the ranks that reported it."""

    def __init__(self, series: List[RankSeries], gaps: List[Gap],
                 expected_ranks: Optional[int] = None):
        self.series = {s.rank: s for s in series}
        self.gaps = list(gaps)
        self.expected_ranks = expected_ranks
        self.per_rank: Dict[int, Dict[int, dict]] = {}
        for s in series:
            by_step: Dict[int, dict] = {}
            for rec in s.records:
                step = _step_of(rec)
                if step is not None:
                    by_step[step] = rec
            self.per_rank[s.rank] = by_step
        # holes inside each rank's own step sequence — counted
        # arithmetically and enumerated BOUNDED: one absurd (but
        # valid-JSON) step value must not materialize a range(1e15) set
        # in the loader whose whole contract is never dying on bad data
        for rank, by_step in self.per_rank.items():
            if by_step:
                steps = sorted(by_step)
                n_missing = (steps[-1] - steps[0] + 1) - len(steps)
                if n_missing > 0:
                    head = []
                    for a, b in zip(steps, steps[1:]):
                        for m in range(a + 1, min(b, a + 9)):
                            head.append(m)
                            if len(head) == 8:
                                break
                        if len(head) == 8:
                            break
                    last_missing = next(
                        b - 1 for a, b in zip(reversed(steps[:-1]),
                                              reversed(steps[1:]))
                        if b - a > 1)
                    self.gaps.append(Gap(
                        "missing_steps", rank=rank,
                        detail=f"{n_missing} step(s) absent between "
                               f"{steps[0]} and {steps[-1]} "
                               f"(first {head}"
                               f"{'...' if n_missing > len(head) else ''})",
                        step=last_missing))
        if expected_ranks is not None:
            for r in range(expected_ranks):
                if r not in self.per_rank:
                    self.gaps.append(Gap(
                        "missing_file", rank=r,
                        detail="rank never wrote a series file"))

    # -- shape ---------------------------------------------------------------

    @property
    def ranks(self) -> List[int]:
        return sorted(self.per_rank)

    def steps(self) -> List[int]:
        """Sorted union of every rank's reported steps."""
        all_steps = set()
        for by_step in self.per_rank.values():
            all_steps.update(by_step)
        return sorted(all_steps)

    def last_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    def rank_last_step(self, rank: int) -> Optional[int]:
        by_step = self.per_rank.get(rank) or {}
        return max(by_step) if by_step else None

    # -- field access --------------------------------------------------------

    def value(self, rank: int, step: int, field: str):
        """One rank's numeric value at one step; lists (an unexploded
        global-view field) collapse to their mean; None when absent."""
        rec = self.per_rank.get(rank, {}).get(step)
        if rec is None:
            return None
        v = rec.get(field)
        if isinstance(v, list):
            return float(np.mean(v)) if v else None
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
        return None

    def series_of(self, rank: int, field: str) -> List[Tuple[int, float]]:
        """Sorted ``(step, value)`` pairs for one rank's field."""
        by_step = self.per_rank.get(rank) or {}
        out = []
        for step in sorted(by_step):
            v = self.value(rank, step, field)
            if v is not None:
                out.append((step, v))
        return out

    def fleet_spread(self, step: int, field: str,
                     exclude: Optional[float] = None
                     ) -> Optional[SpreadStats]:
        """Cross-rank spread of one field at one step (present ranks
        only).  ``exclude``: drop ranks reporting this sentinel value
        (e.g. the ``-1`` UNMEASURED consensus of a degraded
        no-collective step, which would otherwise skew the stats)."""
        vals = []
        for rank in self.ranks:
            v = self.value(rank, step, field)
            if v is not None and (exclude is None or v != exclude):
                vals.append(v)
        return spread(vals)

    def spread_series(self, field: str,
                      steps: Optional[Sequence[int]] = None
                      ) -> List[Tuple[int, SpreadStats]]:
        out = []
        for step in (steps if steps is not None else self.steps()):
            st = self.fleet_spread(step, field)
            if st is not None:
                out.append((step, st))
        return out

    def missing_ranks(self, step: int) -> List[int]:
        """Ranks that reported SOME step but not this one."""
        return [r for r in self.ranks if step not in self.per_rank[r]]

    def latest_edges(self) -> Optional[dict]:
        """The newest ``"edges"`` record (the comm profiler's measured
        per-edge cost matrix riding the JSONL) anywhere in the fleet:
        ``{"step", "rank", "entries", "platform"}``, or None when no
        rank has probed — the view ``bfmonitor --once --json`` hands the
        controller.  ``platform`` (the sibling ``edges_platform`` field)
        is what the probe priced; consumers must gate on it
        (``commprof.matrix_is_usable``) before acting."""
        best = None
        for rank, by_step in self.per_rank.items():
            for step, rec in by_step.items():
                edges = rec.get("edges")
                if isinstance(edges, list) and edges and (
                        best is None or step > best["step"]):
                    best = {"step": step, "rank": rank, "entries": edges,
                            "platform": rec.get("edges_platform")}
        return best

    # -- derived: step wall time --------------------------------------------

    def step_wall_s(self, rank: int) -> List[Tuple[int, float]]:
        """Per-step host wall seconds for one rank: the explicit
        ``step_wall_us`` field when the exporter wrote it, else
        consecutive ``t_us`` deltas (first step then has no sample)."""
        by_step = self.per_rank.get(rank) or {}
        steps = sorted(by_step)
        explicit = []
        for step in steps:
            v = by_step[step].get(STEP_WALL_FIELD)
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                explicit.append((step, float(v) / 1e6))
        if explicit:
            return explicit
        out = []
        for prev, cur in zip(steps, steps[1:]):
            t0, t1 = by_step[prev].get("t_us"), by_step[cur].get("t_us")
            if isinstance(t0, (int, float)) and isinstance(t1, (int, float)):
                out.append((cur, max(0.0, float(t1) - float(t0)) / 1e6))
        return out

    # -- counters ------------------------------------------------------------

    def counter_delta(self, name: str, rank: Optional[int] = None,
                      window: Optional[int] = None,
                      agg: str = "sum") -> float:
        """Increase of one registry counter cell (exact snapshot key, e.g.
        ``bf_step_cache_total{result=build}``) over the window.

        Counters are PROCESS-scoped, so the delta is computed per
        physical counter stream and then aggregated: exploded virtual
        ranks share one file (one representative reads it once, never N
        times), and on a real multi-file fleet each rank's file is its
        own stream — mixing first/last across processes would compare
        unrelated counters.  ``agg``: ``"sum"`` totals the streams,
        ``"max"`` takes the worst stream — right for counters every
        process increments for the same fleet-wide event (a synchronized
        recompile, a majority-confirmed death), where the sum would
        scale with fleet size.  Pass ``rank`` to restrict to one
        stream."""
        if rank is not None:
            reps = [rank]
        else:
            by_stream: Dict[object, int] = {}
            for r in self.ranks:
                s = self.series.get(r)
                key = s.path if (s is not None and s.path) else ("rank", r)
                by_stream.setdefault(key, r)
            reps = sorted(by_stream.values())
        lo = None if window is None else (self.last_step() or 0) - window + 1
        deltas = []
        for r in reps:
            by_step = self.per_rank.get(r) or {}
            first = last = None
            for step in sorted(by_step):
                if lo is not None and step < lo:
                    continue
                c = by_step[step].get("counters")
                if not isinstance(c, dict):
                    continue
                if name not in c:
                    # registry counters are created on their FIRST
                    # increment: a snapshot that lacks the key pins the
                    # baseline at 0, so a counter appearing mid-series
                    # with value 1 reads as one event, not zero
                    if last is None:
                        first = 0.0
                    continue
                if first is None:
                    first = float(c[name])
                last = float(c[name])
            if first is not None and last is not None:
                deltas.append(last - first)
        if not deltas:
            return 0.0
        return max(deltas) if agg == "max" else sum(deltas)

    def counter_keys(self, prefix: str) -> List[str]:
        """Snapshot keys starting with ``prefix`` seen anywhere."""
        keys = set()
        for by_step in self.per_rank.values():
            for rec in by_step.values():
                c = rec.get("counters")
                if isinstance(c, dict):
                    keys.update(k for k in c if k.startswith(prefix))
        return sorted(keys)


def load_fleet(prefix: Optional[str] = None, *,
               paths: Optional[Dict[int, str]] = None,
               expected_ranks: Optional[int] = None,
               explode_virtual: bool = True,
               cache: Optional[TailCache] = None) -> FleetView:
    """Build the fleet view from ``<prefix><rank>.jsonl`` files (or an
    explicit ``{rank: path}`` map).

    ``expected_ranks``: fleet size the caller knows out of band — silent
    ranks become ``missing_file`` gaps.  ``explode_virtual``: when a
    SINGLE physical series carries ``[N]``-list telemetry (the
    single-process virtual mesh), split it into N virtual rank series so
    per-rank rules see per-rank values.  ``cache``: a
    :class:`TailCache` held across calls makes repeated loads parse only
    appended bytes (the live-monitor path)."""
    if paths is None:
        if prefix is None:
            raise ValueError("load_fleet needs a prefix or explicit paths")
        paths = discover_series(prefix)
    series: List[RankSeries] = []
    gaps: List[Gap] = []
    for rank in sorted(paths):
        records, file_gaps = read_jsonl_tolerant(paths[rank], cache)
        for g in file_gaps:
            if g.rank is None:
                g.rank = rank
        gaps.extend(file_gaps)
        truncated = any(g.kind == "truncated" for g in file_gaps)
        series.append(RankSeries(rank=rank, records=records,
                                 path=paths[rank], truncated=truncated))
    if explode_virtual and len(series) == 1 and series[0].records:
        width = _virtual_width(series[0].records)
        if width:
            series = _explode(series[0], width)
            if expected_ranks is None:
                expected_ranks = width
    return FleetView(series, gaps, expected_ranks=expected_ranks)
