"""Step-phase profiling: wall-clock timers around the host-side step loop.

The in-graph telemetry says WHAT the consensus process did; the phase
timers say WHERE the host step's wall time went — the time base
straggler attribution needs.  Four canonical phases:

* ``exchange`` — launching the communication (window put/get/accumulate
  and their waits; for the jitted-strategy family the exchange lives
  inside the graph and is covered by ``compute``),
* ``fold``     — folding received buffers (``win_update`` / collect),
* ``compute``  — the jitted step dispatch (forward/backward/update —
  and, fused in-graph, the exchange itself),
* ``export``   — telemetry fetch + JSONL/timeline write
  (``export.log_step`` times its device->host fetch here).

Each timed phase records THREE ways, all free when observability is off:

1. host registry histogram ``bf_step_phase_seconds{phase=...}``
   (Prometheus-ready latency distribution),
2. a Perfetto span on the ``step_phase`` timeline lane plus a
   ``phase/<name>_ms`` counter lane — the phase timings graph NEXT TO
   the op spans and telemetry lanes,
3. a per-step staging dict drained by ``export.log_step`` into the JSONL
   record (``"phases": {name: seconds}``), which is how the fleet
   aggregator and the health engine's straggler rule see per-rank phase
   time.

Zero cost when disabled: :func:`step_phase` returns a shared
``nullcontext`` after ONE bool check when neither the metrics registry
nor the timeline is active — the same guard discipline as every other
instrumentation site (``observability/metrics.py``).

Usage (any host step loop)::

    from bluefog_tpu.observability import phases

    with phases.step_phase("compute"):
        out = step_fn(variables, opt_state, batch, i)
    export.log_step(i, snap)           # drains the staged phase timings

The built-in optimizer wrappers (``optim/wrappers.py``) and
``training.run_steps`` already instrument their loops.
"""

import contextlib
import time
from typing import Dict, Optional

from .. import timeline as _tl
from . import metrics as _metrics

__all__ = ["PHASES", "step_phase", "record_phase", "take_step_phases",
           "reset_step_phases", "profiling_active", "stage_field",
           "take_step_fields"]

PHASES = ("exchange", "fold", "compute", "export")

# sub-us to minutes: host phase timings live well inside this span
_BUCKETS = (1e-6, 1e-5, 1e-4, 1e-3, 3e-3, 1e-2, 3e-2, 0.1, 0.3, 1.0,
            3.0, 10.0, 30.0, 100.0)

# phase -> seconds staged for the NEXT export.log_step record; a plain
# dict (no lock): step loops are single-threaded by construction, and a
# racing reader at worst misattributes one sample to a neighboring step
_staged: Dict[str, float] = {}

# arbitrary top-level numeric fields staged for the NEXT log_step record
# (same lifecycle as _staged): the comm profiler stages its measured
# `overlap_efficiency` here so the sample rides the SAME JSONL record as
# the step's telemetry instead of needing its own schema
_staged_fields: Dict[str, object] = {}

_NULL = contextlib.nullcontext()


def profiling_active() -> bool:
    """One-bool-each gate shared by every phase site: phases record only
    while the metrics registry or a timeline is on."""
    return _metrics.enabled() or _tl.timeline_enabled()


def record_phase(name: str, seconds: float) -> None:
    """Record one already-measured phase duration (histogram + Perfetto
    lanes + the staged dict).  No-op while profiling is inactive."""
    if not profiling_active():
        return
    _staged[name] = _staged.get(name, 0.0) + seconds
    if _metrics.enabled():
        _metrics.histogram(
            "bf_step_phase_seconds",
            "host wall time per step phase (exchange/fold/compute/export)",
            buckets=_BUCKETS).observe(seconds, phase=name)
    # the counter lane graphs the per-step duration; the span (emitted by
    # the context manager, which knows the start timestamp) shows extent
    _tl.record_counter(f"phase/{name}_ms", seconds * 1e3)


class _PhaseTimer:
    """Reusable timer context: span on the ``step_phase`` lane + the
    :func:`record_phase` sinks."""

    __slots__ = ("_name", "_t0", "_token")

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        self._token = _tl.op_start_us()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = time.perf_counter() - self._t0
        _tl.record_op_span("step_phase", self._name, self._token)
        record_phase(self._name, dt)
        return False


def step_phase(name: str):
    """Context manager timing one phase of the host step loop.

    Returns a shared no-op context (ONE bool check, nothing allocated)
    while neither metrics nor a timeline is enabled — safe to leave in
    hot paths permanently."""
    if not profiling_active():
        return _NULL
    return _PhaseTimer(name)


def reset_step_phases() -> None:
    """Discard staged timings (and staged fields).  Called when a JSONL
    sink opens (``export.metrics_start``): phases timed by a PREVIOUS
    loop that never logged them must not land on the new sink's first
    record."""
    _staged.clear()
    _staged_fields.clear()


def stage_field(name: str, value) -> None:
    """Stage one top-level field for the next ``export.log_step`` record
    — a number (``overlap_efficiency``) or a JSON-ready structure (the
    ``edges`` matrix).  Last-write-wins per step; no-op while profiling
    is inactive."""
    if not profiling_active():
        return
    if isinstance(value, (int, float)) and not isinstance(value, bool):
        value = float(value)
    _staged_fields[name] = value


def take_step_fields() -> Optional[Dict[str, object]]:
    """Drain the staged top-level fields (None when nothing staged) —
    called by ``export.log_step`` alongside :func:`take_step_phases`."""
    if not _staged_fields:
        return None
    out = dict(_staged_fields)
    _staged_fields.clear()
    return out


def take_step_phases() -> Optional[Dict[str, float]]:
    """Drain the staged per-step phase durations ({phase: seconds}), or
    None when nothing was staged.  Called by ``export.log_step`` so the
    timings land on the SAME JSONL record as the step's telemetry."""
    if not _staged:
        return None
    out = dict(_staged)
    _staged.clear()
    return out
