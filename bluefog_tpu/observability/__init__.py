"""Runtime telemetry subsystem — the observability layer.

Cooperating pieces (docs/observability.md):

* ``ingraph``   — traced per-step training-health aggregates computed
  INSIDE the jitted step (consensus distance, mixing-matrix mass, norms,
  pipeline flags), returned as a ``TelemetrySnapshot`` aux pytree via the
  ``telemetry=`` flag on the optimizer factories and
  ``training.make_train_step``.
* ``metrics``   — process-local host registry (counters/gauges/histograms
  with named labels), instrumented into fusion, windows, the service,
  resilience, and the step cache.  Free when disabled.
* ``export``    — JSONL per-step series (``BLUEFOG_METRICS=<prefix>``),
  Prometheus text dump, and Chrome-tracing counter lanes
  (``"ph":"C"``) on the existing timeline.
* ``phases``    — wall-clock step-phase timers around the host step loop
  (exchange launch / fold / compute / export), recorded as registry
  histograms, Perfetto lanes, and JSONL ``"phases"`` fields.
* ``aggregate`` — fleet-wide merge of the per-rank JSONL series:
  step-aligned cross-rank spread stats tolerating missing / partial /
  lagging ranks.
* ``health``    — rule-based health engine over the fleet view:
  structured ``HealthReport`` verdicts (consensus stall/diverge,
  non-finite, residual blow-up, straggler skew, overlap collapse, dead
  ranks, compile storms) for ``bfmonitor`` and the future closed-loop
  controller.
* ``commprof``  — measured comm-path profiling: the per-edge link cost
  matrix (ppermute probe harness -> ``EdgeCostMatrix`` -> ``bf_edge_*``
  gauges / JSONL ``"edges"`` record / controller artifact) and the
  exposed-vs-hidden overlap-efficiency split of the delayed-mix
  pipeline.
* ``tracemerge`` — ``bftrace``: merge N per-rank Chrome traces into one
  clock-aligned fleet trace with cross-rank gossip flow arrows.
* ``plane``     — the in-band telemetry plane: a fixed-shape versioned
  per-rank health vector gossiped over the fabric itself (newest-version
  -wins merge, graph-diameter propagation bound), giving every rank an
  eventually-consistent ``FleetViewLive`` with no shared filesystem and
  no central collector.

Only ``metrics`` loads eagerly (it is stdlib-only and imported from
hot-path modules — fusion, windows, service, timeline); everything else
resolves lazily so importing this package never drags the JAX optimizer
stack or the timeline into an import cycle.
"""

import importlib

from . import metrics

_LAZY = ("ingraph", "export", "phases", "aggregate", "health", "commprof",
         "tracemerge", "plane")

__all__ = ["metrics", *_LAZY]


def __getattr__(name):
    if name in _LAZY:
        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
