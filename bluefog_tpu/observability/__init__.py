"""Runtime telemetry subsystem — the observability layer.

Three cooperating pieces (docs/observability.md):

* ``ingraph``  — traced per-step training-health aggregates computed
  INSIDE the jitted step (consensus distance, mixing-matrix mass, norms,
  pipeline flags), returned as a ``TelemetrySnapshot`` aux pytree via the
  ``telemetry=`` flag on the optimizer factories and
  ``training.make_train_step``.
* ``metrics``  — process-local host registry (counters/gauges/histograms
  with named labels), instrumented into fusion, windows, the service,
  resilience, and the step cache.  Free when disabled.
* ``export``   — JSONL per-step series (``BLUEFOG_METRICS=<prefix>``),
  Prometheus text dump, and Chrome-tracing counter lanes
  (``"ph":"C"``) on the existing timeline.

Only ``metrics`` loads eagerly (it is stdlib-only and imported from
hot-path modules — fusion, windows, service, timeline); ``ingraph`` and
``export`` resolve lazily so importing this package never drags the JAX
optimizer stack or the timeline into an import cycle.
"""

import importlib

from . import metrics

__all__ = ["metrics", "ingraph", "export"]


def __getattr__(name):
    if name in ("ingraph", "export"):
        return importlib.import_module("." + name, __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
